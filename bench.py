"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): ResNet-50 training samples/sec/chip on the
real TPU.  `vs_baseline` is measured-vs-north-star: the reference publishes
no numbers (BASELINE.md), so the comparison point is the commonly cited
nd4j-cuda/V100-class ResNet-50 training throughput of ~400 samples/sec/GPU
(MLPerf-era V100 fp32 figures); >1.0 means we beat it.

Extra per-config results (LeNet, LSTM char-LM) go to stderr so the stdout
contract stays one line.  Run: `python bench.py [--quick]`.

`python bench.py --serving [--quick]` instead benchmarks the
`deeplearning4j_tpu.serving` runtime (closed-loop concurrent clients
against a warmed ModelServer): p50/p99 latency, throughput and batch
occupancy go to stderr; stdout still carries exactly one JSON line (the
serving headline).

`python bench.py --pipeline [--quick]` A/Bs the async input pipeline
(device prefetch + on-device normalization + fused dispatch, no per-step
sync) against the old synchronous per-batch loop; detail to stderr, one
stdout JSON line.

`python bench.py --obs [--quick]` A/Bs the telemetry instrumentation
(monitor registry + spans) enabled vs disabled on that same pipeline loop
and asserts the overhead stays under 2%; detail to stderr, one stdout JSON
line.

`python bench.py --zero1 [--quick]` A/Bs the ZeRO-1 sharded weight update
(`ParallelWrapper.optimizer_sharding`, arXiv:2004.13336) against the
replicated update on the SAME mesh and model: wall time, per-replica
optimizer-state bytes (the HBM headline) and end-of-run parity; detail to
stderr + `BENCH_zero1.json`, one stdout JSON line.

`python bench.py --aot [--quick]` A/Bs cold vs warm PROCESS start through
the persistent executable cache (`deeplearning4j_tpu.compile`): two
identical subprocesses share one cache directory — the first pays every
compile (train step + serving bucket ladder), the second must start warm
with ZERO compiles (exit 1 otherwise); detail to stderr +
`BENCH_aot.json`, one stdout JSON line.

`python bench.py --autotune [--quick]` runs the schedule autotuner
(`compile.ScheduleAutotuner`) over {fused_steps, prefetch_depth,
donation} on the pipeline fixture, persists the winning schedule, reloads
and re-measures it (restart-survival check); detail to stderr +
`BENCH_autotune.json`, one stdout JSON line.

`python bench.py --comms [--quick]` A/Bs the hierarchical compressed
cross-host gradient exchange (threshold int streams + error-feedback
residuals over TCP) against the dense f32 exchange on a simulated 2-host
gang (LocalLauncher: real processes, real sockets): cross-host bytes on
wire (gate: >=5x reduction), steps/sec, and end-of-run loss parity
(gate: within 1%); detail to stderr + `BENCH_comms.json`, one stdout
JSON line.

`python bench.py --elastic [--quick]` A/Bs elastic gang survival: a
3-process gang whose rank 2 is killed mid-run (heartbeat detection,
generation-fenced re-formation at world 2, checkpoint-coordinated
resume) against the same training uninterrupted — gates: detection
within the failure deadline, resumed final loss matches an
uninterrupted world-2 run from the same checkpoint, and the whole
interruption inside the overhead budget; detail to stderr +
`BENCH_elastic.json`, one stdout JSON line.

`python bench.py --fleet [--quick]` A/Bs a long-tail model population
through the warm-pooled `serving.ModelFleet` against the naive
always-resident posture: models served per fixed device-memory budget
(gate: >=2x, with a compile-free second sweep via the persistent AOT
cache) and an overload phase where low-priority traffic is shed while
the high-priority p99 stays within its SLO (gate: both); detail to
stderr + `BENCH_fleet.json`, one stdout JSON line.

`python bench.py --fleetchaos [--quick]` gates serving fault tolerance
(`serving/resilience.py`): `ReplicaChaos` kills one replica and hangs
another mid-flood — gates: zero lost accepted requests, hi-priority p99
within SLO through the failure, every controller respawn compile-free
(`fresh_compiles == 0`), detection->respawn bounded, and a fleet restart
from the crc-guarded topology snapshot reconverging to the pre-crash
shape with zero cold compiles; detail to stderr +
`BENCH_fleetchaos.json`, one stdout JSON line.

`python bench.py --pallas [--quick]` benchmarks the Pallas fused-kernel
tier (`ops.pallas`): per-kernel conformance vs the jnp reference (always,
interpret mode on CPU), timed A/B vs the XLA-fused baseline on an
accelerator (gate: >=1.15x on at least one kernel; on CPU the A/B leg is
skipped and flagged `"simulated": true`), tile search -> persist -> replay
through `compile.autotune_tiles` (gate: the replay is a cache hit with
ZERO re-search), and the AOT-key proof (gate: a warm restart through the
persistent executable cache recompiles NOTHING, while installing a
different tile schedule produces a DISTINCT cache entry); detail to
stderr + `BENCH_pallas.json`, one stdout JSON line.

`python bench.py --quant [--quick]` A/Bs post-training-quantized serving
(`deeplearning4j_tpu.quant`: calibrate → int8 per-channel weights → fused
quantized forward) against the f32 model through the bucketed serving
cache, and round-trips the quantized executables through the persistent
AOT cache in a second subprocess — gates: >=2x throughput per byte
resident OR >=1.5x QPS, parity delta <=1%, warm restart with zero
compiles, quantized fingerprint distinct from f32; detail to stderr +
`BENCH_quant.json`, one stdout JSON line.

`python bench.py --decode [--quick]` floods the autoregressive decode
engine (`serving.decode`: bucketed prefill → token-level continuous
batching → paged KV cache) with sequence-length-skewed traffic and A/Bs
paged-int8 against contiguous-f32 KV memory — gates: zero fresh XLA
compiles after warmup across the skewed flood, tokens/sec floor,
inter-token p99 bound, int8 paged KV holds >=1.5x concurrent sequences
per HBM byte vs an f32 contiguous (max-length-reserving) cache at <=1%
attention parity; detail to stderr + `BENCH_decode.json`, one stdout
JSON line.
"""
import json
import sys
import time

import numpy as np

V100_RESNET50_SAMPLES_SEC = 400.0   # north-star comparison point (fp32 V100)


def _time_steps(fit_fn, n_warmup, n_steps, sync_fn=None):
    """Chained-step timing: steps dispatch back-to-back (device-resident
    data, no per-step host sync — the async-prefetch training loop shape);
    `sync_fn` forces completion once, inside the timed region."""
    for _ in range(n_warmup):
        fit_fn()
    if sync_fn is not None:
        sync_fn()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        fit_fn()
    if sync_fn is not None:
        sync_fn()
    return time.perf_counter() - t0


def _time_train(make_net, x, y, steps, fused_steps):
    """Train-throughput timing with the fused k-step dispatch.

    `fused_steps=k` uses the fit_steps scan dispatch (one host dispatch
    per k steps) — the measured per-step host gap through the remote PJRT
    tunnel is ~3 ms (PERF_ANALYSIS.md r5).  Falls back to per-step
    dispatch if the fused path fails (rebuilding the net first: a runtime
    failure may strike after buffer donation deleted the params)."""
    import jax.numpy as jnp

    net = make_net()
    if fused_steps and fused_steps > 1 and steps % fused_steps == 0:
        xs = jnp.broadcast_to(x, (fused_steps,) + x.shape)
        ys = jnp.broadcast_to(y, (fused_steps,) + y.shape)
        try:
            def block():
                net.fit_steps(xs, ys)

            return _time_steps(block, n_warmup=1,
                               n_steps=steps // fused_steps,
                               sync_fn=lambda: float(net.score()))
        except Exception as e:   # pragma: no cover - perf fallback
            print(f"[bench] fused path failed ({type(e).__name__}: "
                  f"{str(e)[:120]}); falling back to per-step dispatch",
                  file=sys.stderr, flush=True)
            net = make_net()

    def step():
        net.fit(x, y)

    return _time_steps(step, n_warmup=3, n_steps=steps,
                       sync_fn=lambda: float(net.score()))


def bench_resnet50(batch=64, steps=20, image=224, classes=1000,
                   compute_dtype="bfloat16", fused_steps=5):
    # fused_steps=5 -> a 3.9 GB [k,64,224,224,3] f32 block; k=10 doubles
    # that against ~16 GB HBM with step activations live — measured-safe
    # margin first, stage 9 A/Bs the larger k
    """bf16 compute / f32 master params — the TPU-native precision choice
    (f32: ~375 samples/sec on v5e; bf16: ~1636)."""
    from deeplearning4j_tpu.train.updaters import Nesterovs
    from deeplearning4j_tpu.zoo import ResNet50

    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, image, image, 3).astype(np.float32))
    y = jnp.asarray(
        np.eye(classes, dtype=np.float32)[rng.randint(0, classes, batch)])

    dt = _time_train(
        lambda: ResNet50(n_classes=classes, input_shape=(image, image, 3),
                         updater=Nesterovs(0.1, 0.9),
                         compute_dtype=compute_dtype).init_model(),
        x, y, steps, fused_steps)
    return batch * steps / dt


def bench_lenet(batch=256, steps=30, fused_steps=10):
    from deeplearning4j_tpu.zoo import LeNet

    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, 28, 28, 1).astype(np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rng.randint(0, 10, batch)])

    dt = _time_train(lambda: LeNet().init_model(), x, y, steps, fused_steps)
    return batch * steps / dt


def bench_bert_base(batch=64, steps=10, t=128, compute_dtype="bfloat16"):
    """BERT-base masked-LM fine-tune step, tokens/sec (BASELINE config 3).
    bf16 compute (master params f32) — the TPU-native precision choice."""
    import jax
    from deeplearning4j_tpu.train.updaters import Adam
    from deeplearning4j_tpu.zoo import BertConfig, BertModel

    model = BertModel(BertConfig.base(max_len=t,
                                      compute_dtype=compute_dtype),
                      updater=Adam(1e-4))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 30522, (batch, t)).astype(np.int32)
    mask = np.ones((batch, t), np.float32)
    sel = rng.rand(batch, t) < 0.15
    lmask = sel.astype(np.float32)

    import jax.numpy as jnp

    from deeplearning4j_tpu.data.dataset import MultiDataSet
    mds = MultiDataSet(features=[jnp.asarray(ids), jnp.asarray(mask)],
                       labels=[jnp.asarray(ids)],
                       labels_masks=[jnp.asarray(lmask)])   # sparse labels

    fused = 5
    if steps % fused == 0:
        stk = MultiDataSet(
            features=[jnp.broadcast_to(f, (fused,) + f.shape)
                      for f in mds.features],
            labels=[jnp.broadcast_to(l, (fused,) + l.shape)
                    for l in mds.labels],
            labels_masks=[jnp.broadcast_to(m, (fused,) + m.shape)
                          for m in mds.labels_masks])
        try:
            def block():
                model.fit_steps(stk)

            dt = _time_steps(block, n_warmup=1, n_steps=steps // fused,
                             sync_fn=lambda: model.score())
            return batch * t * steps / dt
        except Exception as e:   # pragma: no cover - perf fallback
            print(f"[bench] bert fused path failed ({type(e).__name__}: "
                  f"{str(e)[:120]}); per-step fallback",
                  file=sys.stderr, flush=True)
            model = BertModel(BertConfig.base(max_len=t,
                                              compute_dtype=compute_dtype),
                              updater=Adam(1e-4))

    def step():
        model.fit_batch(mds)

    dt = _time_steps(step, n_warmup=3, n_steps=steps,
                     sync_fn=lambda: model.score())
    return batch * t * steps / dt


def bench_bert_long_seq(batch=4, steps=5, t=2048, compute_dtype="bfloat16"):
    """Long-context BERT MLM step at seq 2048 — the regime where the
    Pallas flash-attention kernels engage (`_FLASH_MIN_SEQ`); at seq 128
    the dispatcher takes the XLA path, so the short-seq config cannot
    exercise them (VERDICT r3 weak #3)."""
    from deeplearning4j_tpu.train.updaters import Adam
    from deeplearning4j_tpu.zoo import BertConfig, BertModel

    model = BertModel(BertConfig.base(max_len=t,
                                      compute_dtype=compute_dtype),
                      updater=Adam(1e-4))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 30522, (batch, t)).astype(np.int32)
    mask = np.ones((batch, t), np.float32)
    lmask = (rng.rand(batch, t) < 0.15).astype(np.float32)

    import jax.numpy as jnp

    from deeplearning4j_tpu.data.dataset import MultiDataSet
    mds = MultiDataSet(features=[jnp.asarray(ids), jnp.asarray(mask)],
                       labels=[jnp.asarray(ids)],
                       labels_masks=[jnp.asarray(lmask)])

    def step():
        model.fit_batch(mds)

    dt = _time_steps(step, n_warmup=2, n_steps=steps,
                     sync_fn=lambda: model.score())
    return batch * t * steps / dt


def build_tf_bert_frozen(batch=32, t=128, layers=12, hidden=768,
                         heads=12, vocab=30522):
    """Build the BERT-base-shaped frozen TF GraphDef (BASELINE config 3's
    source model).  Returns (graph_def, frozen_concrete_fn, encoder_out
    name) — shared by the bench and the full-depth import-conformance
    test (`tests/test_modelimport.py`), so the timed path and the
    value-asserted path are THE SAME graph."""
    import tensorflow as tf
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    rs = np.random.RandomState(0)
    H, NH, L, T, B = hidden, heads, layers, t, batch
    p = {"tok_emb": tf.constant(rs.randn(vocab, H).astype(np.float32)
                                * 0.02),
         "pos_emb": tf.constant(rs.randn(T, H).astype(np.float32) * 0.02)}
    for l in range(L):
        for w in ["wq", "wk", "wv", "wo"]:
            p[f"{l}.{w}"] = tf.constant(
                rs.randn(H, H).astype(np.float32) * 0.02)
        p[f"{l}.w1"] = tf.constant(rs.randn(H, 4 * H).astype(np.float32)
                                   * 0.02)
        p[f"{l}.w2"] = tf.constant(rs.randn(4 * H, H).astype(np.float32)
                                   * 0.02)
        p[f"{l}.g1"] = tf.constant(np.ones(H, np.float32))
        p[f"{l}.b1"] = tf.constant(np.zeros(H, np.float32))
        p[f"{l}.g2"] = tf.constant(np.ones(H, np.float32))
        p[f"{l}.b2"] = tf.constant(np.zeros(H, np.float32))

    def ln(x, g, b):
        mean = tf.reduce_mean(x, axis=-1, keepdims=True)
        var = tf.reduce_mean(tf.math.squared_difference(x, mean), axis=-1,
                             keepdims=True)
        return (x - mean) * tf.math.rsqrt(var + 1e-6) * g + b

    def gelu(x):
        return 0.5 * x * (1.0 + tf.math.erf(
            x / np.sqrt(2.0).astype(np.float32)))

    def f(ids):
        x = tf.gather(p["tok_emb"], ids, axis=0) + p["pos_emb"]
        for l in range(L):
            def heads_of(w):
                y = tf.matmul(tf.reshape(x, [B * T, H]), w)
                return tf.transpose(tf.reshape(y, [B, T, NH, H // NH]),
                                    [0, 2, 1, 3])
            q, k, v = (heads_of(p[f"{l}.wq"]), heads_of(p[f"{l}.wk"]),
                       heads_of(p[f"{l}.wv"]))
            s = tf.matmul(q, k, adjoint_b=True) / np.float32(
                np.sqrt(H // NH))
            ctx = tf.matmul(tf.nn.softmax(s, axis=-1), v)
            ctx = tf.reshape(tf.transpose(ctx, [0, 2, 1, 3]), [B, T, H])
            a = tf.matmul(tf.reshape(ctx, [B * T, H]), p[f"{l}.wo"])
            x = ln(x + tf.reshape(a, [B, T, H]), p[f"{l}.g1"],
                   p[f"{l}.b1"])
            h = gelu(tf.matmul(tf.reshape(x, [B * T, H]), p[f"{l}.w1"]))
            h = tf.matmul(h, p[f"{l}.w2"])
            x = ln(x + tf.reshape(h, [B, T, H]), p[f"{l}.g2"],
                   p[f"{l}.b2"])
        return x

    frozen = convert_variables_to_constants_v2(
        tf.function(f).get_concrete_function(
            tf.TensorSpec((B, T), tf.int32)))
    gd = frozen.graph.as_graph_def()
    # the frozen fn's structured output tensor names the true graph output
    enc = frozen.outputs[0].name.split(":")[0]
    return gd, frozen, enc


def bench_bert_tf_import(batch=32, steps=5, t=128, layers=12,
                         hidden=768, heads=12, vocab=30522):
    """BASELINE config 3 AS WRITTEN: BERT-base fine-tune via SameDiff TF
    import — build the frozen GraphDef in TF, import through
    modelimport.tf_import, attach a trainable head, measure the jitted
    SameDiff fine-tune step.  (Values of this exact import path are
    asserted against TF at full 12-layer depth in
    tests/test_modelimport.py::test_tf_import_full_depth_bert.)"""
    from deeplearning4j_tpu.autodiff import TrainingConfig
    from deeplearning4j_tpu.modelimport import import_graph_def
    from deeplearning4j_tpu.train.updaters import Adam

    rs = np.random.RandomState(0)
    H, T, B = hidden, t, batch
    gd, frozen, enc = build_tf_bert_frozen(batch, t, layers, hidden,
                                           heads, vocab)
    sd = import_graph_def(gd)

    # trainable MLM head over the imported (constant) encoder
    import jax
    import jax.numpy as jnp
    w_head = sd.var("head_w", "XAVIER", H, vocab)
    logits = sd.op("matmul", sd.get_variable(enc), w_head, name="logits")
    lab = sd.placeholder("lab", (B, T))
    sd.loss.sparse_softmax_cross_entropy(lab, logits, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(
        updater=Adam(1e-4), data_set_feature_mapping=["ids"],
        data_set_label_mapping=["lab"]))
    ids = jnp.asarray(rs.randint(0, vocab, (B, T)).astype(np.int32))
    lab_v = jnp.asarray(rs.randint(0, vocab, (B, T)).astype(np.int32))

    def step():
        sd.fit(ids, lab_v)

    dt = _time_steps(step, n_warmup=2, n_steps=steps,
                     sync_fn=lambda: sd.score())
    return B * T * steps / dt


def bench_lstm_charlm(batch=64, steps=10, t=64, vocab=77, fused_steps=5):
    from deeplearning4j_tpu.zoo import TextGenLSTM

    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    idx = rng.randint(0, vocab, (batch, t))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[idx])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[np.roll(idx, -1, 1)])

    dt = _time_train(
        lambda: TextGenLSTM(n_classes=vocab,
                            input_shape=(t, vocab)).init_model(),
        x, y, steps, fused_steps)
    return batch * t * steps / dt


def bench_serving(duration_s=3.0, n_clients=16, max_batch=64,
                  batch_timeout_ms=2.0):
    """Closed-loop serving benchmark: `n_clients` threads drive mixed-size
    requests through a warmed `serving.ModelServer` (zoo LeNet) for
    `duration_s`.  Returns the SLO summary: requests/sec, rows/sec,
    latency percentiles, batch occupancy, compile-cache stats."""
    from concurrent.futures import ThreadPoolExecutor
    from deeplearning4j_tpu.serving import ModelServer

    srv = ModelServer(max_batch=max_batch, batch_timeout_ms=batch_timeout_ms,
                      max_queue=4096)
    srv.deploy("lenet", zoo="LeNet", warmup=True)
    sizes = (1, 2, 3, 4, 8)

    def client(i):
        rs = np.random.RandomState(i)
        reqs = rows = 0
        end = time.monotonic() + duration_s
        while time.monotonic() < end:
            n = sizes[reqs % len(sizes)]
            x = rs.rand(n, 28, 28, 1).astype(np.float32)
            srv.output("lenet", x, timeout=60)
            reqs += 1
            rows += n
        return reqs, rows

    t0 = time.perf_counter()
    with ThreadPoolExecutor(n_clients) as ex:
        totals = list(ex.map(client, range(n_clients)))
    dt = time.perf_counter() - t0
    snap = srv.stats()
    srv.shutdown()
    reqs = sum(r for r, _ in totals)
    rows = sum(r for _, r in totals)
    lat = snap["latency_ms"]
    return {
        "requests_per_sec": reqs / dt,
        "rows_per_sec": rows / dt,
        "p50_ms": lat["p50"], "p95_ms": lat["p95"], "p99_ms": lat["p99"],
        "batch_occupancy": snap["batch_occupancy"],
        "padding_fraction": snap["padding_fraction"],
        "compile_cache": snap["compile_cache"],
        "dispatches": snap["dispatches"],
        "clients": n_clients, "duration_s": dt,
    }


def _pipeline_fixture(n_batches, batch, n_in):
    """Shared fixture for `--pipeline` and `--obs`: an ETL-bearing iterator
    factory, an MLP factory, and a fitted normalizer over deterministic raw
    float64 rows.  Imports stay inside the function so `main()` can decide
    JAX_PLATFORMS before jax loads."""
    from deeplearning4j_tpu.data import (DataSet, DataSetIterator,
                                         NormalizerStandardize)
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)

    class EtlIterator(DataSetIterator):
        """Materializes each batch from raw f64 rows on demand — the
        per-batch host cost a record-reader/augmentation pipeline pays."""

        def __init__(self, raw_x, raw_y, batch):
            self.raw_x, self.raw_y, self._batch = raw_x, raw_y, batch

        def __iter__(self):
            for i in range(0, len(self.raw_x), self._batch):
                x = (self.raw_x[i:i + self._batch] * 0.5
                     + 1.0).astype(np.float32)
                y = np.eye(10, dtype=np.float32)[self.raw_y[i:i + self._batch]]
                yield DataSet(x, y)

        def reset(self):
            pass

        def batch_size(self):
            return self._batch

        def __len__(self):
            return (len(self.raw_x) + self._batch - 1) // self._batch

    def make_net():
        conf = (NeuralNetConfiguration.builder().seed(0)
                .list([DenseLayer(n_out=512, activation="relu"),
                       DenseLayer(n_out=256, activation="relu"),
                       OutputLayer(n_out=10, loss="mcxent",
                                   activation="softmax")])
                .set_input_type(InputType.feed_forward(n_in)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.RandomState(0)
    raw_x = rng.rand(n_batches * batch, n_in) * 100.0       # float64 rows
    raw_y = rng.randint(0, 10, n_batches * batch)

    def make_it():
        return EtlIterator(raw_x, raw_y, batch)

    nz = NormalizerStandardize().fit(make_it())
    return make_it, make_net, nz


def bench_pipeline(n_batches=128, batch=64, fused_steps=16, depth=2,
                   n_in=784):
    """A/B the async input pipeline against the old synchronous loop on the
    SAME ETL-bearing iterator + model (an MLP — dense layers time
    identically inside and outside `lax.scan` on every backend, so the A/B
    isolates the pipeline; conv models hit an XLA:CPU while-loop slow path
    that would swamp it).  Each batch is materialized on demand from raw
    float64 rows (cast + affine + one-hot), the record-reader shape:

    A (sync): host ETL, host normalization, one dispatch per step, and a
      blocking `float(score())` read every iteration — host work and
      device compute strictly serialized, the pre-pipeline loop.
    B (pipeline): the SAME ETL runs in the `DevicePrefetchIterator`
      producer thread overlapped with compute (numpy and XLA both release
      the GIL), staged on device `depth` batches ahead; normalization is
      folded into the jitted step; fused k-step dispatch; one sync at the
      end.

    Default config uses small batches: the pipeline's structural win is
    amortizing per-step host dispatch, which dominates when step compute
    is short (the TPU regime it targets).  At large CPU batches both
    sides are compute-bound on the same single core and the A/B reads
    ~1.0x either way.
    """
    from deeplearning4j_tpu.data import DevicePrefetchIterator

    make_it, make_net, nz = _pipeline_fixture(n_batches, batch, n_in)

    net_a = make_net()

    def run_sync():
        for ds in make_it():
            nz.transform(ds)                      # host-side normalize
            net_a.fit(ds.features, ds.labels)     # one dispatch per step
            float(net_a.score())                  # per-iteration sync

    # best-of-3 epochs per side: a single epoch is short enough on CPU
    # that scheduler noise would dominate a one-shot reading
    t_sync = min(_time_steps(run_sync, n_warmup=1, n_steps=1)
                 for _ in range(3))

    net_b = make_net()
    net_b.set_normalizer(nz)                      # on-device prologue
    pf = DevicePrefetchIterator(make_it(), depth=depth)
    try:
        def run_pipe():
            net_b.fit(pf, fused_steps=fused_steps)

        t_pipe = min(_time_steps(run_pipe, n_warmup=1, n_steps=1,
                                 sync_fn=lambda: float(net_b.score()))
                     for _ in range(3))
    finally:
        pf.close()
    n = batch * n_batches
    return {"sync_wall_s": t_sync, "pipeline_wall_s": t_pipe,
            "speedup": t_sync / t_pipe,
            "sync_samples_per_sec": n / t_sync,
            "pipeline_samples_per_sec": n / t_pipe,
            "n_batches": n_batches, "batch": batch,
            "fused_steps": fused_steps, "prefetch_depth": depth}


def bench_obs(n_batches=96, batch=64, fused_steps=8, depth=2, n_in=784,
              repeats=3):
    """A/B the telemetry overhead on the `--pipeline` training loop: the
    SAME instrumented code runs with the registry enabled vs disabled
    (`monitor.set_enabled`), so the delta is exactly what the PR's
    instrumentation costs on the hottest loop in the repo (per-dispatch
    timing + counters in `_fit_batch`/`fit_steps`, prefetch gauges and
    producer-wait timing in `DevicePrefetchIterator`, the epoch span).

    Each side gets its own net + prefetch iterator, one warmup epoch
    (compile), then `repeats` measured epochs interleaved on/off so clock
    drift and cache effects hit both sides equally; min-of-N per side.
    """
    from deeplearning4j_tpu.data import DevicePrefetchIterator
    from deeplearning4j_tpu.monitor import registry, set_enabled

    make_it, make_net, nz = _pipeline_fixture(n_batches, batch, n_in)

    def make_side():
        net = make_net()
        net.set_normalizer(nz)                    # on-device prologue
        return net, DevicePrefetchIterator(make_it(), depth=depth)

    net_on, pf_on = make_side()
    net_off, pf_off = make_side()

    def epoch(net, pf):
        t0 = time.perf_counter()
        net.fit(pf, fused_steps=fused_steps)
        float(net.score())                        # one sync at the end
        return time.perf_counter() - t0

    t_on, t_off = [], []
    try:
        set_enabled(True)
        epoch(net_on, pf_on)                      # warmup + compile
        set_enabled(False)
        epoch(net_off, pf_off)
        for _ in range(repeats):
            set_enabled(True)
            t_on.append(epoch(net_on, pf_on))
            set_enabled(False)
            t_off.append(epoch(net_off, pf_off))
    finally:
        set_enabled(True)
        pf_on.close()
        pf_off.close()

    best_on, best_off = min(t_on), min(t_off)
    steps = registry().get("training_steps_total",
                           {"model": "MultiLayerNetwork"})
    return {"wall_on_s": best_on, "wall_off_s": best_off,
            "overhead_pct": (best_on - best_off) / best_off * 100.0,
            "steps_recorded": steps.value if steps is not None else 0,
            "n_batches": n_batches, "batch": batch,
            "fused_steps": fused_steps, "prefetch_depth": depth,
            "repeats": repeats}


def bench_resilience(n_batches=256, batch=64, n_in=784, save_every=128,
                     keep_last=3, depth=2, repeats=3):
    """A/B the fault-tolerance tax on the `--pipeline` training loop: the
    SAME per-step loop over `DevicePrefetchIterator`-staged batches runs
    with a `CheckpointManager(async_save=True)` committing every
    `save_every` steps (host snapshot on the step path, npz write +
    retention GC on a background thread, `wait()` INSIDE the timed
    region so in-flight writes are charged to the checkpointing side)
    versus bare.  The async design means the on-path cost is the
    synchronous `device_get` snapshot only (~1ms here); the rest is the
    background writer contending for host cores with XLA — real on this
    CPU A/B, absent on an accelerator.  Even at the bench cadence (a
    full checkpoint every ~128 steps, i.e. every few hundred ms of
    compute — production jobs checkpoint every few MINUTES) the gate
    asserts the whole thing stays under 5% of step time.

    Each side gets its own net + prefetch iterator, one warmup epoch
    (compile), then `repeats` measured epochs interleaved so clock drift
    hits both sides equally; min-of-N per side.
    """
    import os
    import shutil
    import tempfile

    from deeplearning4j_tpu.data import DevicePrefetchIterator
    from deeplearning4j_tpu.monitor.registry import registry
    from deeplearning4j_tpu.train.resilience import CheckpointManager

    make_it, make_net, nz = _pipeline_fixture(n_batches, batch, n_in)
    ckpt_root = tempfile.mkdtemp(prefix="bench_resilience_")

    def make_side(with_ckpt):
        net = make_net()
        net.set_normalizer(nz)                    # on-device prologue
        mgr = CheckpointManager(
            os.path.join(ckpt_root, "ck"), keep_last=keep_last,
            save_every_steps=save_every,
            async_save=True) if with_ckpt else None
        return net, mgr

    def epoch(net, mgr):
        pf = DevicePrefetchIterator(make_it(), depth=depth)
        t0 = time.perf_counter()
        for ds in pf:
            net._fit_dataset(ds)
            if mgr is not None:
                mgr.maybe_save(net)
        if mgr is not None:
            mgr.wait()                            # charge in-flight writes
        float(net.score())                        # one sync at the end
        return time.perf_counter() - t0

    net_ck, mgr = make_side(True)
    net_bare, _ = make_side(False)
    t_ck, t_bare = [], []
    try:
        epoch(net_ck, mgr)                        # warmup + compile
        epoch(net_bare, None)
        for _ in range(repeats):
            t_ck.append(epoch(net_ck, mgr))
            t_bare.append(epoch(net_bare, None))
    finally:
        mgr.wait()
        shutil.rmtree(ckpt_root, ignore_errors=True)

    best_ck, best_bare = min(t_ck), min(t_bare)
    n = n_batches * batch
    saves = registry().counter("resilience_checkpoints_total").value
    saved_bytes = registry().gauge("resilience_checkpoint_bytes").value
    return {"wall_ckpt_s": best_ck, "wall_bare_s": best_bare,
            "overhead_pct": (best_ck - best_bare) / best_bare * 100.0,
            "ckpt_samples_per_sec": n / best_ck,
            "bare_samples_per_sec": n / best_bare,
            "checkpoints_committed": saves,
            "checkpoint_bytes_total": saved_bytes,
            "save_every_steps": save_every, "keep_last": keep_last,
            "n_batches": n_batches, "batch": batch, "repeats": repeats}


def bench_zero1(batch=256, steps=48, fused_steps=8, n_in=256, hidden=1024):
    """A/B the ZeRO-1 sharded weight update against the replicated update
    on the same data mesh, model and batches (`ParallelWrapper` with and
    without `optimizer_sharding`): identical math (asserted at the end),
    different schedule + optimizer-state residency.  The structural win is
    per-replica optimizer-state HBM (~N×, `opt_bytes_ratio`); on real
    chips the reduce-scatter/all-gather decomposition also overlaps with
    backward, on a host-simulated CPU mesh the wall A/B mostly reads
    collective overhead."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.parallel import (ParallelWrapper, make_mesh,
                                             zero)
    from deeplearning4j_tpu.train.updaters import Adam

    devs = jax.devices()
    n = len(devs)

    def make_net():
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
                .list([DenseLayer(n_out=hidden, activation="relu"),
                       DenseLayer(n_out=hidden, activation="relu"),
                       OutputLayer(n_out=10, loss="mcxent",
                                   activation="softmax")])
                .set_input_type(InputType.feed_forward(n_in)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.RandomState(0)
    x = rng.randn(batch, n_in).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, batch)]
    xs = jnp.broadcast_to(jnp.asarray(x), (fused_steps,) + x.shape)
    ys = jnp.broadcast_to(jnp.asarray(y), (fused_steps,) + y.shape)
    blocks = max(steps // fused_steps, 1)

    def side(sharded):
        net = make_net()
        pw = ParallelWrapper(net, make_mesh({"data": n}, devs),
                             optimizer_sharding=sharded)
        dt = _time_steps(lambda: pw.fit_steps(xs, ys), n_warmup=1,
                         n_steps=blocks, sync_fn=lambda: float(net.score()))
        return net, dt, zero.opt_state_bytes_per_replica(net.opt_state_)

    net_a, t_repl, bytes_repl = side(False)
    net_b, t_z1, bytes_z1 = side(True)
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))),
        net_a.params_, net_b.params_)
    max_diff = max(jax.tree_util.tree_leaves(diffs))
    n_samples = batch * fused_steps * blocks
    return {"devices": n, "batch": batch, "fused_steps": fused_steps,
            "steps": fused_steps * blocks,
            "replicated_wall_s": t_repl, "zero1_wall_s": t_z1,
            "replicated_samples_per_sec": n_samples / t_repl,
            "zero1_samples_per_sec": n_samples / t_z1,
            "speedup_vs_replicated": t_repl / t_z1,
            "opt_bytes_replicated": bytes_repl,
            "opt_bytes_zero1": bytes_z1,
            "opt_bytes_ratio": bytes_repl / max(bytes_z1, 1),
            "max_param_diff": max_diff}


def main_zero1(quick: bool):
    """`--zero1` mode: A/B detail to stderr + BENCH_zero1.json, ONE stdout
    JSON line.  CPU fallback simulates an 8-device mesh (a 1-device run
    would make both the sharding and the A/B degenerate)."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; zero1 bench on "
                  "simulated 8-way CPU mesh", file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    if os.environ.get("JAX_PLATFORMS") == "cpu" and \
            "device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    try:
        r = (bench_zero1(batch=64, steps=16, fused_steps=4, hidden=256)
             if quick else bench_zero1())
    except Exception as e:
        print(json.dumps({"metric": "zero1_train_samples_per_sec",
                          "value": None, "unit": "samples/sec",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[zero1] {k} = {v}", file=sys.stderr, flush=True)
    import os
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_zero1.json"), "w") as f:
        json.dump(r, f, indent=2)
    print(json.dumps({
        "metric": "zero1_train_samples_per_sec",
        "value": round(r["zero1_samples_per_sec"], 1),
        "unit": "samples/sec",
        "replicated_samples_per_sec":
            round(r["replicated_samples_per_sec"], 1),
        "speedup_vs_replicated": round(r["speedup_vs_replicated"], 3),
        "opt_bytes_ratio": round(r["opt_bytes_ratio"], 2),
        "max_param_diff": r["max_param_diff"],
    }))


def bench_comms(steps=150, batch=32, procs=2, devices_per_process=2):
    """A/B the hierarchical gradient exchange: dense f32 vs threshold-
    compressed int streams across a simulated 2-host gang.

    Each "host" is a real OS process with its own XLA CPU client and
    local mesh (LocalLauncher), coupled ONLY by the TCP gradient mesh —
    the compiled grad half reduces over the local devices (ICI role), the
    host-side exchange combines across processes (DCN role).  Both sides
    train the same model on the same global data stream; the compressed
    side must land within 1% of the dense final loss on >=5x fewer
    cross-host bytes."""
    import os
    import tempfile
    from deeplearning4j_tpu.parallel.multihost import (LocalLauncher,
                                                       free_port)
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "mh_worker_comms.py")
    out = {}
    with tempfile.TemporaryDirectory() as td:
        for mode in ("dense", "compressed"):
            launcher = LocalLauncher(procs, devices_per_process)
            t0 = time.time()
            launcher.run(worker, [td, mode, steps, batch], timeout=600.0,
                         gradient_port=free_port())
            dt = time.time() - t0
            curves = [np.load(os.path.join(td, f"curve_{mode}_{r}.npz"))
                      for r in range(procs)]
            stats = []
            for r in range(procs):
                with open(os.path.join(td,
                                       f"stats_{mode}_{r}.json")) as f:
                    stats.append(json.load(f))
            # replica consistency: every rank applies the same combined
            # gradient, so end-of-run params must agree across ranks
            for r in range(1, procs):
                np.testing.assert_allclose(curves[0]["w0"],
                                           curves[r]["w0"],
                                           rtol=1e-5, atol=1e-6)
            wire = sum(s["bytes_sent_total"] + s["bytes_received_total"]
                       for s in stats)
            mean_curve = np.mean([c["losses"] for c in curves], axis=0)
            out[mode] = {
                "wall_s": dt, "steps_per_sec": steps / dt,
                "wire_bytes": wire,
                "final_loss": float(mean_curve[-1]),
                "compression_ratio_last":
                    max(s["last_compression_ratio"] for s in stats),
                "loss_curve": [round(float(v), 5) for v in mean_curve],
            }
    dense, comp = out["dense"], out["compressed"]
    reduction = dense["wire_bytes"] / max(comp["wire_bytes"], 1)
    parity = (abs(comp["final_loss"] - dense["final_loss"])
              / max(abs(dense["final_loss"]), 1e-9))
    return {"procs": procs, "devices_per_process": devices_per_process,
            "steps": steps, "batch_per_host": batch,
            "bytes_reduction_x": reduction, "loss_parity_rel": parity,
            "dense": dense, "compressed": comp}


def main_comms(quick: bool):
    """`--comms` mode: A/B detail to stderr + BENCH_comms.json, ONE
    stdout JSON line.  The gang itself always runs on forced-CPU child
    processes (LocalLauncher), so no backend probe is needed — this mode
    measures the DCN exchange, not the accelerator."""
    import os
    try:
        r = (bench_comms(steps=100) if quick else bench_comms())
    except Exception as e:
        print(json.dumps({"metric": "comms_bytes_reduction_x",
                          "value": None, "unit": "x",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        if k in ("dense", "compressed"):
            for kk, vv in v.items():
                if kk != "loss_curve":
                    print(f"[comms] {k}.{kk} = {vv}", file=sys.stderr,
                          flush=True)
        else:
            print(f"[comms] {k} = {v}", file=sys.stderr, flush=True)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_comms.json"), "w") as f:
        json.dump(r, f, indent=2)
    ok = r["bytes_reduction_x"] >= 5.0 and r["loss_parity_rel"] <= 0.01
    print(json.dumps({
        "metric": "comms_bytes_reduction_x",
        "value": round(r["bytes_reduction_x"], 2),
        "unit": "x",
        "loss_parity_rel": round(r["loss_parity_rel"], 5),
        "dense_steps_per_sec": round(r["dense"]["steps_per_sec"], 1),
        "compressed_steps_per_sec":
            round(r["compressed"]["steps_per_sec"], 1),
        "pass": ok,
    }))
    if not ok:
        sys.exit(1)


def bench_elastic(steps=24, kill_step=8, heartbeat_s=0.1,
                  failure_deadline_s=2.0, overhead_budget_ms=15000.0):
    """A/B elastic gang survival: a 3-process gang whose rank 2 is killed
    mid-run (shrink-and-continue) vs the same training uninterrupted.

    Three runs: (A) 3-proc gang with a mid-run kill — the survivors must
    detect within the failure deadline, re-form at world 2 under a new
    generation, and resume from the coordinated checkpoint; (B) a clean
    world-2 gang started from THAT checkpoint — A's final loss must match
    it (nothing lost or double-counted across the reformation); (C) a
    clean 3-proc run of the same length, the wall-clock baseline the
    reformation overhead is reported against."""
    import os
    import shutil
    import tempfile
    from deeplearning4j_tpu.parallel.multihost import ElasticLocalRunner
    from deeplearning4j_tpu.train.resilience import CheckpointManager
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "mh_worker_elastic_gang.py")

    def run(tag, td, procs, kill_rank, kill_at, ckpt_dir):
        out_dir = os.path.join(td, f"out_{tag}")
        os.makedirs(out_dir)
        t0 = time.time()
        res = ElasticLocalRunner(procs, backoff_base_s=0.2).run_elastic(
            worker, [out_dir, str(steps), "1", str(kill_rank), str(kill_at)],
            timeout=600.0, checkpoint_dir=ckpt_dir, policy="shrink",
            heartbeat_s=heartbeat_s, failure_deadline_s=failure_deadline_s,
            relaunch=False)
        wall = time.time() - t0
        if res["r0"][0] != 0:
            raise RuntimeError(f"{tag}: rank 0 failed:\n"
                               + res["r0"][1][-2000:])
        final = np.load(os.path.join(out_dir, "final_0.npz"))
        with open(os.path.join(out_dir, "elastic_0.json")) as f:
            info = json.load(f)
        return wall, final, info

    with tempfile.TemporaryDirectory() as td:
        ckpt_a = os.path.join(td, "ckpt_a")
        wall_a, final_a, info_a = run("a", td, 3, 2, kill_step, ckpt_a)
        reforms = info_a["reformations"]
        if len(reforms) != 1:
            raise RuntimeError(f"expected 1 reformation, got {reforms}")
        rf = reforms[0]
        # B: uninterrupted world-2 comparator from the resume checkpoint
        ckpt_b = os.path.join(td, "ckpt_b")
        shutil.copytree(ckpt_a, ckpt_b)
        for name in os.listdir(ckpt_b):
            p = os.path.join(ckpt_b, name)
            if os.path.isdir(p) and name.startswith(CheckpointManager.PREFIX) \
                    and int(name[len(CheckpointManager.PREFIX):]) \
                    > int(rf["resume_step"]):
                shutil.rmtree(p)
        _, final_b, _ = run("b", td, 2, -1, 0, ckpt_b)
        # C: clean 3-proc baseline for the wall-clock overhead
        wall_c, _, _ = run("c", td, 3, -1, 0, os.path.join(td, "ckpt_c"))
    loss_a, loss_b = float(final_a["score"]), float(final_b["score"])
    loss_delta_rel = abs(loss_a - loss_b) / max(abs(loss_b), 1e-12)
    return {
        "steps": steps, "kill_step": kill_step,
        "heartbeat_s": heartbeat_s,
        "failure_deadline_s": failure_deadline_s,
        "cause": rf["cause"], "world_after": rf["world"],
        "generation_after": info_a["stats"]["generation"],
        "detection_ms": rf["detection_ms"],
        "resume_ms": rf["resume_ms"],
        "reformation_ms": rf["detection_ms"] + rf["resume_ms"],
        "overhead_budget_ms": overhead_budget_ms,
        "final_loss_chaos": loss_a,
        "final_loss_uninterrupted": loss_b,
        "loss_delta_rel": loss_delta_rel,
        "wall_chaos_s": wall_a, "wall_clean_s": wall_c,
        "wall_overhead_s": wall_a - wall_c,
    }


def main_elastic(quick: bool):
    """`--elastic` mode: chaos A/B detail to stderr + BENCH_elastic.json,
    ONE stdout JSON line.  Gates: failure detected within the configured
    deadline (plus reactor slack), resumed final loss matches the
    uninterrupted-from-checkpoint run, and the whole
    detection-to-resumed interruption stays inside the overhead budget.
    The gang runs on forced-CPU child processes, so no backend probe."""
    import os
    try:
        r = (bench_elastic(steps=12, kill_step=4) if quick
             else bench_elastic())
    except Exception as e:
        print(json.dumps({"metric": "elastic_reformation_ms",
                          "value": None, "unit": "ms",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[elastic] {k} = {v}", file=sys.stderr, flush=True)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_elastic.json"), "w") as f:
        json.dump(r, f, indent=2)
    detect_ok = r["detection_ms"] is not None and \
        r["detection_ms"] <= r["failure_deadline_s"] * 1000.0 + 2000.0
    loss_ok = r["loss_delta_rel"] <= 1e-9       # bitwise in practice
    overhead_ok = r["reformation_ms"] <= r["overhead_budget_ms"]
    ok = detect_ok and loss_ok and overhead_ok
    print(json.dumps({
        "metric": "elastic_reformation_ms",
        "value": round(r["reformation_ms"], 1),
        "unit": "ms",
        "detection_ms": round(r["detection_ms"], 1),
        "resume_ms": round(r["resume_ms"], 1),
        "loss_delta_rel": r["loss_delta_rel"],
        "detect_ok": detect_ok, "loss_ok": loss_ok,
        "overhead_ok": overhead_ok,
        "pass": ok,
    }))
    if not ok:
        sys.exit(1)


def main_pipeline(quick: bool):
    """`--pipeline` mode: A/B detail to stderr, ONE stdout JSON line."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        # same bounded probe as --serving: the pipeline is backend-agnostic,
        # so fall back to CPU rather than hang on a dead TPU tunnel
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; pipeline bench on CPU",
                  file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = (bench_pipeline(n_batches=96, batch=64, fused_steps=8)
             if quick else bench_pipeline())
    except Exception as e:
        print(json.dumps({"metric": "pipeline_train_samples_per_sec",
                          "value": None, "unit": "samples/sec",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[pipeline] {k} = {v}", file=sys.stderr, flush=True)
    print(json.dumps({
        "metric": "pipeline_train_samples_per_sec",
        "value": round(r["pipeline_samples_per_sec"], 1),
        "unit": "samples/sec",
        "sync_wall_s": round(r["sync_wall_s"], 3),
        "pipeline_wall_s": round(r["pipeline_wall_s"], 3),
        "speedup_vs_sync_loop": round(r["speedup"], 2),
    }))


def main_obs(quick: bool):
    """`--obs` mode: telemetry-overhead A/B detail to stderr, ONE stdout
    JSON line asserting the enabled-path overhead stays under 2%."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        # the instrumented loop is backend-agnostic; fall back to CPU
        # rather than hang on a dead TPU tunnel
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; obs bench on CPU",
                  file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = (bench_obs(n_batches=48, repeats=2) if quick else bench_obs())
    except Exception as e:
        print(json.dumps({"metric": "telemetry_overhead_pct", "value": None,
                          "unit": "%", "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[obs] {k} = {v}", file=sys.stderr, flush=True)
    ok = r["overhead_pct"] < 2.0 and r["steps_recorded"] > 0
    print(json.dumps({
        "metric": "telemetry_overhead_pct",
        "value": round(r["overhead_pct"], 3),
        "unit": "%",
        "threshold_pct": 2.0,
        "pass": ok,
        "wall_on_s": round(r["wall_on_s"], 3),
        "wall_off_s": round(r["wall_off_s"], 3),
        "steps_recorded": r["steps_recorded"],
    }))
    if not ok:
        sys.exit(1)


def main_resilience(quick: bool):
    """`--resilience` mode: checkpointing-overhead A/B detail to stderr,
    ONE stdout JSON line asserting the async-save step overhead stays
    under 5%."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        # the checkpoint path is backend-agnostic; fall back to CPU
        # rather than hang on a dead TPU tunnel
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; resilience bench on "
                  "CPU", file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = (bench_resilience(n_batches=128, repeats=2) if quick
             else bench_resilience())
    except Exception as e:
        print(json.dumps({"metric": "resilience_ckpt_overhead_pct",
                          "value": None, "unit": "%",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[resilience] {k} = {v}", file=sys.stderr, flush=True)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_resilience.json"), "w") as f:
        json.dump(r, f, indent=2)
    ok = r["overhead_pct"] < 5.0 and r["checkpoints_committed"] > 0
    print(json.dumps({
        "metric": "resilience_ckpt_overhead_pct",
        "value": round(r["overhead_pct"], 3),
        "unit": "%",
        "threshold_pct": 5.0,
        "pass": ok,
        "wall_ckpt_s": round(r["wall_ckpt_s"], 3),
        "wall_bare_s": round(r["wall_bare_s"], 3),
        "checkpoints_committed": r["checkpoints_committed"],
        "checkpoint_bytes_total": r["checkpoint_bytes_total"],
    }))
    if not ok:
        sys.exit(1)


def main_serving(quick: bool):
    """`--serving` mode: serving metrics to stderr, ONE stdout JSON line."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        # probe the TPU backend once (it can hang, not raise — see
        # _wait_for_backend); fall back to CPU rather than block: the
        # serving runtime is backend-agnostic
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; serving bench on CPU",
                  file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = bench_serving(duration_s=1.0 if quick else 3.0,
                          n_clients=8 if quick else 16)
    except Exception as e:
        print(json.dumps({"metric": "serving_lenet_requests_per_sec",
                          "value": None, "unit": "requests/sec",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[serving] {k} = {v}", file=sys.stderr, flush=True)
    print(json.dumps({
        "metric": "serving_lenet_requests_per_sec",
        "value": round(r["requests_per_sec"], 1),
        "unit": "requests/sec",
        "p50_ms": round(r["p50_ms"], 2),
        "p99_ms": round(r["p99_ms"], 2),
        "rows_per_sec": round(r["rows_per_sec"], 1),
        "batch_occupancy": round(r["batch_occupancy"], 2),
    }))


def bench_fleet(n_models=16, max_resident=4, duration_s=4.0,
                flood_requests=400):
    """`--fleet` A/B: a long-tail model population through a warm-pooled
    `serving.ModelFleet` vs the naive always-resident posture.

    Phase A (capacity): `n_models` distinct MLPs served through a
    `max_resident`-slot warm pool backed by a persistent AOT cache.  The
    naive baseline needs all `n_models` param sets device-resident at
    once; the fleet's peak residency is `max_resident` of them.  Gate (i):
    models served per fixed device-memory budget >= 2x naive.  The second
    sweep must be compile-free — every re-admission deserializes from the
    persistent cache.

    Phase B (overload): one high-priority model (generous SLO) plus one
    low-priority model flooded far past capacity.  The flood drives the
    low-priority p99 over its target; the fleet sheds low-priority traffic
    and keeps serving.  Gate (ii): high-priority p99 stays within its SLO
    while low-priority sheds are non-zero."""
    import shutil
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.serving import (LatencySLO, ModelFleet,
                                            RejectedError)
    from deeplearning4j_tpu.train.updaters import Sgd

    n_in = 32

    def make_net(seed, hidden):
        conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(1e-1))
                .list([DenseLayer(n_out=hidden, activation="relu"),
                       OutputLayer(n_out=10, loss="mcxent",
                                   activation="softmax")])
                .set_input_type(InputType.feed_forward(n_in)).build())
        return MultiLayerNetwork(conf).init()

    cache_dir = tempfile.mkdtemp(prefix="bench-fleet-")
    try:
        # ---- Phase A: long-tail capacity through the warm pool ----
        fleet = ModelFleet(max_resident=max_resident,
                           n_slices=2 * max_resident, max_batch=8,
                           batch_timeout_ms=1.0, cache_dir=cache_dir)
        per_model_bytes = []
        for i in range(n_models):
            # distinct widths -> distinct architecture fingerprints (no
            # cross-model executable sharing flattering the cache)
            net = make_net(i, 48 + 8 * (i % 8))
            import jax
            per_model_bytes.append(sum(
                leaf.nbytes for leaf in
                jax.tree_util.tree_leaves(net.params_)))
            fleet.deploy(f"m{i:02d}", net,
                         slo=LatencySLO(target_p99_ms=1000.0))
        rng = np.random.RandomState(0)
        reqs = 0
        t0 = time.perf_counter()
        compiles_after_first = None
        for sweep in range(2):
            for i in rng.permutation(n_models):
                x = rng.rand(2, n_in).astype(np.float32)
                fleet.output(f"m{i:02d}", x, deadline_ms=60_000.0,
                             timeout=120)
                reqs += 1
            if sweep == 0:
                compiles_after_first = fleet.cache.stats["compiles"]
        sweep_dt = time.perf_counter() - t0
        second_sweep_compiles = (fleet.cache.stats["compiles"]
                                 - compiles_after_first)
        st = fleet.fleet_stats()
        cache_stats = dict(fleet.cache.stats)
        warm_admissions = sum(
            1 for m in st["models"].values()
            if m["last_admission_fresh_compiles"] == 0)
        peak_bytes = fleet.resident_bytes_peak
        naive_bytes = sum(per_model_bytes)
        # models servable per fixed budget: the fleet serves all n_models
        # inside a peak residency the naive posture would exhaust after
        # budget/per_model models
        ratio = naive_bytes / peak_bytes if peak_bytes else 0.0
        fleet.shutdown()

        # ---- Phase B: overload -> shed low priority, hold high p99 ----
        hi_slo_ms = 500.0
        fleet = ModelFleet(max_resident=2, n_slices=2, max_batch=8,
                           batch_timeout_ms=1.0, cache_dir=cache_dir,
                           observe_every=4)
        fleet.deploy("hi", make_net(1001, 64),
                     slo=LatencySLO(target_p99_ms=hi_slo_ms, priority=10),
                     warm=True)
        fleet.deploy("lo", make_net(1002, 64),
                     slo=LatencySLO(target_p99_ms=2.0, priority=0),
                     warm=True)
        stop = threading.Event()
        hi_results = []

        def hi_client():
            rs = np.random.RandomState(7)
            while not stop.is_set():
                x = rs.rand(2, n_in).astype(np.float32)
                try:
                    fleet.output("hi", x, timeout=60)
                    hi_results.append(1)
                except RejectedError:
                    hi_results.append(0)
                time.sleep(0.002)

        hi_thread = threading.Thread(target=hi_client, daemon=True)
        hi_thread.start()

        def lo_flood(i):
            rs = np.random.RandomState(i)
            served = shed = 0
            for _ in range(flood_requests):
                x = rs.rand(4, n_in).astype(np.float32)
                try:
                    f = fleet.submit("lo", x)
                    f.exception(timeout=60)          # resolve, keep going
                    served += 1
                except RejectedError:
                    shed += 1
            return served, shed

        t0 = time.perf_counter()
        with ThreadPoolExecutor(8) as ex:
            flood_totals = list(ex.map(lo_flood, range(8)))
        flood_dt = time.perf_counter() - t0
        end = time.monotonic() + min(duration_s, 2.0)
        while time.monotonic() < end:               # hold hi load post-flood
            time.sleep(0.05)
        stop.set()
        hi_thread.join(timeout=30)
        hi_p99 = fleet.member("hi").latency.percentiles((99,))["p99"]
        lo_sheds = fleet.member("lo").sheds
        lo_served = sum(s for s, _ in flood_totals)
        hi_served = sum(hi_results)
        hi_shed = len(hi_results) - hi_served
        breached = fleet.member("lo").tracker.breaches_total
        fleet.shutdown()
        return {
            "n_models": n_models,
            "max_resident": max_resident,
            "sweep_requests": reqs,
            "sweep_requests_per_sec": reqs / sweep_dt,
            "naive_resident_bytes": naive_bytes,
            "fleet_peak_resident_bytes": peak_bytes,
            "models_per_budget_ratio": ratio,
            "second_sweep_compiles": second_sweep_compiles,
            "warm_admissions": warm_admissions,
            "evictions": sum(m["evictions"] for m in st["models"].values()),
            "aot_cache": cache_stats,
            "hi_slo_ms": hi_slo_ms,
            "hi_p99_ms": hi_p99,
            "hi_served": hi_served,
            "hi_shed": hi_shed,
            "lo_served": lo_served,
            "lo_sheds": lo_sheds,
            "lo_breaches": breached,
            "flood_duration_s": flood_dt,
        }
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def main_fleet(quick: bool):
    """`--fleet` mode: A/B detail to stderr + BENCH_fleet.json, ONE stdout
    JSON line.  Gates: (i) >= 2x models per fixed device-memory budget vs
    always-resident, with a compile-free second sweep; (ii) high-priority
    p99 within SLO while low-priority traffic is shed under overload."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        # same bounded probe as --serving: the fleet is backend-agnostic,
        # so fall back to CPU rather than hang on an unreachable TPU
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; fleet bench on CPU",
                  file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = bench_fleet(n_models=8 if quick else 16,
                        max_resident=2 if quick else 4,
                        duration_s=1.0 if quick else 4.0,
                        flood_requests=120 if quick else 400)
    except Exception as e:
        print(json.dumps({"metric": "fleet_models_per_memory_budget",
                          "value": None, "unit": "x",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[fleet] {k} = {v}", file=sys.stderr, flush=True)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_fleet.json"), "w") as f:
        json.dump(r, f, indent=2)
    ok = (r["models_per_budget_ratio"] >= 2.0
          and r["second_sweep_compiles"] == 0
          and r["hi_p99_ms"] <= r["hi_slo_ms"]
          and r["lo_sheds"] > 0)
    print(json.dumps({
        "metric": "fleet_models_per_memory_budget",
        "value": round(r["models_per_budget_ratio"], 2),
        "unit": "x",
        "threshold": 2.0,
        "pass": ok,
        "second_sweep_compiles": r["second_sweep_compiles"],
        "hi_p99_ms": round(r["hi_p99_ms"], 2),
        "hi_slo_ms": r["hi_slo_ms"],
        "lo_sheds": r["lo_sheds"],
        "evictions": r["evictions"],
        "warm_admissions": r["warm_admissions"],
    }))
    if not ok:
        sys.exit(1)


def bench_fleetchaos(quick=False):
    """`--fleetchaos` gate: serving fault tolerance under injected
    replica failure (serving/resilience.py).

    Phase A (chaos flood): a hi-priority and a lo-priority member, two
    replicas each, flooded from client threads while `ReplicaChaos`
    KILLS one hi replica (every dispatch raises `ReplicaKilledError` —
    poison + failover) and HANGS one lo replica (a dispatch sleeps
    inside the compiled run — hedges cover the stuck requests, the
    controller declares it hung).  The reconcile loop must detect both,
    tear them down (remove-from-routing-first, bounded concurrent
    drain) and respawn them on the SAME slice through the persistent
    AOT cache.  Gates: zero lost accepted requests, hi-priority p99
    within its SLO through the failure, every respawn
    `fresh_compiles == 0`, detection->respawn bounded, and the
    degraded-mode ladder back at `full` once healed.

    Phase B (snapshot restart): the fleet commits a topology snapshot
    and shuts down; a NEW fleet process deploys the same models against
    the same cache dir and calls `restore_snapshot()`.  Gate: the
    pre-crash resident set and slice placements reconverge with zero
    cold compiles."""
    import itertools
    import os
    import shutil
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.serving import (FleetPolicy, LatencySLO,
                                            ModelFleet, RejectedError)
    from deeplearning4j_tpu.train.updaters import Sgd
    from deeplearning4j_tpu.utils.chaos import ReplicaChaos

    n_in = 16
    hi_slo_ms = 1500.0
    # 3s budget: the hedge fires at 1.5s — INSIDE the 2.5s hang window,
    # so requests stuck behind the hung dispatch resolve via their hedge
    deadline_ms = 3000.0
    flood = 60 if quick else 200            # requests per client thread
    clients = 3

    def make_net(seed, hidden=32):
        conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(1e-1))
                .list([DenseLayer(n_out=hidden, activation="relu"),
                       OutputLayer(n_out=4, loss="mcxent",
                                   activation="softmax")])
                .set_input_type(InputType.feed_forward(n_in)).build())
        return MultiLayerNetwork(conf).init()

    work_dir = tempfile.mkdtemp(prefix="bench-fleetchaos-")
    cache_dir = os.path.join(work_dir, "exec-cache")
    snap_path = os.path.join(work_dir, "fleet-snapshot.json")
    policy = FleetPolicy(respawn_after_s=0.3, hang_after_s=0.6,
                         drain_timeout_s=1.0, max_failovers=3,
                         ladder_down_after=4, ladder_up_after=3)

    def build_fleet(interval):
        return ModelFleet(max_resident=2, n_slices=4, max_batch=8,
                          batch_timeout_ms=1.0, cache_dir=cache_dir,
                          snapshot_path=snap_path, snapshot_interval_s=0.2,
                          reconcile_interval_s=interval, policy=policy,
                          observe_every=4)

    try:
        # ---- Phase A: chaos flood ----
        fleet = build_fleet(0.05)
        fleet.deploy("hi", make_net(1001),
                     slo=LatencySLO(target_p99_ms=hi_slo_ms, priority=10),
                     replicas=2, warm=True)
        fleet.deploy("lo", make_net(1002),
                     slo=LatencySLO(target_p99_ms=500.0, priority=0),
                     replicas=2, warm=True)
        # int8 standby for the ladder's quantized step; also makes every
        # later respawn warm BOTH versions from the shared AOT cache
        fleet.prepare_quantized("lo")
        x0 = np.random.RandomState(0).rand(2, n_in).astype(np.float32)
        for name in ("hi", "lo"):
            fleet.output(name, x0, deadline_ms=60_000.0, timeout=120)

        kill = ReplicaChaos(mode="kill", at_dispatch=0)
        hang = ReplicaChaos(mode="hang", at_dispatch=0, duration_s=2.5)
        armed = threading.Event()
        progress = itertools.count()         # requests submitted so far
        arm_at = flood * clients // 3        # fire MID-flood, data-driven

        def client(spec):
            name, seed = spec
            rs = np.random.RandomState(seed)
            served = failed = shed = 0
            lat = []
            for _ in range(flood):
                if next(progress) == arm_at:
                    # arm inside the flood, not on a wall clock — on a
                    # fast backend a timed arm can miss the flood window
                    kill.arm(fleet.member("hi").group.replicas[0])
                    hang.arm(fleet.member("lo").group.replicas[0])
                    armed.set()
                x = rs.rand(2, n_in).astype(np.float32)
                t0 = time.perf_counter()
                try:
                    f = fleet.submit(name, x, deadline_ms=deadline_ms)
                except RejectedError:
                    shed += 1
                    continue
                # accepted: this future MUST resolve — a kill/hang on
                # its replica has to fail over, not lose it
                if f.exception(timeout=60) is None:
                    served += 1
                    lat.append((time.perf_counter() - t0) * 1000.0)
                else:
                    failed += 1
            return name, served, failed, shed, lat

        specs = [("hi", 100 + i) for i in range(clients)] \
            + [("lo", 200 + i) for i in range(clients)]
        t0 = time.perf_counter()
        with ThreadPoolExecutor(len(specs)) as ex:
            results = list(ex.map(client, specs))
        flood_dt = time.perf_counter() - t0
        assert armed.wait(timeout=10), "chaos never armed"

        # wait for the controller to heal both members
        heal_deadline = time.monotonic() + 15.0
        while time.monotonic() < heal_deadline:
            healthy = all(
                r.healthy and not r.poisoned
                for name in ("hi", "lo")
                for r in fleet.member(name).group.snapshot())
            if healthy and fleet.member("hi").respawns >= 1 \
                    and fleet.member("lo").respawns >= 1:
                break
            time.sleep(0.05)
        # recovery: "lo" is in sustained SLO breach from the hang window
        # (its p99 window still holds the stuck-request latencies), so
        # it self-sheds all but every-8th probe.  Drive probe traffic
        # until fresh under-target samples displace the hang latencies,
        # the breach clears, and the ladder hysteresis walks back to
        # `full` — the explicit recovery half of the degraded ladder.
        lo_recovery_probes = 0
        recover_deadline = time.monotonic() + 30.0
        while time.monotonic() < recover_deadline:
            try:
                fleet.output("lo", x0, deadline_ms=60_000.0, timeout=120)
                lo_recovery_probes += 1
            except RejectedError:
                pass
            if not fleet.member("lo").tracker.breached \
                    and fleet.ladder.level == 0:
                break
        fleet.output("hi", x0, deadline_ms=60_000.0, timeout=120)

        respawn_actions = [a for rec in fleet.controller.history
                           for a in rec["actions"]
                           if a["action"] == "respawn"]
        hi_p99 = fleet.member("hi").latency.percentiles((99,))["p99"]
        served = {n: 0 for n, *_ in results}
        failed = dict(served)
        shed = dict(served)
        for name, s, f_, sh, _ in results:
            served[name] += s
            failed[name] += f_
            shed[name] += sh
        inst = fleet.instruments
        counters = {
            "hedges": inst.hedges.value,
            "hedge_wasted": inst.hedge_wasted.value,
            "failovers": inst.failovers.value,
            "drain_timeouts": inst.drain_timeouts.value,
            "replica_probes": inst.replica_probes.value,
        }
        ladder_transitions = list(fleet.ladder.transitions)
        ladder_level_end = fleet.ladder.level
        topo_before = {
            "resident": fleet.pool.resident_names(),
            "slices": {name: sorted(r.slice.index
                                    for r in fleet.member(name)
                                    .group.snapshot())
                       for name in ("hi", "lo")},
        }
        fleet.save_snapshot()
        fleet.shutdown()                     # commits a final snapshot too
        kill.restore()
        hang.restore()

        # ---- Phase B: restart from snapshot, zero cold compiles ----
        fleet2 = build_fleet(None)
        fleet2.deploy("hi", make_net(1001),
                      slo=LatencySLO(target_p99_ms=hi_slo_ms, priority=10))
        fleet2.deploy("lo", make_net(1002),
                      slo=LatencySLO(target_p99_ms=500.0, priority=0))
        restore = fleet2.restore_snapshot()
        topo_after = {
            "resident": fleet2.pool.resident_names(),
            "slices": {name: sorted(r.slice.index
                                    for r in fleet2.member(name)
                                    .group.snapshot())
                       for name in ("hi", "lo")},
        }
        for name in ("hi", "lo"):            # the restored fleet serves
            # the snapshot restores lo's sustained-breach hysteresis, so
            # its first probes may be shed exactly like pre-crash
            for _ in range(256):
                try:
                    fleet2.output(name, x0, deadline_ms=60_000.0,
                                  timeout=120)
                    break
                except RejectedError:
                    time.sleep(0.02)
            else:
                raise RuntimeError(
                    f"restored probe for '{name}' never admitted")
        fleet2.shutdown()

        return {
            "flood_requests": flood * clients * 2,
            "flood_duration_s": flood_dt,
            "hi_slo_ms": hi_slo_ms,
            "hi_p99_ms": hi_p99,
            "served": served,
            "failed": failed,
            "shed": shed,
            "lost_accepted": sum(failed.values()),
            "respawns": respawn_actions,
            "respawn_fresh_compiles": [a["fresh_compiles"]
                                       for a in respawn_actions],
            "detect_to_respawn_ms": [
                round(a["detect_ms"] + a["respawn_ms"], 3)
                for a in respawn_actions],
            "counters": counters,
            "lo_recovery_probes": lo_recovery_probes,
            "ladder_transitions": ladder_transitions,
            "ladder_level_end": ladder_level_end,
            "topology_before": topo_before,
            "topology_after": topo_after,
            "restore": restore,
        }
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)


def main_fleetchaos(quick: bool):
    """`--fleetchaos` mode: chaos detail to stderr + BENCH_fleetchaos.json,
    ONE stdout JSON line.  Gates: zero lost accepted requests through a
    replica kill + hang, hi-priority p99 within SLO, every respawn
    compile-free, detection->respawn bounded, snapshot restart
    reconverges to the pre-crash topology with zero cold compiles."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; fleetchaos bench on "
                  "CPU", file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = bench_fleetchaos(quick=quick)
    except Exception as e:
        print(json.dumps({"metric": "fleetchaos_lost_accepted",
                          "value": None, "unit": "requests",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[fleetchaos] {k} = {v}", file=sys.stderr, flush=True)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_fleetchaos.json"), "w") as f:
        json.dump(r, f, indent=2)
    causes = {a["cause"] for a in r["respawns"]}
    ok = (r["lost_accepted"] == 0
          and r["hi_p99_ms"] <= r["hi_slo_ms"]
          and len(r["respawns"]) >= 2
          and {"poisoned", "hung"} <= causes
          and all(c == 0 for c in r["respawn_fresh_compiles"])
          and all(ms <= 10_000.0 for ms in r["detect_to_respawn_ms"])
          and r["ladder_level_end"] == 0
          and r["restore"]["fresh_compiles"] == 0
          and r["topology_after"] == r["topology_before"])
    print(json.dumps({
        "metric": "fleetchaos_lost_accepted",
        "value": r["lost_accepted"],
        "unit": "requests",
        "threshold": 0,
        "pass": ok,
        "hi_p99_ms": round(r["hi_p99_ms"], 2),
        "hi_slo_ms": r["hi_slo_ms"],
        "respawns": len(r["respawns"]),
        "respawn_causes": sorted(causes),
        "respawn_fresh_compiles": r["respawn_fresh_compiles"],
        "detect_to_respawn_ms": r["detect_to_respawn_ms"],
        "restore_fresh_compiles": r["restore"]["fresh_compiles"],
        "ladder_level_end": r["ladder_level_end"],
        "hedges": r["counters"]["hedges"],
        "failovers": r["counters"]["failovers"],
    }))
    if not ok:
        sys.exit(1)


def bench_federation(quick=False):
    """`--federation` gate: cross-host fleet federation under injected
    host failure (serving/federation.py).

    Three in-process hosts, each a full `ModelFleet` (hi + lo members,
    all sharing one persistent AOT cache dir) behind a `HostAgent`,
    fronted by one `FederationRouter`.  Hi/lo client threads flood the
    router; mid-flood `HostChaos` KILLS the hi-affinity host (EOF ->
    cause ``crash``) and PARTITIONS a second host for a window (silence
    -> cause ``partition``; the replies it flushes on heal are
    generation-fenced and counted).  The router must evict both, fail
    over every orphaned in-flight request inside its deadline budget,
    and warm-re-place each dead host's models on a survivor from the
    replicated snapshot (`fresh_compiles == 0`).  The partitioned host
    auto-rejoins on heal; the killed host is relaunched as a NEW agent
    with the same host id and must be re-admitted at a bumped
    generation with its snapshot offered back.  Gates: zero lost
    accepted requests, zero malformed replies delivered, hi-priority
    p99 within SLO through both events, eviction causes >= {crash,
    partition}, every re-placement warm, stale dispatches fenced AND
    counted, detection->replacement bounded, both failed hosts back in
    the membership at the end."""
    import os
    import shutil
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.serving import (FederationPolicy,
                                            FederationRouter, HostAgent,
                                            LatencySLO, ModelFleet,
                                            RejectedError)
    from deeplearning4j_tpu.serving.federation import _rendezvous
    from deeplearning4j_tpu.train.updaters import Sgd
    from deeplearning4j_tpu.utils.chaos import HostChaos

    n_in = 16
    n_out = 4
    hi_slo_ms = 2500.0
    deadline_ms = 8000.0
    flood = 40 if quick else 120            # requests per client thread
    clients = 2                             # threads per priority class
    host_ids = ["h1", "h2", "h3"]

    def make_net(seed, hidden=32):
        conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(1e-1))
                .list([DenseLayer(n_out=hidden, activation="relu"),
                       OutputLayer(n_out=n_out, loss="mcxent",
                                   activation="softmax")])
                .set_input_type(InputType.feed_forward(n_in)).build())
        return MultiLayerNetwork(conf).init()

    work_dir = tempfile.mkdtemp(prefix="bench-federation-")
    cache_dir = os.path.join(work_dir, "exec-cache")   # SHARED across hosts
    policy = FederationPolicy(heartbeat_interval_s=0.1,
                              failure_deadline_s=0.8,
                              straggler_deadline_s=6.0,
                              max_failovers=3, affinity_slack=4,
                              ghost_linger_s=8.0)

    def build_fleet(host_id):
        d = os.path.join(work_dir, host_id)
        os.makedirs(d, exist_ok=True)
        fleet = ModelFleet(max_resident=2, n_slices=4, max_batch=8,
                           batch_timeout_ms=1.0, cache_dir=cache_dir,
                           snapshot_path=os.path.join(d, "snapshot.json"),
                           snapshot_interval_s=0.2, host_id=host_id,
                           observe_every=4)
        fleet.deploy("hi", make_net(1001),
                     slo=LatencySLO(target_p99_ms=hi_slo_ms, priority=10),
                     warm=True)
        fleet.deploy("lo", make_net(1002),
                     slo=LatencySLO(target_p99_ms=1000.0, priority=0),
                     warm=True)
        return fleet

    router = FederationRouter(
        policy, replicas_dir=os.path.join(work_dir, "router-replicas"))
    os.makedirs(router.replicas_dir, exist_ok=True)
    fleets, agents = {}, {}
    try:
        port = router.start(0)
        for h in host_ids:
            fleets[h] = build_fleet(h)
            agents[h] = HostAgent(
                h, fleets[h], ("127.0.0.1", port), policy=policy,
                replicas_dir=os.path.join(work_dir, h, "replicas")).start()
        x0 = np.random.RandomState(0).rand(2, n_in).astype(np.float32)
        for name in ("hi", "lo"):           # warm the cross-host path
            router.output(name, x0, deadline_ms=60_000.0, timeout=120)
        for h in host_ids:                  # replicate a snapshot of each
            fleets[h].save_snapshot()       # host's topology to the router
        rep_deadline = time.monotonic() + 10.0
        while time.monotonic() < rep_deadline:
            if set(router.federation_stats()["replicas"]) >= set(host_ids):
                break
            time.sleep(0.05)
        else:
            raise RuntimeError("snapshot replication never completed")

        # the hi-affinity host takes the kill (it is guaranteed traffic);
        # the lo-affinity host among the SURVIVORS takes the partition,
        # so its post-kill lo dispatches trip the chaos wrapper
        kill_host = _rendezvous(host_ids, "hi")
        part_host = _rendezvous([h for h in host_ids if h != kill_host],
                                "lo")
        kill = HostChaos(mode="kill", at_dispatch=0)
        part = HostChaos(mode="partition", at_dispatch=0, duration_s=1.5)
        armed = {"kill": threading.Event(), "part": threading.Event()}
        progress = threading.Lock()
        submitted = [0]
        total = flood * clients * 2

        def client(spec):
            name, prio, seed = spec
            rs = np.random.RandomState(seed)
            served = failed = shed = bad = 0
            lat = []
            for _ in range(flood):
                with progress:
                    submitted[0] += 1
                    n = submitted[0]
                if n == total // 4 and not kill.fired:
                    kill.arm(agents[kill_host])
                    armed["kill"].set()
                if n == total // 2 and not part.fired:
                    part.arm(agents[part_host])
                    armed["part"].set()
                x = rs.rand(2, n_in).astype(np.float32)
                t0 = time.perf_counter()
                try:
                    f = router.submit(name, x, priority=prio,
                                      deadline_ms=deadline_ms)
                except RejectedError:
                    shed += 1
                    continue
                # accepted: this future MUST resolve — a killed or
                # partitioned host has to fail over, not lose it
                exc = f.exception(timeout=60)
                if exc is None:
                    y = f.result()
                    if y.shape != (2, n_out):   # a stale reply delivered
                        bad += 1                # to a client would land here
                    else:
                        served += 1
                        lat.append((time.perf_counter() - t0) * 1000.0)
                else:
                    failed += 1
            return name, served, failed, shed, bad, lat

        specs = [("hi", 10, 100 + i) for i in range(clients)] \
            + [("lo", 0, 200 + i) for i in range(clients)]
        t0 = time.perf_counter()
        with ThreadPoolExecutor(len(specs)) as ex:
            results = list(ex.map(client, specs))
        flood_dt = time.perf_counter() - t0
        assert armed["kill"].wait(10) and armed["part"].wait(10), \
            "chaos never armed"

        # ---- sustain + recovery: the flood can outrun the failure
        # detector, so keep traffic flowing (still SLO-gated: sustain
        # hi latencies count toward p99) until BOTH faults have fired,
        # both evictions are replaced, and the partitioned host is back
        sustain = {"served": 0, "failed": 0, "shed": 0}
        sustain_hi_lat = []
        rs = np.random.RandomState(999)
        recover_deadline = time.monotonic() + 45.0
        while time.monotonic() < recover_deadline:
            ev = list(router.events)
            replaced = {e["host"] for e in ev if e["event"] == "replaced"}
            if kill.fired and part.fired \
                    and {kill_host, part_host} <= replaced \
                    and part_host in router.hosts() \
                    and agents[part_host].generation == router.generation:
                break
            for name, prio in (("hi", 10), ("lo", 0)):
                x = rs.rand(2, n_in).astype(np.float32)
                ts = time.perf_counter()
                try:
                    f = router.submit(name, x, priority=prio,
                                      deadline_ms=deadline_ms)
                except RejectedError:
                    sustain["shed"] += 1
                    continue
                if f.exception(timeout=60) is None:
                    sustain["served"] += 1
                    if name == "hi":
                        sustain_hi_lat.append(
                            (time.perf_counter() - ts) * 1000.0)
                else:
                    sustain["failed"] += 1
            time.sleep(0.02)
        else:
            raise RuntimeError(
                "federation never recovered: "
                f"kill.fired={kill.fired} part.fired={part.fired} "
                f"events={list(router.events)[-12:]}")
        events = list(router.events)
        evictions = [e for e in events if e["event"] == "evict"]
        replacements = [e for e in events if e["event"] == "replaced"]
        stale_fenced = int(router.instruments.stale_dispatch.value)

        # ---- relaunch the killed host: same id, NEW agent, bumped gen ----
        gen_before = router.generation
        relaunched = HostAgent(
            kill_host, fleets[kill_host], ("127.0.0.1", port),
            policy=policy,
            replicas_dir=os.path.join(work_dir, kill_host, "replicas"))
        relaunched.start(timeout=15.0)
        old_agent, agents[kill_host] = agents[kill_host], relaunched
        old_agent.close()
        for name in ("hi", "lo"):           # full membership serves again
            router.output(name, x0, deadline_ms=60_000.0, timeout=120)

        served = {n: 0 for n, *_ in results}
        failed, shed, bad = dict(served), dict(served), dict(served)
        hi_lat = list(sustain_hi_lat)
        for name, s, f_, sh, b, lat in results:
            served[name] += s
            failed[name] += f_
            shed[name] += sh
            bad[name] += b
            if name == "hi":
                hi_lat.extend(lat)
        hi_lat.sort()
        hi_p99 = hi_lat[min(len(hi_lat) - 1,
                            int(len(hi_lat) * 0.99))] if hi_lat else -1.0

        return {
            "flood_requests": total,
            "flood_duration_s": flood_dt,
            "hi_slo_ms": hi_slo_ms,
            "hi_p99_ms": hi_p99,
            "served": served,
            "failed": failed,
            "shed": shed,
            "bad_replies": bad,
            "sustain": sustain,
            "lost_accepted": sum(failed.values()) + sustain["failed"],
            "kill_host": kill_host,
            "part_host": part_host,
            "evictions": [{k: e[k] for k in
                           ("host", "cause", "detection_ms", "generation")}
                          for e in evictions],
            "replacements": [{k: e[k] for k in
                              ("host", "on", "models", "fresh_compiles",
                               "warm", "replace_ms")}
                             for e in replacements],
            "stale_fenced": stale_fenced,
            "part_host_rejoins": agents[part_host].rejoins,
            "relaunch_generation_before": gen_before,
            "relaunch_generation_after": router.generation,
            "relaunch_agent_generation": relaunched.generation,
            "relaunch_snapshot_restored": relaunched.restored is not None,
            "final_hosts": router.hosts(),
            "final_healthz": router.healthz(),
        }
    finally:
        for a in agents.values():
            try:
                a.close()
            except Exception:
                pass
        router.shutdown()
        for f in fleets.values():
            try:
                f.shutdown()
            except Exception:
                pass
        shutil.rmtree(work_dir, ignore_errors=True)


def main_federation(quick: bool):
    """`--federation` mode: federation detail to stderr +
    BENCH_federation.json, ONE stdout JSON line.  Gates: zero lost
    accepted requests through a host kill + a host partition, zero
    stale replies delivered to clients (fenced AND counted instead),
    hi-priority p99 within SLO, both evictions warm-re-placed within
    bound, partitioned host auto-rejoined, killed host re-admitted at a
    bumped generation."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; federation bench on "
                  "CPU", file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = bench_federation(quick=quick)
    except Exception as e:
        print(json.dumps({"metric": "federation_lost_accepted",
                          "value": None, "unit": "requests",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[federation] {k} = {v}", file=sys.stderr, flush=True)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_federation.json"), "w") as f:
        json.dump(r, f, indent=2)
    causes = {e["cause"] for e in r["evictions"]}
    replaced_hosts = {p["host"] for p in r["replacements"]}
    ok = (r["lost_accepted"] == 0
          and sum(r["bad_replies"].values()) == 0
          and r["hi_p99_ms"] <= r["hi_slo_ms"]
          and {"crash", "partition"} <= causes
          and {r["kill_host"], r["part_host"]} <= replaced_hosts
          and all(p["warm"] and p["fresh_compiles"] == 0
                  for p in r["replacements"])
          and all(e["detection_ms"] <= 5_000.0 for e in r["evictions"])
          and all(p["replace_ms"] <= 10_000.0 for p in r["replacements"])
          and r["stale_fenced"] >= 1
          and r["part_host_rejoins"] >= 1
          and r["relaunch_generation_after"]
          > r["relaunch_generation_before"]
          and r["relaunch_agent_generation"]
          == r["relaunch_generation_after"]
          and sorted(r["final_hosts"]) == ["h1", "h2", "h3"]
          and r["final_healthz"]["ok"])
    print(json.dumps({
        "metric": "federation_lost_accepted",
        "value": r["lost_accepted"],
        "unit": "requests",
        "threshold": 0,
        "pass": ok,
        "hi_p99_ms": round(r["hi_p99_ms"], 2),
        "hi_slo_ms": r["hi_slo_ms"],
        "eviction_causes": sorted(causes),
        "replacements_warm": [p["warm"] for p in r["replacements"]],
        "detection_ms": [e["detection_ms"] for e in r["evictions"]],
        "replace_ms": [p["replace_ms"] for p in r["replacements"]],
        "stale_fenced": r["stale_fenced"],
        "part_host_rejoins": r["part_host_rejoins"],
        "relaunch_generation": r["relaunch_generation_after"],
        "final_hosts": r["final_hosts"],
    }))
    if not ok:
        sys.exit(1)


def aot_child(cache_dir: str, steps: int, batch: int, n_in: int):
    """`--aot-child` worker: ONE process's cold-or-warm measurement.

    Builds the pipeline-fixture MLP with its train step routed through the
    persistent executable cache at `cache_dir`, times time-to-first-step
    and steady-state throughput, then warms a persistent-tier serving
    bucket ladder for the same model.  Prints one JSON line; the parent
    (`bench_aot`) runs this twice against the same directory — the first
    run pays every compile, the second must deserialize all of them."""
    from deeplearning4j_tpu.compile import PersistentExecutableCache
    from deeplearning4j_tpu.serving import BucketedCompileCache

    _, make_net, _ = _pipeline_fixture(1, batch, n_in)
    cache = PersistentExecutableCache(cache_dir)
    net = make_net().set_executable_cache(cache)
    rng = np.random.RandomState(0)
    x = rng.rand(batch, n_in).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, batch)]

    t0 = time.perf_counter()
    net.fit(x, y)
    float(net.score())                       # force completion
    t_first = time.perf_counter() - t0       # compile-or-deserialize + step

    t0 = time.perf_counter()
    for _ in range(steps):
        net.fit(x, y)
    float(net.score())
    t_steady = time.perf_counter() - t0

    scache = BucketedCompileCache(max_batch=16, persistent=cache)
    t0 = time.perf_counter()
    scache.warmup("bench:v1", net, (n_in,), np.float32, parallel=True)
    t_warm = time.perf_counter() - t0

    print(json.dumps({
        "time_to_first_step_s": t_first,
        "steady_steps_per_sec": steps / t_steady,
        "serving_warmup_s": t_warm,
        "serving_buckets": len(scache.buckets),
        "compiles": cache.stats["compiles"],
        "disk_hits": cache.stats["disk_hits"],
        "stores": cache.stats["stores"],
        "bytes_read": cache.stats["bytes_read"],
        "bytes_written": cache.stats["bytes_written"],
    }))


def bench_aot(steps=24, batch=64, n_in=256):
    """Cold vs warm process-start A/B through the persistent executable
    cache: two identical subprocesses share one cache directory — the
    first compiles and persists every executable (train step + every
    serving bucket), the second must start warm (0 compiles, pure
    deserialization)."""
    import os
    import shutil
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="bench-aot-")
    try:
        def child(tag):
            cmd = [sys.executable, os.path.abspath(__file__), "--aot-child",
                   cache_dir, str(steps), str(batch), str(n_in)]
            p = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=1200, env=dict(os.environ))
            if p.returncode != 0:
                raise RuntimeError(
                    f"{tag} aot child failed:\n{p.stderr[-2000:]}")
            return json.loads(p.stdout.strip().splitlines()[-1])

        cold = child("cold")
        warm = child("warm")
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return {
        "cold": cold, "warm": warm,
        "cold_start_s": cold["time_to_first_step_s"],
        "warm_start_s": warm["time_to_first_step_s"],
        "first_step_speedup": (cold["time_to_first_step_s"]
                               / max(warm["time_to_first_step_s"], 1e-9)),
        "warmup_speedup": (cold["serving_warmup_s"]
                           / max(warm["serving_warmup_s"], 1e-9)),
        "warm_compiles": warm["compiles"],
        "warm_zero_compiles": warm["compiles"] == 0,
        "steps": steps, "batch": batch, "n_in": n_in,
    }


def main_aot(quick: bool):
    """`--aot` mode: cold/warm subprocess A/B detail to stderr +
    BENCH_aot.json, ONE stdout JSON line.  Fails (exit 1) if the warm
    process performed any compile — that IS the acceptance contract."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; aot bench on CPU",
                  file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = (bench_aot(steps=8, batch=32, n_in=64) if quick
             else bench_aot())
    except Exception as e:
        print(json.dumps({"metric": "aot_warm_start_speedup",
                          "value": None, "unit": "x",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[aot] {k} = {v}", file=sys.stderr, flush=True)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_aot.json"), "w") as f:
        json.dump(r, f, indent=2)
    print(json.dumps({
        "metric": "aot_warm_start_speedup",
        "value": round(r["first_step_speedup"], 2),
        "unit": "x",
        "cold_start_s": round(r["cold_start_s"], 3),
        "warm_start_s": round(r["warm_start_s"], 3),
        "warmup_speedup": round(r["warmup_speedup"], 2),
        "warm_compiles": r["warm_compiles"],
        "warm_zero_compiles": r["warm_zero_compiles"],
    }))
    if not r["warm_zero_compiles"]:
        sys.exit(1)


def quant_child(cache_dir: str, steps: int, batch: int, n_in: int,
                hidden: int):
    """`--quant-child` worker: ONE process's f32-vs-int8 serving A/B.

    Builds a deterministic MLP, calibrates + quantizes it, warms both the
    f32 and the quantized bucket ladders through a BucketedCompileCache
    backed by the persistent executable cache at `cache_dir`, then times
    steady-state serving QPS for each.  Prints one JSON line; the parent
    (`bench_quant`) runs this twice against the same directory — the warm
    run must deserialize every executable (0 compiles), under a quantized
    fingerprint distinct from the f32 one."""
    from deeplearning4j_tpu.compile import (PersistentExecutableCache,
                                            model_fingerprint)
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.quant import (calibrate, parity_check,
                                          quantize_model)
    from deeplearning4j_tpu.serving import BucketedCompileCache
    from deeplearning4j_tpu.train.updaters import Sgd
    import jax

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=hidden, activation="relu"),
                   DenseLayer(n_out=hidden, activation="relu"),
                   OutputLayer(n_out=10, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.randn(batch, n_in).astype(np.float32)
    # train briefly: parity on an untrained net is all near-tied logits,
    # where a single int8 rounding flip misreads as an accuracy loss
    xt = rng.randn(256, n_in).astype(np.float32)
    yt = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 256)]
    for _ in range(8):
        net.fit(xt, yt)
    stats = calibrate(net, [rng.randn(batch, n_in).astype(np.float32)
                            for _ in range(4)], observer="percentile")
    qm = quantize_model(net, calibration=stats)
    x_eval = rng.randn(512, n_in).astype(np.float32)

    cache = PersistentExecutableCache(cache_dir)
    scache = BucketedCompileCache(max_batch=batch, persistent=cache)
    scache.warmup("f32:v1", net, (n_in,), np.float32)
    scache.warmup("int8:v1", qm, (n_in,), np.float32)

    def qps(key, model):
        scache.run(key, model, x)            # touch the exact bucket
        t0 = time.perf_counter()
        for _ in range(steps):
            out = scache.run(key, model, x)
        np.asarray(out)
        return steps * batch / (time.perf_counter() - t0)

    bytes_f32 = sum(l.nbytes
                    for l in jax.tree_util.tree_leaves(net.params_))
    print(json.dumps({
        "qps_f32": qps("f32:v1", net),
        "qps_int8": qps("int8:v1", qm),
        "bytes_f32": bytes_f32,
        "bytes_int8": qm.bytes_resident(),
        "parity_delta": parity_check(net, qm, x_eval)["delta"],
        "fp_f32": model_fingerprint(net),
        "fp_quant": model_fingerprint(qm),
        "compiles": cache.stats["compiles"],
        "disk_hits": cache.stats["disk_hits"],
        "stores": cache.stats["stores"],
    }))


def bench_quant(steps=200, batch=64, n_in=512, hidden=1024):
    """f32 vs int8 serving A/B plus the quantized warm-restart contract:
    two identical subprocesses share one persistent cache directory — the
    first compiles and persists the f32 AND quantized bucket ladders, the
    second must start warm with zero compiles."""
    import os
    import shutil
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="bench-quant-")
    try:
        def child(tag):
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--quant-child", cache_dir, str(steps), str(batch),
                   str(n_in), str(hidden)]
            p = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=1200, env=dict(os.environ))
            if p.returncode != 0:
                raise RuntimeError(
                    f"{tag} quant child failed:\n{p.stderr[-2000:]}")
            return json.loads(p.stdout.strip().splitlines()[-1])

        cold = child("cold")
        warm = child("warm")
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    qps_ratio = warm["qps_int8"] / max(warm["qps_f32"], 1e-9)
    bytes_ratio = cold["bytes_f32"] / max(cold["bytes_int8"], 1)
    tpb_ratio = qps_ratio * bytes_ratio      # throughput per byte resident
    return {
        "cold": cold, "warm": warm,
        "qps_speedup": qps_ratio,
        "bytes_resident_ratio": bytes_ratio,
        "throughput_per_byte_ratio": tpb_ratio,
        "parity_delta": cold["parity_delta"],
        "fp_distinct": cold["fp_quant"] != cold["fp_f32"],
        "fp_stable": warm["fp_quant"] == cold["fp_quant"],
        "warm_compiles": warm["compiles"],
        "warm_zero_compiles": warm["compiles"] == 0,
        "steps": steps, "batch": batch, "n_in": n_in, "hidden": hidden,
    }


def main_quant(quick: bool):
    """`--quant` mode: A/B detail to stderr + BENCH_quant.json, ONE
    stdout JSON line.  Gates (exit 1 on any failure): >=2x throughput per
    byte resident OR >=1.5x QPS, parity delta <=1%, warm restart with
    zero compiles, quantized fingerprint distinct from f32."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; quant bench on CPU",
                  file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = (bench_quant(steps=25, batch=32, n_in=128, hidden=256)
             if quick else bench_quant())
    except Exception as e:
        print(json.dumps({"metric": "quant_throughput_per_byte_ratio",
                          "value": None, "unit": "x",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[quant] {k} = {v}", file=sys.stderr, flush=True)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_quant.json"), "w") as f:
        json.dump(r, f, indent=2)
    perf_gate = (r["throughput_per_byte_ratio"] >= 2.0
                 or r["qps_speedup"] >= 1.5)
    gates = {
        "perf": perf_gate,
        "parity": r["parity_delta"] <= 0.01,
        "warm_zero_compiles": r["warm_zero_compiles"],
        "fp_distinct": r["fp_distinct"] and r["fp_stable"],
    }
    print(json.dumps({
        "metric": "quant_throughput_per_byte_ratio",
        "value": round(r["throughput_per_byte_ratio"], 2),
        "unit": "x",
        "qps_speedup": round(r["qps_speedup"], 3),
        "bytes_resident_ratio": round(r["bytes_resident_ratio"], 2),
        "parity_delta": round(r["parity_delta"], 5),
        "warm_compiles": r["warm_compiles"],
        "gates": gates,
        "pass": all(gates.values()),
    }))
    if not all(gates.values()):
        sys.exit(1)


def bench_autotune(n_batches=64, batch=64, n_in=256, quick=False):
    """Schedule-autotuner search over the execution-config space on the
    pipeline fixture, then persist → load → re-apply the winner and
    re-measure to confirm the tuned throughput survives a restart."""
    import tempfile

    from deeplearning4j_tpu.compile import (ScheduleAutotuner, load_schedule,
                                            save_schedule)

    make_it, make_net, nz = _pipeline_fixture(n_batches, batch, n_in)

    def measure(sch):
        net = make_net()
        net.set_normalizer(nz)
        net.apply_schedule(sch)
        it = sch.wrap_iterator(make_it())
        try:
            t = _time_steps(lambda: net.fit(it, epochs=1),
                            n_warmup=1, n_steps=1,
                            sync_fn=lambda: float(net.score()))
        finally:
            it.close()
        return n_batches / t

    space = ({"fused_steps": [1, 8], "prefetch_depth": [2],
              "donation": [True]} if quick
             else {"fused_steps": [1, 4, 16], "prefetch_depth": [1, 2, 4],
                   "donation": [True, False]})
    tuner = ScheduleAutotuner(measure, space=space,
                              refine_rounds=0 if quick else 1)
    best = tuner.search()

    sched_dir = tempfile.mkdtemp(prefix="bench-autotune-")
    path = save_schedule(best, sched_dir, name="bench")
    loaded = load_schedule(sched_dir, name="bench")
    remeasured = measure(loaded)
    return {
        "best": best.to_json(),
        "best_steps_per_sec": best.steps_per_sec,
        "baseline_steps_per_sec": best.meta["baseline_steps_per_sec"],
        "speedup_vs_baseline": (best.steps_per_sec
                                / max(best.meta["baseline_steps_per_sec"],
                                      1e-9)),
        "evaluated": best.meta["evaluated"],
        "schedule_path": path,
        "remeasured_steps_per_sec": remeasured,
        "remeasure_ratio": remeasured / max(best.steps_per_sec, 1e-9),
        "n_batches": n_batches, "batch": batch,
    }


def main_autotune(quick: bool):
    """`--autotune` mode: search detail to stderr + BENCH_autotune.json,
    ONE stdout JSON line."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; autotune bench on CPU",
                  file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = bench_autotune(n_batches=16, batch=32, n_in=64, quick=True) \
            if quick else bench_autotune()
    except Exception as e:
        print(json.dumps({"metric": "autotune_steps_per_sec",
                          "value": None, "unit": "steps/sec",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[autotune] {k} = {v}", file=sys.stderr, flush=True)
    import os
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_autotune.json"), "w") as f:
        json.dump(r, f, indent=2)
    print(json.dumps({
        "metric": "autotune_steps_per_sec",
        "value": round(r["best_steps_per_sec"], 1),
        "unit": "steps/sec",
        "speedup_vs_baseline": round(r["speedup_vs_baseline"], 3),
        "fused_steps": r["best"]["fused_steps"],
        "prefetch_depth": r["best"]["prefetch_depth"],
        "donation": r["best"]["donation"],
        "evaluated": r["evaluated"],
        "remeasure_ratio": round(r["remeasure_ratio"], 3),
    }))


def _wait_for_backend(max_wait_s=1800.0, retry_every_s=120.0):
    """Bounded probe-retry for the TPU backend.

    On this host the axon tunnel can be down for hours; `jax.devices()`
    then blocks forever inside `make_c_api_client` (it does not raise), so
    the backend must be probed in a subprocess with a hard timeout.  Re-
    probes every `retry_every_s` for up to `max_wait_s` so the bench can
    catch a tunnel-up window during the driver's run.  Returns the device
    count (>=1) on success; on final failure prints a structured JSON
    error line to stdout and returns 0.
    """
    import os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from __graft_entry__ import _probe_backend_device_count
    t0 = time.monotonic()
    probes = 0
    while True:
        probes += 1
        n = _probe_backend_device_count()
        if n >= 1:
            return n
        elapsed = time.monotonic() - t0
        if n < 0:  # probe died fast — non-transient, retrying is pointless
            print(json.dumps({
                "metric": "resnet50_train_samples_per_sec_per_chip",
                "value": None,
                "unit": "samples/sec/chip",
                "error": "backend probe failed hard (broken jax install "
                         "or platform plugin?) — not retrying",
            }))
            return 0
        if elapsed + retry_every_s > max_wait_s:
            line = {
                "metric": "resnet50_train_samples_per_sec_per_chip",
                "value": None,
                "unit": "samples/sec/chip",
                "error": (f"TPU backend unreachable: {probes} probes over "
                          f"{elapsed / 60:.1f} min (axon tunnel down); "
                          "no measurement possible"),
            }
            # value stays None (nothing was measured in THIS run), but
            # surface the most recent real-hardware measurement from the
            # in-repo validation artifacts so a tunnel outage at bench
            # time doesn't erase the round's on-chip data
            try:
                tv = json.load(open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "bench_artifacts", "TUNNEL_VALIDATION.json")))
                stages = tv.get("stages", {})
                candidates = {}
                head = stages.get("1_headline", {})
                if head.get("resnet50_samples_per_sec"):
                    candidates["per_step"] = head["resnet50_samples_per_sec"]
                for tag, r in stages.get("9_fused_dispatch", {}).items():
                    if isinstance(r, dict) and r.get("samples_per_sec"):
                        candidates[tag] = r["samples_per_sec"]
                if candidates:
                    best = max(candidates, key=candidates.get)
                    line["last_hw_measurement"] = {
                        "resnet50_samples_per_sec": candidates[best],
                        "config": best,
                        "all": candidates,
                        "measured_at": tv.get("started"),
                        "source": "bench_artifacts/TUNNEL_VALIDATION.json",
                    }
            except Exception:
                pass
            print(json.dumps(line))
            return 0
        print(f"[bench] backend unreachable (probe {probes}); retrying in "
              f"{retry_every_s:.0f}s ({(max_wait_s - elapsed) / 60:.0f} min "
              "left in budget)", file=sys.stderr, flush=True)
        time.sleep(retry_every_s)


def _bench_pallas_conformance(quick: bool):
    """Per-kernel conformance vs the jnp reference — runs everywhere (the
    Pallas impls go through interpret mode off-accelerator).  Returns
    {kernel: max_abs_err or bitwise bool}."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas import attention as pa
    from deeplearning4j_tpu.ops.pallas import dispatch as kd
    from deeplearning4j_tpu.ops.pallas import matmul as pm
    from deeplearning4j_tpu.ops.pallas.tiles import TileConfig

    interp = kd.interpret_mode()
    att_tile = TileConfig(block_q=32, block_kv=64)
    mm_tile = TileConfig(block_m=8, block_n=128, block_k=128)
    rng = np.random.RandomState(0)
    out = {}

    # attention: ragged causal+masked (query 0 kept attendable — fully
    # masked rows are mathematically undefined)
    B, H, T, S, D = 1, 2, 100, 72, 64
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
    keep = (rng.rand(B, S) > 0.3).astype(np.float32)
    keep[:, 0] = 1.0
    mask = jnp.asarray(keep)
    got = pa.flash_attention(q, k, v, mask=mask, causal=True,
                             tile=att_tile, interpret=interp)
    want = pa.attention_reference(q, k, v, mask=mask, causal=True)
    out["attention_max_err"] = float(jnp.max(jnp.abs(got - want)))

    # int8 matmul: the integer contraction must be BITWISE under tiling
    M, K, N = 37, 70, 45
    xq = jnp.asarray(rng.randint(-128, 128, (M, K)), jnp.int8)
    wq = jnp.asarray(rng.randint(-128, 128, (K, N)), jnp.int8)
    ws = jnp.asarray(rng.rand(N) * 0.1 + 1e-3, jnp.float32)
    got = pm.int8_matmul(xq, wq, ws, tile=mm_tile, interpret=interp)
    want = pm.int8_matmul_reference(xq, wq, ws)
    out["int8_matmul_bitwise"] = bool(jnp.all(got == want))

    # bf16/f32-activation x int8-weight matmul
    x = jnp.asarray(rng.randn(M, K), jnp.float32)
    got = pm.q_matmul(x, wq, ws, tile=mm_tile, interpret=interp)
    want = pm.q_matmul_reference(x, wq, ws)
    out["q_matmul_max_err"] = float(jnp.max(jnp.abs(got - want)))

    # fused dense epilogue
    w = jnp.asarray(rng.randn(K, N) * 0.1, jnp.float32)
    b = jnp.asarray(rng.randn(N) * 0.1, jnp.float32)
    got = pm.fused_dense(x, w, bias=b, activation="gelu",
                         tile=mm_tile, interpret=interp)
    want = pm.fused_dense_reference(x, w, bias=b, activation="gelu")
    out["fused_dense_max_err"] = float(jnp.max(jnp.abs(got - want)))

    out["pass"] = (out["int8_matmul_bitwise"]
                   and out["attention_max_err"] < 2e-5
                   and out["q_matmul_max_err"] < 2e-5
                   and out["fused_dense_max_err"] < 2e-5)
    return out


def _bench_pallas_ab(quick: bool):
    """Accelerator-only timed A/B: each Pallas kernel vs the XLA-fused
    jnp reference, both jitted, chained dispatch + block_until_ready."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas import attention as pa
    from deeplearning4j_tpu.ops.pallas import dispatch as kd
    from deeplearning4j_tpu.ops.pallas import matmul as pm

    iters = 10 if quick else 50
    rng = np.random.RandomState(1)

    def timed(fn, *args):
        jf = jax.jit(fn)
        jf(*args).block_until_ready()          # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            y = jf(*args)
        y.block_until_ready()
        return (time.perf_counter() - t0) / iters

    speedups = {}

    # flash attention vs XLA-fused reference (causal, long seq)
    B, H, T, D = (1, 4, 2048, 64) if quick else (4, 8, 2048, 64)
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
    tile = kd.get_tile("attention")
    t_ref = timed(lambda a, b, c: pa.attention_reference(
        a, b, c, causal=True), q, k, v)
    t_pal = timed(lambda a, b, c: pa.flash_attention(
        a, b, c, causal=True, tile=tile, interpret=False), q, k, v)
    speedups["attention"] = t_ref / max(t_pal, 1e-12)

    # int8-native matmul vs dequantize-then-f32-dot
    M = K = N = 1024 if quick else 4096
    xq = jnp.asarray(rng.randint(-128, 128, (M, K)), jnp.int8)
    wq = jnp.asarray(rng.randint(-128, 128, (K, N)), jnp.int8)
    ws = jnp.asarray(rng.rand(N) * 0.1 + 1e-3, jnp.float32)
    tile = kd.get_tile("int8_matmul")

    def dequant_first(a, b, s):                # the pre-fix lowering
        return (a.astype(jnp.float32) @ (b.astype(jnp.float32)
                                         * s[None, :]))
    t_ref = timed(dequant_first, xq, wq, ws)
    t_pal = timed(lambda a, b, s: pm.int8_matmul(
        a, b, s, tile=tile, interpret=False), xq, wq, ws)
    speedups["int8_matmul"] = t_ref / max(t_pal, 1e-12)

    # fused dense bias+gelu epilogue vs XLA's fusion
    rows = 2048 if quick else 8192
    x = jnp.asarray(rng.randn(rows, K), jnp.bfloat16)
    w = jnp.asarray(rng.randn(K, N) * 0.02, jnp.bfloat16)
    b = jnp.asarray(rng.randn(N) * 0.02, jnp.float32)
    tile = kd.get_tile("fused_dense")
    t_ref = timed(lambda a, c, d: pm.fused_dense_reference(
        a, c, bias=d, activation="gelu"), x, w, b)
    t_pal = timed(lambda a, c, d: pm.fused_dense(
        a, c, bias=d, activation="gelu", tile=tile, interpret=False),
        x, w, b)
    speedups["fused_dense"] = t_ref / max(t_pal, 1e-12)
    return speedups


def bench_pallas(quick=False):
    """The Pallas fused-kernel tier bench: conformance (always), timed A/B
    vs XLA baselines (accelerator only), tile search->persist->replay, and
    the AOT cache-key proof (warm restart compiles nothing; a different
    tile schedule is a distinct entry)."""
    import os
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.compile.autotune import autotune_tiles
    from deeplearning4j_tpu.compile.fingerprint import \
        kernel_tier_fingerprint
    from deeplearning4j_tpu.compile.persistent import \
        PersistentExecutableCache
    from deeplearning4j_tpu.compile.step_cache import step_function
    from deeplearning4j_tpu.ops.pallas import dispatch as kd
    from deeplearning4j_tpu.ops.pallas import matmul as pm
    from deeplearning4j_tpu.ops.pallas.tiles import TileConfig, shape_class

    kd.reset()
    on_accel = kd.on_accelerator() and kd.pallas_available()
    r = {"backend": jax.default_backend(), "accelerator": on_accel,
         "simulated": not on_accel, "quick": quick}

    r["conformance"] = _bench_pallas_conformance(quick)

    if on_accel:
        r["speedups"] = _bench_pallas_ab(quick)
        r["best_speedup"] = max(r["speedups"].values())
    else:
        r["speedups"] = None                  # CPU: conformance leg only
        r["best_speedup"] = None

    # --- tile search -> persist -> replay --------------------------------
    M = K = N = 1024 if quick else 4096
    sc = shape_class(m=M, k=K, n=N)
    calls = {"n": 0}
    if on_accel:
        rng = np.random.RandomState(2)
        xq = jnp.asarray(rng.randint(-128, 128, (M, K)), jnp.int8)
        wq = jnp.asarray(rng.randint(-128, 128, (K, N)), jnp.int8)
        ws = jnp.asarray(rng.rand(N) * 0.1 + 1e-3, jnp.float32)

        def measure(cfg):
            calls["n"] += 1
            f = jax.jit(lambda a, b, s: pm.int8_matmul(
                a, b, s, tile=cfg, interpret=False))
            f(xq, wq, ws).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(3 if quick else 10):
                y = f(xq, wq, ws)
            y.block_until_ready()
            return 1.0 / max(time.perf_counter() - t0, 1e-12)
    else:
        def measure(cfg):                     # analytic stand-in (CPU)
            calls["n"] += 1
            return -(abs(cfg.block_m - 256) + abs(cfg.block_n - 256)
                     + abs(cfg.block_k - 1024))

    tdir = tempfile.mkdtemp(prefix="bench-pallas-tiles-")
    try:
        t0 = time.perf_counter()
        tile1, info1 = autotune_tiles("int8_matmul", sc, measure, tdir)
        search_ms = (time.perf_counter() - t0) * 1000.0
        n_search = calls["n"]
        tile2, info2 = autotune_tiles("int8_matmul", sc, measure, tdir)
        r["tile_search"] = {
            "shape_class": sc,
            "winner": tile1.to_json(),
            "evaluated": info1["evaluated"],
            "search_ms": round(search_ms, 1),
            "replay_source": info2["source"],
            "replay_measure_calls": calls["n"] - n_search,
            "replay_matches": tile2 == tile1,
        }
    finally:
        shutil.rmtree(tdir, ignore_errors=True)

    # --- AOT proof: warm restart compiles nothing; a different tile is a
    # distinct entry (kernel_tier_fingerprint splits the key) ------------
    rng = np.random.RandomState(3)
    xq = jnp.asarray(rng.randint(-128, 128, (64, 128)), jnp.int8)
    wq = jnp.asarray(rng.randint(-128, 128, (128, 128)), jnp.int8)
    ws = jnp.asarray(rng.rand(128) * 0.1 + 1e-3, jnp.float32)
    interp = kd.interpret_mode()
    mm_tile = kd.get_tile("int8_matmul")

    def body(a, b, s):
        return pm.int8_matmul(a, b, s, tile=mm_tile, interpret=interp)

    key_base = lambda: {"bench": "pallas",
                        "tier": kernel_tier_fingerprint()}
    cdir = tempfile.mkdtemp(prefix="bench-pallas-aot-")
    try:
        f_cold = step_function(body, key_base=key_base,
                               cache=PersistentExecutableCache(cdir))
        f_cold(xq, wq, ws)
        f_warm = step_function(body, key_base=key_base,
                               cache=PersistentExecutableCache(cdir))
        f_warm(xq, wq, ws)
        kd.set_tile("int8_matmul", TileConfig(block_m=128, block_n=128,
                                              block_k=256))
        f_retuned = step_function(body, key_base=key_base,
                                  cache=PersistentExecutableCache(cdir))
        f_retuned(xq, wq, ws)
        r["aot"] = {
            "cold_compiles": f_cold._cache_size(),
            "warm_compiles": f_warm._cache_size(),
            "retuned_tile_compiles": f_retuned._cache_size(),
        }
    finally:
        kd.reset()
        shutil.rmtree(cdir, ignore_errors=True)
    return r


def main_pallas(quick: bool):
    """`--pallas` mode: detail to stderr + BENCH_pallas.json, ONE stdout
    JSON line.  Gates (exit 1 on any failure): conformance, tile replay
    from the persisted table with zero re-search, warm AOT restart with
    zero compiles + distinct entry for a retuned tile, and — on an
    accelerator only — >=1.15x vs the XLA baseline on >=1 kernel (on CPU
    the perf gate is skipped and the line carries `"simulated": true`)."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; pallas bench on CPU "
                  "(conformance leg only)", file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = bench_pallas(quick=quick)
    except Exception as e:
        print(json.dumps({"metric": "pallas_best_kernel_speedup",
                          "value": None, "unit": "x",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[pallas] {k} = {v}", file=sys.stderr, flush=True)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_pallas.json"), "w") as f:
        json.dump(r, f, indent=2)
    gates = {
        "conformance": r["conformance"]["pass"],
        "tile_replay_zero_research": (
            r["tile_search"]["replay_source"] == "cache"
            and r["tile_search"]["replay_measure_calls"] == 0
            and r["tile_search"]["replay_matches"]),
        "aot_warm_zero_compiles": r["aot"]["warm_compiles"] == 0,
        "aot_tile_splits_key": r["aot"]["retuned_tile_compiles"] == 1,
        "perf": (r["best_speedup"] >= 1.15 if r["accelerator"]
                 else True),   # CPU: simulated, conformance-only
    }
    print(json.dumps({
        "metric": "pallas_best_kernel_speedup",
        "value": (round(r["best_speedup"], 3)
                  if r["best_speedup"] is not None else None),
        "unit": "x",
        "simulated": r["simulated"],
        "speedups": ({k: round(v, 3) for k, v in r["speedups"].items()}
                     if r["speedups"] else None),
        "tile_search_evaluated": r["tile_search"]["evaluated"],
        "tile_replay_source": r["tile_search"]["replay_source"],
        "warm_compiles": r["aot"]["warm_compiles"],
        "gates": gates,
        "pass": all(gates.values()),
    }))
    if not all(gates.values()):
        sys.exit(1)


def bench_decode(n_seqs=48, max_seq_len=256, max_decode_batch=8,
                 num_blocks=192, vocab=96, d_model=64, n_heads=4,
                 seed=0):
    """Sequence-length-skewed decode flood + paged-vs-contiguous KV A/B.

    One `DecodeEngine` with int8 paged KV serves `n_seqs` prompts whose
    lengths are skewed across every prefill bucket (short head, long
    tail).  Measured: tokens/sec and inter-token p99 across the flood,
    fresh XLA compiles after warmup (must be zero — admits/retires and
    ragged lengths never change a traced shape), peak KV pages vs peak
    concurrent sequences.  The memory A/B compares measured bytes per
    concurrent sequence against the contiguous-f32 baseline every
    pre-paged serving stack pays: a `max_seq_len` * heads * head_dim *
    2(K,V) * 4(f32) reservation per sequence regardless of actual
    length.  Parity: int8-KV vs f32-KV paged attention on the engine's
    OWN prefill KV (not synthetic noise), relative L2."""
    from deeplearning4j_tpu.ops.pallas import paged_attention as pa
    from deeplearning4j_tpu.ops.quant_kernels import quantize_tensor
    from deeplearning4j_tpu.serving.decode import (DecodeEngine,
                                                   TinyDecodeModel)

    rng = np.random.default_rng(seed)
    model = TinyDecodeModel(vocab=vocab, d_model=d_model,
                            n_heads=n_heads, seed=seed)
    eng = DecodeEngine(model, num_blocks=num_blocks,
                       max_seq_len=max_seq_len,
                       max_decode_batch=max_decode_batch,
                       kv_dtype="int8", model_label="bench")
    try:
        warm = eng.warmup()
        fresh_before = eng.fresh_compiles()

        # skewed lengths: most prompts short, a long tail touching the
        # top buckets — every bucket in the ladder gets traffic
        max_prompt = max_seq_len - 24
        pool = [3, 5, 7, 9, 14, 20, 33, 60]
        pool = [p for p in pool if p < max_prompt] + [max_prompt]
        weights = np.array([4.0] * (len(pool) - 1) + [1.0])
        lens = rng.choice(pool, size=n_seqs, p=weights / weights.sum())
        t0 = time.monotonic()
        futs = [eng.submit(rng.integers(1, vocab, size=int(n)),
                           max_new_tokens=int(rng.integers(4, 20)))
                for n in lens]
        peak_active = peak_blocks = 0
        pending = list(futs)
        while pending:
            peak_active = max(peak_active, eng.cache.active_sequences)
            peak_blocks = max(peak_blocks, eng.cache.blocks_in_use)
            pending = [f for f in pending if not f.done()]
            time.sleep(0.002)
        outs = [f.result(timeout=60) for f in futs]
        wall_s = time.monotonic() - t0
        tokens = int(sum(len(o) for o in outs))
        fresh_after = eng.fresh_compiles()
        p99 = eng.instruments.inter_token("bench").percentiles(
            (50, 99))

        # ---- memory A/B: measured paged-int8 vs contiguous-f32 ----
        head_dim = model.head_dim
        contig_f32_bytes = max_seq_len * n_heads * head_dim * 2 * 4
        paged_bytes = (peak_blocks * eng.cache.bytes_per_block
                       / max(peak_active, 1))
        density_ratio = contig_f32_bytes / max(paged_bytes, 1.0)

        # ---- parity: int8-KV vs f32-KV attention on real prefill KV ----
        import jax.numpy as jnp
        T = min(64, max_prompt)
        prompt = rng.integers(1, vocab, size=(1, T)).astype(np.int32)
        _, k, v = model.prefill(jnp.asarray(prompt),
                                jnp.asarray([T], np.int32))
        k = np.asarray(k)[0]
        v = np.asarray(v)[0]                      # [T, H, D]
        page = eng.page_size
        n_pages = -(-T // page)
        shape = (n_pages, page, n_heads, head_dim)
        kf = np.zeros(shape, np.float32)
        vf = np.zeros(shape, np.float32)
        kf.reshape(-1, n_heads, head_dim)[:T] = k
        vf.reshape(-1, n_heads, head_dim)[:T] = v
        k8 = np.zeros(shape, np.int8)
        v8 = np.zeros(shape, np.int8)
        ks = np.ones(shape[:3], np.float32)
        vs = np.ones(shape[:3], np.float32)
        for p in range(n_pages):
            for s in range(page):
                qt = quantize_tensor(kf[p, s], axis=0)
                k8[p, s] = np.asarray(qt.q)
                ks[p, s] = np.asarray(qt.scale).reshape(-1)
                qt = quantize_tensor(vf[p, s], axis=0)
                v8[p, s] = np.asarray(qt.q)
                vs[p, s] = np.asarray(qt.scale).reshape(-1)
        q1 = rng.standard_normal((1, n_heads, head_dim)).astype(
            np.float32)
        bt = np.arange(n_pages, dtype=np.int32)[None, :]
        sl = np.array([T], np.int32)
        a_f32 = np.asarray(pa.paged_attention_reference(
            q1, kf, vf, bt, sl))
        a_i8 = np.asarray(pa.paged_attention_reference(
            q1, k8, v8, bt, sl, k_scales=ks, v_scales=vs))
        parity = float(np.linalg.norm(a_i8 - a_f32)
                       / max(np.linalg.norm(a_f32), 1e-12))
        stats = eng.stats()
    finally:
        eng.shutdown(drain=False)
    return {
        "n_seqs": n_seqs, "max_seq_len": max_seq_len,
        "max_decode_batch": max_decode_batch, "num_blocks": num_blocks,
        "prompt_lens": sorted(set(int(n) for n in lens)),
        "tokens": tokens, "wall_s": wall_s,
        "tokens_per_sec": tokens / max(wall_s, 1e-9),
        "inter_token_p50_ms": p99["p50"],
        "inter_token_p99_ms": p99["p99"],
        "warmup_programs": warm,
        "fresh_compiles_after_warmup": fresh_after - fresh_before,
        "peak_concurrent_sequences": peak_active,
        "peak_kv_blocks": peak_blocks,
        "paged_int8_bytes_per_seq": paged_bytes,
        "contiguous_f32_bytes_per_seq": contig_f32_bytes,
        "seqs_per_byte_ratio": density_ratio,
        "int8_attention_parity": parity,
        "engine_stats": stats,
    }


def main_decode(quick: bool):
    """`--decode` mode: flood detail to stderr + BENCH_decode.json, ONE
    stdout JSON line.  Gates (exit 1 on any failure): zero fresh compiles
    after warmup across the skewed flood, tokens/sec floor, inter-token
    p99 bound, paged-int8 >=1.5x concurrent sequences per HBM byte vs
    contiguous f32 at <=1% attention parity."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; decode bench on CPU",
                  file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = (bench_decode(n_seqs=12, max_seq_len=64, max_decode_batch=4,
                          num_blocks=64)
             if quick else bench_decode())
    except Exception as e:
        print(json.dumps({"metric": "decode_tokens_per_sec",
                          "value": None, "unit": "tokens/sec",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[decode] {k} = {v}", file=sys.stderr, flush=True)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_decode.json"), "w") as f:
        json.dump(r, f, indent=2)
    gates = {
        "zero_recompile": r["fresh_compiles_after_warmup"] == 0,
        "throughput": r["tokens_per_sec"] >= 5.0,
        "inter_token_p99": r["inter_token_p99_ms"] <= 1000.0,
        "int8_density": r["seqs_per_byte_ratio"] >= 1.5,
        "parity": r["int8_attention_parity"] <= 0.01,
    }
    print(json.dumps({
        "metric": "decode_tokens_per_sec",
        "value": round(r["tokens_per_sec"], 1),
        "unit": "tokens/sec",
        "inter_token_p99_ms": round(r["inter_token_p99_ms"], 3),
        "fresh_compiles_after_warmup": r["fresh_compiles_after_warmup"],
        "seqs_per_byte_ratio": round(r["seqs_per_byte_ratio"], 2),
        "int8_attention_parity": round(r["int8_attention_parity"], 5),
        "gates": gates,
        "pass": all(gates.values()),
    }))
    if not all(gates.values()):
        sys.exit(1)


def arbiter_child(workdir: str, phase: str):
    """`--arbiter-child` subprocess for the --arbiter chaos episode (the
    bench twin of tests/arbiter_worker.py).

    Phase ``run``: build a seeded net + CheckpointManager, a
    LocalElasticGang over slices [0, 1], a ModelFleet sharing `workdir`,
    and a SliceArbiter with a REAL `HandoffChaos(target="arbiter",
    mode="kill", at_phase="shrink")` hooked in — `to_serving()` journals
    the phase-1 intent and the chaos hook `os._exit(9)`s the process
    with the record durable and ZERO side effects executed.

    Phase ``recover``: a fresh process over the SAME journal — the
    arbiter constructor replays the in-flight handoff (the marker keeps
    the chaos one-shot), then writes `recover_result.json` so the parent
    can assert single ownership and a counted replay."""
    import os
    import numpy as np
    from deeplearning4j_tpu.monitor.registry import MetricsRegistry
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.serving import ModelFleet
    from deeplearning4j_tpu.serving.slo import ArbiterPolicy
    from deeplearning4j_tpu.train.arbiter import (LocalElasticGang,
                                                  SliceArbiter)
    from deeplearning4j_tpu.train.resilience import CheckpointManager
    from deeplearning4j_tpu.train.updaters import Sgd
    from deeplearning4j_tpu.utils.chaos import HandoffChaos

    journal = os.path.join(workdir, "journal.json")
    marker = os.path.join(workdir, "chaos_once")
    conf = (NeuralNetConfiguration.builder().seed(11).updater(Sgd(0.1))
            .list([DenseLayer(n_out=8, activation="tanh"),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    manager = CheckpointManager(os.path.join(workdir, "ckpt"),
                                keep_last=50, save_every_steps=None)
    rng = np.random.RandomState(3)
    x = rng.randn(6, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    net.fit(x, y)               # the shrink checkpoint is non-trivial
    gang = LocalElasticGang(net, manager, slices=[0, 1])
    fleet = ModelFleet(max_resident=1, n_slices=1,
                       cache_dir=os.path.join(workdir, "exec-cache"),
                       registry_=MetricsRegistry())
    arb = SliceArbiter(journal, training=gang, fleet=fleet,
                       policy=ArbiterPolicy(min_training_slices=1),
                       registry_=MetricsRegistry())
    if phase == "run":
        arb.chaos = HandoffChaos(target="arbiter", mode="kill",
                                 at_phase="shrink", marker=marker)
        arb.to_serving()                # chaos kills us after phase-1
        print("UNREACHABLE: chaos did not fire", flush=True)
        sys.exit(3)
    # phase == "recover": the constructor already replayed (recover=True)
    result = {
        "recovered": arb.recovered,
        "describe": arb.describe(),
        "gang_held": gang.held_slices(),
        "ckpt_latest": manager.latest_step(),
        "marker_exists": os.path.exists(marker),
    }
    with open(os.path.join(workdir, "recover_result.json"), "w") as f:
        json.dump(result, f)


def bench_arbiter(quick=False):
    """`--arbiter` gate: preemption-safe train/serve slice handoffs
    (train/arbiter.py + docs/robustness.md "Pod arbiter").

    A compressed diurnal pressure trace with a 10x flash spike drives
    `SliceArbiter.maybe_rebalance` over a 3-slice pod shared by a
    LocalElasticGang (training a real net through the real blocking-
    checkpoint shrink/readmit path) and a ModelFleet serving a
    hi-priority model off the shared persistent AOT cache.  An
    uninterrupted reference net trains on the IDENTICAL batch stream.

    Gates: >= 2 full handoff cycles; zero hi-priority SLO breaches at
    peak; per-step training loss bitwise-identical to the uninterrupted
    run (checked at every shrink/grow boundary and every tick) and final
    params bitwise-equal; `fresh_compiles == 0` on BOTH sides of every
    handoff (fleet AOT cache delta == 0, the gang's jitted train step
    never re-traces); plus one REAL mid-handoff arbiter kill in a child
    process (`--arbiter-child`, HandoffChaos `os._exit(9)` right after
    the phase-1 journal commit) recovered by a relaunched arbiter
    replaying the journal with the slice single-owned."""
    import os
    import shutil
    import subprocess
    import tempfile
    import numpy as np
    from deeplearning4j_tpu.monitor.registry import MetricsRegistry
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.serving import LatencySLO, ModelFleet
    from deeplearning4j_tpu.serving.slo import ArbiterPolicy
    from deeplearning4j_tpu.train.arbiter import (LocalElasticGang,
                                                  SliceArbiter)
    from deeplearning4j_tpu.train.resilience import CheckpointManager
    from deeplearning4j_tpu.train.updaters import Sgd

    n_in = 12
    hi_slo_ms = 1500.0
    base_p, peak_p = 0.3, 3.0           # 10x flash spike
    cycles = 2 if quick else 3
    base_len, spike_len = (3, 4) if quick else (5, 6)
    burst = 4 if quick else 8           # hi requests per peak tick

    def make_net(seed):
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Sgd(0.05))
                .list([DenseLayer(n_out=24, activation="relu"),
                       OutputLayer(n_out=3, loss="mcxent",
                                   activation="softmax")])
                .set_input_type(InputType.feed_forward(n_in)).build())
        return MultiLayerNetwork(conf).init()

    # diurnal trace: lull -> flash spike -> lull, repeated
    trace = []
    for _ in range(cycles):
        trace += [base_p] * base_len + [peak_p] * spike_len
    trace += [base_p] * (base_len + 1)  # final lull reclaims the slice

    work_dir = tempfile.mkdtemp(prefix="bench-arbiter-")
    try:
        journal = os.path.join(work_dir, "journal.json")
        # the arbitrated net and the uninterrupted reference: same seed,
        # same batch stream — the handoffs are the ONLY difference
        net, ref = make_net(21), make_net(21)
        rng = np.random.RandomState(5)
        batches = []
        for _ in range(len(trace)):
            x = rng.randn(16, n_in).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[
                (np.abs(x[:, 0]) * 2.9).astype(int) % 3]
            batches.append((x, y))

        manager = CheckpointManager(os.path.join(work_dir, "ckpt"),
                                    keep_last=100, save_every_steps=None)
        gang = LocalElasticGang(net, manager, slices=[0, 1, 2])
        fleet = ModelFleet(max_resident=2, n_slices=1, max_batch=8,
                           batch_timeout_ms=1.0,
                           cache_dir=os.path.join(work_dir, "exec-cache"),
                           registry_=MetricsRegistry())
        fleet.deploy("hi", make_net(1001),
                     slo=LatencySLO(target_p99_ms=hi_slo_ms, priority=10),
                     warm=True)
        policy = ArbiterPolicy(grant_at_forecast=1.5,
                               return_below_forecast=0.5,
                               min_training_slices=1, max_fleet_leases=1,
                               drain_timeout_s=2.0, cooldown_s=0.0)
        arb = SliceArbiter(journal, training=gang, fleet=fleet,
                           policy=policy, registry_=MetricsRegistry())
        fleet.attach_arbiter(arb)

        # pre-warm the request shape so peak traffic (and the leased
        # slice's replicas) runs entirely off the warm AOT cache
        req_x = np.random.RandomState(9).rand(4, n_in).astype(np.float32)
        for _ in range(2):
            fleet.output("hi", req_x, deadline_ms=60_000.0, timeout=120)

        # first step pays the one train-step trace+compile on each net;
        # from here both jit caches must be frozen across every handoff
        net.fit(*batches[0])
        ref.fit(*batches[0])
        step_fn = net._get_train_step()
        train_cache0 = step_fn._cache_size()

        boundaries = []
        loss_mismatch_ticks = []
        hi_lat_ms, hi_breaches, hi_requests = [], 0, 0
        to_serving = to_training = 0
        for t, p in enumerate(trace):
            if t > 0:                   # tick 0 trained above (warmup)
                net.fit(*batches[t])
                ref.fit(*batches[t])
            loss_n, loss_r = net.score(), ref.score()
            if loss_n != loss_r:        # bitwise: exact float equality
                loss_mismatch_ticks.append(t)
            cache_before = fleet.cache.stats["compiles"]
            rec = arb.maybe_rebalance(pressure=p)
            if rec is not None:
                serving_fresh = (fleet.cache.stats["compiles"]
                                 - cache_before)
                cur_step = net._get_train_step()
                train_fresh = (cur_step._cache_size() - train_cache0
                               if cur_step is step_fn else -1)
                if rec["direction"] == "to_serving":
                    to_serving += 1
                else:
                    to_training += 1
                boundaries.append({
                    "tick": t, "direction": rec["direction"],
                    "slice": rec["slice"],
                    "loss": loss_n, "ref_loss": loss_r,
                    "bitwise": loss_n == loss_r,
                    "serving_fresh_compiles": serving_fresh,
                    "training_fresh_compiles": train_fresh,
                    "gang_world": gang.world,
                    "gang_generation": gang.generation,
                })
            if p >= policy.grant_at_forecast:       # peak: hi flood
                for _ in range(burst):
                    hi_requests += 1
                    t0 = time.perf_counter()
                    try:
                        fleet.output("hi", req_x, deadline_ms=60_000.0,
                                     timeout=120)
                        lat = (time.perf_counter() - t0) * 1000.0
                        hi_lat_ms.append(lat)
                        if lat > hi_slo_ms:
                            hi_breaches += 1
                    except Exception:
                        hi_breaches += 1

        hi_member = fleet.member("hi")
        hi_p99 = hi_member.latency.percentiles((99,))["p99"]
        tracker_breaches = hi_member.tracker.breaches_total
        import jax
        params_equal = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree_util.tree_leaves(net.params_),
                            jax.tree_util.tree_leaves(ref.params_)))
        end_step = net._get_train_step()
        train_fresh_total = (end_step._cache_size() - train_cache0
                             if end_step is step_fn else -1)
        final = arb.describe()
        fleet.shutdown()

        # ---- chaos episode: REAL kill between journal phases ----
        chaos_dir = os.path.join(work_dir, "chaos")
        os.makedirs(chaos_dir, exist_ok=True)
        here = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")

        def child(phase):
            return subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--arbiter-child", chaos_dir, phase],
                cwd=here, env=env, capture_output=True, text=True,
                timeout=300)

        run = child("run")
        with open(os.path.join(chaos_dir, "journal.json")) as f:
            killed_state = json.load(f)["state"]
        recover = child("recover")
        rec_result = {}
        rec_path = os.path.join(chaos_dir, "recover_result.json")
        if os.path.exists(rec_path):
            with open(rec_path) as f:
                rec_result = json.load(f)
        recovered = rec_result.get("recovered") or {}
        chaos = {
            "run_rc": run.returncode,                       # want 9
            "journal_phase_after_kill":
                (killed_state.get("handoff") or {}).get("phase"),
            "lease_after_kill":
                killed_state.get("leases", {}).get("1"),
            "recover_rc": recover.returncode,
            "outcome": recovered.get("outcome"),
            "replays": (rec_result.get("describe") or {}).get("replays"),
            "single_owned": (
                (rec_result.get("describe") or {}).get("leases", {})
                .get("1") == "serving"
                and 1 not in (rec_result.get("gang_held") or [1])),
            "marker_exists": rec_result.get("marker_exists"),
            "stderr_tail": (run.stderr or "")[-300:]
            if run.returncode != 9 else "",
        }
        return {
            "ticks": len(trace),
            "base_pressure": base_p,
            "peak_pressure": peak_p,
            "spike_ratio": peak_p / base_p,
            "to_serving_handoffs": to_serving,
            "to_training_handoffs": to_training,
            "handoff_cycles": min(to_serving, to_training),
            "boundaries": boundaries,
            "loss_mismatch_ticks": loss_mismatch_ticks,
            "final_params_bitwise_equal": bool(params_equal),
            "hi_requests_at_peak": hi_requests,
            "hi_breaches_at_peak": hi_breaches,
            "hi_p99_ms": hi_p99,
            "hi_slo_ms": hi_slo_ms,
            "hi_tracker_breaches": tracker_breaches,
            "serving_fresh_compiles_total": sum(
                b["serving_fresh_compiles"] for b in boundaries),
            "training_fresh_compiles_total": train_fresh_total,
            "gang_generation": gang.generation,
            "journal_replays": final["replays"],
            "journal_commits": final["journal_commits"],
            "final_leases": {str(k): v
                             for k, v in final["leases"].items()},
            "chaos": chaos,
        }
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)


def main_arbiter(quick: bool):
    """`--arbiter` mode: trace detail to stderr + BENCH_arbiter.json,
    ONE stdout JSON line.  Gates (exit 1 on any failure): >= 2 handoff
    cycles under the diurnal 10x-spike trace, zero hi-priority SLO
    breaches at peak, bitwise training-loss parity with the
    uninterrupted run at every boundary, fresh_compiles == 0 on both
    sides of every handoff, and the injected mid-handoff kill recovered
    by journal replay with the slice single-owned."""
    import os
    if not os.environ.get("JAX_PLATFORMS"):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _probe_backend_device_count
        if _probe_backend_device_count() < 1:
            print("[bench] TPU backend unreachable; arbiter bench on CPU",
                  file=sys.stderr, flush=True)
            os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        r = bench_arbiter(quick)
    except Exception as e:
        print(json.dumps({"metric": "arbiter_handoff_cycles",
                          "value": None, "unit": "cycles",
                          "error": repr(e)[:300]}))
        sys.exit(1)
    for k, v in r.items():      # detail to stderr: stdout stays one line
        print(f"[arbiter] {k} = {v}", file=sys.stderr, flush=True)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_arbiter.json"), "w") as f:
        json.dump(r, f, indent=2)
    c = r["chaos"]
    gates = {
        "cycles": r["handoff_cycles"] >= 2,
        "slo_at_peak": (r["hi_requests_at_peak"] > 0
                        and r["hi_breaches_at_peak"] == 0
                        and r["hi_tracker_breaches"] == 0),
        "bitwise": (not r["loss_mismatch_ticks"]
                    and all(b["bitwise"] for b in r["boundaries"])
                    and r["final_params_bitwise_equal"]),
        "zero_recompile": (r["serving_fresh_compiles_total"] == 0
                           and r["training_fresh_compiles_total"] == 0),
        "chaos_replay": (c["run_rc"] == 9
                         and c["journal_phase_after_kill"] == "shrink"
                         and c["lease_after_kill"] == "transit"
                         and c["recover_rc"] == 0
                         and c["outcome"] == "replayed"
                         and c["replays"] == 1
                         and bool(c["single_owned"])),
    }
    print(json.dumps({
        "metric": "arbiter_handoff_cycles",
        "value": r["handoff_cycles"],
        "unit": "cycles",
        "threshold": 2,
        "hi_breaches_at_peak": r["hi_breaches_at_peak"],
        "hi_p99_ms": round(r["hi_p99_ms"], 2),
        "fresh_compiles": (r["serving_fresh_compiles_total"]
                           + max(r["training_fresh_compiles_total"], 0)),
        "journal_replays_after_kill": c["replays"],
        "gates": gates,
        "pass": all(gates.values()),
    }))
    if not all(gates.values()):
        sys.exit(1)


def main():
    quick = "--quick" in sys.argv
    if "--arbiter-child" in sys.argv:
        i = sys.argv.index("--arbiter-child")
        arbiter_child(sys.argv[i + 1], sys.argv[i + 2])
        return
    if "--arbiter" in sys.argv:
        main_arbiter(quick)
        return
    if "--aot-child" in sys.argv:
        i = sys.argv.index("--aot-child")
        aot_child(sys.argv[i + 1], int(sys.argv[i + 2]),
                  int(sys.argv[i + 3]), int(sys.argv[i + 4]))
        return
    if "--aot" in sys.argv:
        main_aot(quick)
        return
    if "--quant-child" in sys.argv:
        i = sys.argv.index("--quant-child")
        quant_child(sys.argv[i + 1], int(sys.argv[i + 2]),
                    int(sys.argv[i + 3]), int(sys.argv[i + 4]),
                    int(sys.argv[i + 5]))
        return
    if "--quant" in sys.argv:
        main_quant(quick)
        return
    if "--decode" in sys.argv:
        main_decode(quick)
        return
    if "--pallas" in sys.argv:
        main_pallas(quick)
        return
    if "--autotune" in sys.argv:
        main_autotune(quick)
        return
    if "--serving" in sys.argv:
        main_serving(quick)
        return
    if "--fleetchaos" in sys.argv:
        main_fleetchaos(quick)
        return
    if "--federation" in sys.argv:
        main_federation(quick)
        return
    if "--fleet" in sys.argv:
        main_fleet(quick)
        return
    if "--pipeline" in sys.argv:
        main_pipeline(quick)
        return
    if "--obs" in sys.argv:
        main_obs(quick)
        return
    if "--zero1" in sys.argv:
        main_zero1(quick)
        return
    if "--comms" in sys.argv:
        main_comms(quick)
        return
    if "--elastic" in sys.argv:
        main_elastic(quick)
        return
    if "--resilience" in sys.argv:
        main_resilience(quick)
        return
    n_chips = _wait_for_backend()
    if n_chips == 0:
        sys.exit(1)
    import jax
    print(f"devices: {jax.devices()}", file=sys.stderr)

    if quick:
        sps = bench_resnet50(batch=16, steps=5, image=96, classes=100)
    else:
        sps = bench_resnet50()
    per_chip = sps / n_chips

    # One JSON line per BASELINE config on stdout (VERDICT r3 #9) so the
    # recorded artifact carries all metrics, not just the headline.  Each
    # config is independent: a failure prints an error line for that metric
    # only.  The headline is printed LAST — the driver's `parsed` field
    # takes the final stdout JSON line.
    configs = [
        ("lenet_mnist_samples_per_sec", "samples/sec", lambda: bench_lenet()),
        ("lstm_charlm_tokens_per_sec", "tokens/sec",
         lambda: bench_lstm_charlm(steps=3 if quick else 10)),
        ("bert_base_mlm_tokens_per_sec", "tokens/sec",
         lambda: bench_bert_base(steps=3 if quick else 10)),
    ]
    if not quick:
        configs.append(("bert_long_seq2048_mlm_tokens_per_sec",
                        "tokens/sec", lambda: bench_bert_long_seq()))
        configs.append(("bert_tf_import_finetune_tokens_per_sec",
                        "tokens/sec", lambda: bench_bert_tf_import()))
    for metric, unit, fn in configs:
        try:
            v = fn()
            print(json.dumps({"metric": metric, "value": round(v, 1),
                              "unit": unit}), flush=True)
        except Exception as e:  # a failing extra must not break the headline
            print(json.dumps({"metric": metric, "value": None, "unit": unit,
                              "error": repr(e)[:300]}), flush=True)

    print(json.dumps({
        "metric": "resnet50_train_samples_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(per_chip / V100_RESNET50_SAMPLES_SEC, 3),
    }))


if __name__ == "__main__":
    main()
