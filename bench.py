"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): ResNet-50 training samples/sec/chip on the
real TPU.  `vs_baseline` is measured-vs-north-star: the reference publishes
no numbers (BASELINE.md), so the comparison point is the commonly cited
nd4j-cuda/V100-class ResNet-50 training throughput of ~400 samples/sec/GPU
(MLPerf-era V100 fp32 figures); >1.0 means we beat it.

Extra per-config results (LeNet, LSTM char-LM) go to stderr so the stdout
contract stays one line.  Run: `python bench.py [--quick]`.
"""
import json
import sys
import time

import numpy as np

V100_RESNET50_SAMPLES_SEC = 400.0   # north-star comparison point (fp32 V100)


def _time_steps(fit_fn, n_warmup, n_steps, sync_fn=None):
    """Chained-step timing: steps dispatch back-to-back (device-resident
    data, no per-step host sync — the async-prefetch training loop shape);
    `sync_fn` forces completion once, inside the timed region."""
    for _ in range(n_warmup):
        fit_fn()
    if sync_fn is not None:
        sync_fn()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        fit_fn()
    if sync_fn is not None:
        sync_fn()
    return time.perf_counter() - t0


def bench_resnet50(batch=64, steps=20, image=224, classes=1000,
                   compute_dtype="bfloat16"):
    """bf16 compute / f32 master params — the TPU-native precision choice
    (f32: ~375 samples/sec on v5e; bf16: ~1636)."""
    import jax
    from deeplearning4j_tpu.train.updaters import Nesterovs
    from deeplearning4j_tpu.zoo import ResNet50

    import jax.numpy as jnp

    net = ResNet50(n_classes=classes, input_shape=(image, image, 3),
                   updater=Nesterovs(0.1, 0.9),
                   compute_dtype=compute_dtype).init_model()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, image, image, 3).astype(np.float32))
    y = jnp.asarray(
        np.eye(classes, dtype=np.float32)[rng.randint(0, classes, batch)])

    def step():
        net.fit(x, y)

    dt = _time_steps(step, n_warmup=3, n_steps=steps,
                     sync_fn=lambda: float(net.score()))
    return batch * steps / dt


def bench_lenet(batch=256, steps=30):
    import jax
    from deeplearning4j_tpu.zoo import LeNet

    import jax.numpy as jnp

    net = LeNet().init_model()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, 28, 28, 1).astype(np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rng.randint(0, 10, batch)])

    def step():
        net.fit(x, y)

    dt = _time_steps(step, n_warmup=3, n_steps=steps,
                     sync_fn=lambda: float(net.score()))
    return batch * steps / dt


def bench_bert_base(batch=64, steps=10, t=128, compute_dtype="bfloat16"):
    """BERT-base masked-LM fine-tune step, tokens/sec (BASELINE config 3).
    bf16 compute (master params f32) — the TPU-native precision choice."""
    import jax
    from deeplearning4j_tpu.train.updaters import Adam
    from deeplearning4j_tpu.zoo import BertConfig, BertModel

    model = BertModel(BertConfig.base(max_len=t,
                                      compute_dtype=compute_dtype),
                      updater=Adam(1e-4))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 30522, (batch, t)).astype(np.int32)
    mask = np.ones((batch, t), np.float32)
    sel = rng.rand(batch, t) < 0.15
    lmask = sel.astype(np.float32)

    import jax.numpy as jnp

    from deeplearning4j_tpu.data.dataset import MultiDataSet
    mds = MultiDataSet(features=[jnp.asarray(ids), jnp.asarray(mask)],
                       labels=[jnp.asarray(ids)],
                       labels_masks=[jnp.asarray(lmask)])   # sparse labels

    def step():
        model.fit_batch(mds)

    dt = _time_steps(step, n_warmup=3, n_steps=steps,
                     sync_fn=lambda: model.score())
    return batch * t * steps / dt


def bench_lstm_charlm(batch=64, steps=10, t=64, vocab=77):
    import jax
    from deeplearning4j_tpu.zoo import TextGenLSTM

    import jax.numpy as jnp

    net = TextGenLSTM(n_classes=vocab, input_shape=(t, vocab)).init_model()
    rng = np.random.RandomState(0)
    idx = rng.randint(0, vocab, (batch, t))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[idx])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[np.roll(idx, -1, 1)])

    def step():
        net.fit(x, y)

    dt = _time_steps(step, n_warmup=2, n_steps=steps,
                     sync_fn=lambda: float(net.score()))
    return batch * t * steps / dt


def main():
    quick = "--quick" in sys.argv
    import jax
    n_chips = max(len(jax.devices()), 1)
    print(f"devices: {jax.devices()}", file=sys.stderr)

    if quick:
        sps = bench_resnet50(batch=16, steps=5, image=96, classes=100)
    else:
        sps = bench_resnet50()
    per_chip = sps / n_chips

    extras = {}
    try:
        extras["lenet_mnist_samples_sec"] = round(bench_lenet(), 1)
        extras["lstm_charlm_tokens_sec"] = round(
            bench_lstm_charlm(steps=3 if quick else 10), 1)
        extras["bert_base_mlm_tokens_sec"] = round(
            bench_bert_base(steps=3 if quick else 10), 1)
    except Exception as e:  # extras must never break the headline line
        print(f"extra benches failed: {e}", file=sys.stderr)
    if extras:
        print(json.dumps({"extras": extras}), file=sys.stderr)

    print(json.dumps({
        "metric": "resnet50_train_samples_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(per_chip / V100_RESNET50_SAMPLES_SEC, 3),
    }))


if __name__ == "__main__":
    main()
