// Threshold gradient codec — C++ core.
//
// Reference: libnd4j's threshold encoding op
// (`libnd4j/include/ops/declarable/generic/compression/threshold_encoding
// .cpp` + `TrainingDriver`/`EncodedGradientsAccumulator` Java side): values
// with |g| >= threshold are flattened to a sparse (index, sign) stream and
// SUBTRACTED from the residual so un-sent magnitude carries to the next
// step (1-bit-SGD-style delta compression for slow interconnects).
//
// TPU role: the ICI data plane uses XLA all-reduce (no compression), but
// the optional DCN/multi-slice hop keeps this codec (SURVEY.md §2.4).
// Encoded format: int32 array [n, idx0, idx1, ...] where sign is carried
// in the index's sign bit (idx+1 for +threshold, -(idx+1) for -threshold)
// — the reference's flat-threshold format.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

// Encode: scan grad, emit up to max_elements sparse entries, subtract
// emitted magnitude from residual (residual updated in place).
// Returns number of encoded elements.
int64_t threshold_encode(const float* grad, float* residual, int64_t n,
                         float threshold, int32_t* out,
                         int64_t max_elements) {
    int64_t count = 0;
    for (int64_t i = 0; i < n; ++i) {
        float v = grad[i] + residual[i];
        if (v >= threshold) {
            if (count < max_elements) {
                out[count++] = static_cast<int32_t>(i + 1);
                residual[i] = v - threshold;
            } else {
                residual[i] = v;   // didn't fit: carry everything
            }
        } else if (v <= -threshold) {
            if (count < max_elements) {
                out[count++] = static_cast<int32_t>(-(i + 1));
                residual[i] = v + threshold;
            } else {
                residual[i] = v;
            }
        } else {
            residual[i] = v;       // below threshold: carry
        }
    }
    return count;
}

// Decode: scatter +/- threshold into a dense float buffer (accumulating —
// callers zero it or apply on top of params, reference semantics).
void threshold_decode(const int32_t* encoded, int64_t count,
                      float threshold, float* dense, int64_t n) {
    for (int64_t j = 0; j < count; ++j) {
        int32_t e = encoded[j];
        if (e > 0 && e <= n) {
            dense[e - 1] += threshold;
        } else if (e < 0 && -e <= n) {
            dense[-e - 1] -= threshold;
        }
    }
}

// Fraction of entries that were >= threshold — used for the reference's
// adaptive-threshold logic (ResidualPostProcessor bumps the threshold when
// the update is too dense).
double threshold_density(const float* grad, const float* residual,
                         int64_t n, float threshold) {
    int64_t count = 0;
    for (int64_t i = 0; i < n; ++i) {
        float v = grad[i] + residual[i];
        if (v >= threshold || v <= -threshold) ++count;
    }
    return static_cast<double>(count) / static_cast<double>(n);
}

}  // extern "C"
