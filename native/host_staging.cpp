// Host-side staging buffer pool + parallel batch assembly.
//
// Reference role: the JVM side's pinned-memory staging + AsyncDataSetIterator
// prefetch thread (`nd4j-cuda` AtomicAllocator pinned buffers,
// `deeplearning4j-core/.../AsyncDataSetIterator.java`): get training batches
// assembled into contiguous, aligned host buffers off the training thread so
// the device-feed path never waits on Python-side ETL.
//
// TPU shape of the problem: PJRT H2D wants one contiguous aligned buffer per
// array; Python-side np.stack of many sample rows is single-threaded and
// copies twice.  This module does the gather-into-aligned-buffer step in
// C++ with OpenMP across samples.
//
// C ABI for ctypes.

#include <cstdint>
#include <cstdlib>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// Allocate a 64-byte-aligned buffer (TPU-friendly host alignment).
void* staging_alloc(int64_t bytes) {
    void* p = nullptr;
    if (posix_memalign(&p, 64, static_cast<size_t>(bytes)) != 0) return nullptr;
    return p;
}

void staging_free(void* p) { free(p); }

// Gather: copy `n_samples` rows of `row_bytes` each from arbitrary source
// pointers into one contiguous destination (parallel across samples).
// srcs: array of n_samples pointers.
void staging_gather(const void** srcs, int64_t n_samples, int64_t row_bytes,
                    void* dst) {
    char* out = static_cast<char*>(dst);
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n_samples; ++i) {
        memcpy(out + i * row_bytes, srcs[i], static_cast<size_t>(row_bytes));
    }
}

// Gather with index selection: dst[i] = base[indices[i]] (the shuffled
// minibatch assembly path — one pass, no Python loop).
void staging_gather_indexed(const void* base, const int64_t* indices,
                            int64_t n_samples, int64_t row_bytes,
                            void* dst) {
    const char* src = static_cast<const char*>(base);
    char* out = static_cast<char*>(dst);
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n_samples; ++i) {
        memcpy(out + i * row_bytes, src + indices[i] * row_bytes,
               static_cast<size_t>(row_bytes));
    }
}

// uint8 -> float32 with scale (image pipelines: decode+normalize fused,
// the NativeImageLoader role), parallel across rows.
void staging_u8_to_f32(const uint8_t* src, float* dst, int64_t n,
                       float scale) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n; ++i) {
        dst[i] = static_cast<float>(src[i]) * scale;
    }
}

}  // extern "C"
