import numpy as np, jax, jax.numpy as jnp
from deeplearning4j_tpu.train.updaters import Nesterovs
from deeplearning4j_tpu.zoo import ResNet50

net = ResNet50(n_classes=1000, input_shape=(224,224,3),
               updater=Nesterovs(0.1,0.9), compute_dtype="bfloat16").init_model()
rng = np.random.RandomState(0)
x = jnp.asarray(rng.rand(64,224,224,3).astype(np.float32))
y = jnp.asarray(np.eye(1000,dtype=np.float32)[rng.randint(0,1000,64)])
for _ in range(3): net.fit(x,y)
print("warm score", float(net.score()))
with jax.profiler.trace("/root/repo/bench_artifacts/trace_r50"):
    for _ in range(10): net.fit(x,y)
    print("traced score", float(net.score()))
print("done")
