"""ONNX import conformance tests.

No `onnx`/`onnxruntime` in the image (zero egress), so the tests author
.onnx files with the in-repo `onnx_proto` codec, copy weights out of torch
(CPU) models, and conformance-check the imported SameDiff predictions
against torch's forward pass — a genuine cross-implementation check of op
semantics (reference analog: samediff-import-onnx's TestOnnxIR /
onnx-defined model zoo tests).
"""
import numpy as np
import pytest
import torch
import torch.nn as tnn

from deeplearning4j_tpu.modelimport.onnx_import import (
    UnmappedOnnxOpException, import_onnx_model)
from deeplearning4j_tpu.modelimport.onnx_proto import (
    GraphProto, ModelProto, NodeProto, TensorProto, ValueInfoProto,
    attr_f, attr_i, attr_ints, attr_s, attr_t, load_model)
from deeplearning4j_tpu.autodiff import TrainingConfig
from deeplearning4j_tpu.train.updaters import Adam

torch.manual_seed(0)


def _model(nodes, inputs, outputs, initializers):
    return ModelProto(graph=GraphProto(
        node=nodes, input=inputs, output=outputs,
        initializer=[TensorProto.from_array(a, name=k)
                     for k, a in initializers.items()]))


def _vi(name, shape):
    return ValueInfoProto(name=name, shape=list(shape))


def _N(op, ins, outs, *attrs, name=""):
    return NodeProto(op_type=op, name=name or outs[0], input=list(ins),
                     output=list(outs), attribute=list(attrs))


# ---------------------------------------------------------------------------
# codec round-trip
# ---------------------------------------------------------------------------

def test_proto_roundtrip(tmp_path):
    w = np.random.default_rng(0).standard_normal((4, 3)).astype(np.float32)
    m = _model(
        [_N("Gemm", ["x", "w"], ["y"], attr_f("alpha", 1.0),
            attr_i("transB", 1))],
        [_vi("x", (None, 3))], [_vi("y", (None, 4))], {"w": w.T})
    p = str(tmp_path / "m.onnx")
    with open(p, "wb") as f:
        f.write(m.serialize())
    m2 = load_model(p)
    assert m2.graph.node[0].op_type == "Gemm"
    assert m2.graph.node[0].attribute[0].name == "alpha"
    np.testing.assert_array_equal(m2.graph.initializer[0].to_array(), w.T)
    assert m2.graph.input[0].shape == [None, 3]


def test_tensorproto_dtypes():
    for arr in [np.arange(6, dtype=np.int64).reshape(2, 3),
                np.ones((2, 2), np.float32),
                np.array([True, False]),
                np.arange(4, dtype=np.float16)]:
        t = TensorProto.from_array(arr, "t")
        back = TensorProto.parse(t.serialize()).to_array()
        np.testing.assert_array_equal(back, arr)
        assert back.dtype == arr.dtype


# ---------------------------------------------------------------------------
# LeNet conformance vs torch
# ---------------------------------------------------------------------------

class _TorchLeNet(tnn.Module):
    def __init__(self):
        super().__init__()
        self.c1 = tnn.Conv2d(1, 6, 5, padding=2)
        self.c2 = tnn.Conv2d(6, 16, 5)
        self.f1 = tnn.Linear(16 * 5 * 5, 120)
        self.f2 = tnn.Linear(120, 10)

    def forward(self, x):
        x = torch.max_pool2d(torch.relu(self.c1(x)), 2)
        x = torch.max_pool2d(torch.relu(self.c2(x)), 2)
        x = x.flatten(1)
        x = torch.relu(self.f1(x))
        return self.f2(x)


def _lenet_onnx(net):
    p = {k: v.detach().numpy() for k, v in net.state_dict().items()}
    nodes = [
        _N("Conv", ["x", "c1.weight", "c1.bias"], ["h1"],
           attr_ints("strides", [1, 1]), attr_ints("pads", [2, 2, 2, 2])),
        _N("Relu", ["h1"], ["r1"]),
        _N("MaxPool", ["r1"], ["p1"], attr_ints("kernel_shape", [2, 2]),
           attr_ints("strides", [2, 2])),
        _N("Conv", ["p1", "c2.weight", "c2.bias"], ["h2"],
           attr_ints("strides", [1, 1])),
        _N("Relu", ["h2"], ["r2"]),
        _N("MaxPool", ["r2"], ["p2"], attr_ints("kernel_shape", [2, 2]),
           attr_ints("strides", [2, 2])),
        _N("Flatten", ["p2"], ["flat"], attr_i("axis", 1)),
        _N("Gemm", ["flat", "f1.weight", "f1.bias"], ["fc1"],
           attr_i("transB", 1)),
        _N("Relu", ["fc1"], ["rf1"]),
        _N("Gemm", ["rf1", "f2.weight", "f2.bias"], ["logits"],
           attr_i("transB", 1)),
    ]
    return _model(nodes, [_vi("x", (None, 1, 28, 28))],
                  [_vi("logits", (None, 10))], p)


def test_lenet_import_matches_torch():
    net = _TorchLeNet().eval()
    sd = import_onnx_model(_lenet_onnx(net))
    x = np.random.default_rng(1).standard_normal(
        (4, 1, 28, 28)).astype(np.float32)
    with torch.no_grad():
        want = net(torch.from_numpy(x)).numpy()
    got = np.asarray(sd.output({"x": x}, "logits")["logits"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    assert sd.import_inputs == ["x"] and sd.import_outputs == ["logits"]


def test_lenet_import_fine_tune():
    """VERDICT #6's import-then-train story: imported float initializers are
    trainable variables; attach a loss and fit."""
    net = _TorchLeNet().eval()
    sd = import_onnx_model(_lenet_onnx(net))
    lab = sd.placeholder("lab", (None, 10))
    sd.loss.softmax_cross_entropy(lab, sd.get_variable("logits"),
                                  name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(
        updater=Adam(1e-3), data_set_feature_mapping=["x"],
        data_set_label_mapping=["lab"]))
    rng = np.random.default_rng(2)
    x = rng.standard_normal((16, 1, 28, 28)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)]
    sd.fit(x, y)
    first = sd.score()
    for _ in range(10):
        sd.fit(x, y)
    assert sd.score() < first


# ---------------------------------------------------------------------------
# ResNet-style block conformance vs torch (Conv+BN+residual+GAP+Gemm)
# ---------------------------------------------------------------------------

class _TorchResBlock(tnn.Module):
    def __init__(self):
        super().__init__()
        self.c1 = tnn.Conv2d(3, 8, 3, padding=1, bias=False)
        self.b1 = tnn.BatchNorm2d(8)
        self.c2 = tnn.Conv2d(8, 8, 3, padding=1, bias=False)
        self.b2 = tnn.BatchNorm2d(8)
        self.proj = tnn.Conv2d(3, 8, 1, bias=False)
        self.fc = tnn.Linear(8, 5)

    def forward(self, x):
        h = torch.relu(self.b1(self.c1(x)))
        h = self.b2(self.c2(h))
        h = torch.relu(h + self.proj(x))
        h = h.mean(dim=(2, 3))
        return self.fc(h)


def test_resnet_block_import_matches_torch():
    net = _TorchResBlock().eval()
    # perturb BN running stats so the test isn't mean=0/var=1 trivial
    with torch.no_grad():
        net.b1.running_mean.uniform_(-0.5, 0.5)
        net.b1.running_var.uniform_(0.5, 1.5)
        net.b2.running_mean.uniform_(-0.5, 0.5)
        net.b2.running_var.uniform_(0.5, 1.5)
    p = {k: v.detach().numpy() for k, v in net.state_dict().items()}
    nodes = [
        _N("Conv", ["x", "c1.weight"], ["h1"],
           attr_ints("pads", [1, 1, 1, 1])),
        _N("BatchNormalization",
           ["h1", "b1.weight", "b1.bias", "b1.running_mean",
            "b1.running_var"], ["n1"], attr_f("epsilon", 1e-5)),
        _N("Relu", ["n1"], ["r1"]),
        _N("Conv", ["r1", "c2.weight"], ["h2"],
           attr_ints("pads", [1, 1, 1, 1])),
        _N("BatchNormalization",
           ["h2", "b2.weight", "b2.bias", "b2.running_mean",
            "b2.running_var"], ["n2"], attr_f("epsilon", 1e-5)),
        _N("Conv", ["x", "proj.weight"], ["skip"]),
        _N("Add", ["n2", "skip"], ["res"]),
        _N("Relu", ["res"], ["r2"]),
        _N("GlobalAveragePool", ["r2"], ["gap"]),
        _N("Flatten", ["gap"], ["flat"], attr_i("axis", 1)),
        _N("Gemm", ["flat", "fc.weight", "fc.bias"], ["out"],
           attr_i("transB", 1)),
    ]
    drop = {"b1.num_batches_tracked", "b2.num_batches_tracked"}
    m = _model(nodes, [_vi("x", (None, 3, 8, 8))], [_vi("out", (None, 5))],
               {k: v for k, v in p.items() if k not in drop})
    sd = import_onnx_model(m)
    x = np.random.default_rng(3).standard_normal(
        (2, 3, 8, 8)).astype(np.float32)
    with torch.no_grad():
        want = net(torch.from_numpy(x)).numpy()
    got = np.asarray(sd.output({"x": x}, "out")["out"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# Transformer block conformance vs torch (MatMul/Reshape/Transpose/Softmax/
# LayerNormalization/Erf-GELU/Slice/Split/Gather)
# ---------------------------------------------------------------------------

def test_transformer_block_import_matches_torch():
    B, T, H, NH = 2, 6, 16, 4
    rng = np.random.default_rng(4)
    p = {
        "wq": rng.standard_normal((H, H)).astype(np.float32) * 0.2,
        "wk": rng.standard_normal((H, H)).astype(np.float32) * 0.2,
        "wv": rng.standard_normal((H, H)).astype(np.float32) * 0.2,
        "wo": rng.standard_normal((H, H)).astype(np.float32) * 0.2,
        "w1": rng.standard_normal((H, 4 * H)).astype(np.float32) * 0.2,
        "w2": rng.standard_normal((4 * H, H)).astype(np.float32) * 0.2,
        "ln_g": np.abs(rng.standard_normal(H)).astype(np.float32) + 0.5,
        "ln_b": rng.standard_normal(H).astype(np.float32) * 0.1,
        "scale": np.float32(1.0 / np.sqrt(H // NH)),
    }

    def heads(name_in, w, out):
        return [
            _N("MatMul", [name_in, w], [f"{out}_p"]),
            _N("Reshape", [f"{out}_p", "head_shape"], [f"{out}_r"]),
            _N("Transpose", [f"{out}_r"], [out],
               attr_ints("perm", [0, 2, 1, 3])),
        ]

    nodes = (
        heads("x", "wq", "q") + heads("x", "wk", "k")
        + heads("x", "wv", "v")
        + [
            _N("Transpose", ["k"], ["kT"], attr_ints("perm", [0, 1, 3, 2])),
            _N("MatMul", ["q", "kT"], ["scores_raw"]),
            _N("Mul", ["scores_raw", "scale"], ["scores"]),
            _N("Softmax", ["scores"], ["probs"], attr_i("axis", -1)),
            _N("MatMul", ["probs", "v"], ["ctx"]),
            _N("Transpose", ["ctx"], ["ctx_t"],
               attr_ints("perm", [0, 2, 1, 3])),
            _N("Reshape", ["ctx_t", "merge_shape"], ["ctx_m"]),
            _N("MatMul", ["ctx_m", "wo"], ["attn_out"]),
            _N("Add", ["x", "attn_out"], ["res1"]),
            _N("LayerNormalization", ["res1", "ln_g", "ln_b"], ["ln1"],
               attr_f("epsilon", 1e-5), attr_i("axis", -1)),
            # GELU via erf composition (what real BERT exports contain)
            _N("MatMul", ["ln1", "w1"], ["ff1"]),
            _N("Div", ["ff1", "sqrt2"], ["ff_div"]),
            _N("Erf", ["ff_div"], ["ff_erf"]),
            _N("Add", ["ff_erf", "one"], ["ff_add"]),
            _N("Mul", ["ff1", "ff_add"], ["ff_mul"]),
            _N("Mul", ["ff_mul", "half"], ["ff_gelu"]),
            _N("MatMul", ["ff_gelu", "w2"], ["ff2"]),
            _N("Add", ["ln1", "ff2"], ["y"]),
        ])
    consts = {"head_shape": np.array([0, T, NH, H // NH], np.int64),
              "merge_shape": np.array([0, T, H], np.int64),
              "sqrt2": np.float32(np.sqrt(2.0)), "one": np.float32(1.0),
              "half": np.float32(0.5)}
    m = _model(nodes, [_vi("x", (None, T, H))], [_vi("y", (None, T, H))],
               {**p, **consts})
    sd = import_onnx_model(m)

    x = rng.standard_normal((B, T, H)).astype(np.float32)

    def torch_fwd(xt):
        q = (xt @ torch.from_numpy(p["wq"])).reshape(B, T, NH, -1) \
            .permute(0, 2, 1, 3)
        k = (xt @ torch.from_numpy(p["wk"])).reshape(B, T, NH, -1) \
            .permute(0, 2, 1, 3)
        v = (xt @ torch.from_numpy(p["wv"])).reshape(B, T, NH, -1) \
            .permute(0, 2, 1, 3)
        probs = torch.softmax(q @ k.transpose(-1, -2) * p["scale"].item(),
                              dim=-1)
        ctx = (probs @ v).permute(0, 2, 1, 3).reshape(B, T, H)
        attn = ctx @ torch.from_numpy(p["wo"])
        ln1 = torch.nn.functional.layer_norm(
            xt + attn, (H,), torch.from_numpy(p["ln_g"]),
            torch.from_numpy(p["ln_b"]), eps=1e-5)
        ff1 = ln1 @ torch.from_numpy(p["w1"])
        gelu = torch.nn.functional.gelu(ff1)     # exact erf form
        return ln1 + gelu @ torch.from_numpy(p["w2"])

    with torch.no_grad():
        want = torch_fwd(torch.from_numpy(x)).numpy()
    got = np.asarray(sd.output({"x": x}, "y")["y"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# op-level checks: Split/Slice/Gather/Unsqueeze/ReduceMean/Pad
# ---------------------------------------------------------------------------

def test_shape_op_semantics():
    rng = np.random.default_rng(5)
    data = rng.standard_normal((2, 6, 4)).astype(np.float32)
    nodes = [
        _N("Split", ["x"], ["s0", "s1", "s2"], attr_i("axis", 1),
           attr_ints("split", [2, 2, 2])),
        _N("Slice", ["x", "starts", "ends", "axes", "steps"], ["sl"]),
        _N("Gather", ["x", "idx"], ["g"], attr_i("axis", 1)),
        _N("Unsqueeze", ["x", "uax"], ["u"]),
        _N("ReduceMean", ["x"], ["rm"], attr_ints("axes", [2]),
           attr_i("keepdims", 0)),
        _N("Pad", ["x", "pads"], ["pd"]),
        _N("Concat", ["s0", "s1"], ["cc"], attr_i("axis", 1)),
    ]
    consts = {"starts": np.array([1], np.int64),
              "ends": np.array([5], np.int64),
              "axes": np.array([1], np.int64),
              "steps": np.array([2], np.int64),
              "idx": np.array([0, 3], np.int64),
              "uax": np.array([0], np.int64),
              "pads": np.array([0, 1, 0, 0, 1, 0], np.int64)}
    m = _model(nodes, [_vi("x", (2, 6, 4))],
               [_vi(o, ()) for o in
                ["s0", "sl", "g", "u", "rm", "pd", "cc"]], consts)
    sd = import_onnx_model(m)
    out = sd.output({"x": data}, "s0", "sl", "g", "u", "rm", "pd", "cc")
    np.testing.assert_allclose(out["s0"], data[:, :2])
    np.testing.assert_allclose(out["sl"], data[:, 1:5:2])
    np.testing.assert_allclose(out["g"], data[:, [0, 3]])
    np.testing.assert_allclose(out["u"], data[None])
    np.testing.assert_allclose(out["rm"], data.mean(2), rtol=1e-6)
    np.testing.assert_allclose(
        out["pd"], np.pad(data, ((0, 0), (1, 1), (0, 0))))
    np.testing.assert_allclose(out["cc"], data[:, :4])


def test_unmapped_op_named_error():
    m = _model([_N("FancyNewOp", ["x"], ["y"])], [_vi("x", (2,))],
               [_vi("y", (2,))], {})
    with pytest.raises(UnmappedOnnxOpException, match="FancyNewOp"):
        import_onnx_model(m)


def test_onnx_lstm_gru_state_outputs_and_initial_states():
    """LSTM Y/Y_h/Y_c and GRU Y/Y_h with initial_h/initial_c vs torch
    (the state paths the single-output corpus runner cannot cover)."""
    import torch

    rs = np.random.RandomState(23)
    T, B, I, H = 4, 3, 5, 6

    def g(*s):
        return rs.uniform(-0.4, 0.4, s).astype(np.float32)

    x = g(T, B, I)
    h0 = g(1, B, H)
    c0 = g(1, B, H)

    # --- LSTM (torch ifgo -> onnx iofc) ---
    tw_ih, tw_hh, tb_ih, tb_hh = g(4 * H, I), g(4 * H, H), g(4 * H), g(4 * H)

    def iofc(m):
        i, f, gg, o = np.split(m, 4, 0)
        return np.concatenate([i, o, f, gg], 0)

    lstm = torch.nn.LSTM(I, H, 1)
    st = lstm.state_dict()
    st["weight_ih_l0"] = torch.from_numpy(tw_ih)
    st["weight_hh_l0"] = torch.from_numpy(tw_hh)
    st["bias_ih_l0"] = torch.from_numpy(tb_ih)
    st["bias_hh_l0"] = torch.from_numpy(tb_hh)
    lstm.load_state_dict(st)
    with torch.no_grad():
        ty, (th, tc) = lstm(torch.from_numpy(x),
                            (torch.from_numpy(h0), torch.from_numpy(c0)))

    nodes = [_N("LSTM", ["x", "W", "R", "Bb", "", "h0", "c0"],
                ["y", "yh", "yc"], attr_i("hidden_size", H))]
    model = _model(nodes, [_vi("x", x.shape), _vi("h0", h0.shape),
                           _vi("c0", c0.shape)],
                   [_vi("y", ()), _vi("yh", ()), _vi("yc", ())],
                   {"W": iofc(tw_ih)[None], "R": iofc(tw_hh)[None],
                    "Bb": np.concatenate([iofc(tb_ih), iofc(tb_hh)])[None]})
    sd = import_onnx_model(model)
    got = sd.output({"x": x, "h0": h0, "c0": c0}, "y", "yh", "yc")
    np.testing.assert_allclose(np.asarray(got["y"]), ty.numpy()[:, None],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got["yh"]), th.numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got["yc"]), tc.numpy(),
                               rtol=1e-5, atol=1e-5)

    # --- GRU (torch rzn -> onnx zrh), with initial_h and Y_h output ---
    gw_ih, gw_hh, gb_ih, gb_hh = g(3 * H, I), g(3 * H, H), g(3 * H), g(3 * H)

    def zrh(m):
        r, z, nn_ = np.split(m, 3, 0)
        return np.concatenate([z, r, nn_], 0)

    gru = torch.nn.GRU(I, H, 1)
    st = gru.state_dict()
    st["weight_ih_l0"] = torch.from_numpy(gw_ih)
    st["weight_hh_l0"] = torch.from_numpy(gw_hh)
    st["bias_ih_l0"] = torch.from_numpy(gb_ih)
    st["bias_hh_l0"] = torch.from_numpy(gb_hh)
    gru.load_state_dict(st)
    with torch.no_grad():
        gy, gh = gru(torch.from_numpy(x), torch.from_numpy(h0))

    nodes = [_N("GRU", ["x", "W", "R", "Bb", "", "h0"], ["y", "yh"],
                attr_i("hidden_size", H), attr_i("linear_before_reset", 1))]
    model = _model(nodes, [_vi("x", x.shape), _vi("h0", h0.shape)],
                   [_vi("y", ()), _vi("yh", ())],
                   {"W": zrh(gw_ih)[None], "R": zrh(gw_hh)[None],
                    "Bb": np.concatenate([zrh(gb_ih), zrh(gb_hh)])[None]})
    sd = import_onnx_model(model)
    got = sd.output({"x": x, "h0": h0}, "y", "yh")
    np.testing.assert_allclose(np.asarray(got["y"]), gy.numpy()[:, None],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got["yh"]), gh.numpy(),
                               rtol=1e-5, atol=1e-5)

    # --- LSTM with ONLY initial_c (the silent-drop regression) ---
    nodes = [_N("LSTM", ["x", "W", "R", "Bb", "", "", "c0"],
                ["y"], attr_i("hidden_size", H))]
    model = _model(nodes, [_vi("x", x.shape), _vi("c0", c0.shape)],
                   [_vi("y", ())],
                   {"W": iofc(tw_ih)[None], "R": iofc(tw_hh)[None],
                    "Bb": np.concatenate([iofc(tb_ih), iofc(tb_hh)])[None]})
    sd = import_onnx_model(model)
    with torch.no_grad():
        ty2, _ = lstm(torch.from_numpy(x),
                      (torch.zeros(1, B, H), torch.from_numpy(c0)))
    got = sd.output({"x": x, "c0": c0}, "y")
    np.testing.assert_allclose(np.asarray(got["y"]), ty2.numpy()[:, None],
                               rtol=1e-5, atol=1e-5)
