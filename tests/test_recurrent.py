"""Recurrent + attention layer tests (reference: platform-tests RNN tests,
`LSTMGradientCheckTests`, attention layer tests)."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import (
    Bidirectional, GravesLSTM, InputType, LastTimeStep,
    LearnedSelfAttentionLayer, LSTM, MultiLayerNetwork,
    NeuralNetConfiguration, OutputLayer, RecurrentAttentionLayer,
    RnnOutputLayer, SelfAttentionLayer, SimpleRnn)
from deeplearning4j_tpu.nn.core import Layer
from deeplearning4j_tpu.train import Adam, Sgd
from deeplearning4j_tpu.train.gradientcheck import check_gradients

KEY = jax.random.PRNGKey(0)


def run(layer, input_type, x, mask=None):
    params, state, out_type = layer.initialize(KEY, input_type)
    y, _ = layer.apply(params, state, x, mask=mask)
    return y, out_type


def test_simple_rnn_shapes():
    x = jnp.ones((2, 5, 3))
    y, ot = run(SimpleRnn(n_out=4, weight_init="XAVIER"),
                InputType.recurrent(3, 5), x)
    assert y.shape == (2, 5, 4) and ot.shape == (5, 4)


def test_lstm_shapes_and_mask():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 6, 3)))
    mask = jnp.asarray([[1, 1, 1, 1, 0, 0], [1, 1, 1, 1, 1, 1]], jnp.float32)
    layer = LSTM(n_out=4, weight_init="XAVIER")
    params, state, _ = layer.initialize(KEY, InputType.recurrent(3, 6))
    y, _ = layer.apply(params, state, x, mask=mask)
    assert y.shape == (2, 6, 4)
    # masked steps produce zero output
    np.testing.assert_allclose(np.asarray(y)[0, 4:], 0.0)
    # mask makes trailing input values irrelevant
    x2 = x.at[0, 4:].set(99.0)
    y2, _ = layer.apply(params, state, x2, mask=mask)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y2), atol=1e-6)


def test_lstm_forget_bias():
    layer = LSTM(n_out=4, forget_gate_bias_init=1.0, weight_init="XAVIER")
    params, _, _ = layer.initialize(KEY, InputType.recurrent(3, 6))
    b = np.asarray(params["b"])
    np.testing.assert_allclose(b[4:8], 1.0)   # forget block (IFOG order)
    np.testing.assert_allclose(b[:4], 0.0)


def test_graves_lstm_has_peepholes():
    layer = GravesLSTM(n_out=4, weight_init="XAVIER")
    params, state, _ = layer.initialize(KEY, InputType.recurrent(3, 5))
    assert params["pW"].shape == (3, 4)
    y, _ = layer.apply(params, state, jnp.ones((2, 5, 3)))
    assert y.shape == (2, 5, 4)


def test_bidirectional_concat_and_add():
    x = jnp.ones((2, 5, 3))
    y, ot = run(Bidirectional(fwd=LSTM(n_out=4), weight_init="XAVIER"),
                InputType.recurrent(3, 5), x)
    assert y.shape == (2, 5, 8) and ot.shape == (5, 8)
    y, ot = run(Bidirectional(fwd=LSTM(n_out=4), mode="ADD",
                              weight_init="XAVIER"),
                InputType.recurrent(3, 5), x)
    assert y.shape == (2, 5, 4)


def test_last_time_step_with_mask():
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 5, 3)))
    mask = jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.float32)
    layer = LastTimeStep(underlying=SimpleRnn(n_out=4), weight_init="XAVIER")
    params, state, ot = layer.initialize(KEY, InputType.recurrent(3, 5))
    assert ot.kind == "feedforward" and ot.shape == (4,)
    y, _ = layer.apply(params, state, x, mask=mask)
    full, _ = layer.underlying.apply(params, state, x, mask=mask)
    np.testing.assert_allclose(np.asarray(y[0]), np.asarray(full[0, 2]))
    np.testing.assert_allclose(np.asarray(y[1]), np.asarray(full[1, 4]))


def test_self_attention_shapes():
    x = jnp.ones((2, 5, 6))
    y, ot = run(SelfAttentionLayer(n_out=8, n_heads=2, weight_init="XAVIER"),
                InputType.recurrent(6, 5), x)
    assert y.shape == (2, 5, 8) and ot.shape == (5, 8)


def test_learned_self_attention_fixed_queries():
    x = jnp.ones((3, 7, 6))
    y, ot = run(LearnedSelfAttentionLayer(n_out=8, n_heads=2, n_queries=4,
                                          weight_init="XAVIER"),
                InputType.recurrent(6, 7), x)
    assert y.shape == (3, 4, 8) and ot.shape == (4, 8)


def test_recurrent_attention_shapes():
    x = jnp.ones((2, 5, 6))
    y, ot = run(RecurrentAttentionLayer(n_out=4, weight_init="XAVIER"),
                InputType.recurrent(6, 5), x)
    assert y.shape == (2, 5, 4)


def test_attention_mask_excludes_keys():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(1, 4, 6)))
    layer = SelfAttentionLayer(n_out=6, n_heads=2, weight_init="XAVIER")
    params, state, _ = layer.initialize(KEY, InputType.recurrent(6, 4))
    mask = jnp.asarray([[1, 1, 0, 0]], jnp.float32)
    y1, _ = layer.apply(params, state, x, mask=mask)
    x2 = x.at[0, 2:].set(55.0)
    y2, _ = layer.apply(params, state, x2, mask=mask)
    np.testing.assert_allclose(np.asarray(y1[0, :2]), np.asarray(y2[0, :2]),
                               atol=1e-5)


def build_net(layers, input_type, seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Sgd(0.1)).weight_init("XAVIER")
            .dtype("float64")
            .list(layers).set_input_type(input_type).build())
    return MultiLayerNetwork(conf).init()


def test_lstm_gradient_check():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(3, 4, 3))
    y = np.eye(2)[rng.integers(0, 2, (3, 4))]
    net = build_net([
        LSTM(n_out=5, activation="tanh"),
        RnnOutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.recurrent(3, 4))
    def score(params):
        return net._loss(params, net.state_, jnp.asarray(x, jnp.float64),
                         jnp.asarray(y, jnp.float64), None)[0]
    check_gradients(score, net.params_)


def test_rnn_output_layer_mask_loss():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 5, 3))
    y = np.eye(2)[rng.integers(0, 2, (2, 5))]
    mask = np.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.float64)
    net = build_net([
        SimpleRnn(n_out=4, activation="tanh"),
        RnnOutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.recurrent(3, 5))
    s = net.score_for(x, y, features_mask=jnp.asarray(mask),
                      labels_mask=jnp.asarray(mask))
    assert np.isfinite(s)


def test_bidirectional_json_roundtrip():
    layer = Bidirectional(fwd=LSTM(n_out=4, activation="tanh"), mode="ADD")
    d = layer.to_json()
    back = Layer.from_json(d)
    assert isinstance(back, Bidirectional)
    assert isinstance(back.fwd, LSTM) and back.fwd.n_out == 4
    assert back.mode == "ADD"


def test_lstm_net_fits():
    rng = np.random.default_rng(5)
    x = rng.normal(size=(8, 6, 3)).astype(np.float32)
    y = np.eye(2)[rng.integers(0, 2, 8)].astype(np.float32)
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(0.5)).weight_init("XAVIER")
            .list([
                LSTM(n_out=8, activation="tanh"),
                LastTimeStep(underlying=SimpleRnn(n_out=8, activation="tanh")),
                OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
            ]).set_input_type(InputType.recurrent(3, 6)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(x, y)
    first = net.score()
    for _ in range(30):
        net.fit(x, y)
    assert net.score() < first


def test_last_time_step_non_contiguous_mask():
    x = jnp.asarray(np.random.default_rng(4).normal(size=(1, 4, 3)))
    mask = jnp.asarray([[1, 0, 1, 0]], jnp.float32)
    layer = LastTimeStep(underlying=SimpleRnn(n_out=4), weight_init="XAVIER")
    params, state, _ = layer.initialize(KEY, InputType.recurrent(3, 4))
    y, _ = layer.apply(params, state, x, mask=mask)
    full, _ = layer.underlying.apply(params, state, x, mask=mask)
    # last VALID step is t=2, not count-1=1
    np.testing.assert_allclose(np.asarray(y[0]), np.asarray(full[0, 2]))


def test_wrapped_layers_are_regularized():
    net = build_net([
        Bidirectional(fwd=LSTM(n_out=4, activation="tanh")),
        LastTimeStep(underlying=SimpleRnn(n_out=4, activation="tanh")),
        OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.recurrent(3, 5))
    for layer in net.conf.layers:
        layer.l2 = 0.7
    base = float(net._reg_penalty(net.params_))
    # zero the wrapped LSTM weights -> the penalty must drop
    p2 = jax.tree_util.tree_map(lambda a: a, net.params_)
    p2 = dict(p2)
    name0 = net.conf.layer_name(0)
    p2[name0] = {
        "fwd": {**net.params_[name0]["fwd"],
                "W": jnp.zeros_like(net.params_[name0]["fwd"]["W"]),
                "RW": jnp.zeros_like(net.params_[name0]["fwd"]["RW"])},
        "bwd": net.params_[name0]["bwd"],
    }
    assert float(net._reg_penalty(p2)) < base


def test_mask_cleared_after_seq_length_change():
    # LearnedSelfAttention changes T=6 -> n_queries=3; the [B,6] mask must
    # not reach the downstream SimpleRnn (reference feedForwardMaskArray).
    rng = np.random.default_rng(6)
    x = rng.normal(size=(2, 6, 5)).astype(np.float32)
    y = np.eye(2)[rng.integers(0, 2, 2)].astype(np.float32)
    mask = jnp.asarray(np.ones((2, 6), np.float32))
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(0.1)).weight_init("XAVIER")
            .list([
                LearnedSelfAttentionLayer(n_out=4, n_heads=2, n_queries=3),
                LastTimeStep(underlying=SimpleRnn(n_out=4, activation="tanh")),
                OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
            ]).set_input_type(InputType.recurrent(5, 6)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(x, y, features_mask=mask)   # must not crash
    assert np.isfinite(net.score())


def test_wrapper_dropout_applied():
    x = jnp.ones((4, 5, 3))
    layer = Bidirectional(fwd=SimpleRnn(n_out=4, activation="tanh"),
                          dropout=0.5, weight_init="XAVIER")
    params, state, _ = layer.initialize(KEY, InputType.recurrent(3, 5))
    y1, _ = layer.apply(params, state, x, train=True,
                        rng=jax.random.PRNGKey(1))
    y2, _ = layer.apply(params, state, x, train=False, rng=None)
    assert not np.allclose(np.asarray(y1), np.asarray(y2))


def test_learned_self_attention_rejects_no_projection():
    import pytest
    layer = LearnedSelfAttentionLayer(n_out=4, n_queries=2,
                                      project_input=False)
    with np.testing.assert_raises(ValueError):
        layer.initialize(KEY, InputType.recurrent(4, 5))


def test_gru_layer_trains_and_serializes(tmp_path):
    """GRU (exceeds-reference layer): converges on the sequence-sum sign
    task, config/params round-trip through the zip."""
    from deeplearning4j_tpu.nn import GRU

    rng = np.random.RandomState(0)
    x = rng.randn(128, 10, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[
        (x.sum((1, 2)) > 0).astype(int)]
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
            .list([GRU(n_out=16),
                   LastTimeStep(underlying=GRU(n_out=8)),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.recurrent(4, 10)).build())
    net = MultiLayerNetwork(conf).init()
    first = net.score_for(x, y)
    for _ in range(60):
        net.fit(x, y)
    assert net.score_for(x, y) < first * 0.5
    p = str(tmp_path / "gru.zip")
    net.save(p)
    net2 = MultiLayerNetwork.load(p)
    np.testing.assert_array_equal(np.asarray(net.params()),
                                  np.asarray(net2.params()))
    np.testing.assert_allclose(np.asarray(net.output(x[:8])),
                               np.asarray(net2.output(x[:8])), atol=0)


def test_gru_mask_equals_truncated_sequence():
    """A [B,T] mask zeroing the tail must give the same last valid hidden
    state as physically truncating the sequence (state held at pads)."""
    from deeplearning4j_tpu.nn import GRU

    rng = np.random.RandomState(4)
    layer = GRU(n_out=6)
    params, state, _ = layer.initialize(jax.random.PRNGKey(0),
                                        InputType.recurrent(3, 8))
    x = rng.randn(2, 8, 3).astype(np.float32)
    mask = np.ones((2, 8), np.float32)
    mask[:, 5:] = 0.0
    out_m, _ = layer.apply(params, state, jnp.asarray(x),
                           mask=jnp.asarray(mask))
    out_t, _ = layer.apply(params, state, jnp.asarray(x[:, :5]))
    # last valid step matches the truncated run's last step
    np.testing.assert_allclose(np.asarray(out_m[:, 4]),
                               np.asarray(out_t[:, 4]), atol=1e-6)
    # padded steps are zeroed in the output
    assert float(np.abs(np.asarray(out_m[:, 5:])).max()) == 0.0
