"""Multi-host sharded-inference worker (spawned by test_multihost via
LocalLauncher — NOT a pytest file).

Each process joins the cluster, builds the same seeded network, submits its
local slice of a deterministic global request batch through
MultiHostParallelInference, and writes its local predictions for the
driver test to compare against a single-process forward."""
import os
import sys

import numpy as np

from deeplearning4j_tpu.parallel import multihost

multihost.initialize()

from deeplearning4j_tpu.nn import (DenseLayer, InputType,  # noqa: E402
                                   MultiLayerNetwork, NeuralNetConfiguration,
                                   OutputLayer)
from deeplearning4j_tpu.parallel.multihost import (  # noqa: E402
    MultiHostParallelInference)

out_dir = sys.argv[1]
rank = multihost.process_index()
world = multihost.process_count()

rng = np.random.default_rng(3)
X = rng.standard_normal((12, 6)).astype(np.float32)
per = X.shape[0] // world
xl = X[rank * per:(rank + 1) * per]

conf = (NeuralNetConfiguration.builder().seed(11)
        .list([DenseLayer(n_out=8, activation="tanh"),
               OutputLayer(n_out=3, loss="mcxent", activation="softmax")])
        .set_input_type(InputType.feed_forward(6)).build())
net = MultiLayerNetwork(conf).init()
pi = MultiHostParallelInference(net)
local_out = pi.output(xl)
np.savez(os.path.join(out_dir, f"infer_{rank}.npz"), out=local_out)
print(f"rank {rank}/{world}: local_out={local_out.shape}", flush=True)
