"""Model-import conformance tests — the reference pattern (`Keras import
conformance`: golden h5 -> import -> predict -> compare; `TFGraphTestAll
SameDiff`: graph -> import -> execute -> compare within tolerance).

TF/Keras only builds the golden files; our framework does the inference.
"""
import json
import os

import numpy as np
import pytest

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.modelimport import (  # noqa: E402
    KerasModelImport, TFImportRegistry, import_graph_def)
from deeplearning4j_tpu.modelimport.keras import (  # noqa: E402
    UnsupportedKerasConfigurationException)
from deeplearning4j_tpu.modelimport.tf_import import (  # noqa: E402
    UnmappedTFOpException)


def _save(model, tmp_path, name="m.h5"):
    p = str(tmp_path / name)
    model.save(p)
    return p


def test_sequential_dense_import(tmp_path):
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6,)),
        tf.keras.layers.Dense(16, activation="relu"),
        tf.keras.layers.Dense(8, activation="tanh"),
        tf.keras.layers.Dense(3, activation="softmax")])
    p = _save(km, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(0).randn(5, 6).astype(np.float32)
    expected = km.predict(x, verbose=0)
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_sequential_cnn_import(tmp_path):
    km = tf.keras.Sequential([
        tf.keras.layers.Input((12, 12, 3)),
        tf.keras.layers.Conv2D(8, 3, activation="relu", padding="same"),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Conv2D(16, 3, activation="relu", padding="valid"),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10, activation="softmax")])
    p = _save(km, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(1).rand(3, 12, 12, 3).astype(np.float32)
    expected = km.predict(x, verbose=0)
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_sequential_bn_dropout_import(tmp_path):
    km = tf.keras.Sequential([
        tf.keras.layers.Input((8, 8, 2)),
        tf.keras.layers.Conv2D(4, 3, padding="same"),
        tf.keras.layers.BatchNormalization(),
        tf.keras.layers.Activation("relu"),
        tf.keras.layers.Dropout(0.4),
        tf.keras.layers.GlobalAveragePooling2D(),
        tf.keras.layers.Dense(2, activation="softmax")])
    p = _save(km, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(2).rand(4, 8, 8, 2).astype(np.float32)
    expected = km.predict(x, verbose=0)         # inference: dropout off
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, rtol=1e-3, atol=1e-4)


def test_sequential_lstm_import(tmp_path):
    km = tf.keras.Sequential([
        tf.keras.layers.Input((7, 5)),
        tf.keras.layers.LSTM(12, return_sequences=True),
        tf.keras.layers.LSTM(6),                    # last step only
        tf.keras.layers.Dense(2, activation="softmax")])
    p = _save(km, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(3).randn(4, 7, 5).astype(np.float32)
    expected = km.predict(x, verbose=0)
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, rtol=1e-3, atol=1e-4)


def test_functional_residual_import(tmp_path):
    inp = tf.keras.layers.Input((10,), name="inp")
    d1 = tf.keras.layers.Dense(10, activation="relu")(inp)
    d2 = tf.keras.layers.Dense(10, activation="relu")(d1)
    added = tf.keras.layers.Add()([d1, d2])
    out = tf.keras.layers.Dense(4, activation="softmax")(added)
    km = tf.keras.Model(inp, out)
    p = _save(km, tmp_path)
    net = KerasModelImport.import_keras_model_and_weights(p)
    x = np.random.RandomState(4).randn(6, 10).astype(np.float32)
    expected = km.predict(x, verbose=0)
    (got,) = net.output(x)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4,
                               atol=1e-5)


def test_functional_concat_import(tmp_path):
    a = tf.keras.layers.Input((4,), name="a")
    b = tf.keras.layers.Input((6,), name="b")
    da = tf.keras.layers.Dense(5, activation="tanh")(a)
    db = tf.keras.layers.Dense(7, activation="tanh")(b)
    merged = tf.keras.layers.Concatenate()([da, db])
    out = tf.keras.layers.Dense(2, activation="softmax")(merged)
    km = tf.keras.Model([a, b], out)
    p = _save(km, tmp_path)
    net = KerasModelImport.import_keras_model_and_weights(p)
    xa = np.random.RandomState(5).randn(3, 4).astype(np.float32)
    xb = np.random.RandomState(6).randn(3, 6).astype(np.float32)
    expected = km.predict([xa, xb], verbose=0)
    (got,) = net.output(xa, xb)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4,
                               atol=1e-5)


def test_unsupported_layer_named_error(tmp_path):
    # ConvLSTM2D has no converter; the error must NAME the layer class
    # (GRU formerly played this role — it imports now)
    km = tf.keras.Sequential([
        tf.keras.layers.Input((4, 6, 6, 2)),
        tf.keras.layers.ConvLSTM2D(3, kernel_size=3)])
    p = _save(km, tmp_path)
    with pytest.raises(UnsupportedKerasConfigurationException,
                       match="ConvLSTM2D"):
        KerasModelImport.import_keras_sequential_model_and_weights(p)


def test_imported_model_can_finetune(tmp_path):
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6,)),
        tf.keras.layers.Dense(16, activation="relu"),
        tf.keras.layers.Dense(3, activation="softmax")])
    p = _save(km, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    rng = np.random.RandomState(0)
    x = rng.randn(32, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
    s0 = net.score_for(x, y)
    for _ in range(10):
        net.fit(x, y)
    assert net.score_for(x, y) < s0


# ---------------------------------------------------------------------------
# TF GraphDef import
# ---------------------------------------------------------------------------

def _freeze(fn, *specs):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    cf = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(cf)
    return frozen.graph.as_graph_def(), frozen


def test_tf_mlp_graph_import():
    w1 = tf.constant(np.random.RandomState(0).randn(5, 8).astype(np.float32))
    b1 = tf.constant(np.zeros(8, np.float32))
    w2 = tf.constant(np.random.RandomState(1).randn(8, 3).astype(np.float32))

    def f(x):
        h = tf.nn.relu(tf.matmul(x, w1) + b1)
        return tf.nn.softmax(tf.matmul(h, w2))

    gd, frozen = _freeze(f, tf.TensorSpec((None, 5), tf.float32))
    sd = import_graph_def(gd)
    x = np.random.RandomState(2).randn(4, 5).astype(np.float32)
    expected = frozen(tf.constant(x))[0].numpy()
    out_name = gd.node[-1].name
    got = np.asarray(sd.output({"x": x}, out_name)[out_name])
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_tf_conv_graph_import():
    k = tf.constant(np.random.RandomState(0).randn(3, 3, 2, 4)
                    .astype(np.float32) * 0.1)

    def f(x):
        y = tf.nn.conv2d(x, k, strides=[1, 1, 1, 1], padding="SAME")
        y = tf.nn.relu(y)
        y = tf.nn.max_pool2d(y, 2, 2, padding="VALID")
        return tf.reduce_mean(y, axis=[1, 2])

    gd, frozen = _freeze(f, tf.TensorSpec((None, 8, 8, 2), tf.float32))
    sd = import_graph_def(gd)
    x = np.random.RandomState(1).rand(2, 8, 8, 2).astype(np.float32)
    expected = frozen(tf.constant(x))[0].numpy()
    out_name = gd.node[-1].name
    got = np.asarray(sd.output({"x": x}, out_name)[out_name])
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_tf_unmapped_op_named_error():
    def f(x):
        return tf.nn.depth_to_space(x, 2)

    gd, _ = _freeze(f, tf.TensorSpec((1, 4, 4, 4), tf.float32))
    with pytest.raises(UnmappedTFOpException, match="DepthToSpace"):
        import_graph_def(gd)


# ---------------------------------------------------------------------------
# Frozen-BERT GraphDef import (VERDICT #4 / BASELINE config 3: "BERT via
# SameDiff TF import") — a real 2-layer BERT encoder built from raw TF ops,
# frozen, imported, conformance-checked vs TF execution, then fine-tuned.
# ---------------------------------------------------------------------------

def _tf_mini_bert():
    """2-layer, 4-head, H=32 BERT encoder with embedding lookup, erf-GELU,
    layer norm — the op diet of a real frozen BERT GraphDef (MatMul,
    BatchMatMulV2, GatherV2, Mul/Add/Sub, Mean, SquaredDifference, Rsqrt,
    Softmax, Reshape, Transpose, Erf, StridedSlice, Squeeze)."""
    rs = np.random.RandomState(0)
    V, T, H, NH, L = 50, 8, 32, 4, 2
    p = {}
    p["tok_emb"] = tf.constant(rs.randn(V, H).astype(np.float32) * 0.1)
    p["pos_emb"] = tf.constant(rs.randn(T, H).astype(np.float32) * 0.1)
    for l in range(L):
        for w in ["wq", "wk", "wv", "wo"]:
            p[f"{l}.{w}"] = tf.constant(
                rs.randn(H, H).astype(np.float32) * 0.1)
        p[f"{l}.w1"] = tf.constant(rs.randn(H, 4 * H).astype(np.float32)
                                   * 0.1)
        p[f"{l}.w2"] = tf.constant(rs.randn(4 * H, H).astype(np.float32)
                                   * 0.1)
        for g in ["ln1_g", "ln2_g"]:
            p[f"{l}.{g}"] = tf.constant(np.ones(H, np.float32))
        for b in ["ln1_b", "ln2_b"]:
            p[f"{l}.{b}"] = tf.constant(np.zeros(H, np.float32))
    p["cls_w"] = tf.constant(rs.randn(H, 3).astype(np.float32) * 0.1)

    def ln(x, g, b):
        mean = tf.reduce_mean(x, axis=-1, keepdims=True)
        var = tf.reduce_mean(tf.math.squared_difference(x, mean), axis=-1,
                             keepdims=True)
        return (x - mean) * tf.math.rsqrt(var + 1e-6) * g + b

    def gelu(x):
        return 0.5 * x * (1.0 + tf.math.erf(x / np.sqrt(2.0).astype(
            np.float32)))

    def f(ids):
        x = tf.gather(p["tok_emb"], ids, axis=0) + p["pos_emb"]
        B = 2
        for l in range(L):
            def heads(w):
                y = tf.matmul(tf.reshape(x, [B * T, H]), w)
                return tf.transpose(tf.reshape(y, [B, T, NH, H // NH]),
                                    [0, 2, 1, 3])
            q, k, v = (heads(p[f"{l}.wq"]), heads(p[f"{l}.wk"]),
                       heads(p[f"{l}.wv"]))
            scores = tf.matmul(q, k, adjoint_b=True) / np.float32(
                np.sqrt(H // NH))
            ctx = tf.matmul(tf.nn.softmax(scores, axis=-1), v)
            ctx = tf.reshape(tf.transpose(ctx, [0, 2, 1, 3]), [B, T, H])
            attn = tf.matmul(tf.reshape(ctx, [B * T, H]), p[f"{l}.wo"])
            x = ln(x + tf.reshape(attn, [B, T, H]), p[f"{l}.ln1_g"],
                   p[f"{l}.ln1_b"])
            h = gelu(tf.matmul(tf.reshape(x, [B * T, H]), p[f"{l}.w1"]))
            h = tf.matmul(h, p[f"{l}.w2"])
            x = ln(x + tf.reshape(h, [B, T, H]), p[f"{l}.ln2_g"],
                   p[f"{l}.ln2_b"])
        cls = tf.squeeze(tf.strided_slice(
            x, [0, 0, 0], [B, 1, H], [1, 1, 1]), axis=[1])
        return tf.matmul(cls, p["cls_w"])

    return f, (V, T)


def test_tf_bert_graph_import_matches_tf():
    f, (V, T) = _tf_mini_bert()
    gd, frozen = _freeze(f, tf.TensorSpec((2, T), tf.int32))
    ops_seen = {n.op for n in gd.node}
    # the graph must actually exercise the BERT-class op registry
    assert {"BatchMatMulV2", "GatherV2", "StridedSlice", "Squeeze",
            "Erf", "Rsqrt", "SquaredDifference"} <= ops_seen, ops_seen
    sd = import_graph_def(gd)
    ids = np.random.RandomState(1).randint(0, V, (2, T)).astype(np.int32)
    expected = frozen(tf.constant(ids))[0].numpy()
    out_name = gd.node[-1].name
    got = np.asarray(sd.output({"ids": ids}, out_name)[out_name])
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_tf_bert_import_fine_tune():
    """BASELINE config 3 as written: import the frozen BERT, then fine-tune
    via SameDiff training (constants stay frozen; a trainable head drives
    the loss through the imported encoder)."""
    from deeplearning4j_tpu.autodiff import TrainingConfig
    from deeplearning4j_tpu.train.updaters import Adam as SDAdam
    f, (V, T) = _tf_mini_bert()
    gd, frozen = _freeze(f, tf.TensorSpec((2, T), tf.int32))
    sd = import_graph_def(gd)
    out_name = gd.node[-1].name
    # trainable classifier head on top of the imported graph
    w = sd.var("head_w", "XAVIER", 3, 3)
    logits = sd.op("matmul", sd.get_variable(out_name), w, name="head")
    lab = sd.placeholder("lab", (2, 3))
    sd.loss.softmax_cross_entropy(lab, logits, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(
        updater=SDAdam(5e-2), data_set_feature_mapping=["ids"],
        data_set_label_mapping=["lab"]))
    rs = np.random.RandomState(2)
    ids = rs.randint(0, V, (2, T)).astype(np.int32)
    lb = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 2)]
    sd.fit(ids, lb)
    first = sd.score()
    for _ in range(20):
        sd.fit(ids, lb)
    assert sd.score() < first


def test_tf_fused_batchnorm_and_split_import():
    g1 = tf.constant(np.random.RandomState(0).rand(4).astype(np.float32)
                     + 0.5)
    b1 = tf.constant(np.random.RandomState(1).randn(4).astype(np.float32))
    mean = tf.constant(np.random.RandomState(2).randn(4).astype(np.float32))
    var = tf.constant(np.random.RandomState(3).rand(4).astype(np.float32)
                      + 0.5)

    def f(x):
        y, _, _ = tf.compat.v1.nn.fused_batch_norm(
            x, g1, b1, mean=mean, variance=var, epsilon=1e-3,
            is_training=False)
        a, b = tf.split(y, 2, axis=-1)
        return tf.concat([tf.nn.relu(a), tf.tanh(b)], axis=-1)

    gd, frozen = _freeze(f, tf.TensorSpec((2, 3, 3, 4), tf.float32))
    assert {"FusedBatchNormV3", "Split"} <= {n.op for n in gd.node}
    sd = import_graph_def(gd)
    x = np.random.RandomState(4).randn(2, 3, 3, 4).astype(np.float32)
    expected = frozen(tf.constant(x))[0].numpy()
    out_name = gd.node[-1].name
    got = np.asarray(sd.output({"x": x}, out_name)[out_name])
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_tf_depthwise_conv_import():
    k = tf.constant(np.random.RandomState(0).randn(3, 3, 2, 2)
                    .astype(np.float32) * 0.2)

    def f(x):
        y = tf.nn.depthwise_conv2d(x, k, strides=[1, 1, 1, 1],
                                   padding="SAME")
        return tf.nn.relu(y)

    gd, frozen = _freeze(f, tf.TensorSpec((2, 6, 6, 2), tf.float32))
    assert "DepthwiseConv2dNative" in {n.op for n in gd.node}
    sd = import_graph_def(gd)
    x = np.random.RandomState(1).randn(2, 6, 6, 2).astype(np.float32)
    expected = frozen(tf.constant(x))[0].numpy()
    out = gd.node[-1].name
    got = np.asarray(sd.output({"x": x}, out)[out])
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_keras_extended_layer_converters(tmp_path):
    """Round-2 converter breadth: Conv2DTranspose, Cropping2D, LeakyReLU,
    PReLU, LayerNormalization, pooling variants — import -> predict matches
    TF."""
    km = tf.keras.Sequential([
        tf.keras.layers.Input((10, 10, 3)),
        tf.keras.layers.Conv2D(6, 3, padding="same"),
        tf.keras.layers.LeakyReLU(),
        tf.keras.layers.Conv2DTranspose(4, 2, strides=2, padding="same"),
        tf.keras.layers.PReLU(shared_axes=[1, 2]),
        tf.keras.layers.Cropping2D(((2, 2), (2, 2))),
        tf.keras.layers.AveragePooling2D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(8),
        tf.keras.layers.LayerNormalization(),
        tf.keras.layers.ELU(),
        tf.keras.layers.Dense(3, activation="softmax")])
    # non-trivial weights everywhere
    rs = np.random.RandomState(0)
    for v in km.weights:
        v.assign(rs.randn(*v.shape).astype(np.float32) * 0.3)
    p = _save(km, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = rs.rand(4, 10, 10, 3).astype(np.float32)
    expected = km.predict(x, verbose=0)
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_keras_1d_and_3d_converters(tmp_path):
    km1 = tf.keras.Sequential([
        tf.keras.layers.Input((16, 4)),
        tf.keras.layers.Conv1D(8, 3, padding="same", activation="relu"),
        tf.keras.layers.MaxPooling1D(2),
        tf.keras.layers.GlobalAveragePooling1D(),
        tf.keras.layers.Dense(3, activation="softmax")])
    p1 = _save(km1, tmp_path, "m1d.h5")
    net1 = KerasModelImport.import_keras_sequential_model_and_weights(p1)
    x1 = np.random.RandomState(0).rand(3, 16, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net1.output(x1)),
                               km1.predict(x1, verbose=0),
                               rtol=1e-4, atol=1e-5)

    km3 = tf.keras.Sequential([
        tf.keras.layers.Input((6, 6, 6, 2)),
        tf.keras.layers.Conv3D(4, 2, padding="valid", activation="relu"),
        tf.keras.layers.MaxPooling3D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(2, activation="softmax")])
    p3 = _save(km3, tmp_path, "m3d.h5")
    net3 = KerasModelImport.import_keras_sequential_model_and_weights(p3)
    x3 = np.random.RandomState(1).rand(2, 6, 6, 6, 2).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net3.output(x3)),
                               km3.predict(x3, verbose=0),
                               rtol=1e-4, atol=1e-5)


def test_keras_layernorm_flags_and_param_activations(tmp_path):
    """scale=False LayerNormalization imports (gamma stays 1); LeakyReLU
    alpha survives config JSON round-trip (code-review r2)."""
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6,)),
        tf.keras.layers.Dense(5),
        tf.keras.layers.LayerNormalization(scale=False),
        tf.keras.layers.LeakyReLU(),
        tf.keras.layers.Dense(2, activation="softmax")])
    p = _save(km, tmp_path, "ln_flags.h5")
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               km.predict(x, verbose=0),
                               rtol=1e-4, atol=1e-5)
    # imported config (incl. parameterized LeakyReLU) round-trips via JSON
    from deeplearning4j_tpu.nn import (MultiLayerConfiguration,
                                       MultiLayerNetwork)
    conf2 = MultiLayerConfiguration.from_json(net.conf.to_json())
    net2 = MultiLayerNetwork(conf2).init()
    net2.set_params(net.params())
    np.testing.assert_allclose(np.asarray(net2.output(x)),
                               km.predict(x, verbose=0),
                               rtol=1e-4, atol=1e-5)


def test_keras_bidirectional_lstm_sequence_import(tmp_path):
    """Bidirectional-LSTM sequence model (VERDICT r2 missing #1):
    return_sequences=True inner + TimeDistributed head vs TF."""
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6, 4)),
        tf.keras.layers.Bidirectional(
            tf.keras.layers.LSTM(5, return_sequences=True)),
        tf.keras.layers.TimeDistributed(tf.keras.layers.Dense(3)),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(2, activation="softmax")])
    p = _save(km, tmp_path, "bidir_seq.h5")
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(5).randn(4, 6, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               km.predict(x, verbose=0),
                               rtol=1e-3, atol=1e-4)


def test_keras_bidirectional_last_step_and_merge_modes(tmp_path):
    """return_sequences=False: fwd last step + bwd full-consumption step
    (NOT a plain LastTimeStep over the merged sequence)."""
    for merge in ("concat", "sum", "ave", "mul"):
        km = tf.keras.Sequential([
            tf.keras.layers.Input((5, 3)),
            tf.keras.layers.Bidirectional(
                tf.keras.layers.LSTM(4), merge_mode=merge),
            tf.keras.layers.Dense(2, activation="softmax")])
        p = _save(km, tmp_path, f"bidir_{merge}.h5")
        net = KerasModelImport.import_keras_sequential_model_and_weights(p)
        x = np.random.RandomState(6).randn(3, 5, 3).astype(np.float32)
        np.testing.assert_allclose(np.asarray(net.output(x)),
                                   km.predict(x, verbose=0),
                                   rtol=1e-3, atol=1e-4, err_msg=merge)


def test_keras_bidirectional_simplernn_import(tmp_path):
    km = tf.keras.Sequential([
        tf.keras.layers.Input((5, 3)),
        tf.keras.layers.Bidirectional(
            tf.keras.layers.SimpleRNN(4, return_sequences=True)),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(2, activation="softmax")])
    p = _save(km, tmp_path, "bidir_rnn.h5")
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(7).randn(3, 5, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               km.predict(x, verbose=0),
                               rtol=1e-3, atol=1e-4)


def test_keras_reshape_permute_repeatvector_import(tmp_path):
    """Shape-op layers (VERDICT r2 missing #1: Reshape/Permute/
    RepeatVector) through a mixed pipeline vs TF."""
    km = tf.keras.Sequential([
        tf.keras.layers.Input((12,)),
        tf.keras.layers.Dense(8, activation="relu"),
        tf.keras.layers.RepeatVector(6),          # [B,6,8]
        tf.keras.layers.Permute((2, 1)),          # [B,8,6]
        tf.keras.layers.Reshape((4, 12)),         # [B,4,12]
        tf.keras.layers.LSTM(5),
        tf.keras.layers.Dense(3, activation="softmax")])
    p = _save(km, tmp_path, "shapes.h5")
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(8).randn(4, 12).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               km.predict(x, verbose=0),
                               rtol=1e-3, atol=1e-4)


def _while_fn():
    @tf.function
    def f(x):
        i = tf.constant(0)
        _, y = tf.while_loop(
            lambda i, acc: i < 5,
            lambda i, acc: (i + 1, acc * 1.5 + 1.0),
            [i, x])
        return y
    return f


def test_tf_while_loop_v1_frames_import_matches_tf():
    """Frozen TF1-style loop frames (Enter/Merge/Switch/NextIteration/
    Exit/LoopCond — the format real DL4J-era frozen graphs carry, VERDICT
    r2 missing #4) deframe onto SameDiff.while_loop and match TF."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    f = _while_fn()
    frozen = convert_variables_to_constants_v2(
        f.get_concrete_function(tf.TensorSpec((3,), tf.float32)))
    gd = frozen.graph.as_graph_def()
    assert any(n.op == "Enter" for n in gd.node), \
        "expected v1-lowered control flow"
    sd = import_graph_def(gd)
    out_name = frozen.outputs[0].name.split(":")[0]
    x = np.asarray([1.0, -2.0, 0.5], np.float32)
    want = f(tf.constant(x)).numpy()
    got = np.asarray(sd.output({"x": x}, out_name)[out_name])
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_tf_while_loop_functional_import_matches_tf():
    """Functional While (lower_control_flow=False freezing) lowers onto
    SameDiff.while_loop via graph_def.library bodies."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    f = _while_fn()
    frozen = convert_variables_to_constants_v2(
        f.get_concrete_function(tf.TensorSpec((3,), tf.float32)),
        lower_control_flow=False)
    gd = frozen.graph.as_graph_def()
    assert any(n.op in ("While", "StatelessWhile") for n in gd.node)
    sd = import_graph_def(gd)
    out_name = frozen.outputs[0].name.split(":")[0]
    x = np.asarray([1.0, -2.0, 0.5], np.float32)
    want = f(tf.constant(x)).numpy()
    got = np.asarray(sd.output({"x": x}, out_name)[out_name])
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_tf_cond_import_matches_tf():
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    @tf.function
    def f(x):
        return tf.cond(tf.reduce_sum(x) > 0.0,
                       lambda: x * 2.0,
                       lambda: x - 1.0)

    frozen = convert_variables_to_constants_v2(
        f.get_concrete_function(tf.TensorSpec((4,), tf.float32)),
        lower_control_flow=False)
    gd = frozen.graph.as_graph_def()
    assert any(n.op in ("If", "StatelessIf") for n in gd.node)
    sd = import_graph_def(gd)
    out_name = frozen.outputs[0].name.split(":")[0]
    for x in (np.asarray([1.0, 2.0, 3.0, 4.0], np.float32),
              np.asarray([-1.0, -2.0, -3.0, -4.0], np.float32)):
        want = f(tf.constant(x)).numpy()
        got = np.asarray(sd.output({"x": x}, out_name)[out_name])
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_tf_cond_v1_switch_merge_import_matches_tf():
    """Default (lowered) freezing turns tf.cond into frameless
    Switch/Merge; the importer collapses them into a `where` select."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    @tf.function
    def f(x):
        return tf.cond(tf.reduce_sum(x) > 0.0,
                       lambda: x * 2.0,
                       lambda: x - 1.0)

    frozen = convert_variables_to_constants_v2(
        f.get_concrete_function(tf.TensorSpec((4,), tf.float32)))
    gd = frozen.graph.as_graph_def()
    assert any(n.op == "Switch" for n in gd.node) \
        and not any(n.op == "Enter" for n in gd.node), \
        "expected frameless v1 cond lowering"
    sd = import_graph_def(gd)
    out_name = frozen.outputs[0].name.split(":")[0]
    for x in (np.asarray([1.0, 2.0, 3.0, 4.0], np.float32),
              np.asarray([-1.0, -2.0, -3.0, -4.0], np.float32)):
        want = f(tf.constant(x)).numpy()
        got = np.asarray(sd.output({"x": x}, out_name)[out_name])
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_tf_nested_cond_v1_import_matches_tf():
    """Nested tf.cond (v1 lowering): the outer Merge must be gated by the
    OUTER Switch — the ancestor walk pairs inner Merge/Switch so nesting
    doesn't select the wrong predicate."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    @tf.function
    def f(x):
        def true_branch():
            return tf.cond(tf.reduce_max(x) > 2.0,
                           lambda: x * 10.0, lambda: x * 2.0)
        return tf.cond(tf.reduce_sum(x) > 0.0,
                       true_branch, lambda: x - 1.0)

    frozen = convert_variables_to_constants_v2(
        f.get_concrete_function(tf.TensorSpec((3,), tf.float32)))
    gd = frozen.graph.as_graph_def()
    if not any(n.op == "Switch" for n in gd.node):
        import pytest as _pytest
        _pytest.skip("this TF version did not lower the nested cond")
    sd = import_graph_def(gd)
    out_name = frozen.outputs[0].name.split(":")[0]
    # (outer, inner) truth table: TT, TF, F
    for x in ([1.0, 2.0, 3.0], [1.0, 1.0, 1.0], [-1.0, -5.0, 2.5]):
        xv = np.asarray(x, np.float32)
        want = f(tf.constant(xv)).numpy()
        got = np.asarray(sd.output({"x": xv}, out_name)[out_name])
        np.testing.assert_allclose(got, want, rtol=1e-6, err_msg=str(x))


def test_tf_cond_constant_branch_import_matches_tf():
    """A cond branch that returns a constant has no data path to its
    Switch (control-edge gating only); the importer falls back to the
    other input's walk with flipped branch sense."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    @tf.function
    def f(x):
        return tf.cond(tf.reduce_sum(x) > 0.0,
                       lambda: tf.constant([9.0, 9.0, 9.0]),
                       lambda: x - 1.0)

    frozen = convert_variables_to_constants_v2(
        f.get_concrete_function(tf.TensorSpec((3,), tf.float32)))
    gd = frozen.graph.as_graph_def()
    if not any(n.op == "Switch" for n in gd.node):
        import pytest as _pytest
        _pytest.skip("not lowered to v1 cond by this TF version")
    sd = import_graph_def(gd)
    out_name = frozen.outputs[0].name.split(":")[0]
    for x in ([1.0, 1.0, 1.0], [-1.0, -1.0, -1.0]):
        xv = np.asarray(x, np.float32)
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xv}, out_name)[out_name]),
            f(tf.constant(xv)).numpy(), rtol=1e-6)


def test_keras_conv2d_transpose_exact(tmp_path):
    """Regression: Conv2DTranspose must match Keras EXACTLY at the layer
    output (gradient-form kernel orientation).  The extended-converters
    test alone cannot catch a spatial kernel flip: its deconv (k=s=2)
    feeds an AveragePooling2D(2), and averaging each non-overlapping tile
    is invariant to flipping within the tile."""
    km = tf.keras.Sequential([
        tf.keras.layers.Input((5, 5, 3)),
        tf.keras.layers.Conv2DTranspose(4, 3, strides=2, padding="same"),
        tf.keras.layers.Conv2DTranspose(2, 2, strides=1, padding="valid")])
    rs = np.random.RandomState(3)
    for v in km.weights:
        v.assign(rs.randn(*v.shape).astype(np.float32) * 0.3)
    p = _save(km, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = rs.rand(2, 5, 5, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               km.predict(x, verbose=0),
                               rtol=1e-4, atol=1e-5)


def test_tf_import_full_depth_bert():
    """Full-DEPTH import conformance (VERDICT r4 #3/weak#5): the exact
    12-layer BERT-shaped GraphDef that bench.py times is value-asserted
    against TF here, then fine-tuned — the deepest import path in the
    repo is numerically checked, not just perf-timed.  Width is trimmed
    (H=128, vocab=2000) to stay CPU-affordable; depth and op diet are the
    bench's (reference: TFGraphTestAllSameDiff full-model conformance)."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import build_tf_bert_frozen

    from deeplearning4j_tpu.autodiff import TrainingConfig
    from deeplearning4j_tpu.train.updaters import Adam as SDAdam

    B, T, L, H, NH, V = 2, 32, 12, 128, 4, 2000
    gd, frozen, enc = build_tf_bert_frozen(batch=B, t=T, layers=L,
                                           hidden=H, heads=NH, vocab=V)
    n_layers = len([n for n in gd.node
                    if n.op == "Softmax"])
    assert n_layers == L, f"graph has {n_layers} attention softmaxes"
    sd = import_graph_def(gd)
    rs = np.random.RandomState(5)
    ids = rs.randint(0, V, (B, T)).astype(np.int32)
    want = frozen(tf.constant(ids))[0].numpy()
    got = np.asarray(sd.output({"ids": ids}, enc)[enc])
    # 12 layers of f32 accumulation: per-element tol 1e-4 absolute
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    # fine-tune through the full imported depth: loss must decrease
    w = sd.var("head_w", "XAVIER", H, V)
    logits = sd.op("matmul", sd.get_variable(enc), w, name="logits")
    lab = sd.placeholder("lab", (B, T))
    sd.loss.sparse_softmax_cross_entropy(lab, logits, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(
        updater=SDAdam(5e-3), data_set_feature_mapping=["ids"],
        data_set_label_mapping=["lab"]))
    lab_v = rs.randint(0, V, (B, T)).astype(np.int32)
    sd.fit(ids, lab_v)
    first = sd.score()
    for _ in range(5):
        sd.fit(ids, lab_v)
    assert sd.score() < first, (first, sd.score())


def test_keras_v3_zip_sequential_import_matches_keras():
    """Keras 3 `.keras` zip container (the Keras 3 DEFAULT save format):
    auto-path/positional-vars weight resolution must reproduce keras's
    own predictions — same contract as the legacy-H5 tests."""
    import tempfile

    tf.keras.utils.set_random_seed(5)
    L = tf.keras.layers
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6, 6, 2)),
        L.Conv2D(4, 3, padding="same", activation="relu", name="c1"),
        L.BatchNormalization(name="bn"),
        L.Flatten(name="fl"),
        L.Dense(8, activation="tanh", name="d1"),
        L.Dense(3, activation="softmax", name="out")])
    path = tempfile.mktemp(suffix=".keras")
    km.save(path)

    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = np.random.RandomState(0).rand(3, 6, 6, 2).astype(np.float32)
    got = np.asarray(net.output(x))
    want = km.predict(x, verbose=0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_keras_v3_zip_recurrent_import_matches_keras():
    """.keras container with the nested layouts: Bidirectional LSTM
    (forward_layer/backward_layer/cell/vars), TimeDistributed
    (layer/vars), plain LSTM (cell/vars), use_bias=False Dense."""
    import tempfile

    tf.keras.utils.set_random_seed(6)
    L = tf.keras.layers
    km = tf.keras.Sequential([
        tf.keras.layers.Input((5, 4)),
        L.Bidirectional(L.LSTM(3, return_sequences=True), name="bd"),
        L.TimeDistributed(L.Dense(4, activation="relu"), name="td"),
        L.LSTM(3, name="l2"),
        L.Dense(2, use_bias=False, name="out")])
    path = tempfile.mktemp(suffix=".keras")
    km.save(path)

    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = np.random.RandomState(1).rand(2, 5, 4).astype(np.float32)
    got = np.asarray(net.output(x))
    want = km.predict(x, verbose=0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_tf_saved_model_import(tmp_path):
    """TF2 SavedModel directory -> freeze serving signature -> SameDiff;
    predictions match the SavedModel's own."""
    from deeplearning4j_tpu.modelimport import import_saved_model

    tf.keras.utils.set_random_seed(11)
    km = tf.keras.Sequential([
        tf.keras.layers.Input((7,), name="feats"),
        tf.keras.layers.Dense(9, activation="relu"),
        tf.keras.layers.Dense(4, activation="softmax")])
    d = str(tmp_path / "sm")
    tf.saved_model.save(km, d)

    sd, inputs, outputs = import_saved_model(d)
    assert len(inputs) == 1 and len(outputs) == 1
    x = np.random.RandomState(3).rand(5, 7).astype(np.float32)
    want = km.predict(x, verbose=0)
    got = np.asarray(sd.output({inputs[0]: x}, outputs[0])[outputs[0]])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    # missing signature -> named diagnostic
    with pytest.raises(UnmappedTFOpException, match="no signature"):
        import_saved_model(d, signature="nope")


def test_tf_saved_model_multi_output_op_signature(tmp_path):
    """A signature output that is a NON-ZERO output of a multi-output op
    (tf.split) must keep its ':i' suffix — stripping it silently resolves
    to output 0 of the op."""
    from deeplearning4j_tpu.modelimport import import_saved_model

    class M(tf.Module):
        @tf.function(input_signature=[tf.TensorSpec([None, 6], tf.float32)])
        def serve(self, x):
            lo, hi = tf.split(x, 2, axis=1)
            return {"lo": lo * 2.0, "hi": hi + 1.0, "second_half": hi}

    m = M()
    d = str(tmp_path / "sm_multi")
    tf.saved_model.save(m, d, signatures={"serving_default": m.serve})

    sd, inputs, outputs = import_saved_model(d)
    x = np.random.RandomState(5).rand(3, 6).astype(np.float32)
    want = {k: np.asarray(v) for k, v in m.serve(tf.constant(x)).items()}
    got = sd.output({inputs[0]: x}, *outputs)
    # order-insensitive: every signature output value must be produced by
    # exactly one imported output name
    got_vals = [np.asarray(got[o]) for o in outputs]
    for key, val in want.items():
        assert any(v.shape == val.shape and np.allclose(v, val, atol=1e-6)
                   for v in got_vals), f"signature output {key} not matched"


def test_sequential_gru_import(tmp_path):
    """Keras GRU (reset_after=True default) -> our GRU layer; stacked
    seq->seq then seq->last, predictions must match keras.  (Upstream
    DL4J has no GRU layer — exceeds-reference coverage.)"""
    km = tf.keras.Sequential([
        tf.keras.layers.Input((7, 5)),
        tf.keras.layers.GRU(12, return_sequences=True),
        tf.keras.layers.GRU(6),                     # last step only
        tf.keras.layers.Dense(2, activation="softmax")])
    p = _save(km, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(3).randn(4, 7, 5).astype(np.float32)
    expected = km.predict(x, verbose=0)
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, rtol=1e-3, atol=1e-4)


def test_keras_bidirectional_gru_import(tmp_path):
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6, 4)),
        tf.keras.layers.Bidirectional(
            tf.keras.layers.GRU(5, return_sequences=True)),
        tf.keras.layers.GlobalAveragePooling1D(),
        tf.keras.layers.Dense(3, activation="softmax")])
    p = _save(km, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(8).randn(5, 6, 4).astype(np.float32)
    expected = km.predict(x, verbose=0)
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, rtol=1e-3, atol=1e-4)


def test_keras3_container_gru_import(tmp_path):
    """GRU through the Keras 3 `.keras` zip path (positional-vars weight
    resolution), stacked + Bidirectional."""
    km = tf.keras.Sequential([
        tf.keras.layers.Input((7, 5)),
        tf.keras.layers.GRU(6, return_sequences=True),
        tf.keras.layers.Bidirectional(tf.keras.layers.GRU(4)),
        tf.keras.layers.Dense(2, activation="softmax")])
    p = str(tmp_path / "m.keras")
    km.save(p)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(1).randn(3, 7, 5).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               km.predict(x, verbose=0), rtol=1e-3,
                               atol=1e-4)
