"""Fused/blockwise/ring attention tests — numerics vs the naive reference
(the OpValidation pattern: forward value + gradient agreement)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from deeplearning4j_tpu.parallel.mesh import shard_map

from deeplearning4j_tpu.ops.attention_kernels import (
    blockwise_attention, flash_attention_tpu, fused_attention, mha_reference)
from deeplearning4j_tpu.parallel import make_mesh
from deeplearning4j_tpu.parallel.ring_attention import ring_attention


def _qkv(B=2, H=2, T=256, D=32, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    q = rng.randn(B, H, T, D).astype(dtype) * 0.3
    k = rng.randn(B, H, T, D).astype(dtype) * 0.3
    v = rng.randn(B, H, T, D).astype(dtype) * 0.3
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def test_blockwise_matches_reference():
    q, k, v = _qkv()
    ref = mha_reference(q, k, v)
    out = blockwise_attention(q, k, v, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_blockwise_causal():
    q, k, v = _qkv(T=128)
    ref = mha_reference(q, k, v, causal=True)
    out = blockwise_attention(q, k, v, None, True, None, 32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_blockwise_with_kv_mask():
    q, k, v = _qkv(T=128)
    mask = np.ones((2, 128), np.float32)
    mask[:, 100:] = 0.0
    ref = mha_reference(q, k, v, mask=jnp.asarray(mask))
    out = blockwise_attention(q, k, v, jnp.asarray(mask), block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_blockwise_gradients_match_reference():
    q, k, v = _qkv(T=64, D=16)

    def loss_ref(q_, k_, v_):
        return jnp.sum(mha_reference(q_, k_, v_, causal=True) ** 2)

    def loss_blk(q_, k_, v_):
        return jnp.sum(blockwise_attention(q_, k_, v_, None, True, None,
                                           32) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gb = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_kernel_interpret_matches_reference():
    """Pallas kernel in interpreter mode (CPU) vs reference."""
    q, k, v = _qkv(B=1, H=2, T=256, D=128)
    ref = mha_reference(q, k, v)
    out = flash_attention_tpu(q, k, v, block_q=128, block_k=128,
                              interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_kernel_interpret_causal():
    q, k, v = _qkv(B=1, H=1, T=256, D=128)
    ref = mha_reference(q, k, v, causal=True)
    out = flash_attention_tpu(q, k, v, causal=True, block_q=128,
                              block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_fused_attention_dispatch_cpu():
    # on CPU this takes the blockwise path; just check it's differentiable
    q, k, v = _qkv(T=128, D=16)
    out, grads = jax.value_and_grad(
        lambda q_: jnp.sum(fused_attention(q_, k, v) ** 2))(q)
    assert np.isfinite(float(out))
    assert np.isfinite(np.asarray(grads)).all()


def test_ring_attention_matches_full():
    """Sequence sharded over 8 devices == unsharded reference."""
    mesh = make_mesh({"seq": 8})
    B, H, T, D = 2, 2, 128, 16
    q, k, v = _qkv(B=B, H=H, T=T, D=D)
    ref = mha_reference(q, k, v)

    f = shard_map(
        functools.partial(ring_attention, axis_name="seq"),
        mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3,
        out_specs=P(None, None, "seq", None))
    out = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_causal_matches_full():
    mesh = make_mesh({"seq": 8})
    q, k, v = _qkv(B=1, H=2, T=128, D=16, seed=3)
    ref = mha_reference(q, k, v, causal=True)
    f = shard_map(
        functools.partial(ring_attention, axis_name="seq", causal=True),
        mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3,
        out_specs=P(None, None, "seq", None))
    out = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_differentiable():
    mesh = make_mesh({"seq": 8})
    q, k, v = _qkv(B=1, H=1, T=64, D=8)

    def loss(q_, k_, v_):
        f = shard_map(
            functools.partial(ring_attention, axis_name="seq"),
            mesh=mesh,
            in_specs=(P(None, None, "seq", None),) * 3,
            out_specs=P(None, None, "seq", None))
        return jnp.sum(f(q_, k_, v_) ** 2)

    ref_grads = jax.grad(
        lambda q_, k_, v_: jnp.sum(mha_reference(q_, k_, v_) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_bwd_kernel_interpret_matches_reference():
    """Pallas backward kernels (dq/dkv) vs jax.grad of the naive reference."""
    from deeplearning4j_tpu.ops.attention_kernels import flash_attention_bwd_tpu
    for causal in (False, True):
        q, k, v = _qkv(B=1, H=2, T=256, D=64)
        g = jnp.asarray(np.random.RandomState(7).randn(*q.shape)
                        .astype(np.float32) * 0.3)
        out, lse = flash_attention_tpu(q, k, v, causal=causal, block_q=128,
                                       block_k=128, interpret=True,
                                       return_lse=True)
        dq, dk, dv = flash_attention_bwd_tpu(q, k, v, out, lse, g,
                                             causal=causal, block_q=128,
                                             block_k=128, interpret=True)

        def loss(q_, k_, v_):
            return jnp.sum(mha_reference(q_, k_, v_, causal=causal) * g)

        rdq, rdk, rdv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in ((dq, rdq), (dk, rdk), (dv, rdv)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


def test_flash_kernel_interpret_masked_matches_reference():
    """Padding mask applied in-kernel (additive bias per KV tile) vs the
    masked naive reference — the BERT-shaped masked-batch path."""
    q, k, v = _qkv(B=2, H=2, T=256, D=128)
    mask = np.ones((2, 256), np.float32)
    mask[0, 200:] = 0.0
    mask[1, 97:] = 0.0      # cuts inside a KV block
    mask = jnp.asarray(mask)
    ref = mha_reference(q, k, v, mask=mask)
    out = flash_attention_tpu(q, k, v, block_q=128, block_k=128,
                              interpret=True, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_bwd_kernel_interpret_masked_matches_reference():
    from deeplearning4j_tpu.ops.attention_kernels import flash_attention_bwd_tpu
    q, k, v = _qkv(B=2, H=1, T=256, D=64, seed=5)
    mask = np.ones((2, 256), np.float32)
    mask[0, 130:] = 0.0
    mask[1, 255:] = 0.0
    mask = jnp.asarray(mask)
    g = jnp.asarray(np.random.RandomState(9).randn(*q.shape)
                    .astype(np.float32) * 0.3)
    out, lse = flash_attention_tpu(q, k, v, block_q=128, block_k=128,
                                   interpret=True, return_lse=True,
                                   mask=mask)
    dq, dk, dv = flash_attention_bwd_tpu(q, k, v, out, lse, g, block_q=128,
                                         block_k=128, interpret=True,
                                         mask=mask)

    def loss(q_, k_, v_):
        return jnp.sum(mha_reference(q_, k_, v_, mask=mask) * g)

    rdq, rdk, rdv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in ((dq, rdq), (dk, rdk), (dv, rdv)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_fused_attention_masked_long_seq_dispatches_pallas(monkeypatch):
    """With a [B,S] mask and a long tiling sequence, the dispatcher must
    take the Pallas path on TPU (VERDICT r2 weak #5: it never could)."""
    import deeplearning4j_tpu.ops.attention_kernels as ak
    calls = {}

    def fake_flash(q, k, v, mask, causal, scale, bq, bk):
        calls["mask"] = mask
        return mha_reference(q, k, v, mask, causal, scale)

    monkeypatch.setattr(ak, "_flash_attention_diff", fake_flash)
    monkeypatch.setattr(ak.jax, "default_backend", lambda: "tpu")
    B, H, T, D = 1, 1, 2048, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32) * 0.1)
    mask = jnp.asarray(np.ones((B, T), np.float32))
    ak.fused_attention(q, q, q, mask=mask)
    assert calls["mask"] is mask


def test_flash_lse_matches_reference():
    q, k, v = _qkv(B=1, H=1, T=256, D=64)
    _, lse = flash_attention_tpu(q, k, v, block_q=128, block_k=128,
                                 interpret=True, return_lse=True)
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    ref_lse = jax.scipy.special.logsumexp(scores, axis=-1).reshape(1, 256)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# Pallas fused LayerNorm (ops/norm_kernels.py) — interpret-mode correctness
# vs the jnp reference, values and gradients
# ---------------------------------------------------------------------------

def test_pallas_layer_norm_matches_reference():
    from deeplearning4j_tpu.ops.norm_kernels import (fused_layer_norm,
                                                     layer_norm_reference)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((32, 256)).astype(np.float32))
    g = jnp.asarray(rng.standard_normal(256).astype(np.float32) * 0.5 + 1)
    b = jnp.asarray(rng.standard_normal(256).astype(np.float32) * 0.1)
    want = layer_norm_reference(x, g, b)
    got = fused_layer_norm(x, g, b, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pallas_layer_norm_gradients_match():
    from deeplearning4j_tpu.ops.norm_kernels import (fused_layer_norm,
                                                     layer_norm_reference)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((16, 128)).astype(np.float32))
    g = jnp.asarray(rng.standard_normal(128).astype(np.float32) + 1.0)
    b = jnp.asarray(rng.standard_normal(128).astype(np.float32) * 0.2)
    t = jnp.asarray(rng.standard_normal((16, 128)).astype(np.float32))

    def loss_k(x_, g_, b_):
        return jnp.mean((fused_layer_norm(x_, g_, b_, interpret=True) - t)
                        ** 2)

    def loss_r(x_, g_, b_):
        return jnp.mean((layer_norm_reference(x_, g_, b_) - t) ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(x, g, b)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, g, b)
    for a, bb in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-5)


def test_layer_norm_op_routes_through_fused_dispatch(monkeypatch):
    """The registry op / BERT / LayerNormalizationLayer all call
    fused_layer_norm; on (fake) TPU with tiling BERT shapes the Pallas
    path must engage (VERDICT r2 weak #6: the kernel had no caller)."""
    import deeplearning4j_tpu.ops.norm_kernels as nk
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    calls = []

    real = nk._fused_ln

    def spy(x, gain, bias, eps, interpret):
        calls.append(x.shape)
        return real(x, gain, bias, eps, True)   # interpret: still CPU-safe

    monkeypatch.setattr(nk, "_fused_ln", spy)
    monkeypatch.setattr(nk.jax, "default_backend", lambda: "tpu")
    x = jnp.asarray(np.random.RandomState(0)
                    .randn(8, 128, 256).astype(np.float32))  # 1024 rows
    g = jnp.ones(256, jnp.float32)
    out = OP_TABLE["layer_norm"](x, g)
    assert calls, "Pallas LN did not engage for a BERT-shaped input"
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(nk.layer_norm_reference(x, g)), rtol=1e-5, atol=1e-5)


def test_fused_layer_norm_dispatch_fallback():
    """Ragged shapes must fall back to the jnp reference silently."""
    from deeplearning4j_tpu.ops.norm_kernels import (fused_layer_norm,
                                                     layer_norm_reference)
    x = jnp.asarray(np.random.default_rng(2)
                    .standard_normal((5, 37)).astype(np.float32))
    g = jnp.ones(37, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(fused_layer_norm(x, g)),
        np.asarray(layer_norm_reference(x, g)), rtol=1e-6)


def test_ring_attention_masked_matches_full():
    """Padded long-context batch: the [B, T_local] mask chunk rotates
    around the ring with its KV chunk; result equals full masked
    attention."""
    mesh = make_mesh({"seq": 8})
    B, H, T, D = 2, 2, 128, 16
    q, k, v = _qkv(B=B, H=H, T=T, D=D, seed=11)
    mask = np.ones((B, T), np.float32)
    mask[0, 100:] = 0.0
    mask[1, 50:] = 0.0
    mask = jnp.asarray(mask)
    ref = mha_reference(q, k, v, mask=mask)
    f = shard_map(
        lambda q_, k_, v_, m_: ring_attention(q_, k_, v_, axis_name="seq",
                                              mask=m_),
        mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3 + (P(None, "seq"),),
        out_specs=P(None, None, "seq", None))
    out = jax.jit(f)(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_masked_differentiable():
    mesh = make_mesh({"seq": 8})
    q, k, v = _qkv(B=1, H=1, T=64, D=8, seed=12)
    mask = np.ones((1, 64), np.float32)
    mask[0, 40:] = 0.0
    mask = jnp.asarray(mask)

    def loss(q_, k_, v_):
        f = shard_map(
            lambda qq, kk, vv, mm: ring_attention(qq, kk, vv,
                                                  axis_name="seq", mask=mm),
            mesh=mesh,
            in_specs=(P(None, None, "seq", None),) * 3 + (P(None, "seq"),),
            out_specs=P(None, None, "seq", None))
        return jnp.sum(f(q_, k_, v_, mask) ** 2)

    ref_grads = jax.grad(
        lambda q_, k_, v_: jnp.sum(mha_reference(q_, k_, v_,
                                                 mask=mask) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_masked_causal_matches_full():
    """causal + padding mask together — the padded decoder long-context
    configuration."""
    mesh = make_mesh({"seq": 8})
    q, k, v = _qkv(B=2, H=1, T=128, D=16, seed=13)
    mask = np.ones((2, 128), np.float32)
    mask[0, 90:] = 0.0
    mask[1, 33:] = 0.0
    mask = jnp.asarray(mask)
    ref = mha_reference(q, k, v, mask=mask, causal=True)
    f = shard_map(
        lambda q_, k_, v_, m_: ring_attention(q_, k_, v_, axis_name="seq",
                                              causal=True, mask=m_),
        mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3 + (P(None, "seq"),),
        out_specs=P(None, None, "seq", None))
    out = jax.jit(f)(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_flash_inner_matches_full():
    """Ring attention with the Pallas flash kernel as the inner
    chunk-vs-chunk attention (interpret mode on the CPU mesh) ==
    unsharded full attention, and the logsumexp chunk merge is
    differentiable."""
    from deeplearning4j_tpu.parallel.ring_attention import (
        ring_attention_flash)
    mesh = make_mesh({"seq": 8})
    B, H, T, D = 2, 2, 64, 16
    q, k, v = _qkv(B=B, H=H, T=T, D=D, seed=9)
    ref = mha_reference(q, k, v)

    f = shard_map(
        functools.partial(ring_attention_flash, axis_name="seq",
                          block_q=8, block_k=8, interpret=True),
        mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3,
        out_specs=P(None, None, "seq", None),
        check_vma=False)   # pallas_call outputs carry no vma type
    out = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    g = jax.grad(lambda q_: jnp.sum(f(q_, k, v) ** 2))(q)
    g_ref = jax.grad(
        lambda q_: jnp.sum(mha_reference(q_, k, v) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=5e-4, atol=5e-5)


def test_ring_attention_flash_causal_matches_full():
    """Causal flash-inner ring: the diagonal chunk runs the causal
    kernel once, above-diagonal chunks are suppressed via lse=-inf —
    must equal unsharded causal attention, grads included."""
    from deeplearning4j_tpu.parallel.ring_attention import (
        ring_attention_flash)
    mesh = make_mesh({"seq": 8})
    q, k, v = _qkv(B=1, H=2, T=64, D=16, seed=13)
    ref = mha_reference(q, k, v, causal=True)

    f = shard_map(
        functools.partial(ring_attention_flash, axis_name="seq",
                          causal=True, block_q=8, block_k=8,
                          interpret=True),
        mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3,
        out_specs=P(None, None, "seq", None),
        check_vma=False)
    out = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    g = jax.grad(lambda v_: jnp.sum(f(q, k, v_) ** 2))(v)
    g_ref = jax.grad(
        lambda v_: jnp.sum(mha_reference(q, k, v_, causal=True) ** 2))(v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=5e-4, atol=5e-5)
