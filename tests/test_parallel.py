"""Distributed tests on the virtual 8-device CPU mesh (conftest.py) — the
moral equivalent of the reference's Aeron-on-loopback / Spark local[*]
multi-node-without-a-cluster strategy (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel import (ParallelInference, ParallelWrapper,
                                         ShardingRules, make_mesh,
                                         shard_model_params)
from deeplearning4j_tpu.train.updaters import Adam, Sgd


def _net(seed=0, updater=None):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater or Sgd(1e-1))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent", activation="softmax")])
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    labels = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)
    y = np.eye(3, dtype=np.float32)[labels]
    return x, y


def test_mesh_construction():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8
    mesh2 = make_mesh({"data": 4, "model": 2})
    assert mesh2.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError, match="require"):
        make_mesh({"data": 3})


def test_dp_matches_single_device():
    """Sharded-batch SPMD step == single-device step on the same batch (the
    gradient all-reduce must be exact, not approximate)."""
    x, y = _data(64)
    a = _net(seed=7)
    b = _net(seed=7)
    for _ in range(5):
        a.fit(x, y)
    pw = ParallelWrapper.builder(b).build()
    for _ in range(5):
        pw.fit(x, y)
    np.testing.assert_allclose(a.params(), b.params(), rtol=1e-5, atol=1e-6)


def test_dp_trains_from_iterator():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    x, y = _data(128)
    it = ListDataSetIterator(
        [DataSet(x[i:i + 32], y[i:i + 32]) for i in range(0, 128, 32)])
    net = _net(updater=Adam(1e-2))
    pw = ParallelWrapper.builder(net).training_mode("AVERAGING").build()
    s0 = net.score_for(x, y)
    pw.fit(it, epochs=10)
    assert net.score_for(x, y) < s0


def test_dp_batch_divisibility_error():
    net = _net()
    pw = ParallelWrapper.builder(net).build()
    x, y = _data(30)  # 30 % 8 != 0
    with pytest.raises(ValueError, match="divisible"):
        pw.fit(x, y)


def test_tensor_parallel_sharding_rules():
    mesh = make_mesh({"data": 4, "model": 2})
    net = _net()
    params = shard_model_params(net.params_, mesh, ShardingRules())
    # 2-D kernels split on out-dim over model axis; biases replicated
    w = params["layer_0"]["W"]            # (8, 16): 16 % 2 == 0 -> sharded
    assert w.sharding.spec == P(None, "model")
    b = params["layer_0"]["b"]
    assert b.sharding.spec == P()


def test_tp_training_matches_replicated():
    """Model-sharded params + data sharding must train identically to plain
    DP — XLA inserts the TP collectives, the math is unchanged."""
    x, y = _data(64)
    a = _net(seed=3)
    for _ in range(3):
        a.fit(x, y)
    b = _net(seed=3)
    mesh = make_mesh({"data": 4, "model": 2})
    pw = ParallelWrapper(b, mesh=mesh, sharding_rules=ShardingRules())
    for _ in range(3):
        pw.fit(x, y)
    np.testing.assert_allclose(a.params(), b.params(), rtol=1e-5, atol=1e-6)


def test_parallel_inference_matches_and_pads():
    net = _net(seed=5)
    x, _ = _data(20)   # 20 % 8 != 0 -> padding path
    expected = np.asarray(net.output(x))
    pi = ParallelInference(net)
    got = pi.output(x)
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)
    # batched request list round-trips shapes
    reqs = [x[:3], x[3:10], x[10:20]]
    outs = pi.output(reqs)
    assert [o.shape[0] for o in outs] == [3, 7, 10]
    np.testing.assert_allclose(np.concatenate(outs), expected, rtol=1e-5,
                               atol=1e-6)


def test_params_stay_consistent_across_devices():
    """After DP steps, every device shard of a replicated param is
    identical — the reference's averaging invariant."""
    net = _net()
    pw = ParallelWrapper.builder(net).build()
    x, y = _data(64)
    pw.fit(x, y)
    w = net.params_["layer_0"]["W"]
    shards = [np.asarray(s.data) for s in w.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
