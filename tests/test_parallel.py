"""Distributed tests on the virtual 8-device CPU mesh (conftest.py) — the
moral equivalent of the reference's Aeron-on-loopback / Spark local[*]
multi-node-without-a-cluster strategy (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel import (ParallelInference, ParallelWrapper,
                                         ShardingRules, make_mesh,
                                         shard_model_params)
from deeplearning4j_tpu.train.updaters import Adam, Sgd


def _net(seed=0, updater=None):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater or Sgd(1e-1))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent", activation="softmax")])
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    labels = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)
    y = np.eye(3, dtype=np.float32)[labels]
    return x, y


def test_mesh_construction():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8
    mesh2 = make_mesh({"data": 4, "model": 2})
    assert mesh2.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError, match="require"):
        make_mesh({"data": 3})


def test_dp_matches_single_device():
    """Sharded-batch SPMD step == single-device step on the same batch (the
    gradient all-reduce must be exact, not approximate)."""
    x, y = _data(64)
    a = _net(seed=7)
    b = _net(seed=7)
    for _ in range(5):
        a.fit(x, y)
    pw = ParallelWrapper.builder(b).build()
    for _ in range(5):
        pw.fit(x, y)
    np.testing.assert_allclose(a.params(), b.params(), rtol=1e-5, atol=1e-6)


def test_dp_trains_from_iterator():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    x, y = _data(128)
    it = ListDataSetIterator(
        [DataSet(x[i:i + 32], y[i:i + 32]) for i in range(0, 128, 32)])
    net = _net(updater=Adam(1e-2))
    pw = ParallelWrapper.builder(net).training_mode("AVERAGING").build()
    s0 = net.score_for(x, y)
    pw.fit(it, epochs=10)
    assert net.score_for(x, y) < s0


def test_dp_batch_divisibility_error():
    net = _net()
    pw = ParallelWrapper.builder(net).build()
    x, y = _data(30)  # 30 % 8 != 0
    with pytest.raises(ValueError, match="divisible"):
        pw.fit(x, y)


def test_tensor_parallel_sharding_rules():
    mesh = make_mesh({"data": 4, "model": 2})
    net = _net()
    params = shard_model_params(net.params_, mesh, ShardingRules())
    # 2-D kernels split on out-dim over model axis; biases replicated
    w = params["layer_0"]["W"]            # (8, 16): 16 % 2 == 0 -> sharded
    assert w.sharding.spec == P(None, "model")
    b = params["layer_0"]["b"]
    assert b.sharding.spec == P()


def test_tp_training_matches_replicated():
    """Model-sharded params + data sharding must train identically to plain
    DP — XLA inserts the TP collectives, the math is unchanged."""
    x, y = _data(64)
    a = _net(seed=3)
    for _ in range(3):
        a.fit(x, y)
    b = _net(seed=3)
    mesh = make_mesh({"data": 4, "model": 2})
    pw = ParallelWrapper(b, mesh=mesh, sharding_rules=ShardingRules())
    for _ in range(3):
        pw.fit(x, y)
    np.testing.assert_allclose(a.params(), b.params(), rtol=1e-5, atol=1e-6)


def test_parallel_inference_matches_and_pads():
    net = _net(seed=5)
    x, _ = _data(20)   # 20 % 8 != 0 -> padding path
    expected = np.asarray(net.output(x))
    pi = ParallelInference(net)
    got = pi.output(x)
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)
    # batched request list round-trips shapes
    reqs = [x[:3], x[3:10], x[10:20]]
    outs = pi.output(reqs)
    assert [o.shape[0] for o in outs] == [3, 7, 10]
    np.testing.assert_allclose(np.concatenate(outs), expected, rtol=1e-5,
                               atol=1e-6)


def test_params_stay_consistent_across_devices():
    """After DP steps, every device shard of a replicated param is
    identical — the reference's averaging invariant."""
    net = _net()
    pw = ParallelWrapper.builder(net).build()
    x, y = _data(64)
    pw.fit(x, y)
    w = net.params_["layer_0"]["W"]
    shards = [np.asarray(s.data) for s in w.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_dp_multidataset_cg_matches_single_device():
    """VERDICT weak #5: ParallelWrapper must shard MultiDataSet (multi-input
    CG) batches; SPMD result must match single-device training exactly."""
    from deeplearning4j_tpu.data.dataset import MultiDataSet
    from deeplearning4j_tpu.nn import (ComputationGraph, DenseLayer,
                                       GraphBuilder, InputType, MergeVertex,
                                       OutputLayer)
    from deeplearning4j_tpu.train.updaters import Sgd as SgdU

    def build():
        conf = (GraphBuilder()
                .seed(5).updater(SgdU(0.1))
                .add_inputs("a", "b")
                .set_input_types(InputType.feed_forward(4),
                                 InputType.feed_forward(6))
                .add_layer("da", DenseLayer(n_out=5, activation="tanh"), "a")
                .add_layer("db", DenseLayer(n_out=7, activation="tanh"), "b")
                .add_vertex("m", MergeVertex(), "da", "db")
                .add_layer("out", OutputLayer(n_out=2, loss="mcxent",
                                              activation="softmax"), "m")
                .set_outputs("out").build())
        return ComputationGraph(conf).init()

    rng = np.random.RandomState(3)
    a = rng.randn(16, 4).astype(np.float32)
    b = rng.randn(16, 6).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
    mds = MultiDataSet([a, b], [y])

    single = build()
    for _ in range(4):
        single.fit([a, b], [y])

    spmd = build()
    pw = ParallelWrapper.builder(spmd).build()
    for _ in range(4):
        pw.fit(mds)

    import jax as _jax
    _jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        single.params_, spmd.params_)


def test_tp_opt_state_follows_param_sharding():
    """VERDICT weak #4: TP-sharded params must carry their sharding into
    the optimizer moments (no fully-replicated Adam state)."""
    from deeplearning4j_tpu.train.updaters import Adam as AdamU
    net = _net(updater=AdamU(1e-3))
    rules = (ShardingRules().add(r".*layer_0.*W", P(None, "model"))
             .add(r".*layer_0.*b", P("model")))
    mesh = make_mesh({"data": 4, "model": 2})
    pw = ParallelWrapper(net, mesh, sharding_rules=rules)
    x, y = _data(16)
    pw.fit(x, y)
    m_state = net.opt_state_["layer_0"]["m"]["W"]
    p = net.params_["layer_0"]["W"]
    assert m_state.sharding.spec == p.sharding.spec, (
        m_state.sharding, p.sharding)
    # and a sharded-moment step still trains
    s0 = net.score()
    for _ in range(10):
        pw.fit(x, y)
    assert net.score() < s0


def test_dynamic_batching_inference_concurrent_clients():
    """Concurrent submits are aggregated into batched dispatches and each
    client gets exactly its own rows back (reference ParallelInference
    ObservablesProvider semantics)."""
    from concurrent.futures import ThreadPoolExecutor
    from deeplearning4j_tpu.parallel import (DynamicBatchingInference,
                                             ParallelInference, make_mesh)
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    conf = (NeuralNetConfiguration.builder().seed(7)
            .list([DenseLayer(n_out=8, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(5)).build())
    net = MultiLayerNetwork(conf).init()
    pi = ParallelInference(net, mesh=make_mesh())
    dyn = DynamicBatchingInference(pi, max_batch=16, timeout_ms=400.0)
    rng_ = np.random.RandomState(0)
    reqs = [rng_.rand(n, 5).astype(np.float32) for n in (1, 3, 2, 4, 1, 5)]
    want = [np.asarray(pi.output(r)) for r in reqs]
    # batched-dispatch observability: count underlying _run calls
    calls = []
    orig = pi._run

    def spy(x):
        calls.append(x.shape[0])
        return orig(x)

    pi._run = spy
    with ThreadPoolExecutor(max_workers=6) as ex:
        futs = [ex.submit(dyn.output, r) for r in reqs]
        got = [f.result(timeout=30) for f in futs]
    dyn.shutdown()
    for g, w, r in zip(got, want, reqs):
        assert g.shape == (r.shape[0], 3)
        np.testing.assert_allclose(g, w, rtol=1e-5, atol=1e-6)
    # fewer dispatches than requests -> aggregation actually happened
    assert len(calls) < len(reqs), calls


def test_dp_fit_steps_matches_single_device():
    """SPMD fused dispatch: pw.fit_steps([k, batch, ...]) == k single-
    device fit calls (scan + per-step all-reduce inside one dispatch)."""
    x, y = _data(64)
    k = 4
    xs = np.broadcast_to(np.asarray(x), (k,) + np.asarray(x).shape).copy()
    ys = np.broadcast_to(np.asarray(y), (k,) + np.asarray(y).shape).copy()
    a = _net(seed=7)
    for _ in range(k):
        a.fit(x, y)
    b = _net(seed=7)
    pw = ParallelWrapper.builder(b).build()
    losses = pw.fit_steps(xs, ys)
    assert losses.shape == (k,)
    np.testing.assert_allclose(a.params(), b.params(), rtol=1e-5, atol=1e-6)
    assert b.iteration == k
