"""Pallas conv wgrad prototype — interpret-mode correctness vs the XLA
autodiff reference (on-chip A/B lives in tunnel_playbook.py stage 6)."""
import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.ops.conv_kernels import (conv3x3_wgrad_tpu,
                                                 conv3x3_wgrad_xla)

rs = np.random.RandomState(0)


@pytest.mark.parametrize("B,H,W,Ci,Co", [
    (2, 8, 8, 8, 16),       # even rows, bh=8
    (1, 7, 7, 16, 8),       # odd rows, bh=7 (the ResNet 7x7 tail shape)
    (2, 14, 14, 8, 8),      # bh=14
])
def test_wgrad_matches_xla(B, H, W, Ci, Co):
    x = jnp.asarray(rs.randn(B, H, W, Ci).astype(np.float32) * 0.5)
    dy = jnp.asarray(rs.randn(B, H, W, Co).astype(np.float32) * 0.5)
    got = np.asarray(conv3x3_wgrad_tpu(x, dy, interpret=True))
    want = np.asarray(conv3x3_wgrad_xla(x, dy))
    assert got.shape == (3, 3, Ci, Co)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_wgrad_bf16_inputs_accumulate_f32():
    x = jnp.asarray(rs.randn(2, 8, 8, 8).astype(np.float32))
    dy = jnp.asarray(rs.randn(2, 8, 8, 8).astype(np.float32))
    got = np.asarray(conv3x3_wgrad_tpu(x.astype(jnp.bfloat16),
                                       dy.astype(jnp.bfloat16),
                                       interpret=True))
    want = np.asarray(conv3x3_wgrad_xla(x, dy))
    assert got.dtype == np.float32
    # bf16 INPUT rounding (not accumulation — that is f32) bounds the
    # agreement: ~0.4% relative on dW values of magnitude ~10
    np.testing.assert_allclose(got, want, rtol=3e-2, atol=0.12)


def test_wgrad_rejects_mismatched_shapes():
    x = jnp.zeros((1, 8, 8, 4))
    dy = jnp.zeros((1, 4, 8, 4))
    with pytest.raises(ValueError, match="mismatches"):
        conv3x3_wgrad_tpu(x, dy, interpret=True)


# ---- dgrad (conv-backward-data) ----
from deeplearning4j_tpu.ops.conv_kernels import (conv3x3_dgrad_tpu,  # noqa: E402
                                                 conv3x3_dgrad_xla)


@pytest.mark.parametrize("B,H,W,Ci,Co", [
    (2, 8, 8, 8, 16),       # even rows, bh=8
    (1, 7, 7, 16, 8),       # odd rows, bh=7 (the ResNet 7x7 tail shape)
    (2, 14, 14, 8, 8),      # bh=14
])
def test_dgrad_matches_xla(B, H, W, Ci, Co):
    dy = jnp.asarray(rs.randn(B, H, W, Co).astype(np.float32) * 0.5)
    w = jnp.asarray(rs.randn(3, 3, Ci, Co).astype(np.float32) * 0.5)
    got = np.asarray(conv3x3_dgrad_tpu(dy, w, interpret=True))
    want = np.asarray(conv3x3_dgrad_xla(dy, w))
    assert got.shape == (B, H, W, Ci)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_dgrad_bf16_inputs_accumulate_f32():
    dy = jnp.asarray(rs.randn(2, 8, 8, 8).astype(np.float32))
    w = jnp.asarray(rs.randn(3, 3, 8, 8).astype(np.float32) * 0.3)
    got = np.asarray(conv3x3_dgrad_tpu(dy.astype(jnp.bfloat16),
                                       w.astype(jnp.bfloat16),
                                       interpret=True))
    want = np.asarray(conv3x3_dgrad_xla(dy, w))
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, want, rtol=3e-2, atol=0.12)


def test_dgrad_rejects_bad_filter():
    dy = jnp.zeros((1, 8, 8, 4))
    w = jnp.zeros((5, 5, 4, 4))
    with pytest.raises(ValueError, match="not \\[3, 3"):
        conv3x3_dgrad_tpu(dy, w, interpret=True)
