"""Pallas conv wgrad prototype — interpret-mode correctness vs the XLA
autodiff reference (on-chip A/B lives in tunnel_playbook.py stage 6)."""
import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.ops.conv_kernels import (conv3x3_wgrad_tpu,
                                                 conv3x3_wgrad_xla)

rs = np.random.RandomState(0)


@pytest.mark.parametrize("B,H,W,Ci,Co", [
    (2, 8, 8, 8, 16),       # even rows, bh=8
    (1, 7, 7, 16, 8),       # odd rows, bh=7 (the ResNet 7x7 tail shape)
    (2, 14, 14, 8, 8),      # bh=14
])
def test_wgrad_matches_xla(B, H, W, Ci, Co):
    x = jnp.asarray(rs.randn(B, H, W, Ci).astype(np.float32) * 0.5)
    dy = jnp.asarray(rs.randn(B, H, W, Co).astype(np.float32) * 0.5)
    got = np.asarray(conv3x3_wgrad_tpu(x, dy, interpret=True))
    want = np.asarray(conv3x3_wgrad_xla(x, dy))
    assert got.shape == (3, 3, Ci, Co)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_wgrad_bf16_inputs_accumulate_f32():
    x = jnp.asarray(rs.randn(2, 8, 8, 8).astype(np.float32))
    dy = jnp.asarray(rs.randn(2, 8, 8, 8).astype(np.float32))
    got = np.asarray(conv3x3_wgrad_tpu(x.astype(jnp.bfloat16),
                                       dy.astype(jnp.bfloat16),
                                       interpret=True))
    want = np.asarray(conv3x3_wgrad_xla(x, dy))
    assert got.dtype == np.float32
    # bf16 INPUT rounding (not accumulation — that is f32) bounds the
    # agreement: ~0.4% relative on dW values of magnitude ~10
    np.testing.assert_allclose(got, want, rtol=3e-2, atol=0.12)


def test_wgrad_rejects_mismatched_shapes():
    x = jnp.zeros((1, 8, 8, 4))
    dy = jnp.zeros((1, 4, 8, 4))
    with pytest.raises(ValueError, match="mismatches"):
        conv3x3_wgrad_tpu(x, dy, interpret=True)


# ---- dgrad (conv-backward-data) ----
from deeplearning4j_tpu.ops.conv_kernels import (conv3x3_dgrad_tpu,  # noqa: E402
                                                 conv3x3_dgrad_xla)


@pytest.mark.parametrize("B,H,W,Ci,Co", [
    (2, 8, 8, 8, 16),       # even rows, bh=8
    (1, 7, 7, 16, 8),       # odd rows, bh=7 (the ResNet 7x7 tail shape)
    (2, 14, 14, 8, 8),      # bh=14
])
def test_dgrad_matches_xla(B, H, W, Ci, Co):
    dy = jnp.asarray(rs.randn(B, H, W, Co).astype(np.float32) * 0.5)
    w = jnp.asarray(rs.randn(3, 3, Ci, Co).astype(np.float32) * 0.5)
    got = np.asarray(conv3x3_dgrad_tpu(dy, w, interpret=True))
    want = np.asarray(conv3x3_dgrad_xla(dy, w))
    assert got.shape == (B, H, W, Ci)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_dgrad_bf16_inputs_accumulate_f32():
    dy = jnp.asarray(rs.randn(2, 8, 8, 8).astype(np.float32))
    w = jnp.asarray(rs.randn(3, 3, 8, 8).astype(np.float32) * 0.3)
    got = np.asarray(conv3x3_dgrad_tpu(dy.astype(jnp.bfloat16),
                                       w.astype(jnp.bfloat16),
                                       interpret=True))
    want = np.asarray(conv3x3_dgrad_xla(dy, w))
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, want, rtol=3e-2, atol=0.12)


def test_dgrad_rejects_bad_filter():
    dy = jnp.zeros((1, 8, 8, 4))
    w = jnp.zeros((5, 5, 4, 4))
    with pytest.raises(ValueError, match="not \\[3, 3"):
        conv3x3_dgrad_tpu(dy, w, interpret=True)


# ---- measured-dispatch adoption hook ----
from deeplearning4j_tpu.ops.conv_kernels import (CONV_BWD_PALLAS,  # noqa: E402
                                                 conv3x3_same)


def test_conv_bwd_pallas_hook_grads_match_xla():
    """With the adoption flags on (interpret mode), the conv2d op's
    backward runs the Pallas wgrad+dgrad kernels and must produce the
    same gradients as the XLA path — the train-step-level contract the
    on-chip A/B (playbook stage 8) assumes."""
    import jax
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE

    x = jnp.asarray(rs.randn(2, 8, 8, 4).astype(np.float32) * 0.5)
    w = jnp.asarray(rs.randn(3, 3, 4, 8).astype(np.float32) * 0.3)
    tgt = jnp.asarray(rs.randn(2, 8, 8, 8).astype(np.float32))

    def loss(x_, w_):
        y = OP_TABLE["conv2d"](x_, w_)
        return jnp.sum((y - tgt) ** 2)

    gx_ref, gw_ref = jax.grad(loss, (0, 1))(x, w)

    old = dict(CONV_BWD_PALLAS)
    try:
        CONV_BWD_PALLAS.update(wgrad=True, dgrad=True, interpret=True)
        out_hook = OP_TABLE["conv2d"](x, w)
        # forward identical (same XLA conv)
        np.testing.assert_allclose(
            np.asarray(out_hook),
            np.asarray(conv3x3_same(x, w)), rtol=1e-6)
        gx, gw = jax.grad(loss, (0, 1))(x, w)
    finally:
        CONV_BWD_PALLAS.clear()
        CONV_BWD_PALLAS.update(old)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                               rtol=1e-4, atol=1e-4)
    # flags off again: hook must not engage (plain path, bias works)
    y = OP_TABLE["conv2d"](x, w, jnp.zeros(8, jnp.float32))
    assert y.shape == (2, 8, 8, 8)


def test_conv_layer_hook_training_matches_xla():
    """Layer-level contract: a small conv net trains identically with the
    Pallas backward hook on (interpret) and off."""
    import jax
    from deeplearning4j_tpu.nn import (ConvolutionLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration,
                                       OutputLayer)
    from deeplearning4j_tpu.train import Sgd

    def build():
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1))
                .list([ConvolutionLayer(n_out=4, kernel_size=3,
                                        convolution_mode="Same",
                                        has_bias=False,
                                        activation="relu"),
                       OutputLayer(n_out=3, loss="mcxent",
                                   activation="softmax")])
                .set_input_type(InputType.convolutional(6, 6, 2)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.RandomState(0)
    x = rng.rand(4, 6, 6, 2).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 4)]

    net_a = build()
    net_a.fit(x, y)
    ref = np.asarray(net_a.params())

    old = dict(CONV_BWD_PALLAS)
    try:
        CONV_BWD_PALLAS.update(wgrad=True, dgrad=True, interpret=True)
        net_b = build()
        net_b.fit(x, y)
        got = np.asarray(net_b.params())
    finally:
        CONV_BWD_PALLAS.clear()
        CONV_BWD_PALLAS.update(old)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
