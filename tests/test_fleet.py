"""Multi-model fleet contract (ISSUE 8 acceptance): SLO policy types,
priority-aging in the batcher, per-name roll-vs-eviction locking, metrics
label hygiene, warm-pool LRU eviction with zero-recompile re-admission
(persistent AOT cache), SLO shed ordering (lowest priority first),
controller rebalancing that keeps in-flight requests answered, the
`/fleet` + fleet-aware `/readyz` HTTP surface, and a slow 64-model
long-tail soak."""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.monitor.registry import MetricsRegistry
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import (ContinuousBatcher, FleetPolicy,
                                        LatencySLO, ModelFleet,
                                        ModelRegistry, RejectedError,
                                        Replica, ServingMetrics, SLOTracker)
from deeplearning4j_tpu.train.updaters import Sgd


def _net(seed=0, n_in=8, n_out=3, hidden=16):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=hidden, activation="relu"),
                   OutputLayer(n_out=n_out, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _x(n=2, n_in=8, seed=0):
    return np.random.RandomState(seed).randn(n, n_in).astype(np.float32)


def _fleet(tmp_path, **kw):
    kw.setdefault("max_resident", 2)
    kw.setdefault("max_batch", 4)
    kw.setdefault("batch_timeout_ms", 1.0)
    kw.setdefault("cache_dir", str(tmp_path / "exec-cache"))
    return ModelFleet(**kw)


# ---------------------------------------------------------------------------
# SLO policy types
# ---------------------------------------------------------------------------

def test_latency_slo_and_policy_validation():
    slo = LatencySLO(target_p99_ms=50.0, priority=3)
    assert slo.request_deadline_ms() == 200.0          # 4x target default
    assert LatencySLO(target_p99_ms=50.0,
                      deadline_ms=75.0).request_deadline_ms() == 75.0
    with pytest.raises(ValueError, match="target_p99_ms"):
        LatencySLO(target_p99_ms=0.0)
    with pytest.raises(ValueError, match="deadline_ms"):
        LatencySLO(deadline_ms=-1.0)
    with pytest.raises(ValueError, match="mode"):
        FleetPolicy(mode="panic")
    with pytest.raises(ValueError, match="breach_after"):
        FleetPolicy(breach_after=0)


def test_slo_tracker_hysteresis_both_directions():
    t = SLOTracker(LatencySLO(target_p99_ms=100.0), breach_after=3,
                   clear_after=2)
    assert not t.observe(500.0) and not t.observe(500.0)   # 2 < breach_after
    assert t.observe(500.0)                                # 3rd flips
    assert t.breaches_total == 1
    assert t.observe(50.0)                                 # 1 good: still on
    assert not t.observe(50.0)                             # 2nd clears
    assert not t.observe(float("nan"))    # empty window counts healthy
    t.observe(500.0), t.observe(500.0), t.observe(500.0)
    assert t.breached and t.breaches_total == 2            # onsets counted


# ---------------------------------------------------------------------------
# satellite: batcher priority aging
# ---------------------------------------------------------------------------

def test_effective_priority_ages_near_deadline():
    b = ContinuousBatcher(lambda g, xs: xs, aging_fraction=0.5,
                          aging_bump=1 << 20)
    try:
        now = time.monotonic()
        from deeplearning4j_tpu.serving.batcher import _Request
        from concurrent.futures import Future
        fresh = _Request(x=np.zeros((1, 2)), future=Future(), group=("g",),
                         priority=0, enqueued=now, deadline=now + 1.0)
        assert b._effective_priority(fresh, now) == 0      # full budget left
        # less than half the budget remains -> escalates above priority 5
        aged = _Request(x=np.zeros((1, 2)), future=Future(), group=("g",),
                        priority=0, enqueued=now - 0.6, deadline=now + 0.4)
        assert b._effective_priority(aged, now) > 5
        nodl = _Request(x=np.zeros((1, 2)), future=Future(), group=("g",),
                        priority=2, enqueued=now, deadline=None)
        assert b._effective_priority(nodl, now) == 2       # no deadline: flat
    finally:
        b.shutdown(drain=False)


def test_aging_prevents_priority_starvation():
    """A low-priority near-deadline request dispatches ahead of a steady
    high-priority stream instead of starving straight past its deadline."""
    gate = threading.Event()
    order = []

    def dispatch(group, xs):
        gate.wait(timeout=5.0)
        order.append(group[0])
        return xs

    b = ContinuousBatcher(dispatch, max_batch=1, batch_timeout_ms=0.5,
                          aging_fraction=1.0)    # escalate immediately
    try:
        b.submit(np.zeros((1, 2)), group=("hi",), priority=5)  # blocks worker
        time.sleep(0.05)
        lo = b.submit(np.zeros((1, 2)), group=("lo",), priority=0,
                      deadline_ms=2000.0)
        his = [b.submit(np.zeros((1, 2)), group=("hi",), priority=5)
               for _ in range(4)]
        gate.set()
        lo.result(timeout=5.0)
        for f in his:
            f.result(timeout=5.0)
        # the aged lo request seeded the first post-gate dispatch
        assert order[1] == "lo", order
    finally:
        b.shutdown(drain=False)


def test_shed_decisions_counted_per_priority_class():
    gate = threading.Event()
    reg = MetricsRegistry()
    m = ServingMetrics(registry_=reg, server_label="s", model_label="m")
    b = ContinuousBatcher(lambda g, xs: (gate.wait(5.0), xs)[1],
                          max_batch=1, max_queue=2, metrics=m)
    try:
        b.submit(np.zeros((1, 2)), priority=7)             # occupies worker
        time.sleep(0.05)
        b.submit(np.zeros((1, 2)), priority=7, deadline_ms=1.0)
        b.submit(np.zeros((1, 2)), priority=3)
        with pytest.raises(RejectedError):                 # queue full
            b.submit(np.zeros((1, 2)), priority=1)
        time.sleep(0.05)                # let the p7 deadline lapse in queue
        gate.set()
        deadline = time.monotonic() + 5.0
        while ("expired:p7" not in m.sheds_by_priority()
               and time.monotonic() < deadline):
            time.sleep(0.01)
        sheds = m.sheds_by_priority()
        assert sheds.get("rejected:p1") == 1
        assert sheds.get("expired:p7") == 1
        assert m.snapshot()["sheds"] == sheds
    finally:
        gate.set()
        b.shutdown(drain=False)


# ---------------------------------------------------------------------------
# satellite: registry roll-vs-eviction lock
# ---------------------------------------------------------------------------

def test_name_lock_serializes_roll_against_eviction():
    reg = ModelRegistry()
    reg.register("m", _net(seed=1))
    assert reg.name_lock("m") is reg.name_lock("m")        # stable per name
    assert reg.name_lock("m") is not reg.name_lock("other")
    rolled = threading.Event()

    def roll():
        reg.register("m", _net(seed=2))                    # takes name lock
        rolled.set()

    with reg.name_lock("m"):       # simulated eviction drain/drop window
        t = threading.Thread(target=roll, daemon=True)
        t.start()
        time.sleep(0.15)
        assert not rolled.is_set()          # roll waits for the eviction
    t.join(timeout=5.0)
    assert rolled.is_set() and reg.get("m").version == 2
    # other names are unaffected by a held lock
    with reg.name_lock("m"):
        reg.register("other", _net(seed=3))


# ---------------------------------------------------------------------------
# satellite: metrics label hygiene
# ---------------------------------------------------------------------------

def test_metrics_label_pair_and_family_dedupe():
    reg = MetricsRegistry()
    a = ServingMetrics(registry_=reg, server_label="m0/r1", model_label="m0")
    assert a._base_labels == {"server": "m0/r1", "model": "m0"}
    a.submitted.inc(3)
    # same label pair (a warm re-admission rebuilding the server) reuses
    # the SAME series: no duplicate family member, counters accumulate
    b = ServingMetrics(registry_=reg, server_label="m0/r1", model_label="m0")
    assert b.submitted is a.submitted
    b.submitted.inc()
    assert a.submitted.value == 4
    # a different replica is a distinct series in the same family
    c = ServingMetrics(registry_=reg, server_label="m0/r2", model_label="m0")
    assert c.submitted is not a.submitted
    assert dict(c.submitted.labels)["model"] == "m0"
    # without model_label the series omits the label (back-compat)
    d = ServingMetrics(registry_=reg, server_label="solo")
    assert "model" not in dict(d.submitted.labels)


# ---------------------------------------------------------------------------
# fleet: deploy + route
# ---------------------------------------------------------------------------

def test_fleet_deploy_route_and_errors(tmp_path):
    with _fleet(tmp_path) as fleet:
        fleet.deploy("a", _net(seed=1), slo=LatencySLO(priority=1))
        fleet.deploy("b", _net(seed=2, n_out=5))
        assert fleet.output("a", _x()).shape == (2, 3)
        assert fleet.output("b", _x()).shape == (2, 5)
        assert fleet.member("a").requests == 1
        with pytest.raises(ValueError, match="already deployed"):
            fleet.deploy("a", _net())
        with pytest.raises(ValueError, match="exactly one"):
            fleet.deploy("c")
        with pytest.raises(KeyError, match="no model"):
            fleet.output("missing", _x())
        st = fleet.fleet_stats()
        assert set(st["models"]) == {"a", "b"}
        assert st["capacity"]["max_resident"] == 2
        assert st["models"]["a"]["priority"] == 1


def test_warm_pool_lru_eviction_and_zero_recompile_readmission(tmp_path):
    with _fleet(tmp_path, max_resident=2) as fleet:
        # distinct architectures -> distinct AOT fingerprints
        for i, width in enumerate((8, 12, 20)):
            fleet.deploy(f"m{i}", _net(seed=i, hidden=width))
        fleet.output("m0", _x())
        fleet.output("m1", _x())
        assert fleet.pool.resident_names() == ["m0", "m1"]
        first = fleet.member("m0").last_admission_fresh_compiles
        assert first and first > 0                   # cold start compiles
        fleet.output("m2", _x())                     # evicts LRU = m0
        m0 = fleet.member("m0")
        assert m0.state == "cold" and m0.evictions == 1
        assert fleet.pool.resident_names() == ["m1", "m2"]
        # evicted params went back to host numpy (device memory released)
        entry = fleet.registry.entries("m0")[0]
        import jax
        for leaf in jax.tree_util.tree_leaves(entry.model.params_):
            assert isinstance(leaf, np.ndarray)
        # re-admission: executables deserialize from the persistent AOT
        # cache — ZERO fresh XLA compiles
        before = dict(fleet.cache.stats)
        y = fleet.output("m0", _x())
        assert y.shape == (2, 3)
        assert fleet.member("m0").state == "resident"
        assert fleet.member("m0").admissions == 2
        assert fleet.member("m0").last_admission_fresh_compiles == 0
        assert fleet.cache.stats["compiles"] == before["compiles"]
        assert fleet.cache.stats["disk_hits"] > before["disk_hits"]
        assert fleet.pool.resident_names() == ["m2", "m0"]   # m1 was LRU


def test_eviction_drains_inflight_requests(tmp_path):
    with _fleet(tmp_path) as fleet:
        fleet.deploy("m", _net(seed=4))
        futs = [fleet.submit("m", _x(seed=i)) for i in range(6)]
        assert fleet.evict("m") is True              # drain -> drop
        for f in futs:
            assert f.result(timeout=10.0).shape == (2, 3)
        assert fleet.member("m").state == "cold"
        assert fleet.evict("m") is False             # already cold: no-op


def test_capacity_exhaustion_and_slice_pressure(tmp_path):
    with _fleet(tmp_path, max_resident=2, n_slices=1) as fleet:
        fleet.deploy("a", _net(seed=1))
        fleet.deploy("b", _net(seed=2))
        fleet.output("a", _x())
        # only 1 slice: admitting b evicts a even though max_resident=2
        fleet.output("b", _x())
        assert fleet.pool.resident_names() == ["b"]
        assert fleet.member("a").state == "cold"
    with _fleet(tmp_path, max_resident=2, n_slices=1) as fleet:
        fleet.deploy("wide", _net(seed=3), replicas=2)   # needs 2 slices
        with pytest.raises(RejectedError, match="capacity"):
            fleet.output("wide", _x())


def test_preferred_slice_affinity_on_readmission(tmp_path):
    with _fleet(tmp_path, max_resident=3, n_slices=4) as fleet:
        for i in range(3):
            fleet.deploy(f"m{i}", _net(seed=i))
            fleet.output(f"m{i}", _x())              # m0->s0, m1->s1, m2->s2
        assert fleet.member("m2").group.replicas[0].slice.index == 2
        fleet.evict("m0")
        fleet.evict("m2")                            # free slices: {0, 2, 3}
        fleet.output("m2", _x())
        # affinity: m2 returns to slice 2 (its persistent-cache home on a
        # device-pinned fleet), not the lowest free slice 0
        assert fleet.member("m2").group.replicas[0].slice.index == 2


# ---------------------------------------------------------------------------
# fleet: SLO shed ordering
# ---------------------------------------------------------------------------

def _force_breach(member):
    for _ in range(member.tracker.breach_after):
        member.tracker.observe(member.slo.target_p99_ms * 100.0)
    assert member.tracker.breached


def test_shed_ordering_low_priority_first(tmp_path):
    with _fleet(tmp_path) as fleet:
        fleet.deploy("lo", _net(seed=1), slo=LatencySLO(priority=0))
        hi = fleet.deploy("hi", _net(seed=2),
                          slo=LatencySLO(priority=10), warm=True)
        fleet.output("lo", _x())
        _force_breach(hi)                    # hi under sustained pressure
        assert fleet.router.shed_level() == 10
        # lower-priority traffic sheds first ...
        with pytest.raises(RejectedError, match="shed"):
            fleet.submit("lo", _x())
        assert fleet.member("lo").sheds == 1
        # ... while the highest-priority member keeps being served
        assert fleet.output("hi", _x()).shape == (2, 3)
        assert fleet.member("hi").sheds == 0
        # breach clears -> low-priority traffic flows again
        for _ in range(fleet.policy.clear_after):
            hi.tracker.observe(1.0)
        assert fleet.router.shed_level() is None
        assert fleet.output("lo", _x()).shape == (2, 3)


def test_self_shed_probes_so_breach_can_clear(tmp_path):
    with _fleet(tmp_path) as fleet:
        lo = fleet.deploy("lo", _net(seed=1), slo=LatencySLO(priority=0),
                          warm=True)
        fleet.deploy("hi", _net(seed=2), slo=LatencySLO(priority=10))
        _force_breach(lo)        # lo breached, outranked by hi -> self-shed
        n = 2 * fleet.router.probe_every
        served = sheds = 0
        for i in range(n):
            try:
                fleet.output("lo", _x(seed=i))
                served += 1
            except RejectedError:
                sheds += 1
        # most traffic sheds, but probe admissions keep samples flowing
        assert served == 2 and sheds == n - 2
        assert fleet.member("lo").sheds == sheds


def test_deprioritize_mode_admits_at_floor(tmp_path):
    with _fleet(tmp_path,
                policy=FleetPolicy(mode="deprioritize")) as fleet:
        fleet.deploy("lo", _net(seed=1), slo=LatencySLO(priority=0))
        hi = fleet.deploy("hi", _net(seed=2),
                          slo=LatencySLO(priority=10), warm=True)
        _force_breach(hi)
        # deprioritized, not refused: the request still answers
        assert fleet.output("lo", _x()).shape == (2, 3)
        assert fleet.member("lo").deprioritized == 1
        assert fleet.member("lo").sheds == 0


# ---------------------------------------------------------------------------
# fleet: replica dispatch health
# ---------------------------------------------------------------------------

def test_replica_health_state_machine():
    import types
    r = Replica("m", types.SimpleNamespace(), types.SimpleNamespace(index=0))
    assert r.healthy
    assert not r.record_failure(3) and not r.record_failure(3)
    assert r.record_failure(3)              # third consecutive: flips
    assert not r.healthy
    assert not r.record_failure(3)          # already down: no re-flip
    assert r.failures == 4
    assert r.record_success()               # probe passed: clears
    assert r.healthy and r.consecutive_failures == 0
    assert not r.record_success()           # steady state: no event
    # a success between failures resets the consecutive count
    r.record_failure(3), r.record_success(), r.record_failure(3)
    assert r.healthy and r.consecutive_failures == 1


def test_flaky_replica_marked_unhealthy_probed_and_readmitted(tmp_path):
    from deeplearning4j_tpu.utils import chaos
    with _fleet(tmp_path, max_resident=2, n_slices=2) as fleet:
        m = fleet.deploy("m", _net(seed=1), replicas=2, warm=True)
        assert len(m.group.replicas) == 2
        good, bad = m.group.replicas
        failovers_before = fleet.instruments.failovers.value
        flaky = chaos.FlakyDispatch(bad.server.cache.run, times=10_000)
        bad.server.cache.run = flaky
        # drive traffic: a request the router hands the flaky replica
        # FAILS OVER to the healthy one — the client never sees the
        # ChaosError — while unhealthy_after consecutive dispatch
        # failures open the replica's breaker
        for i in range(32):
            fleet.output("m", _x(seed=i), timeout=10)
            if not bad.healthy:
                break
        deadline = time.monotonic() + 5     # observer runs on done-callback
        while bad.healthy and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not bad.healthy and good.healthy
        assert bad.consecutive_failures >= fleet.policy.unhealthy_after
        assert fleet.instruments.replica_unhealthy.value >= 1
        assert fleet.instruments.failovers.value > failovers_before
        # routing now avoids it except for probe admissions: over two full
        # probe windows, exactly 2 picks land on the sick replica
        picks = [fleet.router.pick(m)
                 for _ in range(2 * fleet.router.probe_every)]
        assert picks.count(bad) == 2
        assert all(r is good for r in picks if r is not bad)
        # while the probe keeps failing it stays out of rotation — and
        # EVERY request is still served, the failed probes included:
        # they re-route to the healthy replica instead of surfacing
        for i in range(2 * fleet.router.probe_every):
            fleet.output("m", _x(seed=i), timeout=10)
        assert not bad.healthy
        # the server recovers: the next probe succeeds and the replica
        # re-enters normal rotation
        bad.server.cache.run = flaky.fn
        for i in range(4 * fleet.router.probe_every):
            fleet.output("m", _x(seed=i), timeout=10)
            if bad.healthy:
                break
        deadline = time.monotonic() + 5
        while not bad.healthy and time.monotonic() < deadline:
            time.sleep(0.01)
        assert bad.healthy and bad.probes >= 1
        assert fleet.instruments.replica_probes.value >= 1
        assert bad in [fleet.router.pick(m) for _ in range(4)]


# ---------------------------------------------------------------------------
# fleet: controller rebalancing
# ---------------------------------------------------------------------------

def test_controller_grows_pressured_member(tmp_path):
    with _fleet(tmp_path, max_resident=2, n_slices=3) as fleet:
        m = fleet.deploy("m", _net(seed=1), warm=True)
        assert len(m.group.replicas) == 1
        _force_breach(m)
        rec = fleet.controller.reconcile()
        assert [a["action"] for a in rec["actions"]] == ["grow"]
        assert len(m.group.replicas) == 2
        assert fleet.fleet_stats()["recent_actions"]
        # both replicas serve (least-loaded routing spreads the stream)
        for i in range(8):
            assert fleet.output("m", _x(seed=i)).shape == (2, 3)


def test_controller_reclaims_idle_donor_slice(tmp_path):
    policy = FleetPolicy(shrink_idle_after_s=0.0)
    with _fleet(tmp_path, max_resident=2, n_slices=2,
                policy=policy) as fleet:
        donor = fleet.deploy("donor", _net(seed=1), warm=True)
        needy = fleet.deploy("needy", _net(seed=2),
                             slo=LatencySLO(priority=5), warm=True)
        # grow one replica onto the donor's... no free slice exists, so
        # the controller must first drain the idle donor's spare. Give the
        # donor a second replica to donate:
        fleet.controller.reconcile()     # no pressure: nothing happens
        assert len(donor.group.replicas) == 1
        _force_breach(needy)
        rec = fleet.controller.reconcile()
        # donor has only its floor replica -> nothing reclaimable
        assert rec["actions"] == []
        assert len(needy.group.replicas) == 1


def test_rebalance_keeps_inflight_answered(tmp_path):
    policy = FleetPolicy(shrink_idle_after_s=0.0)
    with _fleet(tmp_path, max_resident=1, n_slices=2,
                policy=policy) as fleet:
        m = fleet.deploy("m", _net(seed=1), warm=True)
        _force_breach(m)
        fleet.controller.reconcile()                 # grow to 2 replicas
        assert len(m.group.replicas) == 2
        futs = [fleet.submit("m", _x(seed=i)) for i in range(12)]
        for _ in range(fleet.policy.clear_after):    # breach clears
            m.tracker.observe(1.0)
        # shrink engages once the member is idle; the leaving replica is
        # pulled from routing FIRST, then drained — nothing is dropped
        rec, deadline = None, time.monotonic() + 10.0
        while time.monotonic() < deadline:
            m.last_used = time.monotonic() - 1.0     # "idle" for shrink
            rec = fleet.controller.reconcile()
            if rec["actions"]:
                break
            time.sleep(0.02)
        assert rec and [a["action"] for a in rec["actions"]] == ["shrink"]
        assert len(m.group.replicas) == 1
        for f in futs:                 # every in-flight request answered
            assert f.result(timeout=10.0).shape == (2, 3)


# ---------------------------------------------------------------------------
# fleet: mesh-pinned slices
# ---------------------------------------------------------------------------

def test_mesh_slice_replica_groups(tmp_path):
    import jax
    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs >= 4 devices (conftest provides 8 virtual CPUs)")
    with _fleet(tmp_path, max_resident=2, devices=devices,
                slice_size=2) as fleet:
        assert len(fleet._slices) == len(devices) // 2
        fleet.deploy("m", _net(seed=1), warm=True)
        replica = fleet.member("m").group.replicas[0]
        assert replica.server.cache.mesh is not None
        assert len(replica.slice.devices) == 2
        y = fleet.output("m", _x(n=4))
        assert y.shape == (4, 3)
        st = fleet.fleet_stats()
        assert st["capacity"]["slice_size"] == 2
    with pytest.raises(ValueError, match="slice_size"):
        ModelFleet(devices=devices, slice_size=len(devices) + 1)


# ---------------------------------------------------------------------------
# fleet: rolls, schedules, readiness
# ---------------------------------------------------------------------------

def test_roll_is_zero_downtime_and_warms_new_version(tmp_path):
    with _fleet(tmp_path) as fleet:
        fleet.deploy("m", _net(seed=1, n_out=3), warm=True)
        futs = [fleet.submit("m", _x(seed=i)) for i in range(4)]
        entry = fleet.roll("m", _net(seed=2, n_out=5))
        assert entry.version == 2
        for f in futs:       # in-flight stay on the version they resolved
            assert f.result(timeout=10.0).shape[1] in (3, 5)
        assert fleet.output("m", _x()).shape == (2, 5)   # new submits: v2
        # roll on a cold member just registers (admission picks it up)
        fleet.deploy("cold", _net(seed=3))
        assert fleet.roll("cold", _net(seed=4)).version == 2


def test_schedule_applies_on_admission(tmp_path):
    from deeplearning4j_tpu.compile import Schedule
    with _fleet(tmp_path, max_batch=16) as fleet:
        Schedule(buckets=[4, 16]).apply(fleet)       # fleet default hook
        assert fleet.default_schedule is not None
        fleet.deploy("m", _net(seed=1), warm=True)
        replica = fleet.member("m").group.replicas[0]
        assert replica.server.cache.buckets == [4, 16]
        # a per-model schedule wins over the fleet default
        fleet.deploy("n", _net(seed=2), schedule=Schedule(buckets=[8, 16]),
                     warm=True)
        assert fleet.member("n").group.replicas[0] \
            .server.cache.buckets == [8, 16]


def test_fleet_readyz_cold_members_do_not_block(tmp_path):
    fleet = _fleet(tmp_path)
    assert not fleet.readyz()["ready"]               # nothing deployed
    fleet.deploy("m", _net(seed=1))                  # cold but routable
    assert fleet.readyz() == {"ready": True, "reasons": []}
    fleet.output("m", _x())
    assert fleet.readyz()["ready"]
    fleet.shutdown()
    assert not fleet.readyz()["ready"]
    with pytest.raises(RejectedError, match="shut down"):
        fleet.submit("m", _x())


def test_fleet_http_endpoints(tmp_path):
    from deeplearning4j_tpu.ui.server import UIServer
    with _fleet(tmp_path) as fleet:
        ui = UIServer()                  # fresh instance, not the singleton
        ui.attach_fleet(fleet)
        port = ui.start(port=0)
        try:
            # fleet not ready (no models) -> aggregate /readyz is 503
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=5)
            assert ei.value.code == 503
            fleet.deploy("m", _net(seed=1), slo=LatencySLO(priority=2),
                         warm=True)
            fleet.output("m", _x())
            r = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readyz", timeout=5)
            assert json.loads(r.read())["ready"] is True
            r = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet", timeout=5)
            payload = json.loads(r.read())
            assert isinstance(payload, list) and len(payload) == 1
            st = payload[0]
            assert st["resident"] == ["m"]
            assert st["models"]["m"]["state"] == "resident"
            assert st["models"]["m"]["priority"] == 2
            assert st["aot_cache"]["compiles"] > 0
            r = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5)
            assert json.loads(r.read())["fleets"] == 1
        finally:
            ui.stop()


def test_fleet_instruments_record_admissions(tmp_path):
    reg = MetricsRegistry()
    with _fleet(tmp_path, registry_=reg) as fleet:
        fleet.deploy("a", _net(seed=1, hidden=8))
        fleet.deploy("b", _net(seed=2, hidden=12))
        fleet.deploy("c", _net(seed=3, hidden=20))
        for name in ("a", "b", "c", "a"):            # c evicts a; a re-admits
            fleet.output(name, _x())
        cold = reg.get("fleet_admissions_total", {"warm": "false"})
        warm = reg.get("fleet_admissions_total", {"warm": "true"})
        assert cold.value == 3 and warm.value == 1
        assert reg.get("fleet_evictions_total").value >= 2
        assert reg.get("fleet_models").value == 3
        assert reg.get("fleet_models_resident").value == 2
        assert reg.get("fleet_requests_total", {"model": "a"}).value == 2


# ---------------------------------------------------------------------------
# quantized re-admission (ISSUE 10)
# ---------------------------------------------------------------------------

def test_fleet_quantize_rolls_and_shrinks_residency(tmp_path):
    """`fleet.quantize(name)` rolls a QuantizedModel in as the next
    version and demotes the f32 predecessor to host — warm-pool memory
    accounting drops to the int8 bytes while outputs stay equivalent."""
    from deeplearning4j_tpu.quant import QuantizedModel
    with _fleet(tmp_path) as fleet:
        fleet.deploy("m", _net(hidden=128))
        before_out = fleet.output("m", _x())
        before_bytes = fleet.resident_bytes()
        entry = fleet.quantize("m")
        assert entry.source == "quant" and entry.version == 2
        assert isinstance(entry.model, QuantizedModel)
        assert fleet.registry.versions("m") == [1, 2]
        after_bytes = fleet.resident_bytes()
        assert after_bytes < before_bytes / 2, (before_bytes, after_bytes)
        after_out = fleet.output("m", _x())          # served by v2 (int8)
        np.testing.assert_allclose(after_out, before_out,
                                   rtol=5e-2, atol=5e-3)
        assert np.argmax(after_out, -1).tolist() == \
            np.argmax(before_out, -1).tolist()


# ---------------------------------------------------------------------------
# slow: long-tail soak
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_long_tail_soak_64_models(tmp_path):
    """64 models through a 4-model warm pool: every request answers with
    the right shape, the pool never exceeds capacity, and the second sweep
    is compile-free (pure persistent-cache deserialization)."""
    n_models, rounds = 64, 2
    with _fleet(tmp_path, max_resident=4, n_slices=8,
                max_batch=4) as fleet:
        rng = np.random.RandomState(0)
        for i in range(n_models):
            fleet.deploy(f"m{i:02d}", _net(seed=i, n_out=3 + i % 3))
        compiles_after_first = None
        for r in range(rounds):
            order = rng.permutation(n_models)
            for i in order:
                y = fleet.output(f"m{i:02d}", _x(seed=i))
                assert y.shape == (2, 3 + i % 3)
                assert len(fleet.pool.resident()) <= 4
            if r == 0:
                compiles_after_first = fleet.cache.stats["compiles"]
        # second sweep: every re-admission warm, zero fresh compiles
        assert fleet.cache.stats["compiles"] == compiles_after_first
        st = fleet.fleet_stats()
        assert len(st["models"]) == n_models
        evictions = sum(m["evictions"] for m in st["models"].values())
        assert evictions >= n_models - 4             # the tail churned
