"""Hierarchical compressed cross-host gradient all-reduce
(parallel/hierarchical + transport + compression; reference: Aeron
threshold GradientSharing, SURVEY.md §3.4, at DCN scale).

Three layers under test: the codec contracts (explicit thresholds never
mutate state; error-feedback residuals make the sum-over-steps track the
true gradient), the TCP mesh failure posture (dead peers fail FAST with
named-rank errors, never hang), and the split-step training integration
(world=1 dense sharing is BITWISE the plain step; composes with ZeRO-1
and the fused `fit_steps` entry; real multi-process parity over TCP)."""
import json
import os
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.monitor.registry import registry
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel import (HierarchicalGradientSharing,
                                         ParallelWrapper, make_mesh)
from deeplearning4j_tpu.parallel.compression import (
    CompressedGradientExchange)
from deeplearning4j_tpu.parallel.multihost import (ENV_GRAD_PORT,
                                                   LocalLauncher, free_port)
from deeplearning4j_tpu.parallel.transport import (PeerUnreachableError,
                                                   TcpGradientMesh,
                                                   pack_dense, pack_streams,
                                                   unpack_dense,
                                                   unpack_streams)
from deeplearning4j_tpu.train.updaters import Sgd


def _net(seed=7, n_in=8, lr=0.1):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(lr))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent", activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=32, n_in=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, n_in).astype(np.float32)
    labels = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)
    y = np.eye(3, dtype=np.float32)[labels]
    return x, y


def _assert_params_equal(a, b, exact=True):
    def cmp(x, y):
        x, y = np.asarray(x), np.asarray(y)
        if exact:
            np.testing.assert_array_equal(x, y)
        else:
            np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(cmp, a.params_, b.params_)


# ---------------------------------------------------------------------------
# Codec contracts (satellite: decode must not mutate codec state)
# ---------------------------------------------------------------------------

def test_decode_explicit_thresholds_no_mutation():
    """Decoding a peer's stream at the PEER's threshold must not disturb
    this host's codecs: thresholds unchanged after, and the next local
    encode/decode round-trip is unaffected."""
    tmpl = {"w": np.zeros((8, 4), np.float32)}
    ex = CompressedGradientExchange(tmpl, threshold=0.01)
    g = {"w": np.full((8, 4), 0.05, np.float32)}
    streams = ex.encode(g)
    before = [c.threshold for c in ex.codecs]
    peer = ex.decode(streams, thresholds=[0.5])     # peer's coarse stream
    assert float(peer["w"][0, 0]) == pytest.approx(0.5)
    assert [c.threshold for c in ex.codecs] == before
    own = ex.decode(streams)                        # None -> used thresholds
    assert float(own["w"][0, 0]) == pytest.approx(0.01)


def test_decode_empty_threshold_list_honored():
    """An explicit (falsy) empty list is a valid thresholds argument for a
    zero-leaf tree — it must be honored as given, not swapped for the
    last-encode default."""
    ex = CompressedGradientExchange({}, threshold=0.01)
    assert ex.decode(ex.encode({}), thresholds=[]) == {}


def test_residual_error_feedback_flushes_to_true_sum():
    """What a threshold cut this step, the residual re-emits later: the
    sum of decoded exchanges converges to the true gradient sum (the
    reference accumulator's delta semantics)."""
    thr = 0.01
    rng = np.random.RandomState(0)
    g = {"w": (rng.randn(64).astype(np.float32) * 0.03)}
    ex = CompressedGradientExchange(g, threshold=thr)
    total = np.zeros(64, np.float32)
    total += np.asarray(ex.decode(ex.encode(g))["w"])
    zeros = {"w": np.zeros(64, np.float32)}
    for _ in range(20):                 # flush residuals
        total += np.asarray(ex.decode(ex.encode(zeros))["w"])
    np.testing.assert_allclose(total, g["w"], atol=thr + 1e-7)


def test_adaptive_threshold_converges_toward_target_density():
    """A stream denser than 2x target must drive the threshold UP until
    the emitted density falls toward the target."""
    rng = np.random.RandomState(1)
    ex = CompressedGradientExchange({"w": np.zeros(4096, np.float32)},
                                    threshold=1e-4,
                                    adaptive_target_density=1e-2)
    thr0 = ex.codecs[0].threshold
    d_first = d_last = None
    for _ in range(40):
        g = {"w": rng.randn(4096).astype(np.float32) * 0.01}
        (s,) = ex.encode(g)
        d = len(s) / 4096
        d_first = d if d_first is None else d_first
        d_last = d
    assert ex.codecs[0].threshold > thr0
    assert d_last < d_first
    assert d_last < 0.1                 # near the 1e-2 target, not ~1.0


# ---------------------------------------------------------------------------
# Wire formats
# ---------------------------------------------------------------------------

def test_pack_streams_round_trip():
    streams = [np.array([1, -3, 7], np.int32), np.array([], np.int32),
               np.array([-1], np.int32)]
    thrs = [0.01, 0.5, 1e-6]
    back, back_thr = unpack_streams(pack_streams(streams, thrs))
    assert len(back) == 3
    for a, b in zip(back, streams):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(back_thr, thrs, rtol=1e-6)


def test_pack_dense_round_trip_including_scalar():
    leaves = [np.arange(12, dtype=np.float32).reshape(3, 4),
              np.float32(2.5),          # 0-d leaf
              np.array([], np.float32)]
    back = unpack_dense(pack_dense(leaves))
    assert back[0].shape == (3, 4) and back[1].shape == () \
        and back[2].shape == (0,)
    for a, b in zip(back, leaves):
        np.testing.assert_array_equal(a, np.asarray(b))


# ---------------------------------------------------------------------------
# Failure posture (satellite: dead peer must fail fast, named)
# ---------------------------------------------------------------------------

def test_dead_coordinator_fails_fast_with_named_error():
    port = free_port()                  # nothing listens here
    t0 = time.monotonic()
    with pytest.raises(PeerUnreachableError) as ei:
        TcpGradientMesh(rank=1, world=2, port=port, timeout=1.0)
    assert time.monotonic() - t0 < 5.0
    msg = str(ei.value)
    assert "rank 0" in msg and str(port) in msg and "unreachable" in msg


def test_formation_timeout_names_missing_ranks():
    with pytest.raises(PeerUnreachableError) as ei:
        TcpGradientMesh(rank=0, world=3, port=free_port(), timeout=0.5)
    msg = str(ei.value)
    assert "[1, 2]" in msg and "never connected" in msg


def test_peer_unreachable_is_connection_error():
    assert issubclass(PeerUnreachableError, ConnectionError)


# ---------------------------------------------------------------------------
# Split-step training integration (world == 1: no sockets)
# ---------------------------------------------------------------------------

def test_world1_dense_sharing_bitwise_matches_plain_fit():
    """The grad/apply split with a pass-through exchange must be the SAME
    math as the fused plain step — bitwise, not approximately."""
    x, y = _data()
    ref = _net()
    shared = _net()
    shared.set_gradient_sharing(HierarchicalGradientSharing(
        compressed=False, world=1))
    for _ in range(5):
        ref.fit(x, y)
        shared.fit(x, y)
    _assert_params_equal(ref, shared, exact=True)
    assert ref.iteration == shared.iteration == 5
    shared.set_gradient_sharing(None)
    assert shared.gradient_sharing is None


def test_world1_compressed_converges_and_records_metrics():
    """The codec round-trip (residuals included) runs even single-host;
    training must still converge and the comms metrics must land in the
    shared registry."""
    x, y = _data(n=64)
    net = _net()
    net.set_gradient_sharing(HierarchicalGradientSharing(
        threshold=5e-3, world=1))
    first = None
    for _ in range(40):
        net.fit(x, y)
        first = net.score() if first is None else first
    assert net.score() < first * 0.8
    st = net.gradient_sharing.stats()
    assert st["exchanges"] == 40 and st["compressed"] and st["world"] == 1
    assert st["last_wire_bytes"] > 0
    c = registry().get("comms_exchanges_total", {"codec": "threshold"})
    assert c is not None and c.value >= 40
    b = registry().get("comms_bytes_on_wire_total", {"codec": "threshold"})
    assert b is not None and b.value > 0
    g = registry().get("comms_compression_ratio")
    assert g is not None and g.value > 1.0
    h = registry().get("comms_exchange_ms")
    assert h is not None and h.count >= 40
    net.set_gradient_sharing(None)


def test_sharing_composes_with_zero1_and_fit_steps():
    """ZeRO-1 + sharing: the grad half ships the reduce-scattered shard,
    the apply half runs the sharded update on the combined gradient —
    bitwise-equal to plain ZeRO-1 for Sgd, including through the
    `fit_steps` entry (which degrades to per-step exchange)."""
    mesh = make_mesh({"data": 8}, jax.devices())
    rng = np.random.RandomState(2)
    xs = rng.randn(4, 32, 8).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.randint(0, 3, (4, 32))]

    ref = _net()
    pw_ref = ParallelWrapper(ref, mesh, optimizer_sharding=True)
    shared = _net()
    pw_sh = ParallelWrapper(shared, mesh, optimizer_sharding=True,
                            gradient_sharing=HierarchicalGradientSharing(
                                compressed=False, world=1))
    l_ref = pw_ref.fit_steps(xs, ys)
    l_sh = pw_sh.fit_steps(xs, ys)
    np.testing.assert_array_equal(np.asarray(l_ref), np.asarray(l_sh))
    _assert_params_equal(ref, shared, exact=True)
    assert ref.iteration == shared.iteration == 4
    pw_sh.gradient_sharing(None)


def test_computation_graph_world1_dense_parity():
    from deeplearning4j_tpu.nn import ComputationGraph, GraphBuilder

    def build():
        conf = (GraphBuilder().seed(5).updater(Sgd(0.1))
                .add_inputs("in")
                .set_input_types(InputType.feed_forward(8))
                .add_layer("d", DenseLayer(n_out=12, activation="tanh"),
                           "in")
                .add_layer("out", OutputLayer(n_out=3, loss="mcxent",
                                              activation="softmax"), "d")
                .set_outputs("out").build())
        return ComputationGraph(conf).init()

    x, y = _data(n=16)
    ref, shared = build(), build()
    shared.set_gradient_sharing(HierarchicalGradientSharing(
        compressed=False, world=1))
    for _ in range(5):
        ref.fit(x, y)
        shared.fit(x, y)
    _assert_params_equal(ref, shared, exact=True)
    shared.set_gradient_sharing(None)


def test_wrapper_builder_and_runtime_toggle():
    x, y = _data()
    net = _net()
    pw = (ParallelWrapper.builder(net)
          .workers(4)
          .gradient_sharing(HierarchicalGradientSharing(
              compressed=False, world=1))
          .build())
    pw.fit(x, y)
    assert net.gradient_sharing is not None
    assert net.gradient_sharing.world == 1
    pw.gradient_sharing(False)          # runtime off-toggle
    pw.fit(x, y)
    assert net.gradient_sharing is None
    assert net.iteration == 2


def test_composed_parallel_sharing_matches_plain_step():
    """The dp×tp×pp composed step with a pass-through (dense, world=1)
    DCN exchange must track the plain composed step, and the compressed
    config must run through the same facade."""
    from deeplearning4j_tpu.parallel.composed import (ComposedParallel,
                                                      init_stage_params)
    mesh = make_mesh({"data": 2, "model": 2, "pipe": 2}, jax.devices()[:8])
    params = init_stage_params(np.random.RandomState(7), 2, 8, 2, 16)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 8, 8).astype(np.float32)
    y = rng.randn(8, 8, 8).astype(np.float32)

    plain = ComposedParallel(mesh, n_heads=2, lr=0.2)
    shared = ComposedParallel(mesh, n_heads=2, lr=0.2,
                              gradient_sharing=HierarchicalGradientSharing(
                                  compressed=False, world=1))
    p_plain, p_shared = params, params
    for _ in range(2):
        p_plain, l_plain = plain.fit_batch(p_plain, x, y)
        p_shared, l_shared = shared.fit_batch(p_shared, x, y)
    np.testing.assert_allclose(float(l_plain), float(l_shared),
                               rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        p_plain, p_shared)
    assert shared.gradient_sharing.exchanges == 2
    shared.close()

    comp = ComposedParallel(mesh, n_heads=2, lr=0.2,
                            gradient_sharing=HierarchicalGradientSharing(
                                threshold=5e-3, world=1))
    p, loss = comp.fit_batch(params, x, y)
    assert np.isfinite(float(loss))
    assert comp.gradient_sharing.stats()["compressed"]
    comp.close()


def test_config_resolves_from_launcher_env(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_PROCESS_ID", "3")
    monkeypatch.setenv("DL4J_TPU_NUM_PROCESSES", "4")
    monkeypatch.setenv(ENV_GRAD_PORT, "50123")
    cfg = HierarchicalGradientSharing().resolve()
    assert (cfg.rank, cfg.world, cfg.port) == (3, 4, 50123)
    assert cfg.host == "127.0.0.1"
    with pytest.raises(ValueError, match="combine"):
        HierarchicalGradientSharing(combine="max")


# ---------------------------------------------------------------------------
# Real multi-process exchange (acceptance: compressed-vs-dense parity +
# bytes-on-wire reduction over actual TCP)
# ---------------------------------------------------------------------------

def test_multihost_compressed_vs_dense_parity(tmp_path):
    """Two real processes (own XLA clients, coupled only by the TCP
    gradient mesh) train the same model A/B: dense wire vs threshold
    streams.  Ranks must agree bitwise with each other (same combined
    gradient), compressed must track dense loss, and must ship
    meaningfully fewer bytes."""
    worker = os.path.join(os.path.dirname(__file__), "mh_worker_comms.py")
    steps, res = 40, {}
    for mode in ("dense", "compressed"):
        launcher = LocalLauncher(num_processes=2, devices_per_process=1)
        launcher.run(worker, [str(tmp_path), mode, steps, 16],
                     timeout=240.0, gradient_port=free_port())
        curves = [np.load(tmp_path / f"curve_{mode}_{r}.npz")
                  for r in range(2)]
        stats = [json.loads((tmp_path / f"stats_{mode}_{r}.json")
                            .read_text()) for r in range(2)]
        np.testing.assert_allclose(curves[0]["w0"], curves[1]["w0"],
                                   rtol=1e-5, atol=1e-6)
        assert all(s["exchanges"] == steps for s in stats)
        res[mode] = {
            "loss": float(np.mean([c["losses"][-1] for c in curves])),
            "wire": sum(s["bytes_sent_total"] + s["bytes_received_total"]
                        for s in stats)}
    assert res["dense"]["wire"] > res["compressed"]["wire"] * 2
    rel = (abs(res["compressed"]["loss"] - res["dense"]["loss"])
           / abs(res["dense"]["loss"]))
    assert rel < 0.05, f"compressed diverged from dense: {res!r}"
