"""Arbiter-driven elastic-gang worker (spawned by test_arbiter via
ElasticLocalRunner.run_elastic — NOT a pytest file).

Same deterministic gang-sharded training as mh_worker_elastic_gang, but
the trainer opts into the pod arbiter's control-dir shrink protocol
(`ElasticTrainer(control_dir=...)`): the parent test pre-places a
``shrink-request.json`` naming a victim rank, the coordinator commits a
blocking checkpoint and evicts that rank at the coordinated resume step,
and writes ``shrink-ack.json``.  With `chaos_rank >= 0` a
`HandoffChaos(target="gang", mode="kill")` hook hard-kills the victim
THE MOMENT the request names it — racing the coordinator's eviction, so
the run exercises "gang rank dies mid-shrink-window": whichever side
wins, the gang must re-form to world-1 once and the survivors must end
bitwise-identical.

argv: out_dir steps_per_epoch epochs control_dir chaos_rank
  chaos_rank -1 disables the chaos hook
"""
import json
import os
import sys

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel.hierarchical import (
    HierarchicalGradientSharing)
from deeplearning4j_tpu.parallel.multihost import ENV_CKPT, ENV_PID
from deeplearning4j_tpu.parallel.transport import (GangEvictedError,
                                                   PeerUnreachableError)
from deeplearning4j_tpu.train.resilience import (CheckpointManager,
                                                 ElasticTrainer)
from deeplearning4j_tpu.train.updaters import Sgd
from deeplearning4j_tpu.utils.chaos import HandoffChaos

out_dir = sys.argv[1]
steps_per_epoch = int(sys.argv[2])
epochs = int(sys.argv[3])
control_dir = sys.argv[4]
chaos_rank = int(sys.argv[5])

rank = int(os.environ.get(ENV_PID, "0"))
ckpt_dir = os.environ[ENV_CKPT]

N_IN, N_OUT, GLOBAL_BATCH = 16, 3, 12

conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
        .list([DenseLayer(n_out=32, activation="tanh"),
               OutputLayer(n_out=N_OUT, loss="mcxent",
                           activation="softmax")])
        .set_input_type(InputType.feed_forward(N_IN)).build())
net = MultiLayerNetwork(conf).init()
net.set_gradient_sharing(HierarchicalGradientSharing(
    threshold=5e-3, elastic=True))


class GangShardIterator(DataSetIterator):
    """Deterministic global stream, live-rank strided shards (same
    stream contract as mh_worker_elastic_gang)."""

    def __init__(self, model, steps: int):
        self.model = model
        self.steps = int(steps)

    def __iter__(self):
        for i in range(self.steps):
            seed = 1000 + int(self.model.epoch) * self.steps + i
            rng = np.random.RandomState(seed)
            xg = rng.randn(GLOBAL_BATCH, N_IN).astype(np.float32)
            labels = ((xg[:, 0] > 0).astype(int)
                      + (xg[:, 1] > 0).astype(int))
            yg = np.eye(N_OUT, dtype=np.float32)[labels]
            sharing = self.model.gradient_sharing
            r, w = sharing.rank, sharing.world
            yield DataSet(xg[r::w], yg[r::w])

    def __len__(self):
        return self.steps

    def batch_size(self) -> int:
        return GLOBAL_BATCH


manager = CheckpointManager(ckpt_dir, keep_last=200,
                            save_every_steps=1 if rank == 0 else None)
hooks = []
if chaos_rank >= 0:
    hooks.append(HandoffChaos(
        target="gang", mode="kill", rank=chaos_rank,
        control_dir=control_dir,
        marker=os.path.join(out_dir, "chaos_once")))
trainer = ElasticTrainer(net, manager, policy="shrink", rejoin_wait_s=60.0,
                         hooks=hooks, save_initial=(rank == 0),
                         control_dir=control_dir if rank == 0 else None)
data = GangShardIterator(net, steps_per_epoch)
try:
    trainer.fit(data, epochs=epochs)
except (GangEvictedError, PeerUnreachableError) as e:
    print(f"rank {rank}: left the gang: {e}", flush=True)
    net.set_gradient_sharing(None)
    sys.exit(7)

stats = net.gradient_sharing.stats()
np.savez(os.path.join(out_dir, f"final_{rank}.npz"),
         params=np.asarray(net.params()),
         iteration=np.int64(net.iteration),
         score=np.float64(net.score()))
with open(os.path.join(out_dir, f"elastic_{rank}.json"), "w") as f:
    json.dump({"stats": stats, "reformations": trainer.reformations}, f)
net.set_gradient_sharing(None)
print(f"rank {rank}: done at iteration {net.iteration} "
      f"(world={stats['world']}, generation={stats['generation']})",
      flush=True)
