"""OpValidation specs, part 4: multi-config coverage for the
stride/dilation/padding/layout-sensitive op families.

Reference: the opvalidation corpus carries many cases per conv/pool/rnn
op across configs (`platform-tests/.../opvalidation/LayerOpValidation.java`
et al.) — exactly the class of coverage that catches orientation and
padding-convention bugs (the round-4 deconv spatial flip hid in the one
unexercised config).  Goldens here are TF / torch / closed-form numpy,
never re-derivations of the op impls.  Asymmetric-SAME cases use TF
directly because XLA string padding follows TF's asymmetric convention.
"""
import numpy as np

from tests.opval_specs_nn import (C, F, FP, _depthwise_golden,
                                  _gru_cell_golden, _gru_layer_golden,
                                  _lstm_cell_golden, _lstm_layer_golden,
                                  _lstm_layer_full_golden,
                                  _nchw_conv_golden, _rnn_golden,
                                  _sru_golden, _conv1d_golden,
                                  _conv3d_golden)


def _tf():
    import tensorflow as tf
    return tf


def _tf_conv2d_golden(x, w, b=None, stride=(1, 1), padding="SAME",
                      dilation=(1, 1)):
    tf = _tf()
    y = tf.nn.conv2d(x.astype(np.float64), w.astype(np.float64),
                     strides=(1,) + tuple(stride) + (1,), padding=padding,
                     dilations=(1,) + tuple(dilation) + (1,)).numpy()
    return y if b is None else y + b


def _tf_depthwise_golden(x, w, stride=(1, 1), padding="SAME",
                         dilation=(1, 1)):
    tf = _tf()
    kh, kw = w.shape[:2]
    ci = x.shape[-1]
    # repo layout (kh, kw, 1, ci*mult) with group-major channel order ==
    # TF's (kh, kw, ci, mult) after reshape
    wt = w.reshape(kh, kw, ci, -1)
    return tf.nn.depthwise_conv2d(
        x.astype(np.float64), wt.astype(np.float64),
        strides=(1,) + tuple(stride) + (1,), padding=padding,
        dilations=tuple(dilation)).numpy()


def _tf_separable_golden(x, wd, wp, stride=(1, 1), padding="SAME"):
    tf = _tf()
    return tf.nn.separable_conv2d(
        x.astype(np.float64), wd.astype(np.float64),
        wp.astype(np.float64), strides=(1,) + tuple(stride) + (1,),
        padding=padding).numpy()


def _tf_deconv2d_golden(x, w, b=None, stride=(2, 2), padding="SAME"):
    tf = _tf()
    B, H, W, ci = x.shape
    co = w.shape[3]
    y = tf.nn.conv2d_transpose(
        x.astype(np.float64),
        w.transpose(0, 1, 3, 2).astype(np.float64),
        output_shape=(B, H * stride[0], W * stride[1], co),
        strides=(1,) + tuple(stride) + (1,), padding=padding).numpy()
    return y if b is None else y + b


def _tf_pool_golden(mode):
    def g(x, kernel=(2, 2), stride=(2, 2), padding="VALID"):
        tf = _tf()
        fn = tf.nn.max_pool2d if mode == "max" else tf.nn.avg_pool2d
        return fn(x.astype(np.float64), kernel,
                  (1,) + tuple(stride) + (1,), padding).numpy()
    return g


def _tf_resize_golden(method, antialias=True):
    def g(x, size):
        tf = _tf()
        return tf.image.resize(x.astype(np.float32), size, method=method,
                               antialias=antialias).numpy()
    return g


def _nchw_conv_asym_golden(x, w, b=None, stride=(1, 1),
                           pads=(0, 0, 0, 0), dilation=(1, 1), groups=1):
    """pads = (top, left, bottom, right): explicit-pad then VALID conv —
    pins the pads ordering convention, which symmetric cases can't."""
    xp = np.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]),
                    (pads[1], pads[3])))
    return _nchw_conv_golden(xp, w, b, stride, (0, 0, 0, 0), dilation,
                             groups)


def _win_pool3d_golden(mode):
    def g(x, kernel=(2, 2, 2), stride=(1, 1, 1), padding="VALID"):
        from numpy.lib.stride_tricks import sliding_window_view
        v = sliding_window_view(x, kernel, axis=(1, 2, 3))
        v = v[:, ::stride[0], ::stride[1], ::stride[2]]
        return v.max((-3, -2, -1)) if mode == "max" else v.mean(
            (-3, -2, -1))
    return g


rs = np.random.RandomState(4321)
CASES = []

# ---- conv2d NHWC: asymmetric SAME under stride, and dilation ----
_x66 = F(2, 6, 6, 3)
_w333 = F(3, 3, 3, 4, lo=-0.5, hi=0.5)
CASES += [
    # 6x6, k3, s2, SAME -> XLA pads (0,1)x(0,1): the asymmetric case
    C("conv2d", _x66, _w333, kw={"stride": (2, 2), "padding": "SAME"},
      g=_tf_conv2d_golden, tol=1e-4, grad=(0, 1), grad_sample=8,
      gtol=2e-2, tag="same-s2-asym"),
    C("conv2d", _x66, _w333, kw={"dilation": (2, 2), "padding": "SAME"},
      g=_tf_conv2d_golden, tol=1e-4, grad=(0, 1), grad_sample=8,
      gtol=2e-2, tag="dilated-same"),
    C("conv1d", F(2, 8, 3), F(3, 3, 5, lo=-0.5, hi=0.5),
      kw={"stride": 2, "padding": "VALID"}, g=_conv1d_golden, tol=1e-4,
      grad=(0, 1), grad_sample=8, gtol=2e-2, tag="s2-valid"),
    C("conv1d", F(2, 8, 3), F(3, 3, 5, lo=-0.5, hi=0.5),
      kw={"dilation": 2, "padding": "SAME"},
      # SAME with k3 d2 pads (2,2); the shared helper hard-codes pad=1
      g=lambda x, w, stride=1, padding="SAME", dilation=2: __import__(
          "torch.nn.functional", fromlist=["conv1d"]).conv1d(
          __import__("torch").from_numpy(
              x.transpose(0, 2, 1)).double(),
          __import__("torch").from_numpy(
              w.transpose(2, 1, 0)).double(), None, stride, 2,
          dilation).numpy().transpose(0, 2, 1), tol=1e-4,
      tag="dilated-same"),
    C("conv3d", F(1, 4, 4, 4, 2), F(2, 2, 2, 2, 3, lo=-0.5, hi=0.5),
      kw={"stride": (2, 2, 2), "padding": "VALID"},
      g=lambda x, w, b=None, stride=(2, 2, 2), padding="VALID":
      _conv3d_golden(x, w, b, stride, padding), tol=1e-4,
      grad=(0, 1), grad_sample=8, gtol=2e-2, tag="s2-valid"),
    C("depthwise_conv2d", _x66, F(3, 3, 1, 6, lo=-0.5, hi=0.5),
      kw={"stride": (2, 2), "padding": "SAME"},
      g=lambda x, w, stride=(1, 1), padding="SAME":
      _tf_depthwise_golden(x, w, stride, padding), tol=1e-4,
      grad=(0, 1), grad_sample=8, gtol=2e-2, tag="same-s2-asym"),
    C("depthwise_conv2d", _x66, F(3, 3, 1, 6, lo=-0.5, hi=0.5),
      kw={"dilation": (2, 2)},
      g=lambda x, w, stride=(1, 1), padding="SAME", dilation=(2, 2):
      _tf_depthwise_golden(x, w, stride, padding, dilation), tol=1e-4,
      tag="dilated"),
    C("separable_conv2d", _x66, F(3, 3, 3, 2, lo=-0.5, hi=0.5),
      F(1, 1, 6, 4, lo=-0.5, hi=0.5),
      kw={"stride": (2, 2), "padding": "SAME"},
      g=_tf_separable_golden, tol=1e-4, grad=(0, 1, 2), grad_sample=8,
      gtol=2e-2, tag="same-s2-asym"),
    C("pointwise_conv2d", F(2, 5, 3, 7), F(1, 1, 7, 2, lo=-0.5, hi=0.5),
      g=lambda x, w: np.einsum("bhwi,io->bhwo", x, w.reshape(7, 2)),
      tol=1e-4, tag="rect"),
    C("deconv2d", F(2, 3, 3, 2), F(3, 3, 2, 4, lo=-0.5, hi=0.5),
      kw={"stride": (2, 2), "padding": "SAME"},
      g=lambda x, w, b=None, stride=(2, 2), padding="SAME":
      _tf_deconv2d_golden(x, w, b, stride, padding), tol=1e-4,
      grad=(0, 1), grad_sample=8, gtol=2e-2, tag="same-s2"),
    # NCHW: asymmetric explicit pads pin the (top,left,bottom,right)
    # ordering; a groups case pins grouped-channel layout
    C("conv2d_nchw", F(2, 3, 5, 5), F(4, 3, 3, 3, lo=-0.5, hi=0.5),
      kw={"pads": (0, 1, 2, 0)}, g=_nchw_conv_asym_golden, tol=1e-4,
      grad=(0, 1), grad_sample=8, gtol=2e-2, tag="asym-pads"),
    C("conv2d_nchw", F(2, 4, 5, 5), F(6, 2, 3, 3, lo=-0.5, hi=0.5),
      kw={"pads": (1, 1, 1, 1), "groups": 2}, g=_nchw_conv_golden,
      tol=1e-4, tag="groups2"),
]

# ---- pooling configs ----
_x55 = F(2, 5, 5, 3)
CASES += [
    C("max_pooling2d", _x66, kw={"kernel": (3, 3), "stride": (1, 1),
                                 "padding": "SAME"},
      g=_tf_pool_golden("max"), grad=(0,), grad_sample=8,
      tag="k3-s1-same"),
    C("max_pooling2d", _x66, kw={"kernel": (3, 3), "stride": (2, 2),
                                 "padding": "SAME"},
      g=_tf_pool_golden("max"), tag="k3-s2-same-asym"),
    C("avg_pooling2d", _x66, kw={"kernel": (3, 3), "stride": (1, 1),
                                 "padding": "SAME"},
      g=_tf_pool_golden("avg"), tol=1e-5, grad=(0,), grad_sample=8,
      tag="k3-s1-same"),
    C("avg_pooling2d", _x55, kw={"kernel": (2, 2), "stride": (2, 2),
                                 "padding": "SAME"},
      g=_tf_pool_golden("avg"), tol=1e-5, tag="k2-s2-same-asym"),
    C("max_pooling1d", F(2, 8, 3), kw={"kernel": 3, "stride": 1,
                                       "padding": "SAME"},
      g=lambda x, kernel=2, stride=2, padding="VALID": __import__(
          "torch.nn.functional", fromlist=["max_pool1d"]).max_pool1d(
          __import__("torch").from_numpy(
              x.transpose(0, 2, 1)).double(), kernel, stride,
          padding=1).numpy().transpose(0, 2, 1), tag="k3-s1-same"),
    C("avg_pooling1d", F(2, 8, 3), kw={"kernel": 3, "stride": 1,
                                       "padding": "SAME"},
      g=lambda x, kernel=2, stride=2, padding="VALID": _tf().nn.avg_pool1d(
          x.astype(np.float64), kernel, stride, "SAME").numpy(),
      tol=1e-5, tag="k3-s1-same"),
    C("max_pooling3d", F(1, 4, 4, 4, 2), kw={"kernel": (2, 2, 2),
                                             "stride": (1, 1, 1),
                                             "padding": "VALID"},
      g=_win_pool3d_golden("max"), tag="k2-s1-valid"),
    C("avg_pooling3d", F(1, 4, 4, 4, 2), kw={"kernel": (2, 2, 2),
                                             "stride": (1, 1, 1),
                                             "padding": "VALID"},
      g=_win_pool3d_golden("avg"), tol=1e-5, tag="k2-s1-valid"),
    C("pnorm_pool2d", FP(2, 4, 4, 3), kw={"p": 2},
      g=lambda x, kernel=(2, 2), stride=(2, 2), p=2, padding="VALID":
      np.sqrt((x.reshape(2, 2, 2, 2, 2, 3) ** 2).sum((2, 4))),
      tol=1e-4, tag="p2"),
    C("max_pool2d_nchw", F(2, 3, 6, 6), kw={"pads": (1, 1, 1, 1)},
      g=lambda x, kernel=(2, 2), stride=(2, 2), pads=(0, 0, 0, 0):
      __import__("torch.nn.functional", fromlist=["max_pool2d"])
      .max_pool2d(__import__("torch").from_numpy(x).double(), kernel,
                  stride, padding=1).numpy(), tag="pads1"),
    C("avg_pool2d_nchw", F(2, 3, 6, 6),
      g=lambda x, kernel=(2, 2), stride=(2, 2), pads=(0, 0, 0, 0),
      count_include_pad=False: x.reshape(2, 3, 3, 2, 3, 2).mean((3, 5)),
      tol=1e-5, tag="valid"),
    C("upsampling2d", F(2, 3, 3, 2), kw={"scale": 3},
      g=lambda x, scale=2: np.repeat(np.repeat(x, scale, 1), scale, 2),
      tag="scale3"),
    C("upsampling3d", F(1, 2, 2, 2, 2), kw={"size": 3},
      g=lambda x, size=2: np.repeat(np.repeat(np.repeat(
          x, size, 1), size, 2), size, 3), tag="size3"),
    C("lrn", F(2, 4, 4, 8),
      g=lambda x, k=2.0, n=5, alpha=1e-4, beta=0.75: __import__(
          "torch.nn.functional", fromlist=["local_response_norm"])
      .local_response_norm(
          __import__("torch").from_numpy(
              x.transpose(0, 3, 1, 2)).double(), n, alpha * n, beta, k)
      .numpy().transpose(0, 2, 3, 1), tol=1e-4, tag="defaults"),
]

# ---- normalization configs ----
CASES += [
    C("batch_norm", F(2, 3, 3, 4), F(4), FP(4, lo=0.5, hi=2.0),
      kw={"eps": 1e-3},
      g=lambda x, m, v, gamma=None, beta=None, eps=1e-5:
      (x - m) / np.sqrt(v + eps), tol=1e-5, tag="4d-noaffine"),
    C("batch_norm_nchw", F(2, 4, 3, 3), FP(4), F(4), F(4),
      FP(4, lo=0.5, hi=2.0), kw={"eps": 1e-2},
      g=lambda x, s, b, m, v, eps=1e-5: __import__(
          "torch.nn.functional", fromlist=["batch_norm"]).batch_norm(
          __import__("torch").from_numpy(x).double(),
          __import__("torch").from_numpy(m).double(),
          __import__("torch").from_numpy(v).double(),
          __import__("torch").from_numpy(s).double(),
          __import__("torch").from_numpy(b).double(),
          False, 0.0, eps).numpy(), tol=1e-4, tag="eps1e-2"),
    C("fused_batch_norm", F(3, 2, 2, 5), FP(5), F(5), kw={"eps": 1e-2},
      g=None, check=None, tag="eps1e-2",
      custom=lambda fn: np.testing.assert_allclose(
          np.asarray(fn(_FBN_X, _FBN_S, _FBN_O, eps=1e-2)[0]),
          _FBN_S * (_FBN_X - _FBN_X.mean((0, 1, 2)))
          / np.sqrt(_FBN_X.var((0, 1, 2)) + 1e-2) + _FBN_O, atol=1e-4)),
]
_FBN_X, _FBN_S, _FBN_O = F(3, 2, 2, 5), FP(5), F(5)

# ---- resize configs (downscale exercises the antialias kernel path) ----
_r55 = F(1, 5, 5, 2)
CASES += [
    C("resize_bilinear", _r55, kw={"size": (3, 3)},
      g=lambda x, size: _tf_resize_golden("bilinear")(x, size),
      tol=1e-4, grad=(0,), grad_sample=8, tag="downscale"),
    C("resize_bilinear", F(1, 4, 4, 2), kw={"size": (7, 5)},
      g=lambda x, size: _tf_resize_golden("bilinear")(x, size),
      tol=1e-4, tag="upscale-noninteger"),
    C("resize_nearest", F(1, 4, 4, 2), kw={"size": (8, 8)},
      g=lambda x, size: _tf_resize_golden("nearest", False)(x, size),
      tag="upscale"),
    C("resize_bicubic", _r55, kw={"size": (3, 3)},
      g=lambda x, size: _tf_resize_golden("bicubic")(x, size),
      tol=1e-3, tag="downscale"),
    C("resize_lanczos", _r55, kw={"size": (3, 3)},
      g=lambda x, size: _tf_resize_golden("lanczos3")(x, size),
      tol=1e-3, tag="downscale"),
    C("image_resize", F(1, 3, 3, 2), kw={"size": (6, 6),
                                         "method": "nearest"},
      g=lambda x, size, method: _tf_resize_golden("nearest", False)(
          x, size), tag="nearest"),
]

# ---- recurrent configs (different shapes, optional states/biases) ----
CASES += [
    C("lstm_cell", F(1, 2), F(1, 3), F(1, 3),
      F(2, 12, lo=-0.5, hi=0.5), F(3, 12, lo=-0.5, hi=0.5),
      g=lambda x, h, c, wi, wh: _lstm_cell_golden(x, h, c, wi, wh),
      tol=1e-4, tag="nobias"),
    C("gru_cell", F(3, 4), F(3, 2),
      F(4, 6, lo=-0.5, hi=0.5), F(2, 6, lo=-0.5, hi=0.5),
      g=lambda x, h, wi, wh: _gru_cell_golden(x, h, wi, wh),
      tol=1e-4, tag="nobias"),
    C("lstm_layer", F(1, 3, 2), F(2, 12, lo=-0.5, hi=0.5),
      F(3, 12, lo=-0.5, hi=0.5), F(12, lo=-0.5, hi=0.5),
      g=_lstm_layer_golden, tol=1e-4, tag="h3"),
    C("lstm_layer_full", F(3, 2, 4), F(4, 8, lo=-0.5, hi=0.5),
      F(2, 8, lo=-0.5, hi=0.5), F(8, lo=-0.5, hi=0.5),
      g=_lstm_layer_full_golden, tol=1e-4, tag="h2"),
    C("gru_layer", F(2, 3, 3), F(2, 3, lo=-0.5, hi=0.5),
      F(3, 9, lo=-0.5, hi=0.5), F(3, 9, lo=-0.5, hi=0.5),
      F(9, lo=-0.5, hi=0.5), F(9, lo=-0.5, hi=0.5),
      g=_gru_layer_golden, tol=1e-4, tag="h0"),
    C("dynamic_rnn", F(2, 4, 3), F(3, 4, lo=-0.5, hi=0.5),
      F(4, 4, lo=-0.5, hi=0.5), F(4, lo=-0.5, hi=0.5),
      kw={"h0": F(2, 4, lo=-0.5, hi=0.5),
          "seq_lengths": np.asarray([1, 4], np.int32)},
      g=lambda x, w, rw, b=None, h0=None, seq_lengths=None:
      _rnn_golden(x, w, rw, b, h0, seq_lengths), tol=1e-4,
      tag="h0-ragged"),
    C("static_rnn", F(2, 3, 3), F(3, 4, lo=-0.5, hi=0.5),
      F(4, 4, lo=-0.5, hi=0.5), F(4, lo=-0.5, hi=0.5),
      kw={"h0": F(2, 4, lo=-0.5, hi=0.5)},
      g=lambda x, w, rw, b=None, h0=None:
      _rnn_golden(x, w, rw, b, h0), tol=1e-4, tag="h0"),
    C("sru_layer", F(2, 2, 2), np.zeros((2, 2), np.float32),
      F(2, 6, lo=-0.5, hi=0.5), F(4, lo=-0.5, hi=0.5),
      g=lambda x, c0, w, b: _sru_golden(x, c0, w, b), tol=1e-4,
      tag="h2"),
]

#: ops that MUST carry >=2 value-checked configs (the gate in
#: test_op_validation.py) — the stride/dilation/padding/layout-sensitive
#: families where single-config passes hide convention bugs.
CONFIG_CRITICAL = [
    "conv2d", "conv1d", "conv3d", "depthwise_conv2d", "separable_conv2d",
    "pointwise_conv2d", "deconv2d", "conv2d_nchw", "deconv2d_nchw",
    "max_pooling2d", "avg_pooling2d", "max_pooling1d", "avg_pooling1d",
    "max_pooling3d", "avg_pooling3d", "pnorm_pool2d", "max_pool2d_nchw",
    "avg_pool2d_nchw", "upsampling2d", "upsampling3d", "lrn",
    "batch_norm", "batch_norm_nchw", "fused_batch_norm",
    "resize_bilinear", "resize_nearest", "resize_bicubic",
    "resize_lanczos", "image_resize", "lstm_cell", "gru_cell",
    "lstm_layer", "lstm_layer_full", "gru_layer", "dynamic_rnn",
    "static_rnn", "sru_layer",
]
