"""Cross-host fleet federation contract (ISSUE 14 acceptance): the
generation-fenced membership protocol (crash / partition / straggler
host, slow-host negative control), cross-host failover with deadline
budget carry, stale-dispatch fencing (counted, never delivered),
replicated-snapshot warm re-placement incl. corruption fallback to an
older generation, JOIN re-admission with the snapshot offered back, the
federation degraded ladder, `HostChaos` units, and the arrival-rate
forecaster.  One real multi-process run (`mh_worker_federation.py`) and
the full `bench.py --federation --quick` gate ride the slow lane."""
import json
import os
import subprocess
import sys
import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

from deeplearning4j_tpu.monitor.forecast import (ArrivalRateForecaster,
                                                 HoltForecaster)
from deeplearning4j_tpu.monitor.registry import MetricsRegistry
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import (DeadlineExceededError,
                                        FederationPolicy, FederationRouter,
                                        HostAgent, HostLostError,
                                        LatencySLO, ModelFleet,
                                        RejectedError, SnapshotCorruptError,
                                        select_snapshot)
from deeplearning4j_tpu.serving.federation import _rendezvous
from deeplearning4j_tpu.train.updaters import Sgd
from deeplearning4j_tpu.utils.chaos import HostChaos

HERE = os.path.dirname(os.path.abspath(__file__))


def _net(seed=0, n_in=8, n_out=3, hidden=16):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=hidden, activation="relu"),
                   OutputLayer(n_out=n_out, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _x(n=2, n_in=8, seed=0):
    return np.random.RandomState(seed).randn(n, n_in).astype(np.float32)


def _policy(**kw):
    kw.setdefault("heartbeat_interval_s", 0.05)
    kw.setdefault("failure_deadline_s", 0.4)
    kw.setdefault("straggler_deadline_s", 2.0)
    kw.setdefault("ghost_linger_s", 3.0)
    return FederationPolicy(**kw)


def _host_fleet(tmp_path, host_id, models=(("m", 5),)):
    d = tmp_path / host_id
    d.mkdir(exist_ok=True)
    fleet = ModelFleet(max_resident=2, n_slices=2, max_batch=4,
                       batch_timeout_ms=1.0,
                       cache_dir=str(tmp_path / "exec-cache"),
                       snapshot_path=str(d / "snapshot.json"),
                       host_id=host_id)
    for name, prio in models:
        fleet.deploy(name, _net(seed=hash(name) % 97),
                     slo=LatencySLO(target_p99_ms=2000.0, priority=prio),
                     warm=True)
    return fleet


@contextmanager
def _federation(tmp_path, hosts=("h1", "h2"), policy=None,
                models=(("m", 5),), replicate=True, reg=None):
    """Router + one in-process HostAgent-wrapped fleet per host id; all
    hosts share one AOT cache dir (the warm re-placement substrate)."""
    policy = policy if policy is not None else _policy()
    reg = reg if reg is not None else MetricsRegistry()
    router = FederationRouter(policy,
                              replicas_dir=str(tmp_path / "router-replicas"),
                              registry_=reg)
    fleets, agents = {}, {}
    try:
        port = router.start(0)
        for h in hosts:
            fleets[h] = _host_fleet(tmp_path, h, models=models)
            agents[h] = HostAgent(
                h, fleets[h], ("127.0.0.1", port), policy=policy,
                replicas_dir=str(tmp_path / h / "replicas"),
                registry_=reg).start()
        if replicate:
            for h in hosts:
                fleets[h].save_snapshot()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if set(router.federation_stats()["replicas"]) >= set(hosts):
                    break
                time.sleep(0.02)
            else:
                raise RuntimeError("snapshot replication never completed")
        yield router, fleets, agents
    finally:
        for a in agents.values():
            try:
                a.close()
            except Exception:
                pass
        router.shutdown()
        for f in fleets.values():
            try:
                f.shutdown()
            except Exception:
                pass


def _wait(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _events(router, kind):
    return [e for e in list(router.events) if e["event"] == kind]


# ---------------------------------------------------------------------------
# Membership: join, serve, introspection
# ---------------------------------------------------------------------------

def test_membership_join_and_serve(tmp_path):
    with _federation(tmp_path) as (router, fleets, agents):
        assert router.hosts() == ["h1", "h2"]
        assert router.generation == 2            # one bump per admission
        for a in agents.values():
            assert a.generation == router.generation \
                or a.generation == 1             # h1 joined at gen 1
        y = router.output("m", _x(), deadline_ms=30_000.0, timeout=60)
        assert y.shape == (2, 3)
        stats = router.federation_stats()
        assert set(stats["hosts"]) == {"h1", "h2"}
        assert stats["hosts"]["h1"]["models"] == ["m"]
        hz = router.healthz()
        assert hz["ok"] and hz["hosts"] == 2
        assert hz["degraded_mode"] == "full"
        # instruments: membership gauges track the live view
        assert router.instruments.hosts.value == 2
        assert router.instruments.generation.value == 2


def test_unknown_model_and_shutdown_reject(tmp_path):
    with _federation(tmp_path, replicate=False) as (router, _, _a):
        # an unknown model still routes (hosts may admit lazily) but the
        # host classifies it as a CLIENT error — surfaced as ValueError,
        # never a failover storm
        with pytest.raises(ValueError):
            router.output("ghost-model", _x(), deadline_ms=5_000.0,
                          timeout=60)
        saved = router
    with pytest.raises(RejectedError):
        saved.submit("m", _x())                  # shut-down router rejects


# ---------------------------------------------------------------------------
# Failure taxonomy: crash / partition / straggler / slow control
# ---------------------------------------------------------------------------

def test_crash_eviction_failover_and_warm_replacement(tmp_path):
    with _federation(tmp_path) as (router, fleets, agents):
        HostChaos(mode="kill").fire(agents["h1"])
        _wait(lambda: _events(router, "evict"), msg="crash eviction")
        ev = _events(router, "evict")[0]
        assert ev["host"] == "h1" and ev["cause"] == "crash"
        assert router.hosts() == ["h2"]
        # h1's models are warm-re-placed on the survivor from the
        # replicated snapshot: zero fresh compiles (shared AOT cache)
        _wait(lambda: _events(router, "replaced"), msg="re-placement")
        rep = _events(router, "replaced")[0]
        assert rep["host"] == "h1" and rep["on"] == "h2"
        assert rep["warm"] and rep["fresh_compiles"] == 0
        assert router.output("m", _x(), deadline_ms=30_000.0,
                             timeout=60).shape == (2, 3)
        assert router.instruments.evictions("crash").value == 1
        assert router.instruments._replacements[True].value == 1


def test_partition_eviction_stale_fence_and_rejoin(tmp_path):
    with _federation(tmp_path) as (router, fleets, agents):
        victim = _rendezvous(["h1", "h2"], "m")  # the host serving "m"
        agent = agents[victim]
        gen0 = router.generation
        # an in-flight request is mid-dispatch on the victim when the
        # partition hits: its reply is deferred, the router must fail it
        # over to the survivor — and fence the deferred reply on heal
        chaos = HostChaos(mode="partition", at_dispatch=0, duration_s=1.2)
        chaos.arm(agent)
        fut = router.submit("m", _x(), deadline_ms=30_000.0)
        assert fut.result(timeout=60).shape == (2, 3)   # settled via failover
        _wait(lambda: _events(router, "evict"), msg="partition eviction")
        ev = _events(router, "evict")[0]
        assert ev["host"] == victim and ev["cause"] == "partition"
        # detection is heartbeat-driven: bounded by the failure deadline
        # (+ generous scheduler slack)
        assert ev["detection_ms"] <= 5_000.0
        # heal: the deferred stale reply arrives at the OLD generation —
        # fenced and counted, never delivered
        _wait(lambda: router.instruments.stale_dispatch.value >= 1,
              msg="stale reply fenced")
        assert _events(router, "stale-fenced")
        # the healed host auto-rejoins at a bumped generation
        _wait(lambda: victim in router.hosts() and agent.rejoins >= 1,
              msg="auto-rejoin")
        assert router.generation > gen0 + 1      # evict bump + rejoin bump
        _wait(lambda: agent.generation == router.generation,
              msg="agent caught up")
        assert router.output("m", _x(), deadline_ms=30_000.0,
                             timeout=60).shape == (2, 3)
        chaos.restore()


def test_straggler_eviction_via_hang(tmp_path):
    policy = _policy(straggler_deadline_s=0.6, failure_deadline_s=5.0)
    with _federation(tmp_path, policy=policy) as (router, fleets, agents):
        victim = _rendezvous(["h1", "h2"], "m")
        chaos = HostChaos(mode="hang", at_dispatch=0, duration_s=3.0)
        chaos.arm(agents[victim])
        # heartbeats keep flowing — only the straggler detector can see
        # this fault; the stuck request must still settle via failover
        fut = router.submit("m", _x(), deadline_ms=30_000.0)
        assert fut.result(timeout=60).shape == (2, 3)
        _wait(lambda: _events(router, "evict"), msg="straggler eviction")
        ev = _events(router, "evict")[0]
        assert ev["host"] == victim and ev["cause"] == "straggler"
        chaos.restore()


def test_slow_host_is_not_evicted(tmp_path):
    """Negative control: a uniformly slow host stays under every failure
    deadline — chaos fires, nothing is evicted."""
    with _federation(tmp_path) as (router, fleets, agents):
        chaos = HostChaos(mode="slow", at_dispatch=0, delay_s=0.03)
        chaos.arm(agents["h1"])
        chaos2 = HostChaos(mode="slow", at_dispatch=0, delay_s=0.03)
        chaos2.arm(agents["h2"])
        for i in range(8):
            assert router.output("m", _x(seed=i), deadline_ms=30_000.0,
                                 timeout=60).shape == (2, 3)
        assert chaos.fired or chaos2.fired
        time.sleep(0.6)                          # several failure deadlines
        assert router.hosts() == ["h1", "h2"]
        assert not _events(router, "evict")
        chaos.restore()
        chaos2.restore()


# ---------------------------------------------------------------------------
# Cross-host failover: budget carry, exhaustion, HostLostError
# ---------------------------------------------------------------------------

def test_failover_carries_remaining_deadline_budget(tmp_path):
    with _federation(tmp_path) as (router, fleets, agents):
        victim = _rendezvous(["h1", "h2"], "m")
        survivor = "h2" if victim == "h1" else "h1"
        seen = []
        orig = fleets[survivor].submit

        def spy(name, x, **kw):
            seen.append(kw.get("deadline_ms"))
            return orig(name, x, **kw)

        fleets[survivor].submit = spy
        # a PARTITION (not a crash): the victim goes silent but its
        # socket stays connected, so the dispatch genuinely lands on it
        # and only the heartbeat deadline can trigger the failover
        agents[victim].partition(True)
        t0 = time.monotonic()
        fut = router.submit("m", _x(), priority=5, deadline_ms=8_000.0)
        assert fut.result(timeout=60).shape == (2, 3)
        assert router.instruments.cross_host_failovers.value >= 1
        # the re-dispatch carried the REMAINING budget, not a fresh one
        assert seen and seen[-1] is not None
        elapsed_ms = (time.monotonic() - t0) * 1000.0
        assert seen[-1] < 8_000.0
        assert seen[-1] >= 8_000.0 - elapsed_ms - 1_000.0
        fleets[survivor].submit = orig
        agents[victim].partition(False)


def test_failover_budget_exhaustion_is_deadline_exceeded(tmp_path):
    with _federation(tmp_path) as (router, fleets, agents):
        victim = _rendezvous(["h1", "h2"], "m")
        agents[victim].partition(True)
        # a budget far smaller than the failure deadline: by the time the
        # silence is detected and the orphan fails over, it is exhausted
        fut = router.submit("m", _x(), deadline_ms=30.0)
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=60)
        agents[victim].partition(False)


def test_failover_cap_is_host_lost(tmp_path):
    policy = _policy(max_failovers=0)
    with _federation(tmp_path, policy=policy) as (router, fleets, agents):
        victim = _rendezvous(["h1", "h2"], "m")
        agents[victim].partition(True)
        fut = router.submit("m", _x(), deadline_ms=30_000.0)
        with pytest.raises(HostLostError):
            fut.result(timeout=60)
        agents[victim].partition(False)


# ---------------------------------------------------------------------------
# Replicated snapshots: on-disk copies, corruption fallback, restore paths
# ---------------------------------------------------------------------------

def test_snapshot_replication_router_and_peers(tmp_path):
    with _federation(tmp_path) as (router, fleets, agents):
        stats = router.federation_stats()
        assert set(stats["replicas"]) == {"h1", "h2"}
        router_files = os.listdir(str(tmp_path / "router-replicas"))
        assert any(f.startswith("h1-gen") for f in router_files)
        assert any(f.startswith("h2-gen") for f in router_files)
        # peer forwarding: each host also holds its PEER's copy, so the
        # fleet survives losing the router and a host together
        _wait(lambda: os.path.isdir(str(tmp_path / "h2" / "replicas"))
              and any(f.startswith("h1-gen") for f in
                      os.listdir(str(tmp_path / "h2" / "replicas"))),
              msg="peer replica of h1 on h2")


def test_select_snapshot_prefers_highest_intact_generation(tmp_path):
    fleet = _host_fleet(tmp_path, "hA")
    try:
        snap = fleet.snapshotter
        copies = []
        for gen in (1, 2, 3):
            snap.generation = gen
            p = snap.save()
            dst = str(tmp_path / f"copy-gen{gen}.json")
            with open(p) as f, open(dst, "w") as g:
                g.write(f.read())
            copies.append(dst)
        # newest copy is torn mid-write: fall back to generation 2
        with open(copies[2], "w") as f:
            f.write('{"format": 1, "fleet": {"trunc')
        path, payload = select_snapshot(copies)
        assert path == copies[1]
        assert payload["generation"] == 2
        assert payload["host_id"] == "hA"
        # every copy rotten -> explicit SnapshotCorruptError
        for p in copies:
            with open(p, "w") as f:
                f.write("garbage")
        with pytest.raises(SnapshotCorruptError):
            select_snapshot(copies)
    finally:
        fleet.shutdown()


def test_restore_snapshot_from_replicated_paths(tmp_path):
    fleet = _host_fleet(tmp_path, "hA")
    fleet.output("m", _x(), deadline_ms=30_000.0, timeout=60)
    fleet.snapshotter.generation = 4
    path = fleet.save_snapshot()
    fleet.shutdown()
    fleet2 = _host_fleet(tmp_path, "hB")
    try:
        restore = fleet2.restore_snapshot(paths=[path])
        assert restore["fresh_compiles"] == 0    # shared AOT cache: warm
        assert fleet2.pool.resident_names() == ["m"]
    finally:
        fleet2.shutdown()


def test_snapshot_header_stamp_and_age_clamped_under_skew(tmp_path):
    fleet = _host_fleet(tmp_path, "hA")
    try:
        snap = fleet.snapshotter
        assert snap.host_id == "hA"
        snap.generation = 7
        p = snap.save()
        with open(p) as f:
            payload = json.load(f)
        assert payload["host_id"] == "hA"
        assert payload["generation"] == 7
        assert snap.age_s() >= 0.0
        # a replica stamped by a skew-AHEAD clock (saved_at in the
        # future): a fresh snapshotter seeds its age from the file and
        # must clamp at zero, never report negative
        payload["saved_at"] = time.time() + 3_600.0   # header not crc'd
        with open(p, "w") as f:
            json.dump(payload, f)
        from deeplearning4j_tpu.serving.resilience import FleetSnapshotter
        snap2 = FleetSnapshotter(fleet, p, host_id="hA")
        assert snap2.age_s() == 0.0
    finally:
        fleet.shutdown()


# ---------------------------------------------------------------------------
# JOIN re-admission: relaunched host, snapshot offered back, parked joiners
# ---------------------------------------------------------------------------

def test_relaunched_host_readmitted_with_snapshot(tmp_path):
    with _federation(tmp_path) as (router, fleets, agents):
        HostChaos(mode="kill").fire(agents["h1"])
        _wait(lambda: _events(router, "replaced"), msg="re-placement")
        gen0 = router.generation
        # relaunch: same host id, a FRESH fleet process (cold members,
        # same shared cache).  WELCOME offers the replicated snapshot
        # back, so the relaunched host re-admits warm.
        fleet_b = ModelFleet(max_resident=2, n_slices=2, max_batch=4,
                             batch_timeout_ms=1.0,
                             cache_dir=str(tmp_path / "exec-cache"),
                             snapshot_path=str(tmp_path / "h1b.json"),
                             host_id="h1")
        fleet_b.deploy("m", _net(seed=hash("m") % 97),
                       slo=LatencySLO(target_p99_ms=2000.0, priority=5))
        agent_b = HostAgent("h1", fleet_b, ("127.0.0.1", router.port),
                            policy=router.policy)
        try:
            agent_b.start(timeout=15.0)
            assert router.generation > gen0      # re-admitted at a bump
            assert agent_b.generation == router.generation
            join = [e for e in _events(router, "join")
                    if e["host"] == "h1" and e.get("rejoin")]
            assert join, "rejoin JOIN not recorded"
            # the WELCOME snapshot restored its preferred placements warm
            assert agent_b.restored is not None
            assert agent_b.restored["fresh_compiles"] == 0
            assert fleet_b.pool.resident_names() == ["m"]
            assert sorted(router.hosts()) == ["h1", "h2"]
        finally:
            agent_b.close()
            fleet_b.shutdown()


def test_auto_admit_false_parks_joiners(tmp_path):
    policy = _policy(auto_admit=False)
    reg = MetricsRegistry()
    router = FederationRouter(policy, registry_=reg)
    fleet = _host_fleet(tmp_path, "h1")
    agent = HostAgent("h1", fleet, ("127.0.0.1", 0), policy=policy,
                      registry_=reg)
    try:
        agent.address = ("127.0.0.1", router.start(0))
        errors = []

        def run():
            try:
                agent.start(timeout=30.0)
            except Exception as e:               # pragma: no cover
                errors.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        _wait(lambda: router._joiners, msg="parked joiner")
        assert router.hosts() == []              # parked, NOT admitted
        assert router.admit_joiners() == 1
        t.join(timeout=30.0)
        assert not errors
        assert router.hosts() == ["h1"]
        assert agent.generation == router.generation
    finally:
        agent.close()
        router.shutdown()
        fleet.shutdown()


# ---------------------------------------------------------------------------
# Federation degraded ladder
# ---------------------------------------------------------------------------

def test_federation_ladder_sheds_low_priority_under_host_pressure(tmp_path):
    policy = _policy(ladder_down_after=2, ladder_up_after=2)
    models = (("hi", 10), ("lo", 0))
    with _federation(tmp_path, policy=policy, models=models,
                     replicate=False) as (router, fleets, agents):
        # no replicated snapshot -> the lost host CANNOT be re-placed;
        # capacity stays short and the ladder walks down to shed_floor
        agents["h1"].crash()
        _wait(lambda: router.ladder.shed_floor(), timeout=15.0,
              msg="ladder reached shed floor")
        with pytest.raises(RejectedError):
            router.submit("lo", _x(), priority=0, deadline_ms=5_000.0)
        y = router.output("hi", _x(), priority=10, deadline_ms=30_000.0,
                          timeout=60)
        assert y.shape == (2, 3)                 # top class still served
        skipped = _events(router, "replace-skipped")
        assert skipped and skipped[0]["reason"] == "no snapshot"


# ---------------------------------------------------------------------------
# HostChaos units
# ---------------------------------------------------------------------------

def test_host_chaos_validates_mode():
    with pytest.raises(ValueError):
        HostChaos(mode="meteor")


def test_host_chaos_marker_is_one_shot(tmp_path):
    marker = str(tmp_path / "fired")

    class StubAgent:
        def __init__(self):
            self.slowed = []

        def slow(self, d):
            self.slowed.append(d)

    stub = StubAgent()
    chaos = HostChaos(mode="slow", delay_s=0.01, marker=marker)
    assert chaos.armed()
    chaos.fire(stub)
    assert stub.slowed == [0.01]
    assert os.path.exists(marker)
    with open(marker) as f:
        assert f.read().startswith("slow@")
    # a relaunched process re-arming against the same marker stays inert
    chaos2 = HostChaos(mode="slow", delay_s=0.01, marker=marker)
    assert not chaos2.armed()


def test_host_chaos_arm_wraps_and_restore_unwraps(tmp_path):
    class StubAgent:
        def __init__(self):
            self.requests = []

        def _on_request(self, gen, msg, raw):
            self.requests.append(msg)
            return "handled"

        def slow(self, d):
            self.delay = d

    stub = StubAgent()
    chaos = HostChaos(mode="slow", at_dispatch=1, delay_s=0.02)
    chaos.arm(stub)
    with pytest.raises(RuntimeError):
        chaos.arm(stub)                          # double-arm refused
    assert stub._on_request(3, {"id": 1}, b"") == "handled"
    assert not chaos.fired                       # at_dispatch not reached
    assert stub._on_request(3, {"id": 2}, b"") == "handled"
    assert chaos.fired and stub.delay == 0.02    # fired AND passed through
    chaos.restore()
    assert stub.delay == 0.0                     # slow-mode delay cleared
    assert len(stub.requests) == 2


# ---------------------------------------------------------------------------
# Arrival-rate forecaster
# ---------------------------------------------------------------------------

def test_holt_forecaster_ewma_and_trend():
    with pytest.raises(ValueError):
        HoltForecaster(alpha=0.0)
    with pytest.raises(ValueError):
        HoltForecaster(beta=1.5)
    # beta=0: plain EWMA, trend pinned at zero
    ewma = HoltForecaster(alpha=0.5, beta=0.0)
    assert ewma.forecast() == 0.0                # no data yet
    ewma.observe(0.0)
    ewma.observe(10.0)
    assert ewma.forecast() == pytest.approx(5.0)
    assert ewma.trend == 0.0
    # a steady upward series: Holt extrapolates ABOVE the last level
    holt = HoltForecaster(alpha=0.5, beta=0.3)
    for v in (1.0, 2.0, 3.0, 4.0, 5.0):
        holt.observe(v)
    assert holt.forecast(1.0) > holt.level
    assert holt.forecast(5.0) > holt.forecast(1.0)
    # a declining series extrapolates negative — floored at zero
    down = HoltForecaster(alpha=0.5, beta=0.5)
    for v in (10.0, 8.0, 6.0, 4.0, 2.0, 0.0):
        down.observe(v)
    assert down.trend < 0.0
    assert down.forecast(5.0) == 0.0


def test_arrival_rate_forecaster_ticks_from_registry_counters():
    reg = MetricsRegistry()
    c_a = reg.counter("fleet_requests_total", labels={"model": "a"})
    fc = ArrivalRateForecaster(registry_=reg, alpha=1.0, beta=0.0,
                               horizon_s=10.0)
    c_a.inc(100)                                 # historical traffic
    assert fc.tick(now=100.0) == {}              # first sighting: baseline
    c_a.inc(20)                                  # 20 req in 2 s -> 10 req/s
    out = fc.tick(now=102.0)
    assert out["a"] == pytest.approx(10.0)
    # published as a gauge the scrape endpoint exports
    children = reg.children("fleet_arrival_forecast")
    assert [(lbl["model"], g.value) for lbl, g in children] \
        == [("a", pytest.approx(10.0))]
    assert fc.forecasts() == {"a": pytest.approx(10.0)}
    # a model appearing later baselines without a burst misread
    c_b = reg.counter("fleet_requests_total", labels={"model": "b"})
    c_b.inc(1_000_000)
    out = fc.tick(now=104.0)
    assert "b" not in out                        # baselined, not a burst
    c_b.inc(10)
    out = fc.tick(now=105.0)
    assert out["b"] == pytest.approx(10.0)
    # idle model decays toward zero, never below
    out = fc.tick(now=106.0)
    assert out["a"] == 0.0


# ---------------------------------------------------------------------------
# Multi-process: a real host process hard-killed mid-flood (slow lane)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_multiprocess_host_kill_warm_replacement(tmp_path):
    """Three REAL host processes join an in-process router; the one that
    owns model "m" hard-kills itself (`os._exit(9)`) mid-flood.  The
    router must evict it (cause crash), settle every accepted request,
    warm-re-place its model on a survivor, and the survivors must report
    the bumped generation on shutdown."""
    policy = FederationPolicy(heartbeat_interval_s=0.1,
                              failure_deadline_s=0.8,
                              straggler_deadline_s=5.0)
    reg = MetricsRegistry()
    router = FederationRouter(
        policy, replicas_dir=str(tmp_path / "router-replicas"),
        registry_=reg)
    port = router.start(0)
    hosts = ["h1", "h2", "h3"]
    victim = _rendezvous(hosts, "m")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    root = os.path.dirname(HERE)
    procs = {}
    try:
        for h in hosts:
            kill_after = "2" if h == victim else "-1"
            procs[h] = subprocess.Popen(
                [sys.executable,
                 os.path.join(HERE, "mh_worker_federation.py"),
                 h, str(port), str(tmp_path), kill_after],
                cwd=root, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
        _wait(lambda: all(
            os.path.exists(str(tmp_path / f"{h}.ready")) for h in hosts),
            timeout=180.0, msg="all hosts ready")
        _wait(lambda: set(router.federation_stats()["replicas"])
              >= set(hosts), timeout=30.0, msg="snapshot replication")
        served = failed = 0
        for i in range(200):
            try:
                fut = router.submit("m", _x(seed=i), priority=5,
                                    deadline_ms=20_000.0)
            except RejectedError:
                continue
            if fut.exception(timeout=60) is None:
                served += 1
            else:
                failed += 1
            if _events(router, "replaced"):
                break
            time.sleep(0.02)
        assert failed == 0                       # zero lost accepted
        assert served > 0
        ev = _events(router, "evict")
        assert ev and ev[0]["host"] == victim and ev[0]["cause"] == "crash"
        rep = _events(router, "replaced")
        assert rep and rep[0]["host"] == victim
        assert rep[0]["warm"] and rep[0]["fresh_compiles"] == 0
        assert os.path.exists(str(tmp_path / f"{victim}.killed"))
        # wind down the survivors; they report the bumped generation
        # (as of BEFORE their own graceful leaves bump it further)
        gen_at_stop = router.generation
        assert gen_at_stop >= len(hosts) + 1     # 3 joins + >=1 eviction
        with open(str(tmp_path / "stop"), "w") as f:
            f.write("stop")
        survivors = [h for h in hosts if h != victim]
        for h in survivors:
            assert procs[h].wait(timeout=120) == 0, \
                procs[h].stdout.read()[-2000:]
        assert procs[victim].wait(timeout=120) == 9   # os._exit(9)
        for h in survivors:
            with open(str(tmp_path / f"{h}.done")) as f:
                done = json.load(f)
            # at least the post-eviction generation; a peer's own leave
            # REFORM may already have bumped it by the time done is cut
            assert done["generation"] >= gen_at_stop
            assert not done["evicted"]
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        router.shutdown()


# ---------------------------------------------------------------------------
# The tier-1 federation gate: bench.py --federation --quick (slow lane)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bench_federation_quick_gate():
    root = os.path.dirname(HERE)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"),
         "--federation", "--quick"],
        capture_output=True, text=True, timeout=600, cwd=root, env=env)
    assert p.returncode == 0, p.stderr[-2000:]
    line = json.loads(p.stdout.strip().splitlines()[-1])
    assert line["pass"] is True
    assert line["value"] == 0                    # lost accepted
    assert {"crash", "partition"} <= set(line["eviction_causes"])
    assert all(line["replacements_warm"])
    assert line["stale_fenced"] >= 1
    assert line["part_host_rejoins"] >= 1
    assert sorted(line["final_hosts"]) == ["h1", "h2", "h3"]
