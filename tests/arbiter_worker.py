"""Pod-arbiter journal-recovery worker (spawned by test_arbiter — NOT a
pytest file).

Phase ``run``: build a real seeded net + CheckpointManager, a
LocalElasticGang over slices [0, 1], a virtual-slice ModelFleet sharing
`workdir`, and a SliceArbiter with `HandoffChaos(target="arbiter",
mode="kill", at_phase="shrink")` hooked in — `to_serving()` journals the
phase-1 intent and the chaos hook `os._exit(9)`s the process with the
record durable and ZERO side effects executed.

Phase ``recover``: a fresh process over the SAME journal path — the
arbiter's constructor replays the in-flight handoff (the marker file
keeps the chaos from re-firing), the shrink + lease actually execute,
and the result JSON lets the parent assert single ownership, a counted
replay, and a coordinated checkpoint rewind.

argv: workdir phase(run|recover)
"""
import json
import os
import sys

import numpy as np

from deeplearning4j_tpu.monitor.registry import MetricsRegistry
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import ModelFleet
from deeplearning4j_tpu.serving.slo import ArbiterPolicy
from deeplearning4j_tpu.train.arbiter import LocalElasticGang, SliceArbiter
from deeplearning4j_tpu.train.resilience import CheckpointManager
from deeplearning4j_tpu.train.updaters import Sgd
from deeplearning4j_tpu.utils.chaos import HandoffChaos

workdir = sys.argv[1]
phase = sys.argv[2]
journal = os.path.join(workdir, "journal.json")
marker = os.path.join(workdir, "chaos_once")


def _net():
    conf = (NeuralNetConfiguration.builder().seed(11).updater(Sgd(0.1))
            .list([DenseLayer(n_out=8, activation="tanh"),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


net = _net()
manager = CheckpointManager(os.path.join(workdir, "ckpt"), keep_last=50,
                            save_every_steps=None)
# one real step so the checkpoint the shrink commits is non-trivial
rng = np.random.RandomState(3)
x = rng.randn(6, 4).astype(np.float32)
y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
net.fit(x, y)

gang = LocalElasticGang(net, manager, slices=[0, 1])
fleet = ModelFleet(max_resident=1, n_slices=1,
                   cache_dir=os.path.join(workdir, "exec-cache"),
                   registry_=MetricsRegistry())
arb = SliceArbiter(journal, training=gang, fleet=fleet,
                   policy=ArbiterPolicy(min_training_slices=1),
                   registry_=MetricsRegistry())

if phase == "run":
    arb.chaos = HandoffChaos(target="arbiter", mode="kill",
                             at_phase="shrink", marker=marker)
    arb.to_serving()                    # chaos kills us after phase-1
    print("UNREACHABLE: chaos did not fire", flush=True)
    sys.exit(3)

# phase == "recover": the constructor already replayed (recover=True)
result = {
    "recovered": arb.recovered,
    "describe": arb.describe(),
    "gang_held": gang.held_slices(),
    "gang_events": gang.events,
    "ckpt_latest": manager.latest_step(),
    "fleet_free": fleet._available_slices(),
    "marker_exists": os.path.exists(marker),
}
with open(os.path.join(workdir, "recover_result.json"), "w") as f:
    json.dump(result, f)
print("recover ok", flush=True)
