"""Finite-difference gradient checks (reference: GradientCheckTests family,
SURVEY.md §4 — central differences vs backprop in double precision)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import (
    BatchNormalizationLayer, ConvolutionLayer, DenseLayer, InputType,
    MultiLayerNetwork, NeuralNetConfiguration, OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.train import Sgd
from deeplearning4j_tpu.train.gradientcheck import check_gradients


def build_net(layers, input_type, seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Sgd(0.1)).weight_init("XAVIER")
            .dtype("float64")
            .list(layers).set_input_type(input_type).build())
    return MultiLayerNetwork(conf).init()


def score_fn_for(net, x, y):
    x = jnp.asarray(x, jnp.float64)
    y = jnp.asarray(y, jnp.float64)

    def score(params):
        return net._loss(params, net.state_, x, y, None)[0]

    return score


def test_mlp_gradients():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 4))
    y = np.eye(3)[rng.integers(0, 3, 8)]
    net = build_net([
        DenseLayer(n_out=6, activation="tanh"),
        OutputLayer(n_out=3, loss="mcxent", activation="softmax"),
    ], InputType.feed_forward(4))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=None, verbose=True)


def test_mlp_gradients_with_l1_l2():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 4))
    y = np.eye(2)[rng.integers(0, 2, 8)]
    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Sgd(0.1)).weight_init("XAVIER")
            .l1(0.01).l2(0.02).dtype("float64")
            .list([DenseLayer(n_out=5, activation="sigmoid"),
                   OutputLayer(n_out=2, loss="mcxent", activation="softmax")])
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=None, verbose=True)


def test_cnn_gradients():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 6, 6, 2))
    y = np.eye(2)[rng.integers(0, 2, 4)]
    net = build_net([
        ConvolutionLayer(n_out=3, kernel_size=3, activation="tanh",
                         weight_init="XAVIER"),
        SubsamplingLayer(kernel_size=2, stride=2),
        OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.convolutional(6, 6, 2))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=32, verbose=True)


def test_batchnorm_gradients():
    rng = np.random.default_rng(4)
    x = rng.normal(size=(8, 5))
    y = np.eye(2)[rng.integers(0, 2, 8)]
    net = build_net([
        DenseLayer(n_out=6, activation="identity"),
        BatchNormalizationLayer(),
        OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.feed_forward(5))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=None, verbose=True)


@pytest.mark.parametrize("loss,act", [
    ("mse", "identity"), ("l2", "identity"), ("l1", "tanh"),
    ("xent", "sigmoid"), ("negativeloglikelihood", "softmax"),
])
def test_loss_gradients(loss, act):
    rng = np.random.default_rng(5)
    x = rng.normal(size=(6, 3))
    if loss in ("xent",):
        y = (rng.random((6, 2)) > 0.5).astype(np.float64)
    elif loss == "negativeloglikelihood":
        y = np.eye(2)[rng.integers(0, 2, 6)]
    else:
        y = rng.normal(size=(6, 2))
    net = build_net([
        DenseLayer(n_out=4, activation="tanh"),
        OutputLayer(n_out=2, loss=loss, activation=act),
    ], InputType.feed_forward(3))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=None, verbose=True)


# ---------------------------------------------------------------------------
# Extended-layer gradient checks (conv3d, locally-connected, PReLU, center
# loss, separable conv) — the GradientCheckTests family widened
# ---------------------------------------------------------------------------

def test_conv3d_gradients():
    from deeplearning4j_tpu.nn import Convolution3DLayer, Subsampling3DLayer
    rng = np.random.default_rng(10)
    x = rng.normal(size=(2, 4, 4, 4, 2))
    y = np.eye(2)[rng.integers(0, 2, 2)]
    net = build_net([
        Convolution3DLayer(n_out=3, kernel_size=2, convolution_mode="Same",
                           activation="tanh"),
        Subsampling3DLayer(pooling_type="AVG", kernel_size=2, stride=2),
        OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.convolutional3d(4, 4, 4, 2))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=20)


def test_locally_connected_gradients():
    from deeplearning4j_tpu.nn import LocallyConnected2DLayer
    rng = np.random.default_rng(11)
    x = rng.normal(size=(3, 5, 5, 2))
    y = np.eye(2)[rng.integers(0, 2, 3)]
    net = build_net([
        LocallyConnected2DLayer(n_out=3, kernel_size=2, activation="tanh"),
        OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.convolutional(5, 5, 2))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=20)


def test_prelu_gradients():
    from deeplearning4j_tpu.nn import PReLULayer
    rng = np.random.default_rng(12)
    x = rng.normal(size=(6, 4))
    y = np.eye(2)[rng.integers(0, 2, 6)]
    net = build_net([
        DenseLayer(n_out=5, activation="identity"),
        PReLULayer(alpha_init=0.3),
        OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.feed_forward(4))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=None)


def test_center_loss_gradients():
    from deeplearning4j_tpu.nn import CenterLossOutputLayer
    rng = np.random.default_rng(13)
    x = rng.normal(size=(6, 4))
    y = np.eye(3)[rng.integers(0, 3, 6)]
    net = build_net([
        DenseLayer(n_out=5, activation="tanh"),
        CenterLossOutputLayer(n_out=3, lambda_=0.3),
    ], InputType.feed_forward(4))
    # seed centers off zero so their gradient is informative
    net.params_["layer_1"]["centers"] = jnp.asarray(
        rng.normal(size=(3, 5)) * 0.1)
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=None)


def test_separable_conv_gradients():
    from deeplearning4j_tpu.nn import SeparableConvolution2DLayer
    rng = np.random.default_rng(14)
    x = rng.normal(size=(2, 5, 5, 2))
    y = np.eye(2)[rng.integers(0, 2, 2)]
    net = build_net([
        SeparableConvolution2DLayer(n_out=3, kernel_size=3,
                                    convolution_mode="Same",
                                    activation="tanh"),
        OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.convolutional(5, 5, 2))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=20)


def test_capsnet_gradients():
    """CapsNet stack gradient check: dynamic routing is a fixed-iteration
    unrolled loop differentiated end-to-end."""
    from deeplearning4j_tpu.nn import (CapsuleLayer, CapsuleStrengthLayer,
                                       LossLayer, PrimaryCapsules)
    rng = np.random.default_rng(5)
    x = rng.random((4, 8, 8, 1))
    y = np.eye(2)[rng.integers(0, 2, 4)]
    net = build_net([
        PrimaryCapsules(capsules=2, capsule_dim=4, kernel_size=5, stride=2),
        CapsuleLayer(capsules=2, capsule_dim=4, routings=2),
        CapsuleStrengthLayer(),
        LossLayer(loss="mcxent", activation="softmax"),
    ], InputType.convolutional(8, 8, 1))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=12, verbose=True)


def test_samediff_custom_layer_gradients():
    import dataclasses

    from deeplearning4j_tpu.nn import SameDiffLayer, register_layer

    @register_layer
    @dataclasses.dataclass(kw_only=True)
    class _Bilinear(SameDiffLayer):
        n_out: int = 0

        def define_parameters(self, input_type):
            f = input_type.shape[-1]
            return {"W": (f, self.n_out), "U": (f, self.n_out)}

        def define_layer(self, params, x, mask=None):
            return jnp.tanh(x @ params["W"]) * (x @ params["U"])

        def get_output_type(self, input_type):
            return InputType.feed_forward(self.n_out)

    rng = np.random.default_rng(6)
    x = rng.normal(size=(6, 3))
    y = np.eye(2)[rng.integers(0, 2, 6)]
    net = build_net([
        _Bilinear(n_out=5),
        OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.feed_forward(3))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=None, verbose=True)


def test_shape_op_layers_gradients():
    """Reshape/Permute/RepeatVector/Flatten/TimeDistributed path: the
    shape pipeline is param-free but must route gradients exactly through
    to surrounding layers (round-3 layers, reference KerasReshape etc.)."""
    from deeplearning4j_tpu.nn import (FlattenLayer, PermuteLayer,
                                       RepeatVectorLayer, ReshapeLayer,
                                       TimeDistributed)
    rng = np.random.default_rng(5)
    x = rng.normal(size=(4, 6))
    y = np.eye(2)[rng.integers(0, 2, 4)]
    net = build_net([
        DenseLayer(n_out=8, activation="tanh"),
        RepeatVectorLayer(n=4),             # [B,4,8]
        TimeDistributed(underlying=DenseLayer(n_out=6, activation="tanh")),
        PermuteLayer(dims=(2, 1)),          # [B,6,4]
        ReshapeLayer(target_shape=(3, 8)),  # [B,3,8]
        FlattenLayer(),                     # [B,24]
        OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.feed_forward(6))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=None, verbose=True)


def test_bidirectional_return_last_gradients():
    from deeplearning4j_tpu.nn import Bidirectional, LSTM
    rng = np.random.default_rng(6)
    x = rng.normal(size=(3, 5, 4))
    y = np.eye(2)[rng.integers(0, 2, 3)]
    net = build_net([
        Bidirectional(fwd=LSTM(n_out=3), mode="CONCAT", return_last=True),
        OutputLayer(n_out=2, loss="mcxent", activation="softmax"),
    ], InputType.recurrent(4, 5))
    assert check_gradients(score_fn_for(net, x, y), net.params_,
                           max_params_per_leaf=8, verbose=True)
