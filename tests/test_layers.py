"""Layer shape/behavior tests (reference: platform-tests layer tests)."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalizationLayer, Convolution1DLayer,
    ConvolutionLayer, Deconvolution2DLayer, DenseLayer,
    DepthwiseConvolution2DLayer, DropoutLayer, EmbeddingLayer,
    EmbeddingSequenceLayer, GlobalPoolingLayer, InputType,
    LayerNormalizationLayer, LocalResponseNormalizationLayer,
    SeparableConvolution2DLayer, SubsamplingLayer, Upsampling2DLayer,
    ZeroPaddingLayer)

KEY = jax.random.PRNGKey(0)


def run(layer, input_type, x, train=False, rng=None):
    params, state, out_type = layer.initialize(KEY, input_type)
    y, _ = layer.apply(params, state, x, train=train, rng=rng)
    return y, out_type


def test_dense_shapes():
    x = jnp.ones((4, 10), jnp.float32)
    y, ot = run(DenseLayer(n_out=7, activation="relu", weight_init="XAVIER"),
                InputType.feed_forward(10), x)
    assert y.shape == (4, 7)
    assert ot.shape == (7,)


def test_dense_flattens_conv_input():
    x = jnp.ones((2, 4, 4, 3), jnp.float32)
    y, _ = run(DenseLayer(n_out=5, weight_init="XAVIER"),
               InputType.convolutional(4, 4, 3), x)
    assert y.shape == (2, 5)


def test_conv2d_valid_and_same():
    x = jnp.ones((2, 8, 8, 3), jnp.float32)
    y, ot = run(ConvolutionLayer(n_out=6, kernel_size=3, stride=1,
                                 weight_init="RELU"),
                InputType.convolutional(8, 8, 3), x)
    assert y.shape == (2, 6, 6, 6) and ot.shape == (6, 6, 6)
    y, ot = run(ConvolutionLayer(n_out=6, kernel_size=3, stride=2,
                                 convolution_mode="Same", weight_init="RELU"),
                InputType.convolutional(8, 8, 3), x)
    assert y.shape == (2, 4, 4, 6) and ot.shape == (4, 4, 6)


def test_conv1d():
    x = jnp.ones((2, 16, 4), jnp.float32)
    y, ot = run(Convolution1DLayer(n_out=8, kernel_size=3, weight_init="RELU"),
                InputType.recurrent(4, 16), x)
    assert y.shape == (2, 16, 8)
    assert ot.shape == (16, 8)


def test_depthwise_separable_deconv():
    x = jnp.ones((2, 8, 8, 4), jnp.float32)
    y, _ = run(DepthwiseConvolution2DLayer(depth_multiplier=2, kernel_size=3,
                                           weight_init="RELU"),
               InputType.convolutional(8, 8, 4), x)
    assert y.shape == (2, 6, 6, 8)
    y, _ = run(SeparableConvolution2DLayer(n_out=10, kernel_size=3,
                                           weight_init="RELU"),
               InputType.convolutional(8, 8, 4), x)
    assert y.shape == (2, 6, 6, 10)
    y, ot = run(Deconvolution2DLayer(n_out=3, kernel_size=2, stride=2,
                                     weight_init="RELU"),
                InputType.convolutional(8, 8, 4), x)
    assert y.shape == (2, 16, 16, 3) and ot.shape == (16, 16, 3)


def test_pooling_types():
    x = jnp.arange(2 * 4 * 4 * 2, dtype=jnp.float32).reshape(2, 4, 4, 2)
    for pt in ["MAX", "AVG", "SUM", "PNORM"]:
        y, ot = run(SubsamplingLayer(pooling_type=pt, kernel_size=2, stride=2),
                    InputType.convolutional(4, 4, 2), x)
        assert y.shape == (2, 2, 2, 2)
        assert ot.shape == (2, 2, 2)
    # max pool correctness on a known block
    y, _ = run(SubsamplingLayer(pooling_type="MAX", kernel_size=2, stride=2),
               InputType.convolutional(4, 4, 2), x)
    np.testing.assert_allclose(np.asarray(y)[0, 0, 0, 0], float(x[0, 1, 1, 0]))


def test_global_pooling():
    x = jnp.ones((2, 5, 5, 3), jnp.float32)
    y, ot = run(GlobalPoolingLayer(pooling_type="AVG"),
                InputType.convolutional(5, 5, 3), x)
    assert y.shape == (2, 3) and ot.shape == (3,)
    # masked time series
    layer = GlobalPoolingLayer(pooling_type="AVG")
    params, state, _ = layer.initialize(KEY, InputType.recurrent(3, 4))
    xs = jnp.ones((2, 4, 3))
    mask = jnp.array([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
    y, _ = layer.apply(params, state, xs, mask=mask)
    np.testing.assert_allclose(np.asarray(y), np.ones((2, 3)), rtol=1e-6)


def test_batchnorm_train_vs_eval():
    layer = BatchNormalizationLayer(decay=0.5)
    params, state, _ = layer.initialize(KEY, InputType.feed_forward(3))
    x = jnp.array(np.random.default_rng(0).normal(2.0, 3.0, (64, 3)), jnp.float32)
    y, new_state = layer.apply(params, state, x, train=True)
    # batch-normalized output ~ zero mean, unit var
    assert abs(float(jnp.mean(y))) < 1e-4
    assert abs(float(jnp.var(y)) - 1.0) < 0.05
    # running stats moved toward batch stats
    assert float(jnp.max(jnp.abs(new_state["mean"]))) > 0.5
    # eval mode uses running stats, not batch
    y_eval, st = layer.apply(params, new_state, x, train=False)
    assert st is new_state


def test_dropout_semantics():
    layer = DropoutLayer(dropout=0.5)  # retain prob 0.5 (reference semantics)
    params, state, _ = layer.initialize(KEY, InputType.feed_forward(1000))
    x = jnp.ones((4, 1000))
    y_eval, _ = layer.apply(params, state, x, train=False)
    np.testing.assert_allclose(np.asarray(y_eval), np.asarray(x))
    y_tr, _ = layer.apply(params, state, x, train=True, rng=jax.random.PRNGKey(1))
    kept = np.asarray(y_tr) > 0
    assert 0.4 < kept.mean() < 0.6
    # inverted scaling: kept entries are 1/p
    np.testing.assert_allclose(np.asarray(y_tr)[kept], 2.0, rtol=1e-6)


def test_embedding():
    x = jnp.array([1, 3, 2], jnp.int32)
    y, _ = run(EmbeddingLayer(n_in=10, n_out=4, weight_init="NORMAL"),
               InputType.feed_forward(1), x)
    assert y.shape == (3, 4)
    xs = jnp.array([[1, 2], [3, 4]], jnp.int32)
    y, ot = run(EmbeddingSequenceLayer(n_in=10, n_out=4, weight_init="NORMAL"),
                InputType.recurrent(1, 2), xs)
    assert y.shape == (2, 2, 4) and ot.kind == "recurrent"


def test_misc_layers():
    x = jnp.ones((2, 4, 4, 3))
    y, ot = run(Upsampling2DLayer(size=2), InputType.convolutional(4, 4, 3), x)
    assert y.shape == (2, 8, 8, 3)
    y, ot = run(ZeroPaddingLayer(padding=1), InputType.convolutional(4, 4, 3), x)
    assert y.shape == (2, 6, 6, 3) and ot.shape == (6, 6, 3)
    y, _ = run(LocalResponseNormalizationLayer(), InputType.convolutional(4, 4, 3), x)
    assert y.shape == x.shape
    y, _ = run(LayerNormalizationLayer(), InputType.feed_forward(3),
               jnp.ones((2, 3)))
    assert y.shape == (2, 3)
    y, _ = run(ActivationLayer(activation="relu"), InputType.feed_forward(3),
               jnp.array([[-1.0, 0.0, 2.0]]))
    np.testing.assert_allclose(np.asarray(y), [[0.0, 0.0, 2.0]])


# ---------------------------------------------------------------------------
# Extended layer zoo (VERDICT §2 layer-gap rows): 3-D conv/pool, cropping,
# locally-connected, PReLU, center loss
# ---------------------------------------------------------------------------

from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration, OutputLayer
from deeplearning4j_tpu.train.updaters import Sgd


def test_conv3d_and_pool3d_shapes_and_train():
    from deeplearning4j_tpu.nn import (Convolution3DLayer, OutputLayer,
                                       Subsampling3DLayer)
    rng = np.random.RandomState(0)
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
            .list([Convolution3DLayer(n_out=4, kernel_size=3,
                                      convolution_mode="Same",
                                      activation="relu"),
                   Subsampling3DLayer(pooling_type="MAX", kernel_size=2,
                                      stride=2),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.convolutional3d(8, 8, 8, 1)).build())
    net = MultiLayerNetwork(conf).init()
    x = rng.rand(4, 8, 8, 8, 1).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 4)]
    assert net.output(x).shape == (4, 2)
    s0 = net.score_for(x, y)
    for _ in range(10):
        net.fit(x, y)
    assert net.score_for(x, y) < s0


def test_deconv3d_upsamples():
    from deeplearning4j_tpu.nn import Deconvolution3DLayer
    import jax
    layer = Deconvolution3DLayer(n_out=3, kernel_size=2, stride=2,
                                 activation="identity")
    params, state, out_t = layer.initialize(
        jax.random.PRNGKey(0), InputType.convolutional3d(4, 4, 4, 2))
    assert out_t.shape == (8, 8, 8, 3)
    x = np.random.RandomState(0).rand(2, 4, 4, 4, 2).astype(np.float32)
    y, _ = layer.apply(params, state, jnp.asarray(x))
    assert y.shape == (2, 8, 8, 8, 3)


def test_subsampling1d_and_cropping():
    from deeplearning4j_tpu.nn import (Cropping1DLayer, Cropping2DLayer,
                                       Cropping3DLayer, Subsampling1DLayer)
    import jax
    x = np.arange(2 * 8 * 3, dtype=np.float32).reshape(2, 8, 3)
    p = Subsampling1DLayer(pooling_type="MAX", kernel_size=2, stride=2)
    y, _ = p.apply({}, {}, jnp.asarray(x))
    assert y.shape == (2, 4, 3)
    np.testing.assert_array_equal(np.asarray(y)[0, 0], x[0, 1])
    c1 = Cropping1DLayer(cropping=(1, 2))
    y, _ = c1.apply({}, {}, jnp.asarray(x))
    assert y.shape == (2, 5, 3)
    np.testing.assert_array_equal(np.asarray(y)[0, 0], x[0, 1])
    x2 = np.zeros((1, 6, 6, 2), np.float32)
    c2 = Cropping2DLayer(cropping=(1, 2, 0, 3))
    y, _ = c2.apply({}, {}, jnp.asarray(x2))
    assert y.shape == (1, 3, 3, 2)
    x3 = np.zeros((1, 4, 4, 4, 1), np.float32)
    c3 = Cropping3DLayer(cropping=(1, 1, 0, 2, 2, 0))
    y, _ = c3.apply({}, {}, jnp.asarray(x3))
    assert y.shape == (1, 2, 2, 2, 1)


def test_locally_connected_matches_manual():
    from deeplearning4j_tpu.nn import LocallyConnected2DLayer
    import jax
    rng = np.random.RandomState(1)
    layer = LocallyConnected2DLayer(n_out=2, kernel_size=2, stride=1,
                                    activation="identity", has_bias=False)
    params, _, out_t = layer.initialize(
        jax.random.PRNGKey(0), InputType.convolutional(3, 3, 2))
    assert out_t.shape == (2, 2, 2)
    x = rng.rand(1, 3, 3, 2).astype(np.float32)
    y, _ = layer.apply(params, {}, jnp.asarray(x))
    W = np.asarray(params["W"])     # [OH, OW, K*K*C, O]
    # manual: per output position, its own kernel; patches are channel-major
    # (conv_general_dilated_patches emits [C, KH, KW] feature order)
    patch = x[0, 0:2, 0:2, :].transpose(2, 0, 1).reshape(-1)
    np.testing.assert_allclose(np.asarray(y)[0, 0, 0], patch @ W[0, 0],
                               rtol=1e-5)


def test_prelu_learns_slope():
    from deeplearning4j_tpu.nn import PReLULayer
    import jax
    layer = PReLULayer(alpha_init=0.25)
    params, _, _ = layer.initialize(jax.random.PRNGKey(0),
                                    InputType.feed_forward(4))
    x = jnp.asarray([[-2.0, -1.0, 1.0, 2.0]])
    y, _ = layer.apply(params, {}, x)
    np.testing.assert_allclose(np.asarray(y)[0], [-0.5, -0.25, 1.0, 2.0])


def test_center_loss_output_layer_trains_and_pulls_centers():
    from deeplearning4j_tpu.nn import CenterLossOutputLayer, DenseLayer
    rng = np.random.RandomState(3)
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
            .list([DenseLayer(n_out=8, activation="tanh"),
                   CenterLossOutputLayer(n_out=3, lambda_=0.1)])
            .set_input_type(InputType.feed_forward(5)).build())
    net = MultiLayerNetwork(conf).init()
    x = rng.randn(30, 5).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 30)]
    s0 = net.score_for(x, y)
    for _ in range(30):
        net.fit(x, y)
    assert net.score_for(x, y) < s0
    # centers moved off their zero init toward class feature means
    centers = np.asarray(net.params_["layer_1"]["centers"])
    assert np.linalg.norm(centers) > 0.01
    out = net.output(x)
    assert np.allclose(np.asarray(out).sum(1), 1.0, atol=1e-4)
