"""Smoke-run every example script (reference: dl4j-examples are built in
CI; VERDICT r3 #7 — `keras_import_and_serving.py` exercises the longest
dependency chain in the repo and must not rot silently).

Each example self-bootstraps onto CPU and is documented to finish in
under a minute; a nonzero exit fails with the script's tail."""
import os
import subprocess
import sys

import pytest

EXAMPLES_DIR = os.path.join(os.path.dirname(__file__), "..", "examples")
SCRIPTS = sorted(f for f in os.listdir(EXAMPLES_DIR) if f.endswith(".py"))
# multi-process supervisor examples exceed the tier-1 budget; their
# training paths are covered by the `slow` subprocess tests directly
SLOW_SCRIPTS = {"elastic_gang_training.py", "federated_fleet.py"}


def test_every_example_is_covered():
    assert len(SCRIPTS) >= 10, SCRIPTS


@pytest.mark.parametrize(
    "script",
    [pytest.param(s, marks=pytest.mark.slow) if s in SLOW_SCRIPTS
     else s for s in SCRIPTS])
def test_example_runs(script):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)     # examples choose their own mesh size
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES_DIR, script)],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.join(EXAMPLES_DIR, ".."))
    assert proc.returncode == 0, (
        f"{script} failed (rc={proc.returncode}):\n"
        f"{(proc.stdout + proc.stderr)[-3000:]}")
