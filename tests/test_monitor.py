"""Unified telemetry tests: registry primitives, spans, Prometheus
exposition, the UIServer `/metrics` endpoint, and the end-to-end acceptance
path (fit + prefetch + serving all visible in one scrape)."""
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.monitor import (Counter, Gauge, Histogram,
                                        MetricsRegistry, current_span,
                                        enabled, registry, set_enabled,
                                        span, span_stack)
from deeplearning4j_tpu.monitor.instrument import TrainingInstruments


# ---------------------------------------------------------------------------
# Registry primitives
# ---------------------------------------------------------------------------

def test_counter_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("requests_total", help="requests")
    c.inc()
    c.inc(4)
    assert c.value == 5
    g = reg.gauge("depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2.0
    g.set_max(10)
    g.set_max(4)                    # ratchet: never goes down
    assert g.value == 10.0


def test_get_or_create_returns_same_child():
    reg = MetricsRegistry()
    a = reg.counter("c", labels={"m": "x"})
    b = reg.counter("c", labels={"m": "x"})
    other = reg.counter("c", labels={"m": "y"})
    assert a is b
    assert a is not other
    # label order must not matter
    h1 = reg.histogram("h", labels={"a": "1", "b": "2"})
    h2 = reg.histogram("h", labels={"b": "2", "a": "1"})
    assert h1 is h2


def test_kind_conflict_raises():
    reg = MetricsRegistry()
    reg.counter("x_total")
    with pytest.raises(TypeError):
        reg.gauge("x_total")


def test_get_never_creates():
    reg = MetricsRegistry()
    assert reg.get("nope") is None
    reg.counter("yes", labels={"k": "v"})
    assert reg.get("yes") is None               # different (empty) labels
    assert reg.get("yes", {"k": "v"}) is not None


def test_registry_concurrent_increments():
    """8 threads x 1000 increments each land exactly — the counter lock
    holds under the kind of contention training + prefetch producer +
    batcher worker + UI scraper generate."""
    reg = MetricsRegistry()
    c = reg.counter("hits_total")
    h = reg.histogram("lat_ms", maxlen=128)
    n_threads, n_iter = 8, 1000

    def work(i):
        for k in range(n_iter):
            c.inc()
            h.observe(float(k % 17))

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_iter
    assert h.count == n_threads * n_iter        # lifetime count, not window


def test_histogram_percentiles_match_numpy():
    rng = np.random.RandomState(3)
    vals = rng.lognormal(0.0, 1.0, 500)
    h = Histogram("h", maxlen=1000)
    for v in vals:
        h.observe(v)
    got = h.percentiles((50, 95, 99))
    s = np.sort(vals)
    for p in (50, 95, 99):
        # nearest-rank over the sorted sample — numpy's equivalent mode
        expect = s[int(round(p / 100.0 * (len(s) - 1)))]
        assert got[f"p{p}"] == pytest.approx(expect)


def test_histogram_window_slides_but_lifetime_accumulates():
    h = Histogram("h", maxlen=10)
    for v in range(100):
        h.observe(float(v))
    assert h.count == 100
    assert h.sum == pytest.approx(sum(range(100)))
    assert h.max == 99.0
    # window holds only the last 10 -> p50 reflects recent traffic
    assert h.percentiles((50,))["p50"] >= 90.0
    lo, hi, counts = h.bins(5)
    assert (lo, hi) == (90.0, 99.0)
    assert sum(counts) == 10


def test_kill_switch_makes_recording_free():
    reg = MetricsRegistry()
    c = reg.counter("c_total")
    g = reg.gauge("g")
    h = reg.histogram("h")
    set_enabled(False)
    try:
        assert not enabled()
        c.inc()
        g.set(5)
        h.observe(1.0)
        assert c.value == 0
        assert g.value == 0.0
        assert h.count == 0
    finally:
        set_enabled(True)
    c.inc()
    assert c.value == 1


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------

def test_span_records_and_nests():
    reg = MetricsRegistry()
    with span("outer", registry_=reg):
        assert current_span() == "outer"
        with span("inner", registry_=reg):
            assert current_span() == "outer/inner"
            assert span_stack() == ["outer", "outer/inner"]
    assert current_span() is None
    outer = reg.get("span_ms", {"span": "outer"})
    inner = reg.get("span_ms", {"span": "outer/inner"})
    assert outer is not None and outer.count == 1
    assert inner is not None and inner.count == 1
    assert outer.sum >= inner.sum               # child time nests in parent


def test_span_stack_unwinds_on_exception():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        with span("boom", registry_=reg):
            raise ValueError("x")
    assert span_stack() == []
    rec = reg.get("span_ms", {"span": "boom"})
    assert rec is not None and rec.count == 1   # time still recorded


def test_span_disabled_is_a_noop():
    reg = MetricsRegistry()
    set_enabled(False)
    try:
        with span("quiet", registry_=reg):
            assert span_stack() == []
    finally:
        set_enabled(True)
    assert reg.get("span_ms", {"span": "quiet"}) is None


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------

def test_prometheus_text_format_golden():
    """Pin the exposition format: HELP/TYPE lines, label rendering,
    counter value, summary quantiles + _sum/_count."""
    reg = MetricsRegistry()
    c = reg.counter("jobs_total", help="jobs run", labels={"kind": "fit"})
    c.inc(3)
    h = reg.histogram("lat_ms", help="latency", labels={"server": "s0"})
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    text = reg.render_prometheus()
    expected = (
        "# HELP jobs_total jobs run\n"
        "# TYPE jobs_total counter\n"
        'jobs_total{kind="fit"} 3\n'
        "# HELP lat_ms latency\n"
        "# TYPE lat_ms summary\n"
        'lat_ms{server="s0",quantile="0.5"} 3\n'
        'lat_ms{server="s0",quantile="0.95"} 4\n'
        'lat_ms{server="s0",quantile="0.99"} 4\n'
        'lat_ms_sum{server="s0"} 10\n'
        'lat_ms_count{server="s0"} 4\n')
    assert text == expected


def test_prometheus_escapes_label_values():
    reg = MetricsRegistry()
    reg.counter("c_total", labels={"p": 'a"b\\c\nd'}).inc()
    text = reg.render_prometheus()
    assert 'p="a\\"b\\\\c\\nd"' in text


def test_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("c_total").inc(2)
    reg.gauge("g").set(1.5)
    reg.histogram("h").observe(7.0)
    snap = reg.snapshot(bins=4)
    assert snap["counters"] == {"c_total": 2}
    assert snap["gauges"] == {"g": 1.5}
    h = snap["histograms"]["h"]
    assert h["count"] == 1 and h["max"] == 7.0
    assert sum(h["bins"]["counts"]) == 1


# ---------------------------------------------------------------------------
# Instrument bundles
# ---------------------------------------------------------------------------

def test_training_instruments_compile_detection():
    import jax

    reg = MetricsRegistry()
    ins = TrainingInstruments("M", registry_=reg)
    f = jax.jit(lambda x: x * 2)
    ins.check_compile(f)
    assert ins.compiles.value == 0              # nothing traced yet
    f(np.float32(1.0))
    ins.check_compile(f)
    assert ins.compiles.value == 1
    f(np.float32(2.0))                          # same shape: cache hit
    ins.check_compile(f)
    assert ins.compiles.value == 1
    f(np.ones(3, np.float32))                   # new shape: retrace
    ins.check_compile(f)
    assert ins.compiles.value == 2
    g = jax.jit(lambda x: x + 1)                # rebuilt step = new fn
    g(np.float32(1.0))
    ins.check_compile(g)
    assert ins.compiles.value == 3


def test_training_instruments_record_dispatch_fused():
    reg = MetricsRegistry()
    ins = TrainingInstruments("M", registry_=reg)
    ins.record_dispatch(0.080, steps=8)
    assert ins.steps.value == 8
    assert ins.dispatches.value == 1
    assert ins.step_ms.percentiles((50,))["p50"] == pytest.approx(10.0)


# ---------------------------------------------------------------------------
# End-to-end: training + pipeline + serving -> one /metrics scrape
# ---------------------------------------------------------------------------

def _mlp(n_in=6, n_out=3):
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    conf = (NeuralNetConfiguration.builder().seed(7)
            .list([DenseLayer(n_out=12, activation="relu"),
                   OutputLayer(n_out=n_out, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def test_metrics_endpoint_round_trip_during_fit():
    """The ISSUE acceptance path: train through the prefetch pipeline with
    a ModelServer live, then curl /metrics and find step-time,
    prefetch-depth and serving-queue series in one Prometheus scrape."""
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.data.pipeline import DevicePrefetchIterator
    from deeplearning4j_tpu.serving import ModelServer
    from deeplearning4j_tpu.ui.server import UIServer

    rng = np.random.RandomState(0)
    batches = [DataSet(rng.rand(8, 6).astype(np.float32),
                       np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)])
               for _ in range(4)]
    net = _mlp()
    pf = DevicePrefetchIterator(ListDataSetIterator(batches), depth=2)
    try:
        net.fit(pf, epochs=1)
    finally:
        pf.close()

    server = ModelServer(max_batch=8, batch_timeout_ms=2.0)
    ui = UIServer()
    try:
        server.deploy("m", net)
        server.output("m", rng.rand(2, 6).astype(np.float32))
        port = ui.start(port=0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
    finally:
        ui.stop()
        server.shutdown()

    assert "training_step_ms" in text
    assert "pipeline_prefetch_depth" in text
    assert "serving_queue_depth" in text
    assert "# TYPE training_step_ms summary" in text
    assert 'model="MultiLayerNetwork"' in text
    # the fit above really happened: non-zero step count in the scrape
    steps = registry().get("training_steps_total",
                           {"model": "MultiLayerNetwork"})
    assert steps is not None and steps.value >= 4


def test_dashboard_renders_registry_block():
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.stats import render_registry_html

    registry().counter("dash_total", help="x").inc()
    html = render_registry_html(registry().snapshot(bins=8))
    assert "dash_total" in html
    ui = UIServer()
    try:
        port = ui.start(port=0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10) as r:
            page = r.read().decode()
    finally:
        ui.stop()
    assert "Telemetry registry" in page


def test_serving_metrics_is_registry_view():
    """ServingMetrics has no private store: the same numbers the snapshot
    reports are live labeled series in the shared registry."""
    from deeplearning4j_tpu.serving.metrics import ServingMetrics

    m = ServingMetrics(window=16)
    m.record_submit(queue_depth=3)
    m.record_dispatch(n_requests=2, rows=8, padded_rows=2, dispatch_ms=1.5)
    m.record_latency(4.0)
    lbl = {"server": m.server_label}
    assert registry().get("serving_submitted_total", lbl).value == 1
    assert registry().get("serving_queue_depth", lbl).value == 3
    assert registry().get("serving_latency_ms", lbl).count == 1
    snap = m.snapshot()
    assert snap["submitted"] == 1
    assert snap["dispatches"] == 1
    assert snap["batch_occupancy"] == pytest.approx(2.0)
    assert snap["padding_fraction"] == pytest.approx(0.2)


def test_counter_uploads_is_shared_series():
    """The sync-free invariant counter and the /metrics series are ONE
    object — incrementing one is visible through the other."""
    from deeplearning4j_tpu.utils import counters

    series = registry().get("device_counter_uploads_total")
    assert series is counters.counter_uploads
