"""ComputationGraph tests — DAG construction, vertices, training, serde.

Mirrors the reference's ComputationGraph test coverage
(`platform-tests/.../nn/graph/TestComputationGraphNetwork.java`):
multi-input/multi-output, merge/elementwise vertices, residual topology,
JSON round-trip, save/load, gradients vs finite differences.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.nn import (
    ComputationGraph, ComputationGraphConfiguration, DenseLayer,
    ElementWiseVertex, GraphBuilder, InputType, MergeVertex, OutputLayer,
    ScaleVertex, ShiftVertex, StackVertex, SubsetVertex, UnstackVertex,
    L2NormalizeVertex)
from deeplearning4j_tpu.train.updaters import Adam, Sgd


def residual_graph():
    return (GraphBuilder()
            .seed(12345).updater(Adam(1e-2)).weight_init("XAVIER")
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(8))
            .add_layer("d1", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("d2", DenseLayer(n_out=8, activation="relu"), "d1")
            .add_vertex("res", ElementWiseVertex(op="Add"), "d1", "d2")
            .add_layer("out", OutputLayer(n_out=3, loss="mcxent",
                                          activation="softmax"), "res")
            .set_outputs("out")
            .build())


def _toy_data(n=64, f=8, c=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    labels = (x[:, 0] + x[:, 1] > 0).astype(int) + (x[:, 2] > 0.5).astype(int)
    y = np.eye(c, dtype=np.float32)[labels]
    return x, y


def test_residual_graph_trains():
    net = ComputationGraph(residual_graph()).init()
    x, y = _toy_data()
    s0 = net.score_for(x, y)
    net.fit(x, y)
    for _ in range(60):
        net.fit(x, y)
    assert net.score() < s0


def test_multi_input_merge():
    conf = (GraphBuilder()
            .seed(0).updater(Sgd(1e-1))
            .add_inputs("a", "b")
            .set_input_types(InputType.feed_forward(4), InputType.feed_forward(6))
            .add_layer("da", DenseLayer(n_out=5, activation="tanh"), "a")
            .add_layer("db", DenseLayer(n_out=7, activation="tanh"), "b")
            .add_vertex("m", MergeVertex(), "da", "db")
            .add_layer("out", OutputLayer(n_out=2, loss="mcxent",
                                          activation="softmax"), "m")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    xa = np.random.RandomState(0).randn(10, 4).astype(np.float32)
    xb = np.random.RandomState(1).randn(10, 6).astype(np.float32)
    (out,) = net.output(xa, xb)
    assert out.shape == (10, 2)
    assert np.allclose(np.asarray(out).sum(1), 1.0, atol=1e-5)
    # merged activation width = 5 + 7
    acts = net.feed_forward(xa, xb)
    assert acts["m"].shape == (10, 12)


def test_multi_output_losses_sum():
    conf = (GraphBuilder()
            .seed(0).updater(Sgd(1e-1))
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("trunk", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("out1", OutputLayer(n_out=2, loss="mcxent",
                                           activation="softmax"), "trunk")
            .add_layer("out2", OutputLayer(n_out=1, loss="mse",
                                           activation="identity"), "trunk")
            .set_outputs("out1", "out2")
            .build())
    net = ComputationGraph(conf).init()
    x = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    y1 = np.eye(2, dtype=np.float32)[np.random.RandomState(1).randint(0, 2, 16)]
    y2 = np.random.RandomState(2).randn(16, 1).astype(np.float32)
    s0 = net.score_for(x, [y1, y2])
    for _ in range(40):
        net.fit(x, [y1, y2])
    assert net.score() < s0
    o1, o2 = net.output(x)
    assert o1.shape == (16, 2) and o2.shape == (16, 1)


def test_simple_vertices():
    conf = (GraphBuilder()
            .seed(0).updater(Sgd(1e-2))
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(6))
            .add_vertex("scale", ScaleVertex(scale=2.0), "in")
            .add_vertex("shift", ShiftVertex(shift=1.0), "scale")
            .add_vertex("sub", SubsetVertex(range_from=0, range_to=2), "shift")
            .add_vertex("l2", L2NormalizeVertex(), "sub")
            .add_layer("out", OutputLayer(n_out=2, loss="mse",
                                          activation="identity"), "l2")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x = np.ones((4, 6), np.float32)
    acts = net.feed_forward(x)
    assert np.allclose(np.asarray(acts["scale"]), 2.0)
    assert np.allclose(np.asarray(acts["shift"]), 3.0)
    assert acts["sub"].shape == (4, 3)
    norms = np.linalg.norm(np.asarray(acts["l2"]), axis=1)
    assert np.allclose(norms, 1.0, atol=1e-5)


def test_stack_unstack_roundtrip():
    conf = (GraphBuilder()
            .seed(0).updater(Sgd(1e-2))
            .add_inputs("a", "b")
            .set_input_types(InputType.feed_forward(3), InputType.feed_forward(3))
            .add_vertex("st", StackVertex(), "a", "b")
            .add_vertex("u0", UnstackVertex(from_index=0, stack_size=2), "st")
            .add_layer("out", OutputLayer(n_out=2, loss="mse",
                                          activation="identity"), "u0")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    xa = np.random.RandomState(0).randn(5, 3).astype(np.float32)
    xb = np.random.RandomState(1).randn(5, 3).astype(np.float32)
    acts = net.feed_forward(xa, xb)
    assert acts["st"].shape == (10, 3)
    assert np.allclose(np.asarray(acts["u0"]), xa)


def test_json_roundtrip():
    conf = residual_graph()
    s = conf.to_json()
    conf2 = ComputationGraphConfiguration.from_json(s)
    assert conf2.network_inputs == ["in"]
    assert conf2.network_outputs == ["out"]
    assert list(conf2.vertices) == list(conf.vertices)
    assert conf2.to_json() == s
    # restored config builds an equivalent net
    net = ComputationGraph(conf2).init()
    x, y = _toy_data(8)
    (out,) = net.output(x)
    assert out.shape == (8, 3)


def test_save_load_exact_resume(tmp_path):
    net = ComputationGraph(residual_graph()).init()
    x, y = _toy_data(32)
    for _ in range(5):
        net.fit(x, y)
    p = str(tmp_path / "cg.zip")
    net.save(p)
    net2 = ComputationGraph.load(p)
    assert isinstance(net2, ComputationGraph)
    assert np.allclose(net.params(), net2.params())
    assert net2.iteration == net.iteration
    # continued training matches bit-for-bit only if updater state resumed;
    # check scores track closely
    net.fit(x, y)
    net2.fit(x, y)
    assert np.isclose(net.score(), net2.score(), rtol=1e-5)


def test_gradients_match_finite_difference():
    conf = residual_graph()
    conf.dtype = "float64"  # FD in f32 is too noisy for rtol=1e-3
    net = ComputationGraph(conf).init()
    x, y = _toy_data(8)
    grads = net.gradient_for(x, y)
    # central finite differences on a few params of d1/W
    import jax
    flat = net.params().astype(np.float64)
    idxs = [0, 3, 17]
    eps = 1e-4
    # locate offset of d1/W in flattened order
    leaves, _ = jax.tree_util.tree_flatten(net.params_)
    gleaves, _ = jax.tree_util.tree_flatten(grads)
    g_flat = np.concatenate([np.asarray(g).ravel() for g in gleaves])
    for i in idxs:
        fp = flat.copy(); fp[i] += eps
        fm = flat.copy(); fm[i] -= eps
        net.set_params(fp); sp = net.score_for(x, y)
        net.set_params(fm); sm = net.score_for(x, y)
        fd = (sp - sm) / (2 * eps)
        assert np.isclose(g_flat[i], fd, rtol=1e-3, atol=1e-5), (i, g_flat[i], fd)
    net.set_params(flat)


def test_cycle_detection():
    b = (GraphBuilder()
         .add_inputs("in").set_input_types(InputType.feed_forward(4))
         .add_layer("a", DenseLayer(n_out=4), "b")
         .add_layer("b", DenseLayer(n_out=4), "a")
         .add_layer("out", OutputLayer(n_out=2, loss="mse"), "b")
         .set_outputs("out"))
    with pytest.raises(ValueError, match="cycle"):
        ComputationGraph(b.build()).init()


def test_cg_gradient_checkpointing_matches_plain():
    import numpy as np

    from deeplearning4j_tpu.train.updaters import Sgd

    def build(remat):
        b = (GraphBuilder().seed(3).updater(Sgd(0.05))
             .add_inputs("in")
             .set_input_types(InputType.feed_forward(5)))
        if remat:
            b = b.gradient_checkpointing()
        b.add_layer("d1", DenseLayer(n_out=8, activation="tanh"), "in")
        b.add_layer("d2", DenseLayer(n_out=8, activation="relu"), "d1")
        b.add_vertex("res", ElementWiseVertex(op="Add"), "d1", "d2")
        b.add_layer("out", OutputLayer(n_out=2, loss="mcxent",
                                       activation="softmax"), "res")
        b.set_outputs("out")
        return ComputationGraph(b.build()).init()

    rng = np.random.RandomState(2)
    x = rng.randn(6, 5).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 6)]
    a, b_ = build(False), build(True)
    for _ in range(4):
        a.fit(x, y)
        b_.fit(x, y)
    np.testing.assert_allclose(np.asarray(a.params()),
                               np.asarray(b_.params()), atol=1e-6)
    assert ComputationGraphConfiguration.from_json(
        b_.conf.to_json()).remat


def test_cg_fit_steps_matches_sequential_fit():
    """ComputationGraph.fit_steps == k sequential fit() calls, bit-exact."""
    rng = np.random.RandomState(0)
    xs = rng.rand(4, 8, 6).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.randint(0, 3, (4, 8))]

    def build():
        conf = (GraphBuilder().seed(11)
                .updater(Adam(1e-2))
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=16, activation="relu"), "in")
                .add_layer("out", OutputLayer(n_out=3, loss="mcxent",
                                              activation="softmax"), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(6)).build())
        return ComputationGraph(conf).init()

    a, b = build(), build()
    for i in range(4):
        a.fit(xs[i], ys[i])
    losses = b.fit_steps(xs, ys)
    assert losses.shape == (4,)
    for la, lb in zip(jax.tree_util.tree_leaves(a.params_),
                      jax.tree_util.tree_leaves(b.params_)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert a.iteration == b.iteration == 4


def test_cg_fit_iterator_fused_matches_sequential():
    """CG fit(iterator, fused_steps=3) == fit(iterator): multi-input
    graphs stack per-name; the 7-batch epoch leaves a 1-batch tail."""
    from deeplearning4j_tpu.data.dataset import MultiDataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator

    rng = np.random.RandomState(2)
    batches = []
    for _ in range(7):
        xa = rng.rand(8, 4).astype(np.float32)
        xb = rng.rand(8, 6).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)]
        batches.append(MultiDataSet(features=[xa, xb], labels=[y]))

    def build():
        conf = (GraphBuilder().seed(0).updater(Sgd(1e-1))
                .add_inputs("a", "b")
                .set_input_types(InputType.feed_forward(4),
                                 InputType.feed_forward(6))
                .add_layer("da", DenseLayer(n_out=5, activation="tanh"), "a")
                .add_layer("db", DenseLayer(n_out=7, activation="tanh"), "b")
                .add_vertex("m", MergeVertex(), "da", "db")
                .add_layer("out", OutputLayer(n_out=2, loss="mcxent",
                                              activation="softmax"), "m")
                .set_outputs("out").build())
        return ComputationGraph(conf).init()

    a, b = build(), build()
    a.fit(ListDataSetIterator(batches), epochs=2)
    b.fit(ListDataSetIterator(batches), epochs=2, fused_steps=3)
    for la, lb in zip(jax.tree_util.tree_leaves(a.params_),
                      jax.tree_util.tree_leaves(b.params_)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert a.iteration == b.iteration == 14
