"""Async input pipeline: device prefetch, on-device normalization,
sync-free step loop (data/pipeline.py + nn set_normalizer + listeners).

Covers the pipeline's load-bearing invariants:
- prefetch depth bounds how far the producer runs ahead (backpressure)
- early-break consumers and close() shut the producer thread down
- on-device normalization is BITWISE identical to the host normalizer,
  under jit and inside lax.scan, for every supported kind
- the streaming fused epoch (per-step staged lists, stacked inside the
  compiled dispatch) matches the stacked fit_steps form exactly and the
  per-step path numerically
- the steady-state loop performs no per-iteration blocking host read and
  no per-step H2D uploads (score spy + transfer_guard + counter_uploads)
"""
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.data import (DataSet, DeviceNormalizer,
                                     DevicePrefetchIterator,
                                     ImagePreProcessingScaler,
                                     ListDataSetIterator,
                                     NormalizerMinMaxScaler,
                                     NormalizerStandardize, device_blocks)
from deeplearning4j_tpu.data.iterators import (AsyncDataSetIterator,
                                               DataSetIterator)
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)


def _batches(n, batch=8, n_in=6, n_out=3, seed=0):
    rng = np.random.RandomState(seed)
    return [DataSet((rng.rand(batch, n_in) * 10.0).astype(np.float32),
                    np.eye(n_out, dtype=np.float32)[
                        rng.randint(0, n_out, batch)])
            for _ in range(n)]


def _mlp(n_in=6, n_out=3, seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .list([DenseLayer(n_out=12, activation="relu"),
                   OutputLayer(n_out=n_out, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


class CountingIterator(DataSetIterator):
    """Counts how many batches the producer has pulled."""

    def __init__(self, batches):
        self.batches = batches
        self.produced = 0

    def __iter__(self):
        for ds in self.batches:
            self.produced += 1
            yield ds

    def reset(self):
        pass

    def batch_size(self):
        return int(self.batches[0].features.shape[0])

    def __len__(self):
        return len(self.batches)


# ---------------------------------------------------------------------------
# Prefetch: depth / backpressure / shutdown
# ---------------------------------------------------------------------------

def test_prefetch_yields_all_batches_staged():
    batches = _batches(7)
    pf = DevicePrefetchIterator(ListDataSetIterator(list(batches)), depth=3)
    out = list(pf)
    pf.close()
    assert len(out) == len(batches)
    for got, want in zip(out, batches):
        assert isinstance(got.features, jax.Array)   # staged on device
        np.testing.assert_array_equal(np.asarray(got.features),
                                      want.features)
        np.testing.assert_array_equal(np.asarray(got.labels), want.labels)


def test_prefetch_depth_backpressure():
    # a stalled consumer bounds the producer's run-ahead at
    # depth (staged) + queue_size (host queue) + 1 (in-flight item)
    depth, qsize = 2, 2
    src = CountingIterator(_batches(16))
    pf = DevicePrefetchIterator(src, depth=depth, queue_size=qsize)
    it = iter(pf)
    consumed = 3
    for _ in range(consumed):
        next(it)
    deadline = time.time() + 1.0      # let the producer run as far as it can
    while src.produced < len(src.batches) and time.time() < deadline:
        time.sleep(0.02)
    assert src.produced <= consumed + depth + qsize + 1
    assert src.produced < len(src.batches)     # backpressure actually bit
    it.close()
    pf.close()


def test_prefetch_early_break_stops_producer():
    src = CountingIterator(_batches(32))
    pf = DevicePrefetchIterator(src, depth=2)
    for i, _ in enumerate(pf):
        if i == 1:
            break                      # generator close -> producer stop
    deadline = time.time() + 2.0
    while pf.active_producers() and time.time() < deadline:
        time.sleep(0.02)
    assert pf.active_producers() == 0
    pf.close()                         # idempotent
    assert pf.active_producers() == 0


def test_prefetch_depth_validation():
    with pytest.raises(ValueError):
        DevicePrefetchIterator(ListDataSetIterator(_batches(2)), depth=0)


def test_async_iterator_close_joins_producers():
    src = CountingIterator(_batches(64))
    ait = AsyncDataSetIterator(src, queue_size=2)
    it = iter(ait)
    next(it)
    assert ait.active_producers() == 1
    ait.close(timeout=2.0)
    assert ait.active_producers() == 0
    ait.close(timeout=2.0)             # idempotent
    # no thread leak beyond the joined producers
    assert not [t for t in threading.enumerate()
                if t.name.startswith("AsyncDataSetIterator")]


# ---------------------------------------------------------------------------
# On-device normalization: bitwise parity with the host path
# ---------------------------------------------------------------------------

def _fitted(nz, batches):
    return nz.fit(ListDataSetIterator(list(batches)))


@pytest.mark.parametrize("make_nz", [
    lambda b: _fitted(NormalizerStandardize(), b),
    lambda b: _fitted(NormalizerStandardize(fit_labels=True), b),
    lambda b: _fitted(NormalizerMinMaxScaler(), b),
    lambda b: _fitted(NormalizerMinMaxScaler(-1.0, 2.0), b),
    lambda b: ImagePreProcessingScaler(),
    lambda b: ImagePreProcessingScaler(-1.0, 1.0),
], ids=["standardize", "standardize+labels", "minmax01", "minmax-12",
        "image01", "image-11"])
def test_device_normalizer_bitwise(make_nz):
    batches = _batches(3, batch=16, n_in=5, seed=3)
    nz = make_nz(batches)
    x = batches[0].features
    y = batches[0].labels
    host = DataSet(x.copy(), y.copy())
    nz.transform(host)

    dn = DeviceNormalizer.from_host(nz)
    dev_jit = jax.jit(dn.apply_features)(jnp.asarray(x))
    assert np.asarray(dev_jit).dtype == np.float32
    assert np.array_equal(np.asarray(dev_jit).view(np.uint32),
                          host.features.view(np.uint32)), \
        "on-device normalization is not bitwise identical under jit"

    # inside lax.scan — the position it occupies in the fused step body
    def body(c, xi):
        return c, dn.apply_features(xi)
    _, scanned = jax.jit(
        lambda xs: lax.scan(body, 0, xs))(jnp.stack([jnp.asarray(x)] * 2))
    for row in np.asarray(scanned):
        assert np.array_equal(row.view(np.uint32),
                              host.features.view(np.uint32)), \
            "on-device normalization is not bitwise identical inside scan"

    # labels: normalized iff the host normalizer was label-fitted
    dev_y = np.asarray(jax.jit(dn.apply_labels)(jnp.asarray(y)))
    assert np.array_equal(dev_y.view(np.uint32),
                          host.labels.view(np.uint32))


def test_device_normalizer_rejects_unfitted_and_unknown():
    with pytest.raises(ValueError):
        DeviceNormalizer.from_host(NormalizerStandardize())
    with pytest.raises(ValueError):
        DeviceNormalizer.from_host(NormalizerMinMaxScaler())
    with pytest.raises(TypeError):
        DeviceNormalizer.from_host(object())
    dn = DeviceNormalizer.from_host(ImagePreProcessingScaler())
    assert DeviceNormalizer.from_host(dn) is dn        # passthrough


def test_set_normalizer_matches_host_preprocessing():
    batches = _batches(6, seed=11)
    nz = _fitted(NormalizerStandardize(), batches)

    host_net = _mlp()
    for ds in batches:
        d = DataSet(ds.features.copy(), ds.labels)
        nz.transform(d)
        host_net.fit(d.features, d.labels)

    dev_net = _mlp()
    dev_net.set_normalizer(nz)
    for ds in batches:
        dev_net.fit(ds.features, ds.labels)

    for a, b in zip(jax.tree.leaves(host_net.params_),
                    jax.tree.leaves(dev_net.params_)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # clearing restores the raw path
    dev_net.set_normalizer(None)
    assert dev_net._device_norm is None


# ---------------------------------------------------------------------------
# Streaming fused epoch
# ---------------------------------------------------------------------------

def test_streaming_fused_epoch_matches_stacked_and_per_step():
    batches = _batches(8, seed=5)

    streaming = _mlp()
    streaming.fit(ListDataSetIterator(list(batches)), fused_steps=4)

    stacked = _mlp()
    for lo in (0, 4):
        stacked.fit_steps(
            jnp.stack([jnp.asarray(d.features) for d in batches[lo:lo + 4]]),
            jnp.stack([jnp.asarray(d.labels) for d in batches[lo:lo + 4]]))

    per_step = _mlp()
    per_step.fit(ListDataSetIterator(list(batches)), fused_steps=1)

    for a, b in zip(jax.tree.leaves(streaming.params_),
                    jax.tree.leaves(stacked.params_)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "streaming (in-jit stacked) fused epoch != stacked fit_steps"
    for a, c in zip(jax.tree.leaves(streaming.params_),
                    jax.tree.leaves(per_step.params_)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-5, atol=1e-6)


def test_streaming_fused_epoch_from_prefetcher():
    batches = _batches(8, seed=5)
    plain = _mlp()
    plain.fit(ListDataSetIterator(list(batches)), fused_steps=4)

    pf = DevicePrefetchIterator(ListDataSetIterator(list(batches)), depth=2)
    try:
        prefetched = _mlp()
        prefetched.fit(pf, fused_steps=4)
    finally:
        pf.close()
    for a, b in zip(jax.tree.leaves(plain.params_),
                    jax.tree.leaves(prefetched.params_)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert pf.active_producers() == 0


def test_device_blocks_mixed_masks_degrade_to_singles():
    batches = _batches(4, seed=9)
    batches[2].features_mask = np.ones_like(batches[2].features)
    out = list(device_blocks(ListDataSetIterator(list(batches)), 4))
    # the masked batch must not fuse with unmasked neighbours, and its
    # mask must survive
    kinds = [k for k, _ in out]
    assert "block" not in kinds or all(
        payload[2] is not None or all(
            getattr(p, "features_mask", None) is None
            for p in ([payload] if kind == "single" else []))
        for kind, payload in out)
    singles = [p for k, p in out if k == "single"]
    assert any(getattr(p, "features_mask", None) is not None
               for p in singles)
    total = sum(1 if k == "single" else len(p[0]) for k, p in out)
    assert total == len(batches)


def test_fit_steps_list_form_validation():
    net = _mlp()
    xs = [np.zeros((4, 6), np.float32)] * 2
    with pytest.raises(ValueError):
        net.fit_steps(xs, np.zeros((2, 4, 3), np.float32))  # ys not a list


# ---------------------------------------------------------------------------
# Sync-free step loop
# ---------------------------------------------------------------------------

def test_steady_state_loop_no_blocking_score_and_no_h2d():
    from deeplearning4j_tpu.train.listeners import (CollectScoresListener,
                                                    ScoreIterationListener)
    from deeplearning4j_tpu.utils import counters

    batches = _batches(4, seed=13)
    net = _mlp()
    collect = CollectScoresListener()
    net.listeners = [collect, ScoreIterationListener(print_every=1)]

    xs = [jnp.asarray(d.features) for d in batches]
    ys = [jnp.asarray(d.labels) for d in batches]
    net.fit_steps(xs, ys)              # warmup: compile + counter upload

    # any blocking score read in the loop trips this spy
    def boom():                        # pragma: no cover - failure path
        raise AssertionError("blocking score() read in steady-state loop")
    net.score = boom

    uploads_before = counters.counter_uploads.value
    # the guard turns any fresh host->device transfer inside the loop into
    # an error (CPU D2H is zero-copy, so the score spy covers that side)
    with jax.transfer_guard("disallow"):
        for _ in range(3):
            net.fit_steps(xs, ys)
    assert counters.counter_uploads.value == uploads_before, \
        "schedule counters were re-uploaded inside the steady-state loop"

    del net.score                      # restore the class method
    # scores were collected lazily as device arrays; the read syncs
    raw = net.score_array()
    assert isinstance(raw, jax.Array)
    assert len(collect.scores) == 4
    assert all(np.isfinite(s) for s in collect.scores)


def test_score_iteration_listener_skips_sync_when_muted(caplog):
    import logging
    from deeplearning4j_tpu.train.listeners import ScoreIterationListener

    net = _mlp()
    calls = []
    net.score = lambda: calls.append(1) or 0.5
    lst = ScoreIterationListener(print_every=1)
    logger = logging.getLogger("deeplearning4j_tpu")
    old = logger.level
    logger.setLevel(logging.WARNING)   # INFO muted -> no score read at all
    try:
        lst.iteration_done(net, 1, 0)
        assert not calls
        logger.setLevel(logging.INFO)
        with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
            lst.iteration_done(net, 2, 0)
        assert calls                   # emitted line pays the one sync
    finally:
        logger.setLevel(old)


# ---------------------------------------------------------------------------
# SPMD composition
# ---------------------------------------------------------------------------

def test_parallel_wrapper_fit_prefetched():
    from deeplearning4j_tpu.parallel import ParallelWrapper

    n_dev = len(jax.devices())
    batch = 2 * n_dev
    batches = _batches(4, batch=batch, seed=17)
    nz = _fitted(NormalizerStandardize(), batches)
    net = _mlp()
    net.set_normalizer(nz)
    pw = ParallelWrapper(net)
    pw.fit_prefetched(ListDataSetIterator(list(batches)), epochs=1,
                      fused_steps=2)
    assert np.isfinite(float(net.score()))
