"""Native runtime tests (reference: libnd4j gtest suites for the threshold
encoding op + staging paths).  Runs against the C++ library when the
toolchain builds it, and the numpy fallback otherwise — both must agree."""
import numpy as np
import pytest

from deeplearning4j_tpu.native_ops import (ThresholdCodec, gather_indexed,
                                           native_available, u8_to_f32)


def test_native_library_builds():
    # this image ships g++ — the native path should be present
    assert native_available()


def test_threshold_codec_roundtrip():
    rng = np.random.RandomState(0)
    g = rng.randn(1000).astype(np.float32) * 0.01
    codec = ThresholdCodec(1000, threshold=0.01)
    enc = codec.encode(g)
    assert enc.dtype == np.int32
    dense = codec.decode(enc)
    # decoded entries are exactly +/- threshold at encoded positions
    nz = np.nonzero(dense)[0]
    assert len(nz) == len(enc)
    assert set(np.abs(dense[nz])) == {np.float32(0.01)}
    # residual carries: g = decoded + residual (exact decomposition)
    np.testing.assert_allclose(dense + codec.residual, g, atol=1e-6)


def test_threshold_codec_residual_accumulates():
    """Sub-threshold values eventually transmit via residual carry (the
    delta-compression convergence property)."""
    codec = ThresholdCodec(4, threshold=1.0)
    # NOTE |g| <= threshold: the codec emits at most one +/-threshold unit
    # per element per step (1-bit-SGD semantics, as in the reference)
    g = np.array([0.4, -0.4, 0.0, 0.9], np.float32)
    total = np.zeros(4, np.float32)
    for _ in range(10):
        total += codec.decode(codec.encode(g))
    # after 10 steps, transmitted total ~= 10 * g (within one threshold)
    np.testing.assert_allclose(total, 10 * g, atol=1.0)


def test_threshold_codec_max_elements():
    codec = ThresholdCodec(100, threshold=0.1, max_fraction=0.05)
    g = np.full(100, 0.5, np.float32)      # everything over threshold
    enc = codec.encode(g)
    assert len(enc) == 5                   # capped
    # dropped values fully carried in residual
    assert (codec.residual > 0.39).sum() >= 95


def test_threshold_density():
    codec = ThresholdCodec(10, threshold=0.5)
    g = np.array([1.0] * 3 + [0.1] * 7, np.float32)
    assert abs(codec.density(g) - 0.3) < 1e-9


def test_gather_indexed_matches_numpy():
    rng = np.random.RandomState(0)
    base = rng.rand(64, 28, 28, 1).astype(np.float32)
    idx = rng.permutation(64)[:32]
    out = gather_indexed(base, idx)
    np.testing.assert_array_equal(out, base[idx])


def test_u8_to_f32():
    src = np.arange(256, dtype=np.uint8).reshape(16, 16)
    out = u8_to_f32(src)
    np.testing.assert_allclose(out, src.astype(np.float32) / 255.0,
                               rtol=1e-6)


def test_codec_fallback_agrees_with_native():
    """numpy fallback and C++ path produce identical streams."""
    if not native_available():
        pytest.skip("no native lib")
    import deeplearning4j_tpu.native_ops as nat
    rng = np.random.RandomState(1)
    g = rng.randn(500).astype(np.float32) * 0.02

    c_native = ThresholdCodec(500, threshold=0.02)
    enc_native = c_native.encode(g)

    # force fallback by temporarily hiding the lib
    saved = nat._lib
    nat._lib = None
    nat._tried = True
    try:
        c_py = ThresholdCodec(500, threshold=0.02)
        enc_py = c_py.encode(g)
    finally:
        nat._lib = saved
    np.testing.assert_array_equal(enc_native, enc_py)
    np.testing.assert_allclose(c_native.residual, c_py.residual, atol=1e-6)


def test_compressed_gradient_exchange():
    """Pytree encode/decode round-trip with residual convergence."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.parallel.compression import (
        CompressedGradientExchange)
    rng = np.random.RandomState(0)
    grads = {"layer_0": {"W": jnp.asarray(rng.randn(20, 10) * 0.01),
                         "b": jnp.asarray(rng.randn(10) * 0.01)}}
    # high density target: adaptation leaves the threshold near 0.01 so the
    # 30-step convergence bound below is meaningful (the 1% default is for
    # real model sizes where per-step sparsity is the point)
    # threshold > max|g|: each element transmits at most one unit per pass
    # (1-bit semantics), so convergence-within-one-threshold only holds when
    # the residual accumulation drives every emission
    ex_send = CompressedGradientExchange(grads, threshold=0.05,
                                         adaptive_target_density=0.4)
    ex_recv = CompressedGradientExchange(grads, threshold=0.05,
                                         adaptive_target_density=0.4)
    total = {"layer_0": {"W": np.zeros((20, 10), np.float32),
                         "b": np.zeros(10, np.float32)}}
    for _ in range(30):
        streams = ex_send.encode(grads)
        assert ex_send.compression_ratio(streams) > 1.0
        decoded = ex_recv.decode(streams, ex_send.thresholds())
        for k in ("W", "b"):
            total["layer_0"][k] += np.asarray(decoded["layer_0"][k])
    # transmitted sum approaches 30x the true gradient
    for k in ("W", "b"):
        want = 30 * np.asarray(grads["layer_0"][k])
        np.testing.assert_allclose(total["layer_0"][k], want, atol=0.06)
