"""Pod-arbiter contract (ISSUE 20 acceptance): crc-guarded handoff
journal, two-phase slice handoffs between an elastic gang and a serving
fleet with journal-before-side-effect ordering, idempotent journal
replay after a mid-handoff kill (subprocess kill-and-relaunch), the
fleet controller's lease-table check (a slice journaled for return to
training is invisible to growth), the hung-replica drain-deadline
release, the `ElasticTrainer` control-dir shrink protocol against a real
3-process gang, and the gang-rank-killed-mid-shrink composition."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.monitor.registry import MetricsRegistry
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import ModelFleet, RejectedError
from deeplearning4j_tpu.serving.fleet import FleetController
from deeplearning4j_tpu.serving.slo import ArbiterPolicy
from deeplearning4j_tpu.train.arbiter import (ArbiterBusyError,
                                              GangControlClient,
                                              HandoffAbortedError,
                                              HandoffJournal,
                                              JournalCorruptError,
                                              LocalElasticGang, SliceArbiter)
from deeplearning4j_tpu.train.resilience import CheckpointManager
from deeplearning4j_tpu.train.updaters import Sgd
from deeplearning4j_tpu.utils.chaos import HandoffChaos

HERE = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------------------------
# shared fakes (the unit tests; subprocess tests use the real stack)
# ---------------------------------------------------------------------------

class FakeManager:
    """Checkpoint manager double: monotone steps, records ordering."""

    def __init__(self):
        self.step = 0
        self.saves = []
        self.restores = []

    def save(self, model, block=False, **kw):
        assert block, "the arbiter path must use BLOCKING saves"
        self.step += 1
        self.saves.append(self.step)

    def latest_step(self):
        return self.step

    def restore(self, model, step=None):
        self.restores.append(step)


class FakeFleet:
    """Fleet double implementing just the lease API the arbiter uses."""

    def __init__(self):
        self.leases = {}
        self.released = []
        self.n = 0

    def lease_slice(self, devices=None, tag=None):
        if tag in self.leases:
            return self.leases[tag]
        self.n += 1
        self.leases[tag] = self.n
        return self.n

    def release_slice(self, index, timeout=None):
        self.released.append((index, timeout))
        return {"slice": index, "drained": [], "evicted": [],
                "drain_expired": []}


def _arbiter(tmp_path, slices=(0, 1, 2), fleet=None, **policy_kw):
    policy_kw.setdefault("min_training_slices", 1)
    gang = LocalElasticGang(object(), FakeManager(), list(slices))
    arb = SliceArbiter(str(tmp_path / "journal.json"), training=gang,
                       fleet=fleet if fleet is not None else FakeFleet(),
                       policy=ArbiterPolicy(**policy_kw),
                       registry_=MetricsRegistry())
    return arb, gang


def _net(seed=0, n_in=8, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=n_out, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------

def test_journal_roundtrip_atomic_and_corruption(tmp_path):
    j = HandoffJournal(str(tmp_path / "j.json"))
    assert j.load() is None                         # no journal yet
    state = {"seq": 3, "leases": {"0": "training"}, "handoff": None}
    j.commit(state)
    assert j.load() == state
    assert not os.path.exists(j.path + ".tmp")      # replaced, not left

    # crc guards the state body: a flipped byte refuses to load
    with open(j.path) as f:
        payload = json.load(f)
    payload["state"]["seq"] = 4                     # body no longer matches
    with open(j.path, "w") as f:
        json.dump(payload, f)
    with pytest.raises(JournalCorruptError, match="crc"):
        j.load()

    # torn JSON refuses to load rather than half-applying
    with open(j.path, "w") as f:
        f.write('{"format": 1, "state"')
    with pytest.raises(JournalCorruptError, match="unreadable"):
        j.load()

    # future format refuses outright
    with open(j.path, "w") as f:
        json.dump({"format": 99, "state": state, "crc32": 0}, f)
    with pytest.raises(JournalCorruptError, match="format"):
        j.load()


def test_arbiter_policy_validation():
    with pytest.raises(ValueError, match="grant_at_forecast"):
        ArbiterPolicy(grant_at_forecast=0.0)
    with pytest.raises(ValueError, match="return_below_forecast"):
        ArbiterPolicy(grant_at_forecast=1.0, return_below_forecast=1.5)
    with pytest.raises(ValueError, match="min_training_slices"):
        ArbiterPolicy(min_training_slices=0)
    with pytest.raises(ValueError, match="drain_timeout_s"):
        ArbiterPolicy(drain_timeout_s=0.0)
    with pytest.raises(ValueError, match="cooldown_s"):
        ArbiterPolicy(cooldown_s=-1.0)


# ---------------------------------------------------------------------------
# two-phase handoffs (fakes)
# ---------------------------------------------------------------------------

def test_full_handoff_cycle_updates_leases_journal_and_metrics(tmp_path):
    arb, gang = _arbiter(tmp_path)
    fleet = arb.fleet
    assert arb.owner_counts() == {"training": 3, "serving": 0,
                                  "transit": 0}

    out = arb.to_serving()
    assert out["outcome"] == "committed" and out["direction"] == "to_serving"
    assert out["slice"] == 2                # highest index moves first
    assert out["resume_step"] == 1          # blocking save happened
    assert gang.held_slices() == [0, 1]
    assert gang.manager.restores == [1]     # coordinated rewind
    assert arb.fleet_index_of(2) == 1
    assert arb.owners()[2] == "serving"
    # durable: a fresh journal reader sees the committed lease table
    assert HandoffJournal(arb.journal.path).load()["leases"]["2"] \
        == "serving"

    back = arb.to_training()
    assert back["outcome"] == "committed"
    assert back["slice"] == 2
    assert fleet.released == [(1, arb.policy.drain_timeout_s)]
    assert gang.held_slices() == [0, 1, 2]
    assert arb.owner_counts() == {"training": 3, "serving": 0,
                                  "transit": 0}
    assert arb.fleet_index_of(2) is None

    reg = arb._ins._reg
    fams = set(reg.families())
    assert {"arbiter_handoffs_total", "arbiter_handoff_ms",
            "arbiter_slices", "arbiter_journal_replays_total",
            "arbiter_leases"} <= fams
    by_labels = {tuple(sorted(lbl.items())): c.value
                 for lbl, c in reg.children("arbiter_handoffs_total")}
    assert by_labels[(("direction", "to_serving"),
                      ("outcome", "committed"))] == 1
    assert by_labels[(("direction", "to_training"),
                      ("outcome", "committed"))] == 1
    owners = {lbl["owner"]: g.value
              for lbl, g in reg.children("arbiter_slices")}
    assert owners == {"training": 3, "serving": 0, "transit": 0}


def test_policy_floors_and_busy_guard(tmp_path):
    arb, _ = _arbiter(tmp_path, slices=(0, 1), min_training_slices=1,
                      max_fleet_leases=1)
    arb.to_serving()
    # training floor: the last slice never leaves
    with pytest.raises(ValueError, match="min_training_slices"):
        arb.to_serving()
    arb.to_training()
    arb2, _ = _arbiter(tmp_path / "b", slices=(0, 1, 2),
                       max_fleet_leases=1)
    arb2.to_serving()
    with pytest.raises(ValueError, match="max_fleet_leases"):
        arb2.to_serving()
    # moving a slice the named owner does not hold (slice 0 is training)
    with pytest.raises(ValueError, match="owned by"):
        arb2.to_training(pod_slice=0)
    # one handoff at a time (white-box: pin an in-flight record)
    arb2._state["handoff"] = {"id": "hX", "direction": "to_serving",
                              "slice": 0, "phase": "shrink"}
    with pytest.raises(ArbiterBusyError):
        arb2.to_serving()
    with pytest.raises(ArbiterBusyError):
        arb2.to_training()


def test_maybe_rebalance_hysteresis_and_cooldown(tmp_path):
    arb, _ = _arbiter(tmp_path, grant_at_forecast=1.5,
                      return_below_forecast=0.5, cooldown_s=30.0)
    out = arb.maybe_rebalance(pressure=2.0)
    assert out is not None and out["direction"] == "to_serving"
    # cooldown: even at spike pressure, no immediate second move
    assert arb.maybe_rebalance(pressure=5.0) is None
    arb._last_handoff_at = time.monotonic() - 60.0
    assert arb.maybe_rebalance(pressure=1.0) is None    # hysteresis band
    out = arb.maybe_rebalance(pressure=0.1)
    assert out is not None and out["direction"] == "to_training"
    arb._last_handoff_at = time.monotonic() - 60.0
    assert arb.maybe_rebalance(pressure=0.0) is None    # nothing leased


def test_aborted_handoff_rolls_lease_back(tmp_path):
    """A gang that never acks aborts the handoff with no side effects:
    the journal rolls back to the previous owner and the fleet never
    sees a lease."""
    client = GangControlClient(str(tmp_path / "ctl"), slices=[0, 1],
                               timeout_s=0.2, poll_s=0.02)
    fleet = FakeFleet()
    arb = SliceArbiter(str(tmp_path / "j.json"), training=client,
                       fleet=fleet, policy=ArbiterPolicy(),
                       registry_=MetricsRegistry())
    with pytest.raises(HandoffAbortedError, match="did not ack"):
        arb.to_serving()
    assert arb.owners() == {0: "training", 1: "training"}
    assert arb.describe()["handoff"] is None
    assert fleet.leases == {}
    reg = arb._ins._reg
    by_labels = {tuple(sorted(lbl.items())): c.value
                 for lbl, c in reg.children("arbiter_handoffs_total")}
    assert by_labels[(("direction", "to_serving"),
                      ("outcome", "aborted"))] == 1
    # and the arbiter is NOT wedged: a later handoff works
    ctl2 = tmp_path / "ctl"

    def _coordinator_acks():
        req_path = ctl2 / GangControlClient.REQUEST
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if req_path.exists():
                req = json.loads(req_path.read_text())
                (ctl2 / GangControlClient.ACK).write_text(json.dumps(
                    {"request_id": req["id"], "resume_step": 7,
                     "generation": 2, "world": 1, "rank": req["rank"]}))
                return
            time.sleep(0.01)

    client.timeout_s = 5.0
    t = threading.Thread(target=_coordinator_acks, daemon=True)
    t.start()
    out = arb.to_serving()
    t.join(timeout=5.0)
    assert out["outcome"] == "committed" and out["resume_step"] == 7
    assert arb.owners()[out["slice"]] == "serving"


def test_gang_control_client_error_ack_raises(tmp_path):
    ctl = tmp_path / "ctl"
    client = GangControlClient(str(ctl), slices=[0, 1], timeout_s=5.0,
                               poll_s=0.02)

    def _refuse():
        req_path = ctl / GangControlClient.REQUEST
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if req_path.exists():
                req = json.loads(req_path.read_text())
                (ctl / GangControlClient.ACK).write_text(json.dumps(
                    {"request_id": req["id"],
                     "error": "rank 1 not evictable"}))
                return
            time.sleep(0.01)

    t = threading.Thread(target=_refuse, daemon=True)
    t.start()
    with pytest.raises(HandoffAbortedError, match="refused"):
        client.shrink(1)
    t.join(timeout=5.0)
    assert client.held_slices() == [0, 1]   # nothing moved


# ---------------------------------------------------------------------------
# LocalElasticGang against the real checkpoint manager
# ---------------------------------------------------------------------------

def test_local_gang_shrink_then_readmit_is_bitwise_stable(tmp_path):
    net = _net(seed=5)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    net.fit(x, y)
    manager = CheckpointManager(str(tmp_path / "ckpt"), keep_last=20,
                                save_every_steps=None)
    gang = LocalElasticGang(net, manager, slices=[0, 1])
    before = np.asarray(net.params()).copy()

    info = gang.shrink(1)
    assert info["world"] == 1 and info["generation"] == 1
    assert info["resume_step"] == int(manager.latest_step())
    # save-then-pinned-restore round-trips the params bitwise
    np.testing.assert_array_equal(before, np.asarray(net.params()))

    info = gang.readmit(1)
    assert info["world"] == 2 and info["generation"] == 2
    np.testing.assert_array_equal(before, np.asarray(net.params()))
    # idempotency (journal replay re-runs executors)
    assert gang.shrink(5).get("already")
    assert gang.readmit(1).get("already")
    assert gang.held_slices() == [0, 1]


# ---------------------------------------------------------------------------
# fleet lease table: growth must never grab a slice in transit
# ---------------------------------------------------------------------------

class _BlockingArbiter:
    """Arbiter double exposing just the lease-table query."""

    def __init__(self, blocked=()):
        self.blocked = set(blocked)

    def blocked_fleet_slices(self):
        return frozenset(self.blocked)


def test_reconcile_growth_skips_arbiter_blocked_slice(tmp_path):
    """The race ISSUE 20 names: a slice journaled for return to
    training sits in the fleet's free list while the drain runs.  A
    reconcile growth action racing the handoff must not place onto it —
    without the `_available_slices` check in `_free_or_reclaimed_slice`
    this test fails by growing onto the blocked slice."""
    fleet = ModelFleet(max_resident=1, n_slices=2,
                       cache_dir=str(tmp_path / "exec-cache"),
                       registry_=MetricsRegistry())
    fleet.deploy("m", model=_net(), input_shape=(8,), warm=True)
    m = fleet.member("m")
    used = m.group.replicas[0].slice.index
    free = 1 - used
    assert fleet._free_slices == [free]

    arb = _BlockingArbiter(blocked={free})
    fleet.attach_arbiter(arb)
    controller = FleetController(fleet)
    # white-box into the exact decision point reconcile's grow path uses
    with fleet._admission_lock:
        got = controller._free_or_reclaimed_slice(
            m, fleet.pool.resident(), [])
    assert got is None, ("growth grabbed a slice journaled for return "
                         "to training")
    assert fleet._free_slices == [free]     # still free, still blocked
    with pytest.raises(RejectedError):
        with fleet._admission_lock:
            fleet._take_slice()

    # handoff completes -> unblocked -> the same call now grants it
    arb.blocked.clear()
    with fleet._admission_lock:
        got = controller._free_or_reclaimed_slice(
            m, fleet.pool.resident(), [])
    assert got is not None and got.index == free
    fleet.shutdown()


def test_lease_slice_idempotent_by_tag_and_release_idempotent(tmp_path):
    fleet = ModelFleet(max_resident=1, n_slices=1,
                       cache_dir=str(tmp_path / "exec-cache"),
                       registry_=MetricsRegistry())
    idx = fleet.lease_slice(tag="pod-3")
    assert idx == 1 and idx in fleet._free_slices
    assert fleet.lease_slice(tag="pod-3") == idx    # replayed grant
    assert fleet._free_slices.count(idx) == 1
    assert fleet.lease_slice(tag="pod-4") == 2      # distinct lease

    out = fleet.release_slice(idx, timeout=0.5)
    assert idx not in fleet._free_slices
    assert out["drained"] == [] and out["evicted"] == []
    out = fleet.release_slice(idx, timeout=0.5)     # replayed release
    assert out["drained"] == [] and out["evicted"] == []
    out = fleet.release_slice(99)                   # unknown: no-op
    assert out["slice"] == 99
    fleet.shutdown()


def test_release_slice_hung_replica_expires_drain_and_frees_slice(
        tmp_path):
    """ISSUE 20 chaos path (c): a replica hung mid-drain cannot pin the
    slice — the drain deadline expires, the replica is force-shut, and
    the slice is still released."""
    fleet = ModelFleet(max_resident=1, n_slices=1, batch_timeout_ms=1.0,
                       cache_dir=str(tmp_path / "exec-cache"),
                       registry_=MetricsRegistry())
    fleet.deploy("m", model=_net(), input_shape=(8,), warm=True)
    m = fleet.member("m")
    leased = fleet.lease_slice(tag="pod-1")
    with fleet._admission_lock:
        slice_ = fleet._take_slice([leased])
        assert slice_.index == leased
        m.group.replicas.append(fleet._build_replica(m, slice_))
    victim = m.group.replicas[-1]

    from deeplearning4j_tpu.monitor.registry import registry as global_reg

    def _chaos_count():
        return sum(c.value for lbl, c in
                   global_reg().children("chaos_faults_injected_total")
                   if lbl["kind"] == "handoff-replica-hang")

    before = _chaos_count()
    chaos = HandoffChaos(target="replica", mode="hang", duration_s=20.0)
    chaos.arm(victim)
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    victim.server.submit("m", x)            # in-flight work to hang on
    deadline = time.monotonic() + 10.0
    while not chaos.fired and time.monotonic() < deadline:
        time.sleep(0.01)
    assert chaos.fired, "chaos hang never engaged"

    t0 = time.monotonic()
    out = fleet.release_slice(leased, timeout=0.5)
    took = time.monotonic() - t0
    assert out["drain_expired"] == [victim.name]
    assert took < 10.0                      # deadline, not the full hang
    assert leased not in fleet._free_slices
    assert victim not in m.group.replicas   # out of routing first
    assert _chaos_count() == before + 1     # fault was counted
    chaos.restore()
    fleet.shutdown()


# ---------------------------------------------------------------------------
# journal recovery: kill-and-relaunch subprocess tests
# ---------------------------------------------------------------------------

def _run_worker(args, timeout=240):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo_root = os.path.dirname(HERE)
    extra = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    env["PYTHONPATH"] = os.pathsep.join([repo_root] + extra)
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, "arbiter_worker.py")]
        + [str(a) for a in args],
        capture_output=True, text=True, timeout=timeout, env=env)
    return proc


@pytest.mark.slow
def test_arbiter_killed_between_journal_phases_relaunch_replays(tmp_path):
    """ISSUE 20 chaos path (a): the arbiter process is hard-killed right
    after the phase-1 journal write (intent durable, zero side effects).
    A relaunched arbiter over the same journal resumes the handoff:
    the shrink executes, the lease is granted, the slice ends
    single-owned, and the replay is counted."""
    workdir = tmp_path / "pod"
    workdir.mkdir()
    proc = _run_worker([workdir, "run"])
    assert proc.returncode == 9, proc.stdout + proc.stderr

    # the durable phase-1 record: handoff in flight, slice in transit,
    # gang untouched
    state = HandoffJournal(str(workdir / "journal.json")).load()
    assert state["handoff"]["phase"] == "shrink"
    assert state["handoff"]["direction"] == "to_serving"
    assert state["leases"][str(state["handoff"]["slice"])] == "transit"

    proc = _run_worker([workdir, "recover"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(workdir / "recover_result.json") as f:
        result = json.load(f)
    assert result["recovered"]["outcome"] == "replayed"
    assert result["describe"]["replays"] == 1
    assert result["describe"]["handoff"] is None
    moved = result["recovered"]["slice"]
    assert result["describe"]["leases"][str(moved)] == "serving"
    assert moved not in result["gang_held"]         # single-owned
    assert str(moved) in result["describe"]["fleet_index"] \
        or moved in [int(k) for k in result["describe"]["fleet_index"]]
    # the replayed shrink committed a checkpoint and rewound to it
    assert result["gang_events"][0]["resume_step"] == result["ckpt_latest"]
    assert result["marker_exists"]                  # chaos stayed one-shot

    # final journal is clean: a THIRD process sees no handoff in flight
    state = HandoffJournal(str(workdir / "journal.json")).load()
    assert state["handoff"] is None
    assert state["replays"] == 1


def _read_acks(control_dir):
    try:
        with open(os.path.join(control_dir, "shrink-ack.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


@pytest.mark.slow
def test_control_dir_shrink_protocol_coordinated_eviction(tmp_path):
    """A real 3-process gang honors a pre-placed shrink request: the
    coordinator blocking-saves, evicts the named rank at that step
    (cause ``shrink``), acks with the resume step, and the survivors
    finish bitwise-identical."""
    from deeplearning4j_tpu.parallel.multihost import ElasticLocalRunner
    script = os.path.join(HERE, "mh_worker_arbiter_gang.py")
    out, ctl = tmp_path / "out", tmp_path / "ctl"
    out.mkdir()
    ctl.mkdir()
    (ctl / "shrink-request.json").write_text(
        json.dumps({"id": "req-test-1", "rank": 2}))
    runner = ElasticLocalRunner(num_processes=3, backoff_base_s=0.2)
    results = runner.run_elastic(
        script, [str(out), "8", "1", str(ctl), "-1"], timeout=420,
        checkpoint_dir=str(tmp_path / "ckpt"), policy="shrink",
        heartbeat_s=0.1, failure_deadline_s=2.0, relaunch=False)
    assert results["r0"][0] == 0, results["r0"][1][-2000:]
    assert results["r1"][0] == 0, results["r1"][1][-2000:]
    assert results["r2"][0] == 7, results["r2"][1][-2000:]  # evicted, parked
    ack = _read_acks(str(ctl))
    assert ack is not None and ack["request_id"] == "req-test-1"
    assert ack.get("error") is None
    assert ack["rank"] == 2 and ack["world"] == 2
    with open(out / "elastic_0.json") as f:
        info0 = json.load(f)
    reforms = info0["reformations"]
    assert len(reforms) == 1
    assert reforms[0]["cause"] == "shrink"
    assert reforms[0]["world"] == 2
    assert reforms[0]["resume_step"] == ack["resume_step"]
    final0 = np.load(out / "final_0.npz")
    final1 = np.load(out / "final_1.npz")
    np.testing.assert_array_equal(final0["params"], final1["params"])
    assert int(final0["iteration"]) == 8


@pytest.mark.slow
def test_gang_rank_killed_mid_shrink_composes_with_eviction(tmp_path):
    """ISSUE 20 chaos path (b): the victim rank is hard-killed inside
    the shrink window (a HandoffChaos gang hook fires the moment the
    request names it), racing the coordinator's coordinated eviction.
    Whichever side wins, the gang re-forms to world 2 exactly once, an
    ack is written (coordinated, or an error ack when the crash-reform
    got there first), and the survivors end bitwise-identical."""
    from deeplearning4j_tpu.parallel.multihost import ElasticLocalRunner
    script = os.path.join(HERE, "mh_worker_arbiter_gang.py")
    out, ctl = tmp_path / "out", tmp_path / "ctl"
    out.mkdir()
    ctl.mkdir()
    (ctl / "shrink-request.json").write_text(
        json.dumps({"id": "req-test-2", "rank": 2}))
    runner = ElasticLocalRunner(num_processes=3, backoff_base_s=0.2)
    results = runner.run_elastic(
        script, [str(out), "8", "1", str(ctl), "2"], timeout=420,
        checkpoint_dir=str(tmp_path / "ckpt"), policy="shrink",
        heartbeat_s=0.1, failure_deadline_s=2.0, relaunch=False)
    assert results["r0"][0] == 0, results["r0"][1][-2000:]
    assert results["r1"][0] == 0, results["r1"][1][-2000:]
    assert results["r2"][0] in (7, 9), results["r2"][1][-2000:]
    with open(out / "elastic_0.json") as f:
        info0 = json.load(f)
    reforms = info0["reformations"]
    assert len(reforms) == 1, reforms   # composed: ONE world change
    assert reforms[0]["world"] == 2
    assert reforms[0]["cause"] in ("shrink", "crash", "partition",
                                   "straggler")
    assert info0["stats"]["world"] == 2
    # an ack always lands: coordinated when the eviction won the race,
    # an error ack when the crash-reform shrank the world first
    ack = _read_acks(str(ctl))
    assert ack is not None and ack["request_id"] == "req-test-2"
    assert ack.get("error") is not None or ack["resume_step"] >= 0
    final0 = np.load(out / "final_0.npz")
    final1 = np.load(out / "final_1.npz")
    np.testing.assert_array_equal(final0["params"], final1["params"])
    assert int(final0["iteration"]) == 8
