"""Evaluation-suite tests (reference `org.nd4j.evaluation` test family):
ROCBinary, EvaluationCalibration, EvaluationBinary.  Core Evaluation /
RegressionEvaluation / ROC coverage lives with the training-loop tests."""
import numpy as np



# ---------------------------------------------------------------------------
# ROCBinary + EvaluationCalibration (VERDICT §2 evaluation gaps)
# ---------------------------------------------------------------------------

def test_roc_binary_per_output_auc():
    from deeplearning4j_tpu.train import ROCBinary
    rng = np.random.RandomState(0)
    n = 400
    labels = rng.randint(0, 2, (n, 3)).astype(np.float32)
    preds = np.zeros((n, 3), np.float32)
    preds[:, 0] = np.clip(labels[:, 0] * 0.8 + 0.1
                          + rng.randn(n) * 0.05, 0, 1)   # strong signal
    preds[:, 1] = rng.rand(n)                            # random
    preds[:, 2] = np.clip(1 - labels[:, 2] + rng.randn(n) * 0.1, 0, 1)
    roc = ROCBinary()
    roc.eval(labels[:200], preds[:200])
    roc.eval(labels[200:], preds[200:])                  # accumulates
    assert roc.num_labels() == 3
    assert roc.calculate_auc(0) > 0.95
    assert 0.4 < roc.calculate_auc(1) < 0.6
    assert roc.calculate_auc(2) < 0.1                    # anti-correlated
    assert "AUC" in roc.stats()


def test_evaluation_calibration_ece_and_histograms():
    from deeplearning4j_tpu.train import EvaluationCalibration
    rng = np.random.RandomState(1)
    n = 5000
    # perfectly calibrated predictor: P(label=1) == predicted p
    p = rng.rand(n)
    labels1 = (rng.rand(n) < p).astype(np.float32)
    labels = np.stack([1 - labels1, labels1], 1)
    preds = np.stack([1 - p, p], 1).astype(np.float32)
    ec = EvaluationCalibration(reliability_bins=10)
    ec.eval(labels, preds)
    assert ec.expected_calibration_error(1) < 0.05
    mean_p, obs = ec.reliability_diagram(1)
    valid = ~np.isnan(mean_p)
    np.testing.assert_allclose(mean_p[valid], obs[valid], atol=0.12)
    # a maximally overconfident predictor has large ECE
    ec2 = EvaluationCalibration()
    always1 = np.stack([np.zeros(n), np.ones(n)], 1).astype(np.float32)
    ec2.eval(labels, always1)
    assert ec2.expected_calibration_error(1) > 0.4
    assert ec.get_residual_plot_all_classes().sum() == 2 * n
    assert ec.get_probability_histogram(1).sum() == n
    assert "ECE" in ec.stats()


def test_evaluation_binary_per_output_metrics():
    from deeplearning4j_tpu.train import EvaluationBinary
    ev = EvaluationBinary()
    labels = np.array([[1, 0], [1, 1], [0, 0], [0, 1]], np.float32)
    preds = np.array([[0.9, 0.2], [0.8, 0.4], [0.3, 0.1], [0.6, 0.7]],
                     np.float32)
    ev.eval(labels[:2], preds[:2])
    ev.eval(labels[2:], preds[2:])     # accumulates
    assert ev.num_labels() == 2
    # output 0: tp=2 fp=1 tn=1 fn=0
    assert ev.true_positives(0) == 2 and ev.false_positives(0) == 1
    assert abs(ev.accuracy(0) - 0.75) < 1e-9
    assert abs(ev.precision(0) - 2 / 3) < 1e-9
    assert abs(ev.recall(0) - 1.0) < 1e-9
    # output 1: tp=1 fp=0 tn=2 fn=1
    assert abs(ev.recall(1) - 0.5) < 1e-9
    assert "f1=" in ev.stats()

