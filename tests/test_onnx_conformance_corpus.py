"""ONNX per-op conformance corpus.

Counterpart of tests/test_tf_conformance_corpus.py for the ONNX surface
(reference: samediff-import-onnx's op-mapper tests).  No `onnx` package
exists in the image, so each case AUTHORS its graph with the in-repo
`onnx_proto` codec and conformance-checks the import against torch's own
op (the exporter whose graphs this importer targets)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as TTF

from deeplearning4j_tpu.modelimport.onnx_import import import_onnx_model
from tests.test_onnx_import import _N, _model, _vi
from deeplearning4j_tpu.modelimport.onnx_proto import (attr_f, attr_i,
                                                       attr_ints, attr_s,
                                                       attr_t)

rs = np.random.RandomState(7)


def F(*s, lo=-2.0, hi=2.0):
    return rs.uniform(lo, hi, s).astype(np.float32)


CORPUS = []


def case(name, nodes, inputs, inits, golden, tol=1e-5):
    CORPUS.append((name, nodes, inputs, inits, golden, tol))


def _t(a):
    return torch.from_numpy(np.asarray(a))


# ---- conv family ----
_x_img = F(2, 3, 6, 6)
_w_conv = F(4, 3, 3, 3, lo=-0.4, hi=0.4)
case("conv-pads-dil",
     [_N("Conv", ["x", "w"], ["y"], attr_ints("pads", [2, 2, 2, 2]),
         attr_ints("dilations", [2, 2]), attr_ints("strides", [1, 1]),
         attr_ints("kernel_shape", [3, 3]))],
     {"x": _x_img}, {"w": _w_conv},
     lambda x: TTF.conv2d(_t(x), _t(_w_conv), padding=2,
                          dilation=2).numpy())

_w_dec = F(3, 4, 3, 3, lo=-0.4, hi=0.4)
_b_dec = F(4)
case("convtranspose",
     [_N("ConvTranspose", ["x", "w", "b"], ["y"],
         attr_ints("strides", [2, 2]), attr_ints("pads", [1, 1, 1, 1]),
         attr_ints("output_padding", [1, 1]),
         attr_ints("kernel_shape", [3, 3]))],
     {"x": _x_img}, {"w": _w_dec, "b": _b_dec},
     lambda x: TTF.conv_transpose2d(_t(x), _t(_w_dec), _t(_b_dec),
                                    stride=2, padding=1,
                                    output_padding=1).numpy())

case("maxpool-pads",
     [_N("MaxPool", ["x"], ["y"], attr_ints("kernel_shape", [3, 3]),
         attr_ints("strides", [2, 2]), attr_ints("pads", [1, 1, 1, 1]))],
     {"x": _x_img}, {},
     lambda x: TTF.max_pool2d(_t(x), 3, 2, padding=1).numpy())

case("avgpool-include-pad",
     [_N("AveragePool", ["x"], ["y"], attr_ints("kernel_shape", [2, 2]),
         attr_ints("strides", [2, 2]), attr_ints("pads", [1, 1, 1, 1]),
         attr_i("count_include_pad", 1))],
     {"x": _x_img}, {},
     lambda x: TTF.avg_pool2d(_t(x), 2, 2, padding=1,
                              count_include_pad=True).numpy())

_bn_s, _bn_b = F(3, lo=0.5, hi=1.5), F(3)
_bn_m, _bn_v = F(3), F(3, lo=0.5, hi=1.5)
case("batchnorm-inference",
     [_N("BatchNormalization", ["x", "s", "b", "m", "v"], ["y"],
         attr_f("epsilon", 1e-4))],
     {"x": _x_img}, {"s": _bn_s, "b": _bn_b, "m": _bn_m, "v": _bn_v},
     lambda x: TTF.batch_norm(_t(x), _t(_bn_m), _t(_bn_v), _t(_bn_s),
                              _t(_bn_b), False, 0.0, 1e-4).numpy(),
     tol=1e-4)

_w_asym = F(4, 3, 3, 3, lo=-0.4, hi=0.4)
case("conv-asym-pads",
     # ONNX pads = [t, l, b, r]: asymmetric values pin the ordering
     [_N("Conv", ["x", "w"], ["y"], attr_ints("pads", [0, 1, 2, 0]),
         attr_ints("strides", [1, 1]),
         attr_ints("kernel_shape", [3, 3]))],
     {"x": _x_img}, {"w": _w_asym},
     lambda x: TTF.conv2d(
         _t(np.pad(x, ((0, 0), (0, 0), (0, 2), (1, 0)))),
         _t(_w_asym)).numpy())

_w_grp = F(6, 1, 3, 3, lo=-0.4, hi=0.4)   # groups=3 over Ci=3
case("conv-groups",
     [_N("Conv", ["x", "w"], ["y"], attr_i("group", 3),
         attr_ints("pads", [1, 1, 1, 1]),
         attr_ints("kernel_shape", [3, 3]))],
     {"x": _x_img}, {"w": _w_grp},
     lambda x: TTF.conv2d(_t(x), _t(_w_grp), padding=1,
                          groups=3).numpy())

_w_dil_dec = F(2, 3, 2, 2, lo=-0.4, hi=0.4)
case("convtranspose-dilated",
     [_N("ConvTranspose", ["x", "w"], ["y"],
         attr_ints("dilations", [2, 2]),
         attr_ints("kernel_shape", [2, 2]))],
     {"x": F(1, 2, 4, 4)}, {"w": _w_dil_dec},
     lambda x: TTF.conv_transpose2d(_t(x), _t(_w_dil_dec),
                                    dilation=2).numpy())

# ---- linalg ----
_gw = F(5, 4, lo=-0.5, hi=0.5)
_gc = F(5)
case("gemm-transB-alpha",
     [_N("Gemm", ["x", "w", "c"], ["y"], attr_f("alpha", 0.5),
         attr_f("beta", 2.0), attr_i("transB", 1))],
     {"x": F(3, 4)}, {"w": _gw, "c": _gc},
     lambda x: (0.5 * (_t(x) @ _t(_gw).T) + 2.0 * _t(_gc)).numpy())

_mmw = F(2, 4, 5, lo=-0.5, hi=0.5)
case("matmul-batched",
     [_N("MatMul", ["x", "w"], ["y"])],
     {"x": F(2, 3, 4)}, {"w": _mmw},
     lambda x: (_t(x) @ _t(_mmw)).numpy())

# ---- norm ----
_ln_g, _ln_b = F(6, lo=0.5, hi=1.5), F(6)
case("layernorm",
     [_N("LayerNormalization", ["x", "g", "b"], ["y"],
         attr_f("epsilon", 1e-5), attr_i("axis", -1))],
     {"x": F(4, 6)}, {"g": _ln_g, "b": _ln_b},
     lambda x: TTF.layer_norm(_t(x), (6,), _t(_ln_g), _t(_ln_b),
                              1e-5).numpy(), tol=1e-4)

# ---- shape / slicing ----
case("slice-neg-step",
     [_N("Slice", ["x", "starts", "ends", "axes", "steps"], ["y"])],
     {"x": F(4, 6)},
     {"starts": np.asarray([3, 5], np.int64),
      "ends": np.asarray([0, 0], np.int64),
      "axes": np.asarray([0, 1], np.int64),
      "steps": np.asarray([-1, -2], np.int64)},
     lambda x: np.ascontiguousarray(x[3:0:-1, 5:0:-2]))

case("pad-reflect",
     [_N("Pad", ["x", "pads"], ["y"], attr_s("mode", "reflect"))],
     {"x": F(3, 4)},
     {"pads": np.asarray([1, 1, 1, 1], np.int64)},
     lambda x: TTF.pad(_t(x)[None, None], (1, 1, 1, 1),
                       mode="reflect")[0, 0].numpy())

case("split-uneven",
     [_N("Split", ["x", "sizes"], ["a", "b"], attr_i("axis", 1)),
      _N("Concat", ["b", "a"], ["y"], attr_i("axis", 1))],
     {"x": F(3, 7)},
     {"sizes": np.asarray([3, 4], np.int64)},
     lambda x: np.concatenate([x[:, 3:], x[:, :3]], 1))

case("squeeze-unsqueeze",
     [_N("Unsqueeze", ["x", "ax1"], ["u"]),
      _N("Squeeze", ["u", "ax2"], ["y"])],
     {"x": F(3, 4)},
     {"ax1": np.asarray([1], np.int64), "ax2": np.asarray([1], np.int64)},
     lambda x: x)

case("transpose-reshape",
     [_N("Transpose", ["x"], ["t"], attr_ints("perm", [2, 0, 1])),
      _N("Reshape", ["t", "shp"], ["y"])],
     {"x": F(2, 3, 4)},
     {"shp": np.asarray([4, -1], np.int64)},
     lambda x: x.transpose(2, 0, 1).reshape(4, -1))

case("flatten-axis2",
     [_N("Flatten", ["x"], ["y"], attr_i("axis", 2))],
     {"x": F(2, 3, 4, 5)}, {},
     lambda x: x.reshape(6, 20))

case("gather-axis1",
     [_N("Gather", ["x", "idx"], ["y"], attr_i("axis", 1))],
     {"x": F(3, 5)},
     {"idx": np.asarray([4, 0, 2], np.int64)},
     lambda x: x[:, [4, 0, 2]])

# ---- elementwise / logic ----
case("arith-chain",
     [_N("Add", ["x", "x"], ["a"]),
      _N("Mul", ["a", "x"], ["m"]),
      _N("Sub", ["m", "x"], ["s"]),
      _N("Div", ["s", "d"], ["y"])],
     {"x": F(3, 4, lo=0.5, hi=2.0)},
     {"d": np.full((3, 4), 2.0, np.float32)},
     lambda x: ((x + x) * x - x) / 2.0)

case("activations",
     [_N("Relu", ["x"], ["r"]),
      _N("Elu", ["r"], ["e"], attr_f("alpha", 1.0)),
      _N("LeakyRelu", ["x"], ["l"], attr_f("alpha", 0.2)),
      _N("Add", ["e", "l"], ["a1"]),
      _N("Softplus", ["x"], ["sp"]),
      _N("Add", ["a1", "sp"], ["y"])],
     {"x": F(4, 5)}, {},
     lambda x: (TTF.elu(TTF.relu(_t(x)))
                + TTF.leaky_relu(_t(x), 0.2)
                + TTF.softplus(_t(x))).numpy())

case("clip-minmax",
     [_N("Clip", ["x", "lo", "hi"], ["y"])],
     {"x": F(3, 4, lo=-3, hi=3)},
     {"lo": np.float32(-1.0), "hi": np.float32(1.5)},
     lambda x: np.clip(x, -1.0, 1.5))

case("where-greater",
     [_N("Greater", ["x", "z"], ["g"]),
      _N("Where", ["g", "x", "z"], ["y"])],
     {"x": F(3, 4)},
     {"z": np.zeros((3, 4), np.float32)},
     lambda x: np.where(x > 0, x, 0.0))

case("softmax-logsoftmax-axis",
     [_N("Softmax", ["x"], ["s"], attr_i("axis", 1)),
      _N("LogSoftmax", ["x"], ["l"], attr_i("axis", 1)),
      _N("Add", ["s", "l"], ["y"])],
     {"x": F(3, 5, 2)}, {},
     lambda x: (TTF.softmax(_t(x), 1)
                + TTF.log_softmax(_t(x), 1)).numpy(), tol=1e-4)

case("reduce-axes-keepdims",
     [_N("ReduceMean", ["x", "axes"], ["m"], attr_i("keepdims", 1)),
      _N("Sub", ["x", "m"], ["y"])],
     {"x": F(3, 4, 5)},
     {"axes": np.asarray([1, 2], np.int64)},
     lambda x: x - x.mean((1, 2), keepdims=True), tol=1e-4)

case("argmax-keepdims0",
     [_N("ArgMax", ["x"], ["i"], attr_i("axis", 1),
         attr_i("keepdims", 0)),
      _N("Cast", ["i"], ["y"], attr_i("to", 1))],   # 1 = FLOAT
     {"x": F(4, 6)}, {},
     lambda x: x.argmax(1).astype(np.float32))

case("dropout-inference",
     [_N("Dropout", ["x"], ["y"], attr_f("ratio", 0.5))],
     {"x": F(3, 4)}, {},
     lambda x: x)

case("pow-sqrt-reciprocal",
     [_N("Pow", ["x", "e"], ["p"]),
      _N("Sqrt", ["p"], ["sq"]),
      _N("Reciprocal", ["sq"], ["y"])],
     {"x": F(3, 4, lo=0.5, hi=2.0)},
     {"e": np.full((), 2.0, np.float32)},
     lambda x: 1.0 / np.sqrt(x ** 2), tol=1e-4)



# ---- round-5 opset tail: shape/broadcast/norm/activation/misc ----
_x_sm = F(2, 3, 4, 5)
case("shape-expand",
     [_N("Shape", ["x"], ["s"]),
      _N("Expand", ["x2", "tgt"], ["y"])],
     {"x": _x_sm, "x2": F(3, 1, 5)},
     {"tgt": np.asarray([2, 3, 4, 5], np.int64)},
     lambda x, x2: (np.asarray(x.shape, np.int64),
                    np.broadcast_to(x2, (2, 3, 4, 5)))[1])

case("tile",
     [_N("Tile", ["x", "reps"], ["y"])],
     {"x": F(2, 3)}, {"reps": np.asarray([2, 3], np.int64)},
     lambda x: np.tile(x, (2, 3)))

case("constantofshape-range",
     [_N("ConstantOfShape", ["shp"], ["c"],
         attr_t("value", np.asarray([2.5], np.float32))),
      _N("Range", ["r0", "r1", "r2"], ["r"]),
      _N("Mul", ["c", "r"], ["y"])],
     {}, {"shp": np.asarray([4], np.int64),
          "r0": np.asarray(0, np.float32),
          "r1": np.asarray(4, np.float32),
          "r2": np.asarray(1, np.float32)},
     lambda: 2.5 * np.arange(0, 4, 1, dtype=np.float32))

_x_in = F(2, 4, 6, 6)
_sc_in, _b_in = F(4, lo=0.5, hi=1.5), F(4)
case("instancenorm",
     [_N("InstanceNormalization", ["x", "s", "b"], ["y"],
         attr_f("epsilon", 1e-5))],
     {"x": _x_in}, {"s": _sc_in, "b": _b_in},
     lambda x: TTF.instance_norm(_t(x), weight=_t(_sc_in), bias=_t(_b_in),
                                 eps=1e-5).numpy(), tol=1e-4)

_slope = F(3, 1, 1, lo=0.05, hi=0.4)
case("prelu",
     [_N("PRelu", ["x", "a"], ["y"])],
     {"x": _x_img}, {"a": _slope},
     lambda x: np.where(x > 0, x, _slope[None] * x).astype(np.float32))

case("hardsigmoid-hardswish",
     [_N("HardSigmoid", ["x"], ["h"], attr_f("alpha", 1.0 / 6.0),
         attr_f("beta", 0.5)),
      _N("HardSwish", ["x"], ["w"]),
      _N("Mul", ["h", "w"], ["y"])],
     {"x": F(3, 7)}, {},
     lambda x: (TTF.hardsigmoid(_t(x)) * TTF.hardswish(_t(x))).numpy(),
     tol=1e-5)

case("cumsum-reverse-exclusive",
     [_N("CumSum", ["x", "ax"], ["y"], attr_i("exclusive", 1),
         attr_i("reverse", 1))],
     {"x": F(3, 5)}, {"ax": np.asarray(1, np.int64)},
     lambda x: np.flip(np.concatenate(
         [np.zeros((3, 1), np.float32),
          np.cumsum(np.flip(x, 1), 1)[:, :-1]], 1), 1))

case("topk",
     [_N("TopK", ["x", "k"], ["v", "i"]),
      _N("Identity", ["v"], ["y"])],
     {"x": F(4, 9)}, {"k": np.asarray([3], np.int64)},
     lambda x: torch.topk(_t(x), 3, dim=-1).values.numpy())

case("trilu-mod",
     [_N("Trilu", ["x", "k"], ["t"], attr_i("upper", 0)),
      _N("Mod", ["t", "d"], ["y"], attr_i("fmod", 1))],
     {"x": F(5, 5)}, {"k": np.asarray(1, np.int64),
                      "d": np.asarray([1.3], np.float32)},
     lambda x: np.fmod(np.tril(x, 1), np.float32(1.3)))

case("reducel2",
     [_N("ReduceL2", ["x"], ["y"], attr_ints("axes", [1]),
         attr_i("keepdims", 0))],
     {"x": F(4, 6)}, {},
     lambda x: np.sqrt((x * x).sum(1)))

case("onehot-negative-index",
     [_N("OneHot", ["i", "d", "v"], ["y"])],
     {}, {"i": np.asarray([0, 2, -1], np.int64),
          "d": np.asarray(4, np.int64),
          "v": np.asarray([-1.0, 2.0], np.float32)},
     # onnx: index -1 means depth-1
     lambda: (np.eye(4, dtype=np.float32)[[0, 2, 3]] * 3.0 - 1.0))



# ---- recurrent: ONNX LSTM / GRU vs torch.nn reference ----
_T, _B, _I, _H = 5, 2, 3, 4
_x_seq = F(_T, _B, _I)                           # onnx layout 0: [T,B,I]
_rs_lstm = np.random.RandomState(11)


def _g(*s):
    return _rs_lstm.uniform(-0.4, 0.4, s).astype(np.float32)


# torch packs gates ifgo; onnx wants iofc
_tw_ih, _tw_hh = _g(4 * _H, _I), _g(4 * _H, _H)
_tb_ih, _tb_hh = _g(4 * _H), _g(4 * _H)


def _ifgo_to_iofc(m):
    i, f, g, o = np.split(m, 4, 0)
    return np.concatenate([i, o, f, g], 0)


def _lstm_golden(x):
    lstm = torch.nn.LSTM(_I, _H, 1)
    sd_ = lstm.state_dict()
    sd_["weight_ih_l0"] = _t(_tw_ih); sd_["weight_hh_l0"] = _t(_tw_hh)
    sd_["bias_ih_l0"] = _t(_tb_ih); sd_["bias_hh_l0"] = _t(_tb_hh)
    lstm.load_state_dict(sd_)
    with torch.no_grad():
        y, _ = lstm(_t(x))
    return y.numpy()[:, None]                    # [T,1,B,H]


case("lstm",
     [_N("LSTM", ["x", "W", "R", "Bb"], ["y", "yh", "yc"],
         attr_i("hidden_size", _H))],
     {"x": _x_seq},
     {"W": _ifgo_to_iofc(_tw_ih)[None],
      "R": _ifgo_to_iofc(_tw_hh)[None],
      "Bb": np.concatenate([_ifgo_to_iofc(_tb_ih),
                            _ifgo_to_iofc(_tb_hh)])[None]},
     _lstm_golden, tol=1e-5)

# torch GRU packs gates rzn; onnx wants zrh; linear_before_reset=1
_gw_ih, _gw_hh = _g(3 * _H, _I), _g(3 * _H, _H)
_gb_ih, _gb_hh = _g(3 * _H), _g(3 * _H)


def _rzn_to_zrh(m):
    r, z, nn_ = np.split(m, 3, 0)
    return np.concatenate([z, r, nn_], 0)


def _gru_golden(x):
    gru = torch.nn.GRU(_I, _H, 1)
    sd_ = gru.state_dict()
    sd_["weight_ih_l0"] = _t(_gw_ih); sd_["weight_hh_l0"] = _t(_gw_hh)
    sd_["bias_ih_l0"] = _t(_gb_ih); sd_["bias_hh_l0"] = _t(_gb_hh)
    gru.load_state_dict(sd_)
    with torch.no_grad():
        y, _ = gru(_t(x))
    return y.numpy()[:, None]


case("gru",
     [_N("GRU", ["x", "W", "R", "Bb"], ["y"],
         attr_i("hidden_size", _H), attr_i("linear_before_reset", 1))],
     {"x": _x_seq},
     {"W": _rzn_to_zrh(_gw_ih)[None],
      "R": _rzn_to_zrh(_gw_hh)[None],
      "Bb": np.concatenate([_rzn_to_zrh(_gb_ih),
                            _rzn_to_zrh(_gb_hh)])[None]},
     _gru_golden, tol=1e-5)



case("resize-nearest-2x",
     [_N("Resize", ["x", "", "sc"], ["y"], attr_s("mode", "nearest"),
         attr_s("coordinate_transformation_mode", "asymmetric"),
         attr_s("nearest_mode", "floor"))],
     {"x": F(2, 3, 4, 5)},
     {"sc": np.asarray([1.0, 1.0, 2.0, 2.0], np.float32)},
     lambda x: TTF.interpolate(_t(x), scale_factor=2,
                               mode="nearest").numpy())

case("resize-bilinear-half-pixel",
     [_N("Resize", ["x", "", "", "sz"], ["y"], attr_s("mode", "linear"),
         attr_s("coordinate_transformation_mode", "half_pixel"))],
     {"x": F(1, 2, 5, 5)},
     {"sz": np.asarray([1, 2, 8, 9], np.int64)},
     lambda x: TTF.interpolate(_t(x), size=(8, 9), mode="bilinear",
                               align_corners=False).numpy(), tol=1e-5)



case("einsum-gathernd-lse",
     [_N("Einsum", ["x", "w"], ["e"], attr_s("equation", "bij,bjk->bik")),
      _N("ReduceLogSumExp", ["e"], ["l"], attr_ints("axes", [2]),
         attr_i("keepdims", 0)),
      _N("GatherND", ["l", "gi"], ["y"])],
     {"x": F(2, 3, 4), "w": F(2, 4, 5)},
     {"gi": np.asarray([[0, 1], [1, 2]], np.int64)},
     lambda x, w: np.asarray(
         [np.log(np.exp((x[0] @ w[0]))[1].sum()),
          np.log(np.exp((x[1] @ w[1]))[2].sum())], np.float32), tol=1e-5)

case("greater-less-or-equal",
     [_N("GreaterOrEqual", ["a", "b"], ["g"]),
      _N("LessOrEqual", ["a", "b"], ["l"]),
      _N("And", ["g", "l"], ["e"]),
      _N("Cast", ["e"], ["y"], attr_i("to", 1))],
     {"a": F(3, 4), "b": F(3, 4)}, {},
     lambda a, b: ((a >= b) & (a <= b)).astype(np.float32))



case("scatternd-argmin-trig",
     [_N("ScatterND", ["x", "si", "u"], ["s"]),
      _N("Atan", ["s"], ["t"]),
      _N("ReduceSumSquare", ["t"], ["r"], attr_ints("axes", [1]),
         attr_i("keepdims", 0)),
      _N("ArgMin", ["r"], ["am"], attr_i("axis", 0), attr_i("keepdims", 0)),
      _N("Cast", ["am"], ["y"], attr_i("to", 1))],
     {"x": F(4, 6)},
     {"si": np.asarray([[1], [3]], np.int64), "u": F(2, 6)},
     None)  # golden computed below


def _scatternd_golden(x):
    s = x.copy()
    u = CORPUS[-1][3]["u"]
    s[1], s[3] = u[0], u[1]
    t = np.arctan(s)
    r = (t * t).sum(1)
    return np.float32(np.argmin(r))


CORPUS[-1] = CORPUS[-1][:4] + (_scatternd_golden, CORPUS[-1][5])


@pytest.mark.parametrize(
    "name,nodes,inputs,inits,golden,tol", CORPUS,
    ids=[c[0] for c in CORPUS])
def test_onnx_graph_conformance(name, nodes, inputs, inits, golden, tol):
    out_name = nodes[-1].output[0]
    model = _model(nodes,
                   [_vi(k, v.shape) for k, v in inputs.items()],
                   [_vi(out_name, ())], inits)
    sd = import_onnx_model(model)
    got = np.asarray(sd.output(dict(inputs), out_name)[out_name])
    want = np.asarray(golden(*inputs.values()))
    np.testing.assert_allclose(got, want, rtol=tol, atol=tol,
                               err_msg=name)


def test_onnx_corpus_size():
    assert len(CORPUS) >= 20, len(CORPUS)
