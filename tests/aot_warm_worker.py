"""Subprocess worker for test_aot_cache.test_warm_restart_subprocess.

Trains a small MLN with its step routed through the persistent executable
cache at $DL4J_TPU_TEST_CACHE and prints one JSON line: compile/hit stats
plus the final score.  Run twice against the same directory, the second
run must report 0 compiles and the identical score — the cross-process
form of the warm-restart acceptance contract.
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.compile import PersistentExecutableCache  # noqa: E402
from deeplearning4j_tpu.nn import (DenseLayer, InputType,  # noqa: E402
                                   MultiLayerNetwork, NeuralNetConfiguration,
                                   OutputLayer)
from deeplearning4j_tpu.train.updaters import Sgd  # noqa: E402


def main():
    cache = PersistentExecutableCache(os.environ["DL4J_TPU_TEST_CACHE"])
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init().set_executable_cache(cache)

    rs = np.random.RandomState(0)
    x = rs.randn(12, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 12)]
    for _ in range(5):
        net.fit(x, y)
    print(json.dumps({
        "compiles": cache.stats["compiles"],
        "disk_hits": cache.stats["disk_hits"],
        "stores": cache.stats["stores"],
        "step_recompiles": net._train_step._cache_size(),
        "score": float(net.score()),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
