"""UI stats + profiling tests."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.train.updaters import Adam
from deeplearning4j_tpu.ui import (FileStatsStorage, InMemoryStatsStorage,
                                   StatsListener, render_html)
from deeplearning4j_tpu.utils.profiling import (PerformanceTracker,
                                                op_profile)


def _net():
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=64):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return x, y


def test_stats_listener_collects_norms_and_ratios():
    st = InMemoryStatsStorage()
    net = _net().set_listeners(StatsListener(st, frequency=1))
    x, y = _data()
    for _ in range(10):
        net.fit(x, y)
    assert len(st.score) == 10
    assert "layer_0" in st.param_norms and "layer_1" in st.param_norms
    # ratios recorded from the 2nd collection on; healthy magnitude
    ratios = [r for _, r in st.ratios["layer_0"]]
    assert len(ratios) == 9
    assert all(np.isfinite(ratios))
    assert all(1e-6 < r < 1.0 for r in ratios)


def test_file_stats_storage_roundtrip(tmp_path):
    p = str(tmp_path / "stats.jsonl")
    st = FileStatsStorage(p)
    net = _net().set_listeners(StatsListener(st, frequency=2))
    x, y = _data()
    for _ in range(6):
        net.fit(x, y)
    st.close()
    loaded = FileStatsStorage.load(p)
    assert loaded.score == st.score
    assert loaded.ratios.keys() == st.ratios.keys()


def test_render_html(tmp_path):
    st = InMemoryStatsStorage()
    net = _net().set_listeners(StatsListener(st, frequency=1))
    x, y = _data()
    for _ in range(8):
        net.fit(x, y)
    out = str(tmp_path / "report.html")
    html = render_html(st, out)
    assert os.path.exists(out)
    assert "<svg" in html and "Score vs iteration" in html
    assert "layer_0" in html


def test_op_profile_counts_primitives():
    import jax.numpy as jnp

    def f(x, w):
        return jnp.tanh(x @ w).sum()

    counts = op_profile(f, np.ones((4, 5), np.float32),
                        np.ones((5, 3), np.float32))
    assert counts.get("dot_general", 0) >= 1
    assert counts.get("tanh", 0) == 1


def test_performance_tracker():
    import jax.numpy as jnp
    tr = PerformanceTracker()
    x = jnp.ones((128, 128))
    for _ in range(3):
        with tr.step() as done:
            done(x @ x)
    assert len(tr.steps) == 3
    assert tr.mean_step_time() > 0
    assert tr.throughput(128) > 0
    assert "3 steps" in tr.summary()


def test_ui_server_serves_live_stats():
    """VERDICT weak #8: a live (auto-refreshing) training monitor, not just
    an offline report."""
    import urllib.request
    from deeplearning4j_tpu.ui import InMemoryStatsStorage, UIServer
    st = InMemoryStatsStorage()
    st.put_score(0, 1.5)
    st.put_layer(0, "layer_0", 1.0, 1e-3)
    server = UIServer()          # fresh instance; singleton untouched
    server.attach(st)
    port = server.start(port=0)
    try:
        html = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10).read().decode()
        assert "http-equiv=\"refresh\"" in html
        assert "Score vs iteration" in html
        # live: new data appears on the next request without restart
        st.put_score(1, 0.5)
        html2 = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10).read().decode()
        assert html2 != html
    finally:
        server.stop()
    assert UIServer.get_instance() is UIServer.get_instance()


def test_ui_server_attach_file_follows_other_process(tmp_path):
    """Cross-process monitoring: the server re-reads a FileStatsStorage
    written elsewhere on every request."""
    import urllib.request
    from deeplearning4j_tpu.ui import FileStatsStorage, UIServer
    path = str(tmp_path / "stats.jsonl")
    server = UIServer().attach_file(path)
    port = server.start(port=0)
    try:
        html = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10).read().decode()
        assert "No StatsStorage attached" in html      # file absent yet
        st = FileStatsStorage(path)                    # "the training job"
        st.put_score(0, 2.0)
        st.put_score(1, 1.0)
        st.close()
        html2 = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10).read().decode()
        assert "Score vs iteration" in html2
    finally:
        server.stop()


def test_stats_listener_histograms_and_system_metrics(tmp_path):
    """Round-4 StatsListener parity tail: per-layer param/update
    histograms + host/device memory metrics (reference StatsListener
    histogram + system-info chart sets), persisted through
    FileStatsStorage and rendered into the report."""
    path = str(tmp_path / "stats.jsonl")
    st = FileStatsStorage(path)
    net = _net().set_listeners(StatsListener(
        st, frequency=1, histograms=True, hist_bins=16,
        system_metrics=True))
    x, y = _data()
    for _ in range(4):
        net.fit(x, y)
    # histograms for both kinds, every layer, right bin count
    assert set(st.histograms) == {"param", "update"}
    for kind in ("param", "update"):
        assert "layer_0" in st.histograms[kind]
        it, lo, hi, counts = st.histograms[kind]["layer_0"][-1]
        assert len(counts) == 16 and lo < hi
        n_params = sum(np.asarray(p).size
                       for p in __import__("jax").tree_util.tree_leaves(
                           net.params_["layer_0"]))
        assert sum(counts) == n_params
    # system metrics include host RSS and available memory on this host
    assert st.system
    _, metrics = st.system[-1]
    assert metrics["host_rss_mb"] > 10.0
    assert metrics["host_available_mb"] > 10.0
    # persisted lines reload into an equal storage
    st.close()
    loaded = FileStatsStorage.load(path)
    for kind in ("param", "update"):
        assert (loaded.histograms[kind]["layer_0"][-1][3]
                == st.histograms[kind]["layer_0"][-1][3])
    assert loaded.system[-1][1] == st.system[-1][1]
    # the report renders histogram bars + system charts
    html = render_html(loaded)
    assert "histograms" in html and "System metrics" in html
    assert "<rect" in html
