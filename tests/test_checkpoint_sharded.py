"""Sharded multi-host checkpointing (reference: ModelSerializer at
multi-host scale, SURVEY.md §5.4's orbax-style requirement): per-process
shard writes, commit protocol, resume across a CHANGED mesh shape, and
exact training resume including updater state — all over real OS
processes via LocalLauncher."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.checkpoint import (load_sharded,
                                                    read_metadata,
                                                    save_sharded)
from deeplearning4j_tpu.parallel.multihost import LocalLauncher

WORKER = os.path.join(os.path.dirname(__file__), "mh_worker_ckpt.py")


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("data",))


def test_single_process_roundtrip_and_reshard(tmp_path):
    """Save under a 4-way mesh, restore under 2-way AND 8-way meshes and
    as host numpy — values identical, no gather at save."""
    d = str(tmp_path / "ck")
    mesh4 = _mesh(4)
    w = jnp.asarray(np.arange(64, dtype=np.float32).reshape(8, 8))
    w4 = jax.device_put(w, NamedSharding(mesh4, P("data", None)))
    b = jax.device_put(jnp.arange(5.0, dtype=jnp.float32),
                       NamedSharding(mesh4, P()))
    tree = {"w": w4, "b": b, "n": np.int64(3)}
    save_sharded(d, tree, metadata={"iteration": 7})
    assert read_metadata(d)["iteration"] == 7

    for n in (2, 8):
        mesh_n = _mesh(n)
        like = {"w": jax.ShapeDtypeStruct(
            (8, 8), np.float32,
            sharding=NamedSharding(mesh_n, P("data", None))),
            "b": jax.ShapeDtypeStruct(
                (5,), np.float32, sharding=NamedSharding(mesh_n, P())),
            "n": np.int64(0)}
        out = load_sharded(d, like)
        np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(w))
        np.testing.assert_array_equal(np.asarray(out["b"]),
                                      np.arange(5.0))
        assert int(out["n"]) == 3
        assert out["w"].sharding.mesh.shape["data"] == n

    host = load_sharded(d, {"w": np.zeros((8, 8), np.float32),
                            "b": np.zeros(5, np.float32),
                            "n": np.int64(0)})
    np.testing.assert_array_equal(host["w"], np.asarray(w))


def test_uncommitted_checkpoint_rejected(tmp_path):
    d = str(tmp_path / "ck")
    mesh = _mesh(2)
    t = {"w": jax.device_put(jnp.zeros(4),
                             NamedSharding(mesh, P("data")))}
    save_sharded(d, t)
    os.remove(os.path.join(d, "manifest.json"))
    with pytest.raises(FileNotFoundError):
        load_sharded(d, t)


def test_multiprocess_save_then_local_reshard(tmp_path):
    """2 real processes write only their own shards; this (single) process
    restores the full tree under its own mesh."""
    d = str(tmp_path / "ck")
    LocalLauncher(num_processes=2).run(WORKER, ["save", d], timeout=240)
    # every rank wrote a shard file; neither gathered the whole array
    assert os.path.exists(os.path.join(d, "shards-0.npz"))
    assert os.path.exists(os.path.join(d, "shards-1.npz"))
    idx0 = os.path.getsize(os.path.join(d, "shards-0.npz"))
    idx1 = os.path.getsize(os.path.join(d, "shards-1.npz"))
    assert idx0 > 0 and idx1 > 0

    mesh = _mesh(4)   # DIFFERENT mesh shape than the 2-process save
    like = {"w": jax.ShapeDtypeStruct(
        (8, 6), np.float32,
        sharding=NamedSharding(mesh, P("data", None))),
        "b": jax.ShapeDtypeStruct((5,), np.float32,
                                  sharding=NamedSharding(mesh, P())),
        "step": np.int64(0), "host": np.zeros(3, np.float32)}
    out = load_sharded(d, like)
    np.testing.assert_array_equal(
        np.asarray(out["w"]),
        np.arange(48, dtype=np.float32).reshape(8, 6))
    np.testing.assert_array_equal(np.asarray(out["b"]),
                                  np.arange(5.0) * 2)
    assert int(out["step"]) == 17
    np.testing.assert_array_equal(out["host"], np.full(3, 9.0))


def test_multiprocess_exact_resume(tmp_path):
    """Train k steps -> sharded save -> k more (oracle); fresh cluster
    restores and trains k -> params must match the oracle bit-for-bit
    (updater state + counters round-trip)."""
    d = str(tmp_path / "ck")
    LocalLauncher(num_processes=2).run(
        WORKER, ["train_save", d, "3"], timeout=300)
    LocalLauncher(num_processes=2).run(
        WORKER, ["resume", d, "3"], timeout=300)
    oracle = np.load(os.path.join(d, "oracle.npz"))["params"]
    resumed = np.load(os.path.join(d, "resumed.npz"))["params"]
    np.testing.assert_array_equal(resumed, oracle)
