"""Elastic-training worker (spawned by test_multihost via
ElasticLocalRunner — NOT a pytest file).

Trains an MLN across processes with per-step checkpoints; on the FIRST
launch, rank 1 deliberately crashes partway (marker file guards the
one-shot crash).  The relaunch must resume from the checkpoint and finish
all steps — proving failure detection (coordination-service heartbeat
kills the gang) + elastic restart + exact resume.

Two checkpoint paths:
* `DL4J_TPU_CHECKPOINT_DIR` set (ElasticLocalRunner.run(checkpoint_dir=))
  — sharded `train.resilience.CheckpointManager` checkpoints: every rank
  writes its shards, commit is the atomic manifest, resume goes through
  the resharding loader (full state incl. RNG and counters).
* unset — legacy single-process zip via rank 0 (the pre-resilience path).
"""
import os
import sys

import numpy as np

from deeplearning4j_tpu.parallel import multihost

multihost.initialize()

import jax  # noqa: E402

from deeplearning4j_tpu.nn import (DenseLayer, InputType,  # noqa: E402
                                   MultiLayerNetwork, NeuralNetConfiguration,
                                   OutputLayer)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper  # noqa: E402
from deeplearning4j_tpu.train import Sgd  # noqa: E402

work_dir = sys.argv[1]
total_steps = int(sys.argv[2])
crash_at = int(sys.argv[3])
rank = multihost.process_index()
ckpt_dir = os.environ.get(multihost.ENV_CKPT)
ckpt = os.path.join(work_dir, "ckpt.zip")
crash_marker = os.path.join(work_dir, "crashed_once")

rng = np.random.default_rng(0)
X = rng.standard_normal((16, 10)).astype(np.float32)
Y = np.eye(2, dtype=np.float32)[(X.sum(1) > 0).astype(int)]
per = X.shape[0] // multihost.process_count()
xl = X[rank * per:(rank + 1) * per]
yl = Y[rank * per:(rank + 1) * per]


def build():
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .list([DenseLayer(n_out=16, activation="tanh"),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(10)).build())
    return MultiLayerNetwork(conf).init()


manager = None
if ckpt_dir:
    from deeplearning4j_tpu.train.resilience import CheckpointManager
    manager = CheckpointManager(ckpt_dir, keep_last=2)
    net = build()
elif os.path.exists(ckpt):
    net = MultiLayerNetwork.load(ckpt)
    print(f"rank {rank}: resumed at iteration {net.iteration}", flush=True)
else:
    net = build()

mesh = multihost.global_mesh()
pw = ParallelWrapper(net, mesh)
if manager is not None and manager.latest_step() is not None:
    # place FIRST so the resharding loader assembles straight at the
    # global sharding (a committed single-device array can't be re-placed
    # across processes on the CPU backend)
    pw._place_model()
    manager.restore(net)
    print(f"rank {rank}: resumed at iteration {net.iteration}", flush=True)
while net.iteration < total_steps:
    if (net.iteration == crash_at and rank == 1
            and not os.path.exists(crash_marker)):
        open(crash_marker, "w").write("1")
        print(f"rank {rank}: simulating crash at {net.iteration}",
              flush=True)
        os._exit(1)
    pw.fit_host_local(xl, yl)
    # materialize the step on EVERY rank before the next loop turn: jax
    # dispatch is async, so without this a crashing rank can take down
    # collectives that logically "happened" steps ago
    jax.block_until_ready(net.params_)
    if manager is not None:
        # every rank participates (save barrier); commit is atomic
        manager.save(net, block=True)
    elif rank == 0:
        # atomic checkpoint: a mid-write kill must not corrupt the file
        net.save(ckpt + ".tmp")
        os.replace(ckpt + ".tmp", ckpt)
if rank == 0:
    np.savez(os.path.join(work_dir, "final.npz"),
             params=np.asarray(net.params()),
             iteration=np.int64(net.iteration))
print(f"rank {rank}: done at iteration {net.iteration}", flush=True)
