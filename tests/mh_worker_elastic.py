"""Elastic-training worker (spawned by test_multihost via
ElasticLocalRunner — NOT a pytest file).

Trains an MLN across processes with per-step checkpoints; on the FIRST
launch, rank 1 deliberately crashes partway (marker file guards the
one-shot crash).  The relaunch must resume from the checkpoint and finish
all steps — proving failure detection (coordination-service heartbeat
kills the gang) + elastic restart + exact resume."""
import os
import sys

import numpy as np

from deeplearning4j_tpu.parallel import multihost

multihost.initialize()

import jax  # noqa: E402

from deeplearning4j_tpu.nn import (DenseLayer, InputType,  # noqa: E402
                                   MultiLayerNetwork, NeuralNetConfiguration,
                                   OutputLayer)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper  # noqa: E402
from deeplearning4j_tpu.train import Sgd  # noqa: E402

work_dir = sys.argv[1]
total_steps = int(sys.argv[2])
crash_at = int(sys.argv[3])
rank = multihost.process_index()
ckpt = os.path.join(work_dir, "ckpt.zip")
crash_marker = os.path.join(work_dir, "crashed_once")

rng = np.random.default_rng(0)
X = rng.standard_normal((16, 10)).astype(np.float32)
Y = np.eye(2, dtype=np.float32)[(X.sum(1) > 0).astype(int)]
per = X.shape[0] // multihost.process_count()
xl = X[rank * per:(rank + 1) * per]
yl = Y[rank * per:(rank + 1) * per]

if os.path.exists(ckpt):
    net = MultiLayerNetwork.load(ckpt)
    print(f"rank {rank}: resumed at iteration {net.iteration}", flush=True)
else:
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .list([DenseLayer(n_out=16, activation="tanh"),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(10)).build())
    net = MultiLayerNetwork(conf).init()

mesh = multihost.global_mesh()
pw = ParallelWrapper(net, mesh)
while net.iteration < total_steps:
    if (net.iteration == crash_at and rank == 1
            and not os.path.exists(crash_marker)):
        open(crash_marker, "w").write("1")
        print(f"rank {rank}: simulating crash at {net.iteration}",
              flush=True)
        os._exit(1)
    pw.fit_host_local(xl, yl)
    # materialize the step on EVERY rank before the next loop turn: jax
    # dispatch is async, so without this a crashing rank can take down
    # collectives that logically "happened" steps ago
    jax.block_until_ready(net.params_)
    if rank == 0:
        # atomic checkpoint: a mid-write kill must not corrupt the file
        net.save(ckpt + ".tmp")
        os.replace(ckpt + ".tmp", ckpt)
if rank == 0:
    np.savez(os.path.join(work_dir, "final.npz"),
             params=np.asarray(net.params()),
             iteration=np.int64(net.iteration))
print(f"rank {rank}: done at iteration {net.iteration}", flush=True)
