"""Metrics-docs conformance: every metric family any `*Instruments`
class can register must have a row in docs/observability.md.

The test instantiates EVERY instruments bundle on a fresh registry and
touches each lazily-created labeled child, so the family list below is
the real registered surface, not a hand-maintained copy.  A new metric
added without a docs row fails here — the docs table is load-bearing.
"""
import os

import pytest

from deeplearning4j_tpu.monitor import instrument as I
from deeplearning4j_tpu.monitor.forecast import ArrivalRateForecaster
from deeplearning4j_tpu.monitor.registry import MetricsRegistry

DOCS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "observability.md")

# Families registered through the process-global registry by code that
# cannot run against an injected one (utils.chaos counts via the global
# singleton); kept literal so a rename still trips the docs check.
GLOBAL_REGISTRY_FAMILIES = {"chaos_faults_injected_total"}


def _register_everything(reg: MetricsRegistry):
    """Instantiate every instruments bundle and touch every lazy child."""
    I.TrainingInstruments("mlp", reg)
    I.PipelineInstruments(reg)
    I.ParallelInstruments(reg)
    I.ResilienceInstruments(reg)
    I.AotCacheInstruments(reg)
    I.CommsInstruments(reg)
    I.GangInstruments(reg).reformations("crash")
    fleet = I.FleetInstruments(reg)
    fleet.requests("m")
    fleet.sheds("m", 0)
    fleet.breaches("m")
    fleet.respawns("poisoned")
    fleet.breaker_state("m")
    fed = I.FederationInstruments(reg)
    fed.evictions("crash")
    fed.record_replacement(True, 1.0)
    I.QuantInstruments(reg).models("int8")
    I.OpsInstruments(reg).dispatch("matmul", "pallas")
    dec = I.DecodeInstruments(reg)
    dec.tokens("m")
    dec.inter_token("m")
    dec.kv_blocks("m")
    dec.kv_bytes("m", "int8")
    dec.sequences_active("m")
    dec.restarts("m")
    arb = I.ArbiterInstruments(reg)
    arb.handoffs("to_serving", "committed")
    arb.slices("training")
    # forecaster gauge is minted on the first post-baseline tick
    fc = ArrivalRateForecaster(registry_=reg)
    reg.counter("fleet_requests_total", labels={"model": "m"}).inc(10)
    fc.tick(now=100.0)
    reg.counter("fleet_requests_total", labels={"model": "m"}).inc(10)
    fc.tick(now=101.0)


def test_every_registered_family_is_documented():
    reg = MetricsRegistry()
    _register_everything(reg)
    families = set(reg.families()) | GLOBAL_REGISTRY_FAMILIES
    assert "fleet_arrival_forecast" in families  # forecaster ticked above
    with open(DOCS) as f:
        doc = f.read()
    missing = sorted(n for n in families if n not in doc)
    assert not missing, (
        f"{len(missing)} metric families lack a docs/observability.md "
        f"row: {missing}")


def test_documented_series_exist():
    """The reverse direction: every `things_total`-shaped name the docs
    table mentions must still be a registrable family — rows must not
    outlive a metric rename."""
    import re
    reg = MetricsRegistry()
    _register_everything(reg)
    families = set(reg.families()) | GLOBAL_REGISTRY_FAMILIES
    with open(DOCS) as f:
        doc = f.read()
    # backticked bare family names in table rows (strip label stubs);
    # wildcard rows like `serving_*{server=}` document a namespace that
    # lives outside the instruments bundles — skip those
    stale = []
    for m in re.finditer(r"`([a-z0-9_]+)(?:\{[^`]*\})?`", doc):
        name = m.group(1)
        prefix = name.split("_")[0]
        if prefix in ("training", "pipeline", "parallel", "resilience",
                      "aot", "comms", "gang", "fleet", "fed", "quant",
                      "ops", "chaos", "decode", "arbiter") \
                and name not in families:
            stale.append(name)
    assert not stale, f"docs rows reference unknown families: {sorted(set(stale))}"


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
