"""NLP tests (reference: `BertWordPieceTokenizerTests.java`,
`Word2VecTests.java`, `TestBertIterator.java`)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (BertIterator, BertWordPieceTokenizer,
                                    CommonPreprocessor,
                                    DefaultTokenizerFactory, Word2Vec)


VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
         "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over",
         "lazy", "dog", "un", "##able", "."]


def test_default_tokenizer():
    tf = DefaultTokenizerFactory(CommonPreprocessor())
    assert tf.tokenize("The QUICK, brown fox!") == ["the", "quick", "brown",
                                                    "fox"]


def test_wordpiece_tokenizer():
    tok = BertWordPieceTokenizer(VOCAB)
    assert tok.tokenize("the quick fox") == ["the", "quick", "fox"]
    # continuation pieces
    assert tok.tokenize("jumped") == ["jump", "##ed"]
    assert tok.tokenize("jumps") == ["jump", "##s"]
    assert tok.tokenize("unable") == ["un", "##able"]
    # unknown word
    assert tok.tokenize("zebra") == ["[UNK]"]
    # punctuation split
    assert tok.tokenize("dog.") == ["dog", "."]


def test_wordpiece_encode_decode():
    tok = BertWordPieceTokenizer(VOCAB)
    ids = tok.encode("the quick jumped")
    assert tok.decode(ids) == "the quick jumped"


def _corpus():
    # two topic clusters: animals co-occur, numbers co-occur
    animal = "cat dog cat dog bird cat dog bird".split()
    nums = "one two one two three one two three".split()
    sents = []
    rng = np.random.RandomState(0)
    for _ in range(200):
        base = animal if rng.rand() < 0.5 else nums
        sents.append(" ".join(rng.permutation(base)))
    return sents


def test_word2vec_learns_cooccurrence():
    w2v = (Word2Vec.builder()
           .min_word_frequency(2).layer_size(16).window_size(3)
           .negative_sample(4).epochs(3).learning_rate(0.01)
           .batch_size(256).seed(1).build())
    w2v.fit(_corpus())
    assert w2v.has_word("cat") and w2v.has_word("one")
    assert w2v.get_word_vector("cat").shape == (16,)
    # words from the same cluster are closer than across clusters
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "two")
    near = w2v.words_nearest("one", 2)
    assert set(near) <= {"two", "three"}


def test_word2vec_save_load(tmp_path):
    w2v = (Word2Vec.builder().min_word_frequency(1).layer_size(8)
           .epochs(1).seed(0).build())
    w2v.fit(["a b c a b c", "c b a c b a"])
    p = str(tmp_path / "w2v.npz")
    w2v.save(p)
    w2 = Word2Vec.load(p)
    np.testing.assert_array_equal(w2.get_word_vector("a"),
                                  w2v.get_word_vector("a"))


def test_bert_iterator_masked_lm():
    tok = BertWordPieceTokenizer(VOCAB)
    sents = ["the quick brown fox jumped over the lazy dog"] * 8
    it = BertIterator(tok, sents, batch_size=4, max_length=12,
                      task=BertIterator.TASK_UNSUPERVISED, seed=3)
    batches = list(it)
    assert len(batches) == 2
    mds = batches[0]
    ids, mask = mds.features
    assert ids.shape == (4, 12) and mask.shape == (4, 12)
    (labels,) = mds.labels
    assert labels.shape == (4, 12, len(VOCAB))
    (lmask,) = mds.labels_masks
    # masked positions carry one-hot original tokens
    b, t = np.nonzero(lmask)
    assert len(b) > 0
    orig = tok.encode(sents[0])
    for bi, ti in zip(b, t):
        assert labels[bi, ti].sum() == 1.0
        assert labels[bi, ti].argmax() == orig[ti]
    # at least some selected positions replaced with [MASK]
    assert (ids[b, t] == tok.vocab["[MASK]"]).any()


def test_bert_iterator_classification():
    tok = BertWordPieceTokenizer(VOCAB)
    sents = ["the quick fox", "lazy dog", "the dog", "quick brown fox"]
    it = BertIterator(tok, sents, batch_size=2, max_length=6,
                      task=BertIterator.TASK_SEQ_CLASSIFICATION,
                      labels=[0, 1, 1, 0], n_classes=2)
    batches = list(it)
    assert len(batches) == 2
    (y,) = batches[0].labels
    np.testing.assert_array_equal(y, [[1, 0], [0, 1]])


def test_bert_iterator_requires_mask_token():
    with pytest.raises(ValueError, match="MASK"):
        BertIterator(BertWordPieceTokenizer(["[UNK]", "a", "b"]),
                     ["a"], 1, 4)


def test_tokenizer_requires_unk_token():
    with pytest.raises(ValueError, match="unknown-token"):
        BertWordPieceTokenizer(["a", "b"])


def test_word2vec_cbow_learns():
    w2v = (Word2Vec.builder()
           .min_word_frequency(2).layer_size(16).window_size(3)
           .negative_sample(4).epochs(3).learning_rate(0.01)
           .batch_size(256).seed(1)
           .elements_learning_algorithm("CBOW").build())
    assert w2v.elements_algo == "cbow"
    w2v.fit(_corpus())
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "two")


# ---------------------------------------------------------------------------
# GloVe + ParagraphVectors (VERDICT #8 NLP parity)
# ---------------------------------------------------------------------------

def _topic_corpus(n_per=40, seed=0):
    """Two-topic corpus: fruit sentences and vehicle sentences."""
    rng = np.random.RandomState(seed)
    fruit = ["apple", "banana", "cherry", "mango", "grape"]
    vehicle = ["car", "truck", "train", "plane", "bus"]
    glue = ["the", "a", "some", "fresh", "fast"]
    docs = []
    for words in (fruit, vehicle):
        for _ in range(n_per):
            docs.append(" ".join(
                rng.choice(glue) if rng.rand() < 0.3 else rng.choice(words)
                for _ in range(12)))
    return docs, fruit, vehicle


def test_glove_nearest_neighbors_respect_topics():
    from deeplearning4j_tpu.nlp import Glove
    docs, fruit, vehicle = _topic_corpus()
    glove = (Glove.builder().layer_size(24).window_size(4)
             .min_word_frequency(2).epochs(40).learning_rate(0.05)
             .seed(1).build())
    glove.fit(docs)
    assert glove.has_word("apple") and glove.has_word("car")
    # within-topic similarity must dominate cross-topic
    within = np.mean([glove.similarity("apple", w)
                      for w in fruit if w != "apple"])
    across = np.mean([glove.similarity("apple", w) for w in vehicle])
    assert within > across, (within, across)
    near = glove.words_nearest("car", 3)
    assert any(w in vehicle for w in near), near


def test_glove_save_load_roundtrip(tmp_path):
    from deeplearning4j_tpu.nlp import Glove
    docs, _, _ = _topic_corpus(n_per=10)
    g = (Glove.builder().layer_size(8).window_size(3).min_word_frequency(2)
         .epochs(3).seed(0).build())
    g.fit(docs)
    p = str(tmp_path / "glove.npz")
    g.save(p)
    g2 = Glove.load(p)
    np.testing.assert_array_equal(g.get_word_vector("the"),
                                  g2.get_word_vector("the"))


def test_paragraph_vectors_classifies_topics():
    from deeplearning4j_tpu.nlp import ParagraphVectors
    docs, fruit, vehicle = _topic_corpus(n_per=12, seed=2)
    labels = [f"fruit_{i}" for i in range(12)] \
        + [f"vehicle_{i}" for i in range(12)]
    # one doc per label: first 12 are fruit, next 12 vehicle
    pv = (ParagraphVectors.builder().layer_size(24).window_size(3)
          .min_word_frequency(2).epochs(300).learning_rate(0.3)
          .batch_size(64).seed(5).infer_epochs(60).build())
    pv.fit(docs, labels)
    assert pv.doc_vectors.shape == (24, 24)
    # an unseen fruit-y document lands nearer fruit doc vectors
    near = pv.nearest_labels("fresh apple banana cherry mango grape", n=5)
    n_fruit = sum(1 for l in near if l.startswith("fruit"))
    assert n_fruit >= 3, near


def test_paragraph_vectors_dbow_and_roundtrip(tmp_path):
    from deeplearning4j_tpu.nlp import ParagraphVectors
    docs, _, _ = _topic_corpus(n_per=6, seed=3)
    pv = (ParagraphVectors.builder().layer_size(12).window_size(3)
          .min_word_frequency(2).epochs(5)
          .sequence_learning_algorithm("DBOW").seed(1).build())
    pv.fit(docs)
    assert pv.sequence_algo == "dbow"
    p = str(tmp_path / "pv.npz")
    pv.save(p)
    pv2 = ParagraphVectors.load(p)
    np.testing.assert_array_equal(pv.doc_vectors, pv2.doc_vectors)
    v = pv2.infer_vector("the fresh apple")
    assert v.shape == (12,)


def test_word2vec_hierarchical_softmax_learns():
    """HS mode (reference useHierarchicSoftmax): Huffman paths as padded
    [V, L] matrices, one masked-gather step — same co-occurrence structure
    emerges as with negative sampling."""
    w2v = (Word2Vec.builder()
           .min_word_frequency(2).layer_size(16).window_size(3)
           .use_hierarchic_softmax(True).epochs(3).learning_rate(0.02)
           .batch_size(256).seed(1).build())
    w2v.fit(_corpus())
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "two")
    near = w2v.words_nearest("one", 2)
    assert set(near) <= {"two", "three"}


def test_huffman_codes_are_prefix_free_and_short_for_frequent():
    w2v = (Word2Vec.builder().min_word_frequency(1).layer_size(4)
           .use_hierarchic_softmax(True).epochs(1).build())
    w2v.fit(["a a a a a a b b c", "a a b c c b a a a"])
    CODES, POINTS, PMASK = w2v._build_huffman()
    V = len(w2v.vocab)
    lens = PMASK.sum(1).astype(int)
    # the most frequent word gets the shortest code
    assert lens[w2v.vocab["a"]] == lens.min()
    # codes are unique full paths (prefix-free by tree construction)
    paths = {tuple(CODES[i, :lens[i]]) for i in range(V)}
    assert len(paths) == V
    assert POINTS.max() <= V - 2


def test_word2vec_hs_flag_survives_save_load(tmp_path):
    w2v = (Word2Vec.builder().min_word_frequency(1).layer_size(8)
           .use_hierarchic_softmax(True).epochs(1).build())
    w2v.fit(["a b c a b c", "c b a c b a"])
    p = str(tmp_path / "hs.npz")
    w2v.save(p)
    w2 = Word2Vec.load(p)
    assert w2.use_hs
    assert w2.syn1.shape[0] == len(w2.vocab) - 1   # inner-node matrix


def test_tsne_separates_clusters():
    """Dense TPU-native t-SNE (reference BarnesHutTsne role): three
    well-separated gaussian blobs in 20-D must map to three separated
    2-D clusters, and the KL divergence must be small."""
    from deeplearning4j_tpu.nlp.tsne import TSNE
    rs = np.random.RandomState(0)
    centers = rs.randn(3, 20) * 10
    X = np.concatenate([c + rs.randn(25, 20) for c in centers])
    emb = TSNE(perplexity=8.0, n_iter=350, seed=1).fit_transform(X)
    assert emb.shape == (75, 2)
    labels = np.repeat(np.arange(3), 25)
    cents = np.stack([emb[labels == k].mean(0) for k in range(3)])
    intra = np.mean([np.linalg.norm(emb[labels == k] - cents[k], axis=1).mean()
                     for k in range(3)])
    inter = np.mean([np.linalg.norm(cents[a] - cents[b])
                     for a in range(3) for b in range(a + 1, 3)])
    assert inter > 3.0 * intra, (inter, intra)


def test_tsne_perplexity_guard():
    from deeplearning4j_tpu.nlp.tsne import TSNE
    with pytest.raises(ValueError, match="perplexity"):
        TSNE(perplexity=30.0).fit_transform(np.random.randn(10, 4))
