"""NLP tests (reference: `BertWordPieceTokenizerTests.java`,
`Word2VecTests.java`, `TestBertIterator.java`)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (BertIterator, BertWordPieceTokenizer,
                                    CommonPreprocessor,
                                    DefaultTokenizerFactory, Word2Vec)


VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
         "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over",
         "lazy", "dog", "un", "##able", "."]


def test_default_tokenizer():
    tf = DefaultTokenizerFactory(CommonPreprocessor())
    assert tf.tokenize("The QUICK, brown fox!") == ["the", "quick", "brown",
                                                    "fox"]


def test_wordpiece_tokenizer():
    tok = BertWordPieceTokenizer(VOCAB)
    assert tok.tokenize("the quick fox") == ["the", "quick", "fox"]
    # continuation pieces
    assert tok.tokenize("jumped") == ["jump", "##ed"]
    assert tok.tokenize("jumps") == ["jump", "##s"]
    assert tok.tokenize("unable") == ["un", "##able"]
    # unknown word
    assert tok.tokenize("zebra") == ["[UNK]"]
    # punctuation split
    assert tok.tokenize("dog.") == ["dog", "."]


def test_wordpiece_encode_decode():
    tok = BertWordPieceTokenizer(VOCAB)
    ids = tok.encode("the quick jumped")
    assert tok.decode(ids) == "the quick jumped"


def _corpus():
    # two topic clusters: animals co-occur, numbers co-occur
    animal = "cat dog cat dog bird cat dog bird".split()
    nums = "one two one two three one two three".split()
    sents = []
    rng = np.random.RandomState(0)
    for _ in range(200):
        base = animal if rng.rand() < 0.5 else nums
        sents.append(" ".join(rng.permutation(base)))
    return sents


def test_word2vec_learns_cooccurrence():
    w2v = (Word2Vec.builder()
           .min_word_frequency(2).layer_size(16).window_size(3)
           .negative_sample(4).epochs(3).learning_rate(0.01)
           .batch_size(256).seed(1).build())
    w2v.fit(_corpus())
    assert w2v.has_word("cat") and w2v.has_word("one")
    assert w2v.get_word_vector("cat").shape == (16,)
    # words from the same cluster are closer than across clusters
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "two")
    near = w2v.words_nearest("one", 2)
    assert set(near) <= {"two", "three"}


def test_word2vec_save_load(tmp_path):
    w2v = (Word2Vec.builder().min_word_frequency(1).layer_size(8)
           .epochs(1).seed(0).build())
    w2v.fit(["a b c a b c", "c b a c b a"])
    p = str(tmp_path / "w2v.npz")
    w2v.save(p)
    w2 = Word2Vec.load(p)
    np.testing.assert_array_equal(w2.get_word_vector("a"),
                                  w2v.get_word_vector("a"))


def test_bert_iterator_masked_lm():
    tok = BertWordPieceTokenizer(VOCAB)
    sents = ["the quick brown fox jumped over the lazy dog"] * 8
    it = BertIterator(tok, sents, batch_size=4, max_length=12,
                      task=BertIterator.TASK_UNSUPERVISED, seed=3)
    batches = list(it)
    assert len(batches) == 2
    mds = batches[0]
    ids, mask = mds.features
    assert ids.shape == (4, 12) and mask.shape == (4, 12)
    (labels,) = mds.labels
    assert labels.shape == (4, 12, len(VOCAB))
    (lmask,) = mds.labels_masks
    # masked positions carry one-hot original tokens
    b, t = np.nonzero(lmask)
    assert len(b) > 0
    orig = tok.encode(sents[0])
    for bi, ti in zip(b, t):
        assert labels[bi, ti].sum() == 1.0
        assert labels[bi, ti].argmax() == orig[ti]
    # at least some selected positions replaced with [MASK]
    assert (ids[b, t] == tok.vocab["[MASK]"]).any()


def test_bert_iterator_classification():
    tok = BertWordPieceTokenizer(VOCAB)
    sents = ["the quick fox", "lazy dog", "the dog", "quick brown fox"]
    it = BertIterator(tok, sents, batch_size=2, max_length=6,
                      task=BertIterator.TASK_SEQ_CLASSIFICATION,
                      labels=[0, 1, 1, 0], n_classes=2)
    batches = list(it)
    assert len(batches) == 2
    (y,) = batches[0].labels
    np.testing.assert_array_equal(y, [[1, 0], [0, 1]])


def test_bert_iterator_requires_mask_token():
    with pytest.raises(ValueError, match="MASK"):
        BertIterator(BertWordPieceTokenizer(["[UNK]", "a", "b"]),
                     ["a"], 1, 4)


def test_tokenizer_requires_unk_token():
    with pytest.raises(ValueError, match="unknown-token"):
        BertWordPieceTokenizer(["a", "b"])


def test_word2vec_cbow_learns():
    w2v = (Word2Vec.builder()
           .min_word_frequency(2).layer_size(16).window_size(3)
           .negative_sample(4).epochs(3).learning_rate(0.01)
           .batch_size(256).seed(1)
           .elements_learning_algorithm("CBOW").build())
    assert w2v.elements_algo == "cbow"
    w2v.fit(_corpus())
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "two")
