"""Mixed-precision (bf16 compute / f32 master params) tests."""
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.train.updaters import Adam
from deeplearning4j_tpu.zoo import LeNet, ResNet50
from deeplearning4j_tpu.data import SyntheticMnist


def test_mln_bf16_trains_with_f32_master_params():
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .compute_dtype("bfloat16")
            .list([DenseLayer(n_out=32, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[(x[:, 0] > 0).astype(int)
                                    + (x[:, 1] > 0).astype(int)]
    s0 = net.score_for(x, y)
    for _ in range(40):
        net.fit(x, y)
    assert net.score_for(x, y) < s0 * 0.5
    # master params remain f32
    assert net.params_["layer_0"]["W"].dtype == jnp.float32
    # json round-trip keeps the setting
    assert '"compute_dtype": "bfloat16"' in conf.to_json()


def test_lenet_bf16_convergence_close_to_f32():
    f32 = LeNet(seed=1).init_model()
    bf16 = LeNet(seed=1, compute_dtype="bfloat16").init_model()
    it = SyntheticMnist(batch_size=64, n_batches=4)
    for _ in range(3):
        f32.fit(it)
        bf16.fit(it)
    val = SyntheticMnist(batch_size=64, n_batches=2, seed=5)
    a32 = f32.evaluate(val).accuracy()
    a16 = bf16.evaluate(val).accuracy()
    assert a16 > 0.8
    assert abs(a32 - a16) < 0.1


def test_resnet_bf16_graph_trains():
    net = ResNet50(n_classes=3, input_shape=(32, 32, 3),
                   compute_dtype="bfloat16").init_model()
    rng = np.random.RandomState(0)
    x = rng.rand(8, 32, 32, 3).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]
    s0 = net.score_for(x, y)
    for _ in range(8):
        net.fit(x, y)
    s1 = net.score_for(x, y)
    assert np.isfinite(s1) and s1 < s0
    # BN running stats stayed f32 (step-stable state dtypes)
    assert net.state_["stem_bn"]["mean"].dtype == jnp.float32