"""Serving fault tolerance contract (ISSUE 12 acceptance): error
classification (client errors never trip the breaker), the per-replica
circuit breaker incl. the probe-readmission race, concurrent replica
drain under a shared deadline, failover + hedged dispatch with duplicate
suppression, controller self-healing (kill -> poison -> respawn on the
same slice with zero fresh compiles; hang -> detect -> respawn), the
degraded-mode ladder (hedges off -> quantized routing -> shed floor,
hysteresis recovery), and the crc-guarded fleet topology
snapshot/restore.  The full chaos-flood gate lives in
`bench.py --fleetchaos` (slow-marked subprocess test at the bottom)."""
import json
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import (CircuitBreaker, DeadlineExceededError,
                                        DegradedLadder, FailoverRequest,
                                        FatalReplicaError, FleetPolicy,
                                        LatencySLO, ModelFleet,
                                        RejectedError, ReplicaKilledError,
                                        SnapshotCorruptError, classify_error,
                                        drain_replicas, load_snapshot)
from deeplearning4j_tpu.serving.resilience import LADDER_LEVELS
from deeplearning4j_tpu.train.updaters import Sgd
from deeplearning4j_tpu.utils.chaos import ChaosError, ReplicaChaos


def _net(seed=0, n_in=8, n_out=3, hidden=16):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=hidden, activation="relu"),
                   OutputLayer(n_out=n_out, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _x(n=2, n_in=8, seed=0):
    return np.random.RandomState(seed).randn(n, n_in).astype(np.float32)


def _fleet(tmp_path, **kw):
    kw.setdefault("max_resident", 2)
    kw.setdefault("max_batch", 4)
    kw.setdefault("batch_timeout_ms", 1.0)
    kw.setdefault("cache_dir", str(tmp_path / "exec-cache"))
    return ModelFleet(**kw)


# ---------------------------------------------------------------------------
# Error classification
# ---------------------------------------------------------------------------

def test_classify_error_matrix():
    assert classify_error(FatalReplicaError("dead")) == "fatal"
    assert classify_error(ReplicaKilledError("chaos")) == "fatal"
    assert classify_error(DeadlineExceededError("late")) == "deadline"
    assert classify_error(RejectedError("full")) == "overload"
    # malformed input is the CLIENT's fault — never a replica fault
    assert classify_error(ValueError("bad shape")) == "client"
    assert classify_error(TypeError("bad dtype")) == "client"
    assert classify_error(KeyError("model")) == "client"
    # everything else is a genuine dispatch/runtime fault
    assert classify_error(RuntimeError("xla")) == "dispatch"
    assert classify_error(ChaosError("injected")) == "dispatch"


def test_client_errors_never_count_toward_replica_health(tmp_path):
    with _fleet(tmp_path) as fleet:
        m = fleet.deploy("m", _net(), replicas=1, warm=True)
        replica = m.group.replicas[0]
        req = FailoverRequest(fleet, m, _x(), 0, None, time.monotonic())
        for _ in range(10):
            req._account(replica, ValueError("bad input"))
        assert replica.healthy
        assert replica.breaker.consecutive_failures == 0
        assert m.client_errors == 10
        # deadline/overload outcomes are pressure, not replica faults
        req._account(replica, DeadlineExceededError("late"))
        req._account(replica, RejectedError("full"))
        assert replica.healthy and replica.breaker.failures == 0
        # a genuine dispatch fault DOES count
        req._account(replica, RuntimeError("xla fault"))
        assert replica.breaker.consecutive_failures == 1


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------

def test_circuit_breaker_state_machine():
    b = CircuitBreaker(threshold=3)
    assert b.state == CircuitBreaker.CLOSED and b.level() == 0
    assert not b.record_failure() and not b.record_failure()
    assert b.record_failure()               # third consecutive: opens
    assert b.state == CircuitBreaker.OPEN and b.level() == 2
    assert b.opens_total == 1
    first_open = b.opened_at
    assert first_open is not None
    # a probe pick moves it to half-open; a failed probe re-opens it
    # WITHOUT resetting opened_at — the respawn deadline measures from
    # the FIRST failure, not the latest failed probe
    assert b.try_probe() and b.state == CircuitBreaker.HALF_OPEN
    assert b.level() == 1
    assert not b.record_failure()           # probe failed -> open again
    assert b.state == CircuitBreaker.OPEN
    assert b.opened_at == first_open
    # a passed probe closes it and clears the open timestamp
    assert b.try_probe()
    assert b.record_success()
    assert b.state == CircuitBreaker.CLOSED and b.opened_at is None
    assert b.consecutive_failures == 0
    # force_open (poison) trips immediately from closed
    assert b.force_open() and b.state == CircuitBreaker.OPEN
    assert b.opens_total == 2
    assert not b.force_open()               # already open: no event


def test_breaker_probe_race_pins_closed_winner():
    """A probe success racing a fresh failure must neither oscillate nor
    deadlock: the pinned winner is CLOSED — a failure that lands after
    the closing success counts 1 toward a FRESH threshold instead of
    instantly re-opening the breaker."""
    for trial in range(200):
        b = CircuitBreaker(threshold=3)
        b.force_open()
        b.try_probe()                        # probe in flight
        barrier = threading.Barrier(2)

        def probe_success():
            barrier.wait()
            b.record_success()

        def fresh_failure():
            barrier.wait()
            b.record_failure()

        # alternate start order so both interleavings get exercised
        fns = [probe_success, fresh_failure]
        if trial % 2:
            fns.reverse()
        threads = [threading.Thread(target=f) for f in fns]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5)
        assert not any(t.is_alive() for t in threads), "deadlocked"
        # failure-then-success -> success closes; success-then-failure
        # -> failure counts 1 fresh.  Either way: CLOSED, cf <= 1.
        assert b.state == CircuitBreaker.CLOSED
        assert b.consecutive_failures <= 1


# ---------------------------------------------------------------------------
# Concurrent drain
# ---------------------------------------------------------------------------

class _Ctr:
    def __init__(self):
        self.n = 0

    def inc(self):
        self.n += 1


class _FakeServer:
    def __init__(self, delay):
        self.delay = delay

    def shutdown(self, drain=True, timeout=10.0):
        time.sleep(self.delay)


class _FakeReplica:
    def __init__(self, name, delay):
        self.name = name
        self.server = _FakeServer(delay)


def test_concurrent_drain_shared_deadline_reports_expiries():
    """Two drains run CONCURRENTLY: a hung replica must not burn the
    whole budget before the fast one is even tried, and the expiry is
    named + counted."""
    fast = _FakeReplica("fast", 0.2)
    hung = _FakeReplica("hung", 5.0)
    ctr = _Ctr()
    t0 = time.monotonic()
    expired = drain_replicas([fast, hung], timeout=0.6, counter=ctr)
    wall = time.monotonic() - t0
    assert expired == ["hung"]
    assert ctr.n == 1
    # serial would be 0.2 + 0.6; concurrent is bounded by ONE deadline
    assert wall < 2.0
    assert drain_replicas([], timeout=0.1) == []


# ---------------------------------------------------------------------------
# Failover + hedged dispatch
# ---------------------------------------------------------------------------

def test_killed_replica_fails_over_and_respawns_compile_free(tmp_path):
    with _fleet(tmp_path, n_slices=2,
                policy=FleetPolicy(drain_timeout_s=1.0)) as fleet:
        m = fleet.deploy("m", _net(seed=1), replicas=2, warm=True)
        fleet.output("m", _x(), timeout=30)          # buckets warm
        victim = m.group.replicas[0]
        victim_slice = victim.slice.index
        failovers_before = fleet.instruments.failovers.value
        respawns_before = fleet.instruments.respawns("poisoned").value
        chaos = ReplicaChaos(mode="kill", at_dispatch=0)
        chaos.arm(victim)
        # every accepted request resolves: a kill on its replica fails
        # over to the healthy one, never surfaces to the client
        futs = [fleet.submit("m", _x(seed=i), deadline_ms=4000.0)
                for i in range(16)]
        assert all(f.exception(timeout=30) is None for f in futs)
        assert victim.poisoned
        assert victim.breaker.state == CircuitBreaker.OPEN
        assert fleet.instruments.failovers.value > failovers_before
        # the controller tears it down and respawns ON THE SAME SLICE
        # through the persistent AOT cache: deserialize, not recompile
        rec = fleet.controller.reconcile()
        respawns = [a for a in rec["actions"] if a["action"] == "respawn"]
        assert len(respawns) == 1
        assert respawns[0]["cause"] == "poisoned"
        assert respawns[0]["slice"] == victim_slice
        assert respawns[0]["fresh_compiles"] == 0
        assert m.respawns == 1
        assert m.last_respawn["fresh_compiles"] == 0
        assert fleet.instruments.respawns("poisoned").value \
            == respawns_before + 1
        assert victim not in m.group.replicas
        assert all(r.healthy for r in m.group.snapshot())
        # the healed member serves on both replicas again
        fleet.output("m", _x(), timeout=30)


def test_hung_replica_detected_drained_and_respawned(tmp_path):
    policy = FleetPolicy(hang_after_s=0.3, drain_timeout_s=0.3,
                         respawn_after_s=60.0)      # isolate the hang path
    with _fleet(tmp_path, n_slices=2, policy=policy) as fleet:
        m = fleet.deploy("m", _net(seed=2), replicas=2, warm=True)
        fleet.output("m", _x(), timeout=30)
        victim = m.group.replicas[0]
        chaos = ReplicaChaos(mode="hang", at_dispatch=0, duration_s=1.5)
        chaos.arm(victim)
        futs = [fleet.submit("m", _x(seed=i), deadline_ms=8000.0)
                for i in range(8)]
        # wait until the stuck dispatch is visible on the batcher
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            age = victim.server.batcher.inflight_age_s
            if age is not None and age >= policy.hang_after_s:
                break
            time.sleep(0.02)
        drains_before = fleet.instruments.drain_timeouts.value
        hung_before = fleet.instruments.respawns("hung").value
        rec = fleet.controller.reconcile()
        respawns = [a for a in rec["actions"] if a["action"] == "respawn"]
        assert len(respawns) == 1 and respawns[0]["cause"] == "hung"
        assert respawns[0]["fresh_compiles"] == 0
        # the hung server blew the bounded drain deadline — counted
        assert fleet.instruments.drain_timeouts.value > drains_before
        assert fleet.instruments.respawns("hung").value == hung_before + 1
        # NO accepted request is lost: stuck ones resolve when the hang
        # ends; drained leftovers fail over to the healthy replica
        assert all(f.exception(timeout=30) is None for f in futs)
        fleet.output("m", _x(), timeout=30)


def test_hedged_dispatch_first_wins_late_duplicate_suppressed(tmp_path):
    policy = FleetPolicy(hedge_fraction=0.5, max_hedges=1)
    with _fleet(tmp_path, n_slices=2, policy=policy) as fleet:
        m = fleet.deploy("m", _net(seed=3), replicas=2, warm=True)
        fleet.output("m", _x(), timeout=30)
        slow, fast = m.group.replicas
        chaos = ReplicaChaos(mode="slow", at_dispatch=0, delay_s=0.6)
        chaos.arm(slow)
        lat_before = m.latency.count
        hedges_before = fleet.instruments.hedges.value
        wasted_before = fleet.instruments.hedge_wasted.value
        req = FailoverRequest(fleet, m, _x(), 0, 1000.0, time.monotonic())
        fut = req.start(slow)               # primary lands on the slow one
        # the hedge fires at 50% of the budget and wins on the fast
        # replica; the late original completes too but is SUPPRESSED —
        # one answer, one latency sample, one wasted-duplicate count
        assert fut.exception(timeout=30) is None
        assert fleet.instruments.hedges.value == hedges_before + 1
        deadline = time.monotonic() + 5.0
        while fleet.instruments.hedge_wasted.value == wasted_before \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert fleet.instruments.hedge_wasted.value == wasted_before + 1
        assert m.latency.count == lat_before + 1
        chaos.restore()


def test_ladder_hedges_off_disarms_the_hedge_timer(tmp_path):
    with _fleet(tmp_path, n_slices=2) as fleet:
        m = fleet.deploy("m", _net(seed=4), replicas=2, warm=True)
        fleet.output("m", _x(), timeout=30)
        fleet.ladder.restore_state({"level": 1})     # hedges_off
        hedges_before = fleet.instruments.hedges.value
        req = FailoverRequest(fleet, m, _x(), 0, 1000.0, time.monotonic())
        fut = req.start(m.group.replicas[0])
        assert req._hedge_handle is None             # never armed
        assert fut.exception(timeout=30) is None
        assert fleet.instruments.hedges.value == hedges_before


# ---------------------------------------------------------------------------
# Degraded-mode ladder
# ---------------------------------------------------------------------------

def test_degraded_ladder_hysteresis_and_predicates():
    lad = DegradedLadder(down_after=2, up_after=3)
    assert lad.name == "full" and lad.hedges_enabled()
    assert not lad.quantized_routing() and not lad.shed_floor()
    assert lad.observe(True) == 0           # one pressured tick: holds
    assert lad.observe(True) == 1           # second: steps down ONE level
    assert lad.name == "hedges_off" and not lad.hedges_enabled()
    # pressure keeps walking it down one level at a time
    lad.observe(True), lad.observe(True)
    assert lad.name == "quantized" and lad.quantized_routing()
    lad.observe(True), lad.observe(True)
    assert lad.name == "shed_floor" and lad.shed_floor()
    lad.observe(True), lad.observe(True)    # already at the floor: holds
    assert lad.level == len(LADDER_LEVELS) - 1
    # recovery needs up_after consecutive healthy ticks, one level each
    lad.observe(False), lad.observe(False)
    assert lad.level == 3                   # not yet
    lad.observe(False)
    assert lad.name == "quantized"
    # a pressured tick resets the recovery streak (hysteresis)
    lad.observe(False), lad.observe(False), lad.observe(True)
    lad.observe(False), lad.observe(False)
    assert lad.name == "quantized"
    for _ in range(6):
        lad.observe(False)
    assert lad.name == "full"
    assert len(lad.transitions) >= 6
    # snapshot state restores clamped
    lad.restore_state({"level": 99})
    assert lad.level == len(LADDER_LEVELS) - 1
    lad.restore_state(lad.to_state())
    assert lad.level == len(LADDER_LEVELS) - 1


def test_ladder_quantized_routing_and_shed_floor(tmp_path):
    with _fleet(tmp_path, n_slices=4) as fleet:
        hi = fleet.deploy("hi", _net(seed=5),
                          slo=LatencySLO(target_p99_ms=500.0, priority=10),
                          warm=True)
        lo = fleet.deploy("lo", _net(seed=6),
                          slo=LatencySLO(target_p99_ms=500.0, priority=0),
                          warm=True)
        entry = fleet.prepare_quantized("lo")
        # the standby changes NOTHING at full level: f32 stays pinned
        assert lo.quantized_version == entry.version
        assert fleet._route_version(lo) == lo.serving_version
        fleet.output("lo", _x(), timeout=30)
        # at the quantized level, routing flips to the int8 standby —
        # zero compiles, the buckets were warmed at prepare time; a
        # member with no standby keeps its f32 version
        fleet.ladder.restore_state({"level": 2})
        assert fleet._route_version(lo) == entry.version
        assert fleet._route_version(hi) == hi.serving_version
        compiles = fleet.cache.stats["compiles"]
        fleet.output("lo", _x(), timeout=30)
        fleet.output("hi", _x(), timeout=30)
        assert fleet.cache.stats["compiles"] == compiles
        # at the shed floor only the top priority class is admitted
        fleet.ladder.restore_state({"level": 3})
        sheds = lo.sheds
        with pytest.raises(RejectedError, match="shed"):
            fleet.submit("lo", _x())
        assert lo.sheds == sheds + 1
        fleet.output("hi", _x(), timeout=30)
        # recovery restores normal routing
        fleet.ladder.restore_state({"level": 0})
        fleet.output("lo", _x(), timeout=30)
        assert fleet._route_version(lo) == lo.serving_version


def test_ladder_level_exported_via_healthz_and_fleet_stats(tmp_path):
    with _fleet(tmp_path, n_slices=2) as fleet:
        fleet.deploy("m", _net(seed=7), warm=True)
        fleet.ladder.observe(True)
        fleet.ladder.observe(True)              # down_after=2 default
        assert fleet.ladder.level == 1
        assert fleet.healthz()["degraded_mode"] == "hedges_off"
        assert fleet.healthz()["degraded_level"] == 1
        assert fleet.fleet_stats()["degraded"]["level"] == 1
        assert fleet.fleet_stats()["degraded"]["name"] == "hedges_off"


# ---------------------------------------------------------------------------
# Snapshot / restore
# ---------------------------------------------------------------------------

def test_snapshot_roundtrip_restore_zero_compiles(tmp_path):
    snap = str(tmp_path / "fleet-snapshot.json")
    cache = str(tmp_path / "exec-cache")
    fleet = _fleet(tmp_path, n_slices=4, cache_dir=cache,
                   snapshot_path=snap)
    a = fleet.deploy("a", _net(seed=8),
                     slo=LatencySLO(target_p99_ms=250.0, priority=7),
                     replicas=2, warm=True)
    fleet.deploy("b", _net(seed=9))                  # cold member
    fleet.output("a", _x(), timeout=30)
    a.tracker.restore_state({"breached": True, "breaches_total": 2,
                             "over": 1, "under": 0})
    a_slices = sorted(r.slice.index for r in a.group.snapshot())
    assert fleet.save_snapshot() == snap
    assert fleet.instruments.snapshot_age.value == 0.0
    body = load_snapshot(snap)
    assert body["resident"] == ["a"]
    assert body["members"]["a"]["replicas_target"] == 2
    assert sorted(body["members"]["a"]["slices"]) == a_slices
    assert body["members"]["a"]["slo"]["priority"] == 7
    fleet.shutdown()

    # a NEW fleet process: same cache dir, rebuilt to pre-crash shape
    fleet2 = ModelFleet(max_resident=2, max_batch=4, batch_timeout_ms=1.0,
                        n_slices=4, cache_dir=cache, snapshot_path=snap)
    fleet2.deploy("a", _net(seed=8),
                  slo=LatencySLO(target_p99_ms=250.0, priority=7))
    fleet2.deploy("b", _net(seed=9))
    report = fleet2.restore_snapshot()
    assert sorted(report["restored"]) == ["a", "b"]
    assert report["missing"] == []
    assert report["fresh_compiles"] == 0             # warm AOT path
    a2 = fleet2.member("a")
    assert a2.replicas_target == 2
    assert sorted(r.slice.index
                  for r in a2.group.snapshot()) == a_slices
    assert a2.tracker.breached and a2.tracker.breaches_total == 2
    assert fleet2.pool.resident_names() == ["a"]
    # breached members shed all but probes — retry until one admits
    for _ in range(64):
        try:
            fleet2.output("a", _x(), timeout=30)
            break
        except RejectedError:
            continue
    else:
        pytest.fail("restored member never admitted a probe")
    fleet2.shutdown()


def test_snapshot_detects_corruption_and_missing_members(tmp_path):
    snap = str(tmp_path / "snap.json")
    with _fleet(tmp_path, snapshot_path=snap) as fleet:
        fleet.deploy("m", _net(seed=10), warm=True)
        fleet.save_snapshot()
        # crc catches a flipped byte in the body
        with open(snap) as f:
            payload = json.load(f)
        payload["fleet"]["max_resident"] = 99
        with open(snap, "w") as f:
            json.dump(payload, f)
        with pytest.raises(SnapshotCorruptError, match="crc"):
            load_snapshot(snap)
        # torn/truncated writes and wrong formats are refused too
        with open(snap, "w") as f:
            f.write("{\"fleet\": {")
        with pytest.raises(SnapshotCorruptError):
            load_snapshot(snap)
        with open(snap, "w") as f:
            json.dump({"format": 999, "fleet": {}, "crc32": 0}, f)
        with pytest.raises(SnapshotCorruptError, match="format"):
            load_snapshot(snap)
    # a member in the snapshot but not deployed is reported, not fatal
    snap2 = str(tmp_path / "snap2.json")
    with _fleet(tmp_path, snapshot_path=snap2) as fleet:
        fleet.deploy("m", _net(seed=10), warm=True)
        fleet.deploy("gone", _net(seed=11))
        fleet.save_snapshot()
    with _fleet(tmp_path, snapshot_path=snap2) as fleet2:
        fleet2.deploy("m", _net(seed=10))
        report = fleet2.restore_snapshot()
        assert report["missing"] == ["gone"]
        assert "m" in report["restored"]


def test_periodic_snapshot_from_reconcile_tick(tmp_path):
    snap = str(tmp_path / "snap.json")
    with _fleet(tmp_path, snapshot_path=snap,
                snapshot_interval_s=0.0) as fleet:
        fleet.deploy("m", _net(seed=12), warm=True)
        assert fleet.snapshotter.saves == 0
        fleet.controller.reconcile()
        assert fleet.snapshotter.saves == 1          # tick committed one
        assert load_snapshot(snap)["resident"] == ["m"]
        assert fleet.healthz()["snapshot_age_s"] >= 0.0


# ---------------------------------------------------------------------------
# The tier-1 chaos gate: bench.py --fleetchaos --quick (slow lane)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bench_fleetchaos_quick_gate():
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"),
         "--fleetchaos", "--quick"],
        capture_output=True, text=True, timeout=600, cwd=root, env=env)
    assert p.returncode == 0, p.stderr[-2000:]
    line = json.loads(p.stdout.strip().splitlines()[-1])
    assert line["pass"] is True
    assert line["value"] == 0                        # lost accepted
    assert set(line["respawn_causes"]) == {"hung", "poisoned"}
    assert all(c == 0 for c in line["respawn_fresh_compiles"])
    assert line["restore_fresh_compiles"] == 0
