"""Quantized inference subsystem (ISSUE 10 acceptance): int8 kernel
conformance, calibration observers, bf16 fallback for range-hostile
tensors, f32-vs-quantized parity over the import-corpus model shapes,
dtype plumbing under `compute_dtype` mixed precision and TP sharding
rules (lowered-program dtype checks — no silent f32 upcast), serving
integration (compile cache, registry quantized-version roll), distinct
f32/int8 executable fingerprints, and the cross-process warm-restart
round trip through the persistent AOT cache."""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.compile import model_fingerprint
from deeplearning4j_tpu.nn import (ComputationGraph, DenseLayer,
                                   GraphBuilder, InputType,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.ops.attention_kernels import (mha_reference,
                                                      quantized_mha,
                                                      quantized_projection)
from deeplearning4j_tpu.ops.conv_kernels import quantized_conv2d
from deeplearning4j_tpu.ops.quant_kernels import (QTensor, dequantize,
                                                  quantization_error,
                                                  quantize_tensor,
                                                  quantized_matmul,
                                                  quantized_matmul_static,
                                                  range_hostility)
from deeplearning4j_tpu.quant import (CalibrationStats, MinMaxObserver,
                                      PercentileObserver, QuantConfig,
                                      QuantizedModel, calibrate,
                                      parity_check, quantize_model)
from deeplearning4j_tpu.train.updaters import Sgd

rs = np.random.RandomState(7)


def _mlp(seed=0, n_in=32, hidden=64, n_out=10, compute_dtype=None):
    b = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(1e-1)))
    if compute_dtype:
        b = b.compute_dtype(compute_dtype)
    conf = (b.list([DenseLayer(n_out=hidden, activation="relu"),
                    DenseLayer(n_out=hidden, activation="relu"),
                    OutputLayer(n_out=n_out, loss="mcxent",
                                activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _fit(net, n=64, steps=3, n_in=32, n_out=10, seed=0):
    r = np.random.RandomState(seed)
    x = r.randn(n, n_in).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[r.randint(0, n_out, n)]
    for _ in range(steps):
        net.fit(x, y)
    return x


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def test_quantize_dequantize_roundtrip():
    w = rs.randn(64, 48).astype(np.float32)
    qt = quantize_tensor(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 48)
    deq = np.asarray(dequantize(qt))
    # symmetric per-channel int8: worst-case error is half a step
    step = np.asarray(qt.scale)
    assert np.all(np.abs(deq - w) <= 0.5 * step + 1e-7)
    assert quantization_error(w) < 0.01


def test_qtensor_is_a_pytree():
    qt = quantize_tensor(rs.randn(8, 16).astype(np.float32))
    leaves = jax.tree_util.tree_leaves(qt)
    assert len(leaves) == 2                      # q + scale travel as leaves
    doubled = jax.tree_util.tree_map(lambda a: a, qt)
    assert isinstance(doubled, QTensor) and doubled.axis == qt.axis
    assert qt.nbytes == qt.q.nbytes + qt.scale.nbytes


def test_quantized_matmul_matches_dequantized():
    x = rs.randn(16, 64).astype(np.float32)
    w = rs.randn(64, 32).astype(np.float32)
    qt = quantize_tensor(w)
    want = x @ np.asarray(dequantize(qt))
    got = np.asarray(quantized_matmul(jnp.asarray(x), qt))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # 3-D (time-distributed / attention projection shape)
    x3 = rs.randn(4, 7, 64).astype(np.float32)
    got3 = np.asarray(quantized_projection(jnp.asarray(x3), qt))
    np.testing.assert_allclose(got3, x3 @ np.asarray(dequantize(qt)),
                               rtol=1e-5, atol=1e-5)


def test_quantized_matmul_static_int8_activations():
    x = rs.uniform(-3, 3, (16, 64)).astype(np.float32)
    w = rs.randn(64, 32).astype(np.float32)
    qt = quantize_tensor(w)
    got = np.asarray(quantized_matmul_static(jnp.asarray(x), qt,
                                             x_scale=3.0 / 127.0))
    rel = np.linalg.norm(got - x @ w) / np.linalg.norm(x @ w)
    assert rel < 0.02, rel


def test_quantized_conv2d_matches_dequantized():
    x = rs.randn(2, 8, 8, 3).astype(np.float32)
    w = rs.randn(3, 3, 3, 8).astype(np.float32)
    qt = quantize_tensor(w)           # HWIO, per-output-channel
    want = jax.lax.conv_general_dilated(
        jnp.asarray(x), dequantize(qt), (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    got = quantized_conv2d(jnp.asarray(x), qt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_quantized_mha_close_to_f32():
    B, T, F, H = 2, 6, 32, 4
    x = rs.randn(B, T, F).astype(np.float32)
    w_qkv = rs.randn(F, 3 * F).astype(np.float32) * 0.2
    w_out = rs.randn(F, F).astype(np.float32) * 0.2
    got = np.asarray(quantized_mha(jnp.asarray(x), quantize_tensor(w_qkv),
                                   quantize_tensor(w_out), n_heads=H))
    qkv = x @ w_qkv
    q, k, v = np.split(qkv, 3, axis=-1)
    heads = lambda a: a.reshape(B, T, H, F // H).transpose(0, 2, 1, 3)
    o = np.asarray(mha_reference(*(jnp.asarray(heads(a))
                                   for a in (q, k, v))))
    want = o.transpose(0, 2, 1, 3).reshape(B, T, F) @ w_out
    rel = np.linalg.norm(got - want) / np.linalg.norm(want)
    assert rel < 0.03, rel


def test_range_hostility_flags_sub_step_mass():
    ok = rs.randn(32, 32).astype(np.float32)
    assert range_hostility(ok) < 127.0
    hostile = np.full((512, 32), 1e-5, np.float32)
    hostile[0, 0] = 10.0                     # channel mass below one step
    assert range_hostility(hostile) > 127.0


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

def test_minmax_vs_percentile_observer():
    data = np.concatenate([rs.uniform(-1, 1, 10_000),
                           [1000.0]])         # one outlier
    mm = MinMaxObserver()
    mm.observe(data)
    assert mm.range()[1] == 1000.0
    po = PercentileObserver(percentile=99.9)
    po.observe(data, phase=0)
    po.observe(data, phase=1)
    lo, hi = po.range()
    assert hi < 5.0 and lo < -0.9            # outlier tail clipped


def test_calibration_stats_crc_is_stable_and_sensitive():
    a = CalibrationStats({"l0:in": (-1.0, 1.0), "l1:in": (0.0, 2.0)})
    b = CalibrationStats({"l1:in": (0.0, 2.0), "l0:in": (-1.0, 1.0)})
    assert a.crc32() == b.crc32()            # order-insensitive
    c = CalibrationStats({"l0:in": (-1.0, 1.0), "l1:in": (0.0, 2.5)})
    assert a.crc32() != c.crc32()
    rt = CalibrationStats.from_dict(a.to_dict())
    assert rt.crc32() == a.crc32()


def test_calibrate_mln_collects_per_layer_ranges_and_metric():
    from deeplearning4j_tpu.monitor.instrument import quant_instruments
    net = _mlp()
    x = _fit(net)
    before = quant_instruments().calibration_batches.value
    stats = calibrate(net, [x[:16], x[16:32]], observer="percentile")
    assert {"layer_0:in", "layer_1:in", "layer_2:in",
            "__output__"} <= set(stats.ranges)
    assert stats.batches == 2
    # percentile observers replay the iterator: both passes count
    assert quant_instruments().calibration_batches.value - before == 4
    lo, hi = stats.range("layer_0:in")
    assert lo < 0 < hi


# ---------------------------------------------------------------------------
# parity over the import-corpus model shapes
# ---------------------------------------------------------------------------

def test_mln_parity_within_one_percent():
    net = _mlp()
    x = _fit(net)
    stats = calibrate(net, x)
    qm = quantize_model(net, calibration=stats)
    assert qm.dominant_dtype() == "int8"
    r = parity_check(net, qm, x)
    assert r["task"] == "classification" and r["delta"] <= 0.01, r
    # static int8 activations stay within the same gate
    q2 = quantize_model(net, calibration=stats,
                        config=QuantConfig(quantize_activations=True))
    assert parity_check(net, q2, x)["delta"] <= 0.01


def test_graph_parity_within_one_percent():
    conf = (GraphBuilder().seed(0).updater(Sgd(1e-1))
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_out=48, activation="relu"), "in")
            .add_layer("d2", DenseLayer(n_out=48, activation="relu"), "d1")
            .add_layer("out", OutputLayer(n_out=5, loss="mcxent",
                                          activation="softmax"), "d2")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(16)).build())
    cg = ComputationGraph(conf).init()
    x = rs.randn(32, 16).astype(np.float32)
    qm = quantize_model(cg)
    assert qm.kind == "graph"
    assert parity_check(cg, qm, x)["delta"] <= 0.01


def test_onnx_import_parity_within_one_percent():
    """ONNX corpus shape: Gemm -> Relu -> Gemm authored with the in-repo
    onnx_proto codec, imported to SameDiff, quantized, parity-checked."""
    from deeplearning4j_tpu.modelimport.onnx_import import import_onnx_model
    from tests.test_onnx_import import _N, _model, _vi

    r = np.random.RandomState(3)
    w1 = r.randn(16, 32).astype(np.float32) * 0.3
    b1 = np.zeros(32, np.float32)
    w2 = r.randn(32, 8).astype(np.float32) * 0.3
    b2 = np.zeros(8, np.float32)
    x = r.randn(12, 16).astype(np.float32)
    nodes = [_N("Gemm", ["x", "w1", "b1"], ["h"]),
             _N("Relu", ["h"], ["a"]),
             _N("Gemm", ["a", "w2", "b2"], ["y"])]
    model = _model(nodes, [_vi("x", x.shape)], [_vi("y", ())],
                   {"w1": w1, "b1": b1, "w2": w2, "b2": b2})
    sd = import_onnx_model(model)
    qm = quantize_model(sd)
    assert qm.kind == "samediff"
    ref = np.asarray(sd.output({"x": x}, "y")["y"])
    got = np.asarray(qm.output(x))
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel <= 0.01, rel
    # regression-style parity through the shared harness
    assert parity_check(sd, qm, x)["delta"] <= 0.01


@pytest.mark.slow
def test_keras_import_parity_within_one_percent(tmp_path):
    """Keras corpus shape: sequential dense import -> quantize -> parity."""
    tf = pytest.importorskip("tensorflow")
    from deeplearning4j_tpu.modelimport import KerasModelImport
    km = tf.keras.Sequential([
        tf.keras.layers.Input((12,)),
        tf.keras.layers.Dense(32, activation="relu"),
        tf.keras.layers.Dense(6, activation="softmax")])
    r = np.random.RandomState(5)   # keras global-seed init is not stable
    km.set_weights([r.randn(*w.shape).astype(np.float32) * 0.3
                    for w in km.get_weights()])
    p = str(tmp_path / "m.h5")
    km.save(p)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = r.randn(64, 12).astype(np.float32)
    qm = quantize_model(net, calibration=calibrate(net, x))
    # untrained import: logits are near-tied, so gate on relative L2
    # (the trained-model top-1 gate is test_mln_parity_within_one_percent)
    assert parity_check(net, qm, x, task="regression")["delta"] <= 0.01


def test_bf16_fallback_for_range_hostile_layer():
    net = _mlp(hidden=256)
    _fit(net)
    w = np.asarray(net.params_["layer_1"]["W"]).copy()
    w[:] = 1e-5
    w[0, 0] = 50.0                          # hostile: mass below one step
    net.params_["layer_1"]["W"] = jnp.asarray(w)
    qm = quantize_model(net)
    rep = {k: v for k, v in qm.report.items()}
    assert rep["['layer_1']['W']"] == "bfloat16"
    assert rep["['layer_0']['W']"] == "int8"
    # forward still runs through the fallback leaf
    assert np.asarray(qm.output(np.zeros((2, 32), np.float32))).shape == (2, 10)


def test_quantize_model_shrinks_resident_bytes():
    net = _mlp(hidden=128)
    qm = quantize_model(net)
    f32 = sum(l.nbytes for l in jax.tree_util.tree_leaves(net.params_))
    assert qm.bytes_resident() < f32 / 3     # ~4x on W, biases stay f32
    with pytest.raises(ValueError, match="already quantized"):
        quantize_model(qm)


# ---------------------------------------------------------------------------
# dtype plumbing: compiled-program checks
# ---------------------------------------------------------------------------

def _lowered_text(qm, n_in, batch=8):
    def fwd(p, s, xv):
        return qm._forward(p, s, xv, train=False, rng=None)[0]
    x = jnp.zeros((batch, n_in), jnp.float32)
    return jax.jit(fwd).lower(qm.params_, qm.state_, x).as_text()


def test_compiled_program_keeps_int8_params():
    qm = quantize_model(_mlp())
    txt = _lowered_text(qm, 32)
    assert "xi8>" in txt                     # int8 weights enter the program


def test_no_silent_f32_upcast_under_bf16_compute():
    """Mixed precision: with compute_dtype=bfloat16 every matmul in the
    lowered program must consume/produce bf16 — the quantized path must
    not widen back to f32."""
    net = _mlp(compute_dtype="bfloat16")
    qm = quantize_model(net)
    assert str(qm.acc_dtype()) == "bfloat16"
    txt = _lowered_text(qm, 32)
    assert "xi8>" in txt
    dots = [l for l in txt.splitlines() if "dot_general" in l]
    assert dots, "no matmuls in lowered program?"
    for l in dots:
        out_ty = l.split("->")[-1]
        assert "xf32>" not in out_ty, f"f32 matmul leaked into program: {l}"


def test_quantized_inference_under_tp_sharding_rules():
    """ParallelWrapper TP rules: the Megatron-style default splits 2-D
    kernels' output dim over the model axis — QTensor leaves (int8 q and
    its per-output-channel scale) shard the same way and the sharded
    quantized forward matches the unsharded one."""
    from deeplearning4j_tpu.parallel import (ShardingRules, make_mesh,
                                             shard_model_params)
    net = _mlp()
    x = _fit(net)
    qm = quantize_model(net)
    want = np.asarray(qm.output(x))
    mesh = make_mesh({"data": 4, "model": 2})
    sharded = shard_model_params(qm.params_, mesh, ShardingRules())
    q0 = sharded["layer_0"]["W"].q
    assert q0.dtype == jnp.int8
    assert q0.sharding.spec == P(None, "model")    # stayed int8 AND sharded
    assert sharded["layer_0"]["W"].scale.sharding.spec == P(None, "model")
    qm.params_ = sharded
    qm._output_fn = None
    with mesh:
        got = np.asarray(qm.output(x))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------

def test_quantized_model_serves_through_compile_cache():
    from deeplearning4j_tpu.serving import BucketedCompileCache
    net = _mlp()
    x = _fit(net)
    qm = quantize_model(net)
    cache = BucketedCompileCache(max_batch=16)
    out = cache.run("q:v1", qm, x[:5])
    np.testing.assert_allclose(out, np.asarray(qm.output(x[:5])),
                               rtol=1e-5, atol=1e-6)
    assert cache.counters.misses.value == 1
    cache.run("q:v1", qm, x[:5])
    assert cache.counters.hits.value == 1


def test_registry_quantized_version_roll():
    from deeplearning4j_tpu.serving import ModelRegistry
    reg = ModelRegistry()
    net = _mlp()
    _fit(net)
    reg.register("m", net)
    entry = reg.register_quantized("m")
    assert entry.version == 2 and entry.source == "quant"
    assert isinstance(entry.model, QuantizedModel)
    assert reg.get("m").version == 2               # new submits serve int8
    assert reg.get("m", 1).model is net            # f32 still resolvable
    assert entry.input_shape == (32,)


def test_f32_and_int8_fingerprints_are_distinct():
    net = _mlp()
    x = _fit(net)
    stats = calibrate(net, x)
    qm = quantize_model(net, calibration=stats)
    assert model_fingerprint(net) != model_fingerprint(qm)
    # different calibration data -> different quantized program identity
    stats2 = calibrate(net, x * 2.0)
    assert stats.crc32() != stats2.crc32()
    qm2 = quantize_model(net, calibration=stats2)
    assert model_fingerprint(qm) != model_fingerprint(qm2)
    # same inputs -> bit-stable fingerprint (the warm-restart premise)
    qm3 = quantize_model(net, calibration=stats)
    assert model_fingerprint(qm) == model_fingerprint(qm3)


@pytest.mark.slow
def test_quantized_warm_restart_subprocess(tmp_path):
    """ISSUE 10 acceptance: quantized executables round-trip the
    persistent AOT cache — a warm subprocess restart serves the quantized
    model with zero fresh compiles, under a fingerprint distinct from
    the f32 program's."""
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "quant_warm_worker.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(here),
               DL4J_TPU_TEST_CACHE=str(tmp_path))

    def run():
        p = subprocess.run([sys.executable, worker], env=env,
                           capture_output=True, text=True, timeout=600)
        assert p.returncode == 0, p.stderr[-2000:]
        return json.loads(p.stdout.strip().splitlines()[-1])

    cold = run()
    warm = run()
    assert cold["fp_quant"] != cold["fp_f32"]
    assert warm["fp_quant"] == cold["fp_quant"]
    assert warm["calibration_crc"] == cold["calibration_crc"]
    assert cold["compiles"] >= 1 and cold["stores"] >= 1
    assert warm["compiles"] == 0                   # pure deserialization
    assert warm["disk_hits"] >= cold["stores"]
    assert warm["checksum"] == cold["checksum"]
