"""Round-2 declarable-op additions (reference `libnd4j/include/ops/
declarable/generic/{random,bitwise,images,transforms,loss,nn}/**`):
forward values vs numpy/scipy oracles + grad spot-checks.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.ops import OP_TABLE

rng = np.random.default_rng(0)


def op(name):
    assert name in OP_TABLE, f"op '{name}' not registered"
    return OP_TABLE[name]


# ---- random ----

def test_random_ops_shapes_and_ranges():
    key = jax.random.PRNGKey(0)
    u = np.asarray(op("random_uniform")(key, (1000,), 2.0, 5.0))
    assert u.shape == (1000,) and (u >= 2.0).all() and (u < 5.0).all()
    n = np.asarray(op("random_normal")(key, (5000,), 1.0, 2.0))
    assert abs(n.mean() - 1.0) < 0.2 and abs(n.std() - 2.0) < 0.2
    b = np.asarray(op("random_bernoulli")(key, (1000,), 0.25))
    assert 0.15 < b.mean() < 0.35
    e = np.asarray(op("random_exponential")(key, (5000,), 2.0))
    assert (e >= 0).all() and abs(e.mean() - 0.5) < 0.1
    g = np.asarray(op("random_gamma")(key, (5000,), 3.0, 2.0))
    assert abs(g.mean() - 1.5) < 0.2
    p = np.asarray(op("random_poisson")(key, (5000,), 4.0))
    assert abs(p.mean() - 4.0) < 0.3
    a = np.arange(100)
    sh = np.asarray(op("random_shuffle")(key, jnp.asarray(a)))
    assert sorted(sh.tolist()) == a.tolist() and not (sh == a).all()
    logits = jnp.log(jnp.asarray([[0.1, 0.9], [0.5, 0.5]]))
    m = np.asarray(op("multinomial")(key, logits, 200))
    assert m.shape == (2, 200) and m[0].mean() > 0.7


# ---- bitwise ----

def test_bitwise_ops():
    a = jnp.asarray([0b1100, 0b1010], jnp.int32)
    b = jnp.asarray([0b1010, 0b0110], jnp.int32)
    np.testing.assert_array_equal(op("bitwise_and")(a, b), [0b1000, 0b0010])
    np.testing.assert_array_equal(op("bitwise_or")(a, b), [0b1110, 0b1110])
    np.testing.assert_array_equal(op("bitwise_xor")(a, b), [0b0110, 0b1100])
    np.testing.assert_array_equal(op("shift_left")(a, 2), [0b110000, 0b101000])
    np.testing.assert_array_equal(op("shift_right")(a, 2), [0b11, 0b10])
    assert int(op("bits_hamming_distance")(a, b)) == 4


# ---- segment / scatter ----

def test_unsorted_segment_family():
    data = jnp.asarray(rng.standard_normal((6, 3)).astype(np.float32))
    ids = jnp.asarray([2, 0, 1, 0, 2, 2])
    s = np.asarray(op("unsorted_segment_sum")(data, ids, 3))
    m = np.asarray(op("unsorted_segment_mean")(data, ids, 3))
    np.testing.assert_allclose(s[0], np.asarray(data)[[1, 3]].sum(0),
                               rtol=1e-5)
    np.testing.assert_allclose(m[2], np.asarray(data)[[0, 4, 5]].mean(0),
                               rtol=1e-5)
    sq = np.asarray(op("unsorted_segment_sqrt_n")(data, ids, 3))
    np.testing.assert_allclose(
        sq[2], np.asarray(data)[[0, 4, 5]].sum(0) / np.sqrt(3), rtol=1e-5)
    p = np.asarray(op("unsorted_segment_prod")(data, ids, 3))
    np.testing.assert_allclose(p[1], np.asarray(data)[2], rtol=1e-5)


def test_scatter_breadth_and_dynamic_stitch():
    base = jnp.ones((4, 2), jnp.float32)
    idx = jnp.asarray([1, 3])
    upd = jnp.full((2, 2), 3.0)
    np.testing.assert_allclose(np.asarray(op("scatter_mul")(base, idx, upd))[1],
                               3.0)
    np.testing.assert_allclose(np.asarray(op("scatter_sub")(base, idx, upd))[3],
                               -2.0)
    nd_idx = jnp.asarray([[0, 1], [2, 0]])
    out = np.asarray(op("scatter_nd")(nd_idx, jnp.asarray([5.0, 7.0]), (3, 2)))
    assert out[0, 1] == 5.0 and out[2, 0] == 7.0 and out.sum() == 12.0
    st = np.asarray(op("dynamic_stitch")(
        [jnp.asarray([0, 2]), jnp.asarray([1, 3])],
        [jnp.asarray([[1.], [3.]]), jnp.asarray([[2.], [4.]])]))
    np.testing.assert_allclose(st[:, 0], [1, 2, 3, 4])


# ---- distances / reductions ----

def test_distance_ops():
    a = jnp.asarray([[1.0, 0.0], [0.0, 1.0]])
    b = jnp.asarray([[0.0, 0.0], [0.0, 2.0]])
    np.testing.assert_allclose(op("euclidean_distance")(a, b, axis=-1),
                               [1.0, 1.0])
    np.testing.assert_allclose(op("manhattan_distance")(a, b, axis=-1),
                               [1.0, 1.0])
    np.testing.assert_allclose(op("cosine_similarity")(a, a, axis=-1),
                               [1.0, 1.0], rtol=1e-6)
    np.testing.assert_allclose(op("hamming_distance")(a, b, axis=-1),
                               [1.0, 1.0])


def test_reduction_breadth():
    x = jnp.asarray([[-3.0, 1.0], [2.0, -4.0]])
    np.testing.assert_allclose(op("amax")(x), 4.0)
    np.testing.assert_allclose(op("asum")(x), 10.0)
    np.testing.assert_allclose(op("norm1")(x, axis=1), [4.0, 6.0])
    assert bool(op("reduce_any")(x > 1.5))
    assert not bool(op("reduce_all")(x > 0.0))
    p = jnp.asarray([0.5, 0.5])
    np.testing.assert_allclose(op("entropy")(p), np.log(2), rtol=1e-6)
    np.testing.assert_allclose(op("shannon_entropy")(p), 1.0, rtol=1e-6)
    z = jnp.asarray([0.0, 1.0, 0.0, 2.0])
    np.testing.assert_allclose(op("zero_fraction")(z), 0.5)
    v = jnp.asarray(rng.standard_normal(101).astype(np.float32))
    np.testing.assert_allclose(op("median")(v), np.median(np.asarray(v)),
                               rtol=1e-6)
    np.testing.assert_allclose(op("percentile")(v, 25.0),
                               np.percentile(np.asarray(v), 25.0), rtol=1e-5)
    np.testing.assert_allclose(
        op("nth_element")(v, 3), np.sort(np.asarray(v))[3], rtol=1e-6)


# ---- images ----

def test_colorspace_roundtrips():
    img = jnp.asarray(rng.random((2, 4, 4, 3)).astype(np.float32))
    back = op("hsv_to_rgb")(op("rgb_to_hsv")(img))
    np.testing.assert_allclose(np.asarray(back), np.asarray(img), atol=1e-4)
    np.testing.assert_allclose(np.asarray(op("yiq_to_rgb")(op("rgb_to_yiq")(img))),
                               np.asarray(img), atol=1e-4)
    np.testing.assert_allclose(np.asarray(op("yuv_to_rgb")(op("rgb_to_yuv")(img))),
                               np.asarray(img), atol=1e-4)
    g = np.asarray(op("rgb_to_grs")(img))
    assert g.shape == (2, 4, 4, 1)


def test_adjust_ops():
    img = jnp.asarray(rng.random((1, 4, 4, 3)).astype(np.float32))
    same = op("adjust_hue")(img, 0.0)
    np.testing.assert_allclose(np.asarray(same), np.asarray(img), atol=1e-4)
    c = op("adjust_contrast")(img, 1.0)
    np.testing.assert_allclose(np.asarray(c), np.asarray(img), atol=1e-6)
    s = op("adjust_saturation")(img, 1.0)
    np.testing.assert_allclose(np.asarray(s), np.asarray(img), atol=1e-4)


def test_crop_and_resize_identity():
    img = jnp.asarray(rng.random((1, 8, 8, 2)).astype(np.float32))
    boxes = jnp.asarray([[0.0, 0.0, 1.0, 1.0]])
    out = op("crop_and_resize")(img, boxes, jnp.asarray([0]), (8, 8))
    np.testing.assert_allclose(np.asarray(out)[0], np.asarray(img)[0],
                               atol=1e-5)


def test_extract_image_patches_and_im2col():
    img = jnp.asarray(np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1))
    patches = np.asarray(op("extract_image_patches")(img, (2, 2), (2, 2)))
    assert patches.shape == (1, 2, 2, 4)
    np.testing.assert_allclose(patches[0, 0, 0], [0, 1, 4, 5])
    col = np.asarray(op("im2col")(img, 2, 2, 2, 2))
    assert col.shape == (1, 2, 2, 2, 2, 1)


def test_non_max_suppression():
    boxes = jnp.asarray([[0, 0, 1, 1], [0, 0, 1, 1.04],
                         [2, 2, 3, 3]], jnp.float32)
    scores = jnp.asarray([0.9, 0.8, 0.7])
    picked = np.asarray(op("non_max_suppression")(boxes, scores, 3, 0.5))
    assert picked[0] == 0 and 2 in picked.tolist()
    assert 1 not in picked.tolist()


# ---- spatial / shape ----

def test_space_batch_roundtrip_and_misc():
    x = jnp.asarray(rng.random((2, 4, 4, 3)).astype(np.float32))
    rt = op("batch_to_space")(op("space_to_batch")(x, 2), 2)
    np.testing.assert_allclose(np.asarray(rt), np.asarray(x))
    up = np.asarray(op("upsampling2d")(x, 2))
    assert up.shape == (2, 8, 8, 3)
    assert up[0, 0, 0, 0] == up[0, 1, 1, 0]
    m = np.asarray(op("sequence_mask")(jnp.asarray([1, 3]), 4))
    np.testing.assert_allclose(m, [[1, 0, 0, 0], [1, 1, 1, 0]])
    mp = np.asarray(op("mirror_pad")(jnp.asarray([[1.0, 2.0, 3.0]]),
                                     [(0, 0), (1, 1)]))
    np.testing.assert_allclose(mp[0], [2, 1, 2, 3, 2])
    bt = np.asarray(op("broadcast_to")(jnp.asarray([1.0, 2.0]), (3, 2)))
    assert bt.shape == (3, 2)


# ---- nn breadth ----

def test_conv3d_pool3d():
    x = jnp.asarray(rng.standard_normal((1, 4, 4, 4, 2)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((2, 2, 2, 2, 3)).astype(np.float32)
                    * 0.1)
    y = op("conv3d")(x, w, stride=(1, 1, 1), padding="SAME")
    assert y.shape == (1, 4, 4, 4, 3)
    p = op("max_pooling3d")(x)
    assert p.shape == (1, 2, 2, 2, 2)
    a = np.asarray(op("avg_pooling3d")(x))
    np.testing.assert_allclose(
        a[0, 0, 0, 0, 0], np.asarray(x)[0, :2, :2, :2, 0].mean(), rtol=1e-5)


def test_gru_lstm_cells():
    B, I, H = 2, 3, 4
    x = jnp.asarray(rng.standard_normal((B, I)).astype(np.float32))
    h = jnp.zeros((B, H))
    c = jnp.zeros((B, H))
    w_ih3 = jnp.asarray(rng.standard_normal((I, 3 * H)).astype(np.float32)
                        * 0.3)
    w_hh3 = jnp.asarray(rng.standard_normal((H, 3 * H)).astype(np.float32)
                        * 0.3)
    h2 = op("gru_cell")(x, h, w_ih3, w_hh3)
    assert h2.shape == (B, H) and np.isfinite(np.asarray(h2)).all()
    w_ih4 = jnp.asarray(rng.standard_normal((I, 4 * H)).astype(np.float32)
                        * 0.3)
    w_hh4 = jnp.asarray(rng.standard_normal((H, 4 * H)).astype(np.float32)
                        * 0.3)
    h3, c3 = op("lstm_cell")(x, h, c, w_ih4, w_hh4)
    assert h3.shape == (B, H) and np.isfinite(np.asarray(c3)).all()
    # gradient flows through the cell
    g = jax.grad(lambda w: jnp.sum(op("gru_cell")(x, h, w, w_hh3) ** 2))(
        w_ih3)
    assert np.isfinite(np.asarray(g)).all() and float(jnp.sum(g * g)) > 0


def test_prelu_lrn_misc_activations():
    x = jnp.asarray([[-2.0, 3.0]])
    np.testing.assert_allclose(op("prelu")(x, jnp.asarray([0.1, 0.1])),
                               [[-0.2, 3.0]], rtol=1e-6)
    img = jnp.asarray(rng.random((1, 2, 2, 8)).astype(np.float32))
    y = op("lrn")(img)
    assert y.shape == img.shape and np.isfinite(np.asarray(y)).all()
    assert np.isfinite(np.asarray(op("hard_swish")(x))).all()
    assert np.isfinite(np.asarray(op("log_sigmoid")(x))).all()


# ---- matrix ----

def test_matrix_diag_family():
    d = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    md = np.asarray(op("matrix_diag")(d))
    assert md.shape == (2, 2, 2) and md[0, 0, 0] == 1.0 and md[0, 0, 1] == 0
    np.testing.assert_allclose(np.asarray(op("matrix_diag_part")(md)), d)
    a = jnp.ones((2, 2))
    out = np.asarray(op("matrix_set_diag")(a, jnp.asarray([5.0, 6.0])))
    np.testing.assert_allclose(out, [[5, 1], [1, 6]])
    spd = jnp.asarray(np.array([[4.0, 1.0], [1.0, 3.0]], np.float32))
    pl, l_, u_ = op("lu")(spd)
    np.testing.assert_allclose(np.asarray(pl @ l_ @ u_), np.asarray(spd),
                               rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(op("pinv")(spd) @ spd), np.eye(2), atol=1e-5)


# ---- compare/classification ----

def test_is_max_in_top_k_confusion():
    a = jnp.asarray([[1.0, 3.0, 2.0]])
    np.testing.assert_allclose(op("is_max")(a), [[0, 1, 0]])
    preds = jnp.asarray([[0.1, 0.9], [0.8, 0.2]])
    t = np.asarray(op("in_top_k")(preds, jnp.asarray([1, 1]), 1))
    assert t.tolist() == [True, False]
    cm = np.asarray(op("confusion_matrix")(
        jnp.asarray([0, 1, 1]), jnp.asarray([0, 1, 0]), 2))
    np.testing.assert_allclose(cm, [[1, 0], [1, 1]])


# ---- losses ----

def test_loss_breadth():
    labels = jnp.asarray([1.0, 0.0, 1.0])
    logits = jnp.asarray([2.0, -1.0, -0.5])
    h = float(op("hinge_loss")(labels, logits))
    np.testing.assert_allclose(h, np.mean([0.0, 0.0, 1.5]), rtol=1e-6)
    w = float(op("weighted_cross_entropy_with_logits")(labels, logits, 2.0))
    # oracle: TF formula
    ref = np.mean((1 - np.asarray(labels)) * np.asarray(logits)
                  + (1 + np.asarray(labels))
                  * np.log1p(np.exp(-np.abs(np.asarray(logits))))
                  + (1 + np.asarray(labels))
                  * np.maximum(-np.asarray(logits), 0))
    np.testing.assert_allclose(w, ref, rtol=1e-5)
    p = float(op("poisson_loss")(jnp.asarray([2.0]), jnp.asarray([3.0])))
    np.testing.assert_allclose(p, 3.0 - 2.0 * np.log(3.0 + 1e-8), rtol=1e-5)
    kl = float(op("kl_divergence")(jnp.asarray([[0.5, 0.5]]),
                                   jnp.asarray([[0.25, 0.75]])))
    ref_kl = 0.5 * np.log(2) + 0.5 * np.log(2 / 3)
    np.testing.assert_allclose(kl, ref_kl, rtol=1e-5)


def test_ctc_loss_matches_torch():
    torch = pytest.importorskip("torch")
    B, T, C, S = 2, 8, 5, 3
    logits = rng.standard_normal((B, T, C)).astype(np.float32)
    log_probs = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
    labels = np.array([[1, 2, 1], [3, 3, 0]], np.int64)  # second has len 2
    in_len = np.array([8, 6])
    lab_len = np.array([3, 2])
    ours = np.asarray(OP_TABLE["ctc_loss"](
        log_probs, jnp.asarray(labels), jnp.asarray(in_len),
        jnp.asarray(lab_len)))
    t_lp = torch.from_numpy(np.asarray(log_probs)).permute(1, 0, 2)
    ref = torch.nn.functional.ctc_loss(
        t_lp, torch.from_numpy(labels), torch.from_numpy(in_len),
        torch.from_numpy(lab_len), blank=0, reduction="none")
    np.testing.assert_allclose(ours, ref.numpy(), rtol=1e-4, atol=1e-4)


def test_ctc_loss_grad_finite():
    B, T, C = 1, 6, 4
    logits = jnp.asarray(rng.standard_normal((B, T, C)).astype(np.float32))
    labels = jnp.asarray([[1, 2]])
    fn = lambda lg: jnp.sum(OP_TABLE["ctc_loss"](
        jax.nn.log_softmax(lg, -1), labels, jnp.asarray([6]),
        jnp.asarray([2])))
    g = jax.grad(fn)(logits)
    assert np.isfinite(np.asarray(g)).all()


# ---- special functions ----

def test_special_functions():
    sp = pytest.importorskip("scipy.special")
    x = np.linspace(0.1, 3.0, 7).astype(np.float32)
    np.testing.assert_allclose(op("igamma")(2.0, jnp.asarray(x)),
                               sp.gammainc(2.0, x), rtol=1e-4)
    np.testing.assert_allclose(op("igammac")(2.0, jnp.asarray(x)),
                               sp.gammaincc(2.0, x), rtol=1e-4)
    np.testing.assert_allclose(
        op("betainc")(2.0, 3.0, jnp.asarray(x / 4)),
        sp.betainc(2.0, 3.0, x / 4), rtol=1e-4)
    np.testing.assert_allclose(op("zeta")(jnp.asarray([2.0]), 1.0),
                               [np.pi ** 2 / 6], rtol=1e-4)


def test_clip_by_global_norm():
    xs = [jnp.asarray([3.0, 4.0]), jnp.asarray([0.0])]
    out = op("clip_by_global_norm")(1.0, *xs)
    total = np.sqrt(sum(float(jnp.sum(o * o)) for o in out))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_op_count_vs_reference_inventory():
    """Round-2 breadth: the registry should keep growing toward the ~500
    reference declarable ops (VERDICT round 1: 113; round 2: 400+)."""
    assert len(OP_TABLE) >= 400, len(OP_TABLE)


def test_matrix_set_diag_rectangular():
    a = jnp.ones((2, 3))
    out = np.asarray(op("matrix_set_diag")(a, jnp.asarray([7.0, 8.0])))
    np.testing.assert_allclose(out, [[7, 1, 1], [1, 8, 1]])
    a2 = jnp.ones((3, 2))
    out2 = np.asarray(op("matrix_set_diag")(a2, jnp.asarray([7.0, 8.0])))
    np.testing.assert_allclose(out2, [[7, 1], [1, 8], [1, 1]])


def test_dynamic_stitch_sizes_by_max_index():
    out = np.asarray(op("dynamic_stitch")(
        [jnp.asarray([0, 1]), jnp.asarray([1, 2])],
        [jnp.asarray([[1.], [9.]]), jnp.asarray([[2.], [3.]])]))
    assert out.shape == (3, 1)                     # max index + 1, not 4
    np.testing.assert_allclose(out[:, 0], [1, 2, 3])  # later list wins at 1


def test_cyclic_shift_identity_at_zero():
    a = jnp.asarray([5, 9], jnp.int32)
    np.testing.assert_array_equal(op("cyclic_shift_left")(a, 0), a)
    np.testing.assert_array_equal(op("cyclic_shift_left")(a, 32), a)
    np.testing.assert_array_equal(op("cyclic_shift_left")(a, 1), [10, 18])


def test_ctc_loss_empty_targets():
    """S == 0 (all-blank targets): loss is -sum of blank log-probs over the
    input length (code-review r2)."""
    B, T, C = 2, 5, 4
    lp = jax.nn.log_softmax(
        jnp.asarray(rng.standard_normal((B, T, C)).astype(np.float32)), -1)
    out = np.asarray(OP_TABLE["ctc_loss"](
        lp, jnp.zeros((B, 0), jnp.int32), jnp.asarray([5, 3]),
        jnp.asarray([0, 0])))
    ref0 = -np.asarray(lp)[0, :5, 0].sum()
    ref1 = -np.asarray(lp)[1, :3, 0].sum()
    np.testing.assert_allclose(out, [ref0, ref1], rtol=1e-5)


# ---- round-2 second batch: fft / image transforms / set ops / misc ----

def test_fft_family():
    x = jnp.asarray(rng.standard_normal(16).astype(np.float32))
    np.testing.assert_allclose(np.asarray(op("ifft")(op("fft")(x))).real,
                               np.asarray(x), atol=1e-5)
    r = op("rfft")(x)
    assert r.shape == (9,)
    np.testing.assert_allclose(np.asarray(op("irfft")(r, n=16)),
                               np.asarray(x), atol=1e-5)
    img = jnp.asarray(rng.standard_normal((4, 4)).astype(np.float32))
    np.testing.assert_allclose(np.asarray(op("ifft2")(op("fft2")(img))).real,
                               np.asarray(img), atol=1e-5)


def test_image_transforms():
    img = jnp.asarray(np.arange(2 * 4 * 4 * 3, dtype=np.float32)
                      .reshape(2, 4, 4, 3))
    lr = np.asarray(op("image_flip_left_right")(img))
    np.testing.assert_allclose(lr[0, 0, 0], np.asarray(img)[0, 0, 3])
    ud = np.asarray(op("image_flip_up_down")(img))
    np.testing.assert_allclose(ud[0, 0], np.asarray(img)[0, 3])
    r4 = np.asarray(op("image_rot90")(img, 2))
    np.testing.assert_allclose(np.asarray(op("image_rot90")(r4, 2)),
                               np.asarray(img))
    std = np.asarray(op("per_image_standardization")(img))
    assert abs(std[0].mean()) < 1e-5
    cc = np.asarray(op("image_central_crop")(img, 0.5))
    assert cc.shape == (2, 2, 2, 3)
    crop = op("random_crop")(jax.random.PRNGKey(0), img, (2, 2, 2, 3))
    assert crop.shape == (2, 2, 2, 3)


def test_set_and_search_ops():
    a = jnp.asarray([3, 1, 4, 1, 5])
    vals, counts = op("unique_with_counts")(a, size=4)
    assert 1 in np.asarray(vals) and counts[np.asarray(vals) == 1] == 2
    diff = np.asarray(op("setdiff1d")(a, jnp.asarray([1, 5]), size=3))
    assert set(diff.tolist()) == {3, 4}
    nz = np.asarray(op("nonzero")(jnp.asarray([[0, 1], [2, 0]]), size=2))
    np.testing.assert_array_equal(nz, [[0, 1], [1, 0]])
    assert bool(op("equals_with_eps")(jnp.asarray([1.0]),
                                      jnp.asarray([1.0 + 1e-7])))
    assert not bool(op("is_finite_all")(jnp.asarray([1.0, np.inf])))


def test_shape_and_linalg_completions():
    a = jnp.asarray(rng.standard_normal((3, 4)).astype(np.float32))
    parts = op("unstack")(a, axis=0)
    assert len(parts) == 3 and parts[0].shape == (4,)
    assert int(op("size_of")(a)) == 12 and int(op("rank_of")(a)) == 2
    ce = np.asarray(op("cumsum_ext")(jnp.asarray([1.0, 2.0, 3.0]),
                                     exclusive=True))
    np.testing.assert_allclose(ce, [0, 1, 3])
    cr = np.asarray(op("cumsum_ext")(jnp.asarray([1.0, 2.0, 3.0]),
                                     reverse=True))
    np.testing.assert_allclose(cr, [6, 5, 3])
    spd = jnp.asarray([[4.0, 1.0], [1.0, 3.0]])
    sign, logdet = op("slogdet")(spd)
    np.testing.assert_allclose(float(sign) * np.exp(float(logdet)), 11.0,
                               rtol=1e-5)
    assert int(op("matrix_rank")(spd)) == 2
    pm = np.asarray(op("pad_mode")(jnp.asarray([[1.0, 2.0]]),
                                   [(0, 0), (1, 1)], mode="edge"))
    np.testing.assert_allclose(pm[0], [1, 1, 2, 2])
    np.testing.assert_allclose(
        np.asarray(op("truncate_div")(jnp.asarray([-7.0]),
                                      jnp.asarray([2.0]))), [-3.0])


def test_setdiff1d_padding_never_leaks_excluded_values():
    out = np.asarray(op("setdiff1d")(jnp.asarray([1, 2, 3]),
                                     jnp.asarray([1]), size=3))
    assert 1 not in out.tolist()        # pad repeats a kept element instead
    assert set(out.tolist()) == {2, 3}


def test_central_crop_keeps_remainder_pixel():
    img = jnp.asarray(rng.random((1, 5, 5, 1)).astype(np.float32))
    out = op("image_central_crop")(img, 0.5)
    assert out.shape == (1, 3, 3, 1)       # TF keeps the remainder pixel


def test_segment_prod_unsorted_ids():
    data = jnp.asarray([2.0, 3.0, 5.0])
    out = np.asarray(op("segment_prod")(data, jnp.asarray([1, 0, 1]), 2))
    np.testing.assert_allclose(out, [3.0, 10.0])


# ---- round-2 third batch: updater ops / gru / morphology / merges ----

def test_updater_ops_match_stateful_updaters():
    """Functional updater ops vs the train/updaters classes (reference
    generic/updaters/*.cpp are the same duality)."""
    from deeplearning4j_tpu.train.updaters import Adam, Nesterovs, RmsProp  # noqa: F401
    g = jnp.asarray(rng.standard_normal((4, 3)).astype(np.float32))
    p = jnp.asarray(rng.standard_normal((4, 3)).astype(np.float32))

    # adam: one step from zero state, t=0
    upd, m, v = op("adam_updater")(g, jnp.zeros_like(g), jnp.zeros_like(g),
                                   0, lr=1e-3)
    cfg = Adam(1e-3)
    st = cfg.init_state({"w": p})
    ref_upd, _ = cfg.apply(st, {"w": g}, 0, 0, params={"w": p})
    np.testing.assert_allclose(np.asarray(upd), np.asarray(ref_upd["w"]),
                               rtol=1e-5, atol=1e-7)

    # rmsprop
    upd2, s2 = op("rms_prop_updater")(g, jnp.zeros_like(g), lr=1e-3)
    cfg2 = RmsProp(1e-3)
    st2 = cfg2.init_state({"w": p})
    ref2, _ = cfg2.apply(st2, {"w": g}, 0, 0, params={"w": p})
    np.testing.assert_allclose(np.asarray(upd2), np.asarray(ref2["w"]),
                               rtol=1e-4, atol=1e-7)

    # nesterovs: update must match the stateful class exactly
    upd3, v3 = op("nesterovs_updater")(g, jnp.zeros_like(g), lr=0.1,
                                       momentum=0.9)
    cfg3 = Nesterovs(0.1, 0.9)
    st3 = cfg3.init_state({"w": p})
    ref3, _ = cfg3.apply(st3, {"w": g}, 0, 0, params={"w": p})
    np.testing.assert_allclose(np.asarray(upd3), np.asarray(ref3["w"]),
                               rtol=1e-5, atol=1e-7)

    # shapes/finiteness across the rest
    z = jnp.zeros_like(g)
    for name, args in [("sgd_updater", (g,)),
                       ("ada_grad_updater", (g, z)),
                       ("ada_delta_updater", (g, z, z)),
                       ("ada_max_updater", (g, z, z, 0)),
                       ("nadam_updater", (g, z, z, 0)),
                       ("ams_grad_updater", (g, z, z, z, 0))]:
        out = op(name)(*args)
        first = out[0] if isinstance(out, tuple) else out
        assert first.shape == g.shape
        assert np.isfinite(np.asarray(first)).all(), name


def test_gru_layer_scan():
    B, T, F, H = 2, 5, 3, 4
    x = jnp.asarray(rng.standard_normal((B, T, F)).astype(np.float32))
    h0 = jnp.zeros((B, H))
    w_ih = jnp.asarray(rng.standard_normal((F, 3 * H)).astype(np.float32)
                       * 0.3)
    w_hh = jnp.asarray(rng.standard_normal((H, 3 * H)).astype(np.float32)
                       * 0.3)
    ys = op("gru_layer")(x, h0, w_ih, w_hh)
    assert ys.shape == (B, T, H)
    # last output equals manually chaining the cell
    h = h0
    for t in range(T):
        h = op("gru_cell")(x[:, t], h, w_ih, w_hh)
    np.testing.assert_allclose(np.asarray(ys[:, -1]), np.asarray(h),
                               rtol=1e-5)


def test_dilation2d_matches_tf():
    tf = pytest.importorskip("tensorflow")
    x = rng.random((1, 6, 6, 2)).astype(np.float32)
    f = rng.random((3, 3, 2)).astype(np.float32) * 0.1
    ours = np.asarray(op("dilation2d")(jnp.asarray(x), jnp.asarray(f)))
    ref = tf.nn.dilation2d(x, f, strides=(1, 1, 1, 1), padding="SAME",
                           data_format="NHWC", dilations=(1, 1, 1, 1))
    np.testing.assert_allclose(ours, ref.numpy(), rtol=1e-5)
    # negative feature maps: SAME borders must pad with -inf, not zero
    xn = (x - 5.0).astype(np.float32)
    ours_n = np.asarray(op("dilation2d")(jnp.asarray(xn), jnp.asarray(f)))
    ref_n = tf.nn.dilation2d(xn, f, strides=(1, 1, 1, 1), padding="SAME",
                             data_format="NHWC", dilations=(1, 1, 1, 1))
    np.testing.assert_allclose(ours_n, ref_n.numpy(), rtol=1e-5)


def test_max_pool_with_argmax():
    x = jnp.asarray(np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1))
    vals, idxs = op("max_pool_with_argmax")(x)
    np.testing.assert_allclose(np.asarray(vals)[0, :, :, 0],
                               [[5, 7], [13, 15]])
    np.testing.assert_array_equal(np.asarray(idxs)[0, :, :, 0],
                                  [[5, 7], [13, 15]])
    # multi-channel: TF contract index = (h*W + w)*C + c
    tf = pytest.importorskip("tensorflow")
    xc = rng.random((1, 4, 4, 3)).astype(np.float32)
    v2, i2 = op("max_pool_with_argmax")(jnp.asarray(xc))
    tv, ti = tf.nn.max_pool_with_argmax(xc, 2, 2, "VALID",
                                        include_batch_in_index=False)
    np.testing.assert_allclose(np.asarray(v2), tv.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(i2), ti.numpy())


def test_col2im_inverts_im2col_counts():
    x = jnp.ones((1, 4, 4, 1))
    cols = op("im2col")(x, 2, 2, 2, 2)      # non-overlapping
    back = np.asarray(op("col2im")(cols, 4, 4, 2, 2, 2, 2))
    np.testing.assert_allclose(back, np.ones((1, 4, 4, 1)))


def test_merge_and_misc_ops():
    a, b, c = (jnp.asarray([1.0, 5.0]), jnp.asarray([4.0, 2.0]),
               jnp.asarray([3.0, 3.0]))
    np.testing.assert_allclose(op("mergemax")(a, b, c), [4, 5])
    np.testing.assert_allclose(op("mergeadd")(a, b, c), [8, 10])
    np.testing.assert_allclose(op("mergeavg")(a, b, c), [8 / 3, 10 / 3])
    np.testing.assert_allclose(op("norm_p")(jnp.asarray([3.0, 4.0]), p=2),
                               5.0, rtol=1e-6)
    h = np.asarray(op("histogram")(jnp.asarray([0.1, 0.2, 0.9]), 2))
    np.testing.assert_array_equal(h, [2, 1])
    # clip_by_average_norm semantics: divisor is norm2/numel
    cl = np.asarray(op("clip_by_avg_norm")(jnp.asarray([6.0, 8.0]), 1.0))
    np.testing.assert_allclose(cl, [1.2, 1.6], rtol=1e-5)
    lp = float(op("log_poisson_loss")(jnp.asarray([2.0]),
                                      jnp.asarray([1.0])))
    np.testing.assert_allclose(lp, np.exp(1.0) - 2.0, rtol=1e-5)


# ---- round-2 fourth batch ----

def test_sru_layer_and_cell():
    B, T, H = 2, 4, 5
    F = H                 # SRU highway uses the raw input: inSize == nUnits
    x = jnp.asarray(rng.standard_normal((B, T, F)).astype(np.float32))
    c0 = jnp.zeros((B, H))
    w = jnp.asarray(rng.standard_normal((F, 3 * H)).astype(np.float32) * 0.3)
    b = jnp.zeros(2 * H)
    ys = op("sru_layer")(x, c0, w, b)
    assert ys.shape == (B, T, H)
    h, c = op("sru_cell")(x[:, 0], c0, w, b)
    np.testing.assert_allclose(np.asarray(ys[:, 0]), np.asarray(h),
                               rtol=1e-5)
    g = jax.grad(lambda w_: jnp.sum(op("sru_layer")(x, c0, w_, b) ** 2))(w)
    assert np.isfinite(np.asarray(g)).all()


def test_resize_variants_and_solves():
    img = jnp.asarray(rng.random((1, 4, 4, 2)).astype(np.float32))
    assert op("resize_bicubic")(img, (8, 8)).shape == (1, 8, 8, 2)
    assert op("resize_lanczos")(img, (8, 8)).shape == (1, 8, 8, 2)
    spd = jnp.asarray([[4.0, 1.0], [1.0, 3.0]])
    bvec = jnp.asarray([1.0, 2.0])
    chol = jnp.linalg.cholesky(spd)
    np.testing.assert_allclose(np.asarray(op("cholesky_solve")(chol, bvec)),
                               np.linalg.solve(np.asarray(spd),
                                               np.asarray(bvec)), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(op("lu_solve")(spd, bvec)),
                               np.linalg.solve(np.asarray(spd),
                                               np.asarray(bvec)), rtol=1e-5)


def test_mean_pairwise_squared_error_matches_tf():
    """Per-sample values match TF exactly (batch=1); our batch reduction
    is a plain mean, unlike TF's historical SUM_BY_NONZERO_WEIGHTS
    denominator."""
    tf = pytest.importorskip("tensorflow")
    labels = rng.random((3, 5)).astype(np.float32)
    preds = rng.random((3, 5)).astype(np.float32)
    for b in range(3):
        ours = float(op("mean_pairwise_squared_error")(
            jnp.asarray(labels[b:b + 1]), jnp.asarray(preds[b:b + 1])))
        ref = float(tf.compat.v1.losses.mean_pairwise_squared_error(
            labels[b:b + 1], preds[b:b + 1]))
        np.testing.assert_allclose(ours, ref, rtol=1e-4)


def test_ctc_greedy_decode():
    # frames argmax: [1, 1, blank, 2, 2, 1] -> collapse/drop -> [1, 2, 1]
    C = 4
    seq = [1, 1, 0, 2, 2, 1]
    lp = jnp.asarray(np.eye(C, dtype=np.float32)[seq][None] * 10.0)
    out = np.asarray(op("ctc_greedy_decode")(lp, jnp.asarray([6])))
    assert out[0].tolist()[:3] == [1, 2, 1]
    assert (out[0][3:] == -1).all()
    # respects input_lengths
    out2 = np.asarray(op("ctc_greedy_decode")(lp, jnp.asarray([2])))
    assert out2[0].tolist()[:1] == [1] and (out2[0][1:] == -1).all()


def test_alpha_dropout_preserves_moments():
    key = jax.random.PRNGKey(0)
    x = jnp.asarray(rng.standard_normal(20000).astype(np.float32))
    y = np.asarray(op("alpha_dropout")(x, key, p=0.1))
    assert abs(y.mean() - np.asarray(x).mean()) < 0.05
    assert abs(y.std() - np.asarray(x).std()) < 0.1
    np.testing.assert_array_equal(np.asarray(op("alpha_dropout")(x, None)),
                                  np.asarray(x))


def test_sparse_to_dense_and_fused_bn():
    idx = jnp.asarray([[0, 1], [2, 0]])
    dense = np.asarray(op("sparse_to_dense")(idx, (3, 2),
                                             jnp.asarray([5.0, 7.0])))
    assert dense[0, 1] == 5.0 and dense[2, 0] == 7.0
    x = jnp.asarray(rng.random((2, 4, 4, 3)).astype(np.float32))
    y, m, v = op("fused_batch_norm")(x, jnp.ones(3), jnp.zeros(3))
    assert y.shape == x.shape and m.shape == (3,) and v.shape == (3,)
    np.testing.assert_allclose(np.asarray(y).mean(axis=(0, 1, 2)), 0.0,
                               atol=1e-4)
    # batch_var output is Bessel-corrected (TF contract), n = 2*4*4 = 32
    np.testing.assert_allclose(
        np.asarray(v),
        np.asarray(x).reshape(-1, 3).var(0, ddof=1), rtol=1e-5)


def test_dilation2d_integer_dtypes():
    x = jnp.asarray(rng.integers(0, 255, (1, 5, 5, 1)), jnp.int32)
    f = jnp.zeros((3, 3, 1), jnp.int32)
    out = np.asarray(op("dilation2d")(x, f))
    assert out.shape == (1, 5, 5, 1)
    # center output = window max of the input
    assert out[0, 2, 2, 0] == np.asarray(x)[0, 1:4, 1:4, 0].max()


def test_sparse_to_dense_1d():
    out = np.asarray(op("sparse_to_dense")(
        jnp.asarray([0, 2]), (4,), jnp.asarray([5.0, 7.0])))
    np.testing.assert_allclose(out, [5, 0, 7, 0])


def test_encode_decode_threshold_roundtrip_vs_native_codec():
    """Graph-op forms are wire-compatible with the host C++ codec
    (reference threshold_encoding.cpp round-trip)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    from deeplearning4j_tpu.native_ops import ThresholdCodec
    rng = np.random.RandomState(0)
    g = (rng.randn(256).astype(np.float32) * 2e-3)
    thr = 1e-3
    enc = np.asarray(OP_TABLE["encode_threshold"](jnp.asarray(g), thr))
    # codec with zero residual produces the same code stream (order and
    # sign-in-index format), modulo trailing zero padding
    codec = ThresholdCodec(g.size, threshold=thr)
    ref = codec.encode(g)
    nz = enc[enc != 0]
    np.testing.assert_array_equal(nz, ref)
    # decode: graph op == codec decode
    dec = np.asarray(OP_TABLE["decode_threshold"](jnp.asarray(enc), g.size,
                                                  thr))
    ref_dec = ThresholdCodec(g.size, threshold=thr).decode(ref)
    np.testing.assert_allclose(dec, ref_dec, atol=0)
    # every decoded entry is ±thr at positions where |g| >= thr
    np.testing.assert_array_equal(dec != 0, np.abs(g) >= thr)
    # jit-compatible with static capacity
    import jax
    f = jax.jit(lambda x: OP_TABLE["encode_threshold"](x, thr, 64))
    enc64 = np.asarray(f(jnp.asarray(g)))
    assert enc64.shape == (64,)
    np.testing.assert_array_equal(enc64[enc64 != 0], ref[:np.sum(enc64 != 0)])


# ---- round-3 op tail ----

def test_round3_elementwise_and_misc_ops():
    assert np.allclose(op("divide_no_nan")(jnp.asarray([1.0, 2.0]),
                                           jnp.asarray([0.0, 4.0])),
                       [0.0, 0.5])
    p = jnp.asarray([2, 0, 1])
    np.testing.assert_array_equal(op("invert_permutation")(p), [1, 2, 0])
    x = jnp.asarray([0.5, 1.5, 2.5, 10.0])
    np.testing.assert_array_equal(
        op("bucketize")(x, [1.0, 2.0, 3.0]), [0, 1, 2, 3])
    # lbeta vs scipy identity: B(a,b) = G(a)G(b)/G(a+b)
    from scipy.special import betaln
    ab = np.asarray([[2.0, 3.0], [0.5, 0.5]])
    np.testing.assert_allclose(op("lbeta")(jnp.asarray(ab)),
                               betaln(ab[:, 0], ab[:, 1]), rtol=1e-5)
    g = jax.grad(lambda a: jnp.sum(op("stop_gradient")(a) * a))(
        jnp.asarray([3.0]))
    np.testing.assert_allclose(g, [3.0])   # only the non-stopped factor
    np.testing.assert_array_equal(
        op("mergemaxindex")(jnp.asarray([1.0, 5.0]),
                            jnp.asarray([2.0, 1.0])), [1, 0])
    np.testing.assert_array_equal(
        op("reverse")(jnp.arange(6).reshape(2, 3), [0, 1]),
        np.arange(6).reshape(2, 3)[::-1, ::-1])


def test_round3_quantization_ops():
    x = jnp.asarray([-10.0, -1.0, 0.0, 0.3, 5.9, 10.0])
    q = np.asarray(op("fake_quant_with_min_max_args")(x, min=-6.0, max=6.0))
    # output lies on the quantization grid within the nudged range
    scale = 12.0 / 255.0
    np.testing.assert_allclose((q - q.min()) / scale,
                               np.round((q - q.min()) / scale), atol=1e-4)
    assert q.min() >= -6.1 and q.max() <= 6.1
    q2 = np.asarray(op("fake_quant_with_min_max_vars")(
        x, jnp.asarray(-6.0), jnp.asarray(6.0)))
    np.testing.assert_allclose(q, q2)
    bits = op("compare_and_bitpack")(
        jnp.asarray([1.0, -1.0, 1.0, -1.0, -1.0, -1.0, -1.0, 1.0]), 0.0)
    np.testing.assert_array_equal(bits, [0b10100001])


def test_round3_pooling_conv_ops():
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 3)).astype(np.float32))
    pn = op("pnorm_pool2d")(x, (2, 2), (2, 2), p=2)
    want = np.sqrt((np.asarray(x).reshape(2, 4, 2, 4, 2, 3) ** 2)
                   .sum(axis=(2, 4)))
    np.testing.assert_allclose(np.asarray(pn), want, rtol=1e-5)
    xt = jnp.asarray(rng.standard_normal((2, 10, 4)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((3, 4, 6)).astype(np.float32))
    y = op("conv1d")(xt, w, padding="VALID")
    assert y.shape == (2, 8, 6)
    # oracle: manual sliding dot
    want0 = sum(np.asarray(xt)[0, i:i + 3].reshape(-1)
                @ np.asarray(w).reshape(-1, 6) for i in [0])[None]
    np.testing.assert_allclose(np.asarray(y)[0, 0], want0[0], rtol=1e-4)
    mp = op("max_pooling1d")(xt, 2, 2)
    np.testing.assert_allclose(
        np.asarray(mp), np.asarray(xt).reshape(2, 5, 2, 4).max(2),
        rtol=1e-6)
    ap = op("avg_pooling1d")(xt, 2, 2)
    np.testing.assert_allclose(
        np.asarray(ap), np.asarray(xt).reshape(2, 5, 2, 4).mean(2),
        rtol=1e-6)
    # separable == depthwise then 1x1 (oracle via conv2d on each channel)
    xi = jnp.asarray(rng.standard_normal((1, 6, 6, 2)).astype(np.float32))
    wd = jnp.asarray(rng.standard_normal((3, 3, 2, 1)).astype(np.float32))
    wp = jnp.asarray(rng.standard_normal((1, 1, 2, 4)).astype(np.float32))
    ys = op("separable_conv2d")(xi, wd, wp, padding="VALID")
    assert ys.shape == (1, 4, 4, 4)
    yd = op("depthwise_conv2d")(xi, jnp.reshape(wd, (3, 3, 1, 2)),
                               padding="VALID")
    np.testing.assert_allclose(
        np.asarray(ys),
        np.einsum("bhwi,io->bhwo", np.asarray(yd),
                  np.asarray(wp).reshape(2, 4)), rtol=1e-4)


def test_round3_space_batch_nd_roundtrip():
    x = jnp.asarray(rng.standard_normal((2, 6, 4, 3)).astype(np.float32))
    y = op("space_to_batch_nd")(x, [2, 2], [[1, 1], [0, 0]])
    assert y.shape == (8, 4, 2, 3)
    back = op("batch_to_space_nd")(y, [2, 2], [[1, 1], [0, 0]])
    np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=0)


def test_round3_image_ops():
    a = jnp.asarray(rng.standard_normal((1, 8, 8, 2)).astype(np.float32))
    area = op("resize_area")(a, (4, 4))
    np.testing.assert_allclose(
        np.asarray(area),
        np.asarray(a).reshape(1, 4, 2, 4, 2, 2).mean(axis=(2, 4)),
        rtol=1e-6)
    img = jnp.zeros((1, 8, 8, 3), jnp.float32)
    boxes = jnp.asarray([[[0.25, 0.25, 0.75, 0.75]]])
    drawn = np.asarray(op("draw_bounding_boxes")(img, boxes))
    assert drawn.sum() > 0 and drawn[0, 0, 0].sum() == 0  # corner untouched
    ov = jnp.asarray([[1.0, 0.9, 0.0], [0.9, 1.0, 0.0], [0.0, 0.0, 1.0]])
    sc = jnp.asarray([0.9, 0.8, 0.7])
    picked = np.asarray(op("non_max_suppression_overlaps")(ov, sc, 3, 0.5))
    np.testing.assert_array_equal(picked, [0, 2, -1])


def test_round3_rnn_layer_ops():
    B, T, F, H = 2, 5, 3, 4
    x = jnp.asarray(rng.standard_normal((B, T, F)).astype(np.float32))
    w_ih = jnp.asarray(rng.standard_normal((F, 4 * H)).astype(np.float32)
                       * 0.3)
    w_hh = jnp.asarray(rng.standard_normal((H, 4 * H)).astype(np.float32)
                       * 0.3)
    ys, h, c = op("lstm_layer_full")(x, w_ih, w_hh)
    assert ys.shape == (B, T, H)
    # oracle: manual cell loop
    hh = np.zeros((B, H), np.float32)
    cc = np.zeros((B, H), np.float32)
    for t in range(T):
        hh, cc = (np.asarray(v) for v in
                  OP_TABLE["lstm_cell"](x[:, t], jnp.asarray(hh),
                                        jnp.asarray(cc), w_ih, w_hh))
    np.testing.assert_allclose(np.asarray(ys[:, -1]), hh, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(h), hh, rtol=1e-5)


def test_round3_ctc_beam_decode():
    # easy case: beam agrees with greedy collapse
    B, T, C = 1, 6, 4
    logits = np.full((B, T, C), -5.0, np.float32)
    path = [1, 1, 0, 2, 2, 3]
    for t, c in enumerate(path):
        logits[0, t, c] = 0.0
    lp = jnp.asarray(logits - np.log(np.exp(logits).sum(-1, keepdims=True)))
    out = op("ctc_beam_decode")(lp, jnp.asarray([T]), beam_width=4)
    assert out == [[1, 2, 3]]


def test_round3_random_and_partition_ops():
    import jax.random as jr
    key = jr.PRNGKey(0)
    tn = np.asarray(op("truncated_normal")(key, (2000,), 0.0, 1.0))
    assert np.abs(tn).max() <= 2.0 + 1e-6
    ri = np.asarray(op("random_randint")(key, (1000,), 3, 7))
    assert ri.min() >= 3 and ri.max() <= 6
    parts = op("dynamic_partition")(
        jnp.asarray([10., 20., 30., 40.]), jnp.asarray([1, 0, 1, 0]), 2)
    np.testing.assert_allclose(np.asarray(parts[0]), [20., 40.])
    np.testing.assert_allclose(np.asarray(parts[1]), [10., 30.])
    cnt, mss, vss, _ = op("sufficient_statistics")(
        jnp.asarray(np.arange(12, dtype=np.float32).reshape(3, 4)), (0,))
    assert float(cnt) == 3
    np.testing.assert_allclose(np.asarray(mss),
                               np.arange(12.).reshape(3, 4).sum(0))
    x8 = jnp.asarray([0b10110001], jnp.uint8)  # placeholder usage check
    _ = x8
    np.testing.assert_array_equal(
        np.asarray(op("cyclic_shift_right")(jnp.asarray([2], jnp.uint8),
                                            1)), [1])


def test_round3b_parity_ops():
    from scipy.special import erfinv as sp_erfinv
    x = jnp.asarray([0.1, -0.5, 0.9])
    np.testing.assert_allclose(np.asarray(op("erfinv")(x)),
                               sp_erfinv(np.asarray(x)), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(op("polyval")([2.0, 0.0, 1.0], jnp.asarray([3.0]))),
        [19.0])
    assert bool(op("is_non_decreasing")(jnp.asarray([1., 1., 2.])))
    assert not bool(op("is_strictly_increasing")(jnp.asarray([1., 1.])))
    assert op("is_numeric_tensor")(jnp.asarray([1.0]))
    ui = np.asarray(op("unravel_index")(jnp.asarray([5, 7]), (2, 4)))
    np.testing.assert_array_equal(ui, [[1, 1], [1, 3]])
    h1 = int(op("hashcode")(jnp.asarray([1.0, 2.0])))
    h2 = int(op("hashcode")(jnp.asarray([1.0, 2.0])))
    h3 = int(op("hashcode")(jnp.asarray([1.0, 2.1])))
    assert h1 == h2 and h1 != h3
    vals, cnt = op("choose")(jnp.asarray([1., 5., 3., 0.]), 2.5, mode=2)
    np.testing.assert_allclose(np.asarray(vals), [5., 3.])
    assert int(cnt) == 2
    np.testing.assert_array_equal(
        np.asarray(op("broadcast_dynamic_shape")(jnp.asarray([2, 1]),
                                                 jnp.asarray([3]))), [2, 3])
    ra, rb = op("broadcast_gradient_args")(jnp.asarray([2, 1]),
                                           jnp.asarray([2, 3]))
    np.testing.assert_array_equal(np.asarray(ra), [1])
    np.testing.assert_array_equal(np.asarray(rb), [])


def test_round3b_tsne_and_knn_ops():
    g = op("barnes_gains")(jnp.asarray([1.0, 1.0, 0.012]),
                           jnp.asarray([1.0, -1.0, 1.0]),
                           jnp.asarray([1.0, 1.0, 1.0]))
    np.testing.assert_allclose(np.asarray(g), [0.8, 1.2, 0.01], rtol=1e-6)
    # symmetrize a tiny CSR matrix: P[0,1]=1 -> (P+P^T)/2 has 0.5 both ways
    rp, cp, vp = op("barnes_symmetrize")(jnp.asarray([0, 1, 1]),
                                         jnp.asarray([1]),
                                         jnp.asarray([1.0]), 2)
    from scipy.sparse import csr_matrix
    m = csr_matrix((np.asarray(vp), np.asarray(cp), np.asarray(rp)),
                   shape=(2, 2)).toarray()
    np.testing.assert_allclose(m, [[0, 0.5], [0.5, 0]])
    y = jnp.asarray([[0.0, 0.0], [1.0, 0.0]])
    f = np.asarray(op("barnes_edge_forces")(jnp.asarray([0, 1, 2]),
                                            jnp.asarray([1, 0]),
                                            jnp.asarray([1.0, 1.0]), y))
    # symmetric points: equal/opposite attraction, q = 1/(1+1) = 0.5
    np.testing.assert_allclose(f, [[-0.5, 0.0], [0.5, 0.0]], rtol=1e-6)
    d = op("knn_mindistance")(jnp.asarray([0.0, 0.0]),
                              jnp.asarray([1.0, 1.0]),
                              jnp.asarray([2.0, 0.5]))
    np.testing.assert_allclose(float(d), 1.0)
    assert bool(op("cell_contains")(jnp.asarray([0.0, 0.0]),
                                    jnp.asarray([2.0, 2.0]),
                                    jnp.asarray([0.5, -0.5])))


def test_round3b_multi_head_attention_op():
    B, T, F, H, dh = 2, 4, 8, 2, 4
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(B, T, F).astype(np.float32) * 0.3)
    wq = jnp.asarray(r.randn(H, dh, F).astype(np.float32) * 0.3)
    wk = jnp.asarray(r.randn(H, dh, F).astype(np.float32) * 0.3)
    wv = jnp.asarray(r.randn(H, dh, F).astype(np.float32) * 0.3)
    wo = jnp.asarray(r.randn(F, H, dh).astype(np.float32) * 0.3)
    out = op("multi_head_dot_product_attention")(q, q, q, wq, wk, wv, wo)
    assert out.shape == (B, T, F)
    # oracle: naive per-head attention
    from deeplearning4j_tpu.ops.attention_kernels import mha_reference
    qh = np.einsum("btf,hdf->bhtd", q, wq)
    kh = np.einsum("btf,hdf->bhtd", q, wk)
    vh = np.einsum("btf,hdf->bhtd", q, wv)
    ctx = mha_reference(jnp.asarray(qh), jnp.asarray(kh), jnp.asarray(vh))
    want = np.einsum("bhtd,ohd->bto", np.asarray(ctx), wo)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-5)


def test_round3c_bitmap_and_small_ops():
    g = jnp.asarray([2e-3, -5e-3, 1e-4, 0.0, 3e-3])
    packed, cnt = op("encode_bitmap")(g, 1e-3)
    assert int(cnt) == 3
    dec = np.asarray(op("decode_bitmap")(packed, 5, 1e-3))
    np.testing.assert_allclose(dec, [1e-3, -1e-3, 0.0, 0.0, 1e-3])
    # jit-compatible end to end
    f = jax.jit(lambda x: op("decode_bitmap")(
        op("encode_bitmap")(x, 1e-3)[0], 5, 1e-3))
    np.testing.assert_allclose(np.asarray(f(g)), dec)
    np.testing.assert_allclose(np.asarray(op("cube")(jnp.asarray([2.0]))),
                               [8.0])
    assert int(op("count_zero")(jnp.asarray([0., 1., 0.]))) == 2
    np.testing.assert_allclose(float(op("to_degrees")(jnp.asarray(np.pi))),
                               180.0, rtol=1e-6)
    np.testing.assert_allclose(float(op("to_radians")(jnp.asarray(180.0))),
                               np.pi, rtol=1e-6)
    assert op("size_at")(jnp.zeros((3, 7)), 1) == 7
    # cosine distance loss: identical vectors -> 0, opposite -> 2
    a = jnp.asarray([[1.0, 0.0]]); b = jnp.asarray([[-1.0, 0.0]])
    np.testing.assert_allclose(float(op("cosine_distance_loss")(a, a)), 0.0,
                               atol=1e-6)
    np.testing.assert_allclose(float(op("cosine_distance_loss")(a, b)), 2.0,
                               atol=1e-6)


def test_round3d_random_rnn_legacy_ops():
    import jax.random as jr
    key = jr.PRNGKey(0)
    rb = np.asarray(op("random_binomial")(key, (2000,), 10, 0.5))
    assert 4.0 < rb.mean() < 6.0 and rb.min() >= 0 and rb.max() <= 10
    rl = np.asarray(op("random_lognormal")(key, (2000,)))
    assert rl.min() > 0
    src = jnp.asarray([10.0, 20.0, 30.0])
    ch = np.asarray(op("random_choice")(key, src,
                                        jnp.asarray([0.0, 0.0, 1.0]), 50))
    np.testing.assert_allclose(ch, 30.0)
    np.testing.assert_allclose(
        np.asarray(op("reverse_mod")(jnp.asarray([3.0]),
                                     jnp.asarray([7.0]))), [1.0])
    np.testing.assert_allclose(
        np.asarray(op("axpy")(2.0, jnp.asarray([1.0, 2.0]),
                              jnp.asarray([10.0, 10.0]))), [12.0, 14.0])
    a = np.asarray([[4.0, 2.0], [2.0, 3.0]])
    ld = float(op("logdet")(jnp.asarray(a)))
    np.testing.assert_allclose(ld, np.log(np.linalg.det(a)), rtol=1e-6)
    out = op("assert_equal")(jnp.asarray([1.0]), jnp.asarray([1.0]))
    np.testing.assert_allclose(np.asarray(out), [1.0])
    with pytest.raises(ValueError):
        op("assert_equal")(jnp.asarray([1.0]), jnp.asarray([2.0]))


def test_round3d_dynamic_rnn_ops():
    r = np.random.RandomState(0)
    B, T, F, H = 2, 5, 3, 4
    x = jnp.asarray(r.randn(B, T, F).astype(np.float32) * 0.4)
    w = jnp.asarray(r.randn(F, H).astype(np.float32) * 0.4)
    rw = jnp.asarray(r.randn(H, H).astype(np.float32) * 0.4)
    b = jnp.asarray(r.randn(H).astype(np.float32) * 0.1)
    out, hT = op("dynamic_rnn")(x, w, rw, b)
    # oracle loop
    h = np.zeros((B, H), np.float32)
    outs = []
    for t in range(T):
        h = np.tanh(np.asarray(x)[:, t] @ np.asarray(w)
                    + h @ np.asarray(rw) + np.asarray(b))
        outs.append(h)
    np.testing.assert_allclose(np.asarray(out),
                               np.stack(outs, 1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(hT), outs[-1], rtol=1e-5)
    # seq_lengths freeze + zero past the end
    sl = jnp.asarray([3, 5])
    out2, h2 = op("dynamic_rnn")(x, w, rw, b, seq_lengths=sl)
    o2 = np.asarray(out2)
    assert np.all(o2[0, 3:] == 0)
    np.testing.assert_allclose(np.asarray(h2)[1], outs[-1][1], rtol=1e-5)
    # bidirectional: bwd equals fwd of the reversed input, re-flipped
    fwd, bwd, hf, hb = op("dynamic_bidirectional_rnn")(x, w, rw, b,
                                                       w, rw, b)
    ref_b, ref_hb = op("dynamic_rnn")(jnp.flip(x, 1), w, rw, b)
    np.testing.assert_allclose(np.asarray(bwd),
                               np.asarray(jnp.flip(ref_b, 1)), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(hb), np.asarray(ref_hb),
                               rtol=1e-5)


def test_round3e_tensor_list_family():
    lst = op("create_list")()
    assert int(op("size_list")(lst)) == 0
    a = jnp.asarray([[1.0, 2.0]]); b = jnp.asarray([[3.0, 4.0]])
    op("write_list")(lst, 0, a)
    op("write_list")(lst, 2, b)          # auto-grows, slot 1 empty
    op("write_list")(lst, 1, a * 10)
    assert int(op("size_list")(lst)) == 3
    np.testing.assert_allclose(np.asarray(op("read_list")(lst, 2)),
                               [[3.0, 4.0]])
    st = np.asarray(op("stack_list")(lst))
    assert st.shape == (3, 1, 2)
    g = np.asarray(op("gather_list")(lst, jnp.asarray([2, 0])))
    np.testing.assert_allclose(g[:, 0], [[3.0, 4.0], [1.0, 2.0]])
    p = np.asarray(op("pick_list")(lst, jnp.asarray([0, 2])))
    np.testing.assert_allclose(p, [[1.0, 2.0], [3.0, 4.0]])
    x = jnp.asarray(np.arange(12.0).reshape(6, 2))
    l2 = op("create_list")()
    op("split_list")(l2, x, [2, 4])
    with pytest.raises(ValueError):          # sizes must consume all rows
        op("split_list")(op("create_list")(), x, [2, 2])
    with pytest.raises(ValueError):          # unwritten slot is a named error
        op("read_list")(op("create_list")(size=2), 0)
    assert len(l2.arrays) == 2 and l2.arrays[1].shape == (4, 2)
    l3 = op("create_list")()
    op("unstack_list")(l3, x.reshape(3, 2, 2))
    assert int(op("size_list")(l3)) == 3
    l4 = op("scatter_list")(op("create_list")(), jnp.asarray([1, 0]),
                            x.reshape(2, 3, 2))
    np.testing.assert_allclose(np.asarray(l4.arrays[0]),
                               np.asarray(x.reshape(2, 3, 2)[1]))
    torn = op("tear")(x.reshape(2, 3, 2), axis=1)
    assert int(op("size_list")(torn)) == 3
    assert torn.arrays[0].shape == (2, 2)


def test_round3e_lstm_block_and_static_rnn():
    r = np.random.RandomState(1)
    B, T, F, H = 2, 4, 3, 5
    x = jnp.asarray(r.randn(B, T, F).astype(np.float32) * 0.4)
    w_ih = jnp.asarray(r.randn(F, 4 * H).astype(np.float32) * 0.3)
    w_hh = jnp.asarray(r.randn(H, 4 * H).astype(np.float32) * 0.3)
    seqs = op("lstm_block")(x, w_ih, w_hh)
    assert len(seqs) == 7
    assert all(s.shape == (B, T, H) for s in seqs)
    # h sequence matches lstm_cell scan (same IFCO math)
    ys, h, c = op("lstm_layer_full")(x, w_ih, w_hh)
    np.testing.assert_allclose(np.asarray(seqs[5]), np.asarray(ys),
                               rtol=1e-5)
    # static forms delegate to the dynamic impls
    w = jnp.asarray(r.randn(F, H).astype(np.float32) * 0.3)
    rw = jnp.asarray(r.randn(H, H).astype(np.float32) * 0.3)
    b = jnp.zeros(H, jnp.float32)
    o1, h1 = op("static_rnn")(x, w, rw, b)
    o2, h2 = op("dynamic_rnn")(x, w, rw, b)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2))
    # real_div / print_variable passthrough
    np.testing.assert_allclose(
        np.asarray(op("real_div")(jnp.asarray([6.0]), jnp.asarray([3.0]))),
        [2.0])
    out = op("print_variable")(jnp.asarray([1.0]), "v=")
    np.testing.assert_allclose(np.asarray(out), [1.0])


def test_round3f_select_and_word2vec_ops():
    np.testing.assert_allclose(
        np.asarray(op("select")(jnp.asarray([True, False]),
                                jnp.asarray([1.0, 1.0]),
                                jnp.asarray([2.0, 2.0]))), [1.0, 2.0])
    r = np.random.RandomState(0)
    V, D, B, N = 20, 8, 4, 3
    syn0 = jnp.asarray(r.randn(V, D).astype(np.float32) * 0.1)
    syn1 = jnp.asarray(np.zeros((V, D), np.float32))
    centers = jnp.asarray(r.randint(0, V, B))
    contexts = jnp.asarray(r.randint(0, V, B))
    negs = jnp.asarray(r.randint(0, V, (B, N)))
    s0, s1, l0 = op("skipgram")(syn0, syn1, centers, contexts, negs)
    losses = [float(l0)]
    for _ in range(30):
        s0, s1, l = op("skipgram")(s0, s1, centers, contexts, negs)
        losses.append(float(l))
    assert losses[-1] < losses[0]            # the update actually learns
    ctx = jnp.asarray(r.randint(0, V, (B, 4)))
    cm = jnp.asarray(np.ones((B, 4), np.float32))
    c0, c1, cl0 = op("cbow")(syn0, syn1, ctx, cm, centers, negs)
    for _ in range(30):
        c0, c1, cl = op("cbow")(c0, c1, ctx, cm, centers, negs)
    assert float(cl) < float(cl0)
