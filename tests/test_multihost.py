"""Multi-process-on-localhost distributed tests (VERDICT #2; SURVEY §4's
"multi-node without a cluster" obligation — the Aeron-on-loopback / Spark
local[*] analog).

`LocalLauncher` spawns real OS processes, each with its own XLA CPU client;
they form a global device mesh over the `jax.distributed` coordination
service (gloo collectives) and train the same SPMD step — the reference's
`dl4j-spark-parameterserver` SharedTraining story.
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.multihost import LocalLauncher, free_port

HERE = os.path.dirname(os.path.abspath(__file__))


def test_two_process_dp_training_matches_single_process(tmp_path):
    """2 processes x 2 CPU devices = one 4-device global DP mesh.  Both
    ranks must end bit-identical (SPMD sync), and match a single-process
    fit on the full batch (gradient-mean equivalence)."""
    steps = 5
    launcher = LocalLauncher(num_processes=2, devices_per_process=2)
    outs = launcher.run(os.path.join(HERE, "mh_worker_train.py"),
                        [str(tmp_path), str(steps)], timeout=420)
    assert any("devices=4" in o for o in outs), outs[0][-500:]

    p0 = np.load(tmp_path / "params_0.npz")["params"]
    p1 = np.load(tmp_path / "params_1.npz")["params"]
    np.testing.assert_array_equal(p0, p1)

    # single-process reference on the identical seeded net + full batch
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train import Sgd
    rng = np.random.default_rng(0)
    X = rng.standard_normal((16, 10)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[(X.sum(1) > 0).astype(int)]
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .list([DenseLayer(n_out=16, activation="tanh"),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(10)).build())
    net = MultiLayerNetwork(conf).init()
    for _ in range(steps):
        net.fit(X, Y)
    ref = np.asarray(net.params())
    np.testing.assert_allclose(p0, ref, rtol=1e-5, atol=1e-6)


def test_compressed_gradient_allreduce_over_tcp(tmp_path):
    """3 ranks exchange threshold-encoded gradients over the TCP star and
    each must hold the identical decoded sum (the codec's below-threshold
    residuals stay local, so the expected value is the sum of each rank's
    decode(encode(g)) — computed here with fresh codecs)."""
    world = 3
    port = free_port()
    launcher = LocalLauncher(num_processes=world)
    launcher.run(os.path.join(HERE, "mh_worker_grads.py"),
                 [str(port), str(tmp_path)], timeout=240)

    results = [dict(np.load(tmp_path / f"sum_{r}.npz"))
               for r in range(world)]
    for r in range(1, world):
        for k in results[0]:
            np.testing.assert_array_equal(results[0][k], results[r][k])

    from deeplearning4j_tpu.parallel.compression import (
        CompressedGradientExchange)
    template = {"w": np.zeros((64, 32), np.float32),
                "b": np.zeros(32, np.float32)}
    expected = None
    for r in range(world):
        ex = CompressedGradientExchange(template, threshold=0.05)
        rng = np.random.default_rng(100 + r)
        grads = {"w": rng.standard_normal((64, 32)).astype(np.float32) * 0.1,
                 "b": rng.standard_normal(32).astype(np.float32) * 0.1}
        dense = ex.decode(ex.encode(grads))
        expected = dense if expected is None else {
            k: expected[k] + dense[k] for k in expected}
    for k in expected:
        np.testing.assert_allclose(results[0][k], np.asarray(expected[k]),
                                   rtol=1e-6, atol=1e-7)


def test_elastic_restart_resumes_from_checkpoint(tmp_path):
    """Aux subsystem #3 (failure detection / elastic): rank 1 crashes
    mid-training on the first launch; the jax.distributed heartbeat kills
    the gang, ElasticLocalRunner relaunches, and the workers resume from
    the atomic checkpoint and finish."""
    from deeplearning4j_tpu.parallel.multihost import ElasticLocalRunner
    runner = ElasticLocalRunner(num_processes=2, devices_per_process=1,
                                max_restarts=2)
    outs = runner.run(os.path.join(HERE, "mh_worker_elastic.py"),
                      [str(tmp_path), "6", "3"], timeout=420)
    assert runner.restarts >= 1                      # a crash happened
    assert (tmp_path / "crashed_once").exists()
    assert any("resumed at iteration" in o for o in outs)
    final = np.load(tmp_path / "final.npz")
    assert int(final["iteration"]) == 6
    assert np.isfinite(final["params"]).all()


def test_two_process_sharded_inference_matches_single_process(tmp_path):
    """Multi-host ParallelInference (VERDICT r2 missing #7): 2 processes
    submit local request slices, forward runs SPMD over the global mesh,
    each rank gets exactly its own rows; concatenation matches a
    single-process forward."""
    launcher = LocalLauncher(num_processes=2, devices_per_process=2)
    outs = launcher.run(os.path.join(HERE, "mh_worker_infer.py"),
                        [str(tmp_path)], timeout=420)
    assert any("local_out=(6, 3)" in o for o in outs), outs[0][-500:]

    o0 = np.load(tmp_path / "infer_0.npz")["out"]
    o1 = np.load(tmp_path / "infer_1.npz")["out"]

    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    rng = np.random.default_rng(3)
    X = rng.standard_normal((12, 6)).astype(np.float32)
    conf = (NeuralNetConfiguration.builder().seed(11)
            .list([DenseLayer(n_out=8, activation="tanh"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    ref = np.asarray(net.output(X))
    np.testing.assert_allclose(np.concatenate([o0, o1]), ref, rtol=1e-5,
                               atol=1e-6)


def test_elastic_runner_failure_taxonomy_and_backoff():
    """Gang restarts now classify failures (crash/hang/peer-loss) and back
    off exponentially (VERDICT r2 weak #8)."""
    from deeplearning4j_tpu.parallel.multihost import ElasticLocalRunner
    r = ElasticLocalRunner(2, backoff_base_s=0.5, backoff_cap_s=4.0)
    assert r._classify_failure("rank 1 failed (rc=-9):\n<rank timed out>") \
        == "hang"
    assert r._classify_failure("fatal: peer task 0 died") == "peer-loss"
    assert r._classify_failure("rank 0 failed (rc=1):\nTraceback ...") \
        == "crash"
    # decorrelated jitter: seeded (deterministic, no wall clock), first
    # sleep is the base, every sleep stays in [base, cap], and two
    # runners with the same seed draw identical schedules while
    # different seeds decorrelate (no thundering herd)
    r = ElasticLocalRunner(2, backoff_base_s=0.5, backoff_cap_s=4.0,
                           jitter_seed=7)
    seq = [r.backoff_s(a) for a in (1, 2, 3, 4, 5)]
    assert seq[0] == 0.5
    assert all(0.5 <= v <= 4.0 for v in seq)
    twin = ElasticLocalRunner(2, backoff_base_s=0.5, backoff_cap_s=4.0,
                              jitter_seed=7)
    assert [twin.backoff_s(a) for a in (1, 2, 3, 4, 5)] == seq
    other = ElasticLocalRunner(2, backoff_base_s=0.5, backoff_cap_s=4.0,
                               jitter_seed=8)
    assert [other.backoff_s(a) for a in (1, 2, 3, 4, 5)] != seq
    # a doomed gang records a history entry per attempt
    import pytest as _pytest
    fail = ElasticLocalRunner(1, max_restarts=1, backoff_base_s=0.01)
    bad = os.path.join(HERE, "mh_worker_train.py")
    with _pytest.raises(RuntimeError, match="failure kinds"):
        # wrong args -> immediate crash in every attempt
        fail.run(bad, ["/nonexistent-dir/x", "not-an-int"], timeout=120)
    assert len(fail.failure_history) == 2
    assert all(k == "crash" for _, k, _ in fail.failure_history)


def test_two_process_composed_tp_pp_across_boundary(tmp_path):
    """The composed dp x tp x pp step runs with the tensor-parallel axis
    and then the pipeline axis SPANNING the 2-process boundary; its
    2-step loss trajectory must match the single-device oracle (VERDICT
    r4 #4: TP/PP over a real process boundary, not just in-process)."""
    launcher = LocalLauncher(num_processes=2, devices_per_process=4)
    outs = launcher.run(os.path.join(HERE, "mh_worker_composed.py"),
                        [str(tmp_path)], timeout=600)
    assert any("composed multihost done" in o for o in outs), \
        outs[0][-800:]

    r0 = np.load(tmp_path / "composed_0.npz")
    r1 = np.load(tmp_path / "composed_1.npz")
    # both ranks observed identical (replicated) losses
    np.testing.assert_allclose(r0["tp_cross"], r1["tp_cross"], rtol=1e-6)
    np.testing.assert_allclose(r0["pp_cross"], r1["pp_cross"], rtol=1e-6)
    # and both mesh layouts produced the same trajectory
    np.testing.assert_allclose(r0["tp_cross"], r0["pp_cross"], rtol=1e-4)

    # single-device oracle trajectory
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.parallel.composed import (composed_oracle,
                                                      init_stage_params)
    rng = np.random.RandomState(7)
    params = init_stage_params(rng, 2, 8, 2, 16)
    x = jnp.asarray(rng.randn(8, 8, 8).astype(np.float32) * 0.5)
    y = jnp.asarray(rng.randn(8, 8, 8).astype(np.float32) * 0.5)

    @jax.jit
    def oracle_step(p):
        def loss_fn(pp):
            return jnp.mean((composed_oracle(pp, x, 2) - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.2 * b, p, g), loss

    p = params
    want = []
    for _ in range(2):
        p, loss = oracle_step(p)
        want.append(float(loss))
    np.testing.assert_allclose(r0["tp_cross"], want, rtol=1e-4)


def test_two_process_fused_fit_steps_matches_per_step(tmp_path):
    """fit_steps_host_local: k DP steps in one dispatch per host across a
    REAL 2-process boundary — params must bit-match the per-step
    multi-process run (same data, same seeds)."""
    steps = 5
    a_dir = tmp_path / "per_step"
    b_dir = tmp_path / "fused"
    a_dir.mkdir(); b_dir.mkdir()
    launcher = LocalLauncher(num_processes=2, devices_per_process=2)
    launcher.run(os.path.join(HERE, "mh_worker_train.py"),
                 [str(a_dir), str(steps)], timeout=420)
    launcher.run(os.path.join(HERE, "mh_worker_train.py"),
                 [str(b_dir), str(steps), "fused"], timeout=420)
    pa = np.load(a_dir / "params_0.npz")["params"]
    pb = np.load(b_dir / "params_0.npz")["params"]
    np.testing.assert_array_equal(pa, pb)
    # and fused ranks agree with each other
    pb1 = np.load(b_dir / "params_1.npz")["params"]
    np.testing.assert_array_equal(pb, pb1)
