"""Serving runtime contract (ISSUE 1 acceptance): registry versioning,
bucketed AOT compile cache, continuous batching under real thread
concurrency, deadlines/admission control with typed errors, graceful
shutdown, metrics, and the deprecated DynamicBatchingInference shim."""
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import (BucketedCompileCache,
                                        ContinuousBatcher,
                                        DeadlineExceededError, ModelRegistry,
                                        ModelServer, RejectedError,
                                        bucket_for, bucket_sizes)
from deeplearning4j_tpu.train.updaters import Sgd


def _net(seed=0, n_in=8, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=n_out, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------

def test_bucket_ladder():
    assert bucket_sizes(32) == [1, 2, 4, 8, 16, 32]
    assert bucket_sizes(20) == [1, 2, 4, 8, 16, 32]   # top covers max_batch
    assert bucket_sizes(32, min_bucket=8) == [8, 16, 32]
    assert bucket_for(1, 32) == 1
    assert bucket_for(3, 32) == 4
    assert bucket_for(17, 32) == 32
    assert bucket_for(5, 32, min_bucket=8) == 8
    with pytest.raises(ValueError):
        bucket_for(0, 32)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_versioning_and_sources():
    reg = ModelRegistry()
    a = reg.register("m", _net(seed=1))
    b = reg.register("m", _net(seed=2))
    assert (a.version, b.version) == (1, 2)
    assert reg.get("m").version == 2            # newest wins
    assert reg.get("m", 1) is a
    assert reg.versions("m") == [1, 2]
    assert a.input_shape == (8,)                # inferred from InputType
    with pytest.raises(ValueError, match="already registered"):
        reg.register("m", _net(), version=2)
    with pytest.raises(KeyError, match="no model"):
        reg.get("missing")
    with pytest.raises(KeyError, match="versions"):
        reg.get("m", 9)
    z = reg.register_zoo("lenet", "LeNet")
    assert z.source == "zoo" and z.input_shape == (28, 28, 1)
    with pytest.raises(KeyError, match="unknown zoo model"):
        reg.register_zoo("x", "NoSuchModel")
    reg.unregister("m", 1)
    assert reg.versions("m") == [2]


# ---------------------------------------------------------------------------
# compile cache
# ---------------------------------------------------------------------------

def test_compile_cache_pads_transparently_and_counts():
    net = _net(seed=3)
    cache = BucketedCompileCache(max_batch=16)
    x = np.random.RandomState(0).randn(5, 8).astype(np.float32)
    got = cache.run("m:v1", net, x)             # 5 rows -> bucket 8
    np.testing.assert_array_equal(got, np.asarray(net.output(x)))
    assert cache.counters.misses.value == 1
    got2 = cache.run("m:v1", net, x[:7])        # same bucket -> hit
    np.testing.assert_array_equal(got2, np.asarray(net.output(x[:7])))
    assert cache.counters.misses.value == 1
    assert cache.counters.hits.value == 1
    cache.run("m:v1", net, x[:1])               # bucket 1 -> new compile
    assert cache.counters.misses.value == 2
    with pytest.raises(ValueError, match="max_batch"):
        cache.run("m:v1", net, np.zeros((17, 8), np.float32))
    cache.invalidate("m:v1")
    cache.run("m:v1", net, x)
    assert cache.counters.misses.value == 3


def test_compile_cache_warmup_covers_every_bucket():
    net = _net(seed=4)
    cache = BucketedCompileCache(max_batch=8)
    warmed = cache.warmup("m:v1", net, (8,))
    assert warmed == [1, 2, 4, 8] == cache.buckets
    assert cache.counters.misses.value == cache.num_buckets
    # traffic at any size <= max_batch never compiles again
    for n in range(1, 9):
        cache.run("m:v1", net, np.zeros((n, 8), np.float32))
    assert cache.counters.misses.value == cache.num_buckets


def test_compile_cache_sharded_mesh_matches_single_device():
    from deeplearning4j_tpu.parallel import make_mesh
    net = _net(seed=5)
    ref = np.asarray(net.output(
        np.random.RandomState(1).randn(11, 8).astype(np.float32)))
    mesh = make_mesh()
    cache = BucketedCompileCache(max_batch=32, mesh=mesh)
    assert cache.min_bucket == mesh.shape["data"]   # buckets divide the mesh
    x = np.random.RandomState(1).randn(11, 8).astype(np.float32)
    got = cache.run("m:v1", net, x)                  # 11 -> bucket 16, SPMD
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# batcher semantics (driven directly, no model)
# ---------------------------------------------------------------------------

def _echo_dispatch(group, xs):
    return [x * 2.0 for x in xs]


def test_batcher_queue_full_sheds_load():
    gate = threading.Event()

    def slow(group, xs):
        gate.wait(timeout=30)
        return xs

    b = ContinuousBatcher(slow, max_batch=1, batch_timeout_ms=0.0,
                          max_queue=2)
    futs = [b.submit(np.zeros((1, 4)))]          # dispatched, blocks worker
    time.sleep(0.1)
    futs += [b.submit(np.zeros((1, 4))) for _ in range(2)]   # fills queue
    with pytest.raises(RejectedError, match="queue full"):
        b.submit(np.zeros((1, 4)))
    assert b.metrics.rejected.value == 1
    gate.set()
    for f in futs:
        f.result(timeout=30)
    b.shutdown()
    with pytest.raises(RejectedError, match="shut down"):
        b.submit(np.zeros((1, 4)))


def test_batcher_deadline_expires_as_timeout_error():
    gate = threading.Event()

    def slow(group, xs):
        gate.wait(timeout=30)
        return xs

    b = ContinuousBatcher(slow, max_batch=1, batch_timeout_ms=0.0,
                          max_queue=16)
    first = b.submit(np.zeros((1, 4)))           # occupies the worker
    time.sleep(0.05)
    doomed = b.submit(np.zeros((1, 4)), deadline_ms=10.0)
    ok = b.submit(np.zeros((1, 4)))
    time.sleep(0.1)                              # deadline passes in queue
    gate.set()
    with pytest.raises(DeadlineExceededError):
        doomed.result(timeout=30)
    assert isinstance(doomed.exception(), TimeoutError)
    first.result(timeout=30)
    ok.result(timeout=30)
    assert b.metrics.expired.value == 1
    b.shutdown()


def test_batcher_priority_orders_dispatch():
    order = []
    gate = threading.Event()

    def record(group, xs):
        gate.wait(timeout=30)
        order.append(group[0])
        return xs

    b = ContinuousBatcher(record, max_batch=1, batch_timeout_ms=0.0,
                          max_queue=16)
    b.submit(np.zeros((1, 2)), group=("warm",))  # keeps worker busy
    time.sleep(0.05)
    lo = b.submit(np.zeros((1, 2)), group=("lo",), priority=0)
    hi = b.submit(np.zeros((1, 2)), group=("hi",), priority=5)
    gate.set()
    hi.result(timeout=30)
    lo.result(timeout=30)
    b.shutdown()
    assert order[1] == "hi"                      # after warm, hi beats lo


def test_batcher_groups_heterogeneous_shapes():
    seen = []

    def spy(group, xs):
        seen.append({x.shape[1:] for x in xs})
        return [x.sum(axis=tuple(range(1, x.ndim))) for x in xs]

    b = ContinuousBatcher(spy, max_batch=64, batch_timeout_ms=50.0,
                          max_queue=64)
    sub = lambda x: b.submit(x, group=("m", x.shape[1:]))  # noqa: E731
    futs = [sub(np.ones((2, 3))), sub(np.ones((1, 5))),
            sub(np.ones((3, 3))), sub(np.ones((2, 5)))]
    for f in futs:
        f.result(timeout=30)
    b.shutdown()
    for shapes in seen:
        assert len(shapes) == 1                  # never mixed in a dispatch


def test_batcher_dispatch_error_propagates_to_all_waiters():
    def boom(group, xs):
        raise RuntimeError("kaboom")

    b = ContinuousBatcher(boom, max_batch=8, batch_timeout_ms=20.0)
    futs = [b.submit(np.zeros((1, 2))) for _ in range(3)]
    for f in futs:
        with pytest.raises(RuntimeError, match="kaboom"):
            f.result(timeout=30)
    assert b.metrics.failed.value == 3
    b.shutdown()


def test_batcher_shutdown_drains_and_is_idempotent():
    b = ContinuousBatcher(_echo_dispatch, max_batch=4,
                          batch_timeout_ms=200.0, max_queue=64)
    futs = [b.submit(np.full((1, 2), i, np.float32)) for i in range(6)]
    b.shutdown()                                 # drain=True default
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(f.result(timeout=1),
                                      np.full((1, 2), 2.0 * i))
    b.shutdown()                                 # second call: no-op
    b.shutdown(drain=False)


# ---------------------------------------------------------------------------
# ModelServer end to end
# ---------------------------------------------------------------------------

def test_model_server_acceptance_64_concurrent_mixed_shapes():
    """ISSUE acceptance: 64 concurrent mixed-size requests all return
    bitwise-correct results with <= num_buckets compilations (compile-cache
    counters) and mean batch occupancy > 1 request/dispatch."""
    net = _net(seed=7)
    srv = ModelServer(max_batch=32, batch_timeout_ms=100.0, max_queue=256)
    srv.deploy("m", model=net)                   # cold cache: compiles are
    rng = np.random.RandomState(0)               # counted under traffic
    reqs = [rng.randn(1 + i % 4, 8).astype(np.float32) for i in range(64)]
    want = [np.asarray(net.output(r)) for r in reqs]

    with ThreadPoolExecutor(max_workers=16) as ex:
        futs = [ex.submit(srv.output, "m", r, timeout=120) for r in reqs]
        got = [f.result(timeout=120) for f in futs]
    stats = srv.stats()
    srv.shutdown()

    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)      # bitwise: padding is free
    assert stats["compile_cache"]["misses"] <= srv.cache.num_buckets, stats
    assert stats["batch_occupancy"] > 1.0, stats
    assert stats["completed"] == 64
    assert stats["rejected"] == 0 and stats["expired"] == 0


def test_model_server_mixed_trailing_dims_and_versions():
    """Different input widths (true heterogeneous shapes) and model
    versions serve concurrently — each group hits its own executable."""
    a, b = _net(seed=1, n_in=4), _net(seed=2, n_in=6)
    srv = ModelServer(max_batch=16, batch_timeout_ms=20.0)
    srv.deploy("m", model=a)                     # v1: 4-wide
    srv.deploy("m", model=b)                     # v2: 6-wide (newest)
    rng = np.random.RandomState(0)
    x4 = rng.randn(3, 4).astype(np.float32)
    x6 = rng.randn(2, 6).astype(np.float32)
    with ThreadPoolExecutor(max_workers=4) as ex:
        f1 = ex.submit(srv.output, "m", x4, 1)   # pinned to v1
        f2 = ex.submit(srv.output, "m", x6)      # newest
        np.testing.assert_array_equal(f1.result(timeout=60),
                                      np.asarray(a.output(x4)))
        np.testing.assert_array_equal(f2.result(timeout=60),
                                      np.asarray(b.output(x6)))
    srv.shutdown()


def test_model_server_typed_errors_fail_fast():
    srv = ModelServer(max_batch=8, batch_timeout_ms=5.0, max_queue=4)
    srv.deploy("m", model=_net())
    with pytest.raises(KeyError):
        srv.submit("nope", np.zeros((1, 8), np.float32))
    with pytest.raises(ValueError, match=">= 1 rows"):
        srv.submit("m", np.zeros((0, 8), np.float32))
    with pytest.raises(ValueError, match="max_batch"):
        srv.submit("m", np.zeros((9, 8), np.float32))
    fut = srv.submit("m", np.zeros((1, 8), np.float32), deadline_ms=0.0)
    with pytest.raises(TimeoutError):
        fut.result(timeout=30)
    srv.shutdown()
    with pytest.raises(RejectedError):
        srv.submit("m", np.zeros((1, 8), np.float32))
    srv.shutdown()                               # idempotent


def test_model_server_warmup_precompiles_all_buckets():
    srv = ModelServer(max_batch=16, batch_timeout_ms=1.0)
    srv.deploy("m", model=_net(), warmup=True)
    assert srv.metrics.cache.misses.value == srv.cache.num_buckets
    srv.output("m", np.zeros((5, 8), np.float32), timeout=60)
    assert srv.metrics.cache.misses.value == srv.cache.num_buckets  # no new
    assert srv.metrics.cache.hits.value >= 1
    srv.shutdown()


def test_model_server_stats_and_ui_endpoint():
    import json
    import urllib.request
    from deeplearning4j_tpu.ui.server import UIServer

    srv = ModelServer(max_batch=8, batch_timeout_ms=1.0)
    srv.deploy("m", model=_net(), warmup=True)
    srv.output("m", np.zeros((2, 8), np.float32), timeout=60)
    s = srv.stats()
    assert s["completed"] == 1 and s["models"] == {"m": [1]}
    assert {"p50", "p95", "p99"} <= set(s["latency_ms"])

    ui = UIServer()                              # fresh instance, not the
    ui.attach_serving(srv)                       # process-global singleton
    port = ui.start(0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/serving", timeout=10) as r:
            scraped = json.loads(r.read())
        assert scraped[0]["completed"] == 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10) as r:
            page = r.read().decode()
        assert "Serving" in page and "batch occupancy" in page
    finally:
        ui.stop()
        srv.shutdown()


# ---------------------------------------------------------------------------
# satellites: ParallelInference fixes + deprecated shim
# ---------------------------------------------------------------------------

def test_parallel_inference_heterogeneous_shapes_raise():
    from deeplearning4j_tpu.parallel import ParallelInference
    pi = ParallelInference(_net(n_in=8))
    with pytest.raises(ValueError, match="heterogeneous request shapes"):
        pi.output([np.zeros((2, 8), np.float32),
                   np.zeros((2, 5), np.float32)])
    assert pi.output([]) == []


def test_parallel_inference_zero_row_input():
    from deeplearning4j_tpu.parallel import ParallelInference
    pi = ParallelInference(_net(n_in=8))
    out = pi.output(np.zeros((0, 8), np.float32))
    assert out.shape == (0, 3)


def test_dynamic_batching_shim_deprecated_idempotent_mixed_shapes():
    from deeplearning4j_tpu.parallel import (DynamicBatchingInference,
                                             ParallelInference)
    net = _net(seed=9)
    pi = ParallelInference(net)
    with pytest.warns(DeprecationWarning, match="serving.ModelServer"):
        dyn = DynamicBatchingInference(pi, max_batch=16, timeout_ms=50.0)
    # mixed trailing dims used to crash the concatenate; now they group
    seq = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    seq2 = np.random.RandomState(1).randn(3, 8).astype(np.float32)
    f1, f2 = dyn.submit(seq), dyn.submit(seq2)
    np.testing.assert_allclose(f1.result(timeout=60),
                               np.asarray(net.output(seq)),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(f2.result(timeout=60),
                               np.asarray(net.output(seq2)),
                               rtol=1e-6, atol=1e-7)
    dyn.shutdown()
    dyn.shutdown()                               # idempotent now
    with pytest.raises(RuntimeError):
        dyn.submit(seq)


# ---------------------------------------------------------------------------
# soak (excluded from tier-1)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_model_server_soak_sustained_mixed_traffic():
    """Sustained closed-loop traffic: no leaks of queue depth, every
    request accounted, occupancy stays > 1 and compiles stay bounded."""
    srv = ModelServer(max_batch=32, batch_timeout_ms=2.0, max_queue=1024)
    srv.deploy("m", model=_net(seed=11), warmup=True)

    def client(i):
        rs = np.random.RandomState(i)
        n_done = 0
        end = time.monotonic() + 3.0
        while time.monotonic() < end:
            x = rs.rand(1 + n_done % 4, 8).astype(np.float32)
            y = srv.output("m", x, deadline_ms=5000.0, timeout=60)
            assert y.shape == (x.shape[0], 3)
            n_done += 1
        return n_done

    with ThreadPoolExecutor(max_workers=12) as ex:
        done = sum(ex.map(client, range(12)))
    s = srv.stats()
    srv.shutdown()
    assert done > 50
    assert s["completed"] == s["submitted"] == done
    assert s["expired"] == 0 and s["failed"] == 0
    assert s["queue_depth"] == 0
    assert s["batch_occupancy"] > 1.0
    assert s["compile_cache"]["misses"] == srv.cache.num_buckets
