"""Multi-host SPMD training worker (spawned by test_multihost via
LocalLauncher — NOT a pytest file).

Each process joins the jax.distributed cluster, contributes its local slice
of a deterministic global batch, and trains the same seeded MLN through
ParallelWrapper.fit_host_local over the global mesh.  Final params are
written per-rank for the driver test to compare (across ranks, and against
a single-process reference)."""
import os
import sys

import numpy as np

from deeplearning4j_tpu.parallel import multihost

multihost.initialize()

from deeplearning4j_tpu.nn import (DenseLayer, InputType,  # noqa: E402
                                   MultiLayerNetwork, NeuralNetConfiguration,
                                   OutputLayer)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper  # noqa: E402
from deeplearning4j_tpu.train import Sgd  # noqa: E402

out_dir = sys.argv[1]
steps = int(sys.argv[2])
rank = multihost.process_index()
world = multihost.process_count()
mesh = multihost.global_mesh()

rng = np.random.default_rng(0)
X = rng.standard_normal((16, 10)).astype(np.float32)
Y = np.eye(2, dtype=np.float32)[(X.sum(1) > 0).astype(int)]
per = X.shape[0] // world
xl = X[rank * per:(rank + 1) * per]
yl = Y[rank * per:(rank + 1) * per]

conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
        .list([DenseLayer(n_out=16, activation="tanh"),
               OutputLayer(n_out=2, loss="mcxent", activation="softmax")])
        .set_input_type(InputType.feed_forward(10)).build())
net = MultiLayerNetwork(conf).init()
pw = ParallelWrapper(net, mesh)
fused = len(sys.argv) > 3 and sys.argv[3] == "fused"
if fused:
    # same local data every step, stacked on a leading steps axis: k
    # steps in ONE dispatch per host (scan + psum inside the executable)
    xs = np.broadcast_to(xl, (steps,) + xl.shape).copy()
    ys = np.broadcast_to(yl, (steps,) + yl.shape).copy()
    pw.fit_steps_host_local(xs, ys)
else:
    for _ in range(steps):
        pw.fit_host_local(xl, yl)

params = np.asarray(net.params())
np.savez(os.path.join(out_dir, f"params_{rank}.npz"), params=params,
         score=np.float64(net.score()))
print(f"rank {rank}/{world}: devices={len(mesh.devices.flat)} "
      f"score={net.score():.6f}", flush=True)
