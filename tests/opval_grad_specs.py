"""Gradient-coverage specs for the OpValidation sweep.

Reference: `OpValidation.java` validates the analytic gradient of every
differentiable op (`TestCase.gradientCheck(true)` is the default there);
non-differentiable ops are explicitly excluded.  This module partitions
the registry the same way:

- ``AUGMENT``: op -> (tensor-arg indices, FD coordinate sample cap).
  Each listed op's first spec case gains a finite-difference gradient
  check on those args (cap 0 = every coordinate; a positive cap samples
  that many seeded coordinates per arg — the reference's
  `gradCheckMaxPerParam` — and `OPVAL_FULL=1` removes the cap).
- ``NONDIFF``: op -> reason it is excluded from gradient checking.

The gate in test_op_validation.py asserts these two sets plus the
grad-annotated spec cases exactly cover the registry, and that neither
list is stale.
"""

# op -> (grad arg indices, sample cap, gtol override or None)
AUGMENT = {}


def _aug(ops, grad=(0,), sample=0, gtol=None):
    for op in ops:
        AUGMENT[op] = (tuple(grad), sample, gtol)


# ---- reductions / statistics (first-case inputs ~60 elems) ----
_aug(["max", "min", "std", "norm1", "norm_max", "amax", "amin", "asum",
      "amean", "moments", "sufficient_statistics"], sample=12)
_aug(["norm_p", "log_entropy", "shannon_entropy", "median", "nth_element",
      "cummax", "cummin", "cumsum_ext", "sort", "top_k"])
_aug(["percentile"], sample=12)
_aug(["normalize_moments"], grad=(1, 2))

# ---- clipping (kinks are at measure-zero points of the fixed seed) ----
_aug(["clip", "clip_by_value", "clip_by_norm", "clip_by_avg_norm"])
_aug(["clip_by_global_norm"], grad=(1, 2))

# ---- selection by predicate ----
_aug(["where", "select"], grad=(1, 2))
# (divide_no_nan and svd carry dedicated grad cases in opval_specs_core:
# the default divide_no_nan case deliberately contains b=0 jump points,
# and jax only defines the SVD JVP for full_matrices=False)

# ---- shape/data movement (linear maps; catches index arithmetic) ----
_aug(["transpose", "permute", "reshape", "reshape_onnx", "flatten2d",
      "expand_dims", "squeeze", "unstack_at", "unstack", "tile", "slice",
      "slice_onnx", "strided_slice", "tf_strided_slice", "pad",
      "pad_mode", "mirror_pad", "broadcast_to", "repeat", "flip",
      "reverse", "roll", "swap_axes", "swap_last2", "moveaxis",
      "atleast_2d", "ravel", "split_axis", "split_equal",
      "reverse_sequence", "gather_nd", "take_along_axis"])
_aug(["concat", "stack", "meshgrid"], grad=(0, 1))

# ---- scatter / segment ----
_aug(["scatter_sub", "scatter_update", "scatter_max", "scatter_min",
      "scatter_nd_add", "scatter_nd_sub",
      "scatter_nd_update", "scatter_nd_max", "scatter_nd_min"],
     grad=(0, 2))
_aug(["scatter_nd"], grad=(1,))
_aug(["sparse_to_dense"], grad=(2,))
_aug(["segment_max", "segment_min", "segment_mean",
      "unsorted_segment_sum", "unsorted_segment_max",
      "unsorted_segment_min",
      "unsorted_segment_mean", "unsorted_segment_sqrt_n"])
_aug(["mergeavg"], grad=(0, 1, 2))

# ---- linear algebra ----
_aug(["cholesky", "matrix_inverse", "log_matrix_determinant", "slogdet",
      "logdet", "pinv", "expm", "matrix_band_part", "diag", "diag_part",
      "tril", "triu", "matrix_diag", "matrix_diag_part", "lu"],
     gtol=2e-2)
_aug(["qr", "eig_sym"], gtol=5e-2)
_aug(["triangular_solve", "cholesky_solve", "lu_solve", "lstsq"],
     grad=(0, 1), gtol=2e-2)
_aug(["matrix_set_diag", "kron"], grad=(0, 1))

# ---- distances / losses ----
_aug(["manhattan_distance", "cosine_distance_loss", "jaccard_distance",
      "weighted_cross_entropy_with_logits", "absolute_difference",
      "huber_loss", "log_loss", "poisson_loss", "log_poisson_loss",
      "mean_pairwise_squared_error"], grad=(0, 1))
_aug(["hinge_loss", "knn_mindistance"])

# ---- special functions (grads defined wrt the x argument only) ----
_aug(["betainc"], grad=(2,))
_aug(["igamma", "igammac", "polygamma"], grad=(1,))
_aug(["lbeta", "zeta"])

# ---- activations (inputs seeded away from the measure-zero kinks) ----
_aug(["relu6", "celu", "gelu_tanh", "hard_sigmoid", "hard_swish",
      "hard_tanh", "rational_tanh", "rectified_tanh", "thresholded_relu",
      "prelu", "glu", "standardize"])

# ---- normalization ----
_aug(["batch_norm", "batch_norm_nchw"], grad=(0, 1, 2, 3, 4), sample=8)
_aug(["fused_batch_norm"], grad=(0, 1, 2), sample=8)
_aug(["lrn"], sample=8)

# ---- convolution family (sampled: first-case inputs are realistic) ----
_aug(["conv1d", "deconv2d", "deconv3d", "depthwise_conv2d",
      "pointwise_conv2d", "dilation2d"], grad=(0, 1), sample=10)
_aug(["conv3d", "separable_conv2d", "conv2d_nchw", "deconv2d_nchw"],
     grad=(0, 1, 2), sample=10)
_aug(["max_pooling1d", "max_pooling2d", "max_pooling3d", "avg_pooling1d",
      "avg_pooling2d", "avg_pooling3d", "pnorm_pool2d",
      "global_avg_pool_nchw", "max_pool2d_nchw", "avg_pool2d_nchw",
      "max_pool_with_argmax", "upsampling2d", "upsampling3d",
      "extract_image_patches", "im2col"], sample=10)

# ---- attention / recurrent (weights + inputs, sampled) ----
_aug(["multi_head_dot_product_attention"], grad=(0, 3, 6), sample=8)
_aug(["lstm_cell", "lstm_block_cell"], grad=(0, 3, 4, 5), sample=8)
_aug(["gru_cell", "gru_layer"], grad=(0, 2, 3, 4, 5), sample=8)
_aug(["lstm_layer", "lstm_layer_full", "lstm_block", "dynamic_rnn",
      "static_rnn"], grad=(0, 1, 2, 3), sample=8)
_aug(["dynamic_bidirectional_rnn", "static_bidirectional_rnn"],
     grad=(0, 1, 2, 4, 5), sample=6)
_aug(["sru_cell", "sru_layer"], grad=(0, 2, 3), sample=8)

# ---- image ops (linear or piecewise-linear resamplers) ----
_aug(["rgb_to_grs", "rgb_to_yuv", "yuv_to_rgb", "yiq_to_rgb",
      "adjust_contrast_v2", "per_image_standardization",
      "image_central_crop", "image_flip_left_right", "image_flip_up_down",
      "image_rot90", "space_to_depth", "depth_to_space", "space_to_batch",
      "batch_to_space", "space_to_batch_nd", "batch_to_space_nd",
      "crop_and_resize", "resize_bilinear", "resize_bicubic",
      "resize_lanczos", "image_resize"], sample=8)


# ---------------------------------------------------------------------------
# Non-differentiable ops, each with the reason (reference OpValidation's
# explicit exclusion list role).
# ---------------------------------------------------------------------------
NONDIFF = {}


def _nd(ops, reason):
    for op in ops:
        NONDIFF[op] = reason


_nd(["sign", "floor", "ceil", "round", "rint", "trunc", "zero_fraction",
     "relu_derivative"],
    "piecewise-constant output: gradient is zero a.e., FD checks nothing")
_nd(["zeros_rows_like"],
    "constant-zero output regardless of input: gradient identically zero")
_nd(["mod", "fmod", "remainder", "reverse_mod", "truncate_div",
     "floor_div"],
    "discontinuous at quotient boundaries; central FD straddles jumps")
_nd(["less", "less_equal", "greater", "greater_equal", "equal",
     "not_equal", "eq", "neq", "gt", "gte", "lt", "lte", "logical_and",
     "logical_or", "logical_not", "isclose", "equals_with_eps", "isnan",
     "isinf", "is_finite", "is_finite_all", "is_non_decreasing",
     "is_strictly_increasing", "is_numeric_tensor", "reduce_any",
     "reduce_all", "in_top_k", "is_max", "isin", "cell_contains"],
    "boolean-valued output")
_nd(["argmax", "argmin", "argsort", "bincount", "histogram",
     "histogram_fixed_width", "count_nonzero", "count_zero",
     "confusion_matrix", "matrix_rank", "nonzero", "searchsorted",
     "bucketize", "invert_permutation", "unravel_index", "shape_of",
     "size_of", "rank_of", "size_at", "one_hot", "sequence_mask",
     "hamming_distance", "bits_hamming_distance", "population_count",
     "mergemaxindex", "hashcode", "broadcast_dynamic_shape",
     "broadcast_gradient_args"],
    "integer-valued output / integer index inputs")
_nd(["unique", "unique_with_counts", "setdiff1d"],
    "data-dependent output shape (host-side op)")
_nd(["bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
     "toggle_bits", "shift_left", "shift_right", "cyclic_shift_left",
     "cyclic_shift_right", "bitcast", "compare_and_bitpack"],
    "bit-level integer op")
_nd(["cast"], "dtype conversion; identity-gradient covered by autodiff")
_nd(["zeros_like", "ones_like", "fill_like", "eye_like", "eye",
     "linspace", "arange", "full", "tri"],
    "constant generator: output independent of input values")
_nd(["random_uniform", "random_normal", "random_bernoulli",
     "random_exponential", "random_gamma", "random_poisson",
     "random_lognormal", "random_binomial", "truncated_normal",
     "random_randint", "random_shuffle", "multinomial", "random_choice",
     "random_crop", "rng_fold", "rng_fold_opt", "dropout",
     "dropout_inverted", "alpha_dropout"],
    "stochastic sampling op")
_nd(["create_list", "write_list", "read_list", "size_list", "stack_list",
     "unstack_list", "gather_list", "scatter_list", "split_list",
     "pick_list", "tear", "tuple_get", "assign", "compare_and_set",
     "choose", "print_variable", "assert_equal"],
    "stateful/list/control helper, not a differentiable tensor function")
_nd(["stop_gradient"],
    "gradient is intentionally NOT the mathematical derivative")
_nd(["fake_quant_with_min_max_args", "fake_quant_with_min_max_vars"],
    "straight-through estimator: analytic grad deliberately differs "
    "from FD of the quantized forward")
_nd(["encode_threshold", "decode_threshold", "encode_bitmap",
     "decode_bitmap"],
    "gradient-compression codec (int bitstreams)")
_nd(["fft", "ifft", "fft2", "ifft2", "rfft", "irfft", "eig"],
    "complex-valued input/output outside the real-valued FD harness")
_nd(["sgd_updater", "nesterovs_updater", "adam_updater",
     "rms_prop_updater", "ada_grad_updater", "ada_delta_updater",
     "ada_max_updater", "nadam_updater", "ams_grad_updater"],
    "optimizer state-update rule; the reference does not graph-"
    "differentiate updaters either")
_nd(["skipgram", "cbow", "barnes_gains", "barnes_symmetrize",
     "barnes_edge_forces"],
    "embedding-training / t-SNE helper with integer index inputs")
_nd(["ctc_greedy_decode", "ctc_beam_decode", "non_max_suppression",
     "non_max_suppression_overlaps", "draw_bounding_boxes"],
    "discrete decoding / box-selection algorithm")
_nd(["rgb_to_hsv", "hsv_to_rgb", "adjust_hue", "adjust_saturation"],
    "hue-channel selection is piecewise with FD-hostile sector "
    "boundaries (max/argmax over channels)")
_nd(["resize_nearest"], "nearest-neighbour resampling is piecewise-"
    "constant in the input coordinates it drops")
_nd(["dynamic_partition", "dynamic_stitch"],
    "list-typed inputs/outputs outside the positional-arg FD harness; "
    "linearity covered by the partition/stitch round-trip custom case")
_nd(["col2im"],
    "tuple-input custom-validated op; it is the adjoint of im2col, "
    "which is gradient-checked")
_nd(["scatter_mul", "scatter_div", "segment_prod",
     "unsorted_segment_prod"],
    "jax defines no differentiation rule for multiplicative "
    "scatter/segment reductions (NotImplementedError in the JVP)")
