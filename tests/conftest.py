"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's "multi-node without a cluster" strategy (SURVEY.md
§4: Aeron-on-loopback / Spark local[*]) — sharding/collective tests execute
on `xla_force_host_platform_device_count=8` CPU devices; real-TPU paths are
exercised by bench.py / the driver.
"""
import os

# Force CPU: the session env pins JAX_PLATFORMS=axon (real TPU) which has no
# float64 and a slow remote compile path; tests run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")  # gradient checks need f64

import jax  # noqa: E402

# jax may already be imported by a pytest plugin before this conftest runs,
# in which case the env var alone is too late — set the config directly.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
