"""SameDiffLayer escape hatch + CapsNet (reference
`nn/conf/layers/samediff/**` and `PrimaryCapsules`/`CapsuleLayer`/
`CapsuleStrengthLayer`)."""
import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import (CapsuleLayer, CapsuleStrengthLayer,
                                   InputType, LambdaLayer, LossLayer,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer,
                                   PrimaryCapsules, SameDiffLayer)
from deeplearning4j_tpu.nn import register_layer
from deeplearning4j_tpu.train.updaters import Adam


@register_layer
@dataclasses.dataclass(kw_only=True)
class _GatedDense(SameDiffLayer):
    """Custom layer via the escape hatch: out = (xW) * sigmoid(xG)."""

    n_out: int = 0

    def define_parameters(self, input_type):
        f = input_type.shape[-1]
        return {"W": (f, self.n_out), "G": (f, self.n_out),
                "b": ((self.n_out,), "ZERO")}

    def define_layer(self, params, x, mask=None):
        import jax
        return (x @ params["W"] + params["b"]) * jax.nn.sigmoid(
            x @ params["G"])

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.n_out)


def test_samediff_layer_trains_and_serializes():
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .list([_GatedDense(n_out=16),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(5)).build())
    net = MultiLayerNetwork(conf).init()
    assert set(net.params_["layer_0"]) == {"W", "G", "b"}
    rng = np.random.RandomState(0)
    x = rng.randn(32, 5).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
    s0 = net.score_for(x, y)
    for _ in range(30):
        net.fit(x, y)
    assert net.score() < s0
    # registered subclasses JSON-round-trip like built-ins
    js = conf.to_json()
    from deeplearning4j_tpu.nn import MultiLayerConfiguration
    conf2 = MultiLayerConfiguration.from_json(js)
    assert isinstance(conf2.layers[0], _GatedDense)
    assert conf2.layers[0].n_out == 16


def test_lambda_layer_inline_and_serialization_contract():
    conf = (NeuralNetConfiguration.builder().seed(0)
            .list([LambdaLayer(fn=lambda x: x * 2.0),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    out = net.output(np.ones((1, 4), np.float32))
    assert out.shape == (1, 2)
    with pytest.raises(ValueError, match="cannot be serialized"):
        conf.to_json()


def test_capsnet_shapes_and_training():
    """PrimaryCapsules -> CapsuleLayer (routing) -> strength head learns a
    tiny 3-class image problem (the reference CapsNet sample topology)."""
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(3e-3))
            .list([PrimaryCapsules(capsules=4, capsule_dim=4,
                                   kernel_size=5, stride=2),
                   CapsuleLayer(capsules=3, capsule_dim=8, routings=3),
                   CapsuleStrengthLayer(),
                   LossLayer(loss="mcxent", activation="softmax")])
            .set_input_type(InputType.convolutional(12, 12, 1)).build())
    net = MultiLayerNetwork(conf).init()

    rng = np.random.RandomState(0)
    n = 48
    labels = rng.randint(0, 3, n)
    x = np.zeros((n, 12, 12, 1), np.float32)
    for i, c in enumerate(labels):          # class = bright quadrant
        r, col = divmod(c, 2)
        x[i, r * 6:(r + 1) * 6, col * 6:(col + 1) * 6] = 1.0
    x += rng.rand(n, 12, 12, 1).astype(np.float32) * 0.1
    y = np.eye(3, dtype=np.float32)[labels]

    # shape walk (feed_forward returns [input, layer0, ...]): primary caps
    # [B, N, D] -> caps [B, 3, 8] -> strength [B, 3]
    acts = net.feed_forward(x[:2])
    assert acts[2].shape == (2, 3, 8)
    assert acts[3].shape == (2, 3)

    s0 = net.score_for(x, y)
    for _ in range(60):
        net.fit(x, y)
    assert net.score() < s0 * 0.7, (s0, net.score())
    pred = np.asarray(net.output(x)).argmax(1)
    assert (pred == labels).mean() > 0.7
