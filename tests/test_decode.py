"""Decode engine: paged-KV allocator, paged-attention conformance,
bucketed prefill, token-level continuous batching, fleet failover.

Layers under test, bottom up: `KVBlockAllocator` (free-list invariants,
all-or-nothing exhaustion), `PagedKVCache` (page writes, block tables,
int8 page parity), the `paged_attention` kernel pair (Pallas-in-interpret
== jnp reference — the PR 13 two-implementation contract), the
`DecodeEngine` loop (zero fresh compiles after warmup, mid-flight
admit/retire, exhaustion sheds), the `ContinuousBatcher.cancel` slot
release, and `ModelFleet.deploy_decode`/`generate` failover
(restart-and-count, heal via the controller)."""
import time
from concurrent.futures import Future

import numpy as np
import pytest

from deeplearning4j_tpu.compile.fingerprint import model_fingerprint
from deeplearning4j_tpu.ops.pallas import dispatch as kd
from deeplearning4j_tpu.ops.pallas import paged_attention as pa
from deeplearning4j_tpu.serving.batcher import (ContinuousBatcher,
                                                RejectedError)
from deeplearning4j_tpu.serving.decode import (DecodeEngine,
                                               KVBlockAllocator,
                                               KVCacheExhausted,
                                               PagedKVCache,
                                               TinyDecodeModel)


@pytest.fixture(autouse=True)
def _reset_kernel_tier():
    yield
    kd.reset()


def _random_paged(B=3, H=2, D=64, page=8, n_pages=16, max_pages=4,
                  dtype="f32", seed=0):
    """Random paged-attention inputs with ragged per-sequence lengths."""
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((B, H, D)).astype(np.float32)
    k = rng.standard_normal((n_pages, page, H, D)).astype(np.float32)
    v = rng.standard_normal((n_pages, page, H, D)).astype(np.float32)
    # ragged: lengths 1, mid, full
    seq_lens = np.array([1, page * max_pages // 2 + 3,
                         page * max_pages][:B], np.int32)
    bt = np.zeros((B, max_pages), np.int32)
    used = iter(rng.permutation(n_pages))      # distinct physical pages
    for b in range(B):
        n = -(-int(seq_lens[b]) // page)
        bt[b, :n] = [next(used) for _ in range(n)]
    if dtype == "int8":
        from deeplearning4j_tpu.ops.quant_kernels import quantize_tensor
        ks = np.ones((n_pages, page, H), np.float32)
        vs = np.ones((n_pages, page, H), np.float32)
        k8 = np.zeros((n_pages, page, H, D), np.int8)
        v8 = np.zeros((n_pages, page, H, D), np.int8)
        for p in range(n_pages):
            for s in range(page):
                qt = quantize_tensor(k[p, s], axis=0)
                k8[p, s] = np.asarray(qt.q)
                ks[p, s] = np.asarray(qt.scale).reshape(-1)
                qt = quantize_tensor(v[p, s], axis=0)
                v8[p, s] = np.asarray(qt.q)
                vs[p, s] = np.asarray(qt.scale).reshape(-1)
        return q, k8, v8, bt, seq_lens, ks, vs, k, v
    return q, k, v, bt, seq_lens, None, None, k, v


def _tiny(seed=0):
    return TinyDecodeModel(vocab=48, d_model=32, n_heads=2, seed=seed)


def _engine(model=None, **kw):
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("max_decode_batch", 4)
    kw.setdefault("model_label", "t")
    return DecodeEngine(model if model is not None else _tiny(), **kw)


# ---------------------------------------------------------------------------
# Allocator
# ---------------------------------------------------------------------------

class TestKVBlockAllocator:
    def test_alloc_free_roundtrip(self):
        a = KVBlockAllocator(8)
        blocks = a.alloc(5)
        assert len(blocks) == len(set(blocks)) == 5
        assert a.in_use == 5 and a.free_count == 3
        a.free(blocks[:2])
        assert a.in_use == 3 and a.free_count == 5
        assert a.high_water == 5

    def test_exhaustion_is_all_or_nothing(self):
        a = KVBlockAllocator(4)
        a.alloc(3)
        with pytest.raises(KVCacheExhausted):
            a.alloc(2)                    # only 1 free: nothing taken
        assert a.free_count == 1          # the failed alloc left it intact
        assert len(a.alloc(1)) == 1

    def test_exhaustion_is_rejected_error(self):
        # shed-not-crash: admission control catches RejectedError
        assert issubclass(KVCacheExhausted, RejectedError)

    def test_double_free_raises(self):
        a = KVBlockAllocator(4)
        b = a.alloc(2)
        a.free(b)
        with pytest.raises(ValueError, match="double free"):
            a.free([b[0]])

    def test_fragmented_free_order_reuses_any_page(self):
        # free pages out of order, then alloc everything back: position
        # independence means fragmentation cannot strand capacity
        a = KVBlockAllocator(6)
        blocks = a.alloc(6)
        a.free([blocks[1], blocks[4], blocks[2]])
        got = a.alloc(3)
        assert set(got) == {blocks[1], blocks[4], blocks[2]}
        assert a.in_use == 6


# ---------------------------------------------------------------------------
# Paged KV cache
# ---------------------------------------------------------------------------

class TestPagedKVCache:
    def test_write_append_and_block_tables(self):
        c = PagedKVCache(num_blocks=8, page_size=4, n_heads=2, head_dim=8)
        c.allocate(7)
        kv = np.random.default_rng(0).standard_normal((6, 2, 8))
        c.write(7, kv, kv)
        assert c.seq_len(7) == 6
        assert c.blocks_in_use == 2      # ceil(6/4)
        c.write(7, kv[:2], kv[:2])       # fills page 2 exactly
        assert c.seq_len(7) == 8 and c.blocks_in_use == 2
        c.write(7, kv[:1], kv[:1])       # spills into a third page
        assert c.blocks_in_use == 3
        bt, sl = c.block_tables([7], rows=2, max_pages=4)
        assert bt.shape == (2, 4) and sl.tolist() == [9, 1]
        assert (bt[1] == 0).all()        # padding row: page 0, len 1
        c.free_seq(7)
        assert c.blocks_in_use == 0

    def test_atomic_write_on_exhaustion(self):
        c = PagedKVCache(num_blocks=2, page_size=4, n_heads=2, head_dim=8)
        c.allocate(1)
        kv = np.zeros((12, 2, 8), np.float32)     # needs 3 pages, have 2
        with pytest.raises(KVCacheExhausted):
            c.write(1, kv, kv)
        assert c.seq_len(1) == 0                  # untouched
        c.write(1, kv[:8], kv[:8])                # exactly 2 pages fits
        assert c.seq_len(1) == 8

    def test_int8_pages_store_scales_and_roundtrip(self):
        c = PagedKVCache(num_blocks=4, page_size=4, n_heads=2, head_dim=8,
                         dtype="int8")
        rng = np.random.default_rng(1)
        kv = rng.standard_normal((4, 2, 8)).astype(np.float32) * 3.0
        c.allocate(0)
        c.write(0, kv, kv)
        k8, v8, ks, vs = c.pages()
        deq = k8[c._seqs[0].blocks[0]].astype(np.float32) \
            * ks[c._seqs[0].blocks[0]][..., None]
        err = np.abs(deq - kv).max() / np.abs(kv).max()
        assert err < 0.01
        # int8 bytes: 2*page*H*D int8 + 2*page*H f32 scales, per block
        assert c.bytes_per_block == 2 * 4 * 2 * 8 + 2 * 4 * 2 * 4

    def test_int8_block_costs_under_quarter_of_f32(self):
        f32 = PagedKVCache(4, page_size=16, n_heads=4, head_dim=64)
        i8 = PagedKVCache(4, page_size=16, n_heads=4, head_dim=64,
                          dtype="int8")
        assert i8.bytes_per_block < f32.bytes_per_block / 3.5


# ---------------------------------------------------------------------------
# Kernel conformance (the two-implementation contract)
# ---------------------------------------------------------------------------

class TestPagedAttentionKernel:
    def test_pallas_matches_reference_f32_ragged(self):
        q, k, v, bt, sl, _, _, _, _ = _random_paged()
        ref = np.asarray(pa.paged_attention_reference(q, k, v, bt, sl))
        out = np.asarray(pa.paged_attention(q, k, v, bt, sl,
                                            interpret=True))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_pallas_matches_reference_int8(self):
        q, k8, v8, bt, sl, ks, vs, _, _ = _random_paged(dtype="int8",
                                                        seed=3)
        ref = np.asarray(pa.paged_attention_reference(
            q, k8, v8, bt, sl, k_scales=ks, v_scales=vs))
        out = np.asarray(pa.paged_attention(
            q, k8, v8, bt, sl, k_scales=ks, v_scales=vs, interpret=True))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_int8_parity_within_one_percent_of_f32(self):
        # the bench gate's parity criterion, pinned as a unit test
        q, k8, v8, bt, sl, ks, vs, kf, vf = _random_paged(dtype="int8",
                                                          seed=5)
        f32 = np.asarray(pa.paged_attention_reference(q, kf, vf, bt, sl))
        i8 = np.asarray(pa.paged_attention_reference(
            q, k8, v8, bt, sl, k_scales=ks, v_scales=vs))
        rel = np.linalg.norm(i8 - f32) / np.linalg.norm(f32)
        assert rel <= 0.01, f"int8 KV relative error {rel:.4f} > 1%"

    def test_length_one_sequence(self):
        # smallest ragged case: one token, one page, rest of table padded
        q, k, v, bt, sl, _, _, _, _ = _random_paged(B=1, seed=7)
        sl = np.array([1], np.int32)
        ref = np.asarray(pa.paged_attention_reference(q, k, v, bt, sl))
        out = np.asarray(pa.paged_attention(q, k, v, bt, sl,
                                            interpret=True))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_registered_in_dispatch(self):
        spec = kd.kernels()["paged_attention"]
        assert spec.pallas_fn is pa.paged_attention
        assert spec.reference_fn is pa.paged_attention_reference
        q, k, v, bt, sl, _, _, _, _ = _random_paged()
        assert spec.supports(q, k, v, bt, sl)
        assert not spec.supports(q[0], k, v, bt, sl)   # q must be [B,H,D]

    def test_supports_rejects_scaleless_int8(self):
        q, k8, v8, bt, sl, ks, vs, _, _ = _random_paged(dtype="int8")
        spec = kd.kernels()["paged_attention"]
        assert spec.supports(q, k8, v8, bt, sl, k_scales=ks, v_scales=vs)
        assert not spec.supports(q, k8, v8, bt, sl)


# ---------------------------------------------------------------------------
# Fingerprint distinctness
# ---------------------------------------------------------------------------

class TestKvDtypeFingerprint:
    def test_kernel_tier_fingerprint_splits_on_kv_dtype(self):
        kd.set_kv_dtype("f32")
        fp32 = kd.kernel_tier_fingerprint()
        kd.set_kv_dtype("int8")
        fp8 = kd.kernel_tier_fingerprint()
        assert fp32 != fp8
        assert fp32["kv_dtype"] == "f32" and fp8["kv_dtype"] == "int8"

    def test_model_fingerprint_splits_on_kv_dtype(self):
        # f32-KV and int8-KV decode programs must never share an AOT
        # cache entry: the model fingerprint folds the tier in
        model = _tiny()
        kd.set_kv_dtype("f32")
        a = model_fingerprint(model)
        kd.set_kv_dtype("int8")
        b = model_fingerprint(model)
        assert a != b

    def test_engine_installs_its_kv_dtype(self):
        eng = _engine(kv_dtype="int8")
        try:
            assert kd.kv_dtype() == "int8"
        finally:
            eng.shutdown(drain=False)


# ---------------------------------------------------------------------------
# Engine: compile discipline
# ---------------------------------------------------------------------------

class TestZeroRecompile:
    def test_skewed_flood_compiles_nothing_after_warmup(self):
        eng = _engine()
        try:
            warm = eng.warmup()
            assert warm == eng.fresh_compiles() > 0
            # sequence-length-skewed flood: every prompt bucket hit
            rng = np.random.default_rng(0)
            futs = [eng.submit(rng.integers(1, 48, size=n),
                               max_new_tokens=3)
                    for n in (1, 2, 7, 8, 9, 20, 31, 33, 50)]
            for f in futs:
                f.result(timeout=30)
            assert eng.fresh_compiles() == warm, \
                "fresh XLA compile after warmup"
        finally:
            eng.shutdown(drain=False)

    def test_prompt_buckets_are_pow2(self):
        eng = _engine(max_seq_len=128)
        try:
            assert eng.prompt_buckets == [8, 16, 32, 64, 127] \
                or all(b & (b - 1) == 0 for b in eng.prompt_buckets[:-1])
            assert eng.batch_buckets == [1, 2, 4]
        finally:
            eng.shutdown(drain=False)


# ---------------------------------------------------------------------------
# Engine: continuous batching semantics
# ---------------------------------------------------------------------------

class TestContinuousDecode:
    def test_mid_flight_admit_and_retire(self):
        # more sequences than batch slots, wildly different lengths: the
        # short ones retire mid-flight and free slots for the waiting
        eng = _engine(max_decode_batch=2)
        try:
            eng.warmup()
            futs = [eng.submit(np.arange(1, 4), max_new_tokens=n)
                    for n in (2, 12, 3, 9, 2, 5)]
            outs = [f.result(timeout=60) for f in futs]
            assert [len(o) for o in outs] == [2, 12, 3, 9, 2, 5]
            assert eng.cache.blocks_in_use == 0     # all pages released
            assert eng.queue_depth == 0
        finally:
            eng.shutdown(drain=False)

    def test_deterministic_and_prefix_consistent(self):
        # same prompt twice -> same tokens (greedy argmax, shared cache)
        eng = _engine()
        try:
            a = eng.generate(np.arange(1, 6), max_new_tokens=5,
                             timeout=30)
            b = eng.generate(np.arange(1, 6), max_new_tokens=5,
                             timeout=30)
            np.testing.assert_array_equal(a, b)
        finally:
            eng.shutdown(drain=False)

    def test_cancel_waiting_and_active(self):
        eng = _engine(max_decode_batch=1)
        try:
            eng.warmup()
            # long runner occupies the single slot
            long = eng.submit(np.arange(1, 4), max_new_tokens=40)
            waiting = eng.submit(np.arange(1, 4), max_new_tokens=40)
            assert eng.cancel(waiting) is True
            assert waiting.cancelled()
            assert eng.cancel(long) is True         # mid-flight retire
            deadline = time.monotonic() + 5
            while eng.cache.blocks_in_use and time.monotonic() < deadline:
                time.sleep(0.01)
            assert eng.cache.blocks_in_use == 0     # pages back NOW
            assert eng.cancel(Future()) is False    # unknown future
        finally:
            eng.shutdown(drain=False)

    def test_exhaustion_sheds_not_crashes(self):
        # pool of 2 pages (page_size 16 -> 32 tokens): a sequence that
        # outgrows it is shed with KVCacheExhausted; the engine lives on
        eng = _engine(num_blocks=2, page_size=16, max_seq_len=64,
                      max_decode_batch=2)
        try:
            big = eng.submit(np.arange(1, 30), max_new_tokens=20)
            with pytest.raises(KVCacheExhausted):
                big.result(timeout=30)
            # engine still serves admissible work afterward
            ok = eng.generate(np.arange(1, 5), max_new_tokens=3,
                              timeout=30)
            assert len(ok) == 3
            assert eng.cache.blocks_in_use == 0
        finally:
            eng.shutdown(drain=False)

    def test_oversized_prompt_rejected_at_submit(self):
        eng = _engine(max_seq_len=32)
        try:
            with pytest.raises(RejectedError):
                eng.submit(np.arange(1, 31), max_new_tokens=10)
        finally:
            eng.shutdown(drain=False)

    def test_int8_engine_generates(self):
        model = _tiny()
        f32 = _engine(model)
        i8 = _engine(model, kv_dtype="int8", model_label="t8")
        try:
            a = f32.generate(np.arange(1, 9), max_new_tokens=6,
                             timeout=30)
            b = i8.generate(np.arange(1, 9), max_new_tokens=6,
                            timeout=30)
            assert len(a) == len(b) == 6
            # greedy decode may diverge on near-ties; first tokens agree
            assert a[0] == b[0]
        finally:
            f32.shutdown(drain=False)
            i8.shutdown(drain=False)


# ---------------------------------------------------------------------------
# ContinuousBatcher.cancel (the satellite fix)
# ---------------------------------------------------------------------------

class TestBatcherCancel:
    def _batcher(self, **kw):
        started = {"evt": None}

        def dispatch(group, xs):
            if started["evt"] is not None:
                started["evt"].set()
            time.sleep(0.05)
            return xs

        return ContinuousBatcher(dispatch, max_batch=4,
                                 batch_timeout_ms=30.0, **kw), started

    def test_cancel_releases_queue_slot_immediately(self):
        b, _ = self._batcher(max_queue=2)
        try:
            f1 = b.submit(np.ones((1, 2)), group=("a", 1))
            f2 = b.submit(np.ones((1, 2)), group=("b", 1))
            # queue full: a third submit may shed... unless a cancel
            # releases the slot first — mid-group, no boundary wait
            assert b.cancel(f2) is True
            f3 = b.submit(np.ones((1, 2)), group=("a", 1))
            assert f2.cancelled()
            assert np.asarray(f1.result(timeout=5)).shape == (1, 2)
            assert np.asarray(f3.result(timeout=5)).shape == (1, 2)
        finally:
            b.shutdown(drain=False)

    def test_cancel_interleaved_with_admits(self):
        b, _ = self._batcher(max_queue=8)
        try:
            futs = [b.submit(np.ones((1, 2)), group=("g", 1))
                    for _ in range(4)]
            assert b.cancel(futs[1]) is True
            assert b.cancel(futs[3]) is True
            live = [futs[0], futs[2]]
            for f in live:
                assert np.asarray(f.result(timeout=5)).shape == (1, 2)
            assert futs[1].cancelled() and futs[3].cancelled()
        finally:
            b.shutdown(drain=False)

    def test_cancel_unknown_or_dispatched_returns_false(self):
        import threading
        b, started = self._batcher(max_queue=4)
        started["evt"] = threading.Event()
        try:
            assert b.cancel(Future()) is False
            f = b.submit(np.ones((1, 2)), group=("g", 1))
            assert started["evt"].wait(timeout=5)   # now mid-dispatch
            assert b.cancel(f) is False             # cannot recall it
            assert np.asarray(f.result(timeout=5)).shape == (1, 2)
        finally:
            b.shutdown(drain=False)


# ---------------------------------------------------------------------------
# Fleet membership + failover
# ---------------------------------------------------------------------------

class TestDecodeFleet:
    def _fleet(self, replicas=2, **engine_kw):
        from deeplearning4j_tpu.serving import LatencySLO, ModelFleet
        model = _tiny()
        fleet = ModelFleet(max_resident=2)

        def factory(slice_):
            kw = dict(num_blocks=64, max_seq_len=64, max_decode_batch=4,
                      model_label="gen")
            kw.update(engine_kw)
            e = DecodeEngine(model, **kw)
            e.warmup()
            return e

        member = fleet.deploy_decode(
            "gen", factory, slo=LatencySLO(target_p99_ms=1000.0),
            replicas=replicas)
        return fleet, member

    def test_decode_member_is_first_class(self):
        fleet, member = self._fleet()
        try:
            assert member.kind == "decode"
            assert member.state == "resident"
            assert len(member.group.replicas) == 2
            out = fleet.generate("gen", np.arange(1, 5),
                                 max_new_tokens=4).result(timeout=30)
            assert len(out) == 4
            # per-token SLO series feeds the member's latency histogram
            assert member.latency.count > 0
            assert fleet.readyz()["ready"]
            # submit() refuses decode members
            with pytest.raises(ValueError, match="decode member"):
                fleet.submit("gen", np.zeros((1, 4)))
        finally:
            fleet.shutdown()

    def test_failover_restarts_sequence_and_counts(self):
        from deeplearning4j_tpu.monitor.instrument import \
            decode_instruments
        fleet, member = self._fleet()
        try:
            before = decode_instruments().restarts("gen").value
            dead = member.group.replicas[0]
            dead.server.engine.kill()
            # every request lands somewhere: the dead replica's submits
            # fail fatally and restart (from token 0) on the live one
            outs = [fleet.generate("gen", np.arange(1, 6),
                                   max_new_tokens=3).result(timeout=30)
                    for _ in range(8)]
            assert all(len(o) == 3 for o in outs)
            assert dead.poisoned
            after = decode_instruments().restarts("gen").value
            assert after > before, "failover restart was not counted"
        finally:
            fleet.shutdown()

    def test_controller_heals_poisoned_decode_replica(self):
        fleet, member = self._fleet()
        try:
            member.group.replicas[0].server.engine.kill()
            # a probe poisons it (kill sets _poisoned; next submit is
            # fatal), or we poison directly — either way heal respawns
            for _ in range(4):
                fleet.generate("gen", np.arange(1, 5),
                               max_new_tokens=2).result(timeout=30)
            rec = fleet.controller.reconcile()
            heals = [a for a in rec["actions"]
                     if a.get("kind") == "decode"]
            assert heals and heals[0]["cause"] == "poisoned"
            assert member.respawns == 1
            assert all(r.healthy for r in member.group.snapshot())
            out = fleet.generate("gen", np.arange(1, 5),
                                 max_new_tokens=3).result(timeout=30)
            assert len(out) == 3
        finally:
            fleet.shutdown()


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
