"""Listeners, early stopping, transfer learning tests (reference:
`TestEarlyStopping.java`, `TransferLearningMLNTest.java`,
`TestCheckpointListener.java`)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration, TransferLearning, TransferLearningHelper)
from deeplearning4j_tpu.train.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    InMemoryModelSaver, LocalFileModelSaver,
    MaxEpochsTerminationCondition, MaxScoreIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.train.listeners import (
    CheckpointListener, CollectScoresListener, PerformanceListener,
    ScoreIterationListener)
from deeplearning4j_tpu.train.updaters import Adam, Sgd


def _net(n_in=6, n_out=3, seed=0, updater=None):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater or Adam(1e-2))
            .list([DenseLayer(n_out=12, activation="relu"),
                   DenseLayer(n_out=8, activation="relu"),
                   OutputLayer(n_out=n_out, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _iter(n=96, n_in=6, n_out=3, bs=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, n_in).astype(np.float32)
    labels = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)
    y = np.eye(n_out, dtype=np.float32)[labels]
    return ListDataSetIterator([DataSet(x[i:i + bs], y[i:i + bs])
                                for i in range(0, n, bs)])


def test_listeners_collect_and_log():
    net = _net()
    collect = CollectScoresListener()
    perf = PerformanceListener(frequency=2)
    net.set_listeners(ScoreIterationListener(5), collect, perf)
    net.fit(_iter(), epochs=3)
    assert len(collect.scores) == 9      # 3 batches * 3 epochs
    assert collect.scores[-1] < collect.scores[0]
    assert perf.last_iters_per_sec is not None
    assert perf.last_samples_per_sec is not None


def test_checkpoint_listener_rotation(tmp_path):
    net = _net()
    cl = CheckpointListener(str(tmp_path), every_n_iterations=2, keep_last=2)
    net.set_listeners(cl)
    net.fit(_iter(), epochs=3)           # 9 iterations -> 4 checkpoints
    files = [f for f in os.listdir(tmp_path) if f.endswith(".zip")]
    assert len(files) == 2               # rotation keeps last K
    restored = MultiLayerNetwork.load(cl.last_checkpoint())
    assert restored.iteration in (6, 8)


def test_early_stopping_max_epochs():
    net = _net()
    es = EarlyStoppingTrainer(
        EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(_iter(seed=1)),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(4)],
            model_saver=InMemoryModelSaver()),
        net, _iter())
    result = es.fit()
    assert result.total_epochs == 4
    assert result.termination_reason == "EpochTerminationCondition"
    assert result.best_model is not None
    assert result.best_model_score < float("inf")


def test_early_stopping_patience_stops_before_max():
    net = _net(updater=Sgd(1e-6))        # lr too small to improve
    es = EarlyStoppingTrainer(
        EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(_iter(seed=1)),
            epoch_termination_conditions=[
                MaxEpochsTerminationCondition(50),
                ScoreImprovementEpochTerminationCondition(
                    patience=2, min_improvement=1e-3)],
            model_saver=InMemoryModelSaver()),
        net, _iter())
    result = es.fit()
    assert result.total_epochs < 50


def test_early_stopping_divergence_abort():
    net = _net(updater=Sgd(1e6))         # lr absurd -> divergence
    es = EarlyStoppingTrainer(
        EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(_iter(seed=1)),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(10)],
            iteration_termination_conditions=[
                MaxScoreIterationTerminationCondition(1e4)],
            model_saver=InMemoryModelSaver()),
        net, _iter())
    result = es.fit()
    assert result.termination_reason == "IterationTerminationCondition"


def test_early_stopping_local_file_saver(tmp_path):
    net = _net()
    es = EarlyStoppingTrainer(
        EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(_iter(seed=1)),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(2)],
            model_saver=LocalFileModelSaver(str(tmp_path))),
        net, _iter())
    result = es.fit()
    assert os.path.exists(tmp_path / "bestModel.zip")
    assert isinstance(result.best_model, MultiLayerNetwork)


def test_transfer_learning_head_swap():
    base = _net(n_out=3)
    base.fit(_iter(), epochs=2)
    w0_before = np.asarray(base.params_["layer_0"]["W"]).copy()
    new = (TransferLearning.builder(base)
           .fine_tune_configuration(FineTuneConfiguration(updater=Adam(5e-3)))
           .set_feature_extractor(1)        # freeze layers 0..1
           .remove_output_layer()
           .add_layer(OutputLayer(n_out=5, loss="mcxent",
                                  activation="softmax"))
           .build())
    # retained layers kept their params
    np.testing.assert_array_equal(np.asarray(new.params_["layer_0"]["W"]),
                                  w0_before)
    assert new.conf.layers[0].frozen and new.conf.layers[1].frozen
    assert not new.conf.layers[2].frozen
    # new head: 5 classes
    it5 = _iter(n_out=5)
    new.fit(it5, epochs=2)
    # frozen layer params unchanged by training
    np.testing.assert_array_equal(np.asarray(new.params_["layer_0"]["W"]),
                                  w0_before)
    assert new.output(np.zeros((2, 6), np.float32)).shape == (2, 5)


def test_n_out_replace_reinitializes_downstream():
    base = _net()
    new = (TransferLearning.builder(base)
           .n_out_replace(1, 20)
           .build())
    assert new.params_["layer_1"]["W"].shape == (12, 20)
    assert new.params_["layer_2"]["W"].shape == (20, 3)


def test_transfer_learning_helper_featurize():
    base = _net()
    new = (TransferLearning.builder(base)
           .set_feature_extractor(0)
           .build())
    helper = TransferLearningHelper(new)
    it = _iter()
    feat = [helper.featurize(ds) for ds in it]
    assert feat[0].features.shape == (32, 12)   # after layer_0
    s0 = helper.unfrozen_mln().score_for(feat[0].features, feat[0].labels)
    for _ in range(10):
        for f in feat:
            helper.fit_featurized(f)
    s1 = helper.unfrozen_mln().score_for(feat[0].features, feat[0].labels)
    assert s1 < s0
    full = helper.sync_to_full()
    # full-net output consistent with featurized path
    out_full = np.asarray(full.output(it._list[0].features))
    out_feat = np.asarray(helper.output_from_featurized(feat[0].features))
    np.testing.assert_allclose(out_full, out_feat, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# ComputationGraph transfer learning (VERDICT #9; reference
# `TransferLearningCompGraphTest.java`)
# ---------------------------------------------------------------------------

def _cg_base(seed=11):
    from deeplearning4j_tpu.nn import (ComputationGraph, DenseLayer,
                                       GraphBuilder, InputType, OutputLayer)
    conf = (GraphBuilder()
            .seed(seed).updater(Sgd(0.1)).weight_init("XAVIER")
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(8))
            .add_layer("f1", DenseLayer(n_out=12, activation="relu"), "in")
            .add_layer("f2", DenseLayer(n_out=10, activation="relu"), "f1")
            .add_layer("out", OutputLayer(n_out=3, loss="mcxent",
                                          activation="softmax"), "f2")
            .set_outputs("out").build())
    return ComputationGraph(conf).init()


def _cg_data(n=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return x, y


def test_cg_transfer_freeze_and_head_swap():
    from deeplearning4j_tpu.nn import DenseLayer, OutputLayer
    base = _cg_base()
    x, y = _cg_data()
    for _ in range(5):
        base.fit([x], [y])
    f1_before = np.asarray(base.params_["f1"]["W"]).copy()

    new = (TransferLearning.graph_builder(base)
           .set_feature_extractor("f2")
           .remove_vertex_and_connections("out")
           .add_layer("new_out", OutputLayer(n_out=5, loss="mcxent",
                                             activation="softmax"), "f2")
           .set_outputs("new_out")
           .build())
    # retained params transplanted
    np.testing.assert_array_equal(np.asarray(new.params_["f1"]["W"]),
                                  f1_before)
    assert new.params_["new_out"]["W"].shape == (10, 5)
    # frozen ancestors stay fixed through training; head moves
    y5 = np.eye(5, dtype=np.float32)[np.random.RandomState(1).randint(
        0, 5, 32)]
    head_before = np.asarray(new.params_["new_out"]["W"]).copy()
    f2_before = np.asarray(new.params_["f2"]["W"]).copy()
    for _ in range(3):
        new.fit([x], [y5])
    np.testing.assert_array_equal(np.asarray(new.params_["f1"]["W"]),
                                  f1_before)
    np.testing.assert_array_equal(np.asarray(new.params_["f2"]["W"]),
                                  f2_before)
    assert not np.allclose(np.asarray(new.params_["new_out"]["W"]),
                           head_before)
    # and the source network is untouched by the derived net's training
    # (donation-aliasing regression: ADVICE r1 finding)
    base.output([x])


def test_cg_transfer_nout_replace_reinits_consumer():
    base = _cg_base()
    x, y = _cg_data()
    base.fit([x], [y])
    new = (TransferLearning.graph_builder(base)
           .n_out_replace("f2", 16, weight_init="XAVIER")
           .build())
    assert new.params_["f2"]["W"].shape == (12, 16)
    assert new.params_["out"]["W"].shape == (16, 3)
    # f1 retained
    np.testing.assert_array_equal(np.asarray(new.params_["f1"]["W"]),
                                  np.asarray(base.params_["f1"]["W"]))
    new.fit([x], [y])
    assert np.isfinite(new.score())


def test_cg_transfer_splice_vertex():
    from deeplearning4j_tpu.nn import ScaleVertex
    from deeplearning4j_tpu.nn import (ComputationGraph, DenseLayer,
                                       GraphBuilder, InputType, OutputLayer)
    conf = (GraphBuilder()
            .seed(3).updater(Sgd(0.1))
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("d", DenseLayer(n_out=6, activation="tanh"), "in")
            .add_vertex("sc", ScaleVertex(scale=2.0), "d")
            .add_layer("out", OutputLayer(n_out=2, loss="mcxent",
                                          activation="softmax"), "sc")
            .set_outputs("out").build())
    base = ComputationGraph(conf).init()
    new = (TransferLearning.graph_builder(base)
           .remove_vertex_keep_connections("sc")
           .build())
    assert "sc" not in new.conf.vertices
    assert new.conf.vertex_inputs["out"] == ["d"]
    x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    out = new.output([x])
    assert np.asarray(out[0]).shape == (8, 2)


def test_cg_transfer_nout_replace_propagates_through_bn():
    """Width changes must pass through BatchNorm (parameterized but
    width-transparent) and re-init the next conv (review regression)."""
    from deeplearning4j_tpu.nn import (BatchNormalizationLayer,
                                       ComputationGraph, ConvolutionLayer,
                                       GraphBuilder, GlobalPoolingLayer,
                                       InputType, OutputLayer)
    conf = (GraphBuilder().seed(2).updater(Sgd(0.1))
            .add_inputs("in")
            .set_input_types(InputType.convolutional(8, 8, 3))
            .add_layer("conv1", ConvolutionLayer(n_out=4, kernel_size=3,
                                                 convolution_mode="Same"),
                       "in")
            .add_layer("bn1", BatchNormalizationLayer(activation="relu"),
                       "conv1")
            .add_layer("conv2", ConvolutionLayer(n_out=6, kernel_size=3,
                                                 convolution_mode="Same"),
                       "bn1")
            .add_layer("gap", GlobalPoolingLayer(pooling_type="AVG"),
                       "conv2")
            .add_layer("out", OutputLayer(n_out=2, loss="mcxent",
                                          activation="softmax"), "gap")
            .set_outputs("out").build())
    base = ComputationGraph(conf).init()
    new = (TransferLearning.graph_builder(base)
           .n_out_replace("conv1", 8).build())
    assert new.params_["conv1"]["W"].shape[-1] == 8
    assert new.params_["conv2"]["W"].shape == (3, 3, 8, 6)
    x = np.random.RandomState(0).rand(2, 8, 8, 3).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[[0, 1]]
    new.fit([x], [y])
    assert np.isfinite(new.score())
