"""OpValidation specs, part 3: image / random / scatter / segment /
TensorList / compression / word2vec / host-side ops.  TF goldens are used
for the TF-defined image semantics (adjust_hue, central_crop,
crop_and_resize, space_to_depth family, fake_quant) — the same golden
source the reference's TFGraphTestAllSameDiff corpus uses."""
import numpy as np

from deeplearning4j_tpu.autodiff.validation import OpTestCase
from tests.opval_specs_core import C, F, FP, F01, I32, rs

CASES = []

_img = F01(2, 6, 6, 3)


def _tf():
    import tensorflow as tf
    return tf


# ---- colorspace ----
def _hsv_golden(x):
    import colorsys
    flat = x.reshape(-1, 3)
    return np.asarray([colorsys.rgb_to_hsv(*p) for p in flat],
                      np.float64).reshape(x.shape)


def _hsv_inv_golden(x):
    import colorsys
    flat = x.reshape(-1, 3)
    return np.asarray([colorsys.hsv_to_rgb(*p) for p in flat],
                      np.float64).reshape(x.shape)


_YIQ_M = np.asarray([[0.299, 0.587, 0.114],
                     [0.5959, -0.2746, -0.3213],
                     [0.2115, -0.5227, 0.3112]])
_YUV_M = np.asarray([[0.299, 0.587, 0.114],
                     [-0.14714119, -0.28886916, 0.43601035],
                     [0.61497538, -0.51496512, -0.10001026]])

CASES += [
    C("rgb_to_grs", _img, g=lambda x:
      np.sum(x * [0.2989, 0.5870, 0.1140], -1, keepdims=True), tol=1e-4),
    C("rgb_to_hsv", _img, g=_hsv_golden, tol=1e-4),
    C("hsv_to_rgb", _hsv_golden(F01(2, 4, 4, 3)).astype(np.float32),
      g=_hsv_inv_golden, tol=1e-4),
    C("rgb_to_yiq", _img, g=lambda x: x @ _YIQ_M.T, tol=1e-4, grad=(0,),
      grad_sample=12),
    C("yiq_to_rgb", (_img @ _YIQ_M.T).astype(np.float32),
      g=lambda x: x @ np.linalg.inv(_YIQ_M).T, tol=1e-4),
    C("rgb_to_yuv", _img, g=lambda x: x @ _YUV_M.T, tol=1e-4),
    C("yuv_to_rgb", (_img @ _YUV_M.T).astype(np.float32),
      g=lambda x: x @ np.linalg.inv(_YUV_M).T, tol=1e-4),
    C("adjust_hue", _img, g=lambda x, delta: _tf().image.adjust_hue(
        x, delta).numpy(), kw={"delta": 0.15}, tol=1e-3),
    C("adjust_saturation", _img, g=lambda x, factor:
      _tf().image.adjust_saturation(x, factor).numpy(),
      kw={"factor": 1.4}, tol=1e-3),
    C("adjust_contrast", _img, g=lambda x, factor:
      _tf().image.adjust_contrast(x, factor).numpy().astype(np.float64),
      kw={"factor": 1.8}, tol=1e-4, grad=(0,), grad_sample=12),
    C("adjust_contrast_v2", _img, g=lambda x, factor:
      _tf().image.adjust_contrast(x, factor).numpy().astype(np.float64),
      kw={"factor": 0.6}, tol=1e-4),
    C("per_image_standardization", _img, g=lambda x:
      _tf().image.per_image_standardization(x).numpy(), tol=1e-4),
    C("image_central_crop", F01(2, 8, 8, 3), g=lambda x, fraction:
      _tf().image.central_crop(x, fraction).numpy(),
      kw={"fraction": 0.5}),
    C("image_flip_left_right", _img, g=lambda x: x[:, :, ::-1]),
    C("image_flip_up_down", _img, g=lambda x: x[:, ::-1]),
    C("image_rot90", _img, g=lambda x, k=1: np.rot90(
        x, k, axes=(-3, -2)), kw={"k": 3}),
    C("crop_and_resize", F01(2, 8, 8, 2),
      np.asarray([[0.1, 0.1, 0.7, 0.9], [0.0, 0.0, 1.0, 1.0]],
                 np.float32),
      np.asarray([0, 1], np.int32), (4, 4),
      g=lambda img, boxes, bi, size, method="bilinear":
      _tf().image.crop_and_resize(
          img, boxes, bi, size,
          method="bilinear").numpy(), tol=1e-3),
    C("extract_image_patches", None, g=None),  # placeholder, removed below
]
CASES = [c for c in CASES if c.op != "extract_image_patches"]

# ---- space/depth/batch reshuffles (TF goldens) ----
_s2d = F(2, 4, 4, 3)
CASES += [
    C("space_to_depth", _s2d, g=lambda x, block_size=2:
      _tf().nn.space_to_depth(x, block_size).numpy()),
    C("depth_to_space", F(2, 2, 2, 12), g=lambda x, block_size=2:
      _tf().nn.depth_to_space(x, block_size).numpy()),
    C("space_to_batch", _s2d, g=lambda x, block=2,
      paddings=((0, 0), (0, 0)): _tf().space_to_batch(
          x, [block, block], paddings).numpy()),
    C("batch_to_space", F(8, 2, 2, 3), g=lambda x, block=2,
      crops=((0, 0), (0, 0)): _tf().batch_to_space(
          x, [block, block], crops).numpy()),
    C("space_to_batch_nd", _s2d, (2, 2), ((0, 0), (0, 0)),
      g=lambda x, bs, p: _tf().space_to_batch_nd(x, list(bs),
                                                 list(p)).numpy()),
    C("batch_to_space_nd", F(8, 2, 2, 3), (2, 2), ((0, 0), (0, 0)),
      g=lambda x, bs, c: _tf().batch_to_space(x, list(bs),
                                              list(c)).numpy()),
    C("batch_to_space", F(8, 3, 3, 2), kw={"crops": ((1, 1), (0, 2))},
      g=lambda x, block=2, crops=((0, 0), (0, 0)): _tf().batch_to_space(
          x, [block, block], crops).numpy(), tag="crops"),
]

# ---- resize family ----
_r_in = F01(1, 4, 4, 2)
CASES += [
    C("resize_nearest", _r_in, (8, 8), g=lambda x, size:
      np.repeat(np.repeat(x, 2, 1), 2, 2)),
    C("resize_bilinear", _r_in, (4, 4), g=lambda x, size: x,
      tag="same"),
    C("resize_bilinear", np.ones((1, 4, 4, 1), np.float32), (7, 7),
      g=lambda x, size: np.ones((1, 7, 7, 1)), tag="const"),
    C("image_resize", _r_in, (4, 4), g=lambda x, size,
      method="bilinear": x),
    C("resize_bicubic", np.ones((1, 4, 4, 1), np.float32), (6, 6),
      g=lambda x, size: np.ones((1, 6, 6, 1)), tol=1e-4),
    C("resize_lanczos", np.ones((1, 4, 4, 1), np.float32), (6, 6),
      g=lambda x, size: np.ones((1, 6, 6, 1)), tol=1e-4),
    C("resize_area", F01(1, 6, 6, 2), (3, 3), g=lambda x, size:
      x.reshape(1, 3, 2, 3, 2, 2).mean((2, 4)), tol=1e-5, grad=(0,),
      grad_sample=12),
]

# ---- nms / boxes ----
_boxes = np.asarray([[0.0, 0.0, 0.5, 0.5],
                     [0.05, 0.05, 0.55, 0.55],     # IoU with 0 > 0.5
                     [0.6, 0.6, 1.0, 1.0],
                     [0.0, 0.6, 0.4, 1.0]], np.float32)
_scores = np.asarray([0.9, 0.8, 0.7, 0.3], np.float32)


def _iou_matrix(b):
    n = b.shape[0]
    out = np.zeros((n, n), np.float32)
    area = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(
        b[:, 3] - b[:, 1], 0)
    for i in range(n):
        for j in range(n):
            yy1, xx1 = max(b[i, 0], b[j, 0]), max(b[i, 1], b[j, 1])
            yy2, xx2 = min(b[i, 2], b[j, 2]), min(b[i, 3], b[j, 3])
            inter = max(yy2 - yy1, 0) * max(xx2 - xx1, 0)
            out[i, j] = inter / max(area[i] + area[j] - inter, 1e-12)
    return out


CASES += [
    C("non_max_suppression", _boxes, _scores, 3,
      g=lambda b, s, m, iou_threshold=0.5, score_threshold=None:
      _tf().image.non_max_suppression(b, s, m, 0.5).numpy(),
      kw={"iou_threshold": 0.5}),
    C("non_max_suppression_overlaps", _iou_matrix(_boxes), _scores, 3,
      g=lambda o, s, m, overlap_threshold=0.5, score_threshold=None:
      np.asarray([0, 2, 3]), kw={"overlap_threshold": 0.5}),
]


def _draw_boxes_check(out):
    img = out[0]
    # box edge pixel colored, far-away pixel untouched
    assert np.allclose(img[0, 2, 2], 1.0)       # corner of the box
    assert np.allclose(img[0, 7, 7], _DRAW_IMG[0, 7, 7])


_DRAW_IMG = np.zeros((1, 8, 8, 3), np.float32) + 0.2
CASES += [
    C("draw_bounding_boxes", _DRAW_IMG,
      np.asarray([[[2 / 7, 2 / 7, 5 / 7, 5 / 7]]], np.float32),
      check=_draw_boxes_check),
]

# ---- random family (fixed-key property checks) ----


def _rand_case(op, kwargs, check, tag=""):
    def custom(fn):
        import jax
        k = jax.random.PRNGKey(5)
        out = fn(k, **kwargs)
        a = np.asarray(out)
        out2 = np.asarray(fn(k, **kwargs))
        np.testing.assert_array_equal(a, out2)   # deterministic per key
        check(a)
    return C(op, custom=custom, tag=tag)


CASES += [
    _rand_case("random_uniform", {"shape": (2000,), "minval": 1.0,
                                  "maxval": 3.0},
               lambda a: (np.testing.assert_allclose(a.mean(), 2.0,
                                                     atol=0.1),
                          np.testing.assert_array_less(a, 3.0),
                          np.testing.assert_array_less(0.999, a))),
    _rand_case("random_normal", {"shape": (4000,), "mean": 1.0,
                                 "stddev": 2.0},
               lambda a: (np.testing.assert_allclose(a.mean(), 1.0,
                                                     atol=0.15),
                          np.testing.assert_allclose(a.std(), 2.0,
                                                     atol=0.15))),
    _rand_case("random_bernoulli", {"shape": (4000,), "p": 0.3},
               lambda a: np.testing.assert_allclose(a.mean(), 0.3,
                                                    atol=0.05)),
    _rand_case("random_exponential", {"shape": (4000,), "lam": 2.0},
               lambda a: np.testing.assert_allclose(a.mean(), 0.5,
                                                    atol=0.06)),
    _rand_case("random_gamma", {"shape": (4000,), "alpha": 2.0,
                                "beta": 2.0},
               lambda a: np.testing.assert_allclose(a.mean(), 1.0,
                                                    atol=0.1)),
    _rand_case("random_poisson", {"shape": (4000,), "lam": 3.0},
               lambda a: np.testing.assert_allclose(a.mean(), 3.0,
                                                    atol=0.2)),
    _rand_case("random_lognormal", {"shape": (4000,), "mean": 0.0,
                                    "stddev": 0.5},
               lambda a: np.testing.assert_allclose(
                   np.log(a).mean(), 0.0, atol=0.1)),
    _rand_case("random_binomial", {"shape": (3000,), "n": 10, "p": 0.4},
               lambda a: np.testing.assert_allclose(a.mean(), 4.0,
                                                    atol=0.3)),
    _rand_case("truncated_normal", {"shape": (3000,)},
               lambda a: (np.testing.assert_array_less(np.abs(a), 2.001),
                          np.testing.assert_allclose(a.mean(), 0.0,
                                                     atol=0.1))),
    _rand_case("random_randint", {"shape": (2000,), "minval": 2,
                                  "maxval": 7},
               lambda a: (np.testing.assert_array_less(a, 7),
                          np.testing.assert_array_less(1, a))),
]


def _shuffle_custom(fn):
    import jax
    x = np.arange(40, dtype=np.float32)
    y = np.asarray(fn(jax.random.PRNGKey(1), x))
    assert not np.array_equal(y, x)
    np.testing.assert_array_equal(np.sort(y), x)


def _multinomial_custom(fn):
    import jax
    logits = np.log(np.asarray([[0.8, 0.1, 0.1]], np.float32))
    s = np.asarray(fn(jax.random.PRNGKey(2), logits, 500))
    assert s.shape == (1, 500)
    assert set(np.unique(s)) <= {0, 1, 2}
    assert (s == 0).mean() > 0.6


def _choice_custom(fn):
    import jax
    src = np.asarray([10.0, 20.0, 30.0], np.float32)
    p = np.asarray([0.0, 1.0, 0.0], np.float32)
    out = np.asarray(fn(jax.random.PRNGKey(3), src, p, 50))
    np.testing.assert_array_equal(out, np.full(50, 20.0))


def _crop_custom(fn):
    import jax
    x = np.arange(36, dtype=np.float32).reshape(6, 6)
    out = np.asarray(fn(jax.random.PRNGKey(4), x, (3, 3)))
    assert out.shape == (3, 3)
    r0, c0 = int(out[0, 0]) // 6, int(out[0, 0]) % 6
    np.testing.assert_array_equal(out, x[r0:r0 + 3, c0:c0 + 3])


def _rng_fold_custom(fn):
    import jax
    k = jax.random.PRNGKey(0)
    a, b = np.asarray(fn(k, 1)), np.asarray(fn(k, 2))
    assert not np.array_equal(a, b)
    np.testing.assert_array_equal(np.asarray(fn(k, 1)), a)


def _rng_fold_opt_custom(fn):
    import jax
    assert fn(None, 3) is None
    k = jax.random.PRNGKey(0)
    assert fn(k, 3) is not None


CASES += [
    C("random_shuffle", custom=_shuffle_custom),
    C("multinomial", custom=_multinomial_custom),
    C("random_choice", custom=_choice_custom),
    C("random_crop", custom=_crop_custom),
    C("rng_fold", custom=_rng_fold_custom),
    C("rng_fold_opt", custom=_rng_fold_opt_custom),
]

# ---- scatter / segment (independent numpy loops) ----
_sc_a = F(6, 3)
_sc_idx = np.asarray([0, 2, 5], np.int32)           # unique
_sc_dup = np.asarray([0, 2, 2], np.int32)           # duplicates (add-only)
_sc_upd = F(3, 3)


def _np_scatter(a, idx, upd, op):
    out = a.astype(np.float64).copy()
    for i, j in enumerate(idx):
        if op == "add":
            out[j] += upd[i]
        elif op == "set":
            out[j] = upd[i]
        elif op == "max":
            out[j] = np.maximum(out[j], upd[i])
        elif op == "min":
            out[j] = np.minimum(out[j], upd[i])
        elif op == "mul":
            out[j] *= upd[i]
        elif op == "div":
            out[j] /= upd[i]
        elif op == "sub":
            out[j] -= upd[i]
    return out


CASES += [
    C("scatter_add", _sc_a, _sc_dup, _sc_upd,
      g=lambda a, i, u: _np_scatter(a, i, u, "add"), tol=1e-5,
      grad=(0, 2)),
    C("scatter_sub", _sc_a, _sc_dup, _sc_upd,
      g=lambda a, i, u: _np_scatter(a, i, u, "sub"), tol=1e-5),
    C("scatter_update", _sc_a, _sc_idx, _sc_upd,
      g=lambda a, i, u: _np_scatter(a, i, u, "set")),
    C("scatter_max", _sc_a, _sc_idx, _sc_upd,
      g=lambda a, i, u: _np_scatter(a, i, u, "max")),
    C("scatter_min", _sc_a, _sc_idx, _sc_upd,
      g=lambda a, i, u: _np_scatter(a, i, u, "min")),
    C("scatter_mul", _sc_a, _sc_idx, _sc_upd,
      g=lambda a, i, u: _np_scatter(a, i, u, "mul"), tol=1e-5),
    C("scatter_div", _sc_a, _sc_idx, FP(3, 3),
      g=lambda a, i, u: _np_scatter(a, i, u, "div"), tol=1e-5),
]

_nd_idx = np.asarray([[0, 1], [2, 0], [3, 2]], np.int32)
_nd_upd = F(3)


def _np_scatter_nd(a, idx, upd, op):
    out = a.astype(np.float64).copy()
    for k in range(idx.shape[0]):
        i, j = idx[k]
        if op == "add":
            out[i, j] += upd[k]
        elif op == "sub":
            out[i, j] -= upd[k]
        elif op == "set":
            out[i, j] = upd[k]
        elif op == "max":
            out[i, j] = max(out[i, j], upd[k])
        elif op == "min":
            out[i, j] = min(out[i, j], upd[k])
    return out


CASES += [
    C("scatter_nd_add", F(4, 3), _nd_idx, _nd_upd,
      g=lambda a, i, u: _np_scatter_nd(a, i, u, "add"), tol=1e-5),
    C("scatter_nd_sub", F(4, 3), _nd_idx, _nd_upd,
      g=lambda a, i, u: _np_scatter_nd(a, i, u, "sub"), tol=1e-5),
    C("scatter_nd_update", F(4, 3), _nd_idx, _nd_upd,
      g=lambda a, i, u: _np_scatter_nd(a, i, u, "set")),
    C("scatter_nd_max", F(4, 3), _nd_idx, _nd_upd,
      g=lambda a, i, u: _np_scatter_nd(a, i, u, "max")),
    C("scatter_nd_min", F(4, 3), _nd_idx, _nd_upd,
      g=lambda a, i, u: _np_scatter_nd(a, i, u, "min")),
    C("scatter_nd", _nd_idx, _nd_upd, (4, 3),
      g=lambda i, u, s: _np_scatter_nd(np.zeros(s, np.float32), i, u,
                                       "add"), tol=1e-5),
]

_seg_data = F(6, 2)
_seg_ids = np.asarray([0, 0, 1, 2, 2, 2], np.int32)


def _np_segment(data, ids, n, op):
    init = {"sum": 0.0, "prod": 1.0, "max": -np.inf, "min": np.inf}[op]
    out = np.full((n,) + data.shape[1:], init)
    for i, s in enumerate(ids):
        if op == "sum":
            out[s] += data[i]
        elif op == "prod":
            out[s] *= data[i]
        elif op == "max":
            out[s] = np.maximum(out[s], data[i])
        elif op == "min":
            out[s] = np.minimum(out[s], data[i])
    return out


CASES += [
    C("segment_sum", _seg_data, _seg_ids, 3,
      g=lambda d, i, n: _np_segment(d, i, n, "sum"), tol=1e-5,
      grad=(0,)),
    C("segment_max", _seg_data, _seg_ids, 3,
      g=lambda d, i, n: _np_segment(d, i, n, "max")),
    C("segment_min", _seg_data, _seg_ids, 3,
      g=lambda d, i, n: _np_segment(d, i, n, "min")),
    C("segment_prod", _seg_data, _seg_ids, 3,
      g=lambda d, i, n: _np_segment(d, i, n, "prod"), tol=1e-5),
    C("segment_mean", _seg_data, _seg_ids, 3,
      g=lambda d, i, n: _np_segment(d, i, n, "sum")
      / np.asarray([2, 1, 3])[:, None], tol=1e-5),
    C("unsorted_segment_sum", _seg_data,
      np.asarray([2, 0, 1, 0, 2, 2], np.int32), 3,
      g=lambda d, i, n: _np_segment(d, i, n, "sum"), tol=1e-5),
    C("unsorted_segment_max", _seg_data,
      np.asarray([2, 0, 1, 0, 2, 2], np.int32), 3,
      g=lambda d, i, n: _np_segment(d, i, n, "max")),
    C("unsorted_segment_min", _seg_data,
      np.asarray([2, 0, 1, 0, 2, 2], np.int32), 3,
      g=lambda d, i, n: _np_segment(d, i, n, "min")),
    C("unsorted_segment_prod", _seg_data,
      np.asarray([2, 0, 1, 0, 2, 2], np.int32), 3,
      g=lambda d, i, n: _np_segment(d, i, n, "prod"), tol=1e-5),
    C("unsorted_segment_mean", _seg_data,
      np.asarray([2, 0, 1, 0, 2, 2], np.int32), 3,
      g=lambda d, i, n: _np_segment(d, i, n, "sum")
      / np.asarray([2, 1, 3])[:, None], tol=1e-5),
    C("unsorted_segment_sqrt_n", _seg_data,
      np.asarray([2, 0, 1, 0, 2, 2], np.int32), 3,
      g=lambda d, i, n: _np_segment(d, i, n, "sum")
      / np.sqrt(np.asarray([2, 1, 3]))[:, None], tol=1e-5),
]

# ---- dynamic partition / stitch (host-side) ----
CASES += [
    C("dynamic_partition", jit=False, custom=lambda fn: (
        lambda out: (
            np.testing.assert_allclose(np.asarray(out[0]),
                                       [[1., 2.], [5., 6.]]),
            np.testing.assert_allclose(np.asarray(out[1]),
                                       [[3., 4.]]))
    )(fn(np.asarray([[1., 2.], [3., 4.], [5., 6.]], np.float32),
         np.asarray([0, 1, 0], np.int32), 2))),
    C("dynamic_stitch",
      [np.asarray([0, 2], np.int32), np.asarray([1, 3], np.int32)],
      [np.asarray([[1., 1.], [3., 3.]], np.float32),
       np.asarray([[2., 2.], [4., 4.]], np.float32)],
      g=lambda idx, data: np.asarray(
          [[1., 1.], [2., 2.], [3., 3.], [4., 4.]]), jit=False),
]

# ---- sparse / misc transforms ----
CASES += [
    C("sparse_to_dense", np.asarray([[0, 1], [2, 2]], np.int32), (3, 4),
      np.asarray([5.0, 7.0], np.float32),
      g=lambda i, s, v, default_value=0.0: np.asarray(
          [[0, 5, 0, 0], [0, 0, 0, 0], [0, 0, 7, 0]], np.float64)),
    C("mergemax", F(3, 4), F(3, 4), F(3, 4),
      g=lambda *xs: np.maximum(np.maximum(xs[0], xs[1]), xs[2]),
      grad=(0,)),
    C("mergeadd", F(3, 4), F(3, 4), F(3, 4), g=lambda *xs: sum(xs),
      grad=(0, 1, 2)),
    C("mergeavg", F(3, 4), F(3, 4), F(3, 4),
      g=lambda *xs: sum(xs) / 3, tol=1e-5),
    C("mergemaxindex", F(3, 4), F(3, 4), F(3, 4),
      g=lambda *xs: np.argmax(np.stack(xs), 0).astype(np.int32)),
    C("fake_quant_with_min_max_args", F(3, 5, lo=-8, hi=8),
      kw={"min": -6.0, "max": 6.0, "num_bits": 8},
      g=lambda x, min=-6.0, max=6.0, num_bits=8, narrow_range=False:
      _tf().quantization.fake_quant_with_min_max_args(
          x, min, max, num_bits, narrow_range).numpy(), tol=1e-4),
    C("fake_quant_with_min_max_vars", F(3, 5, lo=-8, hi=8),
      np.float32(-4.0), np.float32(4.0),
      g=lambda x, mn, mx, num_bits=8, narrow_range=False:
      _tf().quantization.fake_quant_with_min_max_vars(
          x, float(mn), float(mx), num_bits, narrow_range).numpy(),
      tol=1e-4),
    C("dilation2d", F(1, 5, 5, 2), F(2, 2, 2, lo=-0.3, hi=0.3),
      g=lambda x, f, stride=(1, 1), padding="SAME":
      _tf().nn.dilation2d(
          x, f, strides=(1, 1, 1, 1), padding="SAME",
          data_format="NHWC", dilations=(1, 1, 1, 1)).numpy(),
      tol=1e-4),
    C("max_pool_with_argmax", F(1, 4, 4, 2),
      g=lambda x, kernel=(2, 2), stride=(2, 2), padding="VALID": (
          _tf().nn.max_pool_with_argmax(
              x, (1, 2, 2, 1), (1, 2, 2, 1), "VALID",
              include_batch_in_index=False)[0].numpy(),
          _tf().nn.max_pool_with_argmax(
              x, (1, 2, 2, 1), (1, 2, 2, 1), "VALID",
              include_batch_in_index=False)[1].numpy())),
]

# ---- compression (round-trip property checks) ----


def _threshold_check(fn):
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    g = np.asarray([0.5, -0.2, 0.001, 0.9, -0.0005, -0.7], np.float32)
    enc = np.asarray(fn(g, threshold=0.1, max_elements=6))
    dec = np.asarray(OP_TABLE["decode_threshold"](enc, 6, threshold=0.1))
    want = np.where(np.abs(g) >= 0.1, np.sign(g) * 0.1, 0.0)
    np.testing.assert_allclose(dec, want, atol=1e-6)


def _bitmap_check(fn):
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    g = np.asarray([0.5, -0.2, 0.001, 0.9] * 5, np.float32)
    packed, cnt = fn(g, threshold=0.1)
    dec = np.asarray(OP_TABLE["decode_bitmap"](packed, 20, threshold=0.1))
    want = np.where(np.abs(g) >= 0.1, np.sign(g) * 0.1, 0.0)
    np.testing.assert_allclose(dec, want, atol=1e-6)
    assert int(cnt) == int(np.sum(np.abs(g) >= 0.1))


CASES += [
    C("encode_threshold", custom=_threshold_check),
    C("decode_threshold", np.asarray([1, -2, 0, 4], np.int32), 4,
      kw={"threshold": 0.5},
      g=lambda e, size, threshold=0.5: np.asarray(
          [0.5, -0.5, 0, 0.5], np.float64) * [1, 1, 0, 1] * 1.0),
    C("encode_bitmap", custom=_bitmap_check),
    C("decode_bitmap", custom=lambda fn: _bitmap_check.__wrapped__(fn)
      if hasattr(_bitmap_check, "__wrapped__") else None, jit=False),
]
CASES = [c for c in CASES if not (c.op == "decode_bitmap"
                                  and c.custom is not None)]


def _decode_bitmap_custom(fn):
    packed = np.asarray([0b1001], np.int32)   # flags: [1, 2, 0, ...]
    dec = np.asarray(fn(packed, 4, threshold=0.2))
    np.testing.assert_allclose(dec, [0.2, -0.2, 0.0, 0.0], atol=1e-7)


CASES.append(C("decode_bitmap", custom=_decode_bitmap_custom))

# fix decode_threshold golden above: codes ±(idx+1) scatter ±thr at idx
CASES = [c for c in CASES if c.op != "decode_threshold"]
CASES.append(
    C("decode_threshold", np.asarray([1, -2, 0, 4], np.int32), 4,
      kw={"threshold": 0.5},
      g=lambda e, size, threshold=0.5: np.asarray(
          [0.5, -0.5, 0.0, 0.5], np.float64)))

# ---- TensorList family (host-side stateful) ----


def _list_flow(fn_name, flow):
    def custom(fn):
        flow(fn)
    return C(fn_name, jit=False, custom=custom)


def _f_create(fn):
    lst = fn(size=3)
    assert len(lst) == 3
    assert len(fn()) == 0


def _f_write_read(fn):
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    lst = OP_TABLE["create_list"]()
    fn(lst, 2, np.asarray([1.0, 2.0], np.float32))
    got = np.asarray(OP_TABLE["read_list"](lst, 2))
    np.testing.assert_allclose(got, [1.0, 2.0])


def _f_read(fn):
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    lst = OP_TABLE["create_list"]()
    OP_TABLE["write_list"](lst, 0, np.float32(7.0))
    np.testing.assert_allclose(np.asarray(fn(lst, 0)), 7.0)


def _f_size(fn):
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    lst = OP_TABLE["create_list"](size=4)
    assert int(fn(lst)) == 4


def _f_stack(fn):
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    lst = OP_TABLE["create_list"]()
    for i in range(3):
        OP_TABLE["write_list"](lst, i, np.full(2, i, np.float32))
    np.testing.assert_allclose(np.asarray(fn(lst)),
                               [[0, 0], [1, 1], [2, 2]])


def _f_unstack(fn):
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    lst = OP_TABLE["create_list"]()
    x = np.asarray([[1.0], [2.0]], np.float32)
    fn(lst, x)
    np.testing.assert_allclose(np.asarray(lst.arrays[1]), [2.0])


def _f_gather(fn):
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    lst = OP_TABLE["create_list"]()
    for i in range(4):
        OP_TABLE["write_list"](lst, i, np.full(2, i, np.float32))
    got = np.asarray(fn(lst, np.asarray([3, 1], np.int32)))
    np.testing.assert_allclose(got, [[3, 3], [1, 1]])


def _f_scatter(fn):
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    lst = OP_TABLE["create_list"]()
    fn(lst, np.asarray([1, 0], np.int32),
       np.asarray([[5.0], [6.0]], np.float32))
    np.testing.assert_allclose(np.asarray(lst.arrays[0]), [6.0])
    np.testing.assert_allclose(np.asarray(lst.arrays[1]), [5.0])


def _f_split(fn):
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    lst = OP_TABLE["create_list"]()
    x = np.arange(6, dtype=np.float32).reshape(6, 1)
    fn(lst, x, np.asarray([2, 4], np.int32))
    assert len(lst) == 2
    np.testing.assert_allclose(np.asarray(lst.arrays[1]),
                               x[2:])


def _f_pick(fn):
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    lst = OP_TABLE["create_list"]()
    for i in range(3):
        OP_TABLE["write_list"](
            lst, i, np.full((1, 2), i, np.float32))
    got = np.asarray(fn(lst, np.asarray([2, 0], np.int32)))
    np.testing.assert_allclose(got, [[2, 2], [0, 0]])


def _f_tear(fn):
    lst = fn(np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32), axis=1)
    assert len(lst) == 2
    np.testing.assert_allclose(np.asarray(lst.arrays[0]), [1.0, 3.0])


CASES += [
    _list_flow("create_list", _f_create),
    _list_flow("write_list", _f_write_read),
    _list_flow("read_list", _f_read),
    _list_flow("size_list", _f_size),
    _list_flow("stack_list", _f_stack),
    _list_flow("unstack_list", _f_unstack),
    _list_flow("gather_list", _f_gather),
    _list_flow("scatter_list", _f_scatter),
    _list_flow("split_list", _f_split),
    _list_flow("pick_list", _f_pick),
    _list_flow("tear", _f_tear),
]

# ---- word2vec training ops (loss-decreases property) ----


def _skipgram_custom(fn):
    s0 = rs.uniform(-0.1, 0.1, (20, 8)).astype(np.float32)
    s1 = rs.uniform(-0.1, 0.1, (20, 8)).astype(np.float32)
    centers = np.asarray([1, 2, 3], np.int32)
    contexts = np.asarray([4, 5, 6], np.int32)
    negs = np.asarray([[7, 8], [9, 10], [11, 12]], np.int32)
    n0, n1, loss0 = fn(s0, s1, centers, contexts, negs, lr=0.5)
    _, _, loss1 = fn(np.asarray(n0), np.asarray(n1), centers, contexts,
                     negs, lr=0.5)
    assert float(loss1) < float(loss0)
    assert np.asarray(n0).shape == s0.shape


def _cbow_custom(fn):
    s0 = rs.uniform(-0.1, 0.1, (20, 8)).astype(np.float32)
    s1 = rs.uniform(-0.1, 0.1, (20, 8)).astype(np.float32)
    ctx = np.asarray([[1, 2, 0], [3, 4, 5]], np.int32)
    cmask = np.asarray([[1, 1, 0], [1, 1, 1]], np.float32)
    centers = np.asarray([6, 7], np.int32)
    negs = np.asarray([[8, 9], [10, 11]], np.int32)
    n0, n1, loss0 = fn(s0, s1, ctx, cmask, centers, negs, lr=0.5)
    _, _, loss1 = fn(np.asarray(n0), np.asarray(n1), ctx, cmask, centers,
                     negs, lr=0.5)
    assert float(loss1) < float(loss0)


CASES += [
    C("skipgram", custom=_skipgram_custom),
    C("cbow", custom=_cbow_custom),
]

# ---- barnes-hut t-SNE helpers ----


def _barnes_sym_custom(fn):
    from scipy.sparse import csr_matrix
    rp = np.asarray([0, 2, 3, 4], np.int64)
    cp = np.asarray([1, 2, 0, 1], np.int64)
    vp = np.asarray([0.5, 0.3, 0.2, 0.4], np.float64)
    outp, outc, outv = fn(rp, cp, vp, 3)
    got = csr_matrix((np.asarray(outv), np.asarray(outc),
                      np.asarray(outp)), shape=(3, 3)).toarray()
    m = csr_matrix((vp, cp, rp), shape=(3, 3))
    want = ((m + m.T) * 0.5).toarray()
    np.testing.assert_allclose(got, want, atol=1e-6)


def _barnes_edge_custom(fn):
    rp = np.asarray([0, 1, 2], np.int64)
    cp = np.asarray([1, 0], np.int64)
    vp = np.asarray([0.6, 0.6], np.float64)
    y = np.asarray([[0.0, 0.0], [1.0, 1.0]], np.float32)
    out = np.asarray(fn(rp, cp, vp, y))
    d = y[0] - y[1]
    q = 1.0 / (1.0 + np.sum(d * d))
    np.testing.assert_allclose(out[0], 0.6 * q * d, atol=1e-6)
    np.testing.assert_allclose(out[1], -0.6 * q * d, atol=1e-6)


CASES += [
    C("barnes_gains", FP(5), F(5), F(5),
      g=lambda gains, grad, step: np.maximum(
          np.where(np.sign(grad) == np.sign(step), gains * 0.8,
                   gains + 0.2), 0.01)),
    C("barnes_symmetrize", jit=False, custom=_barnes_sym_custom),
    C("barnes_edge_forces", jit=False, custom=_barnes_edge_custom),
]

# ---- host-side / passthrough / assert ----


def _assert_equal_custom(fn):
    a = np.asarray([1.0, 2.0], np.float32)
    np.testing.assert_allclose(np.asarray(fn(a, a.copy())), a)
    try:
        fn(a, a + 1.0)
    except ValueError:
        return
    raise AssertionError("assert_equal did not raise on mismatch")


def _eig_custom(fn):
    a = np.asarray(rs.uniform(-1, 1, (4, 4)), np.float32)
    w, v = fn(a)
    w, v = np.asarray(w), np.asarray(v)
    np.testing.assert_allclose(a.astype(complex) @ v, v * w[None, :],
                               atol=1e-4)


def _choose_custom(fn):
    x = np.asarray([1.0, 5.0, 3.0, 0.5], np.float32)
    vals, cnt = fn(x, 2.0, mode=2)   # mode 2: '>'
    np.testing.assert_allclose(np.asarray(vals), [5.0, 3.0])
    assert int(cnt) == 2


def _hashcode_custom(fn):
    x = F(4, 5)
    a, b = fn(x), fn(x.copy())
    assert int(a) == int(b)
    assert int(fn(x + 1.0)) != int(a)


CASES += [
    C("assert_equal", jit=False, custom=_assert_equal_custom),
    C("print_variable", np.asarray([1.0], np.float32),
      g=lambda x, message="": x, jit=False, kw={"message": "v="}),
    C("eig", jit=False, custom=_eig_custom),
    C("choose", jit=False, custom=_choose_custom),
    C("hashcode", jit=False, custom=_hashcode_custom),
    C("broadcast_dynamic_shape", np.asarray([3, 1], np.int32),
      np.asarray([1, 4], np.int32),
      g=lambda a, b: np.asarray([3, 4], np.int32), jit=False),
    C("broadcast_gradient_args", np.asarray([3, 1], np.int32),
      np.asarray([3, 4], np.int32),
      g=lambda a, b: (np.asarray([1], np.int32),
                      np.asarray([], np.int32)), jit=False),
]

# ---- onnx/tf layout helpers ----
CASES += [
    C("reshape_onnx", F(2, 3, 4), (0, -1),
      g=lambda x, s: x.reshape(2, 12)),
    C("flatten2d", F(2, 3, 4), g=lambda x, axis=1: x.reshape(2, 12)),
    C("slice_onnx", F(4, 6), (1, 0), (3, 5),
      kw={"axes": (0, 1), "steps": (1, 2)},
      g=lambda x, st, en, axes=None, steps=None: x[1:3, 0:5:2]),
    C("tf_strided_slice", F(4, 6), (1, 0), (3, 6), (1, 2),
      g=lambda x, b, e, s, **kw: x[1:3, 0:6:2]),
    C("tf_strided_slice", F(4, 6), (1, 1), (3, 3), (1, 1),
      kw={"shrink_axis_mask": 2},
      g=lambda x, b, e, s, **kw: x[1:3, 1], tag="shrink"),
]
