"""Composed dp x tp x pp parallelism tests on the 8-way virtual CPU mesh
(conftest.py): the ONE-step 3D-parallel transformer stack — GPipe over
'pipe', Megatron sequence-parallel TP + ring attention over 'model',
batch sharding over 'data' — must match the single-device oracle in both
forward values and training trajectory (reference composed story:
SharedTrainingMaster + ParallelWrapper, SURVEY.md §3.4)."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.composed import (composed_apply,
                                                  composed_oracle,
                                                  composed_train_step,
                                                  init_stage_params)
from deeplearning4j_tpu.parallel.mesh import make_mesh

S, D, H, FF, B, T = 2, 8, 2, 16, 8, 8


def _mesh3d():
    return make_mesh({"data": 2, "model": 2, "pipe": 2},
                     jax.devices()[:8])


def _inputs(seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(B, T, D).astype(np.float32) * 0.5)
    y = jnp.asarray(rng.randn(B, T, D).astype(np.float32) * 0.5)
    return x, y


def test_composed_forward_matches_oracle():
    mesh = _mesh3d()
    params = init_stage_params(np.random.RandomState(7), S, D, H, FF)
    x, _ = _inputs()
    want = composed_oracle(params, x, H)
    got = composed_apply(params, x, mesh, H)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_composed_training_matches_oracle_trajectory():
    """Two SGD steps through the full 3D-parallel step equal the
    single-device trajectory — grads flow correctly through ppermute
    (pipe), ring ppermute + all_gather + psum_scatter (model), and the
    data-parallel mean."""
    mesh = _mesh3d()
    params = init_stage_params(np.random.RandomState(7), S, D, H, FF)
    x, y = _inputs()
    step = composed_train_step(mesh, H, lr=0.2)

    @jax.jit
    def oracle_step(p):
        def loss_fn(pp):
            out = composed_oracle(pp, x, H)
            return jnp.mean((out - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.2 * b, p, g), loss

    p_sharded, p_oracle = params, params
    for i in range(2):
        p_sharded, loss_s = step(p_sharded, x, y)
        p_oracle, loss_o = oracle_step(p_oracle)
        assert np.isfinite(float(loss_s))
        np.testing.assert_allclose(float(loss_s), float(loss_o),
                                   rtol=1e-4,
                                   err_msg=f"loss diverged at step {i}")
    for k in p_sharded:
        np.testing.assert_allclose(
            np.asarray(p_sharded[k]), np.asarray(p_oracle[k]),
            rtol=1e-3, atol=1e-4, err_msg=f"param {k} after 2 steps")
    # training reduced the loss
    _, loss_final = step(p_sharded, x, y)
    assert float(loss_final) < float(loss_s)


def test_composed_more_microbatches():
    mesh = _mesh3d()
    params = init_stage_params(np.random.RandomState(3), S, D, H, FF)
    x, _ = _inputs(2)
    want = composed_oracle(params, x, H)
    got = composed_apply(params, x, mesh, H, num_microbatches=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_composed_remat_matches_no_remat():
    """jax.checkpoint around the per-tick block must not change the
    training math — same grads, recomputed instead of stored."""
    mesh = _mesh3d()
    params = init_stage_params(np.random.RandomState(11), S, D, H, FF)
    x, y = _inputs(5)
    p1, l1 = composed_train_step(mesh, H, lr=0.2)(params, x, y)
    p2, l2 = composed_train_step(mesh, H, lr=0.2, remat=True)(params, x, y)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_composed_fused_steps_match_sequential():
    """composed_train_steps (k 3D-parallel steps in one scan dispatch)
    equals k sequential composed_train_step calls bit-for-bit."""
    from deeplearning4j_tpu.parallel.composed import composed_train_steps

    mesh = _mesh3d()
    params = init_stage_params(np.random.RandomState(7), S, D, H, FF)
    x, y = _inputs()
    k = 3
    xs = jnp.broadcast_to(x, (k,) + x.shape)
    ys = jnp.broadcast_to(y, (k,) + y.shape)

    step = composed_train_step(mesh, H, lr=0.2)
    p_seq = params
    for _ in range(k):
        p_seq, loss_seq = step(p_seq, x, y)

    p_fused, losses = composed_train_steps(mesh, H, lr=0.2)(params, xs, ys)
    assert losses.shape == (k,)
    assert np.isclose(float(losses[-1]), float(loss_seq), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p_seq),
                    jax.tree_util.tree_leaves(p_fused)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
