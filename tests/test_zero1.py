"""ZeRO-1 sharded weight update (parallel/zero.py, arXiv:2004.13336).

Parity is the whole game: `with_sharding_constraint` is value-preserving,
so `optimizer_sharding(True)` must match the replicated update — to float
tolerance for Adam, bitwise for Sgd — under the plain step, the fused
`fit_steps` scan, TP rules, and non-divisible (padded) leaves.  Plus the
observability (`training_opt_state_bytes` gauge) and the sync-free-loop
invariant (zero per-step host transfers)."""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.monitor.registry import registry
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel import (ParallelWrapper, ShardingRules,
                                         make_mesh, zero)
from deeplearning4j_tpu.train.updaters import (Adam, NoOp, Sgd,
                                               tree_map_like_params)


def _net(seed=7, n_in=8, updater=None):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater or Adam(1e-2))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent", activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=32, n_in=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, n_in).astype(np.float32)
    labels = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)
    y = np.eye(3, dtype=np.float32)[labels]
    return x, y


def _mesh4():
    return make_mesh({"data": 4}, jax.devices()[:4])


def _assert_params_close(a, b, rtol=1e-5, atol=1e-6, exact=False):
    def cmp(x, y):
        x, y = np.asarray(x), np.asarray(y)
        if exact:
            np.testing.assert_array_equal(x, y)
        else:
            np.testing.assert_allclose(x, y, rtol=rtol, atol=atol)
    jax.tree_util.tree_map(cmp, a.params_, b.params_)


# ---------------------------------------------------------------------------
# Parity (acceptance criterion)
# ---------------------------------------------------------------------------

def test_zero1_adam_parity_10_steps():
    """4-way mesh, 10 Adam steps: sharded update == replicated update."""
    x, y = _data()
    ref = _net()
    pw_ref = ParallelWrapper(ref, _mesh4())
    z = _net()
    pw_z = ParallelWrapper(z, _mesh4(), optimizer_sharding=True)
    for _ in range(10):
        pw_ref.fit(x, y)
        pw_z.fit(x, y)
    _assert_params_close(ref, z)


def test_zero1_sgd_parity_bitwise():
    """Sgd has no state and an order-preserving update chain — the sharded
    path must be BITWISE identical to the replicated one."""
    x, y = _data()
    ref = _net(updater=Sgd(1e-1))
    pw_ref = ParallelWrapper(ref, _mesh4())
    z = _net(updater=Sgd(1e-1))
    pw_z = ParallelWrapper(z, _mesh4(), optimizer_sharding=True)
    for _ in range(10):
        pw_ref.fit(x, y)
        pw_z.fit(x, y)
    _assert_params_close(ref, z, exact=True)


def test_zero1_fit_steps_fused_scan_parity():
    """The reduce-scatter/step/all-gather must live INSIDE the scan body:
    a [k, batch, ...] fused block matches the replicated fused block."""
    rng = np.random.RandomState(1)
    xs = rng.randn(6, 32, 8).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.randint(0, 3, (6, 32))]
    ref = _net()
    pw_ref = ParallelWrapper(ref, _mesh4())
    z = _net()
    pw_z = ParallelWrapper(z, _mesh4(), optimizer_sharding=True)
    l_ref = pw_ref.fit_steps(xs, ys)
    l_z = pw_z.fit_steps(xs, ys)
    np.testing.assert_allclose(np.asarray(l_ref), np.asarray(l_z),
                               rtol=1e-5, atol=1e-6)
    _assert_params_close(ref, z)
    assert ref.iteration == z.iteration == 6


def test_zero1_with_tp_rule_parity_and_precedence():
    """TP rules win per-leaf: on a 2x2 mesh with layer_0/W tensor-parallel,
    parity must hold AND layer_0's moments keep the TP spec while
    layer_1's moments pick up the data-axis ZeRO sharding."""
    devs = jax.devices()[:4]
    rules = (ShardingRules().add(r"layer_0/W", P(None, "model"))
             .add(r".*", P()))
    x, y = _data()
    ref = _net()
    pw_ref = ParallelWrapper(ref, make_mesh({"data": 2, "model": 2}, devs),
                             sharding_rules=rules)
    z = _net()
    pw_z = ParallelWrapper(z, make_mesh({"data": 2, "model": 2}, devs),
                           sharding_rules=rules, optimizer_sharding=True)
    for _ in range(10):
        pw_ref.fit(x, y)
        pw_z.fit(x, y)
    _assert_params_close(ref, z)
    assert z.opt_state_["layer_0"]["m"]["W"].sharding.spec == \
        P(None, "model")
    assert z.opt_state_["layer_1"]["m"]["W"].sharding.spec == P("data")


def test_zero1_padded_leaf_parity_and_layout():
    """n_in=10 on a 4-way mesh: W (10,16) pads to (12,16).  Parity must
    hold; the moment is stored padded+sharded, the param at its true
    shape (replicated — uneven device layouts don't materialize)."""
    x, y = _data(n_in=10)
    ref = _net(n_in=10)
    pw_ref = ParallelWrapper(ref, _mesh4())
    z = _net(n_in=10)
    pw_z = ParallelWrapper(z, _mesh4(), optimizer_sharding=True)
    for _ in range(10):
        pw_ref.fit(x, y)
        pw_z.fit(x, y)
    _assert_params_close(ref, z)
    mom = z.opt_state_["layer_0"]["m"]["W"]
    assert mom.shape == (12, 16)
    assert mom.sharding.spec == P("data")
    assert z.params_["layer_0"]["W"].shape == (10, 16)
    # the pad region is a fixed point (zero grads -> zero moments)
    assert np.all(np.asarray(mom)[10:] == 0.0)


def test_zero1_disable_unpads_and_matches():
    """optimizer_sharding(False) restores true-shape moments and keeps
    training on the replicated path from the same trajectory."""
    x, y = _data(n_in=10)
    z = _net(n_in=10)
    pw = ParallelWrapper(z, _mesh4(), optimizer_sharding=True)
    pw.fit(x, y)
    pw.optimizer_sharding(False)
    pw.fit(x, y)
    assert z.opt_state_["layer_0"]["m"]["W"].shape == (10, 16)
    assert z._step_transform is None

    ref = _net(n_in=10)
    pw_ref = ParallelWrapper(ref, _mesh4())
    pw_ref.fit(x, y)
    pw_ref.fit(x, y)
    _assert_params_close(ref, z)


# ---------------------------------------------------------------------------
# Observability + memory proof (acceptance criterion)
# ---------------------------------------------------------------------------

def test_opt_state_bytes_gauge_shows_reduction():
    """The training_opt_state_bytes{sharded=} gauge pair must show the ~N×
    per-replica saving (this net on a 4-way mesh: W moments shard 4-way,
    only the 3-wide output bias replicates → ratio ≈ 3.8)."""
    x, y = _data()
    ref = _net()
    ParallelWrapper(ref, _mesh4()).fit(x, y)
    z = _net()
    ParallelWrapper(z, _mesh4(), optimizer_sharding=True).fit(x, y)
    repl = registry().get("training_opt_state_bytes", {"sharded": "false"})
    shard = registry().get("training_opt_state_bytes", {"sharded": "true"})
    assert repl is not None and shard is not None
    assert repl.value > 0 and shard.value > 0
    assert shard.value < repl.value / 2.5, \
        f"expected ~4x reduction, got {repl.value}/{shard.value}"


def test_opt_state_bytes_per_replica_counts_shards_once():
    mesh = _mesh4()
    from jax.sharding import NamedSharding
    repl = jax.device_put(np.zeros((8, 4), np.float32),
                          NamedSharding(mesh, P()))
    shd = jax.device_put(np.zeros((8, 4), np.float32),
                         NamedSharding(mesh, P("data")))
    assert zero.opt_state_bytes_per_replica({"a": repl}) == 8 * 4 * 4
    assert zero.opt_state_bytes_per_replica({"a": shd}) == 8 * 4 * 4 // 4


def test_zero1_no_per_step_host_transfers():
    """Transfer-guard proof: after warmup, the sharded step dispatches with
    ZERO fresh host->device transfers (the gather/scatter are device-side
    collectives, the iteration counter is device-resident)."""
    from deeplearning4j_tpu.utils import counters
    x, y = _data()
    z = _net()
    pw = ParallelWrapper(z, _mesh4(), optimizer_sharding=True)
    pw.fit(x, y)                       # warmup: compile + counter upload
    x_dev = pw.sharded_placement()(x)
    y_dev = pw.sharded_placement()(y)
    pw.fit(x_dev, y_dev)               # second warmup on device-resident args
    uploads_before = counters.counter_uploads.value
    with jax.transfer_guard("disallow"):
        for _ in range(3):
            pw.fit(x_dev, y_dev)
    assert counters.counter_uploads.value == uploads_before


# ---------------------------------------------------------------------------
# SameDiff + ComputationGraph step builders
# ---------------------------------------------------------------------------

def _mlp_sd():
    from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
    sd = SameDiff.create()
    x = sd.placeholder("input", shape=(-1, 4))
    y = sd.placeholder("label", shape=(-1, 3))
    w0 = sd.var("w0", "XAVIER", 4, 16)
    b0 = sd.var("b0", np.zeros(16, np.float32))
    w1 = sd.var("w1", "XAVIER", 16, 3)
    b1 = sd.var("b1", np.zeros(3, np.float32))
    h = sd.nn.tanh(sd.nn.linear(x, w0, b0))
    logits = sd.nn.linear(h, w1, b1, name="logits")
    sd.loss.softmax_cross_entropy(y, logits, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(
        updater=Adam(1e-2),
        data_set_feature_mapping=["input"],
        data_set_label_mapping=["label"]))
    return sd


def test_zero1_samediff_parity():
    x, y = _data(n=32, n_in=4)
    ref, z = _mlp_sd(), _mlp_sd()
    mesh = _mesh4()
    zt = zero.enable_zero1(z, mesh)
    assert z._step_transform is zt
    with mesh:
        for _ in range(10):
            ref.fit(x, y)
            z.fit(x, y)
    for k in ref.variables_:
        np.testing.assert_allclose(np.asarray(ref.variables_[k]),
                                   np.asarray(z.variables_[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    # w0 (4,16) and w1/b0 (16,...) shard over the 4-way axis; b1 (3,) can't
    assert z.opt_state_["m"]["w0"].sharding.spec == P("data")
    assert z.opt_state_["m"]["b1"].sharding.spec == P()


def test_zero1_computation_graph_parity():
    from deeplearning4j_tpu.data.dataset import MultiDataSet
    from deeplearning4j_tpu.nn import (ComputationGraph, GraphBuilder,
                                       MergeVertex)

    def build():
        conf = (GraphBuilder().seed(5).updater(Adam(1e-2))
                .add_inputs("a", "b")
                .set_input_types(InputType.feed_forward(4),
                                 InputType.feed_forward(6))
                .add_layer("da", DenseLayer(n_out=5, activation="tanh"), "a")
                .add_layer("db", DenseLayer(n_out=7, activation="tanh"), "b")
                .add_vertex("m", MergeVertex(), "da", "db")
                .add_layer("out", OutputLayer(n_out=2, loss="mcxent",
                                              activation="softmax"), "m")
                .set_outputs("out").build())
        return ComputationGraph(conf).init()

    rng = np.random.RandomState(3)
    a = rng.randn(16, 4).astype(np.float32)
    b = rng.randn(16, 6).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
    mds = MultiDataSet([a, b], [y])

    ref = build()
    pw_ref = ParallelWrapper(ref, _mesh4())
    z = build()
    pw_z = ParallelWrapper(z, _mesh4(), optimizer_sharding=True)
    for _ in range(6):
        pw_ref.fit(mds)
        pw_z.fit(mds)
    _assert_params_close(ref, z)


# ---------------------------------------------------------------------------
# _shard_opt_state_like structural matching (satellite)
# ---------------------------------------------------------------------------

def _leaf_shardings(tree):
    return [leaf.sharding.spec for leaf in jax.tree_util.tree_leaves(tree)]


def test_shard_opt_state_like_per_layer_layout():
    """{layer: {"m": ..., "v": ...}} moments follow each param leaf's
    sharding leaf-by-leaf."""
    from deeplearning4j_tpu.parallel.wrapper import _shard_opt_state_like
    from jax.sharding import NamedSharding
    mesh = make_mesh({"data": 2, "model": 2}, jax.devices()[:4])
    params = {"layer_0": {
        "W": jax.device_put(np.zeros((4, 8), np.float32),
                            NamedSharding(mesh, P(None, "model"))),
        "b": jax.device_put(np.zeros(8, np.float32),
                            NamedSharding(mesh, P()))}}
    upd = Adam(1e-3)
    opt = {"layer_0": upd.init_state(params["layer_0"])}
    placed = _shard_opt_state_like(opt, params, mesh)
    for mom in ("m", "v"):
        assert placed["layer_0"][mom]["W"].sharding.spec == P(None, "model")
        assert placed["layer_0"][mom]["b"].sharding.spec == P()


def test_shard_opt_state_like_flat_layout():
    """{"m": params, "v": params} (flat updaters, the SameDiff layout)."""
    from deeplearning4j_tpu.parallel.wrapper import _shard_opt_state_like
    from jax.sharding import NamedSharding
    mesh = make_mesh({"data": 2, "model": 2}, jax.devices()[:4])
    params = {
        "w0": jax.device_put(np.zeros((4, 8), np.float32),
                             NamedSharding(mesh, P(None, "model"))),
        "b0": jax.device_put(np.zeros(8, np.float32),
                             NamedSharding(mesh, P()))}
    opt = Adam(1e-3).init_state(params)
    placed = _shard_opt_state_like(opt, params, mesh)
    assert placed["m"]["w0"].sharding.spec == P(None, "model")
    assert placed["v"]["w0"].sharding.spec == P(None, "model")
    assert placed["m"]["b0"].sharding.spec == P()


def test_shard_opt_state_like_scalars_and_empty_states():
    """Scalar step counts replicate; empty Sgd/NoOp states pass through
    without inventing leaves."""
    from deeplearning4j_tpu.parallel.wrapper import _shard_opt_state_like
    from jax.sharding import NamedSharding
    mesh = _mesh4()
    params = {"layer_0": {"W": jax.device_put(
        np.zeros((4, 8), np.float32), NamedSharding(mesh, P()))}}
    opt = {"layer_0": {"m": {"W": np.zeros((4, 8), np.float32)},
                       "step": np.float32(3.0)}}
    placed = _shard_opt_state_like(opt, params, mesh)
    assert placed["layer_0"]["m"]["W"].sharding.spec == P()
    assert placed["layer_0"]["step"].sharding.spec == P()
    assert float(placed["layer_0"]["step"]) == 3.0

    for upd in (Sgd(1e-1), NoOp()):
        empty = {"layer_0": upd.init_state(params["layer_0"])}
        placed = _shard_opt_state_like(empty, params, mesh)
        assert placed == {"layer_0": ()}


def test_tree_map_like_params_shape_of_override():
    """The shared matcher honors a custom shape_of (how zero.py matches
    padded moments against LeafPlan.padded_shape)."""
    state = {"m": {"W": np.zeros((12, 16))}}
    plans = {"W": zero.LeafPlan("shard", (10, 16), 2, P(), P("data"), P())}
    hits = []
    tree_map_like_params(
        lambda s, p: hits.append(True) or s, state, plans,
        lambda s: s, shape_of=lambda pl: pl.padded_shape)
    assert hits == [True]


# ---------------------------------------------------------------------------
# Partial final batch (satellite)
# ---------------------------------------------------------------------------

def test_iterator_partial_final_batch_pads_exactly():
    """Batches 32,32,20 on an 8-way mesh: the 20-row tail is padded with
    repeated rows + a zero labels-mask — must match single-device training
    on the raw (unpadded) batches exactly (masked loss mean)."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    x, y = _data(n=84, seed=2)
    splits = [(0, 32), (32, 64), (64, 84)]
    batches = [DataSet(x[a:b], y[a:b]) for a, b in splits]

    ref = _net(seed=3)
    for a, b in splits:
        # single-device reference with the same masking the padded path
        # uses on the full batches (mask of ones == unmasked mean)
        ref.fit(x[a:b], y[a:b])

    z = _net(seed=3)
    pw = ParallelWrapper(z, make_mesh({"data": 8}, jax.devices()))
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        pw.fit(ListDataSetIterator(batches))
    assert not [w for w in rec if "dropping final partial" in str(w.message)]
    assert z.iteration == 3
    _assert_params_close(ref, z, rtol=1e-5, atol=1e-6)


def test_iterator_partial_batch_drop_warns_once():
    """Rank-3 labels without a labels mask can't be mask-padded: the tail
    batch is dropped with ONE warning across epochs."""
    from deeplearning4j_tpu.parallel.wrapper import _pad_partial_lists
    assert _pad_partial_lists([np.zeros((3, 4))],
                              [np.zeros((3, 2, 5))], None, 1) is None

    class DS:
        def __init__(self, n):
            self.features = np.zeros((n, 4), np.float32)
            self.labels = np.zeros((n, 2, 5), np.float32)
            self.labels_mask = None
            self.features_mask = None
    net = _net()
    pw = ParallelWrapper(net, _mesh4())
    with pytest.warns(UserWarning, match="dropping final partial batch"):
        pw.fit(iter([DS(3)]))
    assert net.iteration == 0
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")  # second epoch: silent
        pw.fit(iter([DS(3)]))
    assert not [w for w in rec if "dropping final partial" in str(w.message)]


def test_direct_fit_still_raises_on_indivisible_batch():
    """fit(x, y) (no iterator) keeps the explicit error — padding is an
    iterator-epoch affordance, not a silent batch rewrite."""
    net = _net()
    pw = ParallelWrapper(net, _mesh4())
    x, y = _data(n=30)
    with pytest.raises(ValueError, match="divisible"):
        pw.fit(x, y)


# ---------------------------------------------------------------------------
# Replica skew (satellite)
# ---------------------------------------------------------------------------

def test_measure_replica_skew_parallel_polling():
    x, y = _data()
    net = _net()
    pw = ParallelWrapper(net, _mesh4())
    pw.fit(x, y)
    skew = pw.measure_replica_skew()
    assert skew >= 0.0
    g = registry().get("parallel_replica_skew_ms")
    assert g is not None and g.value == skew
