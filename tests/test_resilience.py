"""Fault-tolerant training: checkpoint manager, auto-resume, preemption,
divergence guard, and the chaos harness (ISSUE 5).

The acceptance-critical tests kill a real training subprocess (SIGTERM and
SIGKILL) partway and assert the relaunched run's final params are BITWISE
identical to an uninterrupted run — including ZeRO-1 sharded optimizer
state through the resharding loader.  Corruption tests damage committed
checkpoints with `utils.chaos` and assert restore falls back to the
newest intact one.
"""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.data import (ArrayDataSetIterator,
                                     DevicePrefetchIterator, ProducerError)
from deeplearning4j_tpu.data.normalizers import NormalizerStandardize
from deeplearning4j_tpu.monitor.registry import registry
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh
from deeplearning4j_tpu.parallel.checkpoint import (ChecksumError,
                                                    verify_checkpoint)
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.train.resilience import (CheckpointManager,
                                                 DivergenceError,
                                                 DivergenceGuard,
                                                 FaultTolerantTrainer,
                                                 NoIntactCheckpointError,
                                                 Preempted,
                                                 normalizer_from_meta)
from deeplearning4j_tpu.utils import chaos

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
WORKER_ENV = dict(os.environ, JAX_PLATFORMS="cpu",
                  XLA_FLAGS="--xla_force_host_platform_device_count=8",
                  JAX_ENABLE_X64="1", PYTHONPATH=REPO)

rng0 = np.random.default_rng(0)
X = rng0.standard_normal((48, 10))
Y = np.eye(3)[rng0.integers(0, 3, 48)]


def build_net():
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01))
            .list([DenseLayer(n_out=16, activation="tanh"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(10)).build())
    return MultiLayerNetwork(conf).init()


def data_iter(features=None):
    return ArrayDataSetIterator(X if features is None else features, Y, 8)


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------

def test_manager_save_steps_latest_and_metadata(tmp_path):
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=10)
    assert mgr.latest_step() is None and mgr.steps() == []
    mgr.save(net, step=3, metadata={"note": "a"})
    mgr.save(net, step=11)
    assert mgr.steps() == [3, 11]
    assert mgr.latest_step() == 11
    meta = mgr.restore(build_net())
    assert meta["step"] == 11
    # per-chunk checksums landed in the index
    with open(os.path.join(mgr.checkpoint_path(11), "index-0.json")) as f:
        idx = json.load(f)
    assert idx and all("crc32" in e for e in idx)


def test_manager_retention_gc(tmp_path):
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=2)
    for s in (1, 2, 3, 4, 5):
        mgr.save(net, step=s)
    assert mgr.steps() == [4, 5]           # older committed dirs collected
    assert not os.path.exists(mgr.checkpoint_path(1))


def test_manager_gc_spares_uncommitted_head(tmp_path):
    """GC must never delete a newer uncommitted dir (another rank / the
    async writer may still be mid-save), but torn older dirs go."""
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=2)
    mgr.save(net, step=5)
    mgr.save(net, step=6)
    head = mgr.checkpoint_path(99)
    os.makedirs(head)                      # in-flight save, no manifest
    stale = mgr.checkpoint_path(1)
    os.makedirs(stale)                     # torn leftover from a crash
    mgr.gc()
    assert os.path.isdir(head)
    assert not os.path.exists(stale)
    assert mgr.steps() == [5, 6]


def test_maybe_save_step_and_time_triggers(tmp_path):
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "a"), save_every_steps=3)
    net.iteration = 2
    assert not mgr.maybe_save(net)
    net.iteration = 3
    assert mgr.maybe_save(net)
    assert not mgr.maybe_save(net)         # delta resets after a save
    timed = CheckpointManager(str(tmp_path / "b"), save_every_seconds=0.01)
    time.sleep(0.05)
    assert timed.maybe_save(net)


def test_async_save_matches_sync(tmp_path):
    net = build_net()
    FaultTolerantTrainer(net, None, save_initial=False).fit(
        data_iter(), epochs=1)
    sync = CheckpointManager(str(tmp_path / "s"))
    sync.save(net, step=6)
    a = CheckpointManager(str(tmp_path / "a"), async_save=True)
    a.save(net, step=6)
    a.wait()                               # background write committed
    assert a.steps() == [6]
    n1, n2 = build_net(), build_net()
    sync.restore(n1)
    a.restore(n2)
    np.testing.assert_array_equal(np.asarray(n1.params()),
                                  np.asarray(n2.params()))
    assert n1.iteration == n2.iteration == net.iteration


def test_restore_falls_back_to_newest_intact(tmp_path):
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=10,
                            save_every_steps=2)
    FaultTolerantTrainer(net, mgr).fit(data_iter(), epochs=1)
    steps = mgr.steps()
    assert len(steps) >= 3
    before = registry().counter("resilience_restore_fallbacks_total").value
    chaos.corrupt_checkpoint(mgr.checkpoint_path(steps[-1]), "payload")
    chaos.corrupt_checkpoint(mgr.checkpoint_path(steps[-2]), "manifest")
    meta = mgr.restore(build_net())
    assert meta["step"] == steps[-3]
    assert registry().counter(
        "resilience_restore_fallbacks_total").value >= before + 2


def test_restore_skips_uncommitted_latest(tmp_path):
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=10,
                            save_every_steps=3)
    FaultTolerantTrainer(net, mgr).fit(data_iter(), epochs=1)
    steps = mgr.steps()
    chaos.corrupt_checkpoint(mgr.checkpoint_path(steps[-1]), "uncommit")
    assert mgr.steps() == steps[:-1]       # no manifest -> not committed
    assert mgr.restore(build_net())["step"] == steps[-2]


def test_restore_all_corrupt_raises(tmp_path):
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=10,
                            save_every_steps=3)
    FaultTolerantTrainer(net, mgr).fit(data_iter(), epochs=1)
    for s in mgr.steps():                  # corrupt each exactly once
        chaos.corrupt_checkpoint(mgr.checkpoint_path(s), "payload")
    with pytest.raises(NoIntactCheckpointError):
        mgr.restore(build_net())


def test_verify_checkpoint_checksum_error(tmp_path):
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    d = mgr.save(net, step=1)
    verify_checkpoint(d)                   # intact -> no raise
    chaos.corrupt_checkpoint(d, "payload")
    # the byte flip surfaces either as our per-chunk ChecksumError or as
    # the npz zip layer's own CRC failure — both are ValueError and both
    # mean "this checkpoint is rotten"
    with pytest.raises(ValueError,
                       match="checksum mismatch|unreadable checkpoint"):
        verify_checkpoint(d)


def test_restore_recovers_full_state(tmp_path):
    nz = NormalizerStandardize()
    nz.fit(data_iter())
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    FaultTolerantTrainer(net, mgr, normalizer=nz).fit(data_iter(), epochs=2)
    mgr.save(net, normalizer=nz)
    net2 = build_net()
    net2._rng = jax.random.PRNGKey(999)    # must be overwritten
    meta = mgr.restore(net2)
    assert net2.iteration == net.iteration and net2.epoch == net.epoch
    np.testing.assert_array_equal(np.asarray(net2._rng),
                                  np.asarray(net._rng))
    # updater moments came back too
    l1, l2 = (jax.tree_util.tree_leaves(n.opt_state_) for n in (net, net2))
    for a, b in zip(l1, l2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    nz2 = normalizer_from_meta(meta["normalizer"])
    np.testing.assert_array_equal(nz2.mean, nz.mean)
    np.testing.assert_array_equal(nz2.std, nz.std)


# ---------------------------------------------------------------------------
# FaultTolerantTrainer: preemption + resume (in-process)
# ---------------------------------------------------------------------------

def test_sigterm_preempts_and_resume_is_bitwise(tmp_path):
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "ck"), save_every_steps=4)
    ks = chaos.KillSwitch(at_step=7, mode="sigterm",
                          marker=str(tmp_path / "m"))
    with pytest.raises(Preempted) as ei:
        FaultTolerantTrainer(net, mgr, hooks=(ks,)).fit(
            data_iter(), epochs=3)
    assert ei.value.exit_code == 128 + signal.SIGTERM
    assert mgr.latest_step() == 7          # preempt save committed
    # old SIGTERM handler restored after fit unwinds
    assert signal.getsignal(signal.SIGTERM) in (
        signal.SIG_DFL, signal.default_int_handler) or callable(
        signal.getsignal(signal.SIGTERM))
    net2 = build_net()
    tr = FaultTolerantTrainer(
        net2, CheckpointManager(str(tmp_path / "ck"), save_every_steps=4))
    tr.fit(data_iter(), epochs=3)
    assert tr.resumed_from["step"] == 7
    ref = build_net()
    FaultTolerantTrainer(ref, None, save_initial=False).fit(
        data_iter(), epochs=3)
    np.testing.assert_array_equal(np.asarray(net2.params()),
                                  np.asarray(ref.params()))
    assert net2.iteration == ref.iteration == 18


def test_zero1_wrapper_resume_is_bitwise(tmp_path):
    def wrapped():
        net = build_net()
        return net, ParallelWrapper(net, make_mesh(),
                                    optimizer_sharding=True)
    net, pw = wrapped()
    mgr = CheckpointManager(str(tmp_path / "ck"), save_every_steps=4)
    FaultTolerantTrainer(pw, mgr).fit(data_iter(), epochs=1)
    # fresh process simulation: new net + wrapper, auto-resume, continue
    net2, pw2 = wrapped()
    tr = FaultTolerantTrainer(
        pw2, CheckpointManager(str(tmp_path / "ck"), save_every_steps=4))
    tr.fit(data_iter(), epochs=2)
    assert tr.resumed_from is not None
    net3, pw3 = wrapped()
    FaultTolerantTrainer(pw3, None, save_initial=False).fit(
        data_iter(), epochs=2)
    np.testing.assert_array_equal(np.asarray(net2.params()),
                                  np.asarray(net3.params()))


def test_fused_steps_resume_is_bitwise(tmp_path):
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "ck"), save_every_steps=4)
    FaultTolerantTrainer(net, mgr).fit(data_iter(), epochs=1,
                                       fused_steps=2)
    net2 = build_net()
    FaultTolerantTrainer(
        net2,
        CheckpointManager(str(tmp_path / "ck"), save_every_steps=4)).fit(
        data_iter(), epochs=2, fused_steps=2)
    net3 = build_net()
    FaultTolerantTrainer(net3, None, save_initial=False).fit(
        data_iter(), epochs=2, fused_steps=2)
    np.testing.assert_array_equal(np.asarray(net2.params()),
                                  np.asarray(net3.params()))


def test_fused_steps_rejects_guard_and_wrapper(tmp_path):
    net = build_net()
    with pytest.raises(ValueError):
        FaultTolerantTrainer(net, divergence=DivergenceGuard()).fit(
            data_iter(), epochs=1, fused_steps=2)


# ---------------------------------------------------------------------------
# Divergence guard
# ---------------------------------------------------------------------------

def test_divergence_guard_unit():
    g = DivergenceGuard(max_score=10.0, spike_factor=3.0)
    assert g.check(float("nan")) == "nan/inf loss"
    assert g.check(float("inf")) == "nan/inf loss"
    assert "max_score" in g.check(11.0)
    for s in (1.0, 1.1, 0.9, 1.0, 1.05):
        assert g.check(s) is None
    assert "spike" in g.check(5.0)         # 5 > 3x median 1.0, < max_score
    assert g.check(1.2) is None            # healthy scores keep flowing


def test_divergence_skip_policy(tmp_path):
    Xbad = X.copy()
    Xbad[16:24] = np.nan                   # poisons batch 2 of each epoch
    net = build_net()
    g = DivergenceGuard(policy="skip")
    FaultTolerantTrainer(net, CheckpointManager(str(tmp_path / "ck")),
                         divergence=g).fit(data_iter(Xbad), epochs=1)
    assert g.events == 1
    assert np.isfinite(net.score())        # poisoned update was discarded
    assert np.isfinite(np.asarray(net.params())).all()


def test_divergence_rollback_policy(tmp_path):
    Xbad = X.copy()
    Xbad[16:24] = np.nan
    net = build_net()
    g = DivergenceGuard(policy="rollback")
    mgr = CheckpointManager(str(tmp_path / "ck"), save_every_steps=1)
    before = registry().counter("resilience_rollbacks_total").value
    FaultTolerantTrainer(net, mgr, divergence=g).fit(data_iter(Xbad),
                                                     epochs=1)
    assert g.events == 1
    assert registry().counter(
        "resilience_rollbacks_total").value == before + 1
    assert np.isfinite(net.score())
    assert net.iteration == 5              # 6 batches, poisoned one skipped


def test_divergence_max_events_raises(tmp_path):
    Xbad = np.full_like(X, np.nan)
    net = build_net()
    g = DivergenceGuard(policy="skip", max_events=2)
    with pytest.raises(DivergenceError):
        FaultTolerantTrainer(net, None, divergence=g,
                             save_initial=False).fit(data_iter(Xbad),
                                                     epochs=1)
    assert g.events == 3                   # max_events exceeded on the 3rd


def test_grad_norm_precheck_skips_without_stepping(tmp_path):
    net = build_net()
    g = DivergenceGuard(policy="skip", grad_norm_threshold=1e-12)
    FaultTolerantTrainer(net, None, divergence=g, save_initial=False).fit(
        data_iter(), epochs=1)
    assert g.events == 6                   # every batch over the threshold
    assert net.iteration == 0              # flagged BEFORE the step


# ---------------------------------------------------------------------------
# Chaos harness
# ---------------------------------------------------------------------------

def test_killswitch_exception_mode_is_one_shot(tmp_path):
    net = build_net()
    marker = str(tmp_path / "m")
    ks = chaos.KillSwitch(at_step=2, mode="exception", marker=marker)
    with pytest.raises(chaos.ChaosError):
        FaultTolerantTrainer(net, None, hooks=(ks,),
                             save_initial=False).fit(data_iter(), epochs=1)
    assert os.path.exists(marker) and not ks.armed()
    # second run with the same marker does not fire again
    net2 = build_net()
    FaultTolerantTrainer(net2, None, hooks=(ks,), save_initial=False).fit(
        data_iter(), epochs=1)
    assert net2.iteration == 6


def test_flaky_and_slow_iterators():
    flaky = chaos.FlakyIterator(data_iter(), fail_at=2, times=1)
    with pytest.raises(chaos.ChaosError):
        list(flaky)
    flaky.reset()
    assert len(list(flaky)) == 6           # budget exhausted -> clean pass
    slow = chaos.SlowIterator(data_iter(), delay_s=0.002)
    t0 = time.monotonic()
    assert len(list(slow)) == 6
    assert time.monotonic() - t0 >= 0.012


def test_corrupt_checkpoint_counts_faults(tmp_path):
    net = build_net()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=10)
    mgr.save(net, step=1)
    before = registry().counter("chaos_faults_injected_total",
                                labels={"kind": "payload"}).value
    chaos.corrupt_checkpoint(mgr.checkpoint_path(1), "payload")
    assert registry().counter("chaos_faults_injected_total",
                              labels={"kind": "payload"}).value == before + 1


# ---------------------------------------------------------------------------
# Input pipeline: producer failure propagation + retries
# ---------------------------------------------------------------------------

def test_pipeline_producer_error_propagates():
    flaky = chaos.FlakyIterator(data_iter(), fail_at=3, times=1)
    with pytest.raises(ProducerError, match="batch 3"):
        list(DevicePrefetchIterator(flaky))


def test_pipeline_retries_recover_exact_stream():
    flaky = chaos.FlakyIterator(data_iter(), fail_at=3, times=1)
    before = registry().counter("pipeline_producer_retries_total").value
    got = list(DevicePrefetchIterator(flaky, retries=2,
                                      retry_backoff_s=0.001))
    ref = list(DevicePrefetchIterator(data_iter()))
    assert len(got) == len(ref) == 6
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(a.features),
                                      np.asarray(b.features))
    assert registry().counter(
        "pipeline_producer_retries_total").value == before + 1


def test_pipeline_retry_budget_exhausts():
    flaky = chaos.FlakyIterator(data_iter(), fail_at=1, times=5)
    with pytest.raises(ProducerError):
        list(DevicePrefetchIterator(flaky, retries=2,
                                    retry_backoff_s=0.001))


# ---------------------------------------------------------------------------
# Serving + UI health/readiness, dispatch retry
# ---------------------------------------------------------------------------

def test_serving_dispatch_retry_and_health():
    from deeplearning4j_tpu.serving import ModelServer
    srv = ModelServer(max_batch=8, batch_timeout_ms=1.0,
                      dispatch_retries=1, dispatch_retry_backoff_ms=1.0)
    try:
        assert srv.healthz()["ok"]
        assert not srv.readyz()["ready"]   # nothing deployed yet
        srv.deploy("m", build_net())
        assert srv.readyz() == {"ready": True, "reasons": []}
        flaky = chaos.FlakyDispatch(srv.cache.run, times=1)
        srv.cache.run = flaky
        y = srv.output("m", X[:4].astype(np.float32))
        assert y.shape == (4, 3)
        assert flaky.calls == 2            # failed once, retried once
        assert srv.metrics.dispatch_retries.value >= 1
        # a persistent fault still fails the request after the budget
        srv.cache.run = chaos.FlakyDispatch(flaky.fn, times=10)
        with pytest.raises(chaos.ChaosError):
            srv.output("m", X[:4].astype(np.float32))
    finally:
        srv.shutdown()
    assert not srv.readyz()["ready"]       # drained servers tell the LB


def test_ui_health_endpoints_over_http():
    from deeplearning4j_tpu.serving import ModelServer
    from deeplearning4j_tpu.ui.server import UIServer
    ui = UIServer()
    srv = ModelServer(max_batch=8, batch_timeout_ms=1.0)
    port = ui.start(0)
    try:
        r = urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz")
        assert r.status == 200 and json.loads(r.read())["ok"]
        r = urllib.request.urlopen(f"http://127.0.0.1:{port}/readyz")
        assert json.loads(r.read())["ready"]      # no sources -> trivially
        ui.attach_serving(srv)                    # empty registry -> 503
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/readyz")
        assert ei.value.code == 503
        srv.deploy("m", build_net())
        r = urllib.request.urlopen(f"http://127.0.0.1:{port}/readyz")
        assert r.status == 200 and json.loads(r.read())["ready"]
    finally:
        ui.stop()
        srv.shutdown()


# ---------------------------------------------------------------------------
# Elastic runner: checksum corruption is non-retryable
# ---------------------------------------------------------------------------

def test_classify_corrupt_failures():
    from deeplearning4j_tpu.parallel.multihost import ElasticLocalRunner
    c = ElasticLocalRunner._classify_failure
    assert c("rank 0 failed (rc=1):\nChecksumError: shards-0.npz "
             "chunk params/3 checksum mismatch") == "corrupt"
    assert c("rank 1 failed (rc=1):\nTraceback ...") == "crash"


def test_elastic_runner_corrupt_is_nonretryable(tmp_path):
    """A gang whose restore hits rotten bytes must NOT burn restart
    attempts re-reading the same corruption."""
    from deeplearning4j_tpu.parallel.multihost import ElasticLocalRunner
    script = tmp_path / "bad_restore.py"
    script.write_text(
        "import sys\n"
        "sys.stderr.write('ChecksumError: shards-0.npz chunk params/0 "
        "checksum mismatch (stored 123, read 456)')\n"
        "sys.exit(1)\n")
    runner = ElasticLocalRunner(1, max_restarts=3, backoff_base_s=0.01)
    with pytest.raises(RuntimeError, match="non-retryable"):
        runner.run(str(script), timeout=120)
    assert len(runner.failure_history) == 1        # no relaunch happened
    assert runner.failure_history[0][1] == "corrupt"


# ---------------------------------------------------------------------------
# Chaos subprocess tests: kill a REAL training run, resume, compare bitwise
# ---------------------------------------------------------------------------

def _run_worker(work, mode, kill_at=7, zero1="0", fused="0", prefetch="0",
                epochs=3, save_every=4):
    return subprocess.run(
        [sys.executable, os.path.join(HERE, "ft_worker.py"), str(work),
         str(epochs), mode, str(kill_at), zero1, str(save_every), fused,
         prefetch],
        env=WORKER_ENV, capture_output=True, text=True, timeout=300)


_REFS = {}


def _reference_params(tmp_path_factory, **kw):
    """Uninterrupted-run final params, one subprocess per config."""
    key = tuple(sorted(kw.items()))
    if key not in _REFS:
        d = tmp_path_factory.mktemp("ft_ref")
        r = _run_worker(d, "none", **kw)
        assert r.returncode == 0, r.stderr[-2000:]
        _REFS[key] = np.load(d / "final.npz")["params"]
    return _REFS[key]


def _kill_and_resume(tmp_path, mode, expect_rc, **kw):
    attempts = 0
    while attempts < 5:
        r = _run_worker(tmp_path, mode, **kw)
        attempts += 1
        if r.returncode == 0:
            break
        assert r.returncode == expect_rc, (r.returncode, r.stderr[-3000:])
    assert r.returncode == 0, r.stderr[-3000:]
    assert attempts >= 2, (attempts, r.stdout)   # the kill actually happened
    assert "resumed from step" in r.stdout, r.stdout
    return np.load(tmp_path / "final.npz")["params"]


@pytest.mark.slow
def test_chaos_sigterm_resume_bitwise(tmp_path, tmp_path_factory):
    # tier-1 keeps one subprocess proof (the hard-kill below, the strongest
    # mode) plus the in-process sigterm bitwise test; the rest of the
    # kill-mode matrix rides in the slow lane to protect the suite budget
    got = _kill_and_resume(tmp_path, "sigterm", 128 + signal.SIGTERM)
    np.testing.assert_array_equal(got,
                                  _reference_params(tmp_path_factory))


def test_chaos_hard_kill_resume_bitwise(tmp_path, tmp_path_factory):
    """SIGKILL-grade death (os._exit(9)) mid-run: no preempt save happens,
    resume comes from the last PERIODIC commit — still bitwise exact."""
    got = _kill_and_resume(tmp_path, "kill", 9)
    np.testing.assert_array_equal(got,
                                  _reference_params(tmp_path_factory))


@pytest.mark.slow
def test_chaos_hard_kill_zero1_resume_bitwise(tmp_path, tmp_path_factory):
    got = _kill_and_resume(tmp_path, "kill", 9, zero1="1", kill_at=6)
    np.testing.assert_array_equal(
        got, _reference_params(tmp_path_factory, zero1="1", kill_at=6))


@pytest.mark.slow
def test_chaos_sigterm_fused_resume_bitwise(tmp_path, tmp_path_factory):
    got = _kill_and_resume(tmp_path, "sigterm", 128 + signal.SIGTERM,
                           fused="1", kill_at=6)
    np.testing.assert_array_equal(
        got, _reference_params(tmp_path_factory, fused="1", kill_at=6))


@pytest.mark.slow
def test_chaos_hard_kill_prefetch_resume_bitwise(tmp_path,
                                                 tmp_path_factory):
    got = _kill_and_resume(tmp_path, "kill", 9, prefetch="1")
    np.testing.assert_array_equal(
        got, _reference_params(tmp_path_factory, prefetch="1"))


@pytest.mark.slow
def test_chaos_soak_repeated_kills(tmp_path, tmp_path_factory):
    """Kill the run over and over at advancing steps; every relaunch
    resumes, and the eventual finish is still bitwise exact."""
    marker = tmp_path / "killed_once"
    kills = 0
    for i in range(8):
        kill_at = 4 + 3 * i
        if marker.exists():
            marker.unlink()                # re-arm the switch
        mode = "kill" if i % 2 else "sigterm"
        r = _run_worker(tmp_path, mode, kill_at=kill_at)
        if r.returncode == 0:
            break
        kills += 1
        assert r.returncode in (9, 128 + signal.SIGTERM), r.stderr[-2000:]
    assert r.returncode == 0 and kills >= 3
    got = np.load(tmp_path / "final.npz")["params"]
    np.testing.assert_array_equal(got,
                                  _reference_params(tmp_path_factory))


@pytest.mark.slow
def test_elastic_manager_resume_multihost(tmp_path):
    """ElasticLocalRunner hands the checkpoint dir to the gang; after the
    injected crash the relaunch resumes through the sharded
    CheckpointManager (not the legacy zip) and finishes all steps."""
    from deeplearning4j_tpu.parallel.multihost import ElasticLocalRunner
    runner = ElasticLocalRunner(num_processes=2, devices_per_process=1,
                                max_restarts=2)
    outs = runner.run(os.path.join(HERE, "mh_worker_elastic.py"),
                      [str(tmp_path), "6", "3"], timeout=420,
                      checkpoint_dir=str(tmp_path / "ckpt"))
    assert runner.restarts >= 1
    assert any("resumed at iteration" in o for o in outs)
    final = np.load(tmp_path / "final.npz")
    assert int(final["iteration"]) == 6
    assert np.isfinite(final["params"]).all()
    # the sharded manager path was really used
    assert any(n.startswith("ckpt-")
               for n in os.listdir(tmp_path / "ckpt"))
