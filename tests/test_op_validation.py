"""Registry-wide per-op validation sweep (reference OpValidation:
`nd4j-api/.../org/nd4j/autodiff/validation/OpValidation.java` + the
opvalidation test classes under `platform-tests/` — forward goldens,
shape-function agreement, finite-difference gradients, and a coverage
gate that FAILS on any registered op with neither a case nor an
allowlist entry)."""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.validation import (coverage_report,
                                                    validate_case)
from tests import opval_specs_core, opval_specs_misc, opval_specs_nn

ALL_CASES = (opval_specs_core.CASES + opval_specs_nn.CASES
             + opval_specs_misc.CASES)

# Ops with no validation case, each with a reason (kept deliberately
# tiny; a stale entry — op gains a case later — fails the gate too).
ALLOWLIST = {}


@pytest.mark.parametrize("case", ALL_CASES, ids=lambda c: c.id)
def test_op(case):
    validate_case(case)


def test_registry_coverage():
    missing, stale, unknown, pct = coverage_report(ALL_CASES, ALLOWLIST)
    assert not unknown, f"cases/allowlist name unregistered ops: {unknown}"
    assert not stale, f"allowlist entries now have cases: {stale}"
    assert not missing, (
        f"{len(missing)} registered ops have no validation case and no "
        f"allowlist entry: {missing}")
    assert pct >= 0.90, (
        f"only {pct:.1%} of the registry is value-checked (goldens or "
        "property checks); need >= 90%")
