"""Registry-wide per-op validation sweep (reference OpValidation:
`nd4j-api/.../org/nd4j/autodiff/validation/OpValidation.java` + the
opvalidation test classes under `platform-tests/` — forward goldens,
shape-function agreement, finite-difference gradients, and a coverage
gate that FAILS on any registered op with neither a case nor an
allowlist entry)."""
import dataclasses

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.validation import (coverage_report,
                                                    validate_case)
from tests import (opval_specs_bf16, opval_specs_configs,
                   opval_specs_core, opval_specs_misc, opval_specs_nn)
from tests.opval_grad_specs import AUGMENT, NONDIFF


def _augment(cases):
    """Apply the AUGMENT table: each listed op's first non-custom case
    gains a gradient check (reference: gradientCheck defaults to true in
    `TestCase.java`; exclusions are explicit)."""
    todo = dict(AUGMENT)
    out = []
    for c in cases:
        spec = todo.get(c.op)
        if spec is not None and c.custom is None and not c.grad:
            grad, sample, gtol = spec
            c = dataclasses.replace(
                c, grad=grad, grad_sample=sample,
                gtol=gtol if gtol is not None else c.gtol)
            del todo[c.op]
        out.append(c)
    assert not todo, f"AUGMENT ops with no augmentable case: {sorted(todo)}"
    return out


ALL_CASES = (_augment(opval_specs_core.CASES + opval_specs_nn.CASES
                      + opval_specs_misc.CASES)
             + opval_specs_configs.CASES + opval_specs_bf16.CASES)

# Ops with no validation case, each with a reason (kept deliberately
# tiny; a stale entry — op gains a case later — fails the gate too).
ALLOWLIST = {}


@pytest.mark.parametrize("case", ALL_CASES, ids=lambda c: c.id)
def test_op(case):
    validate_case(case)


def test_registry_coverage():
    missing, stale, unknown, pct = coverage_report(ALL_CASES, ALLOWLIST)
    assert not unknown, f"cases/allowlist name unregistered ops: {unknown}"
    assert not stale, f"allowlist entries now have cases: {stale}"
    assert not missing, (
        f"{len(missing)} registered ops have no validation case and no "
        f"allowlist entry: {missing}")
    assert pct >= 0.90, (
        f"only {pct:.1%} of the registry is value-checked (goldens or "
        "property checks); need >= 90%")


def test_gradient_coverage():
    """Every registered op is either gradient-checked or has an explicit
    non-differentiability reason — and neither list is stale (reference
    OpValidation's gradient-coverage gate)."""
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE

    registered = set(OP_TABLE)
    graded = {c.op for c in ALL_CASES if c.grad}
    unknown = (set(NONDIFF) | set(AUGMENT)) - registered
    assert not unknown, f"grad specs name unregistered ops: {sorted(unknown)}"
    stale = sorted(graded & set(NONDIFF))
    assert not stale, f"NONDIFF entries now gradient-checked: {stale}"
    missing = sorted(registered - graded - set(NONDIFF))
    assert not missing, (
        f"{len(missing)} ops neither gradient-checked nor excluded with "
        f"a reason: {missing}")


def test_config_coverage():
    """Every stride/dilation/padding/layout-sensitive op carries >=2
    value-checked configs (reference: the multi-case LayerOpValidation
    corpus; single-config passes hid the round-4 deconv flip)."""
    from collections import Counter

    counts = Counter(c.op for c in ALL_CASES
                     if c.golden is not None or c.check is not None
                     or c.custom is not None)
    thin = sorted(op for op in opval_specs_configs.CONFIG_CRITICAL
                  if counts[op] < 2)
    assert not thin, f"config-critical ops with <2 checked configs: {thin}"
