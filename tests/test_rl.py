"""RL tests (reference rl4j tests: `QLearningDiscreteTest`,
policy/replay unit tests; convergence on a toy MDP)."""
import pytest
import numpy as np

from deeplearning4j_tpu.rl import (CartPole, EpsGreedy, ExpReplay,
                                   LineWorld, QLearningConfiguration,
                                   QLearningDiscrete, Transition)


def test_lineworld_mechanics():
    env = LineWorld(n=4)
    obs = env.reset()
    np.testing.assert_array_equal(obs, [1, 0, 0, 0])
    obs, r, done, _ = env.step(1)
    np.testing.assert_array_equal(obs, [0, 1, 0, 0])
    assert not done and r < 0
    env.step(1)
    obs, r, done, _ = env.step(1)
    assert done and r == 1.0


def test_cartpole_mechanics():
    env = CartPole(seed=0)
    obs = env.reset()
    assert obs.shape == (4,)
    total = 0
    while not env.is_done():
        _, r, done, _ = env.step(np.random.randint(2))
        total += r
    assert 1 <= total <= 500


def test_replay_ring_buffer():
    rp = ExpReplay(max_size=5, batch_size=3, seed=0)
    for i in range(8):
        rp.store(Transition(np.array([i], np.float32), 0, float(i),
                            np.array([i + 1], np.float32), False))
    assert len(rp) == 5
    obs, actions, rewards, next_obs, dones = rp.sample()
    assert obs.shape == (3, 1)
    assert rewards.min() >= 3.0   # oldest entries evicted


def test_eps_greedy_anneals():
    pol = EpsGreedy(lambda o: np.zeros((1, 2)), 2, eps_init=1.0,
                    eps_min=0.1, anneal_steps=100)
    assert pol.epsilon() == 1.0
    for _ in range(100):
        pol.next_action(np.zeros(4))
    assert abs(pol.epsilon() - 0.1) < 1e-6


def test_qlearning_solves_lineworld():
    env = LineWorld(n=6)
    cfg = QLearningConfiguration(
        seed=3, max_step=2_500, batch_size=32, target_update=200,
        update_start=100, gamma=0.95, eps_min=0.05, anneal_steps=1_500,
        replay_size=5_000)
    ql = QLearningDiscrete(env, cfg)
    ql.train()
    policy = ql.get_policy()
    # optimal: 5 steps right -> reward 1 - 5*0.01 = 0.95
    total = policy.play(LineWorld(n=6))
    assert total > 0.9, f"greedy return {total}"
    # learned Q ranks 'right' above 'left' along the corridor
    for pos in range(5):
        obs = np.zeros(6, np.float32)
        obs[pos] = 1.0
        q = ql._q_online(obs[None])[0]
        assert q[1] > q[0], (pos, q)


# ---------------------------------------------------------------------------
# Async-family RL (VERDICT #8: A3C + AsyncNStepQ), batched-synchronous
# ---------------------------------------------------------------------------

def test_a3c_learns_lineworld():
    from deeplearning4j_tpu.rl import (A3CDiscrete, AsyncConfiguration)
    from deeplearning4j_tpu.rl.mdp import LineWorld
    conf = AsyncConfiguration(seed=0, max_step=20000, n_step=5, num_envs=8,
                              learning_rate=5e-2, entropy_coef=0.005,
                              hidden=(32,))
    agent = A3CDiscrete(obs_size=8, n_actions=2, conf=conf)
    agent.train(lambda: LineWorld(8))
    # LineWorld: optimal policy walks right, reward ~ +1
    score = np.mean([agent.play(LineWorld(8)) for _ in range(5)])
    assert score > 0.5, score


def test_async_nstep_q_learns_lineworld():
    from deeplearning4j_tpu.rl import (AsyncConfiguration,
                                       AsyncNStepQLearningDiscrete)
    from deeplearning4j_tpu.rl.mdp import LineWorld
    conf = AsyncConfiguration(seed=1, max_step=12000, n_step=5, num_envs=8,
                              learning_rate=3e-2, anneal_steps=6000,
                              hidden=(32,))
    agent = AsyncNStepQLearningDiscrete(obs_size=8, n_actions=2, conf=conf)
    agent.train(lambda: LineWorld(8))
    score = np.mean([agent.play(LineWorld(8)) for _ in range(5)])
    assert score > 0.5, score


def test_gym_adapter_trains_cartpole():
    """Reference rl4j-gym role: a gymnasium env drives the same learners."""
    pytest.importorskip("gymnasium")
    from deeplearning4j_tpu.rl import (A3CDiscrete, AsyncConfiguration,
                                       GymMDP)
    probe = GymMDP("CartPole-v1", seed=0)
    assert probe.n_actions == 2 and probe.observation_size == 4
    obs = probe.reset()
    assert obs.shape == (4,)
    obs2, r, done, _ = probe.step(0)
    assert r == 1.0 and obs2.shape == (4,)
    probe.close()
    conf = AsyncConfiguration(seed=0, max_step=30000, n_step=8, num_envs=8,
                              learning_rate=3e-2, entropy_coef=0.01,
                              hidden=(64,))
    agent = A3CDiscrete(obs_size=4, n_actions=2, conf=conf)
    # Seed every training env (one stream per worker) so the whole run is
    # deterministic: jax PRNG is seeded via conf, envs via this counter.
    env_seed = iter(range(1000, 2000))
    agent.train(lambda: GymMDP("CartPole-v1", seed=next(env_seed)))
    # Robust statistic (the old mean-of-3 > 100 was a coin flip on the
    # stochastic training run): best-of-5 greedy rollouts must clearly
    # beat random (~20), and the mean must too, with margin.
    scores = [agent.play(GymMDP("CartPole-v1", seed=100 + i))
              for i in range(5)]
    assert max(scores) > 100, scores   # learned-at-all, robustly
    assert np.mean(scores) > 50, scores  # random baseline ~20, 2.5x margin
