"""Elastic gang membership: heartbeats, generation fencing, coordinator
re-formation, joiner admission, and checkpoint-coordinated resume.

Fast tests exercise `ElasticGradientMesh` in-process (each member on a
thread over loopback TCP — deterministic, no subprocess spin-up) plus the
codec/trainer/zero1 pieces the reformation path composes.  The `slow`
tests run the real multi-process chaos scenarios through
`ElasticLocalRunner.run_elastic` and hold the bitwise kill-and-resume
parity bar.
"""
import json
import os
import shutil
import socket
import threading
import time
import types

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.multihost import free_port
from deeplearning4j_tpu.parallel.transport import (
    KIND_DATA, ElasticGradientMesh, GangEvictedError, GangReformed,
    PeerUnreachableError, TcpGradientMesh, _frame_bytes, _FrameReader)

HERE = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------------------------
# helpers: form a gang on threads, run allgathers asynchronously
# ---------------------------------------------------------------------------

def _spawn_gang(world, port, **kw):
    kw.setdefault("timeout", 20.0)
    meshes = [None] * world
    errors = []

    def make(r):
        try:
            meshes[r] = ElasticGradientMesh(r, world, port, **kw)
        except Exception as e:                      # pragma: no cover
            errors.append((r, e))

    threads = [threading.Thread(target=make, args=(r,), daemon=True)
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    assert all(m is not None for m in meshes)
    return meshes


def _allgather_async(mesh, payload):
    box = {}

    def run():
        try:
            box["result"] = mesh.allgather(payload)
        except Exception as e:
            box["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, box


def _round(meshes, payloads):
    """One full allgather round; every member must see every payload."""
    started = [_allgather_async(m, p) for m, p in zip(meshes, payloads)]
    for t, box in started:
        t.join(timeout=20)
        assert "error" not in box, box.get("error")
        assert box["result"] == list(payloads)


def _wait_until(cond, timeout=8.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def _close_all(*meshes):
    for m in meshes:
        if m is not None:
            m.close()


# ---------------------------------------------------------------------------
# frame protocol
# ---------------------------------------------------------------------------

def test_frame_reader_partial_and_pipelined_feeds():
    frame = _frame_bytes(3, KIND_DATA, b"hello")
    reader = _FrameReader()
    # byte-at-a-time: nothing surfaces until the final byte
    for b in frame[:-1]:
        assert reader.feed(bytes([b])) == []
    assert reader.feed(frame[-1:]) == [(3, KIND_DATA, b"hello")]
    # two frames in one recv: both surface, in order
    two = _frame_bytes(7, KIND_DATA, b"a") + _frame_bytes(7, KIND_DATA, b"b")
    assert _FrameReader().feed(two) == [(7, KIND_DATA, b"a"),
                                        (7, KIND_DATA, b"b")]


# ---------------------------------------------------------------------------
# formation, rounds, close
# ---------------------------------------------------------------------------

def test_elastic_mesh_round_and_idempotent_close():
    meshes = _spawn_gang(3, free_port())
    try:
        _round(meshes, [b"p0", b"p1", b"p2"])
        _round(meshes, [b"q0", b"q1", b"q2"])
        for m in meshes:
            s = m.stats()
            assert s["generation"] == 1 and s["reformations"] == 0
    finally:
        _close_all(*meshes)
        _close_all(*meshes)         # close() must be idempotent


def test_tcp_mesh_close_idempotent_and_formation_cleanup():
    port = free_port()
    meshes = [None, None]
    errs = []

    def make(r):
        try:
            meshes[r] = TcpGradientMesh(r, 2, port, timeout=15.0)
        except Exception as e:                      # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=make, args=(r,), daemon=True)
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=20)
    assert not errs and all(meshes)
    try:
        t0, b0 = _allgather_async(meshes[0], b"x")
        t1, b1 = _allgather_async(meshes[1], b"y")
        t0.join(10), t1.join(10)
        assert b0["result"] == [b"x", b"y"] == b1["result"]
    finally:
        for m in meshes:
            m.close()
            m.close()               # second close: no-op, no raise
    # a failed formation must not leak its socket: the same port is
    # immediately bindable again
    dead_port = free_port()
    with pytest.raises(PeerUnreachableError):
        ElasticGradientMesh(1, 2, dead_port, timeout=0.3)
    with socket.create_server(("127.0.0.1", dead_port)):
        pass


# ---------------------------------------------------------------------------
# crash detection + generation fencing
# ---------------------------------------------------------------------------

def test_crash_reformation_fences_inflight_data():
    meshes = _spawn_gang(3, free_port(), heartbeat_interval=0.05,
                         failure_deadline=1.0)
    m0, m1, m2 = meshes
    try:
        _round(meshes, [b"a0", b"a1", b"a2"])
        # rank 2 ships a DATA frame for the next round, then crashes:
        # the frame is already in flight when the EOF is detected, so the
        # reformation must fence (not gather) it
        m2._peer_send(KIND_DATA, b"doomed")
        m2._sock.close()
        assert _wait_until(lambda: m0.generation == 2)
        assert m0.world == 2 and m0.reformations == 1
        assert m0.stale_frames == 1
        with pytest.raises(GangReformed) as ei:
            m0.allgather(b"x")
        e0 = ei.value
        assert e0.cause == "crash" and e0.generation == 2
        assert e0.world == 2 and e0.lost == [2]
        assert e0.detection_ms is not None
        t1, box1 = _allgather_async(m1, b"y")
        t1.join(10)
        e1 = box1["error"]
        assert isinstance(e1, GangReformed)
        assert e1.cause == "crash" and e1.rank == 1   # relative order kept
        # the shrunk gang keeps working under the new generation
        _round([m0, m1], [b"b0", b"b1"])
        assert m0.stats()["generation"] == 2
    finally:
        _close_all(*meshes)


def test_stale_generation_data_is_fenced_never_gathered():
    meshes = _spawn_gang(2, free_port())
    m0, m1 = meshes
    try:
        _round(meshes, [b"a0", b"a1"])
        # a straggler waking up replays a frame from a dead generation
        m1._peer_send(KIND_DATA, b"ghost", generation=0)
        assert _wait_until(lambda: m0.stale_frames == 1)
        assert m0.generation == 1          # fenced, NOT a reformation
        # the next round sees only current-generation payloads
        t0, b0 = _allgather_async(m0, b"c0")
        t1, b1 = _allgather_async(m1, b"c1")
        t0.join(10), t1.join(10)
        assert b0["result"] == [b"c0", b"c1"] == b1["result"]
        assert b"ghost" not in b0["result"]
        assert m0.stats()["stale_frames"] == 1
    finally:
        _close_all(*meshes)


# ---------------------------------------------------------------------------
# partition / straggler detection, eviction
# ---------------------------------------------------------------------------

def test_partition_detection_and_eviction():
    meshes = _spawn_gang(3, free_port(), heartbeat_interval=0.05,
                         failure_deadline=0.5)
    m0, m1, m2 = meshes
    try:
        _round(meshes, [b"a0", b"a1", b"a2"])
        m2.pause_heartbeats(True)          # full silence, socket healthy
        assert _wait_until(lambda: m0.generation == 2)
        with pytest.raises(GangReformed) as ei:
            m0.allgather(b"x")
        assert ei.value.cause == "partition" and ei.value.world == 2
        # detection latency is the silence at declaration: bounded below
        # by the deadline, and not wildly above it
        assert 500.0 * 0.9 <= ei.value.detection_ms <= 10_000.0
        t1, b1 = _allgather_async(m1, b"y")
        t1.join(10)
        assert isinstance(b1["error"], GangReformed)
        # the partitioned rank finds the eviction notice when it wakes
        m2.pause_heartbeats(False)
        with pytest.raises(GangEvictedError):
            m2.allgather(b"z")
        _round([m0, m1], [b"b0", b"b1"])
    finally:
        _close_all(*meshes)


def test_straggler_reformed_out_mid_round():
    meshes = _spawn_gang(3, free_port(), heartbeat_interval=0.05,
                         failure_deadline=0.6)
    m0, m1, m2 = meshes
    try:
        _round(meshes, [b"a0", b"a1", b"a2"])
        # rank 2 heartbeats (stays "alive") but never ships round data
        t0, b0 = _allgather_async(m0, b"x")
        t1, b1 = _allgather_async(m1, b"y")
        t0.join(15), t1.join(15)
        e0 = b0["error"]
        assert isinstance(e0, GangReformed) and e0.cause == "straggler"
        assert e0.world == 2 and e0.lost == [2]
        assert isinstance(b1["error"], GangReformed)
        with pytest.raises(GangEvictedError):
            m2.allgather(b"late")
        _round([m0, m1], [b"b0", b"b1"])
    finally:
        _close_all(*meshes)


# ---------------------------------------------------------------------------
# joiner admission
# ---------------------------------------------------------------------------

def test_joiner_parked_until_admitted_then_gang_grows():
    port = free_port()
    meshes = _spawn_gang(2, port)
    m0, m1 = meshes
    jbox = {}

    def join():
        try:
            jbox["mesh"] = ElasticGradientMesh(0, 0, port, join=True,
                                               join_timeout=20.0)
        except Exception as e:                      # pragma: no cover
            jbox["error"] = e

    jt = threading.Thread(target=join, daemon=True)
    mj = None
    try:
        _round(meshes, [b"a0", b"a1"])
        jt.start()
        assert m0.wait_for_joiner(10.0)
        assert m0.has_pending_joiner()
        info = m0.admit_joiners(resume_step=42)
        assert info["cause"] == "join" and info["world"] == 3
        assert info["generation"] == 2
        jt.join(timeout=10)
        mj = jbox.get("mesh")
        assert mj is not None, jbox.get("error")
        assert (mj.rank, mj.world, mj.generation) == (2, 3, 2)
        assert mj.join_info["resume_step"] == 42
        # the pre-existing peer reforms into the new generation with the
        # SAME resume step, keeping its rank
        t1, b1 = _allgather_async(m1, b"x")
        t1.join(10)
        e1 = b1["error"]
        assert isinstance(e1, GangReformed)
        assert e1.cause == "join" and e1.resume_step == 42 and e1.rank == 1
        _round([m0, m1, mj], [b"b0", b"b1", b"b2"])
    finally:
        _close_all(m0, m1, mj)


# ---------------------------------------------------------------------------
# codec residuals (reformation rebuild semantics)
# ---------------------------------------------------------------------------

def test_residual_reset_take_flush_roundtrip():
    from deeplearning4j_tpu.parallel.compression import (
        CompressedGradientExchange)
    template = {"w": np.zeros(8, np.float32)}
    ex = CompressedGradientExchange(template, threshold=1.0)
    ex.encode({"w": np.full(8, 0.5, np.float32)})   # all below threshold
    norm = ex.residual_norm()
    assert norm > 0
    taken = ex.take_residuals()
    assert ex.residual_norm() == 0.0
    ex.flush_into(taken)
    assert ex.residual_norm() == pytest.approx(norm)
    ex.reset_residuals()
    assert ex.residual_norm() == 0.0
    with pytest.raises(ValueError):
        ex.flush_into([np.zeros(3, np.float32)])


def test_hierarchical_rebuild_reset_vs_flush():
    from deeplearning4j_tpu.parallel.hierarchical import (
        HierarchicalAllReduce, HierarchicalGradientSharing)
    h = HierarchicalAllReduce(HierarchicalGradientSharing(
        threshold=1.0, rank=0, world=1))
    try:
        h.exchange({"w": np.full(8, 0.5, np.float32)})
        norm = h._exchange.residual_norm()
        assert norm > 0
        # forward (non-rewind) membership change: residual mass carried
        h.rebuild(flush_residuals=True)
        assert h._exchange.residual_norm() == pytest.approx(norm)
        # checkpoint-rewind resume: fresh codecs, zero residuals
        h.rebuild(flush_residuals=False)
        assert h._exchange.residual_norm() == 0.0
    finally:
        h.close()


# ---------------------------------------------------------------------------
# ZeRO-1 re-shard for a changed world size
# ---------------------------------------------------------------------------

def test_reshard_zero1_replans_for_new_world():
    import jax

    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh, zero
    from deeplearning4j_tpu.train import Adam
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.randn(32, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[
        (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)]
    mesh4 = make_mesh({"data": 4}, jax.devices()[:4])
    ParallelWrapper(net, mesh4, optimizer_sharding=True).fit(x, y)
    t4 = net._step_transform
    assert t4 is not None and t4.mesh.shape["data"] == 4
    # the gang shrank: re-plan the optimizer shards for world 2
    mesh2 = make_mesh({"data": 2}, jax.devices()[:2])
    t2 = zero.reshard_zero1(net, mesh2)
    assert net._step_transform is t2 and t2 is not t4
    assert t2.mesh.shape["data"] == 2
    # training continues at the new layout
    ParallelWrapper(net, mesh2, optimizer_sharding=True).fit(x, y)
    assert np.isfinite(np.asarray(net.params())).all()


# ---------------------------------------------------------------------------
# chaos hook + trainer policy + env knobs + free_port
# ---------------------------------------------------------------------------

def _fake_trainer(rank, iteration):
    mesh = types.SimpleNamespace(rank=rank)
    sharing = types.SimpleNamespace(mesh=mesh)
    model = types.SimpleNamespace(iteration=iteration,
                                  _grad_sharing=sharing)
    return types.SimpleNamespace(model=model)


def test_peer_killer_targets_live_rank_and_marker(tmp_path):
    from deeplearning4j_tpu.utils.chaos import PeerKiller
    with pytest.raises(ValueError, match="mode"):
        PeerKiller(0, 0, mode="nuke")
    marker = str(tmp_path / "fired")
    pk = PeerKiller(rank=1, at_step=6, mode="slow", delay_s=0.0,
                    marker=marker)
    pk(_fake_trainer(rank=1, iteration=5))     # before at_step: no fire
    assert not pk.fired
    pk(_fake_trainer(rank=0, iteration=6))     # wrong live rank: no fire
    assert not pk.fired
    pk(_fake_trainer(rank=1, iteration=6))
    assert pk.fired and os.path.exists(marker)
    # a relaunched replacement of the killed rank must not re-fire
    relaunched = PeerKiller(rank=1, at_step=6, mode="slow", delay_s=0.0,
                            marker=marker)
    assert not relaunched.armed()
    relaunched(_fake_trainer(rank=1, iteration=9))
    assert not relaunched.fired


def test_peer_killer_partition_pauses_and_resumes_heartbeats():
    from deeplearning4j_tpu.utils.chaos import PeerKiller
    calls = []
    mesh = types.SimpleNamespace(
        rank=1, pause_heartbeats=lambda p: calls.append(p))
    model = types.SimpleNamespace(
        iteration=3, _grad_sharing=types.SimpleNamespace(mesh=mesh))
    trainer = types.SimpleNamespace(model=model)
    pk = PeerKiller(rank=1, at_step=3, mode="partition", duration_s=0.0)
    pk(trainer)
    assert calls == [True, False]


def test_elastic_trainer_policy_validation():
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train import Sgd
    from deeplearning4j_tpu.train.resilience import ElasticTrainer
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .list([DenseLayer(n_out=4, activation="tanh"),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="policy"):
        ElasticTrainer(net, policy="wait")
    t = ElasticTrainer(net, policy="block", rejoin_wait_s=1.5)
    assert t.policy == "block" and t.rejoin_wait_s == 1.5


def test_elastic_env_knob_resolution(monkeypatch):
    from deeplearning4j_tpu.parallel.hierarchical import (
        HierarchicalGradientSharing)
    monkeypatch.setenv("DL4J_TPU_HEARTBEAT_S", "0.125")
    monkeypatch.setenv("DL4J_TPU_FAILURE_DEADLINE_S", "3.5")
    monkeypatch.setenv("DL4J_TPU_JOIN", "1")
    cfg = HierarchicalGradientSharing(elastic=True).resolve()
    assert cfg.heartbeat_interval_s == 0.125
    assert cfg.failure_deadline_s == 3.5
    assert cfg.join is True
    monkeypatch.delenv("DL4J_TPU_JOIN")
    assert HierarchicalGradientSharing(elastic=True).resolve().join is False


def test_free_port_survives_probe_vs_bind_race(monkeypatch):
    from deeplearning4j_tpu.parallel import multihost as mh
    state = {"raced": False}

    class RacySocket(socket.socket):
        def bind(self, addr):
            # fail the first VERIFY bind (explicit port) — the window
            # where another process grabbed the probed port
            if addr[1] != 0 and not state["raced"]:
                state["raced"] = True
                raise OSError(98, "Address already in use")
            return super().bind(addr)

    monkeypatch.setattr(mh.socket, "socket", RacySocket)
    port = mh.free_port()
    assert state["raced"] and 0 < port < 65536
    monkeypatch.undo()
    with socket.socket() as s:                      # genuinely bindable
        s.bind(("127.0.0.1", port))

    class AlwaysLoses(socket.socket):
        def bind(self, addr):
            if addr[1] != 0:
                raise OSError(98, "Address already in use")
            return super().bind(addr)

    monkeypatch.setattr(mh.socket, "socket", AlwaysLoses)
    with pytest.raises(OSError, match="no bindable port"):
        mh.free_port(max_tries=3)


# ---------------------------------------------------------------------------
# multi-process chaos scenarios (slow: real subprocess gangs)
# ---------------------------------------------------------------------------

def _prune_checkpoints_above(directory, step):
    from deeplearning4j_tpu.train.resilience import CheckpointManager
    for name in os.listdir(directory):
        path = os.path.join(directory, name)
        if not (os.path.isdir(path)
                and name.startswith(CheckpointManager.PREFIX)):
            continue
        if int(name[len(CheckpointManager.PREFIX):]) > step:
            shutil.rmtree(path)


@pytest.mark.slow
def test_elastic_gang_kill_shrink_and_bitwise_resume_parity(tmp_path):
    """The acceptance bar: a 3-process gang loses rank 2 mid-run, detects
    within the deadline, re-forms at world 2 under a new generation and
    resumes from the coordinated checkpoint — and the survivors' final
    params BITWISE-match an uninterrupted world-2 run started from that
    same checkpoint."""
    from deeplearning4j_tpu.parallel.multihost import ElasticLocalRunner
    script = os.path.join(HERE, "mh_worker_elastic_gang.py")
    steps, deadline_s = 8, 2.0
    ckpt_a, out_a = tmp_path / "ckpt_a", tmp_path / "out_a"
    out_a.mkdir()
    runner = ElasticLocalRunner(num_processes=3, backoff_base_s=0.2)
    results = runner.run_elastic(
        script, [str(out_a), str(steps), "1", "2", "3"], timeout=420,
        checkpoint_dir=str(ckpt_a), policy="shrink", heartbeat_s=0.1,
        failure_deadline_s=deadline_s, relaunch=False)
    assert results["r0"][0] == 0, results["r0"][1][-2000:]
    assert results["r1"][0] == 0, results["r1"][1][-2000:]
    assert results["r2"][0] != 0                       # the victim died
    with open(out_a / "elastic_0.json") as f:
        info0 = json.load(f)
    reforms = info0["reformations"]
    assert len(reforms) == 1
    assert reforms[0]["cause"] in ("crash", "partition", "straggler")
    assert reforms[0]["world"] == 2
    # detection within the configured deadline (reactor-tick slack)
    assert reforms[0]["detection_ms"] is not None
    assert reforms[0]["detection_ms"] <= deadline_s * 1000.0 + 2000.0
    assert info0["stats"]["generation"] == 2
    final0 = np.load(out_a / "final_0.npz")
    final1 = np.load(out_a / "final_1.npz")
    np.testing.assert_array_equal(final0["params"], final1["params"])
    assert int(final0["iteration"]) == steps

    # comparator: copy the checkpoint dir, drop everything NEWER than the
    # coordinated resume step, and run an uninterrupted world-2 gang from
    # it — bitwise-identical final params prove nothing was lost or
    # double-counted across the reformation
    resume_step = int(reforms[0]["resume_step"])
    ckpt_b, out_b = tmp_path / "ckpt_b", tmp_path / "out_b"
    shutil.copytree(ckpt_a, ckpt_b)
    _prune_checkpoints_above(str(ckpt_b), resume_step)
    out_b.mkdir()
    runner_b = ElasticLocalRunner(num_processes=2, backoff_base_s=0.2)
    results_b = runner_b.run_elastic(
        script, [str(out_b), str(steps), "1", "-1", "0"], timeout=420,
        checkpoint_dir=str(ckpt_b), policy="shrink", heartbeat_s=0.1,
        failure_deadline_s=deadline_s, relaunch=False)
    assert results_b["r0"][0] == 0, results_b["r0"][1][-2000:]
    final_b = np.load(out_b / "final_0.npz")
    assert int(final_b["iteration"]) == steps
    np.testing.assert_array_equal(final0["params"], final_b["params"])
    np.testing.assert_array_equal(final0["score"], final_b["score"])


@pytest.mark.slow
def test_elastic_gang_block_policy_relaunch_and_rejoin(tmp_path):
    """relaunch=True + block policy: the supervisor spawns a replacement
    with DL4J_TPU_JOIN=1; the coordinator admits it at the coordinated
    resume step; the gang finishes back at world 3 with every member
    holding identical params."""
    from deeplearning4j_tpu.parallel.multihost import ElasticLocalRunner
    script = os.path.join(HERE, "mh_worker_elastic_gang.py")
    ckpt, out = tmp_path / "ckpt", tmp_path / "out"
    out.mkdir()
    runner = ElasticLocalRunner(num_processes=3, backoff_base_s=0.2)
    results = runner.run_elastic(
        script, [str(out), "10", "1", "2", "3"], timeout=420,
        checkpoint_dir=str(ckpt), policy="block", heartbeat_s=0.1,
        failure_deadline_s=2.0, relaunch=True, max_replacements=1)
    assert results["r0"][0] == 0, results["r0"][1][-2000:]
    assert results["r1"][0] == 0, results["r1"][1][-2000:]
    assert results["r2"][0] != 0                       # original victim
    assert "r2+j1" in results, sorted(results)
    assert results["r2+j1"][0] == 0, results["r2+j1"][1][-2000:]
    with open(out / "elastic_0.json") as f:
        info0 = json.load(f)
    # crash reform (shrink to 2) then joiner admission (back to 3)
    assert info0["stats"]["world"] == 3
    assert info0["stats"]["generation"] >= 3
    finals = [np.load(out / f"final_{r}.npz") for r in range(3)]
    for f2 in finals[1:]:
        np.testing.assert_array_equal(finals[0]["params"], f2["params"])
    assert int(finals[0]["iteration"]) == 10
