"""TF-graph conformance corpus.

Reference: `platform-tests/.../TFGraphTestAllSameDiff.java` + the
`tf_graphs/` golden corpus (SURVEY.md §4) — there the goldens are stored
protobufs; here TF is installed, so every graph is AUTHORED in this file,
frozen to a GraphDef, imported through `modelimport.import_graph_def`,
and executed against TF itself.  Coverage targets per-op singletons, the
quirky surfaces (StridedSlice masks, FusedBatchNorm variants, conv1d's
expand/squeeze lowering, MirrorPad modes), and control-flow nests
(functional While/If, N-way Case, while+cond nesting).
"""
import numpy as np
import pytest

import tensorflow as tf
from tensorflow.python.framework.convert_to_constants import (
    convert_variables_to_constants_v2)

from deeplearning4j_tpu.modelimport import import_graph_def

rs = np.random.RandomState(42)


def F(*shape, lo=-2.0, hi=2.0):
    return rs.uniform(lo, hi, shape).astype(np.float32)


def spec(*shape, dtype=tf.float32, name="x"):
    return tf.TensorSpec(shape, dtype, name=name)


CORPUS = []


def case(name, specs, inputs, tol=1e-5):
    def deco(fn):
        CORPUS.append((name, fn, tuple(specs), tuple(inputs), tol))
        return fn
    return deco


# ---- elementwise / broadcast ----

@case("unary-chain", [spec(3, 4)], [F(3, 4, lo=0.1, hi=2.0)])
def _g(x):
    return tf.sqrt(tf.exp(tf.math.log(x) * 0.5) + tf.math.rsqrt(x))


@case("binary-broadcast", [spec(3, 1, name="x"), spec(1, 4, name="y")],
      [F(3, 1), F(1, 4)])
def _g(x, y):
    return (x + y) * (x - y) / (tf.abs(y) + 1.0)


@case("int-arith", [spec(5, dtype=tf.int32)],
      [rs.randint(1, 20, 5).astype(np.int32)])
def _g(x):
    return x // 3 + tf.math.floormod(x, 4) - tf.math.minimum(x, 7)


@case("pow-sqdiff-clip", [spec(3, 4)], [F(3, 4, lo=0.2, hi=2.0)])
def _g(x):
    return tf.clip_by_value(
        tf.pow(x, 2.0) + tf.math.squared_difference(x, 1.0), 0.1, 5.0)


@case("activations", [spec(4, 6)], [F(4, 6)])
def _g(x):
    return (tf.nn.relu(x) + tf.nn.relu6(x * 3.0) + tf.nn.elu(x)
            + tf.nn.selu(x) + tf.nn.softplus(x) + tf.nn.softsign(x)
            + tf.nn.leaky_relu(x, alpha=0.3) + tf.sigmoid(x)
            + tf.tanh(x) + tf.math.erf(x))


@case("softmax-family", [spec(4, 7)], [F(4, 7)])
def _g(x):
    return tf.nn.softmax(x) + tf.exp(tf.nn.log_softmax(x, axis=-1))


# ---- linalg ----

@case("matmul-biasadd", [spec(4, 5)], [F(4, 5)])
def _g(x):
    w = tf.constant(rs.randn(5, 3).astype(np.float32))
    b = tf.constant(rs.randn(3).astype(np.float32))
    return tf.nn.bias_add(tf.matmul(x, w), b)


@case("batch-matmul-adj", [spec(2, 3, 4)], [F(2, 3, 4)])
def _g(x):
    y = tf.constant(rs.randn(2, 3, 4).astype(np.float32))
    return tf.linalg.matmul(x, y, adjoint_b=True)


@case("einsum", [spec(3, 4)], [F(3, 4)])
def _g(x):
    w = tf.constant(rs.randn(4, 5).astype(np.float32))
    return tf.einsum("ij,jk->ik", x, w)


@case("l2-normalize-pattern", [spec(4, 6)], [F(4, 6)])
def _g(x):
    # rsqrt(sum(square)) — the hand-rolled layer-norm/l2norm surface
    return x * tf.math.rsqrt(
        tf.reduce_sum(tf.square(x), axis=-1, keepdims=True) + 1e-6)


# ---- reductions / scans ----

@case("reduce-variants", [spec(3, 4, 5)], [F(3, 4, 5)])
def _g(x):
    return (tf.reduce_sum(x, axis=-1)
            + tf.reduce_mean(x, axis=[0, 2], keepdims=False)[None, :],
            tf.reduce_max(x, axis=1) * 0.1
            + tf.reduce_min(x, axis=1) * 0.1)


@case("reduce-prod-keepdims", [spec(3, 4)], [F(3, 4, lo=0.5, hi=1.5)])
def _g(x):
    return tf.reduce_prod(x, axis=1, keepdims=True) * x


@case("argmax-cast", [spec(4, 6)], [F(4, 6)])
def _g(x):
    return (tf.cast(tf.argmax(x, axis=-1), tf.float32)
            - tf.cast(tf.argmin(x, axis=0), tf.float32)[None, :3]
            [:, 0:1] * 0.0)


@case("cumsum-exclusive-reverse", [spec(3, 6)], [F(3, 6)])
def _g(x):
    return (tf.cumsum(x, axis=1, exclusive=True)
            + tf.cumsum(x, axis=1, reverse=True))


@case("top-k-values", [spec(3, 8)], [F(3, 8)])
def _g(x):
    vals, idx = tf.math.top_k(x, k=3)
    return vals + tf.cast(idx, tf.float32) * 0.01


# ---- shape / slicing quirks ----

@case("strided-slice-masks", [spec(4, 5, 6)], [F(4, 5, 6)])
def _g(x):
    a = x[1:3, :, ::2]              # begin/end + stride
    b = x[:, 2, :]                  # shrink_axis
    c = x[..., 1]                   # ellipsis + shrink
    d = x[:, tf.newaxis, 0, :]      # new_axis + shrink
    return (tf.reduce_sum(a) + tf.reduce_sum(b) + tf.reduce_sum(c)
            + tf.reduce_sum(d) + a[0, 0, 0])


@case("neg-stride-slice", [spec(4, 6)], [F(4, 6)])
def _g(x):
    return x[::-1, ::-2]


@case("pad-modes", [spec(3, 4)], [F(3, 4)])
def _g(x):
    p = [[1, 1], [2, 0]]
    return (tf.pad(x, p) + tf.pad(x, p, mode="REFLECT")
            + tf.pad(x, p, mode="SYMMETRIC"))


@case("tile-expand-squeeze", [spec(3, 4)], [F(3, 4)])
def _g(x):
    return tf.squeeze(tf.tile(tf.expand_dims(x, 1), [1, 2, 1]),
                      axis=None) [:, 0, :]


@case("transpose-reshape", [spec(2, 3, 4)], [F(2, 3, 4)])
def _g(x):
    return tf.reshape(tf.transpose(x, [2, 0, 1]), [4, -1])


@case("concat-split-stack", [spec(4, 6)], [F(4, 6)])
def _g(x):
    a, b, c = tf.split(x, 3, axis=1)
    s = tf.stack([a, b, c], axis=0)
    u = tf.unstack(s, axis=0)
    return tf.concat(u, axis=1) + x


@case("gather-axis", [spec(5, 4)], [F(5, 4)])
def _g(x):
    idx = tf.constant([3, 0, 1])
    return tf.gather(x, idx, axis=0), tf.gather(x, [1, 2], axis=1)


@case("gather-nd", [spec(4, 5)], [F(4, 5)])
def _g(x):
    return tf.gather_nd(x, tf.constant([[0, 1], [3, 2], [2, 4]]))


@case("one-hot-depth", [spec(6, dtype=tf.int32)],
      [rs.randint(0, 5, 6).astype(np.int32)])
def _g(x):
    return tf.one_hot(x, 5, on_value=2.0, off_value=-1.0)


@case("cast-chain", [spec(3, 4)], [F(3, 4, lo=-3, hi=3)])
def _g(x):
    return tf.cast(tf.cast(tf.cast(x, tf.int32), tf.bool), tf.float32)


@case("where-select", [spec(3, 4)], [F(3, 4)])
def _g(x):
    return tf.where(x > 0.0, x * 2.0, x - 1.0)


@case("shape-driven-reshape", [spec(3, 8)], [F(3, 8)])
def _g(x):
    s = tf.shape(x)
    return tf.reshape(x, [s[0] * 2, s[1] // 2])


@case("fill-zeros-ones", [spec(3, 4)], [F(3, 4)])
def _g(x):
    return (x + tf.zeros_like(x) + tf.ones_like(x)
            + tf.fill([3, 4], 0.5) + tf.range(4.0)[None, :])


@case("reverse-axis", [spec(3, 4)], [F(3, 4)])
def _g(x):
    return tf.reverse(x, axis=[1]) + tf.reverse(x, axis=[0, 1])


# ---- cnn surfaces ----

@case("conv2d-same-valid", [spec(1, 8, 8, 3)], [F(1, 8, 8, 3)])
def _g(x):
    w1 = tf.constant(rs.randn(3, 3, 3, 4).astype(np.float32) * 0.2)
    w2 = tf.constant(rs.randn(2, 2, 4, 5).astype(np.float32) * 0.2)
    y = tf.nn.conv2d(x, w1, strides=1, padding="SAME")
    return tf.nn.conv2d(y, w2, strides=2, padding="VALID")


@case("depthwise-conv", [spec(1, 6, 6, 3)], [F(1, 6, 6, 3)])
def _g(x):
    w = tf.constant(rs.randn(3, 3, 3, 2).astype(np.float32) * 0.3)
    return tf.nn.depthwise_conv2d(x, w, strides=[1, 1, 1, 1],
                                  padding="SAME")


@case("conv1d-lowering", [spec(2, 10, 3)], [F(2, 10, 3)])
def _g(x):
    # tf.nn.conv1d freezes into ExpandDims -> Conv2D -> Squeeze
    w = tf.constant(rs.randn(3, 3, 5).astype(np.float32) * 0.3)
    return tf.nn.conv1d(x, w, stride=1, padding="SAME")


@case("pools", [spec(1, 8, 8, 2)], [F(1, 8, 8, 2)])
def _g(x):
    return (tf.nn.max_pool2d(x, 2, 2, "VALID")
            + tf.nn.avg_pool2d(x, 2, 2, "VALID"))


@case("fused-bn-v3-inference", [spec(2, 5, 5, 4)], [F(2, 5, 5, 4)])
def _g(x):
    scale = tf.constant(rs.rand(4).astype(np.float32) + 0.5)
    offset = tf.constant(rs.randn(4).astype(np.float32))
    mean = tf.constant(rs.randn(4).astype(np.float32))
    var = tf.constant(rs.rand(4).astype(np.float32) + 0.5)
    res = tf.raw_ops.FusedBatchNormV3(
        x=x, scale=scale, offset=offset, mean=mean, variance=var,
        is_training=False)
    return tf.nn.relu(res[0])


@case("resnet-block", [spec(1, 6, 6, 4)], [F(1, 6, 6, 4)])
def _g(x):
    w1 = tf.constant(rs.randn(3, 3, 4, 4).astype(np.float32) * 0.2)
    w2 = tf.constant(rs.randn(3, 3, 4, 4).astype(np.float32) * 0.2)
    y = tf.nn.relu(tf.nn.conv2d(x, w1, 1, "SAME"))
    return tf.nn.relu(x + tf.nn.conv2d(y, w2, 1, "SAME"))


@case("resize-bilinear", [spec(1, 4, 4, 2)], [F(1, 4, 4, 2)])
def _g(x):
    return tf.image.resize(x, [8, 8], method="bilinear")


# ---- control flow ----

@case("functional-while", [spec(3)], [F(3)])
def _g(x):
    i = tf.constant(0)

    def cond(i, acc):
        return i < 4

    def body(i, acc):
        return i + 1, acc * 1.5 + 0.1

    _, out = tf.while_loop(cond, body, [i, x])
    return out


@case("functional-cond", [spec(4)], [F(4)])
def _g(x):
    return tf.cond(tf.reduce_sum(x) > 0.0,
                   lambda: x * 3.0, lambda: x - 5.0)


@case("case-3way", [spec(3), spec(dtype=tf.int32, name="i")],
      [F(3), np.int32(1)])
def _g(x, i):
    return tf.switch_case(i, branch_fns=[
        lambda: x * 10.0, lambda: x - 100.0, lambda: x * 0.0 + 7.0])


@case("case-3way-b0", [spec(3), spec(dtype=tf.int32, name="i")],
      [F(3), np.int32(0)])
def _g(x, i):
    return tf.switch_case(i, branch_fns=[
        lambda: x * 10.0, lambda: x - 100.0, lambda: x * 0.0 + 7.0])


@case("case-default-out-of-range",
      [spec(3), spec(dtype=tf.int32, name="i")],
      [F(3), np.int32(9)])
def _g(x, i):
    return tf.switch_case(i, branch_fns=[
        lambda: x * 10.0, lambda: x - 100.0, lambda: x + 1.0])


@case("while-cond-nest", [spec(3)], [F(3)])
def _g(x):
    def cond(i, acc):
        return i < 3

    def body(i, acc):
        acc = tf.cond(tf.reduce_sum(acc) > 0.0,
                      lambda: acc * 0.5, lambda: acc + 1.0)
        return i + 1, acc

    _, out = tf.while_loop(cond, body, [tf.constant(0), x])
    return out


# ---- misc quirks ----

@case("minimum-maximum-chain", [spec(3, 4)], [F(3, 4)])
def _g(x):
    return tf.maximum(tf.minimum(x, 0.5), -0.5) + tf.abs(x)


@case("log1p-expm1-sinh", [spec(3, 4)], [F(3, 4, lo=-0.9, hi=0.9)])
def _g(x):
    return tf.math.log1p(tf.abs(x)) + tf.math.expm1(x) + tf.sinh(x) \
        + tf.cosh(x) + tf.atan(x)


@case("floor-ceil-round-sign", [spec(3, 4)], [F(3, 4, lo=-3, hi=3)])
def _g(x):
    return (tf.floor(x) + tf.math.ceil(x) + tf.round(x) + tf.sign(x)
            + tf.math.rint(x))


@case("equal-logical", [spec(4, dtype=tf.int32), spec(4, dtype=tf.int32,
                                                      name="y")],
      [rs.randint(0, 3, 4).astype(np.int32),
       rs.randint(0, 3, 4).astype(np.int32)])
def _g(x, y):
    eq = tf.equal(x, y)
    gt = tf.greater(x, y)
    return tf.cast(tf.logical_or(eq, tf.logical_and(gt, gt)), tf.int32)


@case("squeeze-dims-attr", [spec(3, 1, 4, 1)], [F(3, 1, 4, 1)])
def _g(x):
    return tf.squeeze(x, axis=[1, 3])


@case("mean-all-axes", [spec(2, 3, 4)], [F(2, 3, 4)])
def _g(x):
    return tf.reduce_mean(x) + tf.reduce_sum(x) * 0.001


@case("flatten-shape-of-conv", [spec(2, 6, 6, 3)], [F(2, 6, 6, 3)])
def _g(x):
    # the ubiquitous flatten: Shape of an OP output feeding Reshape
    w = tf.constant(rs.randn(3, 3, 3, 4).astype(np.float32) * 0.2)
    y = tf.nn.conv2d(x, w, strides=2, padding="VALID")
    return tf.reshape(y, [tf.shape(y)[0], -1])


# ---- Shape-derived scalar inputs (the _static_value fallback: size/k/
# axis/multiples arriving from integer Shape subgraphs, not Consts) ----

@case("topk-k-from-shape", [spec(3, 8)], [F(3, 8)])
def _g(x):
    # k = rank-derived scalar (Shape -> StridedSlice -> floordiv)
    k = tf.shape(x)[1] // 4
    vals, idx = tf.math.top_k(x, k=k)
    return vals, tf.cast(idx, tf.int32)


@case("resize-size-from-shape", [spec(1, 4, 6, 2)], [F(1, 4, 6, 2)])
def _g(x):
    # target size = 2x the input's own (static) spatial shape
    sz = tf.shape(x)[1:3] * 2
    return tf.image.resize(x, sz, method="nearest")


@case("tile-reps-from-shape", [spec(2, 3)], [F(2, 3)])
def _g(x):
    reps = tf.stack([tf.shape(x)[1] // 3, 2])
    return tf.tile(x, reps)


@case("fill-dims-from-shape", [spec(2, 5)], [F(2, 5)])
def _g(x):
    dims = tf.shape(x) + 1
    return tf.fill(dims, 0.5) + tf.reduce_mean(x)


@case("cumsum-axis-from-rank", [spec(2, 6)], [F(2, 6)])
def _g(x):
    axis = tf.rank(x) - 1
    return tf.cumsum(x, axis=axis)


@pytest.mark.parametrize("name,fn,specs,inputs,tol", CORPUS,
                         ids=[c[0] for c in CORPUS])
def test_tf_graph_conformance(name, fn, specs, inputs, tol):
    tfn = tf.function(fn)
    frozen = convert_variables_to_constants_v2(
        tfn.get_concrete_function(*specs))
    gd = frozen.graph.as_graph_def()
    sd = import_graph_def(gd)
    feeds = {s.name: a for s, a in zip(specs, inputs)}
    # golden from the FROZEN function: cases that bake random constants
    # at trace time must be compared against that same trace
    wants = frozen(*[tf.constant(a) for a in inputs])
    if not isinstance(wants, (list, tuple)):
        wants = [wants]
    outs = [t.name.split(":")[0] for t in frozen.outputs]
    for out_name, want in zip(outs, wants):
        got = np.asarray(sd.output(feeds, out_name)[out_name])
        np.testing.assert_allclose(got, np.asarray(want), rtol=tol,
                                   atol=tol, err_msg=f"{name}:{out_name}")


def test_corpus_size():
    """The corpus must stay at TFGraphTestAllSameDiff scale."""
    assert len(CORPUS) >= 40, len(CORPUS)


def test_tf1_legacy_resize_rejected():
    """TF1 sampling (half_pixel_centers=False / align_corners=True)
    samples different source pixels than jax.image.resize — importing it
    silently mismatches the source model, so the importer must REFUSE
    with a diagnostic rather than produce wrong values."""
    from deeplearning4j_tpu.modelimport.tf_import import (
        UnmappedTFOpException)

    @tf.function
    def f(x):
        return tf.raw_ops.ResizeBilinear(
            images=x, size=[8, 8], align_corners=False,
            half_pixel_centers=False)

    frozen = convert_variables_to_constants_v2(
        f.get_concrete_function(tf.TensorSpec((1, 4, 4, 2), tf.float32,
                                              name="x")))
    with pytest.raises(UnmappedTFOpException, match="half_pixel_centers"):
        import_graph_def(frozen.graph.as_graph_def())
