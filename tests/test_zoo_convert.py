"""Zoo pretrained-weights converter round-trip tests (reference
`ZooModel.initPretrained()`): source checkpoint (synthetic weights) ->
converter artifact -> `pretrained()` -> predictions match the source.
"""
import os

import numpy as np
import pytest

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.zoo.convert import convert, main  # noqa: E402


def _tf_vgg16(input_shape, n_classes):
    """TF mirror of zoo VGG16 (`zoo/models.py` BLOCKS) with random
    (synthetic) weights."""
    tf.keras.utils.set_random_seed(0)
    layers = [tf.keras.layers.Input(input_shape)]
    for n_convs, ch in [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]:
        for _ in range(n_convs):
            layers.append(tf.keras.layers.Conv2D(ch, 3, padding="same",
                                                 activation="relu"))
        layers.append(tf.keras.layers.MaxPooling2D())
    layers += [
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(4096, activation="relu"),
        tf.keras.layers.Dropout(0.5),
        tf.keras.layers.Dense(4096, activation="relu"),
        tf.keras.layers.Dropout(0.5),
        tf.keras.layers.Dense(n_classes, activation="softmax"),
    ]
    return tf.keras.Sequential(layers)


def test_vgg16_npz_roundtrip_via_pretrained(tmp_path):
    """Keras VGG16 (synthetic weights) -> npz -> zoo VGG16.pretrained():
    flat layouts align, predictions match TF."""
    from deeplearning4j_tpu.zoo import VGG16
    km = _tf_vgg16((32, 32, 3), 4)
    src = str(tmp_path / "vgg16.h5")
    km.save(src)
    dst = str(tmp_path / "vgg16.npz")
    msg = convert(src, dst, "npz")
    assert "positional params" in msg
    net = VGG16(n_classes=4, input_shape=(32, 32, 3)).pretrained(dst)
    x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               km.predict(x, verbose=0),
                               rtol=1e-3, atol=1e-4)


def test_resnet50_zip_roundtrip_via_pretrained(tmp_path):
    """Keras-applications ResNet50 (synthetic weights) -> model zip ->
    pretrained(): the zip is self-describing, predictions match TF."""
    from deeplearning4j_tpu.zoo import ResNet50
    tf.keras.utils.set_random_seed(0)
    km = tf.keras.applications.resnet50.ResNet50(
        weights=None, input_shape=(32, 32, 3), classes=7)
    src = str(tmp_path / "resnet50.h5")
    km.save(src)
    dst = str(tmp_path / "resnet50.zip")
    msg = convert(src, dst, "zip")
    assert "model zip" in msg
    net = ResNet50(n_classes=7, input_shape=(32, 32, 3)).pretrained(dst)
    x = np.random.RandomState(1).rand(2, 32, 32, 3).astype(np.float32)
    (got,) = net.output(x)
    np.testing.assert_allclose(np.asarray(got), km.predict(x, verbose=0),
                               rtol=1e-3, atol=1e-4)
    # the imported weights must be trainable end-to-end: fine-tune on a
    # small batch and require the loss to decrease (reference: the
    # transfer-learning-on-initPretrained workflow)
    rs = np.random.RandomState(2)
    xb = rs.rand(4, 32, 32, 3).astype(np.float32)
    yb = np.eye(7, dtype=np.float32)[rs.randint(0, 7, 4)]
    net.fit(xb, yb)
    first = float(net.score())
    scores = []
    for _ in range(15):
        net.fit(xb, yb)
        scores.append(float(net.score()))
    assert min(scores) < first, (first, scores)


def test_convert_cli_entry(tmp_path):
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6,)),
        tf.keras.layers.Dense(4, activation="softmax")])
    src = str(tmp_path / "m.h5")
    km.save(src)
    dst = str(tmp_path / "m.npz")
    main([src, dst])
    data = np.load(dst)
    assert sum(data[k].size for k in data.files) == 6 * 4 + 4


def test_manifest_fetch_and_init_pretrained(tmp_path):
    """Checksum-verified manifest distribution (reference
    `ZooModel.initPretrained` + `DL4JResources` cache semantics)."""
    from deeplearning4j_tpu.zoo import LeNet
    from deeplearning4j_tpu.zoo.manifest import (build_manifest, fetch,
                                                 load_manifest,
                                                 sha256_file)

    host = tmp_path / "host"
    host.mkdir()
    net = LeNet(n_classes=10, input_shape=(28, 28, 1)).init_model()
    flat = np.asarray(net.params())
    np.savez(host / "LeNet.npz", params=flat)

    mpath = build_manifest(str(host))
    entries = load_manifest(mpath)
    assert entries["LeNet"]["sha256"] == sha256_file(
        str(host / "LeNet.npz"))

    cache = tmp_path / "cache"
    calls = []

    def hook(url, dest):
        calls.append(url)
        import shutil
        shutil.copyfile(url, dest)

    p1 = fetch("LeNet", mpath, cache_dir=str(cache), fetch_hook=hook)
    assert len(calls) == 1 and os.path.dirname(p1) == str(cache)
    # cache hit: the hook is NOT called again
    p2 = fetch("LeNet", mpath, cache_dir=str(cache), fetch_hook=hook)
    assert p2 == p1 and len(calls) == 1

    # corrupt fetch -> checksum rejection, nothing cached
    def bad_hook(url, dest):
        with open(dest, "wb") as f:
            f.write(b"garbage")

    os.remove(p1)
    with pytest.raises(IOError, match="checksum mismatch"):
        fetch("LeNet", mpath, cache_dir=str(cache), fetch_hook=bad_hook)
    assert not os.path.exists(p1)

    # end-to-end: init_pretrained resolves through the manifest
    loaded = LeNet(n_classes=10, input_shape=(28, 28, 1)).init_pretrained(
        mpath, cache_dir=str(cache), fetch_hook=hook)
    np.testing.assert_allclose(np.asarray(loaded.params()), flat)

    # unknown model name is a KeyError listing what exists
    with pytest.raises(KeyError, match="LeNet"):
        fetch("NoSuchModel", mpath, cache_dir=str(cache), fetch_hook=hook)


def test_convert_accepts_keras_v3_zip(tmp_path):
    """The converter CLI consumes the Keras 3 `.keras` container through
    the same import path as legacy H5."""
    from deeplearning4j_tpu.modelimport import KerasModelImport

    tf.keras.utils.set_random_seed(9)
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6,)),
        tf.keras.layers.Dense(5, activation="relu"),
        tf.keras.layers.Dense(3, activation="softmax")])
    src = str(tmp_path / "m.keras")
    km.save(src)
    dst = str(tmp_path / "m.npz")
    msg = convert(src, dst, "npz")
    assert "npz" in msg
    net = KerasModelImport.import_keras_sequential_model_and_weights(src)
    x = np.random.RandomState(2).rand(2, 6).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               km.predict(x, verbose=0),
                               rtol=1e-4, atol=1e-5)
