"""Pipeline-parallel tests on the virtual CPU mesh (conftest.py): GPipe
schedule correctness vs the sequential oracle, gradient equivalence
(reverse pipeline via jax.grad), and end-to-end training."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline import (pipeline_apply,
                                                  sequential_apply,
                                                  stack_stage_params)


def _block(params, x):
    return jnp.tanh(x @ params["W"] + params["b"])


def _stages(S=4, D=8, seed=0):
    rng = np.random.RandomState(seed)
    return stack_stage_params([
        {"W": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.4),
         "b": jnp.asarray(rng.randn(D).astype(np.float32) * 0.1)}
        for _ in range(S)])


def test_pipeline_forward_matches_sequential():
    S, D, B = 4, 8, 16
    mesh = make_mesh({"pipe": S}, jax.devices()[:S])
    params = _stages(S, D)
    x = jnp.asarray(np.random.RandomState(1).randn(B, D)
                    .astype(np.float32))
    want = sequential_apply(_block, params, x)
    got = pipeline_apply(_block, params, x, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    # more microbatches than stages also works
    got8 = pipeline_apply(_block, params, x, mesh, num_microbatches=8)
    np.testing.assert_allclose(np.asarray(got8), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    """jax.grad through the scan/ppermute IS the reverse pipeline
    schedule — gradients must equal the sequential model's."""
    S, D, B = 4, 8, 8
    mesh = make_mesh({"pipe": S}, jax.devices()[:S])
    params = _stages(S, D, seed=2)
    x = jnp.asarray(np.random.RandomState(3).randn(B, D)
                    .astype(np.float32))
    y = jnp.asarray(np.random.RandomState(4).randn(B, D)
                    .astype(np.float32))

    def loss_pipe(p):
        out = pipeline_apply(_block, p, x, mesh)
        return jnp.mean((out - y) ** 2)

    def loss_seq(p):
        out = sequential_apply(_block, p, x)
        return jnp.mean((out - y) ** 2)

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        g_pipe, g_seq)


def test_pipeline_training_decreases_loss():
    S, D, B = 4, 6, 24
    mesh = make_mesh({"pipe": S}, jax.devices()[:S])
    params = _stages(S, D, seed=5)
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))
    y = jnp.asarray(rng.randn(B, D).astype(np.float32) * 0.3)

    @jax.jit
    def step(p):
        def loss_fn(pp):
            out = pipeline_apply(_block, pp, x, mesh,
                                 num_microbatches=6)
            return jnp.mean((out - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.2 * b, p, g), loss

    params, first = step(params)
    for _ in range(30):
        params, loss = step(params)
    assert float(loss) < float(first) * 0.7


def test_pipeline_batch_divisibility_error():
    S = 4
    mesh = make_mesh({"pipe": S}, jax.devices()[:S])
    params = _stages(S, 4)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(_block, params,
                       jnp.zeros((10, 4)), mesh, num_microbatches=4)
