"""Regression tests for review findings (round 1)."""
import json

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import (
    DenseLayer, InputType, MultiLayerNetwork, NeuralNetConfiguration,
    OutputLayer, BatchNormalizationLayer)
from deeplearning4j_tpu.ops.losses import mse, xent
from deeplearning4j_tpu.train import Adam, AdamW, MapSchedule
from deeplearning4j_tpu.train.updaters import IUpdater


def test_adamw_applies_weight_decay():
    params = {"W": jnp.ones((3, 3))}
    grads = {"W": jnp.zeros((3, 3))}
    u = AdamW(1e-2, weight_decay=0.1)
    upd, _ = u.apply(u.init_state(params), grads, 0, params=params)
    # zero grads -> update is purely lr*wd*p
    np.testing.assert_allclose(np.asarray(upd["W"]), 1e-2 * 0.1, rtol=1e-6)
    plain, _ = Adam(1e-2).apply(Adam(1e-2).init_state(params), grads, 0,
                                params=params)
    assert not np.allclose(np.asarray(upd["W"]), np.asarray(plain["W"]))


def test_score_for_uses_eval_mode_batchnorm():
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .list([DenseLayer(n_out=4, activation="identity",
                              weight_init="XAVIER"),
                   BatchNormalizationLayer(),
                   OutputLayer(n_out=2, loss="mcxent", activation="softmax",
                               weight_init="XAVIER")])
            .set_input_type(InputType.feed_forward(3)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(5.0, 1.0, (16, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.random.default_rng(1).integers(0, 2, 16)]
    # eval-mode score must agree with loss computed from output() probs
    # (clip at f32-tiny, not 1e-7 — untrained logits legitimately exceed ±16)
    probs = np.asarray(net.output(x))
    manual = -np.mean(np.sum(y * np.log(np.clip(probs, 1e-37, 1)), axis=-1))
    assert abs(net.score_for(x, y) - manual) < 1e-3
    # and it must NOT equal the train-mode (batch-stats) loss
    train_loss = float(net._loss(net.params_, net.state_, jnp.asarray(x),
                                 jnp.asarray(y), None, train=True)[0])
    assert abs(net.score_for(x, y) - train_loss) > 0.1


def test_masked_timeseries_losses():
    labels = jnp.ones((2, 4, 3))
    preds = jnp.zeros((2, 4, 3))
    mask = jnp.array([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
    # mse: masked timesteps excluded; all errors are 1 -> mean = 1
    assert float(mse(labels, preds, mask)) == 1.0
    # unmasked differs when preds nonzero in masked region
    preds2 = preds.at[0, 3].set(100.0)
    assert float(mse(labels, preds2, mask)) == float(mse(labels, preds, mask))
    # xent with [batch, time] mask runs without shape errors
    assert np.isfinite(float(xent(labels, preds, mask)))


def test_mapschedule_json_roundtrip():
    u = Adam(MapSchedule({0: 0.1, 10: 0.01}))
    u2 = IUpdater.from_json(json.loads(json.dumps(u.to_json())))
    assert float(u2.lr_at(5)) == 0.1
    assert float(u2.lr_at(15)) == 0.01


def test_labels_mask_threaded_from_dataset():
    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .list([DenseLayer(n_out=4, activation="tanh", weight_init="XAVIER"),
                   OutputLayer(n_out=2, loss="mse", activation="identity",
                               weight_init="XAVIER")])
            .set_input_type(InputType.recurrent(3, 4)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 4, 3)).astype(np.float32)
    y = np.zeros((2, 4, 2), np.float32)
    lmask = np.array([[1, 1, 0, 0], [1, 1, 1, 1]], np.float32)
    ds = DataSet(x, y, labels_mask=lmask)
    net.fit(ListDataSetIterator([ds]))  # must run with mask threading
    assert np.isfinite(net.score())
