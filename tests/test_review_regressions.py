"""Regression tests for review findings (round 1)."""
import json

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import (
    DenseLayer, InputType, MultiLayerNetwork, NeuralNetConfiguration,
    OutputLayer, BatchNormalizationLayer)
from deeplearning4j_tpu.ops.losses import mse, xent
from deeplearning4j_tpu.train import Adam, AdamW, MapSchedule
from deeplearning4j_tpu.train.updaters import IUpdater


def test_adamw_applies_weight_decay():
    params = {"W": jnp.ones((3, 3))}
    grads = {"W": jnp.zeros((3, 3))}
    u = AdamW(1e-2, weight_decay=0.1)
    upd, _ = u.apply(u.init_state(params), grads, 0, params=params)
    # zero grads -> update is purely lr*wd*p
    np.testing.assert_allclose(np.asarray(upd["W"]), 1e-2 * 0.1, rtol=1e-6)
    plain, _ = Adam(1e-2).apply(Adam(1e-2).init_state(params), grads, 0,
                                params=params)
    assert not np.allclose(np.asarray(upd["W"]), np.asarray(plain["W"]))


def test_score_for_uses_eval_mode_batchnorm():
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .list([DenseLayer(n_out=4, activation="identity",
                              weight_init="XAVIER"),
                   BatchNormalizationLayer(),
                   OutputLayer(n_out=2, loss="mcxent", activation="softmax",
                               weight_init="XAVIER")])
            .set_input_type(InputType.feed_forward(3)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(5.0, 1.0, (16, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.random.default_rng(1).integers(0, 2, 16)]
    # eval-mode score must agree with loss computed from output() probs
    # (clip at f32-tiny, not 1e-7 — untrained logits legitimately exceed ±16)
    probs = np.asarray(net.output(x))
    manual = -np.mean(np.sum(y * np.log(np.clip(probs, 1e-37, 1)), axis=-1))
    assert abs(net.score_for(x, y) - manual) < 1e-3
    # and it must NOT equal the train-mode (batch-stats) loss
    train_loss = float(net._loss(net.params_, net.state_, jnp.asarray(x),
                                 jnp.asarray(y), None, train=True)[0])
    assert abs(net.score_for(x, y) - train_loss) > 0.1


def test_masked_timeseries_losses():
    labels = jnp.ones((2, 4, 3))
    preds = jnp.zeros((2, 4, 3))
    mask = jnp.array([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
    # mse: masked timesteps excluded; all errors are 1 -> mean = 1
    assert float(mse(labels, preds, mask)) == 1.0
    # unmasked differs when preds nonzero in masked region
    preds2 = preds.at[0, 3].set(100.0)
    assert float(mse(labels, preds2, mask)) == float(mse(labels, preds, mask))
    # xent with [batch, time] mask runs without shape errors
    assert np.isfinite(float(xent(labels, preds, mask)))


def test_mapschedule_json_roundtrip():
    u = Adam(MapSchedule({0: 0.1, 10: 0.01}))
    u2 = IUpdater.from_json(json.loads(json.dumps(u.to_json())))
    assert float(u2.lr_at(5)) == 0.1
    assert float(u2.lr_at(15)) == 0.01


def test_labels_mask_threaded_from_dataset():
    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .list([DenseLayer(n_out=4, activation="tanh", weight_init="XAVIER"),
                   OutputLayer(n_out=2, loss="mse", activation="identity",
                               weight_init="XAVIER")])
            .set_input_type(InputType.recurrent(3, 4)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 4, 3)).astype(np.float32)
    y = np.zeros((2, 4, 2), np.float32)
    lmask = np.array([[1, 1, 0, 0], [1, 1, 1, 1]], np.float32)
    ds = DataSet(x, y, labels_mask=lmask)
    net.fit(ListDataSetIterator([ds]))  # must run with mask threading
    assert np.isfinite(net.score())


# ---- round 2: ADVICE.md findings ----

def _tiny_net(seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list([DenseLayer(n_out=8, activation="relu"),
                   OutputLayer(n_out=2, loss="mcxent", activation="softmax")])
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _xy(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return x, y


def test_transfer_learning_does_not_alias_donated_buffers():
    """ADVICE r1 (medium): fit() on the derived net must not delete the
    source net's buffers via donation."""
    from deeplearning4j_tpu.nn.transferlearning import (TransferLearning,
                                                        TransferLearningHelper)
    x, y = _xy()
    src = _tiny_net()
    src.fit(x, y)
    derived = TransferLearning.builder(src).set_feature_extractor(0).build()
    derived.fit(x, y)
    out = np.asarray(src.output(x))          # must not raise "deleted"
    assert np.all(np.isfinite(out))

    from deeplearning4j_tpu.data.dataset import DataSet
    helper = TransferLearningHelper(src, frozen_till=0)
    feat = helper.featurize(DataSet(x, y))
    helper.fit_featurized(feat)              # donates unfrozen-net buffers
    out2 = np.asarray(src.output(x))         # source must stay intact
    assert np.all(np.isfinite(out2))


def test_inmemory_saver_best_survives_later_fit():
    """ADVICE r1: restoring best then fitting must not destroy the stored
    snapshot for subsequent restores."""
    from deeplearning4j_tpu.train.earlystopping import InMemoryModelSaver
    x, y = _xy()
    net = _tiny_net()
    net.fit(x, y)
    saver = InMemoryModelSaver()
    saver.save_best_model(net)
    best_params = np.asarray(saver._best[0]["layer_0"]["W"]).copy()
    m = saver.get_best_model()
    m.fit(x, y)                               # donates the restored buffers
    m2 = saver.get_best_model()               # must still restore cleanly
    np.testing.assert_allclose(
        np.asarray(m2.params_["layer_0"]["W"]), best_params)


def test_checkpoint_listener_epoch_cadence(tmp_path):
    """ADVICE r1: every_n_epochs=2 fires after epochs 2,4,... not 1,3."""
    from deeplearning4j_tpu.train.listeners import CheckpointListener

    class FakeModel:
        epoch = 0
        iteration = 0

        def save(self, path):
            with open(path, "w") as f:
                f.write("x")

    lst = CheckpointListener(str(tmp_path), every_n_epochs=2)
    m = FakeModel()
    fired = []
    for ep in range(1, 5):
        m.epoch = ep                          # completed epochs count
        before = len(lst._saved)
        lst.on_epoch_end(m)
        if len(lst._saved) > before:
            fired.append(ep)
    assert fired == [2, 4]


def test_gather_indexed_rejects_out_of_range():
    """ADVICE r1: native path must validate indices, not memcpy OOB."""
    from deeplearning4j_tpu.native_ops import gather_indexed
    base = np.arange(12, dtype=np.float32).reshape(4, 3)
    np.testing.assert_array_equal(gather_indexed(base, [2, 0]),
                                  base[[2, 0]])
    for bad in ([-1], [4], [0, 100]):
        try:
            gather_indexed(base, bad)
            assert False, f"expected IndexError for {bad}"
        except IndexError:
            pass
