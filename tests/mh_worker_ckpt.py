"""Sharded-checkpoint worker (spawned by test_checkpoint_sharded via
LocalLauncher — NOT a pytest file).

Modes:
  save <dir>              — build a deterministic tree sharded over the
                            2-process global mesh and save_sharded it.
  train_save <dir> <k>    — train k steps, save_model_sharded, train k
                            more, dump final params (exact-resume oracle).
  resume <dir> <k>        — restore under a fresh cluster, train k steps,
                            dump final params (must match the oracle).
"""
import os
import sys

import numpy as np

from deeplearning4j_tpu.parallel import multihost

multihost.initialize()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from deeplearning4j_tpu.parallel.checkpoint import (  # noqa: E402
    load_model_sharded, save_model_sharded, save_sharded)

mode = sys.argv[1]
out_dir = sys.argv[2]
rank = multihost.process_index()
mesh = multihost.global_mesh()


def make_net():
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train import Adam
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.05))
            .list([DenseLayer(n_out=16, activation="tanh"),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(10)).build())
    return MultiLayerNetwork(conf).init()


def local_batch():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((16, 10)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[(X.sum(1) > 0).astype(int)]
    world = multihost.process_count()
    per = X.shape[0] // world
    return (X[rank * per:(rank + 1) * per],
            Y[rank * per:(rank + 1) * per])


if mode == "save":
    # deterministic global values, sharded + replicated + host leaves
    big = np.arange(48, dtype=np.float32).reshape(8, 6)
    sharded = multihost.shard_host_local_batch(
        mesh, big[rank * 4:(rank + 1) * 4])        # [8, 6] over 'data'
    replicated = jax.device_put(
        jnp.asarray(np.arange(5, dtype=np.float32) * 2),
        NamedSharding(mesh, P()))
    tree = {"w": sharded, "b": replicated,
            "step": np.int64(17), "host": np.full(3, 9.0, np.float32)}
    save_sharded(out_dir, tree, metadata={"note": "roundtrip"})
    print(f"rank {rank}: saved", flush=True)

elif mode == "train_save":
    k = int(sys.argv[3])
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    net = make_net()
    pw = ParallelWrapper(net, mesh)
    xl, yl = local_batch()
    for _ in range(k):
        pw.fit_host_local(xl, yl)
    save_model_sharded(net, out_dir)
    for _ in range(k):
        pw.fit_host_local(xl, yl)
    if rank == 0:
        np.savez(os.path.join(out_dir, "oracle.npz"),
                 params=np.asarray(net.params()))
    print(f"rank {rank}: trained+saved score={net.score():.6f}",
          flush=True)

elif mode == "resume":
    k = int(sys.argv[3])
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    net = make_net()
    pw = ParallelWrapper(net, mesh)
    xl, yl = local_batch()
    pw.fit_host_local(xl, yl)          # materialize opt state to restore
    load_model_sharded(net, out_dir)
    for _ in range(k):
        pw.fit_host_local(xl, yl)
    if rank == 0:
        np.savez(os.path.join(out_dir, "resumed.npz"),
                 params=np.asarray(net.params()))
    print(f"rank {rank}: resumed score={net.score():.6f}", flush=True)

else:
    raise SystemExit(f"unknown mode {mode}")
