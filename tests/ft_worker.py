"""Single-process fault-tolerant training worker (spawned by
test_resilience — NOT a pytest file).

Trains a small seeded MLN under `FaultTolerantTrainer` with periodic
checkpoints; a `chaos.KillSwitch` hook kills the process partway on the
FIRST launch (marker file guards the one-shot).  The test relaunches the
same command line until it exits 0, then compares `final.npz` against an
uninterrupted run — auto-resume must be bitwise invisible.

argv: work_dir epochs kill_mode kill_at zero1 save_every fused prefetch
  kill_mode: none | sigterm | kill | exception
  zero1/fused/prefetch: 0|1
"""
import os
import sys

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from deeplearning4j_tpu.data import (ArrayDataSetIterator,  # noqa: E402
                                     DevicePrefetchIterator)
from deeplearning4j_tpu.data.normalizers import (  # noqa: E402
    NormalizerStandardize)
from deeplearning4j_tpu.nn import (DenseLayer, InputType,  # noqa: E402
                                   MultiLayerNetwork, NeuralNetConfiguration,
                                   OutputLayer)
from deeplearning4j_tpu.parallel import (ParallelWrapper,  # noqa: E402
                                         make_mesh)
from deeplearning4j_tpu.train import Adam  # noqa: E402
from deeplearning4j_tpu.train.resilience import (CheckpointManager,  # noqa: E402
                                                 FaultTolerantTrainer,
                                                 Preempted)
from deeplearning4j_tpu.utils import chaos  # noqa: E402

(work_dir, epochs, kill_mode, kill_at, zero1, save_every, fused,
 prefetch) = sys.argv[1:9]
epochs, kill_at = int(epochs), int(kill_at)
save_every = int(save_every)
zero1, fused, prefetch = zero1 == "1", fused == "1", prefetch == "1"

rng = np.random.default_rng(0)
X = rng.standard_normal((48, 10))
Y = np.eye(3)[rng.integers(0, 3, 48)]

conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01))
        .list([DenseLayer(n_out=16, activation="tanh"),
               OutputLayer(n_out=3, loss="mcxent", activation="softmax")])
        .set_input_type(InputType.feed_forward(10)).build())
net = MultiLayerNetwork(conf).init()
model = (ParallelWrapper(net, make_mesh(), optimizer_sharding=True)
         if zero1 else net)

manager = CheckpointManager(os.path.join(work_dir, "ckpt"), keep_last=3,
                            save_every_steps=save_every, async_save=True)
# pass the fitted normalizer only on a FRESH start; on resume the trainer
# must rebuild it from checkpoint metadata (that's part of the test)
norm = None
if manager.latest_step() is None:
    norm = NormalizerStandardize()
    norm.fit(ArrayDataSetIterator(X, Y, 8))

hooks = ()
if kill_mode != "none":
    hooks = (chaos.KillSwitch(at_step=kill_at, mode=kill_mode,
                              marker=os.path.join(work_dir, "killed_once")),)

data = ArrayDataSetIterator(X, Y, 8)
if prefetch:
    data = DevicePrefetchIterator(data)

trainer = FaultTolerantTrainer(model, manager, normalizer=norm, hooks=hooks)
try:
    trainer.fit(data, epochs=epochs, fused_steps=2 if fused else 1)
except Preempted as e:
    print(f"preempted at iteration {net.iteration}", flush=True)
    sys.exit(e.exit_code)

np.savez(os.path.join(work_dir, "final.npz"),
         params=np.asarray(net.params()),
         iteration=np.int64(net.iteration))
print(f"done at iteration {net.iteration}"
      + (f" (resumed from step {trainer.resumed_from['step']})"
         if trainer.resumed_from is not None else ""), flush=True)
