"""OpValidation specs, part 5: bfloat16 cases for MXU-facing ops.

Reference: the opvalidation corpus runs reduced-precision (half) cases
for the cuDNN-backed ops; the TPU-native equivalent is bfloat16 — the
dtype every matmul/conv actually runs in on the MXU.  Each case feeds
bf16 inputs and compares against an f32 golden computed from the SAME
bf16-rounded values, so the tolerance only has to absorb bf16
accumulation error, not input rounding.  No FD grads here (eps=1e-5 is
far below bf16 resolution); analytic-vs-FD is covered by the f32 cases.
"""
import numpy as np
import ml_dtypes

from tests.opval_specs_core import C

BF16 = np.dtype(ml_dtypes.bfloat16)
rs = np.random.RandomState(97531)


def B(*s, lo=-2.0, hi=2.0):
    """bf16 tensor arg (values exactly representable in bf16)."""
    return rs.uniform(lo, hi, s).astype(np.float32).astype(BF16)


def BP(*s, lo=0.5, hi=2.0):
    return rs.uniform(lo, hi, s).astype(np.float32).astype(BF16)


def _f32(a):
    return np.asarray(a, np.float32)


_TOL = 5e-2   # bf16 has an 8-bit mantissa: ~0.4% per element + reduce

CASES = [
    C("matmul", B(16, 32), B(32, 24),
      g=lambda a, b: _f32(a) @ _f32(b), tol=_TOL, tag="bf16"),
    C("mmul", B(8, 16), B(16, 8),
      g=lambda a, b: _f32(a) @ _f32(b), tol=_TOL, tag="bf16"),
    C("gemm", B(8, 12), B(12, 6),
      g=lambda a, b, c=None, alpha=1.0, beta=1.0, trans_a=0, trans_b=0:
      _f32(a) @ _f32(b), tol=_TOL, tag="bf16"),
    C("tensordot", B(4, 8, 6), B(6, 4, 5), kw={"axes": ([2], [0])},
      g=lambda a, b, axes=2: np.tensordot(_f32(a), _f32(b), axes),
      tol=_TOL, tag="bf16"),
    C("conv2d", B(2, 6, 6, 3, lo=-1, hi=1),
      B(3, 3, 3, 4, lo=-0.5, hi=0.5),
      g=lambda x, w, b=None, stride=(1, 1), padding="SAME",
      dilation=(1, 1): __import__(
          "tests.opval_specs_configs",
          fromlist=["_tf_conv2d_golden"])._tf_conv2d_golden(
          _f32(x), _f32(w), None, stride, padding, dilation),
      tol=_TOL, tag="bf16"),
    C("conv2d_nchw", B(2, 3, 5, 5, lo=-1, hi=1),
      B(4, 3, 3, 3, lo=-0.5, hi=0.5), kw={"pads": (1, 1, 1, 1)},
      g=lambda x, w, b=None, stride=(1, 1), pads=(1, 1, 1, 1),
      dilation=(1, 1), groups=1: __import__(
          "tests.opval_specs_nn",
          fromlist=["_nchw_conv_golden"])._nchw_conv_golden(
          _f32(x), _f32(w), None, stride, pads, dilation, groups),
      tol=_TOL, tag="bf16"),
    C("depthwise_conv2d", B(2, 6, 6, 3, lo=-1, hi=1),
      B(3, 3, 1, 6, lo=-0.5, hi=0.5),
      g=lambda x, w, stride=(1, 1), padding="SAME", dilation=(1, 1):
      __import__("tests.opval_specs_nn",
                 fromlist=["_depthwise_golden"])._depthwise_golden(
          _f32(x), _f32(w), stride, padding, dilation),
      tol=_TOL, tag="bf16"),
    C("batch_norm", B(4, 8), B(8, lo=-1, hi=1), BP(8), BP(8, lo=0.5,
                                                          hi=1.5),
      B(8, lo=-1, hi=1),
      g=lambda x, m, v, gamma, beta, eps=1e-5:
      (_f32(x) - _f32(m)) / np.sqrt(_f32(v) + eps) * _f32(gamma)
      + _f32(beta), tol=_TOL, tag="bf16"),
    C("layer_norm", B(6, 16), BP(16), B(16, lo=-1, hi=1),
      g=lambda x, gain, bias, eps=1e-5, axis=-1:
      (_f32(x) - _f32(x).mean(-1, keepdims=True))
      / np.sqrt(_f32(x).var(-1, keepdims=True) + eps) * _f32(gain)
      + _f32(bias), tol=_TOL, tag="bf16"),
    C("softmax", B(4, 16, lo=-3, hi=3),
      g=lambda a, axis=-1: (lambda e: e / e.sum(-1, keepdims=True))(
          np.exp(_f32(a) - _f32(a).max(-1, keepdims=True))),
      tol=_TOL, tag="bf16"),
    C("relu", B(3, 8), g=lambda a: np.maximum(_f32(a), 0.0), tol=_TOL,
      tag="bf16"),
    C("dot_product_attention", B(2, 6, 8, lo=-1, hi=1),
      B(2, 6, 8, lo=-1, hi=1), B(2, 6, 8, lo=-1, hi=1),
      g=lambda q, k, v, mask=None, scaled=True: __import__(
          "tests.opval_specs_nn", fromlist=["_dpa_golden"])._dpa_golden(
          _f32(q), _f32(k), _f32(v)), tol=_TOL, tag="bf16"),
]
