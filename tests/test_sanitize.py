"""Donation/aliasing sanitizers (SURVEY §5.2: the workspace-misuse
validation equivalent — named errors for use-after-donation and
cross-network buffer sharing)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.utils.sanitize import (BufferValidationError,
                                               assert_disjoint, assert_live,
                                               validate_network)


def _donate(tree):
    """Run a donating jitted identity-ish step, deleting the input buffers."""
    f = jax.jit(lambda t: jax.tree_util.tree_map(lambda a: a + 1.0, t),
                donate_argnums=(0,))
    return f(tree)


def test_assert_live_passes_then_catches_donation():
    tree = {"w": jnp.ones((4, 4)), "b": jnp.zeros(4)}
    assert_live(tree, "model")          # fresh buffers: fine
    _ = _donate(tree)
    with pytest.raises(BufferValidationError, match="donated"):
        assert_live(tree, "model")


def test_assert_disjoint_detects_shared_buffer():
    w = jnp.ones((3, 3))
    a = {"w": w}
    b = {"w": w}                         # alias — the transfer-learning bug
    with pytest.raises(BufferValidationError, match="shared"):
        assert_disjoint(a, b, "src vs dst")
    c = {"w": jnp.copy(w)}               # deep copy — correct transplant
    assert_disjoint(a, c, "src vs dst")


def test_validate_network_names_the_attribute():
    class Net:
        pass

    net = Net()
    net.params_ = {"dense": {"W": jnp.ones((2, 2))}}
    net.state_ = None
    validate_network(net)
    _ = _donate(net.params_)
    with pytest.raises(BufferValidationError, match="params_"):
        validate_network(net)


def test_transfer_learning_nets_hold_disjoint_buffers():
    """Regression guard for ADVICE r1 (transferlearning.py transplant by
    reference): derived net must not share donated buffers with source."""
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.nn.transferlearning import TransferLearning

    conf = (NeuralNetConfiguration.builder().seed(0)
            .list([DenseLayer(n_out=8, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(5)).build())
    src = MultiLayerNetwork(conf).init()
    derived = TransferLearning.builder(src).set_feature_extractor(0).build()
    assert_disjoint(src.params_, derived.params_, "src vs transfer")
    x = np.random.RandomState(0).rand(4, 5).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
    derived.fit(x, y)
    out = src.output(x)                  # source must survive derived's fit
    assert np.isfinite(np.asarray(out)).all()
