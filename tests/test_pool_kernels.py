"""Taps max-pool backward vs XLA select-and-scatter (ops/pool_kernels.py).

Reference role: cuDNN PoolingBackward in CudnnSubsamplingHelper; here the
taps VJP is the TPU-shaped alternative, adopted only on measurement
(tunnel_playbook stage 11)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from deeplearning4j_tpu.ops.pool_kernels import (POOL_BWD_TAPS,
                                                 max_pool2d_taps)


def _xla_pool(x, kernel, stride, padding):
    pad = padding
    if not isinstance(pad, str):
        pad = ((0, 0), tuple(pad[0]), tuple(pad[1]), (0, 0))
    return lax.reduce_window(x, -jnp.inf, lax.max,
                             (1,) + tuple(kernel) + (1,),
                             (1,) + tuple(stride) + (1,), pad)


CONFIGS = [
    ((3, 3), (2, 2), "SAME", (2, 13, 13, 4)),      # resnet stem shape class
    ((2, 2), (2, 2), "VALID", (2, 12, 12, 3)),
    ((3, 3), (1, 1), "SAME", (1, 9, 9, 2)),
    ((3, 2), (2, 3), "VALID", (2, 11, 10, 3)),     # odd kernel/stride mix
    ((3, 3), (2, 2), ((0, 1), (1, 0)), (1, 10, 10, 2)),  # explicit asym
    ((2, 2), (2, 2), "VALID", (1, 13, 13, 1)),     # cropped VALID tail
]


@pytest.mark.parametrize("kernel,stride,padding,shape", CONFIGS)
def test_taps_forward_matches_xla(kernel, stride, padding, shape):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(*shape).astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(max_pool2d_taps(x, kernel, stride, padding)),
        np.asarray(_xla_pool(x, kernel, stride, padding)))


@pytest.mark.parametrize("kernel,stride,padding,shape", CONFIGS)
def test_taps_grad_matches_xla_on_distinct_values(kernel, stride, padding,
                                                  shape):
    """With no exact ties (continuous random values), the taps VJP must
    equal XLA's select-and-scatter gradient exactly."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.rand(*shape).astype(np.float32))
    t = _xla_pool(x, kernel, stride, padding) * 0.7

    g_taps = jax.grad(
        lambda a: jnp.sum((max_pool2d_taps(a, kernel, stride, padding)
                           - t) ** 2))(x)
    g_xla = jax.grad(
        lambda a: jnp.sum((_xla_pool(a, kernel, stride, padding)
                           - t) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_taps), np.asarray(g_xla),
                               rtol=1e-6, atol=1e-6)


def test_taps_grad_splits_ties_conservatively():
    """On a constant input every window ties everywhere; the taps VJP
    splits dy evenly — sum(dx) must still equal sum(dy) (a valid
    subgradient), where select-and-scatter gives all to the first max."""
    x = jnp.ones((1, 8, 8, 1), jnp.float32)
    y, vjp = jax.vjp(
        lambda a: max_pool2d_taps(a, (2, 2), (2, 2), "VALID"), x)
    dy = jnp.full_like(y, 3.0)
    (dx,) = vjp(dy)
    assert np.isclose(float(jnp.sum(dx)), float(jnp.sum(dy)))
    # even split: each of the 4 window positions gets dy/4
    np.testing.assert_allclose(np.asarray(dx), 0.75)


def test_layer_routes_through_flag():
    """SubsamplingLayer takes the taps path only when the flag is on, and
    training results stay consistent (no ties in random data)."""
    from deeplearning4j_tpu.nn import (ConvolutionLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer,
                                       SubsamplingLayer)
    from deeplearning4j_tpu.train import Adam

    def build():
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
                .list([ConvolutionLayer(n_out=4, kernel_size=3,
                                        convolution_mode="Same"),
                       SubsamplingLayer(kernel_size=3, stride=2,
                                        convolution_mode="Same"),
                       OutputLayer(n_out=2, loss="mcxent",
                                   activation="softmax")])
                .set_input_type(InputType.convolutional(12, 12, 2)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.RandomState(0)
    x = rng.rand(8, 12, 12, 2).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)]

    a = build()
    for _ in range(3):
        a.fit(x, y)
    old = dict(POOL_BWD_TAPS)
    try:
        POOL_BWD_TAPS["enabled"] = True
        b = build()
        for _ in range(3):
            b.fit(x, y)
    finally:
        POOL_BWD_TAPS.clear()
        POOL_BWD_TAPS.update(old)
    np.testing.assert_allclose(np.asarray(a.params()),
                               np.asarray(b.params()), rtol=2e-5,
                               atol=1e-6)
