"""Federation host worker (spawned by test_federation's multi-process
scenario and `examples/federated_fleet.py` — NOT a pytest file).

Each process runs ONE failure domain: a full `ModelFleet` (model "m",
deployed warm against the SHARED persistent AOT cache under `work_dir`)
wrapped by a `HostAgent` that joins the parent's `FederationRouter` over
loopback TCP.  Every host builds the SAME seeded net, so a survivor can
warm-re-place a dead host's model with zero fresh compiles.

A `HostChaos(mode="kill", os_kill=True)` hook (argv-armed) hard-kills
the whole process at dispatch `kill_after` — the real multi-process form
of a host crash; the marker file keeps a relaunched replacement from
re-firing.  The worker drops `<host_id>.ready` once WELCOMEd, then parks
until the parent creates `stop`, finally writing `<host_id>.done` with
`agent.describe()` so the parent can assert generations and rejoins.

argv: host_id port work_dir [kill_after]
  kill_after -1 (default) disables chaos
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import (FederationPolicy, HostAgent,
                                        LatencySLO, ModelFleet)
from deeplearning4j_tpu.train.updaters import Sgd
from deeplearning4j_tpu.utils.chaos import HostChaos

host_id = sys.argv[1]
port = int(sys.argv[2])
work_dir = sys.argv[3]
kill_after = int(sys.argv[4]) if len(sys.argv) > 4 else -1

N_IN, N_OUT = 8, 3

conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(1e-1))
        .list([DenseLayer(n_out=16, activation="relu"),
               OutputLayer(n_out=N_OUT, loss="mcxent",
                           activation="softmax")])
        .set_input_type(InputType.feed_forward(N_IN)).build())
net = MultiLayerNetwork(conf).init()

host_dir = os.path.join(work_dir, host_id)
os.makedirs(host_dir, exist_ok=True)
fleet = ModelFleet(max_resident=2, n_slices=2, max_batch=8,
                   batch_timeout_ms=1.0,
                   cache_dir=os.path.join(work_dir, "exec-cache"),
                   snapshot_path=os.path.join(host_dir, "snapshot.json"),
                   snapshot_interval_s=0.2, host_id=host_id)
fleet.deploy("m", net, slo=LatencySLO(target_p99_ms=2000.0, priority=5),
             warm=True)

policy = FederationPolicy(heartbeat_interval_s=0.1, failure_deadline_s=0.8,
                          straggler_deadline_s=5.0)
agent = HostAgent(host_id, fleet, ("127.0.0.1", port), policy=policy,
                  replicas_dir=os.path.join(host_dir, "replicas"))
agent.start(timeout=30.0)
if kill_after >= 0:
    chaos = HostChaos(mode="kill", at_dispatch=kill_after, os_kill=True,
                      marker=os.path.join(work_dir, f"{host_id}.killed"))
    if chaos.armed():
        chaos.arm(agent)
fleet.save_snapshot()                    # replicate topology to the router

with open(os.path.join(work_dir, f"{host_id}.ready"), "w") as f:
    json.dump({"generation": agent.generation, "pid": os.getpid()}, f)
print(f"{host_id}: joined at generation {agent.generation}", flush=True)

stop = os.path.join(work_dir, "stop")
while not os.path.exists(stop):
    time.sleep(0.05)

with open(os.path.join(work_dir, f"{host_id}.done"), "w") as f:
    json.dump(agent.describe(), f)
agent.close()
fleet.shutdown()
print(f"{host_id}: done at generation {agent.generation} "
      f"(rejoins={agent.rejoins})", flush=True)
