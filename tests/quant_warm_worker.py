"""Subprocess worker for test_quant.test_quantized_warm_restart_subprocess.

Builds a deterministic MLN, calibrates + quantizes it, and serves it
through a `BucketedCompileCache` backed by the persistent executable
cache at $DL4J_TPU_TEST_CACHE.  Prints one JSON line: cache stats, the
f32 and quantized model fingerprints, and an output checksum.  Run twice
against the same directory, the second run must report 0 compiles — the
quantized executables round-tripping the persistent AOT tier — and the
identical fingerprints/checksum (quantization is a pure function of
weights + calibration + config).
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.compile import (PersistentExecutableCache,  # noqa: E402
                                        model_fingerprint)
from deeplearning4j_tpu.nn import (DenseLayer, InputType,  # noqa: E402
                                   MultiLayerNetwork, NeuralNetConfiguration,
                                   OutputLayer)
from deeplearning4j_tpu.quant import calibrate, quantize_model  # noqa: E402
from deeplearning4j_tpu.serving import BucketedCompileCache  # noqa: E402
from deeplearning4j_tpu.train.updaters import Sgd  # noqa: E402


def main():
    cache = PersistentExecutableCache(os.environ["DL4J_TPU_TEST_CACHE"])
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=32, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(0)
    calib = [rs.randn(8, 8).astype(np.float32) for _ in range(4)]
    stats = calibrate(net, calib, observer="percentile", percentile=99.5)
    qm = quantize_model(net, calibration=stats)

    scache = BucketedCompileCache(max_batch=8, persistent=cache)
    scache.warmup("q:v1", qm, (8,), np.float32)
    out = scache.run("q:v1", qm, rs.randn(5, 8).astype(np.float32))

    print(json.dumps({
        "compiles": cache.stats["compiles"],
        "disk_hits": cache.stats["disk_hits"],
        "stores": cache.stats["stores"],
        "fp_f32": model_fingerprint(net),
        "fp_quant": model_fingerprint(qm),
        "calibration_crc": stats.crc32(),
        "checksum": float(np.asarray(out, np.float64).sum()),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
