"""Data pipeline tests (reference: datavec-api transform tests,
RecordReaderDataSetIterator tests, normalizer tests)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.data import (
    ArrayDataSetIterator, CollectionRecordReader, CSVRecordReader,
    CSVSequenceRecordReader, DataSet, ImagePreProcessingScaler,
    ImageRecordReader, IrisDataSetIterator, ListDataSetIterator,
    NormalizerMinMaxScaler, NormalizerStandardize,
    RecordReaderDataSetIterator, Schema, SequenceRecordReaderDataSetIterator,
    SyntheticMnist, TransformProcess)


CSV_TEXT = """a,b,label
1.0,2.0,0
3.0,4.0,1
5.0,6.0,2
7.0,8.0,0
"""


def test_csv_record_reader():
    rr = CSVRecordReader(text=CSV_TEXT, skip_lines=1)
    recs = list(rr)
    assert len(recs) == 4
    assert recs[0] == ["1.0", "2.0", "0"]
    # restartable
    assert list(rr) == recs


def test_record_reader_dataset_iterator_classification():
    rr = CSVRecordReader(text=CSV_TEXT, skip_lines=1)
    it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=2,
                                     num_classes=3)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].features.shape == (2, 2)
    assert batches[0].labels.shape == (2, 3)
    np.testing.assert_array_equal(batches[0].labels[1], [0, 1, 0])
    # iterator is reusable after reset
    it.reset()
    assert len(list(it)) == 2


def test_record_reader_dataset_iterator_regression():
    rr = CSVRecordReader(text=CSV_TEXT, skip_lines=1)
    it = RecordReaderDataSetIterator(rr, batch_size=4, label_index=2,
                                     regression=True)
    (ds,) = list(it)
    assert ds.labels.shape == (4, 1)
    np.testing.assert_allclose(ds.labels.ravel(), [0, 1, 2, 0])


def test_transform_process():
    schema = (Schema.builder()
              .add_column_string("name")
              .add_column_categorical("color", ["red", "green", "blue"])
              .add_column_double("x", "y")
              .build())
    tp = (TransformProcess.builder(schema)
          .remove_columns("name")
          .categorical_to_integer("color")
          .math_op_double("x", "Multiply", 2.0)
          .filter_by_condition(lambda s, r: r[s.index_of("y")] > 0)
          .build())
    records = [["a", "red", 1.0, 5.0],
               ["b", "blue", 2.0, -1.0],
               ["c", "green", 3.0, 2.0]]
    out = tp.execute(records)
    assert out == [[0, 2.0, 5.0], [1, 6.0, 2.0]]
    assert tp.final_schema().names() == ["color", "x", "y"]


def test_transform_one_hot():
    schema = (Schema.builder()
              .add_column_categorical("c", ["p", "q"])
              .add_column_double("v").build())
    tp = (TransformProcess.builder(schema)
          .categorical_to_one_hot("c").build())
    out = tp.execute([["q", 3.0]])
    assert out == [[0.0, 1.0, 3.0]]
    assert tp.final_schema().names() == ["c[p]", "c[q]", "v"]


def test_normalizer_standardize_roundtrip():
    rng = np.random.RandomState(0)
    x = rng.randn(100, 5).astype(np.float32) * 3 + 7
    it = ListDataSetIterator([DataSet(x[i:i + 20], np.zeros((20, 1)))
                              for i in range(0, 100, 20)])
    nz = NormalizerStandardize().fit(it)
    ds = DataSet(x.copy(), np.zeros((100, 1)))
    nz.transform(ds)
    assert abs(ds.features.mean()) < 1e-4
    assert abs(ds.features.std() - 1.0) < 1e-2
    back = nz.revert_features(ds.features)
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)
    # serde round-trip
    nz2 = NormalizerStandardize.from_bytes(nz.to_bytes())
    np.testing.assert_allclose(nz2.mean, nz.mean)


def test_normalizer_minmax():
    x = np.array([[0., 10.], [5., 20.], [10., 30.]], np.float32)
    it = ListDataSetIterator([DataSet(x, np.zeros((3, 1)))])
    nz = NormalizerMinMaxScaler().fit(it)
    ds = DataSet(x.copy(), np.zeros((3, 1)))
    nz.transform(ds)
    np.testing.assert_allclose(ds.features.min(0), [0, 0])
    np.testing.assert_allclose(ds.features.max(0), [1, 1])


def test_image_scaler():
    ds = DataSet(np.full((2, 4, 4, 3), 255.0, np.float32),
                 np.zeros((2, 1)))
    ImagePreProcessingScaler().transform(ds)
    np.testing.assert_allclose(ds.features, 1.0)


def test_sequence_iterator_padding(tmp_path):
    # two csv sequence files with different lengths
    p1 = tmp_path / "s1.csv"
    p1.write_text("1.0,2.0,0\n3.0,4.0,1\n5.0,6.0,0\n")
    p2 = tmp_path / "s2.csv"
    p2.write_text("7.0,8.0,1\n")
    rr = CSVSequenceRecordReader([str(p1), str(p2)])
    it = SequenceRecordReaderDataSetIterator(rr, batch_size=2,
                                             label_index=2, num_classes=2)
    (ds,) = list(it)
    assert ds.features.shape == (2, 3, 2)
    np.testing.assert_array_equal(ds.features_mask,
                                  [[1, 1, 1], [1, 0, 0]])
    assert ds.labels.shape == (2, 3, 2)


def test_image_record_reader(tmp_path):
    for label in ["cat", "dog"]:
        d = tmp_path / label
        d.mkdir()
        np.save(d / "img0.npy",
                np.random.RandomState(0).rand(8, 8, 3).astype(np.float32))
    paths = sorted(str(p) for p in tmp_path.rglob("*.npy"))
    rr = ImageRecordReader(paths, 8, 8, 3)
    recs = list(rr)
    assert len(recs) == 2
    assert recs[0][0].shape == (8, 8, 3)
    assert rr.labels == ["cat", "dog"]
    assert [r[1] for r in recs] == [0, 1]


def test_image_record_reader_decodes_real_images(tmp_path):
    """Directory-of-PNG/JPEGs -> training batches end-to-end (VERDICT r2
    missing #2: real image decode via PIL, NativeImageLoader semantics)."""
    from PIL import Image
    rng = np.random.RandomState(0)
    for label in ["cat", "dog"]:
        d = tmp_path / label
        d.mkdir()
        # 16x12 so the 8x8 target exercises the resize path; one PNG and
        # one JPEG per class
        Image.fromarray(rng.randint(0, 255, (12, 16, 3), np.uint8)).save(
            d / "a.png")
        Image.fromarray(rng.randint(0, 255, (8, 8, 3), np.uint8)).save(
            d / "b.jpg", quality=95)
    paths = sorted(str(p) for p in tmp_path.rglob("*.*"))
    rr = ImageRecordReader(paths, 8, 8, 3)
    recs = list(rr)
    assert len(recs) == 4
    for arr, _ in recs:
        assert arr.shape == (8, 8, 3) and arr.dtype == np.float32
        assert 0.0 <= arr.min() and arr.max() <= 255.0
    # exact-decode check (no resize): the JPEG-95 roundtrip stays close
    b_cat = [a for a, lab in recs if lab == 0][1]
    with Image.open(tmp_path / "cat" / "b.jpg") as im:
        want = np.asarray(im.convert("RGB"), np.float32)
    np.testing.assert_allclose(b_cat, want, atol=0)
    # grayscale decode
    rr1 = ImageRecordReader(paths, 8, 8, 1)
    assert next(iter(rr1))[0].shape == (8, 8, 1)
    # feeds the standard iterator -> batches
    from deeplearning4j_tpu.data import RecordReaderDataSetIterator
    it = RecordReaderDataSetIterator(rr, batch_size=4, label_index=1,
                                     num_classes=2)
    ds = next(iter(it))
    assert np.asarray(ds.features).shape == (4, 8, 8, 3)
    assert np.asarray(ds.labels).shape == (4, 2)


def test_video_record_reader_frame_dirs_and_gif(tmp_path):
    from PIL import Image
    from deeplearning4j_tpu.data import VideoRecordReader
    rng = np.random.RandomState(1)
    vid = tmp_path / "clip0"
    vid.mkdir()
    for t in range(5):
        Image.fromarray(rng.randint(0, 255, (8, 8, 3), np.uint8)).save(
            vid / f"frame_{t:03d}.png")
    frames = [Image.fromarray(rng.randint(0, 255, (8, 8, 3), np.uint8))
              for _ in range(4)]
    gif = tmp_path / "clip1.gif"
    frames[0].save(gif, save_all=True, append_images=frames[1:])
    rr = VideoRecordReader([str(vid), str(gif)], 8, 8, 3, max_frames=4)
    seqs = list(rr)
    assert len(seqs) == 2
    assert len(seqs[0]) == 4 and len(seqs[1]) == 4    # max_frames cap
    assert seqs[0][0][0].shape == (8, 8, 3)
    assert seqs[1][0][0].shape == (8, 8, 3)


def test_synthetic_mnist_trains_lenet():
    from deeplearning4j_tpu.zoo import LeNet
    net = LeNet().init_model()
    it = SyntheticMnist(batch_size=32, n_batches=5)
    net.fit(it, epochs=3)
    ev = net.evaluate(SyntheticMnist(batch_size=32, n_batches=3, seed=0))
    assert ev.accuracy() > 0.5


def test_iris_trains():
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train.updaters import Adam
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    it = IrisDataSetIterator(batch_size=50)
    nz = NormalizerStandardize().fit(it)
    datasets = [nz.transform(ds) for ds in it]
    net.fit(ListDataSetIterator(datasets), epochs=30)
    ev = net.evaluate(ListDataSetIterator(datasets))
    assert ev.accuracy() > 0.9


def test_idx_roundtrip(tmp_path):
    from deeplearning4j_tpu.data import read_idx
    import struct
    arr = np.arange(24, dtype=np.uint8).reshape(2, 3, 4)
    p = tmp_path / "test-idx3-ubyte"
    with open(p, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 3))
        f.write(struct.pack(">III", 2, 3, 4))
        f.write(arr.tobytes())
    np.testing.assert_array_equal(read_idx(str(p)), arr)


# ---------------------------------------------------------------------------
# CIFAR-10 / EMNIST iterators (VERDICT #8): tests author files in the REAL
# formats (CIFAR binary records, IDX) and read them back.
# ---------------------------------------------------------------------------

def _write_cifar_bin(path, n, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    imgs = rng.randint(0, 256, (n, 3, 32, 32)).astype(np.uint8)  # CHW
    rec = np.concatenate([labels[:, None],
                          imgs.reshape(n, -1)], axis=1)
    rec.astype(np.uint8).tofile(path)
    return labels, imgs


def _write_idx(path, arr):
    import struct
    arr = np.asarray(arr)
    code = {np.uint8: 0x08}[arr.dtype.type]
    with open(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, code, arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.tobytes())


def test_cifar10_iterator_reads_binary_format(tmp_path):
    from deeplearning4j_tpu.data import Cifar10DataSetIterator
    labs = []
    for i in range(1, 6):
        l, _ = _write_cifar_bin(tmp_path / f"data_batch_{i}.bin", 20, i)
        labs.append(l)
    it = Cifar10DataSetIterator(10, train=True, data_dir=str(tmp_path),
                                shuffle=False)
    batches = list(it)
    assert len(batches) == 10
    assert batches[0].features.shape == (10, 32, 32, 3)
    assert batches[0].features.max() <= 1.0
    np.testing.assert_array_equal(np.argmax(batches[0].labels, 1),
                                  labs[0][:10])
    # HWC layout: channel planes were stored CHW — check one pixel
    raw = np.fromfile(tmp_path / "data_batch_1.bin", np.uint8)
    rec0 = raw[:3073]
    np.testing.assert_allclose(
        batches[0].features[0, 0, 0],
        rec0[1:][[0, 1024, 2048]].astype(np.float32) / 255.0)


def test_cifar10_missing_file_error(tmp_path):
    from deeplearning4j_tpu.data import Cifar10DataSetIterator
    with pytest.raises(FileNotFoundError, match="zero egress"):
        Cifar10DataSetIterator(8, data_dir=str(tmp_path))


def test_emnist_iterator_splits_and_letters_offset(tmp_path):
    from deeplearning4j_tpu.data import EmnistDataSetIterator
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (30, 28, 28)).astype(np.uint8)
    labels = (rng.randint(0, 26) + 1) * np.ones(30, np.uint8)  # 1-indexed
    _write_idx(tmp_path / "emnist-letters-train-images-idx3-ubyte", imgs)
    _write_idx(tmp_path / "emnist-letters-train-labels-idx1-ubyte", labels)
    it = EmnistDataSetIterator("letters", 10, train=True,
                               data_dir=str(tmp_path), shuffle=False)
    assert it.n_classes == 26
    ds = next(iter(it))
    assert ds.features.shape == (10, 28, 28, 1)
    assert ds.labels.shape == (10, 26)
    # loader must undo the EMNIST on-disk transpose
    np.testing.assert_allclose(
        ds.features[0, :, :, 0], imgs[0].T.astype(np.float32) / 255.0)
    np.testing.assert_array_equal(np.argmax(ds.labels, 1),
                                  labels[:10] - 1)
    with pytest.raises(ValueError, match="Unknown EMNIST split"):
        EmnistDataSetIterator("nope", 10, data_dir=str(tmp_path))


def test_synthetic_cifar_trains():
    from deeplearning4j_tpu.data import SyntheticCifar10
    from deeplearning4j_tpu.zoo import SimpleCNN
    net = SimpleCNN(n_classes=10, input_shape=(32, 32, 3)).init_model()
    it = SyntheticCifar10(16, n_batches=4)
    net.fit(it, epochs=2)
    assert np.isfinite(net.score())


# ---------------------------------------------------------------------------
# DataVec joins + analysis (SURVEY §2 L5 gap rows)
# ---------------------------------------------------------------------------

def _join_fixtures():
    from deeplearning4j_tpu.data.transform import Schema
    left = (Schema.builder().add_column_integer("id")
            .add_column_string("name").build())
    right = (Schema.builder().add_column_integer("id")
             .add_column_double("score").build())
    lrec = [[0, "zero"], [1, "one"], [2, "two"]]
    rrec = [[1, 0.5], [2, 0.7], [2, 0.9], [3, 0.1]]
    return left, right, lrec, rrec


def test_join_inner_and_outer_variants():
    from deeplearning4j_tpu.data import Join
    left, right, lrec, rrec = _join_fixtures()

    def run(jt):
        j = (Join.builder(jt).set_left_schema(left)
             .set_right_schema(right).set_join_columns("id").build())
        return j, j.execute(lrec, rrec)

    j, inner = run(Join.INNER)
    assert j.output_schema().names() == ["id", "name", "score"]
    assert sorted(inner) == [[1, "one", 0.5], [2, "two", 0.7],
                             [2, "two", 0.9]]
    _, louter = run(Join.LEFT_OUTER)
    assert [0, "zero", None] in louter and len(louter) == 4
    _, router = run(Join.RIGHT_OUTER)
    assert [3, None, 0.1] in router and len(router) == 4
    _, full = run(Join.FULL_OUTER)
    assert len(full) == 5
    assert [0, "zero", None] in full and [3, None, 0.1] in full


def test_analyze_local_column_stats():
    from deeplearning4j_tpu.data import AnalyzeLocal
    from deeplearning4j_tpu.data.transform import Schema
    schema = (Schema.builder().add_column_double("x")
              .add_column_categorical("cat", ["a", "b"])
              .add_column_string("s").build())
    records = [[1.0, "a", "hello"], [3.0, "b", "hi"],
               [None, "a", "hello"], [5.0, "a", None]]
    an = AnalyzeLocal.analyze(schema, records)
    xa = an.get_column_analysis("x")
    assert xa.count == 3 and xa.count_missing == 1
    assert xa.min == 1.0 and xa.max == 5.0 and abs(xa.mean - 3.0) < 1e-9
    ca = an.get_column_analysis("cat")
    assert ca.counts == {"a": 3, "b": 1}
    sa = an.get_column_analysis("s")
    assert sa.unique == 2 and sa.min_length == 2 and sa.max_length == 5
    assert "x (double)" in str(an)


def test_histogram_percentile_matches_numpy():
    from deeplearning4j_tpu.data import Histogram
    rs = np.random.RandomState(0)
    data = rs.randn(50_000)
    h = Histogram(data.min(), data.max(), bins=2048)
    h.add(data[:20_000])
    h.add(data[20_000:])                       # streaming accumulation
    assert h.total == 50_000
    for p in (1.0, 25.0, 50.0, 99.0, 99.9):
        want = np.percentile(data, p)
        # binned estimate: within one bucket width of the exact value
        assert abs(h.percentile(p) - want) < 2 * h.bin_width, p
    # edges clip, never drop
    h.add(np.array([data.min() - 100.0, data.max() + 100.0]))
    assert h.total == 50_002


def test_histogram_degenerate_range():
    from deeplearning4j_tpu.data import Histogram
    h = Histogram(2.0, 2.0, bins=16)           # constant column
    h.add(np.full(10, 2.0))
    assert abs(h.percentile(50.0) - 2.0) < 1e-6


def test_analyze_local_histogram_bins():
    from deeplearning4j_tpu.data import AnalyzeLocal
    from deeplearning4j_tpu.data.transform import Schema
    schema = Schema.builder().add_column_double("x").build()
    rs = np.random.RandomState(1)
    vals = rs.uniform(-10.0, 10.0, 2000)
    records = [[float(v)] for v in vals]
    an = AnalyzeLocal.analyze(schema, records, histogram_bins=256)
    xa = an.get_column_analysis("x")
    assert xa.histogram is not None and xa.histogram.total == 2000
    assert abs(xa.percentile(50.0) - np.percentile(vals, 50.0)) < 0.5
    assert abs(xa.percentile(99.0) - np.percentile(vals, 99.0)) < 0.5
    # without histogram_bins, percentile() is an explicit error
    plain = AnalyzeLocal.analyze(schema, records)
    with pytest.raises(ValueError, match="histogram"):
        plain.get_column_analysis("x").percentile(50.0)


# ---------------------------------------------------------------------------
# Audio readers (reference datavec-data-audio): tests author real PCM WAV
# files with the stdlib wave module and read them back.
# ---------------------------------------------------------------------------

def _write_wav(path, freq=440.0, rate=8000, seconds=0.25, width=2,
               channels=1):
    import wave as wave_mod
    t = np.arange(int(rate * seconds)) / rate
    x = 0.5 * np.sin(2 * np.pi * freq * t)
    with wave_mod.open(str(path), "wb") as w:
        w.setnchannels(channels)
        w.setsampwidth(width)
        w.setframerate(rate)
        if width == 2:
            data = (x * 32767).astype("<i2")
        else:
            data = ((x * 127) + 128).astype(np.uint8)
        if channels == 2:
            data = np.repeat(data[:, None], 2, 1).reshape(-1)
        w.writeframes(data.tobytes())
    return x


def test_wav_reader_roundtrip(tmp_path):
    from deeplearning4j_tpu.data import WavFileRecordReader, read_wav
    x = _write_wav(tmp_path / "a.wav")
    wav, rate = read_wav(str(tmp_path / "a.wav"))
    assert rate == 8000 and wav.shape == (2000, 1)
    np.testing.assert_allclose(wav[:, 0], x, atol=1e-3)
    _write_wav(tmp_path / "b.wav", freq=880.0, width=1, channels=2)
    rr = WavFileRecordReader(directory=str(tmp_path))
    recs = list(rr)
    assert len(recs) == 2 and len(recs[0]) == 2000


def test_spectrogram_peaks_at_tone_frequency(tmp_path):
    from deeplearning4j_tpu.data import (SpectrogramRecordReader,
                                         read_wav, spectrogram)
    rate, freq = 8000, 1000.0
    _write_wav(tmp_path / "tone.wav", freq=freq, rate=rate, seconds=0.5)
    wav, _ = read_wav(str(tmp_path / "tone.wav"))
    spec = spectrogram(wav, frame_length=256, hop=128, log=False)
    # energy concentrates at bin freq/rate*frame_length = 32
    assert abs(int(np.argmax(spec.mean(0))) - 32) <= 1
    rr = SpectrogramRecordReader(directory=str(tmp_path), n_frames=16)
    rec = next(iter(rr))
    assert len(rec) == 16 * 129


# ---------------------------------------------------------------------------
# Arrow/Parquet record IO (reference datavec-arrow)
# ---------------------------------------------------------------------------

def test_arrow_roundtrip_feather_and_parquet(tmp_path):
    pytest.importorskip("pyarrow")
    from deeplearning4j_tpu.data import (ArrowRecordReader,
                                         write_records_to_file)
    from deeplearning4j_tpu.data.transform import Schema
    schema = (Schema.builder().add_column_integer("id")
              .add_column_double("x").add_column_string("name")
              .add_column_categorical("cat", ["u", "v"]).build())
    records = [[1, 0.5, "a", "u"], [2, 1.5, "b", "v"],
               [3, None, None, "u"]]
    for ext in ("feather", "parquet"):
        p = str(tmp_path / f"t.{ext}")
        write_records_to_file(schema, records, p)
        rr = ArrowRecordReader(p)
        assert rr.schema.names() == ["id", "x", "name", "cat"]
        assert [c.kind for c in rr.schema.columns] == [
            "integer", "double", "string", "categorical"]
        back = list(rr)
        assert back[0] == [1, 0.5, "a", "u"]
        assert back[2][1] is None and back[2][2] is None


def test_transform_process_json_roundtrip():
    """Reference TransformProcess.toJson/fromJson contract."""
    from deeplearning4j_tpu.data.transform import Schema, TransformProcess
    schema = (Schema.builder().add_column_string("height")
              .add_column_categorical("color", ["red", "blue"])
              .add_column_double("score").add_column_string("junk").build())
    tp = (TransformProcess.builder(schema)
          .remove_columns("junk")
          .string_to_double("height")
          .math_op_double("score", "Multiply", 2.0)
          .categorical_to_one_hot("color")
          .build())
    js = tp.to_json()
    tp2 = TransformProcess.from_json(js)
    records = [["1.8", "red", 3.0, "x"], ["1.6", "blue", 1.0, "y"]]
    out1 = tp.execute(records)
    out2 = tp2.execute(records)
    assert out1 == out2
    assert out1[0] == [1.8, 1.0, 0.0, 6.0]
    assert tp2.final_schema().names() == tp.final_schema().names()


def test_transform_process_custom_step_refuses_serialization():
    from deeplearning4j_tpu.data.transform import Schema, TransformProcess
    import pytest as _pytest
    schema = Schema.builder().add_column_double("x").build()
    tp = (TransformProcess.builder(schema)
          .filter_by_condition(lambda s, r: r[0] > 0).build())
    with _pytest.raises(ValueError, match="cannot be serialized"):
        tp.to_json()


# ---------------------------------------------------------------------------
# IMDB sentiment iterators (reference CnnSentenceDataSetIterator over the
# aclImdb corpus)
# ---------------------------------------------------------------------------

def test_imdb_iterator_reads_acl_imdb_tree(tmp_path):
    from deeplearning4j_tpu.data import ImdbReviewIterator
    for sub, texts in (("pos", ["a great movie", "loved it, great fun"]),
                       ("neg", ["terrible film", "a boring terrible mess"])):
        d = tmp_path / "train" / sub
        d.mkdir(parents=True)
        for i, t in enumerate(texts):
            (d / f"{i}_7.txt").write_text(t)
    it = ImdbReviewIterator(2, train=True, data_dir=str(tmp_path),
                            max_len=8, shuffle=False)
    assert "great" in it.vocab and "terrible" in it.vocab
    ds = next(iter(it))
    assert ds.features.shape == (2, 8) and ds.features.dtype == np.int32
    assert ds.features_mask.shape == (2, 8)
    # first review "a great movie" -> 3 tokens masked in
    assert ds.features_mask[0].sum() == 3
    np.testing.assert_array_equal(np.argmax(ds.labels, 1), [1, 1])
    # unknown words map to the unk id under a tiny foreign vocab
    it2 = ImdbReviewIterator(2, train=True, data_dir=str(tmp_path),
                             max_len=8, vocab={"great": 2}, shuffle=False)
    ds2 = next(iter(it2))
    row = ds2.features[0][ds2.features_mask[0] > 0]
    assert set(row.tolist()) == {1, 2}      # unk, unk->'a','movie'; 'great'=2

def test_synthetic_imdb_trains_classifier():
    from deeplearning4j_tpu.data import SyntheticImdb
    from deeplearning4j_tpu.nn import (EmbeddingSequenceLayer,
                                       GlobalPoolingLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train.updaters import Adam
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(5e-2))
            .list([EmbeddingSequenceLayer(n_in=500, n_out=16,
                                          weight_init="NORMAL"),
                   GlobalPoolingLayer(pooling_type="AVG"),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.recurrent(1, 64)).build())
    net = MultiLayerNetwork(conf).init()
    it = SyntheticImdb(16, n_batches=6, max_len=64, vocab_size=500)
    net.fit(it, epochs=6)
    from deeplearning4j_tpu.train.evaluation import Evaluation
    ev = net.evaluate(SyntheticImdb(16, n_batches=4, max_len=64,
                                    vocab_size=500, seed=9), Evaluation())
    assert ev.accuracy() > 0.8, ev.accuracy()


def test_set_pre_processor_applies_per_batch():
    """Reference DataSetIterator.setPreProcessor: attached normalizer runs
    on every yielded batch."""
    from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
    rng = np.random.RandomState(0)
    x = (rng.randn(64, 5) * 4 + 10).astype(np.float32)
    y = np.zeros((64, 1), np.float32)
    it = ArrayDataSetIterator(x, y, batch_size=16)
    nz = NormalizerStandardize().fit(it)
    it.set_pre_processor(nz)
    assert it.pre_processor() is nz
    batches = list(it)
    allx = np.concatenate([b.features for b in batches])
    assert abs(allx.mean()) < 1e-4 and abs(allx.std() - 1.0) < 1e-2
    # second epoch re-reads fresh slices -> stays normalized, not doubled
    allx2 = np.concatenate([b.features for b in it])
    np.testing.assert_allclose(allx2, allx, atol=1e-6)


def test_pre_processor_does_not_double_apply_on_cached_datasets():
    """ListDataSetIterator yields the SAME DataSet objects each epoch; the
    pre-processor wrapper must not re-normalize them (code-review r2)."""
    rng = np.random.RandomState(1)
    x = (rng.randn(40, 3) * 5 + 20).astype(np.float32)
    cached = [DataSet(x[i:i + 10], np.zeros((10, 1))) for i in range(0, 40, 10)]
    it = ListDataSetIterator(cached)
    nz = NormalizerStandardize().fit(it)
    it.set_pre_processor(nz)
    e1 = np.concatenate([b.features for b in it])
    e2 = np.concatenate([b.features for b in it])       # second epoch
    np.testing.assert_allclose(e2, e1, atol=1e-6)
    # cached originals untouched (rebind-on-copy semantics)
    np.testing.assert_allclose(cached[0].features, x[:10])


def test_imdb_test_split_vocab_comes_from_train(tmp_path):
    from deeplearning4j_tpu.data import ImdbReviewIterator
    for part, sub, texts in (("train", "pos", ["great great movie"]),
                             ("train", "neg", ["terrible film"]),
                             ("test", "pos", ["brandnewword great"]),
                             ("test", "neg", ["terrible brandnewword"])):
        d = tmp_path / part / sub
        d.mkdir(parents=True, exist_ok=True)
        (d / "0_1.txt").write_text(texts[0])
    tr = ImdbReviewIterator(1, train=True, data_dir=str(tmp_path), max_len=4,
                            shuffle=False)
    te = ImdbReviewIterator(1, train=False, data_dir=str(tmp_path), max_len=4,
                            shuffle=False)
    assert te.vocab == tr.vocab                   # ids agree across splits
    assert "brandnewword" not in te.vocab         # test-only word -> unk


# ---------------------------------------------------------------------------
# Round-4 reader tail (reference datavec-api jackson/svmlight/regex readers
# + TransformProcessRecordReader) and multi-process ETL
# ---------------------------------------------------------------------------

def test_jackson_line_record_reader():
    from deeplearning4j_tpu.data import JacksonLineRecordReader
    text = ('{"a": 1, "b": {"c": "x"}}\n'
            '{"a": 2}\n'
            '{"a": 3, "b": {"c": "z"}}\n')
    rr = JacksonLineRecordReader(["a", "b/c"], text=text,
                                 defaults=[0, "MISSING"])
    recs = list(rr)
    assert recs == [[1, "x"], [2, "MISSING"], [3, "z"]]


def test_svmlight_record_reader(tmp_path):
    from deeplearning4j_tpu.data import (LibSvmRecordReader,
                                         SVMLightRecordReader)
    p = tmp_path / "data.svm"
    p.write_text("1 1:0.5 3:2.0 # comment\n"
                 "-1 qid:7 2:1.5\n"
                 "\n"
                 "2,3 1:1.0\n")
    # multilabel rows require opting in — the label column stays one type
    with pytest.raises(ValueError, match="multilabel"):
        list(SVMLightRecordReader(3, path=str(p)))
    recs = list(SVMLightRecordReader(3, path=str(p), multilabel=True))
    assert recs[0] == [0.5, 0.0, 2.0, [1.0]]
    assert recs[1] == [0.0, 1.5, 0.0, [-1.0]]
    assert recs[2] == [1.0, 0.0, 0.0, [2.0, 3.0]]
    # without multilabel rows the default parses plain float labels
    recs1 = list(SVMLightRecordReader(2, text="1 1:0.5\n"))
    assert recs1 == [[0.5, 0.0, 1.0]]
    assert LibSvmRecordReader is SVMLightRecordReader
    # zero-based + no label
    recs0 = list(SVMLightRecordReader(2, text="1 0:9.0\n", zero_based=True,
                                      append_label=False))
    assert recs0 == [[9.0, 0.0]]
    with pytest.raises(ValueError):
        list(SVMLightRecordReader(2, text="1 5:1.0\n"))


def test_regex_record_readers(tmp_path):
    from deeplearning4j_tpu.data import (RegexLineRecordReader,
                                         RegexSequenceRecordReader)
    rr = RegexLineRecordReader(
        r"(\d+-\d+-\d+) (\w+) (.*)",
        text="2049-01-01 INFO all good\n2049-01-02 WARN hmm\n")
    assert list(rr) == [["2049-01-01", "INFO", "all good"],
                       ["2049-01-02", "WARN", "hmm"]]
    with pytest.raises(ValueError):
        list(RegexLineRecordReader(r"(\d+)", text="nope\n"))
    p1 = tmp_path / "a.log"
    p1.write_text("1 x\n2 y\n")
    p2 = tmp_path / "b.log"
    p2.write_text("3 z\n")
    seqs = list(RegexSequenceRecordReader(r"(\d+) (\w+)",
                                          [str(p1), str(p2)]))
    assert seqs == [[["1", "x"], ["2", "y"]], [["3", "z"]]]


def test_transform_process_record_reader():
    from deeplearning4j_tpu.data import (CollectionRecordReader, Schema,
                                         TransformProcess,
                                         TransformProcessRecordReader)
    schema = (Schema.Builder().add_column_string("s")
              .add_column_double("v").build())
    tp = (TransformProcess.Builder(schema)
          .string_to_double("s")
          .math_op_double("v", "Multiply", 10.0)
          .build())
    rr = TransformProcessRecordReader(
        CollectionRecordReader([["1.5", 2.0], ["2.5", 3.0]]), tp)
    assert list(rr) == [[1.5, 20.0], [2.5, 30.0]]


def test_local_transform_executor_multiprocess():
    """2 real worker processes produce exactly the inline result, order
    preserved (reference LocalTransformExecutor / SparkTransformExecutor
    role)."""
    from deeplearning4j_tpu.data import Schema, TransformProcess
    from deeplearning4j_tpu.data.local_execution import (
        LocalTransformExecutor)
    schema = (Schema.Builder().add_column_string("s")
              .add_column_double("v").build())
    tp = (TransformProcess.Builder(schema)
          .string_to_double("s")
          .math_op_double("v", "Multiply", 3.0)
          .build())
    records = [[str(i), float(i)] for i in range(11)]
    inline = tp.execute([list(r) for r in records])
    out = LocalTransformExecutor(num_workers=2).execute(records, tp)
    assert out == inline
    assert out[5] == [5.0, 15.0]
    # inline fallback path
    out0 = LocalTransformExecutor(num_workers=0).execute(records, tp)
    assert out0 == inline
