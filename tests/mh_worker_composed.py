"""Composed-parallelism multihost worker (spawned by test_multihost via
LocalLauncher — NOT a pytest file).

2 processes x 4 local CPU devices = one 8-device global mesh.  The same
composed dp x tp x pp transformer step from `parallel/composed.py` runs
twice, with the PROCESS-SPANNING axis chosen differently each time
(make_mesh reshapes devices in dict order, so the FIRST axis crosses the
process boundary):

- pass 1: {"model": 2, ...} — tensor parallelism (ring-attention
  ppermute, all_gather, psum_scatter) rides the gloo inter-process
  transport;
- pass 2: {"pipe": 2, ...} — the GPipe activation ppermute crosses
  processes.

Each pass takes 2 SGD steps and writes its losses; the driver compares
them to the single-device oracle trajectory (grad correctness across the
process boundary, not just forward)."""
import os
import sys

import numpy as np

from deeplearning4j_tpu.parallel import multihost

multihost.initialize()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import multihost_utils  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from deeplearning4j_tpu.parallel.composed import (  # noqa: E402
    composed_train_step, init_stage_params)
from deeplearning4j_tpu.parallel.mesh import make_mesh  # noqa: E402

out_dir = sys.argv[1]
rank = multihost.process_index()

S, D, H, FF, B, T = 2, 8, 2, 16, 8, 8
rng = np.random.RandomState(7)
params0 = init_stage_params(rng, S, D, H, FF)
x_np = rng.randn(B, T, D).astype(np.float32) * 0.5
y_np = rng.randn(B, T, D).astype(np.float32) * 0.5

results = {}
for tag, axes in (("tp_cross", {"model": 2, "data": 2, "pipe": 2}),
                  ("pp_cross", {"pipe": 2, "data": 2, "model": 2})):
    mesh = make_mesh(axes, jax.devices())
    # identical full batch on every process -> replicated global arrays
    x = multihost_utils.host_local_array_to_global_array(
        x_np, mesh, P())
    y = multihost_utils.host_local_array_to_global_array(
        y_np, mesh, P())
    step = composed_train_step(mesh, H, lr=0.2)
    p = jax.tree_util.tree_map(jnp.asarray, params0)
    losses = []
    for _ in range(2):
        p, loss = step(p, x, y)
        # the scalar loss is replicated on every device; read it locally
        losses.append(float(np.asarray(loss.addressable_data(0))))
    results[tag] = losses
    print(f"rank {rank}: {tag} mesh={axes} losses={losses}", flush=True)

np.savez(os.path.join(out_dir, f"composed_{rank}.npz"),
         tp_cross=np.asarray(results["tp_cross"]),
         pp_cross=np.asarray(results["pp_cross"]))
print(f"rank {rank}: composed multihost done", flush=True)
