"""Persistent AOT executable cache + schedule autotuner (ISSUE 6).

Acceptance contract under test:

* a second cache instance over the same directory serves executables from
  disk with ZERO compiles, and the cached executable is bitwise-identical
  in behaviour to a fresh compile;
* every defect (corrupt bytes, torn write, header mismatch) and every
  version/topology change degrades to a recompile — stale executables are
  never served;
* the train-step builders (MLN/CG/SameDiff) route through the cache, so a
  simulated restart pays 0 compiles and reproduces the exact same math;
* the autotuner picks the known-best config on a rigged measure function,
  and schedules round-trip through save/load and apply.

The slow lane (`test_warm_restart_subprocess`) proves the warm start
cross-process: a child process trains against a shared cache directory
twice and the second run must report 0 compiles.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.compile import (DEFAULT_SPACE, AotStepFunction,
                                        PersistentExecutableCache, Schedule,
                                        ScheduleAutotuner, load_schedule,
                                        model_fingerprint, save_schedule,
                                        step_function)
from deeplearning4j_tpu.compile.fingerprint import (
    _reset_environment_fingerprint, environment_fingerprint)
from deeplearning4j_tpu.compile.persistent import ENTRY_SUFFIX
from deeplearning4j_tpu.nn import (ComputationGraph, DenseLayer, GraphBuilder,
                                   InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import BucketedCompileCache
from deeplearning4j_tpu.train.updaters import Adam, Sgd


def _net(seed=0, n_in=8, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=n_out, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _graph(seed=1):
    conf = (GraphBuilder().seed(seed).updater(Sgd(1e-1))
            .add_inputs("in").set_input_types(InputType.feed_forward(6))
            .add_layer("h", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=2, loss="mcxent",
                                          activation="softmax"), "h")
            .set_outputs("out").build())
    return ComputationGraph(conf).init()


def _sd_mlp():
    sd = SameDiff.create()
    x = sd.placeholder("input", shape=(-1, 4))
    y = sd.placeholder("label", shape=(-1, 3))
    w0 = sd.var("w0", "XAVIER", 4, 16)
    b0 = sd.var("b0", np.zeros(16, np.float32))
    w1 = sd.var("w1", "XAVIER", 16, 3)
    b1 = sd.var("b1", np.zeros(3, np.float32))
    h = sd.nn.tanh(sd.nn.linear(x, w0, b0))
    logits = sd.nn.linear(h, w1, b1, name="logits")
    sd.nn.softmax(logits, name="out")
    sd.loss.softmax_cross_entropy(y, logits, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(
        updater=Adam(1e-2), data_set_feature_mapping=["input"],
        data_set_label_mapping=["label"]))
    return sd


def _xy(n=12, n_in=8, n_out=3, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, n_in).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rs.randint(0, n_out, n)]
    return x, y


# ---------------------------------------------------------------------------
# PersistentExecutableCache core
# ---------------------------------------------------------------------------

def test_disk_round_trip_zero_compiles(tmp_path):
    """A second cache instance over the same directory deserializes the
    stored executable — compile_fn must never run — and the result is
    bitwise-identical to the fresh compile's output."""
    def body(a, b):
        return a @ b + 1.0

    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = np.arange(20, dtype=np.float32).reshape(4, 5)
    parts = {"kind": "unit", "name": "mm"}

    c1 = PersistentExecutableCache(str(tmp_path))
    fn1, src1 = c1.get_or_compile(
        parts, lambda: jax.jit(body).lower(a, b).compile())
    assert src1 == "compiled"
    assert c1.stats["compiles"] == 1 and c1.stats["stores"] == 1
    y1 = np.asarray(fn1(a, b))

    c2 = PersistentExecutableCache(str(tmp_path))

    def boom():
        raise AssertionError("warm path must not compile")

    fn2, src2 = c2.get_or_compile(parts, boom)
    assert src2 == "disk"
    assert c2.stats == {"disk_hits": 1, "disk_misses": 0, "compiles": 0,
                        "stores": 0, "errors": 0,
                        "bytes_read": c2.stats["bytes_read"],
                        "bytes_written": 0}
    assert np.array_equal(np.asarray(fn2(a, b)), y1)


def test_corrupted_entry_recompiles_and_rewrites(tmp_path):
    """Flipping payload bytes after commit → crc mismatch → treated as a
    miss, recompiled, entry rewritten; truncation likewise."""
    def body(a):
        return a * 2.0

    a = np.ones((4,), np.float32)
    parts = {"kind": "unit", "name": "corrupt"}
    c = PersistentExecutableCache(str(tmp_path))
    c.get_or_compile(parts, lambda: jax.jit(body).lower(a).compile())
    (entry,) = [p for p in os.listdir(str(tmp_path))
                if p.endswith(ENTRY_SUFFIX)]
    path = os.path.join(str(tmp_path), entry)

    blob = bytearray(open(path, "rb").read())
    blob[-10] ^= 0xFF                      # corrupt committed payload
    open(path, "wb").write(bytes(blob))

    c2 = PersistentExecutableCache(str(tmp_path))
    fn, src = c2.get_or_compile(parts,
                                lambda: jax.jit(body).lower(a).compile())
    assert src == "compiled"               # defect degraded to recompile
    assert c2.stats["errors"] >= 1
    assert np.array_equal(np.asarray(fn(a)), np.full((4,), 2.0, np.float32))

    # ...and the rewrite healed the entry for the next process
    c3 = PersistentExecutableCache(str(tmp_path))
    _, src3 = c3.get_or_compile(parts, lambda: (_ for _ in ()).throw(
        AssertionError("healed entry must hit")))
    assert src3 == "disk"

    # torn write (truncation) is also a miss, never an exception
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) // 2])
    c4 = PersistentExecutableCache(str(tmp_path))
    assert c4.load(parts) is None


def test_version_mismatch_is_a_fresh_key(tmp_path):
    """The environment fingerprint is hashed into the key, so a different
    jax/XLA version (simulated via env=) can never reach the old entry."""
    def body(a):
        return a + 1.0

    a = np.zeros((3,), np.float32)
    parts = {"kind": "unit", "name": "ver"}
    c1 = PersistentExecutableCache(str(tmp_path))
    c1.get_or_compile(parts, lambda: jax.jit(body).lower(a).compile())

    fake_env = dict(environment_fingerprint(), jax_version="0.0.0-other")
    c2 = PersistentExecutableCache(str(tmp_path), env=fake_env)
    assert c2.key_for(parts) != c1.key_for(parts)
    assert c2.load(parts) is None          # unreachable, not mis-served
    _, src = c2.get_or_compile(parts,
                               lambda: jax.jit(body).lower(a).compile())
    assert src == "compiled"


def test_renamed_entry_never_serves_wrong_program(tmp_path):
    """A cache file renamed to another request's key fails the header
    key/parts check and is treated as a miss."""
    def body(a):
        return a - 5.0

    a = np.zeros((2,), np.float32)
    c = PersistentExecutableCache(str(tmp_path))
    c.get_or_compile({"name": "one"},
                     lambda: jax.jit(body).lower(a).compile())
    (entry,) = [p for p in os.listdir(str(tmp_path))
                if p.endswith(ENTRY_SUFFIX)]
    other_key = c.key_for({"name": "two"})
    os.rename(os.path.join(str(tmp_path), entry),
              os.path.join(str(tmp_path), other_key + ENTRY_SUFFIX))
    assert c.load({"name": "two"}) is None
    assert c.stats["errors"] >= 1


def test_environment_fingerprint_cached_and_resettable():
    e1 = environment_fingerprint()
    assert environment_fingerprint() is e1       # cached
    _reset_environment_fingerprint()
    e2 = environment_fingerprint()
    assert e2 == e1                              # same machine, same content


# ---------------------------------------------------------------------------
# step_function / AotStepFunction
# ---------------------------------------------------------------------------

def test_step_function_plain_jit_when_no_cache():
    def body(a):
        return a * 3.0
    fn = step_function(body)
    assert not isinstance(fn, AotStepFunction)    # plain jax.jit, no wrapper
    assert float(fn(np.float32(2.0))) == 6.0


def test_aot_step_function_counts_only_real_compiles(tmp_path):
    """_cache_size() (monitor's check_compile contract) counts compile
    events, not disk hits — a warm restart must read as 0 recompiles."""
    def body(a, b):
        return a.sum() + b.sum()

    cache = PersistentExecutableCache(str(tmp_path))
    f1 = step_function(body, key_base=lambda: {"k": "s"}, cache=cache,
                       dynamic_argnums=(1,))
    a = np.ones((4,), np.float32)
    f1(a, a)
    assert f1._cache_size() == 1
    f1(a, a)                                      # in-memory table hit
    assert f1._cache_size() == 1
    f1(a, np.ones((8,), np.float32)[:4] * 2)      # same sig, table hit
    assert f1._cache_size() == 1
    f1(a, np.ones((2,), np.float32))              # new dynamic sig
    assert f1._cache_size() == 2

    f2 = step_function(body, key_base=lambda: {"k": "s"},
                       cache=PersistentExecutableCache(str(tmp_path)),
                       dynamic_argnums=(1,))
    f2(a, a)
    assert f2._cache_size() == 0                  # disk hit, no compile


# ---------------------------------------------------------------------------
# model restart path (the FaultTolerantTrainer warm-resume contract)
# ---------------------------------------------------------------------------

def test_mln_restart_zero_compiles_bitwise(tmp_path):
    x, y = _xy()
    c1 = PersistentExecutableCache(str(tmp_path))
    n1 = _net().set_executable_cache(c1)
    for _ in range(3):
        n1.fit(x, y)
    assert c1.stats["compiles"] == 1

    c2 = PersistentExecutableCache(str(tmp_path))
    n2 = _net().set_executable_cache(c2)
    for _ in range(3):
        n2.fit(x, y)
    assert c2.stats["compiles"] == 0 and c2.stats["disk_hits"] == 1
    assert n2._train_step._cache_size() == 0
    assert float(n1.score()) == float(n2.score())   # bitwise parity
    np.testing.assert_array_equal(
        np.asarray(n1.params_["layer_0"]["W"]),
        np.asarray(n2.params_["layer_0"]["W"]))

    # uncached baseline computes the same numbers
    n3 = _net()
    for _ in range(3):
        n3.fit(x, y)
    assert float(n3.score()) == float(n1.score())


def test_mln_scan_step_through_cache(tmp_path):
    x, y = _xy()
    xs, ys = np.stack([x, x]), np.stack([y, y])
    n1 = _net().set_executable_cache(str(tmp_path))   # directory coercion
    n1.fit_steps(xs, ys)
    assert n1._exec_cache().stats["compiles"] == 1
    n2 = _net().set_executable_cache(str(tmp_path))
    n2.fit_steps(xs, ys)
    assert n2._exec_cache().stats["compiles"] == 0
    assert float(n1.score()) == float(n2.score())


def test_graph_and_samediff_restart_zero_compiles(tmp_path):
    xg, yg = _xy(8, 6, 2, seed=1)
    g1 = _graph().set_executable_cache(PersistentExecutableCache(str(tmp_path)))
    g1.fit(xg, yg)
    g2 = _graph().set_executable_cache(PersistentExecutableCache(str(tmp_path)))
    g2.fit(xg, yg)
    assert g2._exec_cache().stats["compiles"] == 0
    assert float(g1.score()) == float(g2.score())

    xs, ys = _xy(8, 4, 3, seed=2)
    s1 = _sd_mlp().set_executable_cache(
        PersistentExecutableCache(str(tmp_path)))
    s1.fit(xs, ys)
    s2 = _sd_mlp().set_executable_cache(
        PersistentExecutableCache(str(tmp_path)))
    s2.fit(xs, ys)
    assert s2._exec_cache().stats["compiles"] == 0
    assert float(s1.score()) == float(s2.score())


def test_model_fingerprint_ignores_weights_not_architecture():
    n1, n2 = _net(seed=0), _net(seed=7)      # same arch, different weights
    assert model_fingerprint(n1) == model_fingerprint(n2)
    n3 = _net(n_out=4)                       # different architecture
    assert model_fingerprint(n3) != model_fingerprint(n1)


def test_normalizer_stats_change_the_key(tmp_path):
    """DeviceNormalizer stats are baked into the executable as constants,
    so different stats MUST produce different disk keys."""
    from deeplearning4j_tpu.data import DataSet, NormalizerStandardize
    x, y = _xy(32)
    nz1 = NormalizerStandardize().fit([DataSet(x, y)])
    nz2 = NormalizerStandardize().fit([DataSet(x * 3.0 + 1.0, y)])
    n1 = _net().set_normalizer(nz1)
    n2 = _net().set_normalizer(nz2)
    assert model_fingerprint(n1) != model_fingerprint(n2)
    n3 = _net().set_normalizer(nz1)
    assert model_fingerprint(n1) == model_fingerprint(n3)


# ---------------------------------------------------------------------------
# serving cache: persistent tier, pads, set_buckets, parallel warmup
# ---------------------------------------------------------------------------

def test_serving_warm_instance_zero_compiles(tmp_path):
    net = _net()
    x, _ = _xy(5)
    c1 = BucketedCompileCache(max_batch=16, persistent=str(tmp_path))
    y1 = c1.run("m:v1", net, x)
    assert c1.persistent.stats["compiles"] == 1

    c2 = BucketedCompileCache(max_batch=16, persistent=str(tmp_path))
    y2 = c2.run("m:v1", net, x)
    assert c2.persistent.stats["compiles"] == 0
    assert c2.persistent.stats["disk_hits"] == 1
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))

    # a weights-only model roll (same architecture) also comes up warm
    c3 = BucketedCompileCache(max_batch=16, persistent=str(tmp_path))
    y3 = c3.run("m:v2", _net(seed=9), x)
    assert c3.persistent.stats["compiles"] == 0
    assert y3.shape == y1.shape


def test_serving_pad_buffer_reused(tmp_path):
    net = _net()
    cache = BucketedCompileCache(max_batch=16)
    x, _ = _xy(5)
    cache.run("m:v1", net, x)
    cache.run("m:v1", net, x[:3])
    # one pad buffer per (bucket, trailing, dtype), reused across runs
    assert len(cache._pads) == 2
    pads_before = dict(cache._pads)
    cache.run("m:v1", net, x)
    assert cache._pads == pads_before
    for pad in cache._pads.values():
        assert not pad.any()               # still zeros (never written)


def test_set_buckets_and_parallel_warmup():
    net = _net()
    cache = BucketedCompileCache(max_batch=16)
    assert cache.set_buckets(buckets=[3, 12]) == [3, 12]
    assert cache.bucket_for(2) == 3
    assert cache.bucket_for(4) == 12
    with pytest.raises(ValueError):
        cache.bucket_for(13)
    with pytest.raises(ValueError):
        cache.set_buckets(buckets=[4, 4])
    cache.set_buckets(min_bucket=4)
    assert cache.buckets == [4, 8, 16]
    warmed = cache.warmup("m:v1", net, (8,), np.float32, parallel=True)
    assert warmed == [4, 8, 16]
    assert cache.counters.misses.value == 3
    # every warmed bucket is now an in-memory hit
    cache.run("m:v1", net, np.zeros((5, 8), np.float32))
    assert cache.counters.misses.value == 3


# ---------------------------------------------------------------------------
# autotuner + schedule persistence
# ---------------------------------------------------------------------------

def test_autotuner_finds_rigged_optimum():
    """Analytic measure with a known best point: the search must find it
    and memoize (never re-measure a config)."""
    calls = []

    def measure(s):
        calls.append(s.config_key())
        v = 100.0
        v += {1: 0, 2: 10, 4: 25, 8: 20, 16: 5}[s.fused_steps]
        v += {1: 0, 2: 6, 4: 3}[s.prefetch_depth]
        v += 8 if s.zero1 else 0
        v += 4 if s.donation else 0
        return v

    tuner = ScheduleAutotuner(measure, space=DEFAULT_SPACE)
    best = tuner.search()
    assert (best.fused_steps, best.prefetch_depth, best.zero1,
            best.donation) == (4, 2, True, True)
    assert best.steps_per_sec == measure(best)
    assert best.source == "autotuned"
    assert len(calls) - 1 == len(set(calls[:-1]))   # memoized (re-measure
    # above adds the final duplicate)
    assert best.meta["evaluated"] == len(set(calls))
    assert tuner.history[0]["steps_per_sec"] == \
        best.meta["baseline_steps_per_sec"]


def test_schedule_save_load_apply(tmp_path):
    sch = Schedule(fused_steps=8, prefetch_depth=4, zero1=False,
                   donation=False, steps_per_sec=123.4)
    path = save_schedule(sch, str(tmp_path), name="t")
    assert os.path.basename(path) == "schedule-t.json"
    loaded = load_schedule(str(tmp_path), name="t")
    assert loaded.source == "loaded"
    assert loaded.config_key() == sch.config_key()
    assert loaded.steps_per_sec == 123.4
    assert load_schedule(str(tmp_path), name="absent") is None

    # defect → None, never an exception
    with open(path, "w") as f:
        f.write("{not json")
    assert load_schedule(str(tmp_path), name="t") is None

    # model-keyed path: same architecture resolves the same file
    sch2 = Schedule(fused_steps=2)
    save_schedule(sch2, str(tmp_path), model=_net(seed=0))
    got = load_schedule(str(tmp_path), model=_net(seed=5))
    assert got is not None and got.fused_steps == 2


def test_schedule_apply_to_model_and_buckets():
    net = _net()
    sch = Schedule(fused_steps=4, donation=False)
    assert sch.apply(net) is net
    assert net._schedule is sch
    assert net._donate_argnums() == ()       # donation honored
    x, y = _xy()
    net.fit(x, y)                            # no-donation step still trains
    assert np.isfinite(float(net.score()))

    cache = BucketedCompileCache(max_batch=32)
    Schedule(buckets=[8, 32]).apply(cache)
    assert cache.buckets == [8, 32]


def test_wrapper_apply_schedule_toggles_zero1():
    from deeplearning4j_tpu.parallel import ParallelWrapper
    net = _net()
    pw = ParallelWrapper.builder(net).build()
    sch = Schedule(fused_steps=2, zero1=True)
    pw.apply_schedule(sch)
    assert pw._zero1 is True
    assert net._schedule is sch
    x, y = _xy(16)
    pw.fit(x, y)
    assert np.isfinite(float(net.score()))
    pw.apply_schedule(Schedule(zero1=False))
    assert pw._zero1 is False


# ---------------------------------------------------------------------------
# slow lane: true cross-process warm restart
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_warm_restart_subprocess(tmp_path):
    """Two real processes share a cache directory: the second must train
    with 0 compiles and land on the exact same score."""
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "aot_warm_worker.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(here),
               DL4J_TPU_TEST_CACHE=str(tmp_path))

    def run():
        p = subprocess.run([sys.executable, worker], env=env,
                           capture_output=True, text=True, timeout=600)
        assert p.returncode == 0, p.stderr[-2000:]
        return json.loads(p.stdout.strip().splitlines()[-1])

    cold = run()
    warm = run()
    assert cold["compiles"] >= 1 and cold["stores"] >= 1
    assert warm["compiles"] == 0
    assert warm["disk_hits"] >= cold["stores"]
    assert warm["score"] == cold["score"]
