"""Elastic-gang training worker (spawned by test_elastic and
`bench.py --elastic` via ElasticLocalRunner.run_elastic — NOT a pytest
file).

Each process trains the SAME seeded MLN under `ElasticTrainer` with
`HierarchicalGradientSharing(elastic=True)` (heartbeat / deadline / join
knobs resolve from the supervisor's `DL4J_TPU_*` env).  The data stream
is one deterministic GLOBAL batch per step seeded by (epoch, step) only;
each member trains on the strided shard of its LIVE gang rank, so a
reformation re-shards the same stream at the new world size — the
property the bitwise kill-and-resume parity test relies on.

A `PeerKiller` hook (argv-armed) injects the chaos on exactly one rank;
the marker file keeps a relaunched replacement from re-firing.  Only the
coordinator WRITES checkpoints; peers share the directory read-only and
rewind from it on every reformation.

argv: out_dir steps_per_epoch epochs kill_rank kill_step [kill_mode]
  kill_rank -1 disables chaos; kill_mode: kill | hang | partition | slow
"""
import json
import os
import sys

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel.hierarchical import (
    HierarchicalGradientSharing)
from deeplearning4j_tpu.parallel.multihost import ENV_CKPT, ENV_PID
from deeplearning4j_tpu.parallel.transport import (GangEvictedError,
                                                   PeerUnreachableError)
from deeplearning4j_tpu.train.resilience import (CheckpointManager,
                                                 ElasticTrainer)
from deeplearning4j_tpu.train.updaters import Sgd
from deeplearning4j_tpu.utils.chaos import PeerKiller

out_dir = sys.argv[1]
steps_per_epoch = int(sys.argv[2])
epochs = int(sys.argv[3])
kill_rank = int(sys.argv[4])
kill_step = int(sys.argv[5])
kill_mode = sys.argv[6] if len(sys.argv) > 6 else "kill"

rank = int(os.environ.get(ENV_PID, "0"))
policy = os.environ.get("DL4J_TPU_ELASTIC_POLICY", "shrink")
ckpt_dir = os.environ[ENV_CKPT]

N_IN, N_OUT, GLOBAL_BATCH = 16, 3, 12

conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
        .list([DenseLayer(n_out=32, activation="tanh"),
               OutputLayer(n_out=N_OUT, loss="mcxent",
                           activation="softmax")])
        .set_input_type(InputType.feed_forward(N_IN)).build())
net = MultiLayerNetwork(conf).init()
net.set_gradient_sharing(HierarchicalGradientSharing(
    threshold=5e-3, elastic=True))


class GangShardIterator(DataSetIterator):
    """Deterministic global stream, live-rank strided shards (see module
    docstring).  Rank/world are read per batch, NOT captured at
    construction — that is what lets the same iterator keep feeding a
    reformed gang."""

    def __init__(self, model, steps: int):
        self.model = model
        self.steps = int(steps)

    def __iter__(self):
        for i in range(self.steps):
            seed = 1000 + int(self.model.epoch) * self.steps + i
            rng = np.random.RandomState(seed)
            xg = rng.randn(GLOBAL_BATCH, N_IN).astype(np.float32)
            labels = ((xg[:, 0] > 0).astype(int)
                      + (xg[:, 1] > 0).astype(int))
            yg = np.eye(N_OUT, dtype=np.float32)[labels]
            sharing = self.model.gradient_sharing
            r, w = sharing.rank, sharing.world
            yield DataSet(xg[r::w], yg[r::w])

    def __len__(self):
        return self.steps

    def batch_size(self) -> int:
        return GLOBAL_BATCH


# coordinator writes every step; keep_last is high because the parity
# comparator reruns from the exact reform step, which retention must not
# have pruned by the end of the run
manager = CheckpointManager(ckpt_dir, keep_last=200,
                            save_every_steps=1 if rank == 0 else None)
hooks = []
if kill_rank >= 0:
    hooks.append(PeerKiller(kill_rank, kill_step, mode=kill_mode,
                            duration_s=6.0,
                            marker=os.path.join(out_dir, "killed_once")))
trainer = ElasticTrainer(net, manager, policy=policy, rejoin_wait_s=60.0,
                         hooks=hooks, save_initial=(rank == 0))
data = GangShardIterator(net, steps_per_epoch)
try:
    trainer.fit(data, epochs=epochs)
except (GangEvictedError, PeerUnreachableError) as e:
    print(f"rank {rank}: left the gang: {e}", flush=True)
    net.set_gradient_sharing(None)
    sys.exit(7)

stats = net.gradient_sharing.stats()
np.savez(os.path.join(out_dir, f"final_{rank}.npz"),
         params=np.asarray(net.params()),
         iteration=np.int64(net.iteration),
         score=np.float64(net.score()))
with open(os.path.join(out_dir, f"elastic_{rank}.json"), "w") as f:
    json.dump({"stats": stats, "reformations": trainer.reformations}, f)
net.set_gradient_sharing(None)           # close the gang sockets
print(f"rank {rank}: done at iteration {net.iteration} "
      f"(world={stats['world']}, generation={stats['generation']})",
      flush=True)
