"""BERT model tests: masked-LM + classification training, serde, shapes."""
import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.nlp import BertIterator, BertWordPieceTokenizer
from deeplearning4j_tpu.zoo import BertConfig, BertModel
from deeplearning4j_tpu.train.updaters import Adam


VOCAB = (["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
         + [f"w{i}" for i in range(95)])


def _tok():
    return BertWordPieceTokenizer(VOCAB)


def _sentences(n=32, seed=0):
    rng = np.random.RandomState(seed)
    # structured sentences: wK follows wK-1 — learnable co-occurrence
    out = []
    for _ in range(n):
        start = rng.randint(0, 80)
        out.append(" ".join(f"w{start + j}" for j in range(8)))
    return out


def test_bert_mlm_trains():
    model = BertModel(BertConfig.tiny(), seed=0, updater=Adam(1e-3))
    it = BertIterator(_tok(), _sentences(), batch_size=8, max_length=16,
                      task=BertIterator.TASK_UNSUPERVISED, seed=1)
    losses = []
    for _ in range(6):
        if hasattr(it, "reset"):
            it.reset()
        for mds in it:
            losses.append(model.fit_batch(mds))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_bert_classification_trains():
    cfg = BertConfig.tiny(n_classes=2)
    model = BertModel(cfg, seed=0, updater=Adam(1e-3))
    sents = _sentences(32)
    # label = whether sentence contains w10
    labels = [1 if "w10" in s.split() else 0 for s in sents]
    it = BertIterator(_tok(), sents, batch_size=8, max_length=16,
                      task=BertIterator.TASK_SEQ_CLASSIFICATION,
                      labels=labels, n_classes=2)
    first = None
    for _ in range(10):
        for mds in it:
            loss = model.fit_batch(mds)
            if first is None:
                first = loss
    assert loss < first
    ids = np.zeros((2, 16), np.int32)
    mask = np.ones((2, 16), np.float32)
    probs = np.asarray(model.output_cls(ids, mask))
    assert probs.shape == (2, 2)
    np.testing.assert_allclose(probs.sum(1), 1.0, atol=1e-5)


def test_bert_hidden_and_mlm_shapes():
    cfg = BertConfig.tiny()
    model = BertModel(cfg)
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (3, 12))
    mask = np.ones((3, 12), np.float32)
    h = np.asarray(model.output_hidden(ids, mask))
    assert h.shape == (3, 12, cfg.hidden)
    logits = np.asarray(model.output_mlm(ids, mask))
    assert logits.shape == (3, 12, cfg.vocab_size)


def test_bert_bf16_compute():
    cfg = BertConfig.tiny(compute_dtype="bfloat16")
    model = BertModel(cfg, updater=Adam(1e-3))
    it = BertIterator(_tok(), _sentences(16), batch_size=8, max_length=16,
                      seed=2)
    for mds in it:
        loss = model.fit_batch(mds)
    assert np.isfinite(loss)
    # master params stay f32
    assert model.params_["tok_emb"].dtype == jnp.float32


def test_bert_save_load_resume(tmp_path):
    model = BertModel(BertConfig.tiny(), updater=Adam(1e-3))
    it = BertIterator(_tok(), _sentences(16), batch_size=8, max_length=16)
    for mds in it:
        model.fit_batch(mds)
    p = str(tmp_path / "bert.zip")
    model.save(p)
    m2 = BertModel.load(p)
    assert m2.iteration == model.iteration
    ids = np.zeros((1, 8), np.int32)
    mask = np.ones((1, 8), np.float32)
    np.testing.assert_allclose(np.asarray(model.output_hidden(ids, mask)),
                               np.asarray(m2.output_hidden(ids, mask)),
                               rtol=1e-5, atol=1e-6)
    # updater state round-trips: one more identical step matches
    it2 = BertIterator(_tok(), _sentences(16), batch_size=8, max_length=16)
    mds = next(iter(it2))
    l1 = model.fit_batch(mds)
    l2 = m2.fit_batch(mds)
    assert np.isclose(l1, l2, rtol=1e-4)


def test_bert_fit_steps_matches_sequential():
    """fit_steps (k steps fused into one lax.scan dispatch) must match k
    sequential fit_batch calls bit-exactly on the MLM path."""
    from deeplearning4j_tpu.data.dataset import MultiDataSet
    import jax

    rng = np.random.RandomState(0)
    k, b, t, vocab = 4, 8, 16, 100
    ids = rng.randint(0, vocab, (k, b, t)).astype(np.int32)
    mask = np.ones((k, b, t), np.float32)
    lmask = (rng.rand(k, b, t) < 0.15).astype(np.float32)

    a = BertModel(BertConfig.tiny(), seed=0, updater=Adam(1e-3))
    b_ = BertModel(BertConfig.tiny(), seed=0, updater=Adam(1e-3))
    seq_losses = []
    for i in range(k):
        mds = MultiDataSet(features=[ids[i], mask[i]], labels=[ids[i]],
                           labels_masks=[lmask[i]])
        seq_losses.append(float(a.fit_batch(mds)))
    stacked = MultiDataSet(features=[ids, mask], labels=[ids],
                           labels_masks=[lmask])
    losses = b_.fit_steps(stacked)
    np.testing.assert_allclose(np.asarray(losses), seq_losses, rtol=1e-6)
    for la, lb in zip(jax.tree_util.tree_leaves(a.params_),
                      jax.tree_util.tree_leaves(b_.params_)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert a.iteration == b_.iteration == k


def test_bert_fit_iterator_fused_matches_sequential():
    """BertModel.fit(iterator, fused_steps=2) == plain fit(iterator)."""
    import jax

    def run(fused):
        model = BertModel(BertConfig.tiny(), seed=0, updater=Adam(1e-3))
        it = BertIterator(_tok(), _sentences(), batch_size=8, max_length=16,
                          task=BertIterator.TASK_UNSUPERVISED, seed=1)
        model.fit(it, epochs=2, fused_steps=2 if fused else 1)
        return model

    a, b = run(False), run(True)
    assert a.iteration == b.iteration
    for la, lb in zip(jax.tree_util.tree_leaves(a.params_),
                      jax.tree_util.tree_leaves(b.params_)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
