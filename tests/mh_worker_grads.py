"""Compressed-gradient TCP exchange worker (spawned by test_multihost via
LocalLauncher — NOT a pytest file).

Each rank threshold-encodes a deterministic rank-dependent gradient tree,
all-gathers the sparse streams over TcpGradientMesh, decodes every peer's
stream and sums — the Aeron gradient-sharing loop on loopback.  Results are
written per-rank for the driver to verify."""
import os
import sys

import numpy as np

from deeplearning4j_tpu.parallel.compression import (
    CompressedGradientExchange, allreduce_compressed)
from deeplearning4j_tpu.parallel.multihost import ENV_NPROC, ENV_PID
from deeplearning4j_tpu.parallel.transport import TcpGradientMesh

port = int(sys.argv[1])
out_dir = sys.argv[2]
rank = int(os.environ[ENV_PID])
world = int(os.environ[ENV_NPROC])

template = {"w": np.zeros((64, 32), np.float32),
            "b": np.zeros(32, np.float32)}
ex = CompressedGradientExchange(template, threshold=0.05)
rng = np.random.default_rng(100 + rank)
grads = {"w": rng.standard_normal((64, 32)).astype(np.float32) * 0.1,
         "b": rng.standard_normal(32).astype(np.float32) * 0.1}

with TcpGradientMesh(rank, world, port) as mesh:
    total = allreduce_compressed(ex, mesh, grads)

np.savez(os.path.join(out_dir, f"sum_{rank}.npz"),
         **{k: np.asarray(v) for k, v in total.items()})
print(f"rank {rank}/{world}: exchange done", flush=True)
