"""OpValidation specs, part 2: nn activations / conv / pool / rnn /
attention / norm / updater ops.  Conv and recurrent goldens come from
torch (CPU) with explicit layout/gate-order mapping — the same
cross-framework conformance strategy the reference uses against TF goldens
in `TFGraphTestAllSameDiff` (SURVEY.md §4)."""
import numpy as np
import scipy.special as ss

from tests.opval_specs_core import C, F, FP, F01, I32, rs

CASES = []

_x = F(3, 5)

# ---- activations (independent numpy closed forms) ----
_SELU_L = 1.0507009873554805
_SELU_A = 1.6732632423543772


def _sig(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_softmax(x, axis=-1):
    e = np.exp(x - np.max(x, axis=axis, keepdims=True))
    return e / np.sum(e, axis=axis, keepdims=True)


CASES += [
    C("relu", _x, g=lambda a: np.maximum(a, 0), grad=(0,)),
    C("relu6", F(3, 5, lo=-2, hi=8), g=lambda a: np.clip(a, 0, 6)),
    C("relu_derivative", _x, g=lambda a: (a > 0).astype(np.float32)),
    C("leaky_relu", _x, g=lambda a, alpha=0.01:
      np.where(a > 0, a, alpha * a), kw={"alpha": 0.2}, grad=(0,)),
    C("elu", _x, g=lambda a: np.where(a > 0, a, np.expm1(a)), grad=(0,)),
    C("selu", _x, g=lambda a: _SELU_L * np.where(
        a > 0, a, _SELU_A * np.expm1(a)), tol=1e-4, grad=(0,)),
    C("celu", _x, g=lambda a, alpha=1.0:
      np.maximum(a, 0) + np.minimum(0, alpha * np.expm1(a / alpha)),
      kw={"alpha": 0.7}, tol=1e-4),
    C("gelu", _x, g=lambda a: 0.5 * a * (1 + np.tanh(
        np.sqrt(2 / np.pi) * (a + 0.044715 * a ** 3))), tol=2e-3,
      grad=(0,), gtol=2e-2),
    C("gelu_tanh", _x, g=lambda a: 0.5 * a * (1 + np.tanh(
        np.sqrt(2 / np.pi) * (a + 0.044715 * a ** 3))), tol=1e-4),
    C("sigmoid", _x, g=_sig, grad=(0,)),
    C("log_sigmoid", _x, g=lambda a: np.log(_sig(a)), grad=(0,)),
    C("softplus", _x, g=lambda a: np.log1p(np.exp(a)), grad=(0,)),
    C("softsign", _x, g=lambda a: a / (1 + np.abs(a)), grad=(0,)),
    C("swish", _x, g=lambda a: a * _sig(a), grad=(0,)),
    C("mish", _x, g=lambda a: a * np.tanh(np.log1p(np.exp(a))),
      grad=(0,), tol=1e-4),
    C("hard_sigmoid", F(3, 5, lo=-4, hi=4),
      g=lambda a: np.clip(a + 3, 0, 6) / 6, tol=1e-4),
    C("hard_swish", F(3, 5, lo=-4, hi=4),
      g=lambda a: a * np.clip(a + 3, 0, 6) / 6, tol=1e-4),
    C("hard_tanh", F(3, 5, lo=-3, hi=3), g=lambda a: np.clip(a, -1, 1)),
    C("rational_tanh", _x, g=lambda a: 1.7159 * np.tanh(2 * a / 3),
      tol=1e-4),
    C("rectified_tanh", _x, g=lambda a: np.maximum(0, np.tanh(a))),
    C("thresholded_relu", _x, g=lambda a, theta=1.0:
      np.where(a > theta, a, 0.0), kw={"theta": 0.5}),
    C("prelu", _x, np.float32(0.25),
      g=lambda x, al: np.where(x >= 0, x, 0.25 * x)),
    C("glu", F(3, 6), g=lambda a, axis=-1:
      a[..., :3] * _sig(a[..., 3:]), tol=1e-5),
    C("softmax", _x, g=lambda a, axis=-1: _np_softmax(a, axis),
      grad=(0,), tol=1e-4),
    C("log_softmax", _x, g=lambda a, axis=-1:
      np.log(_np_softmax(a, axis)), grad=(0,), tol=1e-4),
]

# ---- norms ----
_ln_x = F(4, 6)
_gain, _bias = FP(6), F(6)
CASES += [
    C("layer_norm", _ln_x, _gain, _bias,
      g=lambda x, g, b, eps=1e-5, axis=-1:
      (x - x.mean(-1, keepdims=True))
      / np.sqrt(x.var(-1, keepdims=True) + eps) * g + b,
      tol=1e-4, grad=(0, 1, 2), gtol=2e-2),
    C("batch_norm", _ln_x, F(6), FP(6, lo=0.5, hi=2.0), FP(6), F(6),
      g=lambda x, m, v, gamma, beta, eps=1e-5:
      (x - m) / np.sqrt(v + eps) * gamma + beta, tol=1e-4),
    C("standardize", _ln_x, g=lambda a, axis=-1, eps=1e-8:
      (a - a.mean(-1, keepdims=True)) / (a.std(-1, keepdims=True) + eps),
      tol=1e-4),
    C("l2_normalize", _ln_x, g=lambda a, axis=-1, eps=0:
      a / np.linalg.norm(a, axis=-1, keepdims=True), tol=1e-4,
      grad=(0,)),
    C("fused_batch_norm", F(2, 3, 3, 4), FP(4), F(4),
      g=lambda x, s, o, eps=1e-3: (
          (x - x.mean((0, 1, 2))) / np.sqrt(x.var((0, 1, 2)) + eps)
          * s + o,
          x.mean((0, 1, 2)),
          x.var((0, 1, 2)) * (18 / 17)), tol=1e-4),
]


# ---- torch golden helpers ----
def _nhwc_conv_golden(x, w, b=None, stride=(1, 1), padding="SAME",
                      dilation=(1, 1)):
    import torch
    import torch.nn.functional as TF
    pad = 1 if padding == "SAME" else 0   # configs below keep this exact
    y = TF.conv2d(torch.from_numpy(x.transpose(0, 3, 1, 2)).double(),
                  torch.from_numpy(w.transpose(3, 2, 0, 1)).double(),
                  None if b is None else torch.from_numpy(b).double(),
                  stride=stride, padding=pad, dilation=dilation)
    return y.numpy().transpose(0, 2, 3, 1)


def _depthwise_golden(x, w, stride=(1, 1), padding="SAME",
                      dilation=(1, 1)):
    import torch
    import torch.nn.functional as TF
    pad = 1 if padding == "SAME" else 0
    c = x.shape[-1]
    y = TF.conv2d(torch.from_numpy(x.transpose(0, 3, 1, 2)).double(),
                  torch.from_numpy(w.transpose(3, 2, 0, 1)).double(),
                  None, stride=stride, padding=pad, dilation=dilation,
                  groups=c)
    return y.numpy().transpose(0, 2, 3, 1)


def _conv1d_golden(x, w, stride=1, padding="SAME", dilation=1):
    import torch
    import torch.nn.functional as TF
    pad = 1 if padding == "SAME" else 0
    y = TF.conv1d(torch.from_numpy(x.transpose(0, 2, 1)).double(),
                  torch.from_numpy(w.transpose(2, 1, 0)).double(),
                  None, stride=stride, padding=pad, dilation=dilation)
    return y.numpy().transpose(0, 2, 1)


def _conv3d_golden(x, w, b=None, stride=(1, 1, 1), padding="SAME",
                   dilation=(1, 1, 1)):
    import torch
    import torch.nn.functional as TF
    pad = 1 if padding == "SAME" else 0
    y = TF.conv3d(torch.from_numpy(x.transpose(0, 4, 1, 2, 3)).double(),
                  torch.from_numpy(w.transpose(4, 3, 0, 1, 2)).double(),
                  None if b is None else torch.from_numpy(b).double(),
                  stride=stride, padding=pad, dilation=dilation)
    return y.numpy().transpose(0, 2, 3, 4, 1)


def _deconv2d_valid_golden(x, w, b=None, stride=(2, 2), padding="VALID"):
    """Independent scatter-accumulate transposed conv, VALID padding."""
    B, H, W, Ci = x.shape
    kh, kw, ci, co = w.shape
    sh, sw = stride
    out = np.zeros((B, (H - 1) * sh + kh, (W - 1) * sw + kw, co))
    for i in range(H):
        for j in range(W):
            patch = np.einsum("bc,hwco->bhwo", x[:, i, j], w)
            out[:, i * sh:i * sh + kh, j * sw:j * sw + kw] += patch
    return out if b is None else out + b


def _deconv3d_valid_golden(x, w, stride=(2, 2, 2), padding="VALID"):
    B, D, H, W, Ci = x.shape
    kd, kh, kw, ci, co = w.shape
    sd, sh, sw = stride
    out = np.zeros((B, (D - 1) * sd + kd, (H - 1) * sh + kh,
                    (W - 1) * sw + kw, co))
    for d in range(D):
        for i in range(H):
            for j in range(W):
                patch = np.einsum("bc,dhwco->bdhwo", x[:, d, i, j], w)
                out[:, d * sd:d * sd + kd, i * sh:i * sh + kh,
                    j * sw:j * sw + kw] += patch
    return out


def _pool2d_golden(mode):
    def g(x, kernel=(2, 2), stride=(2, 2), padding="VALID"):
        import torch
        import torch.nn.functional as TF
        t = torch.from_numpy(x.transpose(0, 3, 1, 2)).double()
        y = (TF.max_pool2d(t, kernel, stride) if mode == "max"
             else TF.avg_pool2d(t, kernel, stride))
        return y.numpy().transpose(0, 2, 3, 1)
    return g


_img = F(2, 6, 6, 3)
_w33 = F(3, 3, 3, 4, lo=-0.5, hi=0.5)
CASES += [
    C("conv2d", _img, _w33, F(4), g=_nhwc_conv_golden, tol=1e-4,
      grad=(0, 1), grad_sample=12, gtol=2e-2),
    C("conv2d", _img, _w33, kw={"stride": (2, 2), "padding": "VALID"},
      g=_nhwc_conv_golden, tol=1e-4, tag="valid-s2"),
    C("depthwise_conv2d", _img, F(3, 3, 1, 6, lo=-0.5, hi=0.5),
      g=_depthwise_golden, tol=1e-4),
    C("conv1d", F(2, 8, 3), F(3, 3, 5, lo=-0.5, hi=0.5),
      g=_conv1d_golden, tol=1e-4),
    C("conv3d", F(1, 4, 4, 4, 2), F(3, 3, 3, 2, 3, lo=-0.5, hi=0.5),
      F(3), g=_conv3d_golden, tol=1e-4),
    C("deconv2d", F(2, 3, 3, 2), F(2, 2, 2, 3, lo=-0.5, hi=0.5),
      kw={"stride": (2, 2), "padding": "VALID"},
      g=lambda x, w, b=None, stride=(2, 2), padding="VALID":
      _deconv2d_valid_golden(x, w, b, stride), tol=1e-4),
    C("deconv3d", F(1, 2, 2, 2, 2), F(2, 2, 2, 2, 3, lo=-0.5, hi=0.5),
      kw={"stride": (2, 2, 2), "padding": "VALID"},
      g=lambda x, w, stride=(2, 2, 2), padding="VALID":
      _deconv3d_valid_golden(x, w, stride), tol=1e-4),
    C("max_pooling2d", _img, g=_pool2d_golden("max")),
    C("avg_pooling2d", _img, g=_pool2d_golden("avg"), tol=1e-5),
    C("max_pooling1d", F(2, 8, 3), g=lambda x, kernel=2, stride=2,
      padding="VALID": x.reshape(2, 4, 2, 3).max(2)),
    C("avg_pooling1d", F(2, 8, 3), g=lambda x, kernel=2, stride=2,
      padding="VALID": x.reshape(2, 4, 2, 3).mean(2), tol=1e-5),
    C("max_pooling3d", F(1, 4, 4, 4, 2), g=lambda x, kernel=(2, 2, 2),
      stride=(2, 2, 2), padding="VALID":
      x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max((2, 4, 6))),
    C("avg_pooling3d", F(1, 4, 4, 4, 2), g=lambda x, kernel=(2, 2, 2),
      stride=(2, 2, 2), padding="VALID":
      x.reshape(1, 2, 2, 2, 2, 2, 2, 2).mean((2, 4, 6)), tol=1e-5),
    C("pnorm_pool2d", FP(2, 4, 4, 3), kw={"p": 3},
      g=lambda x, kernel=(2, 2), stride=(2, 2), p=2, padding="VALID":
      (x.reshape(2, 2, 2, 2, 2, 3) ** p).sum((2, 4)) ** (1 / p),
      tol=1e-4),
    C("global_avg_pool_nchw", F(2, 3, 4, 4),
      g=lambda x: x.mean((2, 3), keepdims=True), tol=1e-5),
    C("pointwise_conv2d", _img, F(1, 1, 3, 5, lo=-0.5, hi=0.5),
      g=lambda x, w: np.einsum("bhwi,io->bhwo", x,
                               w.reshape(3, 5)), tol=1e-4),
    C("separable_conv2d", _img, F(3, 3, 3, 2, lo=-0.5, hi=0.5),
      F(1, 1, 6, 4, lo=-0.5, hi=0.5),
      g=lambda x, wd, wp, stride=(1, 1), padding="SAME":
      np.einsum("bhwi,io->bhwo",
                _depthwise_golden(
                    x, wd.reshape(3, 3, 1, 6), stride, padding),
                wp.reshape(6, 4)), tol=1e-4),
    C("upsampling2d", F(2, 3, 3, 2), g=lambda x, scale=2:
      np.repeat(np.repeat(x, scale, 1), scale, 2)),
    C("upsampling3d", F(1, 2, 2, 2, 2), g=lambda x, size=2:
      np.repeat(np.repeat(np.repeat(x, size, 1), size, 2), size, 3)),
    C("lrn", F(2, 4, 4, 8), kw={"k": 1.0, "n": 3, "alpha": 1e-2,
                                "beta": 0.75},
      g=lambda x, k=2.0, n=5, alpha=1e-4, beta=0.75: __import__(
          "torch.nn.functional", fromlist=["local_response_norm"])
      .local_response_norm(
          __import__("torch").from_numpy(
              x.transpose(0, 3, 1, 2)).double(), n, alpha * n, beta, k)
      .numpy().transpose(0, 2, 3, 1), tol=1e-4),
]


# NCHW / ONNX-layout convs
def _nchw_conv_golden(x, w, b=None, stride=(1, 1), pads=(1, 1, 1, 1),
                      dilation=(1, 1), groups=1):
    import torch
    import torch.nn.functional as TF
    y = TF.conv2d(torch.from_numpy(x).double(),
                  torch.from_numpy(w).double(),
                  None if b is None else torch.from_numpy(b).double(),
                  stride=stride, padding=(pads[0], pads[1]),
                  dilation=dilation, groups=groups)
    return y.numpy()


def _nchw_deconv_golden(x, w, b=None, stride=(1, 1), pads=(0, 0, 0, 0),
                        dilation=(1, 1), output_padding=(0, 0),
                        groups=1):
    import torch
    import torch.nn.functional as TF
    # torch pads symmetrically; a future asymmetric case must fail loudly
    assert pads[0] == pads[2] and pads[1] == pads[3], pads
    y = TF.conv_transpose2d(
        torch.from_numpy(x).double(), torch.from_numpy(w).double(),
        None if b is None else torch.from_numpy(b).double(),
        stride=stride, padding=(pads[0], pads[1]),
        output_padding=output_padding, dilation=dilation, groups=groups)
    return y.numpy()


CASES += [
    C("conv2d_nchw", F(2, 3, 5, 5), F(4, 3, 3, 3, lo=-0.5, hi=0.5),
      F(4), kw={"pads": (1, 1, 1, 1)}, g=_nchw_conv_golden, tol=1e-4),
    C("deconv2d_nchw", F(2, 3, 4, 4), F(3, 4, 3, 3, lo=-0.5, hi=0.5),
      F(4), kw={"stride": (2, 2), "pads": (1, 1, 1, 1),
                "output_padding": (1, 1)},
      g=_nchw_deconv_golden, tol=1e-4),
    C("deconv2d_nchw", F(1, 2, 4, 4), F(2, 3, 2, 2, lo=-0.5, hi=0.5),
      kw={"dilation": (2, 2)}, g=_nchw_deconv_golden, tol=1e-4,
      tag="dilated"),
    C("max_pool2d_nchw", F(2, 3, 6, 6),
      g=lambda x, kernel=(2, 2), stride=(2, 2), pads=(0, 0, 0, 0):
      x.reshape(2, 3, 3, 2, 3, 2).max((3, 5))),
    C("avg_pool2d_nchw", F(2, 3, 6, 6),
      kw={"pads": (1, 1, 1, 1), "count_include_pad": False},
      g=lambda x, kernel=(2, 2), stride=(2, 2), pads=(0, 0, 0, 0),
      count_include_pad=False: __import__(
          "torch.nn.functional", fromlist=["avg_pool2d"]).avg_pool2d(
          __import__("torch").from_numpy(x).double(), kernel, stride,
          padding=1, count_include_pad=False).numpy(), tol=1e-4),
    C("batch_norm_nchw", F(2, 4, 3, 3), FP(4), F(4), F(4),
      FP(4, lo=0.5, hi=2.0),
      g=lambda x, s, b, m, v, eps=1e-5: __import__(
          "torch.nn.functional", fromlist=["batch_norm"]).batch_norm(
          __import__("torch").from_numpy(x).double(),
          __import__("torch").from_numpy(m).double(),
          __import__("torch").from_numpy(v).double(),
          __import__("torch").from_numpy(s).double(),
          __import__("torch").from_numpy(b).double(),
          False, 0.0, eps).numpy(), tol=1e-4),
]

# ---- im2col / patches ----
_p_in = F(1, 4, 4, 2)


def _patches_golden(x, ksizes, strides=(1, 1), rates=(1, 1),
                    padding="VALID"):
    from numpy.lib.stride_tricks import sliding_window_view
    kh, kw = ksizes
    v = sliding_window_view(x, (kh, kw), axis=(1, 2))   # B,OH,OW,C,kh,kw
    v = v[:, ::strides[0], ::strides[1]]
    return v.transpose(0, 1, 2, 4, 5, 3).reshape(
        v.shape[0], v.shape[1], v.shape[2], -1)


CASES += [
    C("extract_image_patches", _p_in, (3, 3), g=_patches_golden),
    C("im2col", _p_in, 3, 3, g=lambda x, kh, kw, sh=1, sw=1, ph=0, pw=0,
      dh=1, dw=1: _patches_golden(x, (kh, kw), (sh, sw)).reshape(
          1, 2, 2, 3, 3, 2)),
    C("col2im", custom=None, jit=False,
      check=None, g=None),
]
CASES = [c for c in CASES if c.op != "col2im"]


def _col2im_custom(fn):
    from numpy.lib.stride_tricks import sliding_window_view
    x = F(1, 4, 4, 2)
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE
    cols = OP_TABLE["im2col"](x, 2, 2, 2, 2)   # non-overlapping 2x2
    out = np.asarray(fn(cols, 4, 4, 2, 2, 2, 2))
    np.testing.assert_allclose(out, x, atol=1e-6)


CASES.append(C("col2im", custom=_col2im_custom))

# ---- attention ----
_q, _k, _v = F(2, 4, 6), F(2, 4, 6), F(2, 4, 6)


def _dpa_golden(q, k, v, mask=None, scaled=True):
    s = q @ np.swapaxes(k, -1, -2)
    if scaled:
        s = s / np.sqrt(q.shape[-1])
    if mask is not None:
        s = np.where(mask[..., None, :] > 0, s, -1e9)
    return _np_softmax(s, -1) @ v


_amask = (rs.rand(2, 4) > 0.3).astype(np.float32)
CASES += [
    C("dot_product_attention", _q, _k, _v, g=_dpa_golden, tol=1e-4,
      grad=(0, 1, 2), grad_sample=12, gtol=2e-2),
    C("dot_product_attention", _q, _k, _v, _amask, g=_dpa_golden,
      tol=1e-4, tag="masked"),
]


def _mhdpa_golden(q, k, v, wq, wk, wv, wo, mask=None, scaled=True):
    qh = np.einsum("btf,hdf->bhtd", q, wq)
    kh = np.einsum("btf,hdf->bhtd", k, wk)
    vh = np.einsum("btf,hdf->bhtd", v, wv)
    s = np.einsum("bhtd,bhsd->bhts", qh, kh)
    if scaled:
        s = s / np.sqrt(qh.shape[-1])
    if mask is not None:
        s = np.where(mask[:, None, None, :] > 0, s, -1e9)
    ctx = np.einsum("bhts,bhsd->bhtd", _np_softmax(s, -1), vh)
    return np.einsum("bhtd,ohd->bto", ctx, wo)


CASES += [
    C("multi_head_dot_product_attention", F(2, 4, 6), F(2, 4, 6),
      F(2, 4, 6), F(2, 3, 6, lo=-0.5, hi=0.5),
      F(2, 3, 6, lo=-0.5, hi=0.5), F(2, 3, 6, lo=-0.5, hi=0.5),
      F(6, 2, 3, lo=-0.5, hi=0.5), g=_mhdpa_golden, tol=1e-4),
]


# ---- recurrent (torch goldens with explicit gate-order mapping) ----
def _lstm_cell_golden(x, h, c, w_ih, w_hh, b=None):
    import torch
    cell = torch.nn.LSTMCell(x.shape[-1], h.shape[-1]).double()
    with torch.no_grad():
        cell.weight_ih.copy_(torch.from_numpy(w_ih.T))
        cell.weight_hh.copy_(torch.from_numpy(w_hh.T))
        cell.bias_ih.copy_(torch.from_numpy(
            b if b is not None else np.zeros(4 * h.shape[-1])))
        cell.bias_hh.zero_()
    hn, cn = cell(torch.from_numpy(x).double(),
                  (torch.from_numpy(h).double(),
                   torch.from_numpy(c).double()))
    return hn.detach().numpy(), cn.detach().numpy()


def _gru_cell_golden(x, h, w_ih, w_hh, b_ih=None, b_hh=None):
    import torch
    H = h.shape[-1]
    cell = torch.nn.GRUCell(x.shape[-1], H).double()
    with torch.no_grad():
        cell.weight_ih.copy_(torch.from_numpy(w_ih.T))
        cell.weight_hh.copy_(torch.from_numpy(w_hh.T))
        cell.bias_ih.copy_(torch.from_numpy(
            b_ih if b_ih is not None else np.zeros(3 * H)))
        cell.bias_hh.copy_(torch.from_numpy(
            b_hh if b_hh is not None else np.zeros(3 * H)))
    hn = cell(torch.from_numpy(x).double(), torch.from_numpy(h).double())
    return hn.detach().numpy()


def _torch_lstm_seq(x, w_ih_t, w_hh_t, b_t):
    """Run torch.nn.LSTM with torch-order [i,f,g,o] weight rows."""
    import torch
    B, T, Fdim = x.shape
    H = w_hh_t.shape[1]
    m = torch.nn.LSTM(Fdim, H, batch_first=True).double()
    with torch.no_grad():
        m.weight_ih_l0.copy_(torch.from_numpy(w_ih_t))
        m.weight_hh_l0.copy_(torch.from_numpy(w_hh_t))
        m.bias_ih_l0.copy_(torch.from_numpy(b_t))
        m.bias_hh_l0.zero_()
    out, (hn, cn) = m(torch.from_numpy(x).double())
    return (out.detach().numpy(), hn.detach().numpy()[0],
            cn.detach().numpy()[0])


def _lstm_layer_golden(x, w, rw, b):
    """ours IFOG columns -> torch [i,f,g,o] rows."""
    H = rw.shape[0]

    def remap(m):   # columns i,f,o,g -> rows i,f,g,o
        return np.concatenate([m[:, :H], m[:, H:2 * H], m[:, 3 * H:],
                               m[:, 2 * H:3 * H]], axis=1).T
    bt = np.concatenate([b[:H], b[H:2 * H], b[3 * H:], b[2 * H:3 * H]])
    return _torch_lstm_seq(x, remap(w), remap(rw), bt)[0]


def _lstm_layer_full_golden(x, w_ih, w_hh, b=None, h0=None, c0=None):
    """ours IFCO columns == torch [i,f,g,o] rows directly."""
    bt = b if b is not None else np.zeros(4 * w_hh.shape[0])
    return _torch_lstm_seq(x, w_ih.T, w_hh.T, bt)


_B, _T, _F, _H = 2, 5, 3, 4
_lx = F(_B, _T, _F)
CASES += [
    C("lstm_cell", F(_B, _F), F(_B, _H), F(_B, _H),
      F(_F, 4 * _H, lo=-0.5, hi=0.5), F(_H, 4 * _H, lo=-0.5, hi=0.5),
      F(4 * _H, lo=-0.5, hi=0.5), g=_lstm_cell_golden, tol=1e-4),
    C("gru_cell", F(_B, _F), F(_B, _H),
      F(_F, 3 * _H, lo=-0.5, hi=0.5), F(_H, 3 * _H, lo=-0.5, hi=0.5),
      F(3 * _H, lo=-0.5, hi=0.5), F(3 * _H, lo=-0.5, hi=0.5),
      g=_gru_cell_golden, tol=1e-4),
    C("lstm_layer", _lx, F(_F, 4 * _H, lo=-0.5, hi=0.5),
      F(_H, 4 * _H, lo=-0.5, hi=0.5), F(4 * _H, lo=-0.5, hi=0.5),
      g=_lstm_layer_golden, tol=1e-4),
    C("lstm_layer_full", _lx, F(_F, 4 * _H, lo=-0.5, hi=0.5),
      F(_H, 4 * _H, lo=-0.5, hi=0.5), F(4 * _H, lo=-0.5, hi=0.5),
      g=_lstm_layer_full_golden, tol=1e-4),
]


def _gru_layer_golden(x, h0, w_ih, w_hh, b_ih=None, b_hh=None):
    import torch
    B, T, Fdim = x.shape
    H = w_hh.shape[0]
    m = torch.nn.GRU(Fdim, H, batch_first=True).double()
    with torch.no_grad():
        m.weight_ih_l0.copy_(torch.from_numpy(w_ih.T))
        m.weight_hh_l0.copy_(torch.from_numpy(w_hh.T))
        m.bias_ih_l0.copy_(torch.from_numpy(
            b_ih if b_ih is not None else np.zeros(3 * H)))
        m.bias_hh_l0.copy_(torch.from_numpy(
            b_hh if b_hh is not None else np.zeros(3 * H)))
    out, _ = m(torch.from_numpy(x).double(),
               torch.from_numpy(h0[None]).double())
    return out.detach().numpy()


def _rnn_golden(x, w, rw, b=None, h0=None, seq_lengths=None):
    """Independent numpy recurrence for dynamic_rnn."""
    B, T, Fdim = x.shape
    H = rw.shape[0]
    h = np.zeros((B, H)) if h0 is None else h0.copy()
    bias = 0 if b is None else b
    outs = np.zeros((B, T, H))
    for t in range(T):
        h_new = np.tanh(x[:, t] @ w + h @ rw + bias)
        if seq_lengths is not None:
            live = (t < seq_lengths)[:, None]
            h_new = np.where(live, h_new, h)
            outs[:, t] = np.where(live, h_new, 0.0)
        else:
            outs[:, t] = h_new
        h = h_new
    return outs, h


def _sru_golden(x, c0, w, b):
    B, T, Fdim = x.shape
    H = c0.shape[-1]
    c = c0.copy().astype(np.float64)
    hs = np.zeros((B, T, H))
    for t in range(T):
        z = x[:, t] @ w
        xt, f_in, r_in = z[:, :H], z[:, H:2 * H], z[:, 2 * H:]
        f = _sig(f_in + b[:H])
        r = _sig(r_in + b[H:])
        c = f * c + (1 - f) * xt
        hs[:, t] = r * np.tanh(c) + (1 - r) * x[:, t]
    return hs


_rnn_w = F(_F, _H, lo=-0.5, hi=0.5)
_rnn_rw = F(_H, _H, lo=-0.5, hi=0.5)
_rnn_b = F(_H, lo=-0.5, hi=0.5)
_seq_l = np.asarray([3, 5], np.int32)
CASES += [
    C("gru_layer", _lx, np.zeros((_B, _H), np.float32),
      F(_F, 3 * _H, lo=-0.5, hi=0.5), F(_H, 3 * _H, lo=-0.5, hi=0.5),
      F(3 * _H, lo=-0.5, hi=0.5), F(3 * _H, lo=-0.5, hi=0.5),
      g=_gru_layer_golden, tol=1e-4),
    C("dynamic_rnn", _lx, _rnn_w, _rnn_rw, _rnn_b,
      kw={"seq_lengths": np.asarray([3, 5], np.int32)},
      g=lambda x, w, rw, b=None, h0=None, seq_lengths=None:
      _rnn_golden(x, w, rw, b, h0, seq_lengths), tol=1e-4),
    C("static_rnn", _lx, _rnn_w, _rnn_rw, _rnn_b,
      g=lambda x, w, rw, b=None, h0=None:
      _rnn_golden(x, w, rw, b, h0), tol=1e-4),
    C("dynamic_bidirectional_rnn", _lx, _rnn_w, _rnn_rw, _rnn_b,
      F(_F, _H, lo=-0.5, hi=0.5), F(_H, _H, lo=-0.5, hi=0.5),
      F(_H, lo=-0.5, hi=0.5),
      g=lambda x, wf, rwf, bf, wb, rwb, bb, seq_lengths=None: (
          _rnn_golden(x, wf, rwf, bf)[0],
          _rnn_golden(x[:, ::-1], wb, rwb, bb)[0][:, ::-1],
          _rnn_golden(x, wf, rwf, bf)[1],
          _rnn_golden(x[:, ::-1], wb, rwb, bb)[1]), tol=1e-4),
    C("static_bidirectional_rnn", _lx, _rnn_w, _rnn_rw, _rnn_b,
      F(_F, _H, lo=-0.5, hi=0.5), F(_H, _H, lo=-0.5, hi=0.5),
      F(_H, lo=-0.5, hi=0.5),
      g=lambda x, wf, rwf, bf, wb, rwb, bb: (
          _rnn_golden(x, wf, rwf, bf)[0],
          _rnn_golden(x[:, ::-1], wb, rwb, bb)[0][:, ::-1],
          _rnn_golden(x, wf, rwf, bf)[1],
          _rnn_golden(x[:, ::-1], wb, rwb, bb)[1]), tol=1e-4),
    C("sru_cell", F(_B, _H), F(_B, _H),
      F(_H, 3 * _H, lo=-0.5, hi=0.5), F(2 * _H, lo=-0.5, hi=0.5),
      g=lambda x, c, w, b: (
          _sru_golden(x[:, None], c, w, b)[:, 0],
          _sig((x @ w)[:, _H:2 * _H] + b[:_H]) * c
          + (1 - _sig((x @ w)[:, _H:2 * _H] + b[:_H])) * (x @ w)[:, :_H]),
      tol=1e-4),
    C("sru_layer", F(_B, _T, _H), np.zeros((_B, _H), np.float32),
      F(_H, 3 * _H, lo=-0.5, hi=0.5), F(2 * _H, lo=-0.5, hi=0.5),
      g=lambda x, c0, w, b: _sru_golden(x, c0, w, b), tol=1e-4),
]


def _lstm_block_check(out):
    """7 leaves (i, c, f, o, z, h, y): h matches torch, y == h."""
    i, c, f, o, z, h, y = out
    np.testing.assert_allclose(y, h, atol=1e-6)
    w_ih, w_hh, b = _BLOCK_W
    want, _, _ = _torch_lstm_seq(_BLOCK_X.astype(np.float64), w_ih.T,
                                 w_hh.T, b)
    np.testing.assert_allclose(h, want, atol=1e-4)


_BLOCK_X = F(_B, _T, _F)
_BLOCK_W = (F(_F, 4 * _H, lo=-0.5, hi=0.5),
            F(_H, 4 * _H, lo=-0.5, hi=0.5), F(4 * _H, lo=-0.5, hi=0.5))
CASES += [
    C("lstm_block", _BLOCK_X, *_BLOCK_W, check=_lstm_block_check),
    C("lstm_block_cell", F(_B, _F), np.zeros((_B, _H), np.float32),
      np.zeros((_B, _H), np.float32), F(_F, 4 * _H, lo=-0.5, hi=0.5),
      F(_H, 4 * _H, lo=-0.5, hi=0.5), F(4 * _H, lo=-0.5, hi=0.5),
      check=lambda out: (
          np.testing.assert_allclose(out[5], out[6], atol=1e-6),
          np.testing.assert_allclose(
              out[1], out[2] * 0.0 + out[0] * out[4], atol=1e-5))),
]

# ---- ctc (torch golden) ----
_ctc_B, _ctc_T, _ctc_C, _ctc_S = 2, 6, 5, 3
_raw = rs.randn(_ctc_B, _ctc_T, _ctc_C).astype(np.float32)
_ctc_lp = np.log(_np_softmax(_raw, -1)).astype(np.float32)
_ctc_lab = rs.randint(1, _ctc_C, (_ctc_B, _ctc_S)).astype(np.int32)
_ctc_il = np.asarray([6, 5], np.int32)
_ctc_ll = np.asarray([3, 2], np.int32)


def _ctc_golden(lp, labels, il, ll, blank=0):
    import torch
    loss = torch.nn.functional.ctc_loss(
        torch.from_numpy(lp.transpose(1, 0, 2)).double(),
        torch.from_numpy(labels.astype(np.int64)),
        torch.from_numpy(il.astype(np.int64)),
        torch.from_numpy(ll.astype(np.int64)),
        blank=blank, reduction="none", zero_infinity=False)
    return loss.numpy()


def _ctc_greedy_golden(lp, il, blank=0):
    B, T, Cn = lp.shape
    out = np.full((B, T), -1, np.int64)
    for b in range(B):
        best = lp[b, :il[b]].argmax(-1)
        prev, pos = -1, 0
        for t, s in enumerate(best):
            if s != blank and s != prev:
                out[b, pos] = s
                pos += 1
            prev = s
    return out


CASES += [
    C("ctc_loss", _ctc_lp, _ctc_lab, _ctc_il, _ctc_ll,
      g=_ctc_golden, tol=1e-3, grad=(0,), gtol=2e-2,
      # each eager eval runs the full forward-backward DP scan — full
      # 60-coordinate FD costs ~45 s on this 1-core box
      grad_sample=12),
    C("ctc_greedy_decode", _ctc_lp, _ctc_il, g=_ctc_greedy_golden),
    C("ctc_beam_decode", jit=False, custom=lambda fn: (
        np.testing.assert_array_equal(
            fn(_ctc_lp, _ctc_il, beam_width=1)[0],
            [x for x in _ctc_greedy_golden(_ctc_lp, _ctc_il)[0]
             if x >= 0]))),
]

# ---- embeddings / dropout ----
CASES += [
    C("embedding_lookup", F(7, 4), I32(5, hi=7),
      g=lambda t, i: t[i], grad=(0,)),
    C("dropout", _x, g=lambda x, rng=None, p=0.5: x, tag="infer"),
]


def _dropout_check(out):
    y = out[0]
    x = _DROP_X
    kept = y != 0
    np.testing.assert_allclose(y[kept], (x / 0.8)[kept], atol=1e-5)
    assert 0.5 < kept.mean() < 0.97


_DROP_X = FP(40, 25)
CASES += [
    C("dropout", _DROP_X, kw={"p": 0.8}, check=_dropout_check,
      tag="train", jit=False, custom=None),
]
# rng arg: feed a real key through custom (PRNGKey is a jnp array —
# build it lazily inside the custom to avoid import-time backend init)


def _dropout_train_custom(fn):
    import jax
    y = np.asarray(fn(_DROP_X, jax.random.PRNGKey(3), p=0.8))
    kept = y != 0
    np.testing.assert_allclose(y[kept], (_DROP_X / 0.8)[kept], rtol=1e-5)
    assert 0.55 < kept.mean() < 0.97


def _dropout_inv_custom(fn):
    import jax
    y = np.asarray(fn(_DROP_X, jax.random.PRNGKey(3), p=0.3))
    kept = y != 0
    np.testing.assert_allclose(y[kept], (_DROP_X / 0.7)[kept], rtol=1e-5)
    assert 0.4 < kept.mean() < 0.95


def _alpha_dropout_custom(fn):
    import jax
    y = np.asarray(fn(_DROP_X, jax.random.PRNGKey(3), p=0.1))
    a = ((1.0 - 0.1) * (1.0 + 0.1 * (-1.7580993408473766) ** 2)) ** -0.5
    kept = np.isclose(y, a * _DROP_X + (-a * 0.1 * (-1.7580993408473766)))
    assert 0.75 < kept.mean() <= 1.0


CASES = [c for c in CASES if not (c.op == "dropout" and c.tag == "train")]
CASES += [
    C("dropout", custom=_dropout_train_custom, tag="train"),
    C("dropout_inverted", custom=_dropout_inv_custom),
    C("alpha_dropout", custom=_alpha_dropout_custom),
]

# ---- updater ops (independent numpy closed forms) ----
_g, _m0, _v0 = F(5), FP(5, lo=0.0, hi=0.3), FP(5, lo=0.0, hi=0.3)
CASES += [
    C("sgd_updater", _g, g=lambda g, lr=0.01: g * lr, kw={"lr": 0.05}),
    C("nesterovs_updater", _g, _m0,
      g=lambda g, v, lr=0.1, momentum=0.9: (
          momentum * v - (1 + momentum) * (momentum * v - lr * g),
          momentum * v - lr * g)),
    C("adam_updater", _g, _m0, _v0, np.float32(3.0),
      g=lambda g, m, v, t, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8: (
          lr * (beta1 * m + (1 - beta1) * g) / (1 - beta1 ** (t + 1))
          / (np.sqrt((beta2 * v + (1 - beta2) * g * g)
                     / (1 - beta2 ** (t + 1))) + eps),
          beta1 * m + (1 - beta1) * g,
          beta2 * v + (1 - beta2) * g * g), tol=1e-4),
    C("rms_prop_updater", _g, _v0,
      g=lambda g, s, lr=1e-3, decay=0.95, eps=1e-8: (
          lr * g / np.sqrt(decay * s + (1 - decay) * g * g + eps),
          decay * s + (1 - decay) * g * g), tol=1e-4),
    C("ada_grad_updater", _g, _v0,
      g=lambda g, h, lr=1e-2, eps=1e-6: (
          lr * g / (np.sqrt(h + g * g) + eps), h + g * g), tol=1e-4),
    C("ada_delta_updater", _g, _m0, _v0,
      g=lambda g, msg, msdx, rho=0.95, eps=1e-6: (
          np.sqrt(msdx + eps)
          / np.sqrt(rho * msg + (1 - rho) * g * g + eps) * g,
          rho * msg + (1 - rho) * g * g,
          rho * msdx + (1 - rho) * (
              np.sqrt(msdx + eps)
              / np.sqrt(rho * msg + (1 - rho) * g * g + eps) * g) ** 2),
      tol=1e-4),
    C("ada_max_updater", _g, _m0, _v0, np.float32(2.0),
      g=lambda g, m, u, t, lr=2e-3, beta1=0.9, beta2=0.999, eps=1e-8: (
          (lr / (1 - beta1 ** (t + 1))) * (beta1 * m + (1 - beta1) * g)
          / (np.maximum(beta2 * u, np.abs(g)) + eps),
          beta1 * m + (1 - beta1) * g,
          np.maximum(beta2 * u, np.abs(g))), tol=1e-4),
    C("nadam_updater", _g, _m0, _v0, np.float32(2.0),
      g=lambda g, m, v, t, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8: (
          lr * (beta1 * ((beta1 * m + (1 - beta1) * g)
                         / (1 - beta1 ** (t + 1)))
                + (1 - beta1) * g / (1 - beta1 ** (t + 1)))
          / (np.sqrt((beta2 * v + (1 - beta2) * g * g)
                     / (1 - beta2 ** (t + 1))) + eps),
          beta1 * m + (1 - beta1) * g,
          beta2 * v + (1 - beta2) * g * g), tol=1e-4),
    C("ams_grad_updater", _g, _m0, _v0, FP(5, lo=0.0, hi=0.3),
      np.float32(2.0),
      g=lambda g, m, v, vhat, t, lr=1e-3, beta1=0.9, beta2=0.999,
      eps=1e-8: (
          lr * (beta1 * m + (1 - beta1) * g)
          / (np.sqrt(np.maximum(vhat, beta2 * v + (1 - beta2) * g * g))
             + eps),
          beta1 * m + (1 - beta1) * g,
          beta2 * v + (1 - beta2) * g * g,
          np.maximum(vhat, beta2 * v + (1 - beta2) * g * g)), tol=1e-4),
]
